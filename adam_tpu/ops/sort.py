"""Read sorting by reference position.

Re-designs ``adamSortReadsByReferencePosition``
(rdd/AdamRDDFunctions.scala:63-93): mapped reads order by (referenceId,
start); unmapped reads sort after every mapped read.  The reference scatters
unmapped reads across 10k synthetic refIds purely to avoid Spark range-
partitioner skew (:66-82) — irrelevant here: this module is a single
vectorized host lexsort.  The distributed forms are (a) the streaming
pipeline's range partition (genome bins) + per-bin sort
(parallel/pipeline.streaming_transform pass 4) and (b) the on-device
sample sort over XLA collectives (parallel/sort.py), both differentially
tested against this host sort.  Unmapped reads keep their input order at
the end.
"""

from __future__ import annotations

import numpy as np
import pyarrow as pa

from .. import schema as S

_UNMAPPED_KEY = np.int64(1) << 40


def sort_order(flags: np.ndarray, refid: np.ndarray,
               start: np.ndarray) -> np.ndarray:
    """[N] permutation sorting reads by position, unmapped last (stable)."""
    flags = np.asarray(flags, np.int64)
    refid = np.asarray(refid, np.int64)
    start = np.asarray(start, np.int64)
    mapped = (flags & S.FLAG_UNMAPPED) == 0
    key_ref = np.where(mapped, refid, _UNMAPPED_KEY)
    key_pos = np.where(mapped, start, 0)
    return np.lexsort((key_pos, key_ref))


def sort_reads(table: pa.Table) -> pa.Table:
    from ..packing import column_int64
    order = sort_order(column_int64(table, "flags", 0),
                       column_int64(table, "referenceId"),
                       column_int64(table, "start"))
    return table.take(pa.array(order))
