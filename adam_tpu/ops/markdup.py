"""Duplicate marking — Picard-compatible 5'-position-pair algorithm.

Re-designs ``rdd/MarkDuplicates.scala:24-110`` + ``models/SingleReadBucket``
+ ``models/ReferencePositionPair`` for the TPU substrate.  The reference runs
two Spark shuffles (group by (recordGroup, readName), then by (leftPos,
library)) and per-group Scala loops.  Here:

  * the per-base hot work — orientation-aware unclipped 5' positions
    (ReferencePositionPair via RichADAMRecord.fivePrimePosition) and the
    phred>=15 quality sums (MarkDuplicates.score :37-39) — runs on device as
    batched tensor ops;
  * the grouping/winner logic runs host-side as vectorized numpy sorts over
    *encoded integer keys* (no Python loops, no string shuffles): a
    position-with-orientation packs into one int64 preserving the reference's
    (refId, pos, strand) comparison order (ReferencePosition.scala:45-55).

Decision semantics (MarkDuplicates.apply :59-109):
  * bucket reads by (recordGroupId, readName); take the first two
    primary-mapped reads as the pair (ReferencePositionPair.scala:11-48 —
    both branches pair iff a second primary exists);
  * key = sorted (left, right) 5' positions; group by (left, library);
  * left == None  => all reads in those buckets are non-duplicates;
  * if the group has pairs: fragment buckets (right == None) are all
    duplicates; within each right-position subgroup the highest-scoring
    bucket's primaries survive, every other primary and all secondaries are
    duplicates;
  * no pairs in group => fragments are scored the same way;
  * unmapped reads are never duplicates.

Ties on score break toward the earliest bucket in input order (the reference
inherits whatever order the shuffle produced — Scala's stable sortBy on a
nondeterministic grouping; we make it deterministic).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import pyarrow as pa

from .. import schema as S
from ..packing import ReadBatch, dictionary_codes, pack_reads
from . import cigar as C

_POS_BIAS = np.int64(1) << 31   # unclipped positions can go negative


def encode_position_with_orientation(refid, pos, negative_strand):
    """(refId, pos, strand) -> one int64 key preserving the reference's
    comparison order (ReferencePositionWithOrientation.compare :47-55);
    0 is the None sentinel and sorts below every real position."""
    refid = np.asarray(refid, np.int64)
    pos = np.asarray(pos, np.int64)
    strand = np.asarray(negative_strand, np.int64)
    return ((refid + 1) << 33) | ((pos + _POS_BIAS) << 1) | strand


@partial(jax.jit, static_argnames=())
def _device_fiveprime_and_score(flags, start, cigar_ops, cigar_lens, n_cigar,
                                quals):
    fp = C.five_prime_position(start, flags, cigar_ops, cigar_lens, n_cigar)
    score = jnp.sum(jnp.where(quals >= 15, quals, 0).astype(jnp.int32), axis=-1)
    return fp, score


def _first_two_per_bucket(bucket_id: np.ndarray, rows: np.ndarray,
                          n_buckets: int):
    """For rows sorted into buckets, return (first_row, second_row) per
    bucket (-1 when absent), keeping input order within a bucket."""
    order = np.argsort(bucket_id[rows], kind="stable")
    srows = rows[order]
    sb = bucket_id[rows][order]
    first = np.full(n_buckets, -1, np.int64)
    second = np.full(n_buckets, -1, np.int64)
    is_first = np.ones(len(srows), bool)
    is_first[1:] = sb[1:] != sb[:-1]
    first[sb[is_first]] = srows[is_first]
    is_second = np.zeros(len(srows), bool)
    is_second[1:] = ~is_first[1:] & is_first[:-1]
    second[sb[is_second]] = srows[is_second]
    return first, second


def decide_duplicates(flags: np.ndarray, refid: np.ndarray, fp: np.ndarray,
                      score: np.ndarray, bucket_id: np.ndarray,
                      lib_idx: np.ndarray) -> np.ndarray:
    """The grouping/winner core over per-read columns -> dup bool [N].

    Inputs are global arrays in dataset order: SAM ``flags``, ``refid``,
    orientation-aware unclipped 5' positions ``fp``, phred>=15 quality sums
    ``score``, dense (recordGroup, readName) bucket ids, and dense library
    codes.  Split out from :func:`mark_duplicates_flags` so the streaming
    pipeline can run it over compact key columns accumulated across chunks
    without holding the records themselves.
    """
    n = len(flags)
    flags = np.asarray(flags, np.int64)
    refid = np.asarray(refid, np.int64)
    mapped = (flags & S.FLAG_UNMAPPED) == 0
    primary = (flags & S.FLAG_SECONDARY) == 0
    strand = (flags & S.FLAG_REVERSE) != 0
    n_buckets = int(bucket_id.max(initial=-1)) + 1

    # ---- first two primary-mapped reads per bucket = the position pair
    pm_rows = np.flatnonzero(mapped & primary)
    r1, r2 = _first_two_per_bucket(bucket_id, pm_rows, n_buckets)

    poskey = encode_position_with_orientation(refid, fp, strand)
    k1 = np.where(r1 >= 0, poskey[np.maximum(r1, 0)], 0)
    k2 = np.where(r2 >= 0, poskey[np.maximum(r2, 0)], 0)
    left = np.where((k2 > 0) & (k2 < k1), k2, k1)
    right = np.where(k2 > 0, np.where(k2 < k1, k1, k2), 0)

    # ---- library of allReads(0) (MarkDuplicates.scala:62-64): first read by
    # (primary-mapped, secondary-mapped, unmapped) priority then input order
    priority = np.where(mapped & primary, 0, np.where(mapped, 1, 2))
    order = np.lexsort((np.arange(n), priority, bucket_id))
    ob = bucket_id[order]
    is_first = np.ones(n, bool)
    is_first[1:] = ob[1:] != ob[:-1]
    bucket_lib = np.zeros(n_buckets, np.int64)
    bucket_lib[ob[is_first]] = lib_idx[order[is_first]]
    bucket_first_row = np.zeros(n_buckets, np.int64)
    bucket_first_row[ob[is_first]] = order[is_first]

    # ---- bucket score = sum of primary-mapped phred>=15 sums (:41-43)
    bucket_score = np.bincount(bucket_id[pm_rows],
                               weights=score[pm_rows].astype(np.float64),
                               minlength=n_buckets).astype(np.int64)

    # ---- group by (library, left); subgroup by right; pick winners
    bo = np.lexsort((bucket_first_row, -bucket_score, right, left, bucket_lib))
    slib, sleft, sright = bucket_lib[bo], left[bo], right[bo]
    new_group = np.ones(n_buckets, bool)
    new_group[1:] = (slib[1:] != slib[:-1]) | (sleft[1:] != sleft[:-1])
    group_id_sorted = np.cumsum(new_group) - 1
    n_groups = int(group_id_sorted[-1]) + 1 if n_buckets else 0
    # does the (lib,left) group contain any pair bucket?
    group_has_pairs = np.zeros(n_groups, bool)
    np.maximum.at(group_has_pairs, group_id_sorted, sright != 0)
    new_subgroup = np.ones(n_buckets, bool)
    new_subgroup[1:] = new_group[1:] | (sright[1:] != sright[:-1])
    # the first bucket of each subgroup has the best (score, order) — winner
    winner_sorted = new_subgroup
    is_winner = np.zeros(n_buckets, bool)
    is_winner[bo] = winner_sorted
    bucket_group = np.zeros(n_buckets, np.int64)
    bucket_group[bo] = group_id_sorted

    # ---- per-read verdicts
    if n_buckets:
        bleft = left[bucket_id]
        bright = right[bucket_id]
        bpairs = group_has_pairs[bucket_group[bucket_id]]
        bwin = is_winner[bucket_id]
    else:
        bleft = bright = np.zeros(n, np.int64)
        bpairs = bwin = np.zeros(n, bool)
    frag_in_pair_group = (bleft != 0) & (bright == 0) & bpairs
    scored = (bleft != 0) & ((bright != 0) | ~bpairs)
    return mapped & (frag_in_pair_group | (scored & (~primary | ~bwin)))


def bucket_ids_from_keys(rgid: np.ndarray, *name_keys: np.ndarray
                         ) -> np.ndarray:
    """Dense (recordGroup, readName) bucket ids from integer key columns.

    ``name_keys`` identify the read name (a dictionary code, or the two
    words of a 128-bit hash in the streaming pipeline).  Buckets number by
    first appearance order of nothing in particular — only equality matters.
    """
    n = len(rgid)
    cols = (np.asarray(rgid, np.int64),) + tuple(
        np.asarray(k).view(np.int64) if np.asarray(k).dtype == np.uint64
        else np.asarray(k, np.int64) for k in name_keys)
    order = np.lexsort(cols[::-1])
    new = np.zeros(n, bool)
    new[0:1] = True
    for c in cols:
        s = c[order]
        new[1:] |= s[1:] != s[:-1]
    ids_sorted = np.cumsum(new) - 1
    bucket_id = np.empty(n, np.int64)
    bucket_id[order] = ids_sorted
    return bucket_id


def mark_duplicates_flags(table: pa.Table, batch: ReadBatch | None = None
                          ) -> np.ndarray:
    """Compute the new packed ``flags`` column with FLAG_DUPLICATE set/cleared
    per the reference algorithm.  Returns int64 [num_rows]."""
    n = table.num_rows
    if batch is None:
        batch = pack_reads(table)

    fp_dev, score_dev = _device_fiveprime_and_score(
        jnp.asarray(batch.flags), jnp.asarray(batch.start),
        jnp.asarray(batch.cigar_ops), jnp.asarray(batch.cigar_lens),
        jnp.asarray(batch.n_cigar), jnp.asarray(batch.quals))
    fp = np.asarray(fp_dev)[:n]
    score = np.asarray(score_dev)[:n]

    flags = np.asarray(batch.flags[:n], np.int64)
    refid = np.asarray(batch.refid[:n], np.int64)
    rgid = np.asarray(batch.read_group[:n], np.int64)

    # ---- bucket by (recordGroupId, readName) (SingleReadBucket.scala:30-37)
    name_idx = dictionary_codes(table.column("readName"))
    bucket_id = bucket_ids_from_keys(rgid, name_idx)
    lib_idx = dictionary_codes(table.column("recordGroupLibrary"))

    dup = decide_duplicates(flags, refid, fp, score, bucket_id, lib_idx)
    return np.where(dup, flags | S.FLAG_DUPLICATE,
                    flags & ~np.int64(S.FLAG_DUPLICATE))


def mark_duplicates(table: pa.Table, batch: ReadBatch | None = None) -> pa.Table:
    """Return the table with its ``flags`` column rewritten (adamMarkDuplicates
    analog, AdamRDDFunctions.scala:100-102)."""
    new_flags = mark_duplicates_flags(table, batch)
    idx = table.column_names.index("flags")
    return table.set_column(idx, "flags",
                            pa.array(new_flags.astype(np.uint32),
                                     pa.uint32()))
