"""Flagstat: read-flag statistics as one fused device pass.

Re-designs ``rdd/FlagStat.scala:21-115`` (per-read FlagStatMetrics map +
tree aggregate to the driver) as a single masked matmul: build a [K, N]
indicator matrix of the 17 counters over the packed flag words, multiply by
the [N, 2] (passed, failed) vendor-quality split, and ``psum`` the [K, 2]
result across the mesh.  The reference needed a full RDD pass + JVM object
per read; here it is one memory-bound sweep that XLA fuses end to end.

Counter semantics match FlagStat.scala:90-103 and DuplicateMetrics :28-47
exactly (e.g. "cross chromosome" compares referenceId to mateReferenceId with
no mapped-ness requirement, and read1/read2 require the paired flag).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from .. import schema as S
from ..packing import ReadBatch
from ..platform import shard_map

#: counter order in the [K] axis of the kernel output
COUNTER_NAMES = (
    "total",
    "dup_primary_total", "dup_primary_both_mapped",
    "dup_primary_only_read_mapped", "dup_primary_cross_chromosome",
    "dup_secondary_total", "dup_secondary_both_mapped",
    "dup_secondary_only_read_mapped", "dup_secondary_cross_chromosome",
    "mapped", "paired_in_sequencing", "read1", "read2", "properly_paired",
    "with_self_and_mate_mapped", "singleton",
    "with_mate_mapped_to_diff_chromosome",
    "with_mate_mapped_to_diff_chromosome_mapq5",
)
K = len(COUNTER_NAMES)


@dataclass(frozen=True)
class DuplicateMetrics:
    """Mirrors DuplicateMetrics (FlagStat.scala:50-58)."""
    total: int
    both_mapped: int
    only_read_mapped: int
    cross_chromosome: int


@dataclass(frozen=True)
class FlagStatMetrics:
    """Mirrors FlagStatMetrics (FlagStat.scala:59-82)."""
    total: int
    duplicates_primary: DuplicateMetrics
    duplicates_secondary: DuplicateMetrics
    mapped: int
    paired_in_sequencing: int
    read1: int
    read2: int
    properly_paired: int
    with_self_and_mate_mapped: int
    singleton: int
    with_mate_mapped_to_diff_chromosome: int
    with_mate_mapped_to_diff_chromosome_mapq5: int

    @classmethod
    def from_counters(cls, c) -> "FlagStatMetrics":
        c = [int(x) for x in c]
        return cls(c[0], DuplicateMetrics(*c[1:5]), DuplicateMetrics(*c[5:9]),
                   *c[9:18])


def flagstat_kernel(flags: jnp.ndarray, mapq: jnp.ndarray,
                    refid: jnp.ndarray, mate_refid: jnp.ndarray,
                    valid: jnp.ndarray,
                    axis_name: str | None = None) -> jnp.ndarray:
    """[K, 2] int32 counters (columns: QC-passed, QC-failed).

    Pure function of the packed columns so it can run under jit, vmap over
    shards, or inside shard_map with ``axis_name`` set for the cross-device
    psum (the reference's driver-side aggregate, FlagStat.scala:102-114).
    """
    return _flagstat_core(flags, mapq, refid != mate_refid, valid, axis_name)


def indicator_masks(flags, mapq, cross, valid):
    """The 18 flagstat indicators (COUNTER_NAMES order) + the (passed,
    failed) vendor-quality split, all bool, over the 26 bits flagstat
    actually consumes.  Single definition shared by the XLA einsum core
    below and the Pallas wire sweep (:mod:`.flagstat_pallas`) so counter
    semantics cannot diverge between the two."""
    def has(bit):
        return (flags & bit) != 0

    paired = has(S.FLAG_PAIRED)
    mapped = ~has(S.FLAG_UNMAPPED)
    mate_mapped = ~has(S.FLAG_MATE_UNMAPPED)
    primary = ~has(S.FLAG_SECONDARY)
    dup = has(S.FLAG_DUPLICATE)
    mate_diff_chr = paired & mapped & mate_mapped & cross

    dup_p = dup & primary
    dup_s = dup & ~primary
    ones = jnp.ones_like(paired, bool)

    inds = (
        ones,
        dup_p, dup_p & mapped & mate_mapped, dup_p & mapped & ~mate_mapped,
        dup_p & cross,
        dup_s, dup_s & mapped & mate_mapped, dup_s & mapped & ~mate_mapped,
        dup_s & cross,
        mapped,
        paired,
        paired & has(S.FLAG_FIRST_OF_PAIR),
        paired & has(S.FLAG_SECOND_OF_PAIR),
        paired & has(S.FLAG_PROPER_PAIR),
        paired & mapped & mate_mapped,
        paired & mapped & ~mate_mapped,
        mate_diff_chr,
        mate_diff_chr & (mapq >= 5),
    )
    failed = has(S.FLAG_QC_FAIL) & valid
    passed = valid & ~failed
    return inds, passed, failed


def _flagstat_core(flags, mapq, cross, valid, axis_name=None):
    """Counting core: [K, N] indicator stack x [N, 2] split einsum."""
    inds, passed, failed = indicator_masks(flags, mapq, cross, valid)
    indicators = jnp.stack(inds)              # [K, N] bool
    split = jnp.stack([passed, failed], axis=1)  # [N, 2]
    counts = jnp.einsum("kn,nc->kc", indicators.astype(jnp.int32),
                        split.astype(jnp.int32),
                        preferred_element_type=jnp.int32)
    if axis_name is not None:
        counts = jax.lax.psum(counts, axis_name)
    return counts


#: bytes per read in the contiguous wire block (two u32 words)
WIRE_BYTES = 8
_REFID_BIAS = 1 << 15


def _check_refid_range(refid, mate_refid):
    """Both wire formats carry refids in 16 bits; values outside int16
    would silently corrupt neighboring fields (or wrap and fake a
    same-chromosome mate), so refuse loudly."""
    for name, col in (("refid", refid), ("mate_refid", mate_refid)):
        col = np.asarray(col)
        info = np.iinfo(col.dtype)
        may_exceed = info.min < -_REFID_BIAS or info.max >= _REFID_BIAS
        if may_exceed and col.size and (
                int(col.min()) < -_REFID_BIAS or int(col.max()) >= _REFID_BIAS):
            raise ValueError(
                f"{name} outside int16 range: the flagstat wire formats "
                "carry 16-bit reference ids (supports up to 32k contigs); "
                "renumber or use the unpacked kernel for wider ids")


def _check_flags_mapq_range(flags, mapq) -> None:
    """Out-of-range flags/mapq would silently corrupt neighboring wire
    bit-fields (valid/cross bits) — raise instead, like the refid check."""
    for name, col, hi in (("flags", flags, 1 << 16), ("mapq", mapq, 256)):
        col = np.asarray(col)
        info = np.iinfo(col.dtype)
        if (info.min < 0 or info.max >= hi) and col.size and (
                int(col.min()) < 0 or int(col.max()) >= hi):
            raise ValueError(
                f"{name} outside [0, {hi}) for the flagstat wire word; "
                "sanitize the column (e.g. clip null sentinels) first")


def pack_flagstat_wire(flags, mapq, refid, mate_refid, valid) -> np.ndarray:
    """Pack the five flagstat columns into ONE contiguous [2N] u32 buffer.

    Word A (first N): flags(16) | mapq(8)<<16 | valid(1)<<24.
    Word B (second N): (refid+2^15)(16) | (mate_refid+2^15)(16)<<16.

    One buffer means one host->device copy, and u32 is the fast dtype on the
    transfer path: measured over the tunnel, five small column copies run
    ~244 MB/s, one contiguous u32 block ~430 MB/s, and u8 blocks only
    ~130 MB/s.  The device unbundles with shifts, which XLA fuses into the
    counting pass.
    """
    _check_refid_range(refid, mate_refid)
    _check_flags_mapq_range(flags, mapq)
    word_a = (flags.astype(np.uint32)
              | (mapq.astype(np.uint32) << 16)
              | ((valid != 0).astype(np.uint32) << 24))
    word_b = ((refid.astype(np.int64) + _REFID_BIAS).astype(np.uint32)
              | ((mate_refid.astype(np.int64) + _REFID_BIAS)
                 .astype(np.uint32) << 16))
    return np.concatenate([word_a, word_b])


def unpack_flagstat_wire(wire: jnp.ndarray):
    """Device-side inverse of :func:`pack_flagstat_wire` (shifts only)."""
    n = wire.shape[0] // 2
    word_a = wire[:n]
    word_b = wire[n:]
    flags = (word_a & 0xFFFF).astype(jnp.int32)
    mapq = ((word_a >> 16) & 0xFF).astype(jnp.int32)
    valid = ((word_a >> 24) & 1) != 0
    refid = (word_b & 0xFFFF).astype(jnp.int32) - _REFID_BIAS
    mate_refid = ((word_b >> 16) & 0xFFFF).astype(jnp.int32) - _REFID_BIAS
    return flags, mapq, refid, mate_refid, valid


def flagstat_kernel_wire(wire: jnp.ndarray,
                         axis_name: str | None = None) -> jnp.ndarray:
    """Flagstat straight off the wire block — unpack + count in one fusion."""
    return flagstat_kernel(*unpack_flagstat_wire(wire), axis_name=axis_name)


def pack_flagstat_wire32(flags, mapq, refid, mate_refid, valid) -> np.ndarray:
    """The minimal 4-byte projection word: flags(16) | mapq(8)<<16 |
    valid<<24 | (refid != mate_refid)<<25.

    Pushing the reference's 13-field projection to its limit: flagstat
    consumes only these 26 bits per read, so the packer derives the
    cross-chromosome bit while it already holds both refid columns and ships
    half the bytes of :func:`pack_flagstat_wire`.  The transfer link is the
    pipeline bottleneck (~260 MB/s steady over the tunnel), so halving the
    wire halves the wall time.  Use the 8-byte block when downstream kernels
    need real refids.
    """
    _check_refid_range(refid, mate_refid)
    _check_flags_mapq_range(flags, mapq)
    n = len(flags)
    cols = (np.ascontiguousarray(flags, np.uint16),
            np.ascontiguousarray(mapq, np.uint8),
            np.ascontiguousarray(refid, np.int16),
            np.ascontiguousarray(mate_refid, np.int16),
            np.ascontiguousarray(valid, np.uint8))
    try:
        import adam_tpu_native as _native
        packer = getattr(_native, "pack_wire32", None)
    except ImportError:  # pragma: no cover - toolchain-less environments
        packer = None
    if packer is not None:
        out = np.empty(n, np.uint32)
        packer(*cols, out)
        return out
    flags, mapq, refid, mate_refid, valid = cols
    cross = refid != mate_refid
    return (flags.astype(np.uint32)
            | (mapq.astype(np.uint32) << 16)
            | ((valid != 0).astype(np.uint32) << 24)
            | (cross.astype(np.uint32) << 25))


def flagstat_kernel_wire32(wire: jnp.ndarray,
                           axis_name: str | None = None) -> jnp.ndarray:
    """Flagstat off the 4-byte projection word."""
    flags = (wire & 0xFFFF).astype(jnp.int32)
    mapq = ((wire >> 16) & 0xFF).astype(jnp.int32)
    valid = ((wire >> 24) & 1) != 0
    cross = ((wire >> 25) & 1) != 0
    return _flagstat_core(flags, mapq, cross, valid, axis_name)


@jax.jit
def flagstat_kernel_wire32_segmented(wire: jnp.ndarray,
                                     bounds: jnp.ndarray) -> jnp.ndarray:
    """[S, 18, 2] counters over S tenant segments of ONE shared wire
    buffer — the serve front-end's cross-tenant fold (adam_tpu/serve).

    ``bounds`` is the int32 prefix sum of the segments' row counts
    (``[S+1]``; segment s covers flat rows ``[bounds[s], bounds[s+1])``),
    the same positional-bound convention as the ragged flagstat concat
    (ops/flagstat_pallas, docs/ARCHITECTURE.md §6g) extended from one
    live range to S of them: rows past ``bounds[-1]`` (and empty
    segments, ``bounds[s] == bounds[s+1]``) belong to no segment and
    contribute nothing, so the buffer slack may hold garbage.  Each
    segment's [18, 2] block is the exact integer sum of its rows'
    indicator contributions — :func:`indicator_masks` is shared with the
    solo kernels, so per-tenant counters folded across shared dispatches
    equal that tenant's solo run bit-for-bit (the serve byte-identity
    contract, tests/test_serve.py).

    The compiled shape depends only on (capacity, S): the server pads
    the segment count to a fixed width, so every shared dispatch of a
    serve lifetime reuses one compiled executable.  The fold is a
    row→segment segment-sum (the PR 8 ragged kernels' XLA fallback
    pattern), so packing S tenants costs the same counting work their
    rows would cost through the solo kernel — never S-times it.
    """
    n_seg = bounds.shape[0] - 1
    flags = (wire & 0xFFFF).astype(jnp.int32)
    mapq = ((wire >> 16) & 0xFF).astype(jnp.int32)
    valid = ((wire >> 24) & 1) != 0
    cross = ((wire >> 25) & 1) != 0
    inds, passed, failed = indicator_masks(flags, mapq, cross, valid)
    indicators = jnp.stack(inds, axis=1).astype(jnp.int32)   # [N, K]
    idx = jnp.arange(wire.shape[0], dtype=jnp.int32)
    # row -> segment id: bounds[s] <= i < bounds[s+1]; 'right' search
    # over the upper edges lands duplicates (empty segments) on the
    # following live segment, matching the positional-bound convention
    seg_id = jnp.minimum(
        jnp.searchsorted(bounds[1:], idx, side="right"),
        n_seg - 1).astype(jnp.int32)
    in_range = (idx < bounds[-1]).astype(jnp.int32)          # [N]
    out = []
    for flag_col in (passed, failed):
        w = indicators * (flag_col.astype(jnp.int32) *
                          in_range)[:, None]                 # [N, K]
        out.append(jax.ops.segment_sum(w, seg_id,
                                       num_segments=n_seg))  # [S, K]
    return jnp.stack(out, axis=-1)                           # [S, K, 2]


@jax.jit
def flagstat_kernel_wire32_segmented_paged(pool: jnp.ndarray,
                                           page_table: jnp.ndarray,
                                           bounds: jnp.ndarray
                                           ) -> jnp.ndarray:
    """[S, 18, 2] per-tenant counters off the RESIDENT page pool — the
    paged twin of :func:`flagstat_kernel_wire32_segmented` and the
    serve front-end's continuous-batching dispatch (serve/packed.py,
    docs/ARCHITECTURE.md §6l).

    One gather assembles the logical shared wire from
    ``pool[page_table]`` (pages filled in admission order; only DELTA
    pages ever crossed the link), then the same segment fold runs over
    the same positional bounds — so a tenant's counters under paging
    equal its solo run bit-for-bit however its rows landed in pages
    (the PR 10 identity matrix re-run under paging,
    tests/test_paged.py).  The compiled shape depends only on
    (pool geometry, table length, S): one executable per serve
    lifetime."""
    from ..parallel.pagedbuf import gather_pages

    wire = gather_pages(pool, page_table)
    return flagstat_kernel_wire32_segmented(wire, bounds)


_flagstat_jit = jax.jit(partial(flagstat_kernel, axis_name=None))


@functools.lru_cache(maxsize=None)
def flagstat_sharded(mesh):
    """jit-compiled flagstat over a device mesh: per-shard masked matmul +
    psum over ICI (replaces the reference's executor map + driver tree
    aggregate, FlagStat.scala:102-114).

    Memoized per mesh like :func:`flagstat_wire32_sharded` — a fresh
    ``jax.jit`` wrapper per call would recompile on every warm-path
    invocation (jit caches hang off the wrapper object)."""
    from jax.sharding import PartitionSpec as P
    from ..parallel.mesh import READS_AXIS
    spec = P(READS_AXIS)
    fn = shard_map(
        partial(flagstat_kernel, axis_name=READS_AXIS), mesh=mesh,
        in_specs=(spec, spec, spec, spec, spec), out_specs=P())
    return jax.jit(fn)


@functools.lru_cache(maxsize=None)
def flagstat_wire32_sharded(mesh, donate: bool = False):
    """jit-compiled wire32 flagstat over a device mesh: per-shard count +
    psum over ICI, fed by the 4-byte projection word (the streaming CLI
    path — reference: executor map + driver aggregate, FlagStat.scala:102).

    ``donate=True`` donates the wire buffer to the call (the streaming
    executor's per-chunk feed: each chunk's wire is used exactly once,
    so the device reuses its HBM instead of re-allocating every chunk).
    Callers that re-dispatch the same buffer — the bench chain loops —
    must keep the default.

    Memoized per (mesh, donate): a fresh ``jax.jit`` wrapper per call
    would make every serve-mode job recompile kernels the previous job
    already compiled (jit caches hang off the wrapper object) — the
    warm-path reuse gap.  ``Mesh`` hashes by devices + axis names, so
    equal meshes from repeated ``make_mesh()`` calls share one wrapper.
    """
    from jax.sharding import PartitionSpec as P
    from ..parallel.mesh import READS_AXIS
    fn = shard_map(
        partial(flagstat_kernel_wire32, axis_name=READS_AXIS), mesh=mesh,
        in_specs=(P(READS_AXIS),), out_specs=P())
    return jax.jit(fn, donate_argnums=(0,) if donate else ())


def flagstat(batch: ReadBatch) -> tuple[FlagStatMetrics, FlagStatMetrics]:
    """(QC-failed, QC-passed) metrics — same pair order as the reference's
    ``adamFlagStat`` (FlagStat.scala:85-114)."""
    counts = np.asarray(_flagstat_jit(
        jnp.asarray(batch.flags), jnp.asarray(batch.mapq),
        jnp.asarray(batch.refid), jnp.asarray(batch.mate_refid),
        jnp.asarray(batch.valid)))
    passed = FlagStatMetrics.from_counters(counts[:, 0])
    failed = FlagStatMetrics.from_counters(counts[:, 1])
    return failed, passed


def format_report(failed: FlagStatMetrics, passed: FlagStatMetrics) -> str:
    """samtools-flavored report, same lines as cli/FlagStat.scala:66-79."""
    def pct(fraction, total):
        return 0.0 if total == 0 else 100.0 * fraction / total

    p, f = passed, failed
    return "\n".join([
        "",
        f"{p.total} + {f.total} in total (QC-passed reads + QC-failed reads)",
        f"{p.duplicates_primary.total} + {f.duplicates_primary.total} primary duplicates",
        f"{p.duplicates_primary.both_mapped} + {f.duplicates_primary.both_mapped} primary duplicates - both read and mate mapped",
        f"{p.duplicates_primary.only_read_mapped} + {f.duplicates_primary.only_read_mapped} primary duplicates - only read mapped",
        f"{p.duplicates_primary.cross_chromosome} + {f.duplicates_primary.cross_chromosome} primary duplicates - cross chromosome",
        f"{p.duplicates_secondary.total} + {f.duplicates_secondary.total} secondary duplicates",
        f"{p.duplicates_secondary.both_mapped} + {f.duplicates_secondary.both_mapped} secondary duplicates - both read and mate mapped",
        f"{p.duplicates_secondary.only_read_mapped} + {f.duplicates_secondary.only_read_mapped} secondary duplicates - only read mapped",
        f"{p.duplicates_secondary.cross_chromosome} + {f.duplicates_secondary.cross_chromosome} secondary duplicates - cross chromosome",
        f"{p.mapped} + {f.mapped} mapped ({pct(p.mapped, p.total):.2f}%:{pct(f.mapped, f.total):.2f}%)",
        f"{p.paired_in_sequencing} + {f.paired_in_sequencing} paired in sequencing",
        f"{p.read1} + {f.read1} read1",
        f"{p.read2} + {f.read2} read2",
        f"{p.properly_paired} + {f.properly_paired} properly paired ({pct(p.properly_paired, p.total):.2f}%:{pct(f.properly_paired, f.total):.2f}%)",
        f"{p.with_self_and_mate_mapped} + {f.with_self_and_mate_mapped} with itself and mate mapped",
        f"{p.singleton} + {f.singleton} singletons ({pct(p.singleton, p.total):.2f}%:{pct(f.singleton, f.total):.2f}%)",
        f"{p.with_mate_mapped_to_diff_chromosome} + {f.with_mate_mapped_to_diff_chromosome} with mate mapped to a different chr",
        f"{p.with_mate_mapped_to_diff_chromosome_mapq5} + {f.with_mate_mapped_to_diff_chromosome_mapq5} with mate mapped to a different chr (mapQ>=5)",
        "",
    ])
