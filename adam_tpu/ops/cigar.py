"""Vectorized CIGAR geometry: ends, clips, 5' positions, per-base reference
positions.

Re-designs the lazy per-record walks of ``rich/RichADAMRecord.scala`` as
batched tensor ops over the packed ``cigar_ops``/``cigar_lens`` columns:

  * ``end``             — RichADAMRecord.end (:77-87): start + ref-consuming lens
  * ``unclipped_start`` — :99-109: start minus leading S/H clips
  * ``unclipped_end``   — :89-97: end plus trailing S/H clips
  * ``five_prime``      — fivePrimePosition (:112-118)
  * ``reference_positions`` — :156-187: the per-base read-offset ->
    reference-position map (M/X/=/S advance from unclippedStart, D/P/N skip
    reference, I yields no position, H ignored)

The per-base map is computed with a cumulative-sum-over-op-runs trick instead
of the reference's list fold: each base finds its op slot by comparing its
read offset against the running read-consumption cumsum, then offsets from
that op's walk position.  Everything is jit/vmap/shard_map compatible; -1 is
the "no position" sentinel (the reference's None).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from .. import schema as S

# per-op advance tables, indexed by cigar op code (M I D N S H P = X)
_CONSUMES_READ = np.array(S.CIGAR_CONSUMES_READ, np.int32)
_CONSUMES_REF = np.array(S.CIGAR_CONSUMES_REF, np.int32)
# the referencePositions walk: advances for every op except I and H
# (S counts because the walk starts at unclippedStart; RichADAMRecord:163-178)
_WALK_ADVANCES = np.array([1, 0, 1, 1, 1, 0, 1, 1, 1], np.int32)
_IS_CLIP = np.array([0, 0, 0, 0, 1, 1, 0, 0, 0], np.int32)

NO_POSITION = -1


def _table(tab: np.ndarray, ops: jnp.ndarray) -> jnp.ndarray:
    """Gather a per-op-code table over an op tensor; padding (-1) -> 0."""
    safe = jnp.where(ops < 0, 0, ops)
    return jnp.where(ops < 0, 0, jnp.asarray(tab)[safe])


def reference_lengths(cigar_ops, cigar_lens) -> jnp.ndarray:
    """[N] bases of reference consumed by each read's alignment."""
    return jnp.sum(_table(_CONSUMES_REF, cigar_ops) * cigar_lens, axis=-1)


def read_end(start, cigar_ops, cigar_lens) -> jnp.ndarray:
    """[N] exclusive reference end position (RichADAMRecord.end :77-87)."""
    return start + reference_lengths(cigar_ops, cigar_lens)


def _leading_clip(cigar_ops, cigar_lens, soft_only: bool = False) -> jnp.ndarray:
    """[N] total clipped bases before the first aligned op."""
    is_clip = _table(_IS_CLIP, cigar_ops)
    # a clip op counts while every op before it (inclusive) is a clip
    still_leading = jnp.cumprod(is_clip, axis=-1)
    if soft_only:
        still_leading = still_leading * (cigar_ops == S.CIGAR_S)
    return jnp.sum(still_leading * cigar_lens, axis=-1)


def _trailing_clip(cigar_ops, cigar_lens, n_cigar) -> jnp.ndarray:
    """[N] total clipped bases after the last aligned op."""
    C = cigar_ops.shape[-1]
    idx = jnp.arange(C)
    in_range = idx[None, :] < n_cigar[:, None]
    is_clip = jnp.where(in_range, _table(_IS_CLIP, cigar_ops), 1)
    # scan from the right: op counts while everything after it is clip/padding
    still_trailing = jnp.flip(jnp.cumprod(jnp.flip(is_clip, -1), -1), -1) * in_range
    return jnp.sum(still_trailing * cigar_lens, axis=-1)


def unclipped_start(start, cigar_ops, cigar_lens) -> jnp.ndarray:
    """[N] start minus leading clips (RichADAMRecord.unclippedStart :99-109)."""
    return start - _leading_clip(cigar_ops, cigar_lens)


def unclipped_end(start, cigar_ops, cigar_lens, n_cigar) -> jnp.ndarray:
    """[N] end plus trailing clips (RichADAMRecord.unclippedEnd :89-97)."""
    return read_end(start, cigar_ops, cigar_lens) + \
        _trailing_clip(cigar_ops, cigar_lens, n_cigar)


def five_prime_position(start, flags, cigar_ops, cigar_lens, n_cigar) -> jnp.ndarray:
    """[N] orientation-aware unclipped 5' position
    (RichADAMRecord.fivePrimePosition :112-118; the markdup key ingredient,
    ReferencePositionPair.scala:8-87)."""
    reverse = (flags & S.FLAG_REVERSE) != 0
    return jnp.where(reverse,
                     unclipped_end(start, cigar_ops, cigar_lens, n_cigar),
                     unclipped_start(start, cigar_ops, cigar_lens))


def reference_positions(start, cigar_ops, cigar_lens, max_len: int) -> jnp.ndarray:
    """[N, L] reference position of every read base, NO_POSITION at
    insertions/padding (RichADAMRecord.referencePositions :156-187).

    ``max_len`` is the static padded read length (bases.shape[1]).
    Soft-clipped bases get (out-of-alignment) positions extrapolated before
    ``start``, like the reference.  One deliberate divergence: the reference
    starts this walk at unclippedStart, which also subtracts leading *hard*
    clips but never re-advances past them (RichADAMRecord.scala:158,171-173),
    so every position in a hard-clipped read shifts left by the H length and
    disagrees with the read's own MD-tag coordinates.  We subtract leading
    soft clips only, so the first M base always lands on ``start``.
    """
    N, C = cigar_ops.shape
    L = max_len
    ops_safe = jnp.where(cigar_ops < 0, 0, cigar_ops)
    consumes_read = _table(_CONSUMES_READ, cigar_ops) * cigar_lens   # [N, C]
    walk_adv = _table(_WALK_ADVANCES, cigar_ops) * cigar_lens        # [N, C]

    read_cum = jnp.cumsum(consumes_read, axis=-1)                    # inclusive
    read_begin = read_cum - consumes_read                            # exclusive
    walk_cum = jnp.cumsum(walk_adv, axis=-1)
    walk_start = start - _leading_clip(cigar_ops, cigar_lens, soft_only=True)
    walk_begin = walk_start[:, None] + (walk_cum - walk_adv)         # [N, C]

    offs = jnp.arange(L, dtype=read_cum.dtype)                       # [L]
    # op slot owning each read offset: first j with read_cum[j] > off
    owned = offs[None, :, None] >= read_cum[:, None, :]              # [N, L, C]
    slot = jnp.sum(owned.astype(jnp.int32), axis=-1)                 # [N, L]
    slot = jnp.clip(slot, 0, C - 1)

    op_at = jnp.take_along_axis(ops_safe, slot, axis=1)              # [N, L]
    begin_at = jnp.take_along_axis(read_begin, slot, axis=1)
    walk_at = jnp.take_along_axis(walk_begin, slot, axis=1)
    pos = walk_at + (offs[None, :] - begin_at)

    in_read = offs[None, :] < read_cum[:, -1:]
    is_ins = op_at == S.CIGAR_I
    return jnp.where(in_read & ~is_ins, pos, NO_POSITION)
