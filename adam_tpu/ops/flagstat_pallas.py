"""Pallas TPU flagstat: one VMEM-resident sweep over the 4-byte wire word.

The XLA formulation (``flagstat.flagstat_kernel_wire32``) materializes a
[K, N] int32 indicator matrix plus an [N, 2] split in HBM before its einsum
— ~80 bytes of traffic per 4-byte wire word.  This kernel instead streams
the wire in VMEM-sized blocks under a sequential grid, computes the same 18
indicator masks in vector registers, reduces each (indicator ∧ passed/
failed) pair on the VPU, and accumulates the 36 scalar counters in SMEM.
Traffic drops to the 4 wire bytes per read; measured on one v5e chip this
is ~4.5x the einsum core (7.6 Greads/s vs 1.7), i.e. the reference's whole
51.5M-read NA12878-chr20 flagstat (17 s on its laptop baseline,
``/root/reference/README.md:171-174``) in under 7 ms of device time.

Counter semantics are inherited from :mod:`.flagstat` (which itself mirrors
``rdd/FlagStat.scala:21-115``); the differential test pins this kernel to
the einsum core bit for bit.

Blocks are ``[BLOCK_ROWS, LANES]`` = 128x1024 u32 (512 KiB): large enough
to amortize grid/DMA overhead, small enough that the ~36 boolean
intermediates stay inside the 16 MiB scoped-VMEM budget (2^19-element
blocks exceed it).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from ..platform import pallas_tpu_compiler_params, shard_map
from .flagstat import flagstat_kernel_wire32

LANES = 1024
BLOCK_ROWS = 128
BLOCK = BLOCK_ROWS * LANES

#: kernel variant for the product paths: "v1" (per-block SMEM scalar
#: reductions), "v2" (deferred per-lane reduction, 4x block), or "auto"
#: (default): on TPU backends, race both once per process with a
#: correctness gate against the XLA core and keep the winner — the same
#: self-tuning pattern as realign's conv-vs-pallas sweep race.
_VARIANT_ENV = "ADAM_TPU_FLAGSTAT_PALLAS"


def _t_of(thunk) -> float:
    import time
    t0 = time.perf_counter()
    thunk()
    return time.perf_counter() - t0


def _variant() -> str:
    choice = os.environ.get(_VARIANT_ENV, "auto")
    if choice in ("v1", "v2"):
        return choice
    return _auto_variant()


@functools.lru_cache(maxsize=1)
def _auto_variant() -> str:
    from ..platform import is_tpu_backend
    if not is_tpu_backend():
        return "v1"          # variants only differ compiled; tests pin both
    try:
        from .flagstat import pack_flagstat_wire32

        rng = np.random.RandomState(0)
        n = 16 * V2_BLOCK                  # 32 MiB of wire, 64 v1 blocks
        wire = pack_flagstat_wire32(
            rng.randint(0, 1 << 12, n).astype(np.uint16),
            rng.randint(0, 61, n).astype(np.uint8),
            rng.randint(0, 4, n).astype(np.int16),
            rng.randint(0, 4, n).astype(np.int16),
            np.ones(n, bool))
        ref = np.asarray(flagstat_kernel_wire32(jnp.asarray(wire)))
        w1 = jax.device_put(wire.reshape(-1, BLOCK_ROWS, LANES))
        w4 = jax.device_put(wire.reshape(-1, V2_ROWS, LANES))
        tail = jax.device_put(wire[:0])

        # the one device_get sync pays a tunnel round trip with ms-scale
        # jitter (block_until_ready is a no-op over axon), so: measure
        # the sync floor, chain enough dispatches that kernel time
        # dominates it, take min-of-3, and demand a real margin
        g = jax.jit(lambda a: a[0, :1, :1].astype(jnp.int32))
        jax.device_get(g(w1))
        rtt = min(_t_of(lambda: jax.device_get(g(w1)))
                  for _ in range(3))

        def timed(fn, arg):
            out = fn(arg, tail)
            if not np.array_equal(np.asarray(out), ref):
                return None              # correctness gate

            def once():
                o = None
                for _ in range(32):      # chained dispatch; one sync
                    o = fn(arg, tail)
                jax.device_get(o)
            once()                       # warm
            return max(min(_t_of(once) for _ in range(3)) - rtt, 1e-6)

        t1 = timed(_flagstat_blocked, w1)
        t2 = timed(_flagstat_blocked_v2, w4)
        if t2 is not None and (t1 is None or t2 < 0.9 * t1):
            return "v2"
    except Exception:  # noqa: BLE001 — v1 is the safe answer
        pass
    return "v1"


def _wire_masks(wire):
    """Unpack the wire word and delegate to the shared indicator-mask
    definition in :mod:`.flagstat` (one source of counter semantics)."""
    from .flagstat import indicator_masks

    flags = (wire & 0xFFFF).astype(jnp.int32)
    mapq = ((wire >> 16) & 0xFF).astype(jnp.int32)
    valid = ((wire >> 24) & 1) != 0
    cross = ((wire >> 25) & 1) != 0
    return indicator_masks(flags, mapq, cross, valid)


def _kernel(wire_ref, out_ref):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        for k in range(18):
            out_ref[k, 0] = 0
            out_ref[k, 1] = 0

    inds, passed, failed = _wire_masks(wire_ref[...])
    for k, ind in enumerate(inds):
        out_ref[k, 0] += jnp.sum((ind & passed).astype(jnp.int32))
        out_ref[k, 1] += jnp.sum((ind & failed).astype(jnp.int32))


#: v2 block geometry: 4 sublane-tiles per grid step (2 MiB of wire).  The
#: sublane row count bounds the per-lane per-block count at 512 < 2^16, so
#: the passed/failed pair packs into one int32 lane sum (low|high 16 bits).
V2_ROWS = 512
V2_BLOCK = V2_ROWS * LANES


def _kernel_v2(wire_ref, acc_ref):
    """Deferred-reduction wire sweep (roofline round: VERDICT r3 #3).

    The v1 kernel's cost is 36 full cross-lane reduction trees per 512 KiB
    block — measured ~30 GB/s of v5e's 819.  v2 removes both overheads:

      * counters accumulate PER LANE in a revisited [36, LANES] int32
        block; the 36 cross-lane reductions happen once per call in the
        XLA epilogue, not once per block;
      * each indicator contributes via ONE select + ONE sublane-axis sum
        of the packed value ``passed + (failed << 16)`` — half the
        selects/sums of treating the split as two masks (the per-lane
        row count 512 keeps both 16-bit halves exact).
    """
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    inds, passed, failed = _wire_masks(wire_ref[...])
    pf = passed.astype(jnp.int32) + (failed.astype(jnp.int32) << 16)
    zero = jnp.zeros_like(pf)
    for k, ind in enumerate(inds):
        part = jnp.sum(jnp.where(ind, pf, zero), axis=0)     # [LANES]
        acc_ref[k, :] += part & 0xFFFF
        acc_ref[18 + k, :] += part >> 16


@functools.partial(jax.jit, static_argnames=("interpret",))
def _blocked_call_v2(wire3d, *, interpret: bool):
    # module-scope jit owns the trace cache: callers inside jit inline
    # it for free, and the direct (bench/oracle) route stops re-tracing
    # a fresh pallas_call wrapper per invocation
    from jax.experimental.pallas import tpu as pltpu

    n_blk, rows, lanes = wire3d.shape
    acc = pl.pallas_call(
        _kernel_v2,
        grid=(n_blk,),
        in_specs=[pl.BlockSpec((None, rows, lanes),
                               lambda i: (i, 0, 0))],
        out_specs=pl.BlockSpec((36, LANES), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((36, LANES), jnp.int32),
        compiler_params=pallas_tpu_compiler_params(
            dimension_semantics=("arbitrary",)),
        interpret=interpret,
    )(wire3d)
    # cross-lane reduction epilogue: 36 lane sums, once per call
    return jnp.stack([jnp.sum(acc[:18], axis=1),
                      jnp.sum(acc[18:], axis=1)], axis=1)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _flagstat_blocked_v2(wire3d, tail, interpret=False):
    counts = _blocked_call_v2(wire3d, interpret=interpret)
    return counts + flagstat_kernel_wire32(tail)


def flagstat_pallas_wire32_v2(wire, interpret: bool = False) -> jnp.ndarray:
    """[18, 2] counters via the v2 deferred-reduction sweep ([512, 1024]
    u32 blocks); ragged tail (< one block) to the XLA core."""
    wire = np.asarray(wire, np.uint32)
    n_blk = wire.shape[0] // V2_BLOCK
    tail = wire[n_blk * V2_BLOCK:]
    if n_blk == 0:
        return flagstat_kernel_wire32(jnp.asarray(tail))
    wire3d = wire[:n_blk * V2_BLOCK].reshape(n_blk, V2_ROWS, LANES)
    return _flagstat_blocked_v2(jnp.asarray(wire3d), jnp.asarray(tail),
                                interpret=interpret)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _blocked_call(wire3d, *, interpret: bool):
    from jax.experimental.pallas import tpu as pltpu

    n_blk, rows, lanes = wire3d.shape
    return pl.pallas_call(
        _kernel,
        grid=(n_blk,),
        in_specs=[pl.BlockSpec((None, rows, lanes),
                               lambda i: (i, 0, 0))],
        out_specs=pl.BlockSpec(memory_space=pltpu.SMEM),
        out_shape=jax.ShapeDtypeStruct((18, 2), jnp.int32),
        compiler_params=pallas_tpu_compiler_params(
            dimension_semantics=("arbitrary",)),
        interpret=interpret,
    )(wire3d)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _flagstat_blocked(wire3d, tail, interpret=False):
    counts = _blocked_call(wire3d, interpret=interpret)
    return counts + flagstat_kernel_wire32(tail)


def _local_flagstat(wire, *, interpret: bool):
    """Traceable flat-wire flagstat: blocked Pallas sweep + XLA tail.
    Shapes are static under jit, so the block split happens at trace
    time; usable inside shard_map shards."""
    n = wire.shape[0]
    if _variant() == "v2":
        n_blk = n // V2_BLOCK
        if n_blk == 0:
            return flagstat_kernel_wire32(wire)
        w3 = wire[:n_blk * V2_BLOCK].reshape(n_blk, V2_ROWS, LANES)
        counts = _blocked_call_v2(w3, interpret=interpret)
        return counts + flagstat_kernel_wire32(wire[n_blk * V2_BLOCK:])
    n_blk = n // BLOCK
    if n_blk == 0:
        return flagstat_kernel_wire32(wire)
    w3 = wire[:n_blk * BLOCK].reshape(n_blk, BLOCK_ROWS, LANES)
    counts = _blocked_call(w3, interpret=interpret)
    return counts + flagstat_kernel_wire32(wire[n_blk * BLOCK:])


@functools.lru_cache(maxsize=None)
def flagstat_wire32_sharded_pallas(mesh, interpret: bool = False,
                                   donate: bool = False):
    """Mesh-sharded fast path: each shard runs the Pallas wire sweep on its
    local slice, counters psum over ICI — drop-in for
    :func:`..ops.flagstat.flagstat_wire32_sharded` (the streaming CLI
    kernel; reference: executor map + driver aggregate,
    FlagStat.scala:102-114).  ``interpret=True`` lets the virtual-CPU test
    mesh execute the same code path.  Memoized per (mesh, interpret,
    donate) so serve-mode job 2+ reuses the warm jit wrapper instead of
    recompiling (see flagstat.flagstat_wire32_sharded)."""
    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import READS_AXIS

    def fn(wire):
        counts = _local_flagstat(wire, interpret=interpret)
        return jax.lax.psum(counts, READS_AXIS)

    # check_vma=False: pallas_call's out_shape carries no varying-mesh-axes
    # annotation, and shard_map's vma checker rejects that once the shard
    # actually reaches the kernel (>= one VMEM block).  Shards below one
    # block take the XLA tail and never trip it — which is why only a
    # full-block dryrun caught this.
    # donate=True (the streaming executor's per-chunk feed) lets the
    # device reuse each chunk's wire HBM; see flagstat_wire32_sharded
    f = shard_map(fn, mesh=mesh, in_specs=(P(READS_AXIS),),
                      out_specs=P(), check_vma=False)
    return jax.jit(f, donate_argnums=(0,) if donate else ())


def flagstat_pallas_wire32(wire, interpret: bool = False) -> jnp.ndarray:
    """[18, 2] int32 counters off the 4-byte wire word, Pallas fast path.

    Splits the wire into 128x1024 VMEM blocks for the kernel and hands the
    ragged tail (< one block) to the XLA core; the two partial counter
    tensors add exactly (int32 sums).  ``interpret=True`` runs the Mosaic
    interpreter for CPU-backed tests.
    """
    if _variant() == "v2":
        return flagstat_pallas_wire32_v2(wire, interpret=interpret)
    wire = np.asarray(wire, np.uint32)
    n = wire.shape[0]
    n_blk = n // BLOCK
    wire3d = wire[:n_blk * BLOCK].reshape(n_blk, BLOCK_ROWS, LANES)
    tail = wire[n_blk * BLOCK:]
    if n_blk == 0:
        return flagstat_kernel_wire32(jnp.asarray(tail))
    return _flagstat_blocked(jnp.asarray(wire3d), jnp.asarray(tail),
                             interpret=interpret)


# ---------------------------------------------------------------------------
# ragged wire sweep: prefix-sum row bound instead of per-chunk padding
# ---------------------------------------------------------------------------
#
# The padded streaming path pads EVERY chunk's wire to a ladder rung and
# burns valid=0 words on the pad rows (<35% mean, but real device cycles).
# The ragged form dispatches one fixed-capacity CONCATENATION of many
# variable-length chunks: validity is positional — a row counts iff its
# flat index sits below the row-offset prefix sum's total — so the slack
# past the total may be arbitrary garbage (never zeroed, never shipped
# per-chunk) and the pad tax collapses to the final partial buffer.
# Same sequential grid and SMEM accumulator structure as the v1 sweep.

def _kernel_ragged(total_ref, wire_ref, out_ref):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        for k in range(18):
            out_ref[k, 0] = 0
            out_ref[k, 1] = 0

    wire = wire_ref[...]
    rows, lanes = wire.shape
    # global flat row index of every word in this block — the prefix-sum
    # walk: a word is live iff it sits below the offsets' total
    idx = (i * rows * lanes
           + jax.lax.broadcasted_iota(jnp.int32, (rows, lanes), 0) * lanes
           + jax.lax.broadcasted_iota(jnp.int32, (rows, lanes), 1))
    live = idx < total_ref[0]
    inds, passed, failed = _wire_masks(wire)
    passed &= live          # slack words may be garbage: the positional
    failed &= live          # bound gates them, not a valid bit
    for k, ind in enumerate(inds):
        out_ref[k, 0] += jnp.sum((ind & passed).astype(jnp.int32))
        out_ref[k, 1] += jnp.sum((ind & failed).astype(jnp.int32))


def _blocked_call_ragged(wire3d, total, *, interpret: bool):
    from jax.experimental.pallas import tpu as pltpu

    n_blk, rows, lanes = wire3d.shape
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n_blk,),
        in_specs=[pl.BlockSpec((None, rows, lanes),
                               lambda i, total_ref: (i, 0, 0))],
        out_specs=pl.BlockSpec(memory_space=pltpu.SMEM),
    )
    return pl.pallas_call(
        _kernel_ragged,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((18, 2), jnp.int32),
        compiler_params=pallas_tpu_compiler_params(
            dimension_semantics=("arbitrary",)),
        interpret=interpret,
    )(total, wire3d)


@jax.jit
def _flagstat_ragged_tail(tail, base, total):
    """XLA ragged tail: words at flat indices [base, base+len) count iff
    below ``total`` (a zeroed word carries valid=0, so one where does
    the positional masking)."""
    idx = base + jnp.arange(tail.shape[0], dtype=jnp.int32)
    return flagstat_kernel_wire32(jnp.where(idx < total, tail, 0))


@functools.partial(jax.jit, static_argnames=("interpret",))
def _flagstat_blocked_ragged(wire3d, tail, total, interpret=False):
    counts = _blocked_call_ragged(wire3d, total, interpret=interpret)
    n_blk, rows, lanes = wire3d.shape
    return counts + _flagstat_ragged_tail(
        tail, jnp.int32(n_blk * rows * lanes), total[0])


def flagstat_pallas_wire32_ragged(wire, row_offsets,
                                  interpret: bool = False) -> jnp.ndarray:
    """[18, 2] counters over a fixed-capacity concatenation of
    variable-length chunk wires — the ragged twin of
    :func:`flagstat_pallas_wire32`.

    ``row_offsets`` is the int32 prefix sum of the source chunks' row
    counts (``io/wirespill`` length-sidecar format, cumulated); only
    rows below ``row_offsets[-1]`` count, everything past it is slack
    the kernel never consumes.  The compiled shape depends only on the
    wire CAPACITY, so a whole run dispatches one shape regardless of how
    the input raggedly chunks — bit-identical to summing the padded
    kernel over the source chunks (exact int32 monoid), pinned by
    tests/test_ragged.py.
    """
    wire = np.asarray(wire, np.uint32)
    offs = np.asarray(row_offsets, np.int32)
    total = jnp.asarray(offs[-1:], jnp.int32)
    n_blk = wire.shape[0] // BLOCK
    tail = wire[n_blk * BLOCK:]
    if n_blk == 0:
        return _flagstat_ragged_tail(jnp.asarray(tail), jnp.int32(0),
                                     total[0])
    wire3d = wire[:n_blk * BLOCK].reshape(n_blk, BLOCK_ROWS, LANES)
    return _flagstat_blocked_ragged(jnp.asarray(wire3d), jnp.asarray(tail),
                                    total, interpret=interpret)


def flagstat_ragged_dispatch(wire, total, *, interpret: bool = False,
                             use_pallas: bool = False) -> jnp.ndarray:
    """[18, 2] counters off one fixed-capacity wire buffer (device or
    host array) with ``total`` live rows — the streaming ragged path's
    dispatcher (parallel/pipeline.py).  ``use_pallas`` routes full
    blocks through the ragged Mosaic sweep (interpret mode off-TPU);
    otherwise the one-where XLA form runs.  The buffer capacity is the
    only compiled shape either way."""
    wire = jnp.asarray(wire)
    tot = jnp.asarray([int(total)], jnp.int32)
    n_blk = wire.shape[0] // BLOCK
    if use_pallas and n_blk:
        w3 = wire[:n_blk * BLOCK].reshape(n_blk, BLOCK_ROWS, LANES)
        return _flagstat_blocked_ragged(w3, wire[n_blk * BLOCK:], tot,
                                        interpret=interpret)
    return _flagstat_ragged_tail(wire, jnp.int32(0), tot[0])


def flagstat_wire32_ragged_xla(wire, row_offsets) -> jnp.ndarray:
    """XLA fallback of the ragged sweep (the off-TPU product path): one
    fused where + the einsum core — the positional bound zeroes slack
    words (valid bit 0) instead of requiring pre-zeroed padding."""
    offs = np.asarray(row_offsets, np.int32)
    return _flagstat_ragged_tail(jnp.asarray(wire),
                                 jnp.int32(0),
                                 jnp.int32(int(offs[-1])))


# ---------------------------------------------------------------------------
# paged wire sweep: the page table replaces the fresh concat buffer
# ---------------------------------------------------------------------------
#
# The ragged sweep still consumes a freshly concatenated host buffer —
# one full-capacity device_put per dispatch, slack included.  The paged
# twin (docs/ARCHITECTURE.md §6l) reads the RESIDENT page pool
# (parallel/pagedbuf.PagePool): grid step i scalar-prefetches the page
# table and pulls physical page ``page_table[i]`` straight from the
# pool, so only delta pages ever crossed the link.  Validity stays
# positional — logical flat index below the prefix-sum total — exactly
# the ragged kernel's bound, so the two are bit-identical by
# construction over any page placement.

def _kernel_paged(pt_ref, total_ref, pool_ref, out_ref):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        for k in range(18):
            out_ref[k, 0] = 0
            out_ref[k, 1] = 0

    wire = pool_ref[...]            # physical page pt[i], via index_map
    rows, lanes = wire.shape
    # LOGICAL flat index: position in page-table order, not in the pool
    idx = (i * rows * lanes
           + jax.lax.broadcasted_iota(jnp.int32, (rows, lanes), 0) * lanes
           + jax.lax.broadcasted_iota(jnp.int32, (rows, lanes), 1))
    live = idx < total_ref[0]
    inds, passed, failed = _wire_masks(wire)
    passed &= live
    failed &= live
    for k, ind in enumerate(inds):
        out_ref[k, 0] += jnp.sum((ind & passed).astype(jnp.int32))
        out_ref[k, 1] += jnp.sum((ind & failed).astype(jnp.int32))


def _blocked_call_paged(pool3, page_table, total, *, interpret: bool):
    from jax.experimental.pallas import tpu as pltpu

    n_logical = page_table.shape[0]
    _, rows, lanes = pool3.shape
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(n_logical,),
        in_specs=[pl.BlockSpec((None, rows, lanes),
                               lambda i, pt_ref, total_ref:
                               (pt_ref[i], 0, 0))],
        out_specs=pl.BlockSpec(memory_space=pltpu.SMEM),
    )
    return pl.pallas_call(
        _kernel_paged,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((18, 2), jnp.int32),
        compiler_params=pallas_tpu_compiler_params(
            dimension_semantics=("arbitrary",)),
        interpret=interpret,
    )(page_table, total, pool3)


#: sublane tile of the paged Pallas block: pages must hold whole
#: [8, LANES] tiles to map onto kernel blocks
_PAGE_TILE = 8 * LANES


@functools.partial(jax.jit, static_argnames=("interpret",))
def _flagstat_paged_pallas(pool, page_table, total, interpret=False):
    P, page_rows = pool.shape
    pool3 = pool.reshape(P, page_rows // LANES, LANES)
    return _blocked_call_paged(pool3, page_table, total,
                               interpret=interpret)


@jax.jit
def flagstat_wire32_paged_xla(pool, page_table, total):
    """XLA fallback of the paged sweep (the off-TPU product path): one
    gather assembles the logical wire from the resident pool in
    page-table order, then the positional-bound einsum core — the
    ragged fallback fed by residency instead of a fresh concat."""
    from ..parallel.pagedbuf import gather_pages

    wire = gather_pages(pool, page_table)
    idx = jnp.arange(wire.shape[0], dtype=jnp.int32)
    return flagstat_kernel_wire32(jnp.where(idx < total, wire, 0))


def flagstat_pallas_wire32_paged(pool, page_table, total,
                                 interpret: bool = False) -> jnp.ndarray:
    """[18, 2] counters off the RESIDENT page pool — the paged twin of
    :func:`flagstat_pallas_wire32_ragged`.

    ``pool`` is the ``[pool_pages, page_rows]`` resident device array,
    ``page_table`` the int32 physical-page sequence in logical order,
    ``total`` the live-row prefix-sum bound (rows past it — including
    the repeated pad entries at the table's tail — are slack the kernel
    never counts).  The compiled shape depends only on the POOL
    geometry and the table length, so a serve lifetime dispatches one
    shape however tenants land in pages — bit-identical to the ragged
    concat sweep over the same logical rows (tests/test_paged.py).
    Pages whose size is not a multiple of the 8x1024 block tile route
    through the XLA gather form.
    """
    pool = jnp.asarray(pool)
    pt = jnp.asarray(page_table, jnp.int32)
    tot = jnp.asarray(np.asarray([int(total)], np.int32))
    if pool.shape[1] % _PAGE_TILE:
        return flagstat_wire32_paged_xla(pool, pt, tot[0])
    return _flagstat_paged_pallas(pool, pt, tot, interpret=interpret)


def flagstat_paged_dispatch(pool, page_table, total, *,
                            interpret: bool = False,
                            use_pallas: bool = False) -> jnp.ndarray:
    """[18, 2] counters off the resident pool — the streaming paged
    path's dispatcher (parallel/pipeline.py), mirroring
    :func:`flagstat_ragged_dispatch`: ``use_pallas`` routes through the
    scalar-prefetch Mosaic sweep (interpret mode off-TPU), otherwise
    the one-gather XLA form runs."""
    pool = jnp.asarray(pool)
    pt = jnp.asarray(page_table, jnp.int32)
    if use_pallas and pool.shape[1] % _PAGE_TILE == 0:
        tot = jnp.asarray(np.asarray([int(total)], np.int32))
        return _flagstat_paged_pallas(pool, pt, tot,
                                      interpret=interpret)
    return flagstat_wire32_paged_xla(pool, pt, jnp.int32(int(total)))


def available() -> bool:
    """True when the active backend can run the compiled kernel."""
    from ..platform import is_tpu_backend

    return is_tpu_backend()
