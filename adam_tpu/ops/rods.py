"""Rods: per-locus pileup groups, kept columnar.

Re-designs ``models/ADAMRod.scala`` + the rod functions of
``rdd/AdamRDDFunctions.scala`` (adamRecords2Rods :144-191,
adamPileupsToRods :252-258, adamSplitRodsBySamples :267-274,
adamDivideRodsBySamples :276-283, adamAggregateRods :285-296,
adamRodCoverage :298-314).

A rod is "all pileup bases at one locus".  The reference materializes a
Scala object per locus holding a List[ADAMPileup]; here a ``RodView`` is the
pileup table sorted by locus plus segment offsets — the same information
with zero per-locus allocation, and the layout segment-reductions want.

The reference's two-phase bucketed grouping (reads duplicated into 1-2
fixed-width 1000 bp buckets, then per-bucket locus grouping) exists to bound
shuffle skew; as written it also emits duplicate rods for reads that span a
bucket boundary (bucketedReadsToRods does not trim pileups to the bucket
range, :175-187).  The TPU design does not need the trick single-host — the
distribution analog (genome-bin sharding with boundary-read duplication and
halo trimming) lives in parallel/pileup.py — so ``reads_to_rods`` grouping is
a plain global sort+segment, which matches what the reference computes minus
the boundary duplicates.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Iterator, List, Optional, Tuple

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

from .pileup import aggregate_pileups, reads_to_pileups


@dataclass
class RodView:
    """Pileups sorted by locus (and optionally sample) with rod boundaries.

    rods[i] = pileups.slice(offsets[i], offsets[i+1]-offsets[i]) — all at
    position (ref_ids[i], positions[i]).
    """
    pileups: pa.Table
    ref_ids: np.ndarray      # [n_rods]
    positions: np.ndarray    # [n_rods]
    offsets: np.ndarray      # [n_rods + 1]
    by_sample: bool = False  # rods further split per sample

    def __len__(self) -> int:
        return len(self.ref_ids)

    def rod(self, i: int) -> pa.Table:
        return self.pileups.slice(self.offsets[i],
                                  self.offsets[i + 1] - self.offsets[i])

    def __iter__(self) -> Iterator[Tuple[int, int, pa.Table]]:
        for i in range(len(self)):
            yield int(self.ref_ids[i]), int(self.positions[i]), self.rod(i)


def _segment(pileups: pa.Table, keys: List[np.ndarray]) -> Tuple[pa.Table,
                                                                 np.ndarray,
                                                                 np.ndarray]:
    """Sort the table by key columns and return (sorted, starts, order)."""
    order = np.lexsort(tuple(reversed(keys)))
    sorted_t = pileups.take(pa.array(order))
    ks = [k[order] for k in keys]
    n = len(order)
    new = np.zeros(n, bool)
    if n:
        new[0] = True
    for k in ks:
        new[1:] |= k[1:] != k[:-1]
    return sorted_t, np.flatnonzero(new), order


def pileups_to_rods(pileups: pa.Table) -> RodView:
    """Group pileups by reference position (adamPileupsToRods :252-258)."""
    refid = pileups.column("referenceId").to_numpy(zero_copy_only=False)
    pos = pileups.column("position").to_numpy(zero_copy_only=False)
    sorted_t, starts, order = _segment(pileups, [refid, pos])
    offsets = np.append(starts, len(pileups))
    return RodView(sorted_t, refid[order][starts], pos[order][starts], offsets)


def reads_to_rods(table: pa.Table) -> RodView:
    """Reads → pileups → rods (adamRecords2Rods :144-191).

    The reference's ``bucketSize`` knob is deliberately absent: its
    bucketed shuffle is a distribution trick (see module docstring); the
    windowed streaming analog takes its window size from
    ``parallel.pipeline``'s genome bins, not from a rod-level parameter.
    """
    mapped = table.filter(pc.is_valid(table.column("start")))
    return pileups_to_rods(reads_to_pileups(mapped))


def split_rods_by_samples(rods: RodView) -> RodView:
    """Split each rod per sample, flat (adamSplitRodsBySamples :267-274)."""
    refid = rods.pileups.column("referenceId").to_numpy(zero_copy_only=False)
    pos = rods.pileups.column("position").to_numpy(zero_copy_only=False)
    sample = np.asarray(rods.pileups.column("recordGroupSample")
                        .to_pylist(), object)
    sample = np.where(sample == None, "", sample)  # noqa: E711
    sorted_t, starts, order = _segment(rods.pileups, [refid, pos, sample])
    offsets = np.append(starts, len(sorted_t))
    return RodView(sorted_t, refid[order][starts], pos[order][starts],
                   offsets, by_sample=True)


def divide_rods_by_samples(rods: RodView
                           ) -> List[Tuple[int, int, List[pa.Table]]]:
    """Per-position list of single-sample rods
    (adamDivideRodsBySamples :276-283)."""
    split = split_rods_by_samples(rods)
    out: List[Tuple[int, int, List[pa.Table]]] = []
    for r, p, t in split:
        if out and out[-1][0] == r and out[-1][1] == p:
            out[-1][2].append(t)
        else:
            out.append((r, p, [t]))
    return out


def aggregate_rods(rods: RodView) -> RodView:
    """In-rod evidence aggregation (adamAggregateRods :285-296)."""
    return pileups_to_rods(aggregate_pileups(rods.pileups))


def rod_coverage(rods: RodView) -> float:
    """Average pileup depth across covered loci (adamRodCoverage :298-314)."""
    if len(rods) == 0:
        return float("nan")
    return len(rods.pileups) / len(rods)
