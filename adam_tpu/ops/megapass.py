"""Fused mega-pass device kernel: one dispatch per chunk for flagstat
counters + markdup key columns + BQSR covariate counts.

``BENCH_TPU_EVIDENCE.json`` records the flagstat kernel at 0.06 GB/s of
device bandwidth — ~0.01% of HBM peak — because the hot path is
dispatch-latency-bound, not compute-bound: per chunk the product path
compiles and launches up to THREE separate executables that all read
the same wire planes (the flagstat indicator einsum; the markdup
5'-position/score kernel; the BQSR covariate pack + count fold, itself
two jit boundaries).  PR 7 collapsed the host-side re-streams of the
same bytes; this module collapses the device side the same way, the
ragged-paged-attention pattern (docs/ARCHITECTURE.md §6p): ONE jitted
multi-output program per layout that loads the base/qual/flag/position
planes once and emits

  * ``flagstat`` — the [18, 2] counter block
    (:func:`..ops.flagstat._flagstat_core`, the single indicator
    definition every flagstat kernel shares);
  * ``markdup`` — the per-read key columns ``(fp, score)``
    (:func:`..ops.markdup._device_fiveprime_and_score`, inlined under
    this jit);
  * ``bqsr`` — the 7 covariate count tensors
    (:func:`..bqsr.count_pallas._pack_words` /
    :func:`.._pack_words_flat` + the XLA segment-sum or Mosaic
    one-hot-matmul fold), sharing the ragged prefix-sum row walk with
    the other legs.

The composition is STRUCTURAL identity, never a re-implementation:
every leg calls the exact jitted monoid the unfused pass dispatches, so
fused results are bit-identical by construction (pinned over the
adversarial corpus on both the XLA and Mosaic-interpreter routes by
tests/test_megapass.py).  XLA fuses the shared plane loads across the
legs inside the single program; on TPU the BQSR fold runs the same
Mosaic kernel the unfused path runs (``impl="pallas"``).

The static ``want`` tuple selects the outputs, so a pass that needs one
leg compiles a program that computes one leg — arming the fused route
never computes unconsumed outputs.  Layout twins mirror the PR 8/13
machinery: ``megapass_padded`` ([N, L] planes), ``megapass_ragged``
(flat [T] planes + the prefix-sum row walk), ``megapass_paged`` (the
resident page pool; one gather reconstructs the ragged view, exactly
:func:`..bqsr.count_pallas.count_kernel_paged`'s delegation), plus the
wire32 entries for the streaming-flagstat product route.

Plan integration: ``decide_plan``'s replayable ``fused_device``
dimension (``-mega`` / ``ADAM_TPU_MEGA`` pin > ledger ``mega_race``
evidence > off, parallel/executor.py) arms the route;
``PassExecutor.dispatch`` counts every device dispatch per pass
(``dispatch_count{pass=}``), so the collapse is a gated number
(tools/bench_gate.py gate 10), not a story.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..packing import _round_up

#: every output leg the mega-pass can emit, in canonical order
WANT_ALL = ("flagstat", "markdup", "bqsr")


def _check_want(want) -> None:
    """Trace-time guard: ``want`` is a static tuple, so a typo'd leg
    name fails loudly at the first call, never silently drops output."""
    if not want or any(w not in WANT_ALL for w in want):
        raise ValueError(
            f"megapass want={want!r}: expected a non-empty subset of "
            f"{WANT_ALL}")


# ---------------------------------------------------------------------------
# the three legs — each one IS the unfused kernel's monoid, shared by
# reference so counter/key/count semantics cannot diverge from the
# standalone dispatches
# ---------------------------------------------------------------------------

def _flagstat_leg(flags, mapq, refid, mate_refid, valid):
    from .flagstat import _flagstat_core

    # exactly flagstat_kernel's call: raw mapq (null -1 fails the >=5
    # indicator the same way 0 does), cross bit from the refid compare
    return _flagstat_core(flags.astype(jnp.int32),
                          mapq.astype(jnp.int32),
                          refid != mate_refid, valid)


def _markdup_leg_padded(flags, start, cigar_ops, cigar_lens, n_cigar,
                        quals):
    from .markdup import _device_fiveprime_and_score

    # the jitted key kernel inlines under the enclosing mega-pass jit:
    # same 5'-position walk, same phred>=15 integer score sum
    return _device_fiveprime_and_score(flags, start, cigar_ops,
                                       cigar_lens, n_cigar, quals)


def _markdup_leg_ragged(flags, start, cigar_ops, cigar_lens, n_cigar,
                        quals_flat, row_of, n_bases, n_rows: int):
    from . import cigar as C

    fp = C.five_prime_position(start, flags, cigar_ops, cigar_lens,
                               n_cigar)
    # the padded leg's per-row sum as a segment reduction over the flat
    # plane; slack past n_bases is excluded POSITIONALLY (the ragged
    # contract) — the ragged batch's QUAL_PAD slack would fail the
    # >= 15 test anyway, but a paged gather's slack can alias real
    # pages, so the flat index is the only safe exclusion
    live = jnp.arange(quals_flat.shape[0], dtype=jnp.int32) < n_bases
    q = quals_flat
    score = jax.ops.segment_sum(
        jnp.where(live & (q >= 15), q, 0).astype(jnp.int32), row_of,
        num_segments=n_rows)
    return fp, score


def _bqsr_fold(word3, wbits3, n_qual_rg: int, n_cycle: int, impl: str,
               interpret: bool):
    """Packed covariate words -> the 7 count tensors: the same fold the
    unfused count dispatches (XLA segment-sum off-TPU, the Mosaic
    one-hot-matmul sweep on TPU)."""
    from ..bqsr.count_pallas import (_count_call, _count_flat_xla,
                                     _unpack_tables)

    if impl != "pallas":
        return _count_flat_xla(word3, wbits3, n_qual_rg=n_qual_rg,
                               n_cycle=n_cycle)
    q_rows = _round_up(n_qual_rg, 8)
    cyc_bins = _round_up(n_cycle, 128)
    obs, mm, qh = _count_call(word3, wbits3, q_rows=q_rows,
                              cyc_bins=cyc_bins, interpret=interpret)
    return _unpack_tables(obs, mm, qh, n_qual_rg=n_qual_rg,
                          n_cycle=n_cycle, cyc_bins=cyc_bins)


# ---------------------------------------------------------------------------
# layout entries: one jitted multi-output program per layout
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnames=("want", "n_qual_rg",
                                             "n_cycle", "impl",
                                             "interpret"))
def megapass_padded(flags, mapq, refid, mate_refid, valid, start,
                    cigar_ops, cigar_lens, n_cigar, bases, quals,
                    read_len, read_group, state, usable, *,
                    want=WANT_ALL, n_qual_rg: int = 0, n_cycle: int = 0,
                    impl: str = "xla", interpret: bool = True):
    """The padded-layout mega-pass: one compiled program computing the
    ``want`` legs off one set of [N]/[N, L] planes.  Unused inputs may
    be None (an un-selected leg's planes are never traced)."""
    from ..bqsr.count_pallas import _pack_words

    _check_want(want)
    out = {}
    if "flagstat" in want:
        out["flagstat"] = _flagstat_leg(flags, mapq, refid, mate_refid,
                                        valid)
    if "markdup" in want:
        out["markdup"] = _markdup_leg_padded(flags, start, cigar_ops,
                                             cigar_lens, n_cigar, quals)
    if "bqsr" in want:
        word3, wbits3 = _pack_words(bases, quals, read_len, flags,
                                    read_group, state, usable,
                                    n_qual_rg=n_qual_rg,
                                    n_cycle=n_cycle)
        out["bqsr"] = _bqsr_fold(word3, wbits3, n_qual_rg, n_cycle,
                                 impl, interpret)
    return out


@functools.partial(jax.jit, static_argnames=("want", "n_rows",
                                             "n_qual_rg", "n_cycle",
                                             "max_read_len", "impl",
                                             "interpret"))
def megapass_ragged(flags, mapq, refid, mate_refid, valid, start,
                    cigar_ops, cigar_lens, n_cigar, bases_flat,
                    quals_flat, row_of, pos_of, row_starts, read_len,
                    read_group, state_flat, usable, n_bases, *,
                    want=WANT_ALL, n_rows: int = 0, n_qual_rg: int = 0,
                    n_cycle: int = 0, max_read_len: int = 0,
                    impl: str = "xla", interpret: bool = True):
    """The ragged-layout twin: flat [T] planes + the prefix-sum row walk
    (packing.RaggedBatch), shared across all selected legs — slack past
    ``n_bases`` is excluded positionally, never by a valid bit."""
    from ..bqsr.count_pallas import _pack_words_flat

    _check_want(want)
    out = {}
    if "flagstat" in want:
        out["flagstat"] = _flagstat_leg(flags, mapq, refid, mate_refid,
                                        valid)
    if "markdup" in want:
        out["markdup"] = _markdup_leg_ragged(flags, start, cigar_ops,
                                             cigar_lens, n_cigar,
                                             quals_flat, row_of,
                                             n_bases, n_rows)
    if "bqsr" in want:
        word3, wbits3 = _pack_words_flat(
            bases_flat, quals_flat, row_of, pos_of, row_starts,
            read_len, flags, read_group, state_flat, usable, n_bases,
            n_rows=n_rows, n_qual_rg=n_qual_rg, n_cycle=n_cycle,
            max_read_len=max_read_len)
        out["bqsr"] = _bqsr_fold(word3, wbits3, n_qual_rg, n_cycle,
                                 impl, interpret)
    return out


@functools.partial(jax.jit, static_argnames=("want", "n_rows",
                                             "n_qual_rg", "n_cycle",
                                             "max_read_len", "impl",
                                             "interpret"))
def megapass_paged(pools, page_table, flags, mapq, refid, mate_refid,
                   valid, start, cigar_ops, cigar_lens, n_cigar,
                   row_starts, read_len, read_group, usable, n_bases, *,
                   want=WANT_ALL, n_rows: int = 0, n_qual_rg: int = 0,
                   n_cycle: int = 0, max_read_len: int = 0,
                   impl: str = "xla", interpret: bool = True):
    """The paged-layout twin: the RESIDENT page pools + this chunk's
    page table (parallel/pagedbuf).  One gather per plane reconstructs
    exactly the flat arrays the ragged entry consumes — the page-table
    walk IS the prefix-sum row walk relocated into residency, the
    ``count_kernel_paged`` delegation pattern — then the ragged body
    runs INSIDE the same compiled program, so paged results equal
    ragged ones bit-for-bit over any page placement.

    ``pools`` maps the :data:`..bqsr.count_pallas.PAGED_COUNT_PLANES`
    names to their ``[pool_pages, page_rows]`` device arrays (the
    ``bases``/``pos_of``/``state`` planes are only touched when the
    bqsr leg is wanted)."""
    from ..parallel.pagedbuf import gather_pages

    _check_want(want)
    pt = page_table.astype(jnp.int32)
    quals_flat = gather_pages(pools["quals"], pt)
    row_of = gather_pages(pools["row_of"], pt)
    out = {}
    if "flagstat" in want:
        out["flagstat"] = _flagstat_leg(flags, mapq, refid, mate_refid,
                                        valid)
    if "markdup" in want:
        out["markdup"] = _markdup_leg_ragged(flags, start, cigar_ops,
                                             cigar_lens, n_cigar,
                                             quals_flat, row_of,
                                             n_bases, n_rows)
    if "bqsr" in want:
        from ..bqsr.count_pallas import _pack_words_flat

        word3, wbits3 = _pack_words_flat(
            gather_pages(pools["bases"], pt), quals_flat, row_of,
            gather_pages(pools["pos_of"], pt), row_starts, read_len,
            flags, read_group, gather_pages(pools["state"], pt),
            usable, n_bases, n_rows=n_rows, n_qual_rg=n_qual_rg,
            n_cycle=n_cycle, max_read_len=max_read_len)
        out["bqsr"] = _bqsr_fold(word3, wbits3, n_qual_rg, n_cycle,
                                 impl, interpret)
    return out


# ---------------------------------------------------------------------------
# wire32 entries — the streaming-flagstat product route (the flagstat
# pass carries only the 26-bit projection word, not full batches)
# ---------------------------------------------------------------------------

@jax.jit
def megapass_wire32(wire):
    """Fused-route flagstat off one padded wire32 chunk: the same 26-bit
    unpack + indicator einsum as ``flagstat_kernel_wire32``, housed in
    the mega-pass program so the fused plan's one-dispatch accounting
    holds on the flagstat-only pass too."""
    from .flagstat import _flagstat_core

    flags = (wire & 0xFFFF).astype(jnp.int32)
    mapq = ((wire >> 16) & 0xFF).astype(jnp.int32)
    valid = ((wire >> 24) & 1) != 0
    cross = ((wire >> 25) & 1) != 0
    return _flagstat_core(flags, mapq, cross, valid)


@jax.jit
def megapass_wire32_bounded(wire, total):
    """The ragged-concat twin: fixed-capacity wire buffer with ``total``
    live rows — validity is positional (slack past the bound may hold
    garbage), exactly the ragged flagstat sweep's convention."""
    from .flagstat import _flagstat_core

    flags = (wire & 0xFFFF).astype(jnp.int32)
    mapq = ((wire >> 16) & 0xFF).astype(jnp.int32)
    valid = ((wire >> 24) & 1) != 0
    cross = ((wire >> 25) & 1) != 0
    live = jnp.arange(wire.shape[0], dtype=jnp.int32) < total
    return _flagstat_core(flags, mapq, cross, valid & live)


@jax.jit
def megapass_wire32_paged(pool, page_table, total):
    """The paged twin: gather the logical wire from the resident pool,
    then the bounded sweep — one compiled program, only delta pages
    ever crossed the link."""
    from ..parallel.pagedbuf import gather_pages

    wire = gather_pages(pool, page_table.astype(jnp.int32))
    return megapass_wire32_bounded(wire, total)


# ---------------------------------------------------------------------------
# host conveniences — batch objects -> the jitted entries (tests/bench)
# ---------------------------------------------------------------------------

def megapass_from_batch(batch, *, want=WANT_ALL, state=None, usable=None,
                        n_qual_rg: int = 0, n_cycle: int = 0,
                        impl: str = "xla", interpret: bool = True):
    """Run the padded mega-pass off a :class:`..packing.ReadBatch`.
    ``state``/``usable``/table geometry are required only when ``want``
    includes the bqsr leg."""
    a = jnp.asarray
    need_bqsr = "bqsr" in want
    return megapass_padded(
        a(batch.flags), a(batch.mapq), a(batch.refid),
        a(batch.mate_refid), a(batch.valid), a(batch.start),
        a(batch.cigar_ops), a(batch.cigar_lens), a(batch.n_cigar),
        a(batch.bases) if need_bqsr else None, a(batch.quals),
        a(batch.read_len) if need_bqsr else None,
        a(batch.read_group) if need_bqsr else None,
        None if state is None else a(state),
        None if usable is None else a(usable),
        want=tuple(want), n_qual_rg=n_qual_rg, n_cycle=n_cycle,
        impl=impl, interpret=interpret)


def megapass_from_ragged(rb, *, want=WANT_ALL, state_flat=None,
                         usable=None, n_qual_rg: int = 0,
                         n_cycle: int = 0, max_read_len: int = 0,
                         impl: str = "xla", interpret: bool = True):
    """Run the ragged mega-pass off a :class:`..packing.RaggedBatch`
    (or the paged gather view, which carries the same fields)."""
    a = jnp.asarray
    need_bqsr = "bqsr" in want
    return megapass_ragged(
        a(rb.flags), a(rb.mapq), a(rb.refid), a(rb.mate_refid),
        a(rb.valid), a(rb.start), a(rb.cigar_ops), a(rb.cigar_lens),
        a(rb.n_cigar),
        a(rb.bases_flat) if need_bqsr else None, a(rb.quals_flat),
        a(rb.row_of),
        a(rb.pos_of) if need_bqsr else None,
        a(rb.row_offsets[:-1]),
        a(rb.read_len) if need_bqsr else None,
        a(rb.read_group) if need_bqsr else None,
        None if state_flat is None else a(state_flat),
        None if usable is None else a(usable),
        jnp.int32(rb.n_bases),
        want=tuple(want), n_rows=rb.n_reads, n_qual_rg=n_qual_rg,
        n_cycle=n_cycle, max_read_len=max_read_len, impl=impl,
        interpret=interpret)


# ---------------------------------------------------------------------------
# single-leg conveniences — the product wiring's fused routes call these
# so a fused pass that only needs one leg compiles a one-leg program
# ---------------------------------------------------------------------------

def megapass_markdup(flags, start, cigar_ops, cigar_lens, n_cigar,
                     quals):
    """Fused-route markdup keys (stream 1): the mega-pass program with
    ``want=("markdup",)`` — argument order matches
    :func:`..ops.markdup._device_fiveprime_and_score` so the call site
    swaps in place."""
    return megapass_padded(
        flags, None, None, None, None, start, cigar_ops, cigar_lens,
        n_cigar, None, quals, None, None, None, None,
        want=("markdup",))["markdup"]


def megapass_bqsr(bases, quals, read_len, flags, read_group, state,
                  usable, *, n_qual_rg: int, n_cycle: int,
                  impl: str = "xla", interpret: bool = True):
    """Fused-route padded BQSR counts (s2): the mega-pass program with
    ``want=("bqsr",)`` — argument order matches
    :func:`..bqsr.count_pallas.count_kernel_pallas`."""
    return megapass_padded(
        flags, None, None, None, None, None, None, None, None, bases,
        quals, read_len, read_group, state, usable, want=("bqsr",),
        n_qual_rg=n_qual_rg, n_cycle=n_cycle, impl=impl,
        interpret=interpret)["bqsr"]


def megapass_bqsr_paged(pools, page_table, *, row_starts, read_len,
                        flags, read_group, usable, n_bases,
                        n_rows: int, n_qual_rg: int, n_cycle: int,
                        max_read_len: int, impl: str = "xla",
                        interpret: bool = True):
    """Fused-route paged BQSR counts: the paged mega-pass program with
    ``want=("bqsr",)`` — keyword surface matches
    :func:`..bqsr.count_pallas.count_kernel_paged` minus the delegated
    knobs."""
    return megapass_paged(
        pools, page_table, flags, None, None, None, None, None, None,
        None, None, row_starts, read_len, read_group, usable,
        jnp.int32(n_bases), want=("bqsr",), n_rows=n_rows,
        n_qual_rg=n_qual_rg, n_cycle=n_cycle,
        max_read_len=max_read_len, impl=impl,
        interpret=interpret)["bqsr"]
