"""Pileup engine: read -> per-base pileup records, and pileup aggregation.

Re-designs ``rdd/Reads2PileupProcessor.scala`` (the CIGAR+MD walk emitting one
ADAMPileup per base, :34-194) and ``rdd/PileupAggregator.scala`` (group by
position / (base, rangeOffset, sample), evidence combination :25-218).

The reference walks each read with a per-base Scala loop inside ``flatMap``
(data amplification ~readLen x).  Here the walk geometry (per-base reference
positions under pileup rules, op codes, in-op offsets) is one batched device
kernel over the packed cigar columns, and record assembly is vectorized Arrow
takes over the emitted (read, base) index pairs.  Aggregation becomes
sort+segment reductions instead of a shuffle.

Emission semantics (Reads2PileupProcessor.readToPileups :34-194):
  * reads without a CIGAR or MD tag emit nothing (:35-39);
  * M bases emit readBase + referenceBase (read base when MD matches, MD
    mismatch base otherwise);
  * I bases emit readBase at the *current* reference position (not advanced),
    rangeOffset/rangeLength set, null referenceBase;
  * S bases emit like I plus numSoftClipped=1 (:164-183); the reference
    position is pinned, i.e. soft clips pile on the boundary base;
  * D positions emit referenceBase from the MD deletion record, no readBase,
    sangerQuality of the next read base (:146-161 uses the post-deletion
    readPos — mirrored);
  * N/H/P advance silently per their consume rules.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

from .. import schema as S
from ..packing import ReadBatch, pack_reads
from ..util.mdtag import MdTag
from . import cigar as C

_BASES_ARR = np.frombuffer(S.BASES.encode(), np.uint8)

# pileup-walk advance: ops that consume reference (M D N = X)
_PILEUP_ADVANCES = np.array(S.CIGAR_CONSUMES_REF, np.int32)
_CONSUMES_READ = np.array(S.CIGAR_CONSUMES_READ, np.int32)


@partial(jax.jit, static_argnames=("max_len",))
def pileup_walk(start, cigar_ops, cigar_lens, max_len: int):
    """Per-read-base pileup geometry.

    Returns (pos, op, off_in_op, op_len, in_read), all [N, L]:
      pos       reference position each read base piles onto (I/S pinned at
                the op's start position)
      op        cigar op code owning the base
      off_in_op 0-based offset within the op (rangeOffset for I/S)
      op_len    length of the owning op (rangeLength)
      in_read   mask of real read bases
    """
    N, Cc = cigar_ops.shape
    ops_safe = jnp.where(cigar_ops < 0, 0, cigar_ops)
    consumes_read = C._table(_CONSUMES_READ, cigar_ops) * cigar_lens
    walk_adv = C._table(_PILEUP_ADVANCES, cigar_ops) * cigar_lens

    read_cum = jnp.cumsum(consumes_read, axis=-1)
    read_begin = read_cum - consumes_read
    walk_cum = jnp.cumsum(walk_adv, axis=-1)
    walk_begin = start[:, None] + (walk_cum - walk_adv)

    offs = jnp.arange(max_len, dtype=read_cum.dtype)
    owned = offs[None, :, None] >= read_cum[:, None, :]
    slot = jnp.clip(jnp.sum(owned.astype(jnp.int32), axis=-1), 0, Cc - 1)

    op_at = jnp.take_along_axis(ops_safe, slot, axis=1)
    begin_at = jnp.take_along_axis(read_begin, slot, axis=1)
    walk_at = jnp.take_along_axis(walk_begin, slot, axis=1)
    len_at = jnp.take_along_axis(cigar_lens, slot, axis=1)
    off_in_op = offs[None, :] - begin_at
    advances = C._table(_PILEUP_ADVANCES, op_at) > 0
    pos = jnp.where(advances, walk_at + off_in_op, walk_at)
    in_read = offs[None, :] < read_cum[:, -1:]
    return pos, op_at, off_in_op, len_at, in_read


def _col_valid(col) -> np.ndarray:
    """Arrow (chunked) column -> bool validity numpy array."""
    arr = col.combine_chunks() if isinstance(col, pa.ChunkedArray) else col
    if len(arr) == 0:
        return np.zeros(0, bool)
    return np.asarray(arr.is_valid())


def _md_lookup_arrays(mds, starts, usable_rows):
    """Parse MD tags (host) into flat lookup arrays.

    ``mds`` is an Arrow string column (fast path: one native C pass over
    its offsets+data buffers) or a Python list (fallback FSM).  Returns
    (mm_keys, mm_bases, del_keys, del_bases) where keys combine
    (read_row << 34 | ref_pos), sorted, for vectorized searchsorted
    lookups.
    """
    native = None
    if isinstance(mds, (pa.ChunkedArray, pa.Array)):
        try:
            import adam_tpu_native as N
            native = getattr(N, "md_parse", None)
        except ImportError:  # pragma: no cover - toolchain-less envs
            native = None
        if native is not None:
            arr = mds.combine_chunks() if isinstance(mds, pa.ChunkedArray) \
                else mds
            if len(arr) == 0:
                z = np.zeros(0, np.int64), np.zeros(0, np.uint8)
                return z[0], z[1], z[0].copy(), z[1].copy()
            bufs = arr.buffers()
            offsets = np.frombuffer(bufs[1], np.int32, count=len(arr) + 1,
                                    offset=arr.offset * 4)
            data = np.frombuffer(bufs[2], np.uint8) \
                if bufs[2] is not None else np.zeros(0, np.uint8)
            mm_k, mm_b, del_k, del_b = native(
                offsets, data,
                np.ascontiguousarray(usable_rows, np.int64),
                np.ascontiguousarray(starts, np.int64))
            return (np.frombuffer(mm_k, np.int64).copy(),
                    np.frombuffer(mm_b, np.uint8).copy(),
                    np.frombuffer(del_k, np.int64).copy(),
                    np.frombuffer(del_b, np.uint8).copy())
        mds = mds.to_pylist()
    mm_k, mm_b, del_k, del_b = [], [], [], []
    for row in usable_rows:
        md = MdTag.parse(mds[row], int(starts[row]))
        base = np.int64(row) << 34
        for p, b in md.mismatches.items():
            mm_k.append(base | p)
            mm_b.append(ord(b))
        for p, b in md.deletes.items():
            del_k.append(base | p)
            del_b.append(ord(b))
    def sorted_pair(keys, bases):
        k = np.array(keys, np.int64)
        b = np.array(bases, np.uint8)
        o = np.argsort(k)
        return k[o], b[o]
    return sorted_pair(mm_k, mm_b) + sorted_pair(del_k, del_b)


def _lookup(keys: np.ndarray, table_keys: np.ndarray, table_vals: np.ndarray,
            default=0):
    """Vectorized dict lookup via searchsorted; missing -> default."""
    if len(table_keys) == 0:
        return np.full(len(keys), default, table_vals.dtype if len(table_vals)
                       else np.uint8), np.zeros(len(keys), bool)
    idx = np.searchsorted(table_keys, keys)
    idx = np.minimum(idx, len(table_keys) - 1)
    found = table_keys[idx] == keys
    return np.where(found, table_vals[idx], default), found


def reads_to_pileups(table: pa.Table, batch: Optional[ReadBatch] = None
                     ) -> pa.Table:
    """adamRecords2Pileup (AdamRDDFunctions.scala:130-142) — reads table ->
    ADAMPileup table (PILEUP_SCHEMA)."""
    n = table.num_rows
    if n == 0:
        return pa.Table.from_pydict(
            {f: [] for f in S.PILEUP_SCHEMA.names}, schema=S.PILEUP_SCHEMA)
    if batch is None:
        batch = pack_reads(table)
    L = batch.max_len

    pos_d, op_d, off_d, oplen_d, inread_d = pileup_walk(
        jnp.asarray(batch.start), jnp.asarray(batch.cigar_ops),
        jnp.asarray(batch.cigar_lens), L)
    end_d = C.read_end(jnp.asarray(batch.start), jnp.asarray(batch.cigar_ops),
                       jnp.asarray(batch.cigar_lens))
    pos = np.asarray(pos_d)[:n]
    op = np.asarray(op_d)[:n]
    off = np.asarray(off_d)[:n]
    oplen = np.asarray(oplen_d)[:n]
    in_read = np.asarray(inread_d)[:n]
    read_end = np.asarray(end_d)[:n]

    md_col = table.column("mismatchingPositions")
    usable = _col_valid(md_col) & _col_valid(table.column("cigar"))
    usable_rows = np.flatnonzero(usable)
    starts = np.asarray(batch.start[:n], np.int64)
    mm_keys, mm_bases, del_keys, del_bases = _md_lookup_arrays(
        md_col, starts, usable_rows)

    # ---- read-base emissions: ops M, I, S
    emit = in_read & usable[:, None] & ((op == S.CIGAR_M) | (op == S.CIGAR_I) |
                                        (op == S.CIGAR_S))
    rrow, rcol = np.nonzero(emit)
    e_pos = pos[rrow, rcol].astype(np.int64)
    e_op = op[rrow, rcol]
    read_base = _BASES_ARR[np.asarray(batch.bases[:n])[rrow, rcol]]
    sanger = np.asarray(batch.quals[:n])[rrow, rcol].astype(np.int32)

    is_m = e_op == S.CIGAR_M
    keys = (rrow.astype(np.int64) << 34) | e_pos
    mm_base, mm_found = _lookup(keys, mm_keys, mm_bases)
    ref_base = np.where(is_m, np.where(mm_found, mm_base, read_base), 0)

    # ---- deletion emissions: walk D ops host-side from the packed cigars
    ops_np = np.asarray(batch.cigar_ops[:n])
    lens_np = np.asarray(batch.cigar_lens[:n])
    is_d_op = (ops_np == S.CIGAR_D) & usable[:, None]
    drow_op, dslot = np.nonzero(is_d_op)
    # reference position at the start of each D op; read bases consumed before
    ref_adv = _PILEUP_ADVANCES[np.where(ops_np < 0, 0, ops_np)] * lens_np
    read_adv = _CONSUMES_READ[np.where(ops_np < 0, 0, ops_np)] * lens_np
    ref_before = np.cumsum(ref_adv, axis=1) - ref_adv
    read_before = np.cumsum(read_adv, axis=1) - read_adv
    d_len = lens_np[drow_op, dslot]
    d_rows = np.repeat(drow_op, d_len)
    d_off = np.arange(int(d_len.sum())) - np.repeat(np.cumsum(d_len) - d_len,
                                                    d_len)
    d_pos = starts[d_rows] + ref_before[drow_op, dslot].repeat(d_len) + d_off
    d_readpos = read_before[drow_op, dslot].repeat(d_len)
    d_lenv = d_len.repeat(d_len)
    d_keys = (d_rows.astype(np.int64) << 34) | d_pos
    d_base, d_found = _lookup(d_keys, del_keys, del_bases)
    if len(d_keys) and not d_found.all():
        raise ValueError("CIGAR delete but the MD tag is not a delete")
    qual_np = np.asarray(batch.quals[:n])
    d_sanger = qual_np[d_rows, np.minimum(d_readpos, L - 1)].astype(np.int32)

    # ---- assemble the Arrow table: base rows then deletion rows
    all_rows = np.concatenate([rrow, d_rows]).astype(np.int64)
    flags = np.asarray(batch.flags[:n])
    reverse = (flags & S.FLAG_REVERSE) != 0

    def chars_to_str_array(codes, null_mask):
        vals = [chr(c) if not nb else None
                for c, nb in zip(codes.tolist(), null_mask.tolist())]
        return pa.array(vals, pa.string())

    n_base = len(rrow)
    n_del = len(d_rows)
    col = {
        "position": pa.array(np.concatenate([e_pos, d_pos]), pa.int64()),
        "rangeOffset": pa.array(
            np.concatenate([off[rrow, rcol], d_off]).astype("int32"),
            pa.int32(), mask=np.concatenate([is_m, np.zeros(n_del, bool)])),
        "rangeLength": pa.array(
            np.concatenate([oplen[rrow, rcol], d_lenv]).astype("int32"),
            pa.int32(), mask=np.concatenate([is_m, np.zeros(n_del, bool)])),
        "readBase": chars_to_str_array(
            np.concatenate([read_base, np.zeros(n_del, np.uint8)]),
            np.concatenate([np.zeros(n_base, bool), np.ones(n_del, bool)])),
        "referenceBase": chars_to_str_array(
            np.concatenate([ref_base, d_base]).astype(np.uint8),
            np.concatenate([~is_m, np.zeros(n_del, bool)])),
        "sangerQuality": pa.array(np.concatenate([sanger, d_sanger]),
                                  pa.int32()),
        "numSoftClipped": pa.array(
            np.concatenate([(e_op == S.CIGAR_S).astype("int32"),
                            np.zeros(n_del, np.int32)]), pa.int32()),
        "numReverseStrand": pa.array(
            reverse[all_rows].astype("int32"), pa.int32()),
        "countAtPosition": pa.array(np.ones(len(all_rows), np.int32),
                                    pa.int32()),
        "readStart": pa.array(starts[all_rows], pa.int64()),
        "readEnd": pa.array(read_end[all_rows].astype("int64"), pa.int64()),
    }
    take_idx = pa.array(all_rows)
    passthrough = {
        "referenceName": "referenceName", "referenceId": "referenceId",
        "mapQuality": "mapq", "readName": "readName",
    }
    for rg in ("recordGroupSequencingCenter", "recordGroupDescription",
               "recordGroupRunDateEpoch", "recordGroupFlowOrder",
               "recordGroupKeySequence", "recordGroupLibrary",
               "recordGroupPredictedMedianInsertSize", "recordGroupPlatform",
               "recordGroupPlatformUnit", "recordGroupSample"):
        passthrough[rg] = rg
    for dst, src in passthrough.items():
        col[dst] = table.column(src).take(take_idx).combine_chunks() \
            .cast(S.PILEUP_SCHEMA.field(dst).type)

    return pa.Table.from_pydict(
        {name: col[name] for name in S.PILEUP_SCHEMA.names},
        schema=S.PILEUP_SCHEMA)


# ----------------------------------------------------------------------
# aggregation (PileupAggregator.scala:25-218)
# ----------------------------------------------------------------------

_SUMMED = ("numSoftClipped", "numReverseStrand")
_JOINED_RG = ("recordGroupSequencingCenter", "recordGroupDescription",
              "recordGroupFlowOrder", "recordGroupKeySequence",
              "recordGroupLibrary", "recordGroupPlatform",
              "recordGroupPlatformUnit", "recordGroupSample")
_SINGLE_RG = ("recordGroupRunDateEpoch", "recordGroupPredictedMedianInsertSize")


def _distinct_per_list(col) -> tuple:
    """First-seen distinct non-null elements of a list column, vectorized.

    Returns (parents [K], flat_indices [K], n_lists, flat_values): the
    distinct elements of list g, in first-seen order, are
    ``flat_values.take(flat_indices[parents == g])``; ``n_lists`` is the
    number of input lists (parents for empty lists never appear).  No
    per-group Python — the old per-group dict.fromkeys comprehension
    dominated aggregate_pileups at genome scale (VERDICT r1 weak #7).
    """
    arr = col.combine_chunks()
    lengths = pc.fill_null(pc.list_value_length(arr), 0) \
        .to_numpy(zero_copy_only=False)
    values = arr.flatten()  # exactly the list elements, in list order
    parents = np.repeat(np.arange(len(arr), dtype=np.int64), lengths)
    valid = pc.is_valid(values).to_numpy(zero_copy_only=False)
    idx0 = np.flatnonzero(valid)
    if len(idx0) == 0:
        return np.zeros(0, np.int64), idx0, len(arr), values
    enc = values.dictionary_encode()
    codes = enc.indices.to_numpy(zero_copy_only=False)[idx0].astype(np.int64)
    key = (parents[idx0] << 32) | codes
    _, first = np.unique(key, return_index=True)
    sel = np.sort(first)  # flattened order == per-parent first-seen order
    orig = idx0[sel]
    return parents[orig], orig, len(arr), values


def _join_distinct_lists(col) -> pa.Array:
    """",".join(distinct non-null) per list, empty -> null."""
    parents, orig, n, values = _distinct_per_list(col)
    counts = np.bincount(parents, minlength=n)
    offs = np.zeros(n + 1, np.int64)
    np.cumsum(counts, out=offs[1:])
    lists = pa.ListArray.from_arrays(pa.array(offs, pa.int32()),
                                     values.take(pa.array(orig)))
    joined = pc.binary_join(lists, ",")
    return pc.if_else(pc.equal(joined, ""), pa.nulls(n, pa.string()), joined)


def _single_distinct_lists(col, typ) -> pa.Array:
    """The value when a list holds exactly one distinct non-null, else null."""
    parents, orig, n, values = _distinct_per_list(col)
    counts = np.bincount(parents, minlength=n)
    single = counts == 1
    starts = np.searchsorted(parents, np.arange(n))
    if len(orig) == 0:
        return pa.nulls(n, typ)
    picked = values.take(pa.array(orig[np.minimum(starts, len(orig) - 1)]))
    return pc.if_else(pa.array(single), picked.cast(typ), pa.nulls(n, typ))


def aggregate_pileups(pileups: pa.Table, validate: bool = False) -> pa.Table:
    """Aggregate pileups by (position, readBase, rangeOffset, sample).

    Quality merging follows the *intent* of combineEvidence
    (PileupAggregator.scala:155-175): count-weighted sum of map/sanger
    qualities divided by total count ("phred is logarithmic so geometric mean
    is sum / count").  The reference's pairwise left-fold re-weights
    already-summed qualities for groups of 3+ (:161-167) — a bug we do not
    reproduce; we compute the exact sum/count.
    """
    if validate:
        for f in ("mapQuality", "sangerQuality", "countAtPosition",
                  "numSoftClipped", "numReverseStrand", "readName",
                  "readStart", "readEnd"):
            if pileups.column(f).null_count:
                raise ValueError(
                    f"Cannot aggregate pileup with required field null: {f}")
    count = pileups.column("countAtPosition")
    weighted = pileups.append_column(
        "wMapQ", pc.multiply(pileups.column("mapQuality"), count)) \
        .append_column(
        "wSangerQ", pc.multiply(pileups.column("sangerQuality"), count))

    keys = ["referenceId", "position", "readBase", "rangeOffset",
            "recordGroupSample"]
    aggs = [("wMapQ", "sum"), ("wSangerQ", "sum"),
            ("countAtPosition", "sum"),
            ("readStart", "min"), ("readEnd", "max"),
            ("readName", "list"),
            ("referenceName", "first"), ("referenceBase", "first"),
            ("rangeLength", "first")]
    aggs += [(f, "sum") for f in _SUMMED]
    aggs += [(f, "list") for f in _JOINED_RG]
    aggs += [(f, "list") for f in _SINGLE_RG]
    g = weighted.group_by(keys, use_threads=False).aggregate(aggs)

    total = g.column("countAtPosition_sum")
    out = {
        "referenceName": g.column("referenceName_first"),
        "referenceId": g.column("referenceId"),
        "position": g.column("position"),
        "rangeOffset": g.column("rangeOffset"),
        "rangeLength": g.column("rangeLength_first"),
        "referenceBase": g.column("referenceBase_first"),
        "readBase": g.column("readBase"),
        "sangerQuality": pc.cast(
            pc.divide(g.column("wSangerQ_sum"), total), pa.int32()),
        "mapQuality": pc.cast(
            pc.divide(g.column("wMapQ_sum"), total), pa.int32()),
        "numSoftClipped": pc.cast(g.column("numSoftClipped_sum"), pa.int32()),
        "numReverseStrand": pc.cast(g.column("numReverseStrand_sum"),
                                    pa.int32()),
        "countAtPosition": pc.cast(total, pa.int32()),
        "readName": pc.binary_join(g.column("readName_list"), ","),
        "readStart": g.column("readStart_min"),
        "readEnd": g.column("readEnd_max"),
    }
    # record-group strings: comma-join *distinct* non-null values (:83-152)
    for f in _JOINED_RG:
        out[f] = _join_distinct_lists(g.column(f"{f}_list"))
    # numeric rg fields: only kept when single-valued (:99-104,:131-136)
    for f, typ in zip(_SINGLE_RG, (pa.int64(), pa.int32())):
        out[f] = _single_distinct_lists(g.column(f"{f}_list"), typ)

    return pa.Table.from_pydict(
        {name: out[name] for name in S.PILEUP_SCHEMA.names},
        schema=S.PILEUP_SCHEMA)
