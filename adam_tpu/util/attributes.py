"""Typed SAM optional-field attributes.

Mirrors ``models/Attribute.scala:29-48`` (the ``tag:type:value`` triple with
its SAM-spec type letters) and ``util/AttributeUtils.scala:26-103`` (parsing
the tab-separated ``attributes`` column back into typed values).  The read
schema stores attributes exactly as the reference does — one string column of
``TAG:T:value`` entries joined by tabs (adam.avdl:48-53) — and this module is
the typed view over it.
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from enum import Enum
from typing import Any, List, Sequence, Union


class TagType(Enum):
    """SAM optional-field type letters (SAMv1 spec §1.5)."""

    CHARACTER = "A"
    INTEGER = "i"
    FLOAT = "f"
    STRING = "Z"
    BYTE_SEQUENCE = "H"
    NUMERIC_SEQUENCE = "B"

    def __str__(self) -> str:
        return self.value


@dataclass(frozen=True)
class Attribute:
    """One typed optional field (Attribute.scala:29-31).

    ``array_subtype`` preserves the element-type letter of a B-typed array
    (``c/C/s/S/i/I/f``) so round-tripping keeps the on-disk encoding.
    """

    tag: str
    tag_type: TagType
    value: Any
    array_subtype: Union[str, None] = None

    def __str__(self) -> str:
        if self.tag_type is TagType.NUMERIC_SEQUENCE:
            head = self.array_subtype or (
                "f" if any(isinstance(v, float) for v in self.value) else "i")
            body = head + "," + ",".join(str(v) for v in self.value)
        elif self.tag_type is TagType.BYTE_SEQUENCE:
            body = "".join(f"{b:02X}" for b in self.value)
        else:
            body = str(self.value)
        return f"{self.tag}:{self.tag_type}:{body}"


_ATTR_RE = re.compile(r"^([^:]{2}):([AifZHB])(?::(.*))?$")


def _typed_value(type_letter: str, text: str):
    if type_letter == "A":
        if not text:
            raise ValueError("empty value for A-typed attribute")
        return text[0], None
    if type_letter == "i":
        return int(text), None
    if type_letter == "f":
        return float(text), None
    if type_letter == "Z":
        return text, None
    if type_letter == "H":
        return bytes.fromhex(text), None
    # B: first subfield is the element type letter, then comma-separated
    parts = text.split(",")
    if parts and parts[0] in ("c", "C", "s", "S", "i", "I", "f"):
        elem, parts = parts[0], parts[1:]
    else:  # tolerate the bare form the reference accepts
        elem = None
    if elem == "f" or any("." in p or "e" in p.lower() for p in parts):
        return [float(p) for p in parts], elem
    return [int(p) for p in parts], elem


def parse_attribute(encoded: str) -> Attribute:
    """``TAG:T:value`` -> :class:`Attribute` (AttributeUtils.scala:62-71)."""
    m = _ATTR_RE.match(encoded)
    if not m:
        raise ValueError(
            f"attribute string {encoded!r} doesn't match tag:type:value")
    tag, letter, text = m.group(1), m.group(2), m.group(3) or ""
    value, subtype = _typed_value(letter, text)
    return Attribute(tag, TagType(letter), value, subtype)


def parse_attributes(tag_string: Union[str, None]) -> List[Attribute]:
    """Parse the tab-joined ``attributes`` column value
    (AttributeUtils.scala:53-58); empty/None -> []."""
    if not tag_string:
        return []
    return [parse_attribute(s) for s in tag_string.split("\t") if s]


def format_attributes(attrs: Sequence[Attribute]) -> str:
    """Inverse of :func:`parse_attributes`: the on-disk column encoding."""
    return "\t".join(str(a) for a in attrs)
