"""MD ("mismatching positions") tag machinery — host side.

Faithful re-implementation of ``util/MdTag.scala`` (the load-bearing string
logic for BQSR masking, pileup emission, reference reconstruction and
realignment rewrites): the parse FSM (:38-98), ``moveAlignment`` re-derivation
after a cigar change (:137-233), ``getReference`` reconstruction (:306-372)
and the ``toString`` FSM (:380-442).

MD strings follow ``[0-9]+(([A-Z]+|\\^[A-Z]+)[0-9]+)*`` where runs of digits
count matching bases, letters are reference bases at mismatches, and ``^``
precedes deleted reference bases.  Positions here are absolute 0-based
reference coordinates, like the reference implementation.

The device-facing view (per-base mismatch masks / reference base codes) lives
in :mod:`adam_tpu.ops.mdtag_masks`.
"""

from __future__ import annotations

import re
from typing import Dict, List, Optional, Tuple

_DIGITS = re.compile(r"\d+")
_BASES = re.compile(r"[AaGgCcTtNnUuKkMmRrSsWwBbVvHhDdXxYy]+")

# cigar text helpers (replaces samtools TextCigarCodec)
_CIGAR_ELEM = re.compile(r"(\d+)([MIDNSHP=X])")


def parse_cigar(cigar: str) -> List[Tuple[int, str]]:
    """CIGAR text -> [(length, op)] list."""
    if not cigar or cigar == "*":
        return []
    elems = _CIGAR_ELEM.findall(cigar)
    if "".join(f"{l}{o}" for l, o in elems) != cigar:
        raise ValueError(f"malformed cigar {cigar!r}")
    return [(int(l), o) for l, o in elems]


def cigar_to_string(elems: List[Tuple[int, str]]) -> str:
    return "".join(f"{l}{o}" for l, o in elems)


_CONSUMES_READ = set("MIS=X")
_CONSUMES_REF = set("MDN=X")


class MdTag:
    """Parsed MD tag: match ranges + mismatch/delete base maps
    (MdTag.scala:439-444 class body)."""

    def __init__(self, matches: List[range], mismatches: Dict[int, str],
                 deletes: Dict[int, str]):
        self.matches = matches
        self.mismatches = mismatches
        self.deletes = deletes

    # -- parse (MdTag.scala:38-98) --------------------------------------
    @classmethod
    def parse(cls, md: str, reference_start: int) -> "MdTag":
        matches: List[range] = []
        mismatches: Dict[int, str] = {}
        deletes: Dict[int, str] = {}
        if md:
            tag = md.upper()
            offset = 0
            ref_pos = reference_start

            def read_matches(err: str) -> None:
                nonlocal offset, ref_pos
                m = _DIGITS.match(tag, offset)
                if not m:
                    raise ValueError(err + f": {md!r}")
                length = int(m.group())
                if length > 0:
                    matches.append(range(ref_pos, ref_pos + length))
                offset = m.end()
                ref_pos += length

            read_matches("MD tag must start with a digit")
            while offset < len(tag):
                is_delete = tag[offset] == "^"
                if is_delete:
                    offset += 1
                m = _BASES.match(tag, offset)
                if not m:
                    raise ValueError(
                        "Failed to find deleted or mismatched bases after a "
                        f"match: {md!r}")
                for base in m.group():
                    (deletes if is_delete else mismatches)[ref_pos] = base
                    ref_pos += 1
                offset = m.end()
                read_matches("MD tag should have matching bases after "
                             "mismatched or missing bases")
        return cls(matches, mismatches, deletes)

    # -- queries (MdTag.scala:240-296) ----------------------------------
    def is_match(self, pos: int) -> bool:
        return any(pos in r for r in self.matches)

    def mismatched_base(self, pos: int) -> Optional[str]:
        return self.mismatches.get(pos)

    def deleted_base(self, pos: int) -> Optional[str]:
        return self.deletes.get(pos)

    def has_mismatches(self) -> bool:
        return bool(self.mismatches)

    def start(self) -> int:
        starts = [r.start for r in self.matches] + \
            list(self.mismatches) + list(self.deletes)
        return min(starts) if starts else 0  # empty (zero-length) tag

    def end(self) -> int:
        ends = [r.stop - 1 for r in self.matches] + \
            list(self.mismatches) + list(self.deletes)
        return max(ends) if ends else -1  # empty tag: end < start

    # -- reference reconstruction (MdTag.scala:306-372) ------------------
    def get_reference(self, read_sequence: str, cigar: str | List[Tuple[int, str]],
                      reference_from: int) -> str:
        """Rebuild the reference sequence overlapping this read from the read
        bases + mismatch/delete records."""
        elems = parse_cigar(cigar) if isinstance(cigar, str) else cigar
        ref_pos = self.start()
        read_pos = 0
        out: List[str] = []
        for length, op in elems:
            if op == "M":
                for _ in range(length):
                    out.append(self.mismatches.get(ref_pos) or
                               read_sequence[read_pos])
                    read_pos += 1
                    ref_pos += 1
            elif op == "D":
                for _ in range(length):
                    base = self.deletes.get(ref_pos)
                    if base is None:
                        raise ValueError(
                            f"Could not find deleted base at ref pos {ref_pos}")
                    out.append(base)
                    ref_pos += 1
            else:
                if op in _CONSUMES_READ:
                    read_pos += length
                if op in _CONSUMES_REF:
                    raise ValueError(f"Cannot handle operator: {op}")
        return "".join(out)

    # -- re-derivation after realignment (MdTag.scala:137-233) -----------
    @classmethod
    def move_alignment(cls, reference: str, sequence: str,
                       new_cigar: str | List[Tuple[int, str]],
                       read_start: int) -> "MdTag":
        """Recompute the MD events of ``sequence`` aligned at ``read_start``
        against ``reference`` (0-indexed at the alignment) under ``new_cigar``."""
        elems = parse_cigar(new_cigar) if isinstance(new_cigar, str) else new_cigar
        ref_pos = 0
        read_pos = 0
        matches: List[range] = []
        mismatches: Dict[int, str] = {}
        deletes: Dict[int, str] = {}
        for length, op in elems:
            if op == "M":
                range_start = 0
                in_match = False
                for _ in range(length):
                    if reference[ref_pos] == sequence[read_pos]:
                        if not in_match:
                            range_start = ref_pos
                            in_match = True
                    else:
                        if in_match:
                            matches.append(range(range_start + read_start,
                                                 ref_pos + read_start))
                            in_match = False
                        mismatches[ref_pos + read_start] = reference[ref_pos]
                    read_pos += 1
                    ref_pos += 1
                if in_match:
                    matches.append(range(range_start + read_start,
                                         ref_pos + read_start))
            elif op == "D":
                for _ in range(length):
                    deletes[ref_pos + read_start] = reference[ref_pos]
                    ref_pos += 1
            else:
                if op in _CONSUMES_READ:
                    read_pos += length
                if op in _CONSUMES_REF:
                    raise ValueError(f"Cannot handle operator: {op}")
        return cls(matches, mismatches, deletes)

    # -- serialization (MdTag.scala:380-442) -----------------------------
    def __str__(self) -> str:
        """Event-walk form of the reference's position-by-position toString
        FSM: equivalent output for well-formed tags (every position in
        [start, end] is a match, a mismatch, or a deletion), O(events)
        instead of O(span x match-runs) — the FSM dominated realignment
        profiles via its per-position ``is_match`` scans."""
        if not (self.matches or self.mismatches or self.deletes):
            return "0"  # zero-length tag (the reference FSM cannot emit one)
        evs = sorted(
            [(p, False, b) for p, b in self.mismatches.items()] +
            [(p, True, b) for p, b in self.deletes.items()])
        out: List[str] = []
        cursor = self.start()
        prev_del_pos = -2
        for p, is_del, base in evs:
            gap = p - cursor
            if is_del and prev_del_pos == p - 1 and gap == 0:
                out.append(base)          # continue the ^-run
            elif is_del:
                out.append(str(gap))
                out.append("^")
                out.append(base)
            else:
                out.append(str(gap))
                out.append(base)
            cursor = p + 1
            prev_del_pos = p if is_del else -2
        out.append(str(self.end() + 1 - cursor))
        return "".join(out)

    def __eq__(self, other) -> bool:
        return isinstance(other, MdTag) and str(self) == str(other) and \
            self.start() == other.start()
