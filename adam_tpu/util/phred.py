"""Phred <-> probability tables (util/PhredUtils.scala:20-44).

256-entry lookup tables, exposed both as numpy arrays for host code and as
device constants for kernels (a gather from a [256] table vectorizes the
reference's per-base calls)."""

from __future__ import annotations

import numpy as np

PHRED_TO_ERROR = 10.0 ** (-np.arange(256) / 10.0)
PHRED_TO_SUCCESS = 1.0 - PHRED_TO_ERROR


def phred_to_error_probability(phred):
    return PHRED_TO_ERROR[phred]


def phred_to_success_probability(phred):
    return PHRED_TO_SUCCESS[phred]


def _probability_to_phred(p) -> int:
    # truncation (not rounding) matches PhredUtils.scala:33
    return int(-10.0 * np.log10(p))


def success_probability_to_phred(p) -> int:
    return _probability_to_phred(1.0 - p)


def error_probability_to_phred(p) -> int:
    return _probability_to_phred(p)
