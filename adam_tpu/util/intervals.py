"""GATK-style interval list files.

Re-designs ``util/IntervalListReader.scala:31-80``: a tab-separated file of
``contig  start  end  strand  name`` lines preceded by a SAM-style header
whose ``@SQ`` lines carry the sequence dictionary.  One deviation from the
reference, which parses column 0 with ``toInt`` (so named contigs crash):
contig names resolve through the header dictionary first, falling back to
the integer form for dictionary-less files.
"""

from __future__ import annotations

from typing import Iterator, List, Tuple

from ..models.dictionary import SequenceDictionary, SequenceRecord
from ..models.region import ReferenceRegion


def _parse_sq_line(line: str, next_id: int) -> SequenceRecord:
    name, length, url = None, None, None
    for field in line.rstrip("\n").split("\t")[1:]:
        key, _, value = field.partition(":")
        if key == "SN":
            name = value
        elif key == "LN":
            length = int(value)
        elif key == "UR":
            url = value
    if name is None or length is None:
        raise ValueError(f"@SQ line missing SN/LN: {line!r}")
    return SequenceRecord(next_id, name, length, url)


class IntervalListReader:
    """Iterate (ReferenceRegion, name) pairs from an interval list file.

    The embedded dictionary is available as :attr:`sequence_dictionary`
    (IntervalListReader.scala:37-49); ids are assigned in header order.
    """

    def __init__(self, path):
        self.path = path
        self._dict: SequenceDictionary | None = None

    @property
    def sequence_dictionary(self) -> SequenceDictionary:
        if self._dict is None:
            records: List[SequenceRecord] = []
            with open(self.path, encoding="utf-8") as f:
                for line in f:
                    if line.startswith("@SQ"):
                        records.append(_parse_sq_line(line, len(records)))
            self._dict = SequenceDictionary(records)
        return self._dict

    def __iter__(self) -> Iterator[Tuple[ReferenceRegion, str]]:
        seq_dict = self.sequence_dictionary
        with open(self.path, encoding="utf-8") as f:
            for line in f:
                if line.startswith("@") or not line.strip():
                    continue
                contig, start, end, strand, name = \
                    line.rstrip("\n").split("\t")[:5]
                if strand != "+":
                    raise ValueError(
                        f"only +-strand intervals supported: {line!r}")
                rec = seq_dict.get(contig)
                ref_id = rec.id if rec is not None else int(contig)
                yield ReferenceRegion(ref_id, int(start), int(end)), name

    def regions(self) -> List[Tuple[ReferenceRegion, str]]:
        return list(self)
