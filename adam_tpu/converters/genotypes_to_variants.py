"""Genotype -> variant computation (``compute_variants``).

Re-designs ``converters/GenotypesToVariantsConverter.scala`` (:37-494): group
genotypes by (referenceId, position, allele) and synthesize per-site variant
statistics.

Math (:108-160):
  * rms over phred values runs in success-probability space:
    phred(rms(successProb(q)));
  * variant quality = phred(1 - prod(successProb(GQ))) (:146,:346-352);
  * allele frequency = genotypes carrying the allele / all genotypes at the
    site.  (The reference passes the *group's* own length as the denominator
    (:452-489 calls convertGenotypes with ``genotypes.length`` of the group),
    so its AF is always 1.0 — we use the site total, which is what the code
    comments say it wants.)

Validation (:37-106): consistent reference name/allele/isReference within a
group is always required; per-sample ploidy/haplotype checks run under
``validate=True`` and raise under ``strict=True`` (the reference's
-runValidation / -runStrictValidation knobs, ComputeVariants.scala:45-49).
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Tuple

import numpy as np
import pyarrow as pa

from .. import schema as S
from ..util.phred import (phred_to_success_probability,
                          success_probability_to_phred)


def _rms_phred(quals: List[int]) -> Optional[int]:
    if not quals:
        return None
    probs = [phred_to_success_probability(q) for q in quals]
    rms = math.sqrt(sum(p * p for p in probs) / len(probs))
    return success_probability_to_phred(rms)


def _variant_quality(gqs: List[int]) -> Optional[int]:
    if not gqs:
        return None
    prod = 1.0
    for q in gqs:
        prod *= phred_to_success_probability(q)
    return success_probability_to_phred(1.0 - prod)


def _validate_sample(genotypes: List[dict], strict: bool,
                     warnings: List[str]) -> None:
    """Per-sample checks (validateGenotypes :37-106)."""
    ploidies = {g["ploidy"] for g in genotypes if g["ploidy"] is not None}
    msgs = []
    if len(ploidies) > 1:
        msgs.append(f"inconsistent ploidy {ploidies}")
    haplos = [g["haplotypeNumber"] for g in genotypes
              if g["haplotypeNumber"] is not None]
    if len(haplos) != len(set(haplos)):
        msgs.append("duplicate haplotype numbers")
    for m in msgs:
        full = f"sample {genotypes[0]['sampleId']}: {m}"
        if strict:
            raise ValueError(full)
        warnings.append(full)


def convert_genotypes(genotypes: pa.Table,
                      existing_variants: Optional[pa.Table] = None,
                      validate: bool = False,
                      strict: bool = False) -> pa.Table:
    """Genotype table -> variant table, one row per (site, allele)."""
    g_rows = genotypes.to_pylist()
    by_site: Dict[Tuple, List[dict]] = {}
    for g in g_rows:
        by_site.setdefault((g["referenceId"], g["position"]), []).append(g)

    existing: Dict[Tuple, dict] = {}
    if existing_variants is not None:
        for v in existing_variants.to_pylist():
            existing[(v["referenceId"], v["position"], v["variant"])] = v

    warnings: List[str] = []
    out_rows = []
    for (refid, pos), site_gs in by_site.items():
        total = len(site_gs)
        by_allele: Dict[str, List[dict]] = {}
        for g in site_gs:
            by_allele.setdefault(g["allele"], []).append(g)
        for allele, gs in by_allele.items():
            # critical validation (:171-177): consistent within group
            for field in ("referenceName", "referenceAllele", "isReference"):
                if len({g[field] for g in gs}) > 1:
                    raise ValueError(
                        f"{field} inconsistent at {refid}:{pos} {allele}")
            if validate:
                by_sample: Dict[str, List[dict]] = {}
                for g in gs:
                    by_sample.setdefault(g["sampleId"], []).append(g)
                for sample_gs in by_sample.values():
                    _validate_sample(sample_gs, strict, warnings)

            head = gs[0]
            ex = existing.get((refid, pos, allele))
            gqs = [g["genotypeQuality"] for g in gs
                   if g["genotypeQuality"] is not None]
            row = {
                "referenceId": refid,
                "referenceName": head["referenceName"],
                "position": pos,
                "referenceAllele": head["referenceAllele"],
                "isReference": head["isReference"],
                "variant": allele,
                "variantType": head["alleleVariantType"],
                "alleleFrequency": len(gs) / max(total, 1),
                "quality": (ex["quality"] if ex is not None and
                            ex.get("quality") is not None
                            else _variant_quality(gqs)),
                "id": (ex or {}).get("id"),
                "filters": (ex or {}).get("filters"),
                "filtersRun": (ex or {}).get("filtersRun", False),
                "rmsBaseQuality": _rms_phred(
                    [g["rmsBaseQuality"] for g in gs
                     if g["rmsBaseQuality"] is not None and
                     g["depth"] is not None]),
                "siteRmsMappingQuality": _rms_phred(
                    [g["rmsMapQuality"] for g in gs
                     if g["rmsMapQuality"] is not None and
                     g["depth"] is not None]),
                "totalSiteMapCounts": (sum(g["depth"] for g in gs
                                           if g["depth"] is not None)
                                       if any(g["depth"] is not None
                                              for g in gs) else None),
                "siteMapQZeroCounts": (sum(g["readsMappedMapQ0"] for g in gs
                                           if g["readsMappedMapQ0"] is not None)
                                       if any(g["readsMappedMapQ0"] is not None
                                              for g in gs) else None),
                "numberOfSamplesWithData": len({g["sampleId"] for g in gs}),
            }
            if head.get("svType") is not None:
                for f in ("svType", "svLength", "svIsPrecise", "svEnd",
                          "svConfidenceIntervalStartLow",
                          "svConfidenceIntervalStartHigh",
                          "svConfidenceIntervalEndLow",
                          "svConfidenceIntervalEndHigh"):
                    row[f] = head[f]
            out_rows.append(row)

    for w in warnings:
        print(f"validation warning: {w}")
    cols = {name: [r.get(name) for r in out_rows]
            for name in S.VARIANT_SCHEMA.names}
    return pa.Table.from_pydict(cols, schema=S.VARIANT_SCHEMA)
