"""Self-diagnosing probe analysis: turn raw probe measurements into a
record that explains its own anomalies.

Round 3's on-chip calibration put the repeat-matmul at ~190 TFLOPs on
the v5e; round 5 captured 124 TFLOPs and nobody could say whether the
chip, the tunnel, or the timing discipline was at fault.  This module
is the pure half of the fix (bench._stage_probe supplies the raw
measurements; nothing here imports jax):

* **RTT** — the tunnel round-trip floor every chained timing subtracts;
* **repeat matmul** — N tflops samples from chained matmul runs at
  increasing chain lengths; their spread bounds the timing noise;
* **chain-linearity residual** — least-squares fit of ``time = a +
  b * k`` over the (chain length, wall time) points; a large residual
  means the "per-iteration" rate is not actually linear in k (tunnel
  stall, async-dispatch misaccounting) and the tflops number cannot be
  trusted;
* **calibration deviation** — the best sample vs the round-3 on-chip
  calibration (190 TFLOPs); >10 % deviation sets a flag that rides the
  probe record into the ledger, so a partial artifact carries its own
  health verdict.

Records land in the evidence ledger's ``probes`` history and the probe
stage payload; ``tools/check_evidence.py`` validates the field set.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

#: round-3 on-chip repeat-matmul calibration for the 2048^3 bf16 chain
#: (BENCH_r03; v5 lite).  The deviation flag is computed against this,
#: not the spec-sheet peak — the question a window must answer is "does
#: the chip behave like it did when the numbers were good".
CALIBRATION_TFLOPS = 190.0

#: relative deviation beyond which the probe flags itself
DEVIATION_THRESHOLD = 0.10


def chain_linearity_residual(points: Sequence[Tuple[float, float]]
                             ) -> Optional[float]:
    """Max relative residual of the least-squares line ``t = a + b*k``
    over ``points = [(k, seconds), ...]``.  Needs >= 3 distinct chain
    lengths; returns None otherwise.  ~0 means per-iteration cost is
    genuinely constant (the chained-timing discipline holds); large
    values mean the timing is lying (e.g. dispatch "finished" at 8x
    peak because block_until_ready did not sync the tunnel)."""
    pts = [(float(k), float(t)) for k, t in points]
    if len({k for k, _ in pts}) < 3:
        return None
    n = len(pts)
    mean_k = sum(k for k, _ in pts) / n
    mean_t = sum(t for _, t in pts) / n
    var_k = sum((k - mean_k) ** 2 for k, _ in pts)
    if var_k <= 0:
        return None
    b = sum((k - mean_k) * (t - mean_t) for k, t in pts) / var_k
    a = mean_t - b * mean_k
    resid = 0.0
    for k, t in pts:
        pred = a + b * k
        denom = max(abs(t), 1e-9)
        resid = max(resid, abs(pred - t) / denom)
    return resid


def diagnose(record: dict) -> str:
    """One human-readable line explaining the record's health — what a
    partial artifact says for itself when nobody was watching."""
    parts = []
    dev = record.get("calibration_deviation")
    if record.get("calibration_deviation_flag"):
        parts.append(
            f"matmul {record.get('matmul_tflops')} TFLOPs deviates "
            f"{dev:+.0%} from the round-3 calibration "
            f"{record.get('calibration_tflops')} — link or device "
            f"contention; treat this window's rates as lower bounds")
    resid = record.get("chain_linearity_residual")
    if resid is not None and resid > 0.15:
        parts.append(
            f"chain timing nonlinear (residual {resid:.2f}) — "
            f"per-iteration rates from this window are unreliable")
    if not parts:
        if record.get("calibration_applies"):
            parts.append("probe healthy: matmul within calibration, "
                         "chain timing linear")
        else:
            parts.append("non-TPU backend: calibration not applicable")
    return "; ".join(parts)


def analyze_probe(*, rtt_s: float,
                  tflops_samples: Sequence[float],
                  chain_points: Sequence[Tuple[float, float]],
                  is_tpu: bool,
                  link_bytes_per_sec: Optional[float] = None,
                  calibration_tflops: float = CALIBRATION_TFLOPS,
                  threshold: float = DEVIATION_THRESHOLD) -> dict:
    """Build the self-diagnosing probe record from raw measurements.

    ``tflops_samples``: repeat-matmul rate per chain run (>=1);
    ``chain_points``: the (chain length, wall seconds) pairs behind
    those samples.  Calibration deviation only applies on a TPU backend
    — flagging a CPU fallback against 190 TFLOPs would make every CPU
    artifact "anomalous" and bury the real signal.
    """
    samples = [round(float(s), 2) for s in tflops_samples]
    best = max(samples) if samples else None
    resid = chain_linearity_residual(chain_points)
    rec: dict = {
        "rtt_ms": round(rtt_s * 1e3, 1),
        "repeat_matmul_tflops": samples,
        "repeat_matmul_n": len(samples),
        "matmul_tflops": best,
        "matmul_tflops_spread": round(max(samples) - min(samples), 2)
        if len(samples) >= 2 else None,
        "chain_points": [[int(k), round(float(t), 4)]
                         for k, t in chain_points],
        "chain_linearity_residual": round(resid, 4)
        if resid is not None else None,
        "link_bytes_per_sec": round(float(link_bytes_per_sec), 1)
        if link_bytes_per_sec else None,
        "calibration_tflops": calibration_tflops,
        "calibration_applies": bool(is_tpu),
    }
    if is_tpu and best:
        dev = (best - calibration_tflops) / calibration_tflops
        rec["calibration_deviation"] = round(dev, 4)
        rec["calibration_deviation_flag"] = bool(abs(dev) > threshold)
    else:
        rec["calibration_deviation"] = None
        rec["calibration_deviation_flag"] = False
    rec["diagnosis"] = diagnose(rec)
    return rec
