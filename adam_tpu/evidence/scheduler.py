"""Information-first capture scheduling for bench stages.

A tunnel window is the scarce resource; the scheduler's one job is to
make any window — even 60 seconds — yield the never-captured evidence
first.  Ordering rule (information-per-byte):

1. stages with NO on-chip ledger record come before stages that already
   have one (an on-chip number is never re-paid before a stage without
   one);
2. within each group, higher information tier first — the tier encodes
   what each stage adjudicates (the six-way count race decides the
   product's default backend; the pallas checks decide which kernels
   ship; the fused transform is the product headline; flagstat already
   has CPU-adjudicated numbers; the int8 legs are exploratory);
3. ties break toward the smallest wire, so a flapping link loses the
   least when a stage dies mid-transfer.

This fixes the round-4/5 inversion (bench.py ran the 34 MB flagstat
wire before the 8 MB race — VERDICT r4, ``bench.py:912``): the default
order with an empty ledger is ``probe → bqsr_race → pallas →
ragged_race → transform → flagstat → bqsr_race8``, pinned by
tests/test_bench_orchestration.py.

The scheduler also owns the per-stage deadline table (bench._run_worker
enforces it over the worker's stdout; ``ADAM_TPU_BENCH_STAGE_TIMEOUTS``
overrides single entries) and the link-rate problem-size scaling: once
the probe measures the tunnel's actual byte rate, each wire-shipping
stage is shrunk so its transfer fits a bounded slice of the window
instead of stalling it (the round-5 flagstat hang was a 206 MB wire on
a ~1 MB/s flap).
"""

from __future__ import annotations

import os
from typing import Iterable, Optional

#: canonical stage order with an empty ledger — probe always first (it
#: supplies platform/link context to everything after it).  ragged_race
#: adjudicates the executor's padded-vs-ragged layout dimension
#: (ISSUE 8) right after the kernel adjudication stages.
DEFAULT_STAGE_ORDER = ("probe", "bqsr_race", "pallas", "ragged_race",
                       "transform", "flagstat", "bqsr_race8")

#: information tier per stage (lower = captured earlier); see module
#: docstring for what each stage adjudicates
INFO_TIER = {"probe": 0, "bqsr_race": 1, "pallas": 2, "ragged_race": 3,
             "transform": 4, "flagstat": 5, "bqsr_race8": 6}

#: per-stage stdout deadlines enforced by bench._run_worker (probe
#: covers backend init + first compile over the tunnel); one hung stage
#: can cost at most its own entry, never the window
STAGE_DEADLINES_S = {"probe": 150.0, "flagstat": 180.0, "transform": 280.0,
                     "bqsr_race": 300.0, "bqsr_race8": 150.0,
                     "pallas": 240.0, "ragged_race": 300.0,
                     # CPU-mesh fleet scaling (4 full flagstat runs +
                     # worker spawns); never in the TPU capture order —
                     # reached only via --worker/--only shard_scale
                     "shard_scale": 600.0,
                     # warm-serve amortization (K cold CLI spawns + one
                     # serve process + a packed pair); never in the TPU
                     # capture order — reached only via --worker/--only
                     # serve_warm
                     "serve_warm": 600.0,
                     # fleet-serve scaling (two fleets, 1+2 warm worker
                     # boots, 2K jobs); never in the TPU capture order —
                     # reached only via --worker/--only fleet_serve
                     "fleet_serve": 600.0,
                     # resident paged buffers: kernel-twin identity +
                     # the in-process serve steady-state h2d leg; never
                     # in the TPU capture order — reached only via
                     # --worker/--only paged_race
                     "paged_race": 400.0,
                     # overload protection (ISSUE 14): two serve
                     # processes driven at 2x accepted capacity; never
                     # in the TPU capture order — reached only via
                     # --worker/--only overload
                     "overload": 600.0,
                     # variant-calling plane (ISSUE 17): solo call +
                     # oracle differential + warm rerun + served
                     # co-tenant leg; never in the TPU capture order —
                     # reached only via --worker/--only call
                     "call": 600.0,
                     # fused mega-pass (ISSUE 18): kernel-twin identity
                     # + the in-process combined dispatch-count leg;
                     # never in the TPU capture order — reached only
                     # via --worker/--only mega_race
                     "mega_race": 400.0}

TIMEOUTS_ENV = "ADAM_TPU_BENCH_STAGE_TIMEOUTS"

# -- analytic wire models ----------------------------------------------------
# bytes the stage moves over the host->device link at its default
# problem size (flagstat ships a real packed wire; the race/transform
# batches are generated on device, so their wire is the per-read
# accounting footprint bench reports, not a host transfer — the model
# only needs to rank stages and scale problem sizes consistently).

FLAGSTAT_WIRE_BYTES_PER_READ = 4.0
RACE_WIRE_BYTES_PER_READ = 8.0          # index word + weight byte per base
TRANSFORM_WIRE_BYTES_PER_READ = 33.0    # scalars + LUT slices per read

_DEFAULT_READS = {"flagstat": 12_000_000, "bqsr_race": 1_000_000,
                  "bqsr_race8": 1_000_000, "transform": 1_500_000,
                  "ragged_race": 3_000_000}


def wire_bytes_for(stage: str, payload: Optional[dict] = None,
                   n_reads: Optional[int] = None) -> Optional[int]:
    """Analytic wire bytes for a stage, from its payload's read count
    when available (ledger accounting), else the default sizes."""
    p = payload or {}
    if n_reads is None:
        n_reads = (p.get("n_reads") or p.get("race_n_reads") or
                   p.get("race8_n_reads") or p.get("transform_n_reads") or
                   _DEFAULT_READS.get(stage))
    if stage == "probe":
        return 2 * 2048 * 2048            # the bf16 matmul operand
    if stage == "pallas":
        return 64 * 100 * 8               # tiny check arrays
    if stage == "flagstat":
        return int(FLAGSTAT_WIRE_BYTES_PER_READ * n_reads)
    if stage == "ragged_race":
        # dominated by its flagstat leg's wire (both layouts)
        return int(2 * FLAGSTAT_WIRE_BYTES_PER_READ * n_reads)
    if stage in ("bqsr_race", "bqsr_race8"):
        return int(RACE_WIRE_BYTES_PER_READ * n_reads)
    if stage == "transform":
        return int(TRANSFORM_WIRE_BYTES_PER_READ * n_reads)
    return None


def order_stages(want: Iterable[str], ledger=None) -> list:
    """Order ``want`` information-first against the ledger state (see
    module docstring).  ``ledger`` may be None (empty-ledger order) or
    anything with ``captured_on_tpu(stage)``."""
    want = list(dict.fromkeys(want))      # de-dup, keep caller's extras

    def key(stage):
        captured = 1 if (ledger is not None and
                         ledger.captured_on_tpu(stage)) else 0
        tier = INFO_TIER.get(stage, len(INFO_TIER))
        return (0 if stage == "probe" else 1, captured, tier,
                wire_bytes_for(stage) or 0)

    return sorted(want, key=key)


#: the CPU fallback pass exists to complete the ARTIFACT, not to buy
#: on-chip evidence: headline metric (flagstat) first, then the product
#: transform, then the race adjudication — the reverse of the window's
#: information-first order, which is meaningless off-chip (the seed's
#: CPU artifacts landed flagstat+transform+race in exactly this order;
#: racing first would let the slow CPU race legs eat the fallback
#: deadline and zero the headline value)
CPU_FALLBACK_ORDER = ("probe", "flagstat", "transform", "bqsr_race",
                      "ragged_race")


def order_cpu_fallback(missing: Iterable[str]) -> list:
    """Order the CPU fallback pass's stages artifact-first (see
    CPU_FALLBACK_ORDER); unknown stages keep their relative order at
    the end."""
    known = {s: i for i, s in enumerate(CPU_FALLBACK_ORDER)}
    return sorted(missing, key=lambda s: known.get(s, len(known)))


def parse_only(spec: Optional[str]) -> Optional[list]:
    """``--only``/``ADAM_TPU_BENCH_ONLY`` parsing: comma-separated stage
    names; probe is always prepended (every worker needs its platform
    probe).  None/empty -> None (run everything)."""
    if not spec:
        return None
    stages = [s.strip() for s in spec.split(",") if s.strip()]
    if not stages:
        return None
    return ["probe"] + [s for s in stages if s != "probe"]


def parse_stage_timeouts(spec: Optional[str],
                         base: Optional[dict] = None) -> dict:
    """Merge ``name=seconds`` comma-pairs over the deadline table.
    Malformed entries are skipped, not fatal — a typo in a watcher env
    must not cost the window."""
    out = dict(base if base is not None else STAGE_DEADLINES_S)
    for item in (spec or "").split(","):
        if "=" not in item:
            continue
        name, _, val = item.partition("=")
        try:
            sec = float(val)
        except ValueError:
            continue
        if name.strip() and sec > 0:
            out[name.strip()] = sec
    return out


#: floor on the scaled flagstat wire: rates are size-independent past
#: ~4M reads (one resident chain block), so never shrink below that
MIN_FLAGSTAT_READS = 4_000_000
MIN_RACE_READS = 250_000
MIN_TRANSFORM_READS = 250_000


def scaled_reads_env(link_bytes_per_sec: Optional[float],
                     transfer_budget_s: float = 45.0) -> dict:
    """Problem sizes scaled to the link rate the probe just measured:
    env overrides capping each wire-shipping stage's transfer at
    ``transfer_budget_s`` seconds of the measured link.  No link rate
    (or a fast link that fits the defaults) -> no overrides."""
    if not link_bytes_per_sec or link_bytes_per_sec <= 0:
        return {}
    cap = link_bytes_per_sec * transfer_budget_s
    out = {}
    n_flag = int(cap / FLAGSTAT_WIRE_BYTES_PER_READ)
    if n_flag < _DEFAULT_READS["flagstat"]:
        out["ADAM_TPU_BENCH_FLAGSTAT_READS"] = \
            str(max(MIN_FLAGSTAT_READS, n_flag))
    n_race = int(cap / RACE_WIRE_BYTES_PER_READ)
    if n_race < _DEFAULT_READS["bqsr_race"]:
        out["ADAM_TPU_BENCH_RACE_READS"] = \
            str(max(MIN_RACE_READS, n_race))
    n_tr = int(cap / TRANSFORM_WIRE_BYTES_PER_READ)
    if n_tr < _DEFAULT_READS["transform"]:
        out["ADAM_TPU_BENCH_TRANSFORM_READS"] = \
            str(max(MIN_TRANSFORM_READS, n_tr))
    return out


def scale_env_from_probe(probe_payload: Optional[dict]) -> dict:
    """benchlib.orchestrate hook: once an attempt's probe payload lands,
    derive the size overrides for every subsequent attempt in the same
    window (re-entry after a flap runs shrunken stages)."""
    if not probe_payload:
        return {}
    return scaled_reads_env(probe_payload.get("link_bytes_per_sec"))
