"""``adam_tpu.evidence`` — cross-window TPU evidence ledger and
information-first capture scheduler.

Hardware windows are rare (~1 per 18 h observed) and flap on minute
scales, so every second of a window must buy evidence that does not yet
exist.  Three modules, all importable without jax:

* :mod:`.ledger` — the persisted per-stage evidence record
  (``EVIDENCE_LEDGER.json`` next to the ``BENCH_*.json`` artifacts),
  merged keep-best across windows: a stage with an on-chip number is
  never clobbered by a CPU fallback, and never re-paid before a stage
  without one;
* :mod:`.scheduler` — orders runnable stages by information-per-byte
  (never-captured-on-TPU first, then smallest wire), scales per-stage
  problem sizes to the link rate the probe just measured, and owns the
  per-stage deadline table ``bench._run_worker`` enforces;
* :mod:`.probe` — pure analysis for the self-diagnosing probe record
  (RTT, repeat-matmul samples, chain-linearity residual, calibration
  deviation vs the round-3 190 TFLOPs number) so a partial artifact
  like the 124-TFLOPs anomaly explains itself.

``bench.py`` drives all three; ``tools/tpu_watch.py`` reads the ledger
to re-enter a window with only the missing stages; ledger writes emit
through :mod:`adam_tpu.obs` so evidence and telemetry share one
artifact chain.  Format documented in docs/EVIDENCE.md, validated by
``tools/check_evidence.py``.
"""

from __future__ import annotations

from .ledger import Ledger, new_window_id  # noqa: F401
from .probe import (CALIBRATION_TFLOPS,  # noqa: F401
                    DEVIATION_THRESHOLD, analyze_probe,
                    chain_linearity_residual)
from .scheduler import (CPU_FALLBACK_ORDER,  # noqa: F401
                        DEFAULT_STAGE_ORDER, STAGE_DEADLINES_S,
                        order_cpu_fallback, order_stages, parse_only,
                        scaled_reads_env, wire_bytes_for)
