"""The cross-window evidence ledger: one persisted record per bench
stage, merged keep-best across capture windows.

The file (default ``EVIDENCE_LEDGER.json``, next to the ``BENCH_*.json``
artifacts) is one JSON document::

    {"schema": 1,
     "updated_at": "<iso8601>",
     "stages": {<stage>: {stage, platform, device_kind, wire_bytes,
                          wall_s, result_digest, window_id,
                          link_bytes_per_sec, captured_at, payload}},
     "probes": [<probe record>, ...]}   # newest last, capped

Keep-best merge semantics (the whole point — round 5 lost a window to
stage-order inversion and an earlier round to artifact clobbering):

* an on-chip (``platform == "tpu"``) record is NEVER replaced by a
  non-TPU one — a tunnel flap mid-bench cannot destroy captured
  evidence (the generalization of tpu_watch's old whole-file
  keep-dont-clobber);
* between two records of equal quality the newer ``captured_at`` wins;
* ``save()`` re-reads the file and merges before the atomic replace,
  so two concurrent writers (bench.py + a stray manual run) both keep
  the best of what either saw.

Writes are atomic (tmp + fsync + ``os.replace``) and every recorded
stage emits a ``ledger_stage`` event plus registry counters through
:mod:`adam_tpu.obs`, so evidence and telemetry share one artifact
chain.  Schema validated by ``tools/check_evidence.py``; documented in
docs/EVIDENCE.md.
"""

from __future__ import annotations

import hashlib
import json
import os
import time
from typing import Iterable, Optional

LEDGER_SCHEMA_VERSION = 1

#: default ledger filename (lands next to BENCH_*.json, i.e. the cwd
#: bench.py runs from unless ``ADAM_TPU_EVIDENCE_LEDGER`` redirects it)
DEFAULT_LEDGER_NAME = "EVIDENCE_LEDGER.json"
LEDGER_ENV = "ADAM_TPU_EVIDENCE_LEDGER"

#: probe history cap — enough to see convergence across many windows
#: without the file growing unboundedly on a week-long watch
MAX_PROBES = 64

#: minimal per-stage success markers: a payload carrying NONE of its
#: stage's markers is a failure report (every race leg errored, both
#: pallas kernels rejected), not evidence — recording it would mark the
#: stage as paid for and re-entry would never retry it.  Stages not
#: listed only need to be non-skip.
STAGE_SUCCESS_KEYS = {
    "flagstat": ("reads_per_sec",),
    "transform": ("transform_fused_reads_per_sec",),
    "bqsr_race": ("race_winner",),
    "bqsr_race8": ("race_pallas8_reads_per_sec",
                   "race_pallas_rows8_reads_per_sec"),
    "pallas": ("sweep_pallas_ok", "sw_pallas_ok"),
    "ragged_race": ("ragged_realign_ragged_per_sec",
                    "ragged_bqsr_ragged_per_sec",
                    "ragged_flagstat_ragged_per_sec"),
    "paged_race": ("paged_h2d_reduction",),
    "call": ("call_reads_per_sec",),
    "mega_race": ("mega_dispatch_reduction",),
}

#: pallas is special: the ok flags are present on failure too (False)
_TRUTHY_SUCCESS_STAGES = ("pallas",)


def now_iso() -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())


def new_window_id() -> str:
    """A window id unique enough across watcher wake-ups and retries."""
    return f"w{time.strftime('%Y%m%dT%H%M%SZ', time.gmtime())}-{os.getpid()}"


def result_digest(payload: dict) -> str:
    """Stable digest of a stage payload (canonical JSON) — lets two
    windows' records be compared for "same result" without diffing."""
    blob = json.dumps(payload, sort_keys=True, default=str)
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


def is_capture(payload: dict, stage: Optional[str] = None) -> bool:
    """Skip markers ({"skipped": ...}, {"race8_skipped": ...}) and
    all-legs-failed payloads (see STAGE_SUCCESS_KEYS) are not evidence
    — recording one would mark the stage as paid for and the scheduler
    would never re-attempt it."""
    if not isinstance(payload, dict) or any(
            k == "skipped" or k.endswith("_skipped") for k in payload):
        return False
    keys = STAGE_SUCCESS_KEYS.get(stage or "")
    if keys is None:
        return True
    if stage in _TRUTHY_SUCCESS_STAGES:
        return any(payload.get(k) for k in keys)
    return any(k in payload for k in keys)


def record_quality(rec: Optional[dict]) -> tuple:
    """Sort key for keep-best: on-chip beats everything, then recency."""
    if not rec:
        return (-1, "")
    q = 1 if rec.get("platform") == "tpu" else 0
    return (q, rec.get("captured_at") or "")


def merge_records(a: Optional[dict], b: Optional[dict]) -> Optional[dict]:
    """The better of two records for one stage (see module docstring).
    Ties (same quality, same timestamp) keep ``a`` (the incumbent)."""
    if a is None:
        return b
    if b is None:
        return a
    return b if record_quality(b) > record_quality(a) else a


def empty_doc() -> dict:
    return {"schema": LEDGER_SCHEMA_VERSION, "updated_at": now_iso(),
            "stages": {}, "probes": []}


def load_doc(path: str) -> dict:
    """Read a ledger document; missing/corrupt/foreign-schema files
    degrade to a fresh empty ledger (evidence capture never dies on a
    torn artifact — the merge-on-save keeps whatever was readable)."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return empty_doc()
    if not isinstance(doc, dict) or \
            doc.get("schema") != LEDGER_SCHEMA_VERSION or \
            not isinstance(doc.get("stages"), dict):
        return empty_doc()
    doc.setdefault("probes", [])
    return doc


def merge_docs(ours: dict, theirs: dict) -> dict:
    """Stage-wise keep-best union; probes unioned by (window_id,
    captured_at) with newest last, capped at MAX_PROBES."""
    out = empty_doc()
    for s in set(ours.get("stages", {})) | set(theirs.get("stages", {})):
        out["stages"][s] = merge_records(ours.get("stages", {}).get(s),
                                         theirs.get("stages", {}).get(s))
    seen = set()
    probes = []
    for p in list(theirs.get("probes", [])) + list(ours.get("probes", [])):
        if not isinstance(p, dict):
            continue
        key = (p.get("window_id"), p.get("captured_at"))
        if key in seen:
            continue
        seen.add(key)
        probes.append(p)
    probes.sort(key=lambda p: p.get("captured_at") or "")
    out["probes"] = probes[-MAX_PROBES:]
    out["updated_at"] = now_iso()
    return out


def save_doc(path: str, doc: dict) -> dict:
    """Merge ``doc`` with whatever is on disk, then atomically replace
    (checkpoint.atomic_write: tmp + fsync + rename + parent-dir fsync).
    Returns the merged document actually written."""
    from ..checkpoint import atomic_write

    merged = merge_docs(doc, load_doc(path))
    atomic_write(path, json.dumps(merged, indent=1, sort_keys=True))
    return merged


class Ledger:
    """The mutable in-process view over one ledger file."""

    def __init__(self, path: str):
        self.path = path
        self.doc = load_doc(path)

    # -- queries ----------------------------------------------------------

    def record(self, stage: str) -> Optional[dict]:
        return self.doc["stages"].get(stage)

    def captured_on_tpu(self, stage: str) -> bool:
        rec = self.record(stage)
        return bool(rec) and rec.get("platform") == "tpu"

    def missing_stages(self, want: Iterable[str]) -> list:
        """Stages still lacking an on-chip number — what the next window
        must buy (tpu_watch's --only re-entry list)."""
        return [s for s in want if not self.captured_on_tpu(s)]

    def summary_line(self, want: Iterable[str]) -> str:
        """One line for tpu_watch.log: convergence across windows."""
        want = list(want)
        have = [s for s in want if self.captured_on_tpu(s)]
        missing = [s for s in want if s not in have]
        line = f"ledger: {len(have)}/{len(want)} on-chip"
        if have:
            line += f" ({','.join(have)})"
        line += ("; missing: " + ",".join(missing)) if missing \
            else "; complete"
        return line

    def last_probe(self) -> Optional[dict]:
        probes = self.doc.get("probes") or []
        return probes[-1] if probes else None

    # -- recording --------------------------------------------------------

    def record_stage(self, stage: str, payload: dict, *,
                     platform: str, window_id: str,
                     device_kind: Optional[str] = None,
                     wire_bytes: Optional[int] = None,
                     wall_s: Optional[float] = None,
                     link_bytes_per_sec: Optional[float] = None
                     ) -> Optional[dict]:
        """Fold one stage capture in (keep-best); returns the record now
        held for the stage.  Skip-marker and failure payloads are
        ignored (is_capture)."""
        if not is_capture(payload, stage):
            return self.record(stage)
        rec = {
            "stage": stage,
            "platform": platform,
            "device_kind": device_kind,
            "wire_bytes": int(wire_bytes) if wire_bytes is not None
            else None,
            "wall_s": round(float(wall_s), 3) if wall_s is not None
            else None,
            "result_digest": result_digest(payload),
            "window_id": window_id,
            "link_bytes_per_sec": round(float(link_bytes_per_sec), 1)
            if link_bytes_per_sec else None,
            "captured_at": now_iso(),
            "payload": payload,
        }
        best = merge_records(self.record(stage), rec)
        self.doc["stages"][stage] = best
        self._emit_obs(stage, rec, kept=best is rec)
        return best

    def record_probe(self, probe_record: dict) -> None:
        """Append a probe record (self-diagnosing window health — see
        evidence.probe.analyze_probe) to the capped history."""
        self.doc["probes"] = (self.doc.get("probes") or [])[
            -(MAX_PROBES - 1):] + [dict(probe_record)]

    def record_stages(self, got: dict, *, window_id: str,
                      probe: Optional[dict] = None) -> None:
        """Fold a bench attempt's stage->payload dict in.  ``probe`` (the
        attempt's probe payload, defaulting to ``got["probe"]``) supplies
        platform/device_kind/link-rate context for stages whose payloads
        do not carry a backend field."""
        from .scheduler import wire_bytes_for

        probe = probe or got.get("probe") or {}
        link = probe.get("link_bytes_per_sec")
        kind = probe.get("device_kind")
        for stage, payload in got.items():
            if not isinstance(payload, dict):
                continue
            platform = (payload.get("backend") or
                        payload.get("race_backend") or
                        probe.get("platform") or "unknown")
            # the tunnel plugin reports "axon"; normalize like bench.py
            if platform in ("axon",):
                platform = "tpu"
            self.record_stage(
                stage, payload, platform=platform, window_id=window_id,
                device_kind=kind,
                wire_bytes=wire_bytes_for(stage, payload),
                wall_s=payload.get("stage_wall_s"),
                link_bytes_per_sec=link)
            if stage == "probe" and is_capture(payload):
                self.record_probe({"window_id": window_id,
                                   "captured_at": now_iso(), **payload})

    def save(self) -> None:
        self.doc = save_doc(self.path, self.doc)

    # -- obs wiring -------------------------------------------------------

    def _emit_obs(self, stage: str, rec: dict, *, kept: bool) -> None:
        """Evidence and telemetry share one artifact chain: each capture
        lands in the run's obs sidecar and the registry snapshot."""
        try:
            from adam_tpu import obs

            obs.emit("ledger_stage", stage=stage,
                     platform=rec["platform"],
                     window_id=rec["window_id"],
                     result_digest=rec["result_digest"],
                     kept=kept)
            obs.registry().counter(
                "ledger_stage_captured", platform=rec["platform"]).inc()
            obs.registry().gauge("ledger_on_chip_stages").set(
                sum(1 for r in self.doc["stages"].values()
                    if r and r.get("platform") == "tpu"))
        except Exception:  # noqa: BLE001 — telemetry never fails capture
            pass


def default_path(base_dir: Optional[str] = None) -> str:
    """``ADAM_TPU_EVIDENCE_LEDGER`` wins; else DEFAULT_LEDGER_NAME under
    ``base_dir`` (the directory the BENCH artifacts land in)."""
    env = os.environ.get(LEDGER_ENV)
    if env:
        return env
    return os.path.join(base_dir or ".", DEFAULT_LEDGER_NAME)
