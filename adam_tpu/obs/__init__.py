"""``adam_tpu.obs`` — pipeline-wide metrics and structured run telemetry.

Two halves, both process-global and always importable without jax:

* :mod:`.registry` — counters / gauges / histograms with labels, the
  merge-able metrics plane (worker snapshots fold into the coordinator,
  parallel/distributed.py);
* :mod:`.events` — the opt-in JSONL event log behind the CLI's
  ``-metrics PATH`` flag (manifest, per-stage / per-chunk events, final
  summary with the registry snapshot).

Wiring (who reports what):

* ``instrument.stage`` → ``stage_calls`` / ``stage_seconds{stage=}`` +
  a ``stage`` event per call;
* streaming passes (parallel/pipeline.py) → ``chunk_rows`` /
  ``bytes_in`` / ``bytes_out`` / ``pad_waste_frac`` / ``reads_per_sec``
  + a ``chunk`` event per chunk;
* platform.py → ``compile_cache_hits`` / ``compile_cache_misses`` /
  ``compile_count`` / ``compile_seconds`` via jax.monitoring;
* the summary → ``device_mem_peak`` (best effort).

Everything here is telemetry: failures degrade to no-ops, nothing takes
a device barrier, and with no ``-metrics`` flag the event half is dead
weightless code (tests/test_obs.py pins both properties).
"""

from __future__ import annotations

import contextlib
import os
import time
from typing import Iterator, Optional

from . import events, ioledger, series, startup, trace  # noqa: F401 (planes)
from .registry import (counter, gauge, histogram, registry,  # noqa: F401
                       reset_registry)
from .series import SERIES_ENV, series_path_from  # noqa: F401
from .trace import (TRACE_ENV, trace_path_from, trace_run)  # noqa: F401

#: env fallback for the CLI flag — lets bench workers and elastic worker
#: subprocesses write a sidecar without threading a flag through argv
METRICS_ENV = "ADAM_TPU_METRICS"

emit = events.emit


def reset_all() -> None:
    """Zero every piece of process-global telemetry (test isolation)."""
    reset_registry()
    events.discard_log()
    ioledger.reset()
    trace.discard_trace()
    series.discard_series()
    startup.begin()


# ---------------------------------------------------------------------------
# hooks for the instrument / pipeline layers
# ---------------------------------------------------------------------------

def stage_finished(name: str, seconds: float) -> None:
    """Called by ``instrument.stage`` on every stage exit.  Off the main
    thread the event carries the lane name (``thread``) — the stage
    stack is thread-aware now, so feeder/prep-pool stages are real and
    a metrics reader needs to know which lane a sample came from."""
    registry().counter("stage_calls", stage=name).inc()
    registry().histogram("stage_seconds", stage=name).observe(seconds)
    import threading
    t = threading.current_thread()
    if t is threading.main_thread():
        events.emit("stage", name=name, seconds=round(seconds, 6))
    else:
        events.emit("stage", name=name, seconds=round(seconds, 6),
                    thread=t.name)


def chunk_processed(pass_name: str, rows: int, *,
                    pad_rows: Optional[int] = None,
                    bytes_in: int = 0, seconds: Optional[float] = None
                    ) -> None:
    """Per-chunk accounting from the streaming passes.

    ``pad_rows=None`` means the caller did not measure padding — no
    ``pad_waste_frac`` sample is recorded (an unconditional 0.0 would
    drown the real samples and halve the reported mean waste)."""
    r = registry()
    r.counter("chunks", **{"pass": pass_name}).inc()
    r.counter("rows_in", **{"pass": pass_name}).inc(rows)
    r.histogram("chunk_rows", **{"pass": pass_name}).observe(rows)
    if bytes_in:
        r.counter("bytes_in", **{"pass": pass_name}).inc(bytes_in)
    if pad_rows is not None and rows + pad_rows:
        r.histogram("pad_waste_frac",
                    **{"pass": pass_name}).observe(pad_rows / (rows + pad_rows))
    fields = {"pass": pass_name, "rows": rows}
    if pad_rows:
        fields["pad_rows"] = pad_rows
    if bytes_in:
        fields["bytes_in"] = bytes_in
    if seconds is not None:
        fields["seconds"] = round(seconds, 6)
    events.emit("chunk", **fields)


def pad_waste(pass_name: str, rows: int, padded_rows: int,
              max_len: Optional[int] = None,
              padded_len: Optional[int] = None) -> None:
    """Bucket-padding accounting: the fraction of a packed chunk that is
    padding (wasted device work), from pipeline.pad_bucket consumers.

    The ROW axis (``pad_waste_frac``) was the only measured axis through
    PR 7, but base-level kernels pad a LENGTH axis too (the 128-multiple
    bucket) — on a length-skewed input the lane slack dwarfs the row
    slack.  ``max_len``/``padded_len`` (the chunk's true max read length
    vs its bucket) add a ``pad_waste_lane_frac`` sample so the executor's
    padded-vs-ragged layout decision is justified by measured waste on
    every padded axis (docs/OBSERVABILITY.md)."""
    r = registry()
    if padded_rows > 0:
        r.histogram("pad_waste_frac", **{"pass": pass_name}).observe(
            (padded_rows - rows) / padded_rows)
        r.counter("pad_rows", **{"pass": pass_name}).inc(padded_rows - rows)
    if max_len is not None and padded_len is not None and padded_len > 0:
        r.histogram("pad_waste_lane_frac", **{"pass": pass_name}).observe(
            (padded_len - min(max_len, padded_len)) / padded_len)


def _path_bytes(path: Optional[str]) -> int:
    if not path:
        return 0
    try:
        if os.path.isdir(path):
            return sum(os.path.getsize(os.path.join(path, f))
                       for f in os.listdir(path) if f.endswith(".parquet"))
        return os.path.getsize(path)
    except OSError:
        return 0


def run_totals(op: str, rows: int, wall_seconds: float,
               input_path: Optional[str] = None,
               output_path: Optional[str] = None) -> None:
    """End-of-run rollup for a streaming command: total rows, headline
    throughput gauge, file-level bytes in/out."""
    r = registry()
    r.counter("rows_total", op=op).inc(rows)
    if wall_seconds > 0:
        r.gauge("reads_per_sec", op=op).set(rows / wall_seconds)
    b_in = _path_bytes(input_path)
    if b_in:
        r.counter("bytes_in", op=op).inc(b_in)
    b_out = _path_bytes(output_path)
    if b_out:
        r.counter("bytes_out", op=op).inc(b_out)
    events.emit("run_totals", op=op, rows=rows,
                wall_seconds=round(wall_seconds, 6),
                bytes_in=b_in, bytes_out=b_out)


def record_device_mem_peak() -> None:
    """Fold each local device's peak-bytes-in-use into a gauge (max-merge
    across workers gives the fleet peak).  CPU backends typically return
    no stats — that is fine, the gauge just stays unset."""
    try:
        import jax

        peak = 0
        for d in jax.local_devices():
            stats = d.memory_stats()
            if stats:
                peak = max(peak, stats.get("peak_bytes_in_use", 0))
        if peak:
            registry().gauge("device_mem_peak").set(peak)
    except Exception:  # noqa: BLE001 — telemetry never fails a run
        pass


# ---------------------------------------------------------------------------
# the run wrapper (CLI -metrics, bench sidecars, worker env)
# ---------------------------------------------------------------------------

@contextlib.contextmanager
def metrics_run(path: Optional[str], *, argv=None,
                config: Optional[dict] = None, **manifest_extra
                ) -> Iterator[Optional[events.EventLog]]:
    """Open the event log, write the manifest, run, close with a summary.

    ``path=None`` is a no-op context (the common, un-flagged case).  The
    summary event carries the wall time, an ``ok`` flag, and the full
    registry snapshot; the file publishes atomically on exit even when
    the body raises, so a failed run still leaves valid telemetry.
    """
    if not path:
        yield None
        return
    try:
        from ..platform import install_compile_metrics

        install_compile_metrics()
    except Exception:  # noqa: BLE001
        pass
    log = events.open_log(path)
    events.write_manifest(log, argv=argv, config=config, **manifest_extra)
    t0 = time.perf_counter()
    ok = True
    err = None
    try:
        yield log
    except BaseException as e:
        ok = False
        err = f"{type(e).__name__}: {e}"
        raise
    finally:
        record_device_mem_peak()
        # the cold-start breakdown (backend init / first compile / first
        # dispatch) lands in EVERY command's sidecar, so the serve-mode
        # warmup win is measured against a recorded per-run baseline
        startup.emit_event(log)
        fields = dict(wall_seconds=round(time.perf_counter() - t0, 6),
                      ok=ok, metrics=registry().snapshot())
        if err:
            fields["error"] = err[:500]
        log.emit("summary", **fields)
        log.close()
        if events.active() is log:
            events.close_log()


def metrics_path_from(flag_value: Optional[str]) -> Optional[str]:
    """The CLI flag wins; the ``ADAM_TPU_METRICS`` env var is the fallback
    (how bench workers and elastic workers get a per-process sidecar)."""
    return flag_value or os.environ.get(METRICS_ENV) or None


def metrics_run_from_env(**kw):
    """:func:`metrics_run` keyed purely off ``ADAM_TPU_METRICS`` — what a
    spawned worker (bench subprocess, elastic incarnation) uses when no
    CLI flag reaches it.  No-op context when the var is unset."""
    return metrics_run(metrics_path_from(None), **kw)


# ---------------------------------------------------------------------------
# snapshot-file merge (elastic supervisor side)
# ---------------------------------------------------------------------------

def read_snapshot_file(path: str) -> Optional[dict]:
    """The registry snapshot recorded in a finished run's JSONL (its
    summary event's ``metrics`` field) or in a bare snapshot JSON file;
    ``None`` when the file is missing, torn, or carries no snapshot."""
    import json

    try:
        with open(path) as f:
            lines = [ln for ln in f if ln.strip()]
    except OSError:
        return None
    for ln in reversed(lines):
        try:
            doc = json.loads(ln)
        except ValueError:
            continue
        if doc.get("event") == "summary" and "metrics" in doc:
            return doc["metrics"]
        if {"counters", "gauges", "histograms"} & set(doc):
            return doc  # a bare registry snapshot file
    return None


def snapshot_is_fleet_merged(snap: dict) -> bool:
    """Whether this snapshot already holds fleet totals (its process ran
    ``distributed.merge_worker_metrics``, which stamps the marker gauge).
    Folding two fleet views double-counts — aggregators must merge at
    most one (parallel/elastic.py's supervisor does)."""
    return (snap.get("gauges") or {}).get("fleet_merged", 0) >= 1


def merge_metrics_file(path: str) -> bool:
    """Fold a finished run's JSONL (or bare snapshot JSON) into THIS
    process's registry.  Returns True when something merged.  This is how
    the elastic supervisor aggregates worker sidecars after an
    incarnation completes (parallel/elastic.py)."""
    snap = read_snapshot_file(path)
    if snap is None:
        return False
    registry().merge(snap)
    return True
