"""Cold-start accounting: where a command's warmup time actually goes.

Every batch CLI invocation pays the same three tolls before its first
useful byte of work: the jax backend initialization, the first XLA
compile, and the first device dispatch.  The serve front-end
(adam_tpu/serve) exists to amortize exactly those tolls across a request
stream — so they must be *numbers in the sidecar*, not a claim.  This
module is the passive recorder: cheap first-occurrence marks that the
existing hooks stamp as a run warms up, emitted as one
``startup_seconds`` event into the metrics sidecar (obs.metrics_run)
on every command.

Marks (all seconds, all best-effort — absent when the run never reached
that phase):

* ``backend_init_s``     — duration of the first backend-initializing
  jax call this process made through an instrumented site
  (platform.is_tpu_backend, the metrics manifest's backend probe, or
  platform.warm);
* ``first_compile_at_s`` — elapsed from :func:`begin` to the end of the
  first backend compile (platform.install_compile_metrics' listener);
* ``first_compile_s``    — that compile's own duration;
* ``first_dispatch_at_s``— elapsed to the first device dispatch
  (resilience.retry.dispatch_with_retry, site ``device_dispatch``).

The anchor defaults to module import time and :func:`begin` re-anchors
it (the CLI calls it at entry, before any jax import).  Everything here
is telemetry: lock-free reads, first-write-wins marks, never raises.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Dict, Iterator, Optional

_LOCK = threading.Lock()
#: anchor for the ``*_at_s`` marks — import time approximates process
#: start; begin() re-anchors at CLI entry
_T0: float = time.perf_counter()
_MARKS: Dict[str, float] = {}


def begin() -> None:
    """Re-anchor the clock and clear the marks (one per command run;
    the CLI and the bench/serve workers call this at entry)."""
    global _T0
    with _LOCK:
        _T0 = time.perf_counter()
        _MARKS.clear()


def mark_at(phase: str) -> None:
    """Record ``<phase>_at_s`` = elapsed since the anchor, first write
    wins (later occurrences of the same phase are not startup)."""
    t = time.perf_counter() - _T0
    with _LOCK:
        _MARKS.setdefault(f"{phase}_at_s", round(t, 6))


def mark_duration(phase: str, seconds: float) -> None:
    """Record ``<phase>_s`` = a measured duration, first write wins."""
    with _LOCK:
        _MARKS.setdefault(f"{phase}_s", round(float(seconds), 6))


@contextlib.contextmanager
def phase(name: str) -> Iterator[None]:
    """Time a block as ``<name>_s`` (first occurrence only).  The check
    whether the mark already landed is deliberately NOT taken up front:
    two racing first callers both measure, first write wins — cheaper
    than holding the lock across the body."""
    t0 = time.perf_counter()
    try:
        yield
    finally:
        mark_duration(name, time.perf_counter() - t0)


def note_first_compile(duration_s: float) -> None:
    """The compile listener's hook (platform.install_compile_metrics):
    the first backend compile stamps both its duration and when it
    finished relative to the anchor."""
    mark_duration("first_compile", duration_s)
    mark_at("first_compile")


def snapshot() -> Dict[str, float]:
    with _LOCK:
        return dict(_MARKS)


def emit_event(log=None) -> Optional[dict]:
    """Emit the ``startup_seconds`` event (into ``log`` when given, else
    the process-global event sink); returns the emitted fields or None
    when nothing was marked — a run that never touched jax has no
    startup story to tell."""
    snap = snapshot()
    if not snap:
        return None
    if log is not None:
        log.emit("startup_seconds", **snap)
    else:
        from . import events

        events.emit("startup_seconds", **snap)
    return snap
