"""Live time-series plane: a daemon sampler over the metrics registry.

The metrics plane so far is batch-shaped — the registry snapshot lands
in a sidecar only when a run's ``metrics_run`` context closes, so a
long-lived serve process (PRs 10-15) is a black box while it runs and a
SIGKILL loses everything since boot.  This module is the live axis: a
daemon thread snapshots the registry (counters/gauges/histograms, which
by now carry every serve signal — ``serve_backlog``/``serve_inflight``
gauges from the loop, ``overload_level``, ``breaker_open{site=}``,
``h2d_bytes{pass=}``, the ``serve_queue_seconds``/``serve_service_seconds``
tail histograms — plus an ``rss_mb`` gauge this sampler refreshes
itself) into a bounded in-memory ring and flushes the rows to a durable
``series.jsonl``.

Contract (the obs no-op discipline, same as trace.py):

* **zero overhead when off** — nothing is sampled, allocated, or
  written until :func:`start_series` runs; ``active()`` is one
  module-global read and no hot path ever calls in here.
* **crash-durable rows** — the file publishes atomically ONCE (tmp +
  fsync + rename, before the first sample row) and rows append to the
  published inode line-at-a-time with per-flush fsync, so a SIGKILL'd
  server keeps every row already flushed; readers skip a torn final
  line (:func:`read_series`).
* **bounded memory** — the pending ring holds at most ``max_rows``
  samples (``ADAM_TPU_SERIES_MAX_ROWS``); when flushing cannot keep up
  (an unwritable disk degrades to one stderr line, never a crash) the
  oldest pending samples drop and the cumulative ``dropped`` count is
  stamped on every later row and in the ``series_written`` receipt.
* **rows are exact monoids** — each sample carries a full registry
  snapshot (cumulative), so merging worker series follows the registry
  merge law exactly: counters sum, gauges max, histograms bucket-add
  (:func:`merge_snapshots`).  :func:`fold_rows` aligns rows from
  different sources on time buckets — last row per source per bucket,
  then the monoid across sources — which is how ``adam-tpu status``
  folds a fleet's worker series and how tools/check_series.py verifies
  the identity law on every written file.

Wiring: the serve loop starts a sampler at ``SPOOL/series.jsonl`` on
boot (serve/server.py, serve/scheduler.py — each fleet worker samples
its own sub-spool), and shard-fleet workers inherit a per-incarnation
path through ``ADAM_TPU_SERIES`` (parallel/shardstream.py), exactly
like ``ADAM_TPU_METRICS``.  docs/OBSERVABILITY.md documents the row
schema; tools/check_series.py validates written files.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

from . import events as _events
from .registry import registry

#: env fallback naming the output file — how spawned workers (shard
#: fleet incarnations) get a per-process series without a CLI flag
SERIES_ENV = "ADAM_TPU_SERIES"
#: sampling cadence in seconds (default 1.0)
SERIES_INTERVAL_ENV = "ADAM_TPU_SERIES_INTERVAL_S"
#: pending-ring bound in rows (default 4096)
SERIES_MAX_ROWS_ENV = "ADAM_TPU_SERIES_MAX_ROWS"

SCHEMA_VERSION = 1
DEFAULT_INTERVAL_S = 1.0
DEFAULT_MAX_ROWS = 4096

_SAMPLER: "Optional[SeriesSampler]" = None


def _rss_mb() -> Optional[float]:
    """Current resident set in MB (the serve ladder's memory signal,
    re-read here so every sample row carries it as a gauge).  Local
    /proc read — obs must not import the serve layer."""
    try:
        with open("/proc/self/statm") as f:
            pages = int(f.read().split()[1])
        return pages * (os.sysconf("SC_PAGE_SIZE") / (1 << 20))
    except Exception:  # noqa: BLE001 — a signal, never a crash
        return None


class SeriesSampler:
    """One process's live sampler: ring + file + daemon thread.

    ``source`` labels every row (pid plus whatever the caller adds —
    worker id, role) so folded fleet views can tell rows apart without
    trusting filenames.
    """

    def __init__(self, path: str, *, interval_s: Optional[float] = None,
                 max_rows: Optional[int] = None,
                 source: Optional[dict] = None):
        from ..resilience.retry import env_float, env_int

        self.path = path
        self.interval_s = max(env_float(interval_s, SERIES_INTERVAL_ENV,
                                        DEFAULT_INTERVAL_S), 0.005)
        self.max_rows = max(env_int(max_rows, SERIES_MAX_ROWS_ENV,
                                    DEFAULT_MAX_ROWS), 1)
        self.source = dict(source or {})
        self.source.setdefault("pid", os.getpid())
        self._lock = threading.Lock()
        self._ring: "collections.deque" = collections.deque()
        self._seq = 0
        self.dropped = 0
        self.rows_written = 0
        self._f = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._warned = False

    # -- sampling ----------------------------------------------------------

    def sample_now(self) -> dict:
        """Take one sample into the ring (drop-oldest past the bound)
        and return the row.  Called by the daemon loop; callable
        directly for deterministic tests."""
        rss = _rss_mb()
        if rss is not None:
            registry().gauge("rss_mb").set(rss)
        row = {"kind": "sample", "schema": SCHEMA_VERSION,
               "t": round(time.time(), 6), "source": dict(self.source),
               "metrics": registry().snapshot()}
        with self._lock:
            self._seq += 1
            row["seq"] = self._seq
            if len(self._ring) >= self.max_rows:
                self._ring.popleft()
                self.dropped += 1
            row["dropped"] = self.dropped
            self._ring.append(row)
        return row

    # -- durable file ------------------------------------------------------

    def _publish(self) -> None:
        """Create the durable file: manifest row into a tmp, fsync,
        atomic rename, KEEP the handle — the rename moves the inode, so
        later appends land on the published path while the publish
        itself can never leave a torn file under the real name."""
        tmp = self.path + ".tmp"
        parent = os.path.dirname(os.path.abspath(self.path))
        os.makedirs(parent, exist_ok=True)
        f = open(tmp, "w")
        f.write(json.dumps(
            {"kind": "series_manifest", "schema": SCHEMA_VERSION,
             "t0": round(time.time(), 6), "source": dict(self.source),
             "interval_s": self.interval_s,
             "max_rows": self.max_rows}, sort_keys=True) + "\n")
        f.flush()
        os.fsync(f.fileno())
        os.replace(tmp, self.path)
        self._f = f

    def _flush(self, fsync: bool = True) -> None:
        """Drain the pending ring to the file, one whole line per row.
        Failures degrade (one stderr warning; rows stay ringed and the
        bound drops the oldest) — telemetry never takes a server down."""
        with self._lock:
            rows = list(self._ring)
            self._ring.clear()
        if not rows:
            return
        try:
            if self._f is None:
                self._publish()
            for row in rows:
                self._f.write(json.dumps(row, sort_keys=True) + "\n")
            self._f.flush()
            if fsync:
                os.fsync(self._f.fileno())
            with self._lock:
                self.rows_written += len(rows)
        except (OSError, ValueError):
            with self._lock:
                # put the rows back (bounded) so a transient disk
                # error loses nothing the ring can still hold
                for row in rows:
                    if len(self._ring) >= self.max_rows:
                        self._ring.popleft()
                        self.dropped += 1
                    self._ring.append(row)
            if not self._warned:
                self._warned = True
                import sys
                print(f"adam-tpu: series not written to {self.path}",
                      file=sys.stderr)

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "SeriesSampler":
        self.sample_now()
        self._flush()
        self._thread = threading.Thread(
            target=self._run, name="series-sampler", daemon=True)
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.sample_now()
            self._flush()

    def stop(self, publish: bool = True) -> Optional[dict]:
        """Stop the daemon; with ``publish`` take one final sample,
        flush, fsync and return the write receipt."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        if not publish:
            if self._f is not None:
                try:
                    self._f.close()
                except OSError:
                    pass
                self._f = None
            return None
        self.sample_now()
        self._flush()
        if self._f is not None:
            try:
                self._f.close()
            except OSError:
                pass
            self._f = None
        return {"path": self.path, "rows": self.rows_written,
                "dropped": self.dropped}


# ---------------------------------------------------------------------------
# the process-global sampler
# ---------------------------------------------------------------------------

def active() -> Optional[SeriesSampler]:
    """``None`` (the default) means the plane is off: no thread, no
    ring, no file — the zero-overhead state."""
    return _SAMPLER


def start_series(path: str, *, interval_s: Optional[float] = None,
                 max_rows: Optional[int] = None,
                 source: Optional[dict] = None) -> SeriesSampler:
    """Install and start the process-global sampler (stopping any
    previous one without a receipt — the caller owns lifecycle)."""
    global _SAMPLER
    if _SAMPLER is not None:
        _SAMPLER.stop(publish=False)
    _SAMPLER = SeriesSampler(path, interval_s=interval_s,
                             max_rows=max_rows, source=source).start()
    return _SAMPLER


def stop_series() -> Optional[dict]:
    """Stop + final flush; emits the ``series_written`` receipt through
    the metrics plane (so a ``-metrics`` sidecar records where the
    run's series went) and returns it."""
    global _SAMPLER
    s, _SAMPLER = _SAMPLER, None
    if s is None:
        return None
    receipt = s.stop()
    if receipt:
        _events.emit("series_written", **receipt)
    return receipt


def discard_series() -> None:
    """Drop an active sampler without a final sample/receipt (test
    isolation — obs.reset_all)."""
    global _SAMPLER
    s, _SAMPLER = _SAMPLER, None
    if s is not None:
        s.stop(publish=False)


def series_path_from(flag_value: Optional[str]) -> Optional[str]:
    """The explicit path wins; ``ADAM_TPU_SERIES`` is the fallback (how
    shard-fleet workers get a per-incarnation series)."""
    return flag_value or os.environ.get(SERIES_ENV) or None


def maybe_start_from_env() -> Optional[SeriesSampler]:
    """Start a sampler iff ``ADAM_TPU_SERIES`` names a path and none is
    active — the worker-process entry hook (parallel/shardstream.py)."""
    path = series_path_from(None)
    if not path or _SAMPLER is not None:
        return None
    return start_series(path)


# ---------------------------------------------------------------------------
# the monoid: snapshot merge + cross-source fold
# ---------------------------------------------------------------------------

def empty_snapshot() -> dict:
    return {"counters": {}, "gauges": {}, "histograms": {}}


def merge_snapshots(a: dict, b: dict) -> dict:
    """PURE registry-snapshot merge — the exact law
    ``MetricsRegistry.merge`` applies (counters sum, gauges max,
    histograms bucket-add), on plain dicts so folds never touch the
    process-global registry.  ``empty_snapshot()`` is the identity."""
    out = {"counters": dict(a.get("counters") or {}),
           "gauges": dict(a.get("gauges") or {}),
           "histograms": {k: dict(v, buckets=dict(v.get("buckets") or {}))
                          for k, v in (a.get("histograms") or {}).items()}}
    for k, v in (b.get("counters") or {}).items():
        out["counters"][k] = out["counters"].get(k, 0) + v
    for k, v in (b.get("gauges") or {}).items():
        out["gauges"][k] = max(out["gauges"].get(k, v), v)
    for k, d in (b.get("histograms") or {}).items():
        h = out["histograms"].get(k)
        if h is None:
            out["histograms"][k] = dict(d, buckets=dict(d.get("buckets")
                                                        or {}))
            continue
        h["count"] = h.get("count", 0) + d.get("count", 0)
        h["sum"] = h.get("sum", 0.0) + d.get("sum", 0.0)
        for side, pick in (("min", min), ("max", max)):
            if d.get(side) is not None:
                h[side] = d[side] if h.get(side) is None \
                    else pick(h[side], d[side])
        buckets = h["buckets"]
        for bk, n in (d.get("buckets") or {}).items():
            buckets[bk] = buckets.get(bk, 0) + n
    return out


def _source_key(row: dict) -> str:
    return json.dumps(row.get("source") or {}, sort_keys=True)


def fold_rows(rows: Sequence[dict],
              bucket_s: Optional[float] = None) -> List[dict]:
    """Fold sample rows from ANY number of sources into one merged
    series: per time bucket take each source's LAST row (cumulative
    snapshots within one source supersede, they never add) then merge
    across sources by the registry monoid.  A single-source series
    folds to itself (the identity check in tools/check_series.py)."""
    samples = [r for r in rows if isinstance(r, dict)
               and r.get("kind") == "sample"]
    if not samples:
        return []
    if bucket_s is None or bucket_s <= 0:
        bucket_s = DEFAULT_INTERVAL_S
    per: Dict[int, Dict[str, dict]] = {}
    for r in samples:
        t = r.get("t")
        if not isinstance(t, (int, float)) or isinstance(t, bool):
            continue
        per.setdefault(int(t // bucket_s), {})[_source_key(r)] = r
    out = []
    for bucket in sorted(per):
        by_src = per[bucket]
        metrics = empty_snapshot()
        for key in sorted(by_src):
            metrics = merge_snapshots(metrics,
                                      by_src[key].get("metrics") or {})
        out.append({"kind": "sample", "schema": SCHEMA_VERSION,
                    "t": max(r["t"] for r in by_src.values()),
                    "sources": len(by_src), "metrics": metrics})
    return out


def read_series(path: str) -> Tuple[Optional[dict], List[dict]]:
    """``(manifest, sample_rows)`` from a written series file.  A torn
    final line (the crash case) is skipped; a missing/unreadable file
    is ``(None, [])`` — readers (status/top/explain) degrade, never
    crash."""
    manifest = None
    rows: List[dict] = []
    try:
        with open(path, encoding="utf-8", errors="replace") as f:
            lines = f.readlines()
    except OSError:
        return None, []
    for ln in lines:
        ln = ln.strip()
        if not ln:
            continue
        try:
            doc = json.loads(ln)
        except ValueError:
            continue            # torn tail (or tampering): skip the line
        if not isinstance(doc, dict):
            continue
        if doc.get("kind") == "series_manifest" and manifest is None:
            manifest = doc
        elif doc.get("kind") == "sample":
            rows.append(doc)
    return manifest, rows


def fold_series_files(paths: Sequence[str],
                      bucket_s: Optional[float] = None) -> List[dict]:
    """Read + fold several series files (a fleet's workers) into one
    merged series — the sidecar-merge twin of
    ``obs.merge_metrics_file``, at every time bucket instead of once at
    the end."""
    rows: List[dict] = []
    interval = None
    for p in paths:
        manifest, rs = read_series(p)
        rows.extend(rs)
        if manifest and isinstance(manifest.get("interval_s"),
                                   (int, float)):
            iv = float(manifest["interval_s"])
            interval = iv if interval is None else max(interval, iv)
    return fold_rows(rows, bucket_s=bucket_s if bucket_s else interval)
