"""Structured JSONL run-telemetry log (the ``-metrics PATH`` sink).

One run = one JSONL file:

  line 1   ``manifest``  — schema version, argv, config fingerprint,
                           backend/mesh shape (best effort), git rev
  lines    ``stage`` / ``chunk`` / domain events as the run progresses
  last     ``summary``   — wall time plus the full registry snapshot

Atomicity: events append to ``PATH.tmp`` (each line flushed whole, so a
tail is readable mid-run) and the file publishes to ``PATH`` by
fsync+rename on close — a crashed run leaves the partial ``.tmp``, never
a truncated final artifact.  ``tools/check_metrics.py`` validates the
published file against this schema (documented in
docs/OBSERVABILITY.md); bump ``SCHEMA_VERSION`` on any breaking change.

The sink is process-global and opt-in: ``emit`` is a no-op until a log
is open, so hot paths call it unconditionally.
"""

from __future__ import annotations

import hashlib
import json
import os
import socket
import subprocess
import sys
import threading
import time
from typing import Optional

SCHEMA_VERSION = 1

_LOCK = threading.Lock()
_LOG: "Optional[EventLog]" = None


class EventLog:
    def __init__(self, path: str):
        self.path = path
        self.tmp = path + ".tmp"
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)
        self._f = open(self.tmp, "w")
        self._t0 = time.time()
        self._closed = False

    def emit(self, event: str, **fields) -> None:
        if self._closed:
            return
        line = json.dumps({"event": event,
                           "t": round(time.time() - self._t0, 6),
                           **fields}, default=str)
        with _LOCK:
            self._f.write(line + "\n")
            self._f.flush()

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        with _LOCK:
            self._f.flush()
            os.fsync(self._f.fileno())
            self._f.close()
        os.replace(self.tmp, self.path)


def open_log(path: str) -> EventLog:
    """Open the process-global event log (closing any previous one)."""
    global _LOG
    if _LOG is not None:
        _LOG.close()
    _LOG = EventLog(path)
    return _LOG


def active() -> Optional[EventLog]:
    return _LOG


def emit(event: str, **fields) -> None:
    """Append one event; no-op when no log is open (the common case)."""
    if _LOG is not None:
        _LOG.emit(event, **fields)


def close_log() -> None:
    global _LOG
    if _LOG is not None:
        _LOG.close()
        _LOG = None


def discard_log() -> None:
    """Drop an open log without publishing (test isolation)."""
    global _LOG
    if _LOG is not None:
        _LOG._closed = True
        try:
            _LOG._f.close()
            os.unlink(_LOG.tmp)
        except OSError:
            pass
        _LOG = None


# ---------------------------------------------------------------------------
# manifest helpers
# ---------------------------------------------------------------------------

def config_fingerprint(config: Optional[dict]) -> str:
    blob = json.dumps(config or {}, sort_keys=True, default=str)
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


def _git_rev() -> Optional[str]:
    try:
        out = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            cwd=os.path.dirname(os.path.dirname(
                os.path.dirname(os.path.abspath(__file__)))),
            capture_output=True, text=True, timeout=5)
        return out.stdout.strip() or None
    except Exception:  # noqa: BLE001 — telemetry never fails a run
        return None


def _backend_info() -> dict:
    """Backend + mesh shape, best effort.  Only queried when a metrics log
    was requested (a run follows, so initializing the backend here is not
    an extra cost); any failure degrades to nulls."""
    info: dict = {"backend": None, "n_devices": None, "device_kind": None,
                  "process_index": 0, "process_count": 1}
    try:
        from . import startup

        # when a -metrics run's manifest is the first backend touch,
        # this probe IS the backend init — time it into the cold-start
        # breakdown (first write wins across the instrumented sites)
        with startup.phase("backend_init"):
            import jax

            info["backend"] = jax.default_backend()
        devs = jax.devices()
        info["n_devices"] = len(devs)
        info["device_kind"] = getattr(devs[0], "device_kind", None)
        info["process_index"] = jax.process_index()
        info["process_count"] = jax.process_count()
    except Exception:  # noqa: BLE001
        pass
    return info


def write_manifest(log: EventLog, argv=None, config: Optional[dict] = None,
                   **extra) -> None:
    log.emit("manifest",
             schema=SCHEMA_VERSION,
             time=time.strftime("%Y-%m-%dT%H:%M:%S%z"),
             argv=list(argv if argv is not None else sys.argv),
             config=config or {},
             config_fingerprint=config_fingerprint(config),
             git_rev=_git_rev(),
             host=socket.gethostname(),
             pid=os.getpid(),
             **_backend_info(),
             **extra)
