"""Process-global metrics registry: counters, gauges, histograms with labels.

The reference had no metrics plane at all — observability was log4j
printlns plus the Spark web UI (instrument.py's note); our rebuild's
telemetry so far was one private stage-timer tree.  This registry is the
substrate the whole pipeline reports through: `instrument.stage` feeds
per-stage counters/histograms, the streaming passes feed per-chunk
throughput and padding waste, platform.py feeds compile-cache hits and
compile wall-time, and the distributed layer merges per-worker snapshots
into the coordinator's registry (parallel/distributed.py).

Three metric kinds, three merge semantics (the monoid each one is):

* Counter   — monotonic float/int; merge = sum (an executor-map count)
* Gauge     — last-set value; merge = max (peaks: device_mem_peak)
* Histogram — sparse power-of-two buckets + count/sum/min/max;
              merge = bucket-wise add (exact, like the 18x2 flagstat
              counter block)

Updates are a dict lookup plus a float add — cheap enough to leave on
unconditionally, like the stage timers; nothing here ever touches a
device (the no-barrier guarantee is pinned by tests/test_obs.py).

Keys are Prometheus-style ``name{label=value,...}`` strings, which makes
snapshots JSON-plain and lets `merge` work on keys without parsing
labels back out.
"""

from __future__ import annotations

import math
import threading
from typing import Dict, Optional


def _key(name: str, labels: dict) -> str:
    if not labels:
        return name
    inner = ",".join(f"{k}={labels[k]}" for k in sorted(labels))
    return f"{name}{{{inner}}}"


class Counter:
    __slots__ = ("key", "value", "_lock")

    def __init__(self, key: str):
        self.key = key
        self.value = 0.0
        self._lock = threading.Lock()

    def inc(self, v: float = 1.0) -> None:
        # locked like Histogram.observe: the pipelined ingest pool calls
        # inc from worker threads, and += is a read-add-store
        with self._lock:
            self.value += v


class Gauge:
    __slots__ = ("key", "value", "_lock")

    def __init__(self, key: str):
        self.key = key
        self.value = 0.0
        self._lock = threading.Lock()

    def set(self, v: float) -> None:
        with self._lock:
            self.value = float(v)


class Histogram:
    """Sparse base-2 exponential histogram.

    A value lands in the bucket of its binary exponent (``frexp``), so one
    dict covers microsecond stage times and million-row chunk counts alike;
    bucket-wise addition makes the merge exact.  Non-positive values get
    their own sentinel bucket — zero-waste chunks must not share a bucket
    with the (0.5, 1] range, which is exactly what ``pad_waste_frac``
    exists to expose.
    """

    __slots__ = ("key", "count", "sum", "min", "max", "buckets", "_lock")

    def __init__(self, key: str):
        self.key = key
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf
        self.buckets: Dict[int, int] = {}
        self._lock = threading.Lock()

    #: bucket for v <= 0 — below every frexp exponent a positive double
    #: can produce (the smallest subnormal's is -1073), so it never
    #: collides with a real value bucket
    NONPOS_BUCKET = -1075

    def observe(self, v: float) -> None:
        v = float(v)
        # v in (2^(b-1), 2^b]; non-positive → the sentinel bucket
        b = self.NONPOS_BUCKET if v <= 0.0 else math.frexp(v)[1]
        with self._lock:
            self.count += 1
            self.sum += v
            self.min = min(self.min, v)
            self.max = max(self.max, v)
            self.buckets[b] = self.buckets.get(b, 0) + 1

    def mean(self) -> float:
        return self.sum / self.count if self.count else 0.0

    def to_dict(self) -> dict:
        return {"count": self.count, "sum": self.sum,
                "min": self.min if self.count else None,
                "max": self.max if self.count else None,
                "buckets": {str(b): n for b, n in sorted(self.buckets.items())}}


class MetricsRegistry:
    """One per process (module-global below), like instrument._REPORT."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._histograms: Dict[str, Histogram] = {}
        #: bumped on every reset() — lets once-per-run consumers (the
        #: distributed metrics merge) tell "same run" from "fresh run"
        self.generation = 0

    # -- get-or-create accessors -------------------------------------------

    def counter(self, name: str, **labels) -> Counter:
        key = _key(name, labels)
        c = self._counters.get(key)
        if c is None:
            with self._lock:
                c = self._counters.setdefault(key, Counter(key))
        return c

    def gauge(self, name: str, **labels) -> Gauge:
        key = _key(name, labels)
        g = self._gauges.get(key)
        if g is None:
            with self._lock:
                g = self._gauges.setdefault(key, Gauge(key))
        return g

    def histogram(self, name: str, **labels) -> Histogram:
        key = _key(name, labels)
        h = self._histograms.get(key)
        if h is None:
            with self._lock:
                h = self._histograms.setdefault(key, Histogram(key))
        return h

    # -- snapshot / merge ---------------------------------------------------

    def snapshot(self) -> dict:
        """JSON-plain view: the wire format between workers and the
        coordinator, and the ``metrics`` field of the JSONL summary event
        (docs/OBSERVABILITY.md)."""
        return {
            "counters": {k: c.value for k, c in self._counters.items()},
            "gauges": {k: g.value for k, g in self._gauges.items()},
            "histograms": {k: h.to_dict()
                           for k, h in self._histograms.items()},
        }

    def merge(self, snap: dict) -> None:
        """Fold another process's snapshot in: counters sum, gauges max,
        histograms bucket-add.  Keys need no parsing — they are identity."""
        for k, v in (snap.get("counters") or {}).items():
            c = self._counters.get(k)
            if c is None:
                with self._lock:
                    c = self._counters.setdefault(k, Counter(k))
            c.inc(v)
        for k, v in (snap.get("gauges") or {}).items():
            g = self._gauges.get(k)
            if g is None:
                with self._lock:
                    g = self._gauges.setdefault(k, Gauge(k))
            with g._lock:
                g.value = max(g.value, v)
        for k, d in (snap.get("histograms") or {}).items():
            h = self._histograms.get(k)
            if h is None:
                with self._lock:
                    h = self._histograms.setdefault(k, Histogram(k))
            with h._lock:
                h.count += d.get("count", 0)
                h.sum += d.get("sum", 0.0)
                if d.get("min") is not None:
                    h.min = min(h.min, d["min"])
                if d.get("max") is not None:
                    h.max = max(h.max, d["max"])
                for b, n in (d.get("buckets") or {}).items():
                    b = int(b)
                    h.buckets[b] = h.buckets.get(b, 0) + n

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._histograms.clear()
            self.generation += 1

    def is_empty(self) -> bool:
        return not (self._counters or self._gauges or self._histograms)


_REGISTRY = MetricsRegistry()


def registry() -> MetricsRegistry:
    return _REGISTRY


def counter(name: str, **labels) -> Counter:
    return _REGISTRY.counter(name, **labels)


def gauge(name: str, **labels) -> Gauge:
    return _REGISTRY.gauge(name, **labels)


def histogram(name: str, **labels) -> Histogram:
    return _REGISTRY.histogram(name, **labels)


def reset_registry() -> None:
    _REGISTRY.reset()
