"""Per-pass I/O ledger: bytes decoded vs spilled vs re-read.

ROADMAP item 1's claim — "inter-pass spill I/O is now the dominant
un-attacked cost in ``transform``" — was, until this module, a number
nobody measured.  The streaming transform decodes the input once (pass
1), spills it raw, then re-streams that spill twice (passes 2 and 3),
spills again into genome bins (+realign halos), and re-reads every bin
in pass 4.  This ledger counts each of those byte flows **at the I/O
layer itself** (``DatasetWriter`` close, ``reread()``, the bin/sub-spill
loads, the BAM/Parquet stream opens), attributed to the pass that paid
them:

* ``decoded`` — bytes of ORIGINAL input read off disk (file/dataset
  size at stream open; the one unavoidable read);
* ``spilled`` — bytes written to intermediate spill datasets (raw
  chunks, genome bins, halos, hot-bin sub-ranges) — the p1 raw spill,
  p3's bin routing, p4's hot-bin splits;
* ``reread`` — spill bytes read back (p2/p3 re-streams, p4 bin loads).

The derived **spill amplification** — (spilled + reread) / decoded — is
the number the item-1 single-stream fusion PR targets: a fused pipeline
that decodes once and materializes only the shuffle-shaped stages drives
it toward the p3/p4 floor.  Per-pass rows report their own contribution
against the run's decoded bytes, so the event stream shows WHERE the
amplification comes from.

Mechanics: byte counts land in registry counters
(``io_bytes_{decoded,spilled,reread}{pass=}`` — merge-able across
workers like every other counter) plus a process-local totals dict for
the end-of-run report; :func:`emit_events` emits one ``io_ledger``
event per pass plus a ``total`` rollup and sets the
``io_spill_amplification`` gauge.  Attribution uses an explicit
``pass_name`` where the call site knows it (writers, reread) and a
contextvar :func:`pass_scope` where the I/O layer is generic (the
stream openers) — readers record only when a scope is active, so
telemetry never misattributes unrelated I/O.

Everything here is telemetry: failures degrade to no-ops, byte counts
come from ``os.stat`` (never from reading data twice), and with no
consumer the counters are a dict lookup + add (the obs discipline).
"""

from __future__ import annotations

import contextlib
import contextvars
import os
import threading
from typing import Dict, Iterator, Optional

from . import events as _events
from .registry import registry

KINDS = ("decoded", "spilled", "reread")

_PASS: "contextvars.ContextVar[Optional[str]]" = contextvars.ContextVar(
    "adam_tpu_io_pass", default=None)

_LOCK = threading.Lock()
_TOTALS: Dict[str, Dict[str, int]] = {}    # pass -> kind -> bytes


@contextlib.contextmanager
def pass_scope(name: str) -> Iterator[None]:
    """Attribute reader-side I/O opened inside this block to ``name``.
    Contextvar-scoped, so concurrent passes in other threads (or other
    runs in async contexts) never cross-attribute."""
    tok = _PASS.set(name)
    try:
        yield
    finally:
        _PASS.reset(tok)


def current_pass() -> Optional[str]:
    return _PASS.get()


def path_bytes(path: Optional[str]) -> int:
    """On-disk bytes of a file or a Parquet dataset directory (sum of
    its part files) — the reconciliation currency of the whole ledger:
    every count here can be checked against ``du``."""
    if not path:
        return 0
    try:
        if os.path.isdir(path):
            return sum(os.path.getsize(os.path.join(path, f))
                       for f in os.listdir(path) if f.endswith(".parquet"))
        return os.path.getsize(path)
    except OSError:
        return 0


def dataset_bytes(path: Optional[str], columns=None) -> int:
    """On-disk bytes of a Parquet file/dataset, restricted to a
    projected column subset when ``columns`` is given.

    The honest-accounting fix behind the fused transform's gauge: a
    re-streaming pass that PROJECTS a column subset reads only those
    columns' pages off disk (Parquet pushdown), so charging it the full
    dataset size (the pre-fusion ``reread()`` behavior) overstated the
    re-read side of ``io_spill_amplification``.  Per-column compressed
    sizes come from the part footers (column-chunk
    ``total_compressed_size``; nested paths attribute to their root
    column) — still ``os.stat``-reconcilable: summing every column of
    every part is the file size minus footer overhead.  ``columns is
    None`` keeps the whole-file stat path.  Telemetry-grade: any footer
    trouble degrades to the full-size count, never an exception."""
    if not path:
        return 0
    if columns is None:
        return path_bytes(path)
    want = {c.split(".", 1)[0] for c in columns}
    try:
        import pyarrow.parquet as pq

        if os.path.isdir(path):
            parts = [os.path.join(path, f) for f in os.listdir(path)
                     if f.endswith(".parquet")]
        else:
            parts = [path]
        total = 0
        for part in parts:
            md = pq.ParquetFile(part).metadata
            for rg in range(md.num_row_groups):
                g = md.row_group(rg)
                for ci in range(g.num_columns):
                    col = g.column(ci)
                    if col.path_in_schema.split(".", 1)[0] in want:
                        total += col.total_compressed_size
        return int(total)
    except Exception:  # noqa: BLE001 — telemetry-grade, never fatal
        return path_bytes(path)


def record(kind: str, nbytes: int, pass_name: Optional[str] = None) -> None:
    """Count ``nbytes`` of ``kind`` I/O against ``pass_name`` (or the
    active :func:`pass_scope`).  No pass in scope and none given →
    dropped (generic I/O outside any instrumented pass is not ledger
    material)."""
    if nbytes <= 0:
        return
    name = pass_name or _PASS.get()
    if name is None:
        return
    registry().counter(f"io_bytes_{kind}", **{"pass": name}).inc(nbytes)
    with _LOCK:
        row = _TOTALS.setdefault(name, dict.fromkeys(KINDS, 0))
        row[kind] += int(nbytes)


def record_input(path: str, pass_name: Optional[str] = None) -> None:
    """Reader-side hook: a full scan of ``path`` begins — count its
    on-disk size as decoded input.  No-op outside a pass scope (the
    stream openers call this unconditionally)."""
    if pass_name or _PASS.get():
        record("decoded", path_bytes(path), pass_name)


def snapshot() -> Dict[str, Dict[str, int]]:
    with _LOCK:
        return {p: dict(row) for p, row in _TOTALS.items()}


def _totals(snap: Dict[str, Dict[str, int]]) -> Dict[str, int]:
    return {k: sum(row.get(k, 0) for row in snap.values()) for k in KINDS}


def spill_amplification(snap: Optional[dict] = None) -> Optional[float]:
    """(spilled + reread) / decoded over the whole run; None when the
    run decoded nothing (nothing to amortize against)."""
    tot = _totals(snapshot() if snap is None else snap)
    if tot["decoded"] <= 0:
        return None
    return (tot["spilled"] + tot["reread"]) / tot["decoded"]


def emit_events() -> Dict[str, Dict[str, int]]:
    """End-of-run rollup: one ``io_ledger`` event per pass (its bytes +
    its amplification contribution against the run's decoded bytes),
    one ``total`` event, and the ``io_spill_amplification`` gauge.
    Returns the snapshot it emitted (empty dict → emitted nothing)."""
    snap = snapshot()
    if not snap:
        return snap
    tot = _totals(snap)
    # decoded == 0 (a checkpoint resume that skipped pass 1, a
    # spill-only tool) leaves the ratio UNDEFINED: emit null, never a
    # clamped denominator — a raw byte count masquerading as a ratio
    # would feed straight into compare_bench's gate
    denom = tot["decoded"]

    def amp_of(row) -> Optional[float]:
        if denom <= 0:
            return None
        return round((row["spilled"] + row["reread"]) / denom, 4)

    for name in sorted(snap):
        row = snap[name]
        _events.emit("io_ledger", **{"pass": name},
                     decoded=row["decoded"], spilled=row["spilled"],
                     reread=row["reread"], amplification=amp_of(row))
    amp = amp_of(tot)
    _events.emit("io_ledger", **{"pass": "total"},
                 decoded=tot["decoded"], spilled=tot["spilled"],
                 reread=tot["reread"], amplification=amp)
    if amp is not None:
        registry().gauge("io_spill_amplification").set(amp)
    return snap


def format_report() -> str:
    """Human lines for the end-of-run report (``-timing``); empty string
    when no instrumented pass recorded I/O."""
    snap = snapshot()
    if not snap:
        return ""
    tot = _totals(snap)
    denom = tot["decoded"]

    def mb(n: int) -> str:
        return f"{n / 1e6:10.2f} MB"

    def amp_str(row) -> str:
        if denom <= 0:
            return "  n/a"      # undefined ratio (e.g. resumed run)
        return f"{(row['spilled'] + row['reread']) / denom:5.2f}x"

    lines = ["i/o ledger (decoded / spilled / re-read, "
             "amp = (spill+reread)/decoded):"]
    for name in sorted(snap):
        row = snap[name]
        lines.append(f"  {name:<10s}{mb(row['decoded'])}"
                     f"{mb(row['spilled'])}{mb(row['reread'])}"
                     f"   amp {amp_str(row)}")
    lines.append(f"  {'total':<10s}{mb(tot['decoded'])}"
                 f"{mb(tot['spilled'])}{mb(tot['reread'])}"
                 f"   amp {amp_str(tot)}")
    return "\n".join(lines)


def reset() -> None:
    """Zero the process-local totals (test isolation; the registry
    counters reset through the registry's own reset)."""
    with _LOCK:
        _TOTALS.clear()
