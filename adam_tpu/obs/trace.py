"""Run-wide tracing plane: thread-aware spans, Chrome-trace export.

The reference's only timeline view was the Spark web UI's stage bars;
``instrument.py`` rebuilt the per-stage wall-clock *totals* but its
report could never show host/feeder/device overlap — the stage stack was
process-shared, so PR 3 had to run feed producers unstaged and attribute
their cost consumer-side.  This module is the missing axis: a process-
global, **opt-in** span collector whose events carry (pid, tid) lanes,
exported as Chrome-trace / Perfetto-loadable JSON (``chrome://tracing``,
https://ui.perfetto.dev).  The span *stack* itself lives in
``instrument.py`` (one contextvar per thread); this module owns the
event sink and the file format.

Contract (the obs no-op discipline):

* **zero overhead when off** — ``active()`` is one module-global read;
  every hot-path hook checks it before doing any work.  No collector,
  no allocation, no lock, no event.
* **atomic publish** — the timeline writes via the shared
  ``checkpoint.atomic_write`` (tmp + fsync + rename), so a crashed run
  never leaves a torn JSON.
* **multiprocess merge** — workers write their own file (the
  ``ADAM_TPU_TRACE`` env names it, exactly like ``ADAM_TPU_METRICS``);
  the supervisor/coordinator folds worker events in by
  :func:`merge_trace_file` (elastic sidecars) or the KV gather
  (``parallel.distributed.merge_worker_traces``).  Timestamps are
  wall-clock-anchored microseconds, so lanes from different processes
  align on one timeline.

Event kinds (Chrome Trace Event Format):

* ``X`` complete — one per finished span (``instrument.stage``, executor
  dispatches, realign sweeps), with ``ts``/``dur`` in µs;
* ``C`` counter — small numeric series (prefetch in-flight depth);
* ``i`` instant — point markers (pass boundaries);
* ``M`` metadata — process/thread names, appended at finalize so every
  lane is labeled (feeder threads, the realign prep pool, workers).

``tools/check_trace.py`` validates the written file (schema, per-lane
monotonic timestamps, span nesting); ``docs/OBSERVABILITY.md`` has the
how-to-read walkthrough.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import List, Optional

from . import events as _events

#: env fallback for the CLI ``-trace`` flag — how bench workers and
#: elastic worker subprocesses get a per-process timeline sidecar
TRACE_ENV = "ADAM_TPU_TRACE"
#: buffered-event cap — a batch transform never hits it, but an
#: always-on server traced for days would otherwise grow the buffer
#: unboundedly; past the cap the OLDEST events drop (the recent window
#: is what you debug a live server with) and the count is stamped into
#: the published doc (``droppedEvents``) and the write receipt
TRACE_MAX_EVENTS_ENV = "ADAM_TPU_TRACE_MAX_EVENTS"
DEFAULT_TRACE_MAX_EVENTS = 1_000_000

_TRACE: "Optional[TraceCollector]" = None


class TraceCollector:
    """One run's span/counter event buffer plus its output path.

    Thread-safe appends; events buffer in memory (a streaming transform
    run produces thousands of spans, not millions — stage granularity,
    not instruction granularity) and publish once, atomically, at
    :meth:`write`.
    """

    def __init__(self, path: str, max_events: Optional[int] = None):
        from ..resilience.retry import env_int

        self.path = path
        self._lock = threading.Lock()
        self._events: List[dict] = []
        self.max_events = max(env_int(max_events, TRACE_MAX_EVENTS_ENV,
                                      DEFAULT_TRACE_MAX_EVENTS), 1)
        self.dropped = 0
        self._threads: dict = {}        # tid -> thread name (this process)
        self._pid = os.getpid()
        # wall-anchored clock: ts = wall0 + (perf_now - perf0), so spans
        # from different processes land on one aligned timeline while
        # durations keep perf_counter's resolution
        self._wall0 = time.time()
        self._perf0 = time.perf_counter()

    # -- clock -------------------------------------------------------------

    def now_us(self) -> float:
        """Wall-anchored timestamp in microseconds (Chrome-trace units)."""
        return (self._wall0 + (time.perf_counter() - self._perf0)) * 1e6

    # -- recording ---------------------------------------------------------

    def _push(self, ev: dict) -> None:
        """Ring-capped append — caller holds ``self._lock``.  Dropping
        the oldest keeps the recent window, which is the debuggable one
        on a long-lived server."""
        if len(self._events) >= self.max_events:
            overflow = len(self._events) - self.max_events + 1
            del self._events[:overflow]
            self.dropped += overflow
        self._events.append(ev)

    def _note_thread(self) -> int:
        t = threading.current_thread()
        tid = t.ident or 0
        if tid not in self._threads:
            self._threads[tid] = t.name
        return tid

    def complete(self, name: str, ts_us: float, dur_us: float,
                 cat: str = "stage", args: Optional[dict] = None) -> None:
        """One finished span (``X`` phase), recorded at span EXIT."""
        ev = {"name": name, "ph": "X", "cat": cat,
              "ts": round(ts_us, 3), "dur": round(max(dur_us, 0.0), 3),
              "pid": self._pid, "tid": self._note_thread()}
        if args:
            ev["args"] = args
        with self._lock:
            self._push(ev)

    def instant(self, name: str, cat: str = "mark",
                args: Optional[dict] = None) -> None:
        ev = {"name": name, "ph": "i", "cat": cat, "s": "t",
              "ts": round(self.now_us(), 3),
              "pid": self._pid, "tid": self._note_thread()}
        if args:
            ev["args"] = args
        with self._lock:
            self._push(ev)

    def counter(self, name: str, value: float) -> None:
        ev = {"name": name, "ph": "C", "cat": "counter",
              "ts": round(self.now_us(), 3), "pid": self._pid, "tid": 0,
              "args": {name: value}}
        with self._lock:
            self._push(ev)

    # -- merge (workers -> coordinator) ------------------------------------

    def add_events(self, evs: List[dict]) -> int:
        """Fold another process's events in (they carry their own
        pid/tid lanes and wall-anchored timestamps)."""
        evs = [e for e in evs if isinstance(e, dict)]
        with self._lock:
            for e in evs:
                self._push(e)
        return len(evs)

    def events(self) -> List[dict]:
        """Snapshot of the raw event list (the KV-gather wire format)."""
        with self._lock:
            return list(self._events)

    # -- publish -----------------------------------------------------------

    def finalize_doc(self) -> dict:
        """The Chrome-trace document: events sorted by timestamp plus
        process/thread name metadata for every lane this process saw
        (merged workers ship their own ``M`` events)."""
        with self._lock:
            evs = sorted(self._events,
                         key=lambda e: (e.get("pid", 0), e.get("tid", 0),
                                        e.get("ts", 0.0)))
            threads = dict(self._threads)
            dropped = self.dropped
        meta = [{"name": "process_name", "ph": "M", "pid": self._pid,
                 "tid": 0, "args": {"name": f"adam-tpu pid={self._pid}"}}]
        for tid, tname in sorted(threads.items()):
            meta.append({"name": "thread_name", "ph": "M",
                         "pid": self._pid, "tid": tid,
                         "args": {"name": tname}})
        doc = {"traceEvents": meta + evs, "displayTimeUnit": "ms"}
        if dropped:
            # the honesty stamp: a capped server trace is a WINDOW, and
            # the doc says so (check_trace tolerates extra keys)
            doc["droppedEvents"] = dropped
        return doc

    def write(self) -> dict:
        """Atomic publish (tmp + fsync + rename via the one shared
        ``atomic_write``); returns ``{path, events, lanes}``."""
        from ..checkpoint import atomic_write  # lazy: avoids an import
        #       cycle (checkpoint -> resilience.faults -> obs -> trace)

        doc = self.finalize_doc()
        # default=str: a span arg holding a non-JSON type (a numpy int,
        # a Path) must degrade to its repr, not crash the publish
        atomic_write(self.path, json.dumps(doc, default=str))
        lanes = {(e.get("pid"), e.get("tid")) for e in doc["traceEvents"]
                 if e.get("ph") == "X"}
        receipt = {"path": self.path,
                   "events": sum(1 for e in doc["traceEvents"]
                                 if e.get("ph") != "M"),
                   "lanes": len(lanes)}
        if doc.get("droppedEvents"):
            receipt["dropped"] = doc["droppedEvents"]
        return receipt


# ---------------------------------------------------------------------------
# the process-global collector
# ---------------------------------------------------------------------------

def active() -> Optional[TraceCollector]:
    """THE hot-path gate: one module-global read.  ``None`` (the default)
    means every trace hook is a no-op."""
    return _TRACE


def start_trace(path: str) -> TraceCollector:
    """Install the process-global collector (replacing any previous one
    WITHOUT writing it — ``trace_run`` owns the publish)."""
    global _TRACE
    _TRACE = TraceCollector(path)
    return _TRACE


def stop_trace() -> Optional[dict]:
    """Write and uninstall; returns the write receipt (or None)."""
    global _TRACE
    t, _TRACE = _TRACE, None
    return t.write() if t is not None else None


def discard_trace() -> None:
    """Drop an active collector without publishing (test isolation)."""
    global _TRACE
    _TRACE = None


def trace_path_from(flag_value: Optional[str]) -> Optional[str]:
    """The CLI flag wins; ``ADAM_TPU_TRACE`` is the fallback (how bench
    workers and elastic workers get a per-process timeline)."""
    return flag_value or os.environ.get(TRACE_ENV) or None


class span:
    """``with trace.span("name"):`` — a hand-rolled context manager (not
    ``@contextmanager``: no generator allocation on the off path, which
    hot loops take every chunk)."""

    __slots__ = ("name", "cat", "args", "_t", "_ts")

    def __init__(self, name: str, cat: str = "stage",
                 args: Optional[dict] = None):
        self.name = name
        self.cat = cat
        self.args = args
        self._t = None

    def __enter__(self):
        t = _TRACE
        if t is not None:
            self._t = t
            self._ts = t.now_us()
        return self

    def __exit__(self, *exc):
        t = self._t
        if t is not None:
            t.complete(self.name, self._ts, t.now_us() - self._ts,
                       cat=self.cat, args=self.args)
        return False


def instant(name: str, **args) -> None:
    t = _TRACE
    if t is not None:
        t.instant(name, args=args or None)


def counter(name: str, value: float) -> None:
    t = _TRACE
    if t is not None:
        t.counter(name, value)


# ---------------------------------------------------------------------------
# run wrapper + multiprocess merge
# ---------------------------------------------------------------------------

def trace_run(path: Optional[str]):
    """Context manager: open the collector, run, atomically publish the
    timeline (even when the body raises — a failed run's partial
    timeline is exactly what you debug with).  ``path=None`` is a no-op
    context, the common un-flagged case.  Emits a ``trace_written``
    event through the metrics plane so a ``-metrics`` sidecar records
    where its run's timeline went."""
    import contextlib

    @contextlib.contextmanager
    def _run():
        if not path:
            yield None
            return
        t = start_trace(path)
        try:
            yield t
        finally:
            # only publish if nobody swapped the collector underneath
            # (a nested start_trace owns the newer one)
            if _TRACE is t:
                try:
                    receipt = stop_trace()
                except Exception as e:  # noqa: BLE001 — telemetry must
                    # never fail an otherwise-successful run (the obs
                    # discipline): an unwritable trace path surfaces as
                    # one stderr line, not a nonzero exit after hours
                    # of completed work
                    import sys
                    print(f"adam-tpu: trace not written to {path}: {e}",
                          file=sys.stderr)
                else:
                    if receipt:
                        _events.emit("trace_written", **receipt)
    return _run()


def read_trace_events(path: str) -> Optional[List[dict]]:
    """A written timeline's events, or None when missing/torn."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    evs = doc.get("traceEvents") if isinstance(doc, dict) else None
    return evs if isinstance(evs, list) else None


def merge_trace_file(path: str) -> bool:
    """Fold a finished worker's timeline file into THIS process's active
    collector (the elastic supervisor's sidecar path).  Returns True
    when events merged; False when tracing is off here or the file is
    missing/torn."""
    t = _TRACE
    if t is None:
        return False
    evs = read_trace_events(path)
    if not evs:
        return False
    t.add_events(evs)
    return True
