"""Consensus generation + indel left-normalization (host-side string logic).

Re-implements ``models/Consensus.scala:23-63``, ``util/NormalizationUtils.scala``
(leftAlignIndel :36-115, barrel-rotate shift count :125-142, shiftIndel
:152-162) and ``rich/RichCigar.moveLeft`` (:53-110).

moveLeft is written to its intended semantics — trim one base from the
element before the indel, pad one onto the element after (appending 1M when
nothing follows) — rather than copying the reference's list surgery, which
silently drops elements for some cigar shapes (RichCigar.scala:76-80).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Tuple

from ..util.mdtag import MdTag, cigar_to_string, parse_cigar

_CONSUMES_READ = set("MIS=X")
_CONSUMES_REF = set("MDN=X")


@dataclass(frozen=True)
class Consensus:
    """An alternate allele hypothesis (Consensus.scala:54-63).

    ``start == end``: insertion of ``bases`` at position ``start``;
    ``end > start``: deletion of reference [start, end).
    """
    bases: str
    start: int
    end: int

    def insert_into_reference(self, reference: str, ref_start: int,
                              ref_end: int) -> str:
        if not (ref_start <= self.start <= ref_end and
                ref_start <= self.end <= ref_end):
            raise ValueError(
                f"Consensus [{self.start},{self.end}] and reference "
                f"[{ref_start},{ref_end}] do not overlap")
        return reference[:self.start - ref_start] + self.bases + \
            reference[self.end - ref_start:]

    @property
    def is_insertion(self) -> bool:
        return self.start == self.end


def generate_alternate_consensus(sequence: str, start: int,
                                 cigar: List[Tuple[int, str]]
                                 ) -> Optional[Consensus]:
    """Consensus.generateAlternateConsensus (Consensus.scala:25-50): a
    consensus exists for reads with exactly one I or D, all other ops M."""
    n_indel = sum(1 for _, op in cigar if op in "ID")
    if n_indel != 1:
        return None
    read_pos = 0
    ref_pos = start
    for length, op in cigar:
        if op == "I":
            return Consensus(sequence[read_pos:read_pos + length],
                             ref_pos, ref_pos)
        if op == "D":
            return Consensus("", ref_pos, ref_pos + length)
        if op in _CONSUMES_READ and op in _CONSUMES_REF:
            read_pos += length
            ref_pos += length
        else:
            return None
    return None


def num_alignment_blocks(cigar: List[Tuple[int, str]]) -> int:
    """RichCigar.numAlignmentBlocks (:38-45): count of M elements."""
    return sum(1 for _, op in cigar if op == "M")


def move_left(cigar: List[Tuple[int, str]], index: int
              ) -> List[Tuple[int, str]]:
    """Move element ``index`` one position left (RichCigar.moveLeft intent):
    the element before it shrinks by one, the element after grows by one
    (append 1M when the indel is last)."""
    if index <= 0 or index >= len(cigar):
        return list(cigar)
    out = [list(e) for e in cigar]
    out[index - 1][0] -= 1
    if index + 1 < len(out):
        out[index + 1][0] += 1
    else:
        out.append([1, "M"])
    result = [(l, op) for l, op in out if l > 0]
    return result


def cigar_total_length(cigar: List[Tuple[int, str]]) -> int:
    return sum(l for l, _ in cigar)


def shift_indel(cigar: List[Tuple[int, str]], index: int,
                shifts: int) -> List[Tuple[int, str]]:
    """NormalizationUtils.shiftIndel (:152-162): apply up to ``shifts``
    single-base left moves, stopping when the cigar would degenerate."""
    total = cigar_total_length(cigar)
    current = list(cigar)
    cur_index = index
    for _ in range(shifts):
        new = move_left(current, cur_index)
        if cigar_total_length(new) != total or len(new) < len(current):
            # the element before the indel vanished; the reference stops here
            break
        current = new
    return current


def num_positions_to_shift(variant: str, preceding: str) -> int:
    """Barrel-rotate shift count (NormalizationUtils:125-142)."""
    count = 0
    v = variant
    p = preceding
    while p and v and p[-1] == v[-1]:
        v = v[-1] + v[:-1]
        p = p[:-1]
        count += 1
    return count


def left_align_indel(sequence: str, cigar: List[Tuple[int, str]],
                     md: Optional[MdTag]) -> List[Tuple[int, str]]:
    """NormalizationUtils.leftAlignIndel (:36-115): shift a single indel as
    far left as the preceding read bases allow."""
    indel_pos = -1
    indel_len = 0
    is_insert = False
    read_pos = 0
    ref_pos = 0
    for i, (length, op) in enumerate(cigar):
        if op in "ID":
            if indel_pos != -1:
                return list(cigar)  # second indel: bail
            indel_pos = i
            indel_len = length
            is_insert = op == "I"
        elif indel_pos == -1:
            if op in _CONSUMES_READ:
                read_pos += length
            if op in _CONSUMES_REF:
                ref_pos += length
    if indel_pos == -1:
        return list(cigar)
    if is_insert:
        variant = sequence[read_pos:read_pos + indel_len]
    else:
        if md is None:
            return list(cigar)
        ref_seq = md.get_reference(sequence, cigar, 0)
        variant = ref_seq[ref_pos:ref_pos + indel_len]
    preceding = sequence[:read_pos]
    shifts = num_positions_to_shift(variant, preceding)
    return shift_indel(cigar, indel_pos, shifts)
