"""Indel realignment driver + the consensus sweep kernel.

Re-designs ``rdd/RealignIndels.scala``: target discovery reuses the pileup
engine (targets.py), reads map to targets by interval search, and each target
group is realigned against candidate indel consensuses.  The hot loop — every
read swept across every consensus at every admissible offset, scored by
summed mismatch quality (sweepReadOverReferenceForQuality :376-394, the
O(reads x consensuses x offsets x readLen) core) — runs as one batched device
kernel: a [R, O, L] mismatch tensor contracted against the quality vector.
Cigar/MD/start rewrites stay host-side string logic, checked against the
device-chosen offsets.

Acceptance: the best consensus must improve total mismatch quality by more
than lodThreshold (5.0) phred-decades over the original alignments
(RealignIndels.scala:176-182,308).  Realigned reads get mapq + 10 (:320).

One deliberate divergence: the reference's post-sweep cigar rewrite
(:327-345) emits an all-M cigar whenever the new start precedes the consensus
indel — which is exactly the common case, so its output contradicts the GATK
golden file its own test suite ships (the test passes vacuously: it filters
on ``getReadName == "read4"`` where getReadName is an Avro Utf8, so the
comparison is always false and the asserts run on empty lists).  We emit the
correct GATK-style cigar: M(bases before indel) I/D M(bases after), which
reproduces GATK's output for the artificial golden fixture.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from functools import lru_cache, partial
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import pyarrow as pa

from .. import schema as S
from ..packing import ReadBatch, column_int64, pack_reads, shape_rung
from ..util.mdtag import MdTag, cigar_to_string
from .consensus import (Consensus, generate_alternate_consensus,
                        left_align_indel, num_alignment_blocks)
from .targets import find_targets, map_reads_to_targets

LOD_THRESHOLD = 5.0   # RealignIndels.scala:181
MAX_INDEL_SIZE = 3000
BIG = 1 << 30


@partial(jax.jit, static_argnames=())
def _sweep_kernel(reads_u8, quals, read_lens, cons_u8, cons_len):
    """Batched sweep: best (mismatch-quality, offset) per read.

    reads_u8 [R, L], quals [R, L], read_lens [R], cons_u8 [CL] (padded),
    cons_len scalar.  Admissible offsets are 0 <= o < cons_len - read_len
    (sweepReadOverReferenceForQuality :381); ties resolve to the lowest
    offset, like the reference's reduction.
    """
    R, L = reads_u8.shape
    CL = cons_u8.shape[0]
    offs = jnp.arange(CL)
    idx = jnp.clip(offs[:, None] + jnp.arange(L)[None, :], 0, CL - 1)
    cons_win = cons_u8[idx]                                    # [CL, L]
    in_read = jnp.arange(L)[None, :] < read_lens[:, None]      # [R, L]
    w = jnp.where(in_read, quals, 0).astype(jnp.int32)
    mm = reads_u8[:, None, :] != cons_win[None, :, :]          # [R, CL, L]
    score = jnp.sum(mm * w[:, None, :], axis=-1)               # [R, CL]
    valid = offs[None, :] < (cons_len - read_lens)[:, None]
    score = jnp.where(valid, score, BIG)
    best_o = jnp.argmin(score, axis=1)
    best_q = jnp.take_along_axis(score, best_o[:, None], 1)[:, 0]
    return best_q, best_o


# every IUPAC nucleotide code — either case, since SAM sequence is
# [A-Za-z=.] and soft-masked references are lowercase — gets its own one-hot
# class so that class equality == byte equality for any real sequence; only
# bytes outside this alphabet alias into the trailing 'other' class
_BASE_ALPHABET = b"ACGTNRYSWKMBDHVU=acgtnryswkmbdhvu."
_N_BASE_CLASSES = len(_BASE_ALPHABET) + 1


def _sweep_conv_impl(reads_u8, quals, read_lens, cons_u8, cons_len):
    """The sweep as one MXU convolution.

    score[r, o] = sum_l w[r,l] * [read[r,l] != cons[o+l]]
                = wsum[r] - sum_{l,b} (w[r,l] * readOH[r,l,b]) * consOH[o+l,b]

    i.e. total quality minus a correlation of the quality-weighted one-hot
    read against the one-hot consensus — a single conv_general_dilated with
    the consensus as the (N=1, C=B, W=CL+L) input and the reads as (O=R,
    I=B, W=L) filters, B the per-character class count, output [R, CL+1].  XLA lowers it straight onto the systolic array; no
    [R, O, L] intermediate ever exists.  f32 accumulation is exact here
    (scores are integers < 2^24).
    """
    classes = jnp.arange(_N_BASE_CLASSES, dtype=jnp.int32)

    def encode(u8):
        lut = jnp.full((256,), _N_BASE_CLASSES - 1, jnp.int32)
        for i, c in enumerate(_BASE_ALPHABET):
            lut = lut.at[c].set(i)
        return lut[u8.astype(jnp.int32)]

    R, L = reads_u8.shape
    CL = cons_u8.shape[0]
    in_read = jnp.arange(L)[None, :] < read_lens[:, None]
    w = jnp.where(in_read, quals, 0).astype(jnp.float32)          # [R, L]
    read_oh = (encode(reads_u8)[:, :, None] == classes).astype(jnp.float32)
    wq = w[:, :, None] * read_oh                                  # [R, L, B]
    cons_oh = (encode(cons_u8)[:, None] == classes).astype(jnp.float32)
    # pad by L all-zero columns so every admissible offset of a short read
    # (up to cons_len - read_len > CL - L) gets a conv output; the padding
    # itself is never scored — admissible windows keep weighted lanes inside
    # the true consensus
    cons_oh = jnp.concatenate(
        [cons_oh, jnp.zeros((L, _N_BASE_CLASSES), jnp.float32)], axis=0)
    match = jax.lax.conv_general_dilated(
        cons_oh.T[None, :, :],                # [1, B, CL]
        jnp.transpose(wq, (0, 2, 1)),         # [R, B, L]
        window_strides=(1,), padding="VALID",
        dimension_numbers=("NCH", "OIH", "NCH"),
        preferred_element_type=jnp.float32)[0]                    # [R, CL-L+1]
    score = (jnp.sum(w, axis=1, keepdims=True) - match).astype(jnp.int32)
    offs = jnp.arange(score.shape[1])
    valid = offs[None, :] < (cons_len - read_lens)[:, None]
    score = jnp.where(valid, score, BIG)
    best_o = jnp.argmin(score, axis=1)
    best_q = jnp.take_along_axis(score, best_o[:, None], 1)[:, 0]
    return best_q, best_o


_sweep_conv = jax.jit(_sweep_conv_impl)

#: many (target-group, consensus) jobs of one padded shape in ONE dispatch —
#: the batching VERDICT r1 #7 called for (the reference amortizes its
#: per-target loop across Spark executors, RealignIndels.scala:238-364;
#: here the amortization axis is the G dimension of a vmapped MXU conv)
_sweep_conv_many = jax.jit(jax.vmap(_sweep_conv_impl))


#: sweep implementation override: "conv" | "pallas" | "auto" (default).
#: auto races both once per process on TPU backends and keeps the winner —
#: the bench artifact records the same comparison (bench.py --worker pallas)
_SWEEP_IMPL_ENV = "ADAM_TPU_SWEEP_IMPL"


@lru_cache(maxsize=1)
def _sweep_backend() -> str:
    choice = os.environ.get(_SWEEP_IMPL_ENV, "auto")
    if choice in ("conv", "pallas"):
        return choice
    if jax.default_backend() == "cpu":
        return "conv"     # pallas needs a TPU (interpret mode is test-only)
    try:
        from .sweep_pallas import sweep_pallas
        import numpy as _np
        import time as _time
        rng = _np.random.RandomState(0)
        R, L, CL = 64, 100, 512
        bases = _np.frombuffer(b"ACGT", _np.uint8)
        reads = jnp.asarray(bases[rng.randint(0, 4, (R, L))])
        quals = jnp.asarray(rng.randint(2, 41, (R, L)).astype(_np.int32))
        lens = jnp.full((R,), L, jnp.int32)
        cons = jnp.asarray(bases[rng.randint(0, 4, (CL,))])
        qp, op_ = sweep_pallas(reads, quals, lens, cons, CL)
        qc, oc = _sweep_conv(reads, quals, lens, cons, CL)
        jax.block_until_ready((qp, op_, qc, oc))
        if not (jnp.array_equal(qp, qc) and jnp.array_equal(op_, oc)):
            return "conv"
        t0 = _time.perf_counter()
        for _ in range(5):
            jax.block_until_ready(
                sweep_pallas(reads, quals, lens, cons, CL))
        t_pl = _time.perf_counter() - t0
        t0 = _time.perf_counter()
        for _ in range(5):
            jax.block_until_ready(_sweep_conv(reads, quals, lens, cons, CL))
        t_cv = _time.perf_counter() - t0
        return "pallas" if t_pl < t_cv else "conv"
    except Exception:  # noqa: BLE001 — any pallas failure means conv
        return "conv"


def _sweep(reads_u8, quals, read_lens, cons_u8, cons_len):
    """Production sweep: backend-selected between the conv formulation
    (MXU; vectorized everywhere) and the VMEM-streaming pallas kernel
    (sweep_pallas), raced once per process on TPU (VERDICT r2 weak #2:
    the kernels must be wired in or proven, not decorative).
    ``_sweep_kernel`` is the O(R*O*L)-materializing naive oracle for
    tests."""
    if _sweep_backend() == "pallas":
        from .sweep_pallas import sweep_pallas
        return sweep_pallas(reads_u8, quals, read_lens, cons_u8,
                            int(cons_len))
    return _sweep_conv(reads_u8, quals, read_lens, cons_u8, cons_len)


@lru_cache(maxsize=1)
def _sweep_conv_donating():
    """Single-job counterpart of :func:`_sweep_conv_many_donating` —
    buckets that dispatch exactly one job (rare shapes, tail chunks)
    follow the same donation discipline as the batched path."""
    return jax.jit(_sweep_conv_impl, donate_argnums=(0, 1, 2, 3))


@lru_cache(maxsize=1)
def _sweep_conv_many_donating():
    """TPU variant of the batched conv sweep with its per-dispatch
    operands donated, so the device reuses the arriving batch's HBM for
    outputs/scratch instead of re-allocating every dispatch (PR 3's
    donation discipline applied to the realign hot loop).  Off-TPU
    donation buys nothing and XLA warns per call, so callers gate it
    (realign_exec's plan sets donate only on TPU backends)."""
    return jax.jit(jax.vmap(_sweep_conv_impl),
                   donate_argnums=(0, 1, 2, 3, 4))


def _sweep_many(reads_b, quals_b, lens_b, cons_b, clen_b,
                donate: bool = False):
    """Batched sweep over one padded-shape bucket (G leading axis)."""
    if _sweep_backend() == "pallas":
        from .sweep_pallas import sweep_pallas_batch
        return sweep_pallas_batch(reads_b, quals_b, lens_b, cons_b, clen_b)
    fn = _sweep_conv_many_donating() if donate else _sweep_conv_many
    return fn(jnp.asarray(reads_b), jnp.asarray(quals_b),
              jnp.asarray(lens_b), jnp.asarray(cons_b),
              jnp.asarray(clen_b))


# ---------------------------------------------------------------------------
# ragged sweep: concatenated reads across jobs, (CL, G)-only bucketing
# ---------------------------------------------------------------------------

@partial(jax.jit, static_argnames=("cl_pad",))
def _sweep_ragged_impl(base_flat, w_flat, row_of, pos_of, job_of_row,
                       read_len_r, cons_flat, cons_len_g, cl_pad):
    """The consensus sweep over the RAGGED layout — the XLA segment-sum
    formulation (the off-TPU product path; sweep_pallas.sweep_pallas_ragged
    is the Mosaic twin).

    Reads from MANY (group, consensus) jobs concatenate into flat [T]
    base/weight planes with a prefix-sum row index (``row_of``/
    ``pos_of``); each read row maps to its job's consensus through
    ``job_of_row``.  score[r, o] = sum over the read's bases of
    w * [base != cons[job(r), o + pos]] — one [T, CLp] gather+compare,
    then ONE segment_sum over the row index.  No (R, L) padding exists:
    compiled shapes depend only on the flat-plane rung, the row rung,
    and the (CL, G) rungs — the four-axis pad tax of the padded batch
    collapses to rung slack on the two concatenated totals.

    Integer scores, BIG at inadmissible offsets, argmin tie-break to the
    lowest offset: exactly the padded kernels' semantics, so per-job
    results are bit-identical to :func:`_sweep_conv` / sweep_pallas on
    any real sequence (raw-byte comparison, the pallas kernel's rule).
    """
    offs = jnp.arange(cl_pad, dtype=jnp.int32)
    cidx = job_of_row[row_of] * cl_pad + pos_of               # [T]
    idx = jnp.clip(cidx[:, None] + offs[None, :], 0,
                   cons_flat.shape[0] - 1)                    # [T, CLp]
    mm = (base_flat[:, None] != cons_flat[idx]).astype(jnp.int32)
    contrib = mm * w_flat[:, None]
    scores = jax.ops.segment_sum(contrib, row_of,
                                 num_segments=read_len_r.shape[0])
    valid = offs[None, :] < (cons_len_g[job_of_row][:, None]
                             - read_len_r[:, None])
    scores = jnp.where(valid, scores, BIG)
    best_o = jnp.argmin(scores, axis=1)
    best_q = jnp.take_along_axis(scores, best_o[:, None], 1)[:, 0]
    return best_q, best_o


def _sweep_ragged_xla(base_flat, w_flat, row_of, pos_of, job_of_row,
                      read_len_r, cons_b, cons_len_g):
    """Wrapper flattening the [G, CLp] consensus block for the jitted
    impl (cl_pad must be a concrete int for the index arithmetic)."""
    G, CLp = cons_b.shape
    return _sweep_ragged_impl(
        jnp.asarray(base_flat), jnp.asarray(w_flat), jnp.asarray(row_of),
        jnp.asarray(pos_of), jnp.asarray(job_of_row),
        jnp.asarray(read_len_r), jnp.asarray(cons_b).reshape(-1),
        jnp.asarray(cons_len_g), cl_pad=CLp)


#: flat-plane rung multiple for the ragged sweep (lane-aligned); row
#: rung multiple matches the padded R rung's 32
_RAGGED_T_MULT = 2048
_RAGGED_R_MULT = 32


def sweep_dispatch_ragged(pairs: List[Tuple["_GroupState", "_SweepJob"]],
                          donate: bool = False):
    """One RAGGED device dispatch over (group, consensus) jobs sharing a
    CL rung — the counterpart of :func:`sweep_dispatch` that needs no
    shared (R, L): each job contributes its group's TRUE rows at TRUE
    lengths to the concatenated planes.

    Returns ``[(q, o)]`` numpy pairs per job (true row count each) —
    exactly what ``_finish_group`` consumes, bit-identical to the padded
    dispatch's per-job lanes.  ``donate`` is accepted for signature
    parity; the flat planes are rebuilt per dispatch, so donation buys
    nothing here (the plan's donate knob stays a padded-path lever).
    """
    CL = pairs[0][1].shape[2]
    assert all(job.shape[2] == CL for _, job in pairs), "one CL rung"
    n_rows = [len(st.reads_to_clean) for st, _ in pairs]
    t_rows = [int(st.lens[:r].sum()) for (st, _), r in zip(pairs, n_rows)]
    Rt = sum(n_rows)
    T = sum(t_rows)
    G = 1 << max(len(pairs) - 1, 0).bit_length()
    CLp = CL

    # shared (cheap) geometry: per-row job map, true lengths, consensus
    # block — slack rows sweep nothing (read_len = CL leaves no
    # admissible offset, the padded kernels' own pad-row rule).  The
    # XLA branch pads rows/bases to its own rungs; the row-structured
    # Mosaic branch pads (8, 128)-tile geometry inside
    # sweep_pallas_ragged — stats report whichever geometry THIS
    # dispatch actually allocated (the realign_sweep_dispatch event's
    # honesty contract).
    Rp = shape_rung(max(Rt, 1), _RAGGED_R_MULT)
    job_of_row = np.zeros(Rp, np.int32)
    read_len_r = np.full(Rp, CL, np.int32)
    cons_b = np.zeros((G, CLp), np.int32)
    cons_len_g = np.zeros(G, np.int32)
    r0 = 0
    spans = []
    for g, ((st, job), nr) in enumerate(zip(pairs, n_rows)):
        job_of_row[r0:r0 + nr] = g
        read_len_r[r0:r0 + nr] = st.lens[:nr]
        cons_b[g, :len(job.cons_u8)] = job.cons_u8.astype(np.int32)
        cons_len_g[g] = job.cons_len
        spans.append((r0, r0 + nr))
        r0 += nr
    # padded job lanes replicate lane 0 (no garbage consensus swept)
    cons_b[len(pairs):] = cons_b[0]
    cons_len_g[len(pairs):] = cons_len_g[0]

    if _sweep_backend() == "pallas":
        from .sweep_pallas import sweep_pallas_ragged
        # row-structured form for Mosaic: [Rt, Lmax] planes + a per-row
        # consensus gather (same values, kernel-friendly layout); the
        # flat planes below are the XLA branch's and are never built
        # here — each branch pays only its own layout's host prep
        Lmax = max((int(st.lens[:nr].max(initial=1))
                    for (st, _), nr in zip(pairs, n_rows)), default=1)
        reads_rows = np.zeros((Rt, Lmax), np.int32)
        w_rows = np.zeros((Rt, Lmax), np.int32)
        r0 = 0
        for (st, _), nr in zip(pairs, n_rows):
            W = min(st.reads_u8.shape[1], Lmax)
            reads_rows[r0:r0 + nr, :W] = st.reads_u8[:nr, :W]
            w_rows[r0:r0 + nr, :W] = st.quals_arr[:nr, :W]
            r0 += nr
        lane = np.arange(Lmax, dtype=np.int32)[None, :]
        w_rows = np.where(lane < read_len_r[:Rt, None], w_rows, 0)
        q, o = sweep_pallas_ragged(
            reads_rows, w_rows, read_len_r[:Rt],
            cons_b[job_of_row[:Rt]], cons_len_g[job_of_row[:Rt]])
        # the Mosaic kernel's own tile geometry (sweep_pallas_ragged
        # pads to 8 sublanes x 128 lanes), not the XLA branch's rungs
        rows_pad = -(-max(Rt, 8) // 8) * 8
        bases_pad = rows_pad * (-(-max(Lmax, 128) // 128) * 128)
    else:
        rows_pad = Rp
        bases_pad = Tp = shape_rung(max(T, 1), _RAGGED_T_MULT)
        base_flat = np.zeros(Tp, np.int32)
        w_flat = np.zeros(Tp, np.int32)
        row_of = np.zeros(Tp, np.int32)
        pos_of = np.zeros(Tp, np.int32)
        r0 = t0 = 0
        for (st, _), nr, tr in zip(pairs, n_rows, t_rows):
            lens = st.lens[:nr].astype(np.int64)
            mask = np.arange(st.reads_u8.shape[1])[None, :] < \
                lens[:, None]
            base_flat[t0:t0 + tr] = st.reads_u8[:nr][mask]
            w_flat[t0:t0 + tr] = st.quals_arr[:nr][mask]
            row_of[t0:t0 + tr] = r0 + np.repeat(np.arange(nr), lens)
            pos_of[t0:t0 + tr] = _pos_within(lens)
            r0 += nr
            t0 += tr
        q, o = _sweep_ragged_xla(base_flat, w_flat, row_of, pos_of,
                                 job_of_row, read_len_r, cons_b,
                                 cons_len_g)
        q, o = q[:Rt], o[:Rt]
    stats = dict(rows=Rt, rows_pad=rows_pad, bases=T, bases_pad=bases_pad,
                 g=G, cl=CLp,
                 cons_true=int(cons_len_g[:len(pairs)].sum()))
    return np.asarray(q), np.asarray(o), spans, stats


#: the flat planes the paged sweep pages — int32 like the XLA ragged
#: branch's planes (name, dtype)
PAGED_SWEEP_PLANES = (("base", "int32"), ("w", "int32"),
                      ("row_of", "int32"), ("pos_of", "int32"))


def sweep_paged_xla(pools: dict, page_table, job_of_row, read_len_r,
                    cons_b, cons_len_g):
    """Paged entry of the consensus sweep — the ragged XLA form fed by
    RESIDENT page pools instead of freshly concatenated flat planes
    (docs/ARCHITECTURE.md §6l).

    ``pools`` maps each :data:`PAGED_SWEEP_PLANES` name to its
    ``[pool_pages, page_rows]`` device array; ``page_table`` lists this
    dispatch's physical pages in logical order.  One gather per plane
    reconstructs exactly the arrays :func:`_sweep_ragged_xla` consumes
    — bit-identical per job to the ragged dispatch by construction
    (tests/test_paged.py pins it against
    :func:`sweep_dispatch_ragged`'s XLA branch)."""
    from ..parallel.pagedbuf import gather_pages

    pt = jnp.asarray(page_table, jnp.int32)
    return _sweep_ragged_xla(
        gather_pages(pools["base"], pt), gather_pages(pools["w"], pt),
        gather_pages(pools["row_of"], pt),
        gather_pages(pools["pos_of"], pt),
        job_of_row, read_len_r, cons_b, cons_len_g)


def sweep_dispatch_paged(pairs: List[Tuple["_GroupState", "_SweepJob"]],
                         pool=None):
    """One PAGED device dispatch over (group, consensus) jobs sharing a
    CL rung — :func:`sweep_dispatch_ragged`'s paged twin: the flat
    base/weight/walk planes ship page-granular through a resident
    :class:`..parallel.pagedbuf.PagePool` (only live pages cross the
    link; the rung slack past the last page never ships) and the kernel
    walks the page table.  Returns the same ``(q, o, spans, stats)``
    contract.  ``pool`` (optional) is a caller-held resident pool
    reused across dispatches; a transient one is built otherwise.
    Falls back to :func:`sweep_dispatch_ragged` when the pool would
    thrash (decide_pages' fallback answer)."""
    from ..parallel.pagedbuf import DEFAULT_PAGE_ROWS, PagePool

    CL = pairs[0][1].shape[2]
    assert all(job.shape[2] == CL for _, job in pairs), "one CL rung"
    n_rows = [len(st.reads_to_clean) for st, _ in pairs]
    t_rows = [int(st.lens[:r].sum()) for (st, _), r in zip(pairs, n_rows)]
    Rt = sum(n_rows)
    T = sum(t_rows)
    G = 1 << max(len(pairs) - 1, 0).bit_length()
    Rp = shape_rung(max(Rt, 1), _RAGGED_R_MULT)
    job_of_row = np.zeros(Rp, np.int32)
    read_len_r = np.full(Rp, CL, np.int32)
    cons_b = np.zeros((G, CL), np.int32)
    cons_len_g = np.zeros(G, np.int32)
    r0 = 0
    spans = []
    for g, ((st, job), nr) in enumerate(zip(pairs, n_rows)):
        job_of_row[r0:r0 + nr] = g
        read_len_r[r0:r0 + nr] = st.lens[:nr]
        cons_b[g, :len(job.cons_u8)] = job.cons_u8.astype(np.int32)
        cons_len_g[g] = job.cons_len
        spans.append((r0, r0 + nr))
        r0 += nr
    cons_b[len(pairs):] = cons_b[0]
    cons_len_g[len(pairs):] = cons_len_g[0]

    if pool is None:
        page_rows = min(DEFAULT_PAGE_ROWS, _RAGGED_T_MULT)
        n_pages = max(-(-max(T, 1) // page_rows) * 2, 2)
        pool = PagePool("p4", n_pages, page_rows,
                        planes=PAGED_SWEEP_PLANES)
    page_rows = pool.page_rows
    need = -(-max(T, 1) // page_rows)
    ids = pool.alloc(need)
    if ids is None:         # pool thrash: the concat path is the answer
        return sweep_dispatch_ragged(pairs)
    Tp = need * page_rows
    base_flat = np.zeros(Tp, np.int32)
    w_flat = np.zeros(Tp, np.int32)
    row_of = np.zeros(Tp, np.int32)
    pos_of = np.zeros(Tp, np.int32)
    r0 = t0 = 0
    for (st, _), nr, tr in zip(pairs, n_rows, t_rows):
        lens = st.lens[:nr].astype(np.int64)
        mask = np.arange(st.reads_u8.shape[1])[None, :] < lens[:, None]
        base_flat[t0:t0 + tr] = st.reads_u8[:nr][mask]
        w_flat[t0:t0 + tr] = st.quals_arr[:nr][mask]
        row_of[t0:t0 + tr] = r0 + np.repeat(np.arange(nr), lens)
        pos_of[t0:t0 + tr] = _pos_within(lens)
        r0 += nr
        t0 += tr
    pool.write(ids, base=base_flat, w=w_flat, row_of=row_of,
               pos_of=pos_of)
    try:
        q, o = sweep_paged_xla(
            {n: pool.device(n) for n, _ in PAGED_SWEEP_PLANES},
            pool.table(ids), job_of_row, read_len_r, cons_b, cons_len_g)
        q, o = np.asarray(q)[:Rt], np.asarray(o)[:Rt]
    finally:
        pool.free(ids)
    stats = dict(rows=Rt, rows_pad=Rp, bases=T, bases_pad=Tp,
                 g=G, cl=CL,
                 cons_true=int(cons_len_g[:len(pairs)].sum()))
    return q, o, spans, stats


def _pos_within(lens: np.ndarray) -> np.ndarray:
    """0..len_i-1 per read, concatenated (int32) — the shared
    prefix-sum walk primitive, narrowed for the device planes."""
    from ..packing import _ranges_within

    return _ranges_within(lens).astype(np.int32)


#: per-dispatch budget for the ragged sweep's [T, CLp] working set (the
#: gather/compare intermediate, int32) — the analogue of
#: _SWEEP_BATCH_BUDGET for the flat formulation
_RAGGED_SWEEP_BUDGET = 128 << 20


def ragged_chunk_jobs(members_t: List[int], cl_pad: int) -> List[int]:
    """Split points for a ragged bucket's member list: cumulative flat
    bases are bounded so the [T, CLp] int32 working set stays under
    budget (always at least one member per chunk)."""
    cap = max(_RAGGED_SWEEP_BUDGET // (4 * max(cl_pad, 1)), 1)
    splits = []
    acc = 0
    for i, t in enumerate(members_t):
        if acc and acc + t > cap:
            splits.append(i)
            acc = 0
        acc += t
    return splits


@dataclass
class _Read:
    """Host-side view of one read inside a target group."""
    row: int
    seq: str
    quals: List[int]
    start: int
    mapq: int
    cigar: List[Tuple[int, str]]
    md: Optional[MdTag]
    md_str: Optional[str]

    def end(self) -> int:
        return self.start + sum(l for l, op in self.cigar if op in "MDN=X")


def _sum_mismatch_quality(read: _Read) -> int:
    """Summed quality of the read's mismatching bases under its current
    alignment.

    Deliberate divergence: the reference's sumMismatchQuality (:425-430) zips
    the read against its MD-derived reference *positionally, ignoring the
    cigar*, so for a deletion-spanning read every base after the deletion is
    compared against the wrong reference column and counted as a mismatch.
    That inflates the "original" score, makes every deletion-spanning read
    look improvable, and hands out spurious mapq+10 bumps the GATK golden
    file does not have.  We walk the cigar and count only MD-recorded
    mismatches — which makes read1/3/5 of the golden fixture stay untouched,
    matching GATK.
    """
    q = 0
    read_pos = 0
    ref_pos = read.start
    for length, op in read.cigar:
        if op in "M=X":
            for i in range(length):
                if read.md.mismatched_base(ref_pos + i) is not None:
                    q += read.quals[read_pos + i]
            read_pos += length
            ref_pos += length
        elif op in "IS":
            read_pos += length
        elif op in "DN":
            ref_pos += length
    return q


def _reference_from_reads(reads: List[_Read]) -> Tuple[str, int, int]:
    """getReferenceFromReads (:147-167): stitch the target's reference from
    the reads' MD tags."""
    spans = sorted(((r.md.get_reference(r.seq, r.cigar, r.start),
                     r.start, r.end()) for r in reads if r.md is not None),
                   key=lambda t: t[1])
    ref, ref_start, ref_end = spans[0][0], spans[0][1], spans[0][2]
    for seq, s, e in spans[1:]:
        if e < ref_end:
            continue
        if ref_end >= s:
            ref = ref + seq[ref_end - s:]
            ref_end = e
        else:
            raise ValueError(f"reference gap at {ref_end} before {s}")
    return ref, ref_start, ref_end


def _rewrite_read(read: _Read, cons: Consensus, ref: str, ref_start: int,
                  remap: int) -> Optional[_Read]:
    """GATK-style start/cigar/MD rewrite for an accepted remapping.

    Returns None for degenerate placements (read only partially overlaps an
    insertion, or would run past the stitched reference) — the caller keeps
    the original alignment.
    """
    rl = len(read.seq)
    indel_off = cons.start - ref_start       # indel point in consensus coords
    if cons.is_insertion:
        ilen = len(cons.bases)
        m1 = indel_off - remap
        if 0 < m1 and m1 + ilen < rl:
            new_start = ref_start + remap
            cigar = [(m1, "M"), (ilen, "I"), (rl - m1 - ilen, "M")]
        elif remap >= indel_off + ilen:       # entirely after the insertion
            new_start = ref_start + remap - ilen
            cigar = [(rl, "M")]
        elif m1 >= rl:                        # entirely before the insertion
            new_start = ref_start + remap
            cigar = [(rl, "M")]
        else:                                 # partial overlap: unplaceable
            return None
    else:
        dlen = cons.end - cons.start
        m1 = indel_off - remap
        if 0 < m1 < rl:
            new_start = ref_start + remap
            cigar = [(m1, "M"), (dlen, "D"), (rl - m1, "M")]
        elif remap >= indel_off:              # entirely after the deletion
            new_start = ref_start + remap + dlen
            cigar = [(rl, "M")]
        else:
            new_start = ref_start + remap
            cigar = [(rl, "M")]
    # the rewrite must stay within the stitched reference
    ref_consumed = sum(l for l, op in cigar if op in "MDN=X")
    if new_start - ref_start + ref_consumed > len(ref):
        return None
    new_md = MdTag.move_alignment(ref[new_start - ref_start:], read.seq,
                                  cigar, new_start)
    return _Read(read.row, read.seq, read.quals, new_start, read.mapq + 10,
                 cigar, new_md, str(new_md))


@dataclass
class _SweepJob:
    """One (target group, consensus) sweep: packed device inputs."""
    cons: Consensus
    cons_u8: np.ndarray   # [CL] padded
    cons_len: int
    shape: Tuple[int, int, int]   # (R, L, CL) padded bucket


@dataclass
class _GroupState:
    """Host-side state of one target group between prepare and finish."""
    reads_to_clean: List[_Read]
    ref: str
    ref_start: int
    original_quals: List[int]
    total_pre: int
    reads_u8: np.ndarray   # [R, L] padded
    quals_arr: np.ndarray  # [R, L]
    lens: np.ndarray       # [R]
    jobs: List[_SweepJob]


def _prepare_group(reads: List[_Read]) -> Optional[_GroupState]:
    """findConsensus (:184-228) + packing; no device work."""
    reads_to_clean: List[_Read] = []
    consensuses: List[Consensus] = []
    for r in reads:
        cigar = r.cigar
        md = r.md
        if md is None:
            continue
        if num_alignment_blocks(cigar) == 2:
            new_cigar = left_align_indel(r.seq, cigar, md)
            if new_cigar != cigar:
                ref = md.get_reference(r.seq, cigar, r.start)
                md = MdTag.move_alignment(ref, r.seq, new_cigar, r.start)
                cigar = new_cigar
        if md.has_mismatches():
            md_str = r.md_str if md is r.md else str(md)
            cleaned = _Read(r.row, r.seq, r.quals, r.start, r.mapq, cigar,
                            md, md_str)
            reads_to_clean.append(cleaned)
            c = generate_alternate_consensus(r.seq, r.start, cigar)
            if c is not None and c not in consensuses:
                consensuses.append(c)
    if not reads_to_clean or not consensuses:
        return None

    try:
        ref, ref_start, ref_end = _reference_from_reads(reads)
    except ValueError:
        return None  # reference gap: leave the group unrealigned

    original_quals = [_sum_mismatch_quality(r) for r in reads_to_clean]

    # R and L pad to the canonical geometric rung ladder (packing.
    # shape_rung — the executor's row_bucket_ladder recurrence) so XLA
    # compilations amortize across the many differently-sized groups, many
    # groups share one batched sweep, and the whole run's sweep shape set
    # stays bounded by the ladder (the cross-bin batcher in
    # parallel/realign_exec.py buckets jobs from every in-flight bin by
    # exactly these rungs)
    R = shape_rung(len(reads_to_clean), 32)
    L = shape_rung(max(len(r.seq) for r in reads_to_clean), 32)
    reads_u8 = np.zeros((R, L), np.uint8)
    quals_arr = np.zeros((R, L), np.int32)
    lens = np.zeros(R, np.int32)
    for i, r in enumerate(reads_to_clean):
        b = r.seq.encode()
        reads_u8[i, :len(b)] = np.frombuffer(b, np.uint8)
        quals_arr[i, :len(r.quals)] = r.quals
        lens[i] = len(b)

    jobs: List[_SweepJob] = []
    for cons in consensuses:
        try:
            cons_seq = cons.insert_into_reference(ref, ref_start, ref_end)
        except ValueError:
            continue
        CL = shape_rung(max(len(cons_seq), L + 1), 64)
        cons_u8 = np.zeros(CL, np.uint8)
        cb = cons_seq.encode()
        cons_u8[:len(cb)] = np.frombuffer(cb, np.uint8)
        jobs.append(_SweepJob(cons, cons_u8, len(cons_seq), (R, L, CL)))
    if not jobs:
        return None
    return _GroupState(reads_to_clean, ref, ref_start, original_quals,
                       sum(original_quals), reads_u8, quals_arr, lens, jobs)


def _finish_group(state: _GroupState,
                  results: List[Tuple[np.ndarray, np.ndarray]]
                  ) -> Dict[int, _Read]:
    """Pick the best consensus, apply the LOD gate, rewrite reads
    (realignTargetGroup :296-364)."""
    n = len(state.reads_to_clean)
    orig = np.asarray(state.original_quals)
    best = None  # (total, consensus, per-read offsets)
    for job, (q, o) in zip(state.jobs, results):
        q = np.asarray(q)[:n]
        o = np.asarray(o)[:n]
        # fall back to the original alignment when the sweep cannot improve
        use = q < orig
        quals_final = np.where(use, q, orig)
        offsets_final = np.where(use, o, -1)
        total = int(quals_final.sum())
        if best is None or total < best[0]:
            best = (total, job.cons, offsets_final)

    total_best, cons, offsets = best
    if (state.total_pre - total_best) / 10.0 <= LOD_THRESHOLD:
        return {}

    out: Dict[int, _Read] = {}
    for r, off in zip(state.reads_to_clean, offsets):
        rewritten = _rewrite_read(r, cons, state.ref, state.ref_start,
                                  int(off)) if off >= 0 else None
        # unplaceable rewrites keep the (left-normalized) original alignment
        out[r.row] = rewritten if rewritten is not None else r
    return out


#: cap on per-dispatch device workspace BYTES for the batched sweep; the
#: dominant operands are the quality-weighted one-hot filters [G, R, L, 35]
#: f32, the one-hot consensus [G, CL+L, 35] f32 and the [G, R, CL+1] scores
_SWEEP_BATCH_BUDGET = 256 << 20

#: tests flip this to exercise the vmapped path on the CPU backend
_BATCH_ON_CPU = False

#: groups prepared ahead of the sweep; bounds host RSS at genome scale
#: while keeping shape buckets full enough to batch well
_GROUP_SLAB = 4096


def _sweep_g_max(R: int, L: int, CL: int) -> int:
    """Jobs per dispatch (a power of two, so padded chunk shapes repeat).

    On accelerators, batching amortizes dispatch latency (over the dev
    tunnel each dispatch is a network round trip) and feeds the MXU full
    tiles.  On the CPU backend the measured optimum is the opposite —
    per-job dispatches beat every batched configuration (XLA:CPU's batched
    conv is memory-bound on the one-hot intermediates: 1000 synthetic
    targets realign in 4.9 s per-job vs 7-11 s batched) — so CPU runs go
    one job at a time unless a test forces batching."""
    if jax.default_backend() == "cpu" and not _BATCH_ON_CPU:
        return 1
    per_job = 4 * (R * L * _N_BASE_CLASSES + (CL + L) * _N_BASE_CLASSES +
                   R * (CL + 1))
    g = max(1, _SWEEP_BATCH_BUDGET // per_job)
    return 1 << (g.bit_length() - 1)


def sweep_dispatch(pairs: List[Tuple[_GroupState, _SweepJob]],
                   donate: bool = False):
    """One device dispatch over same-shape (group, consensus) jobs.

    ``pairs`` share ``job.shape == (R, L, CL)``.  Returns ``(qs, os_)``
    DEVICE arrays with leading axis ``G >= len(pairs)`` — G pads to a
    power of two so chunk shapes repeat across dispatches, and padded
    lanes REPLICATE LANE 0 (they used to sweep a garbage consensus of
    dummy length L+1: wasted MXU work that could poison a result if lane
    indexing ever drifted; a replica computes something already being
    computed and is discarded the same way).  Lanes are vmapped
    independently, so each job's result is identical whatever else shares
    the batch — the property the cross-bin batcher
    (parallel/realign_exec.py) leans on for byte-identical scheduling.
    """
    R, L, CL = pairs[0][1].shape
    if len(pairs) == 1:
        st, job = pairs[0]
        args = (jnp.asarray(st.reads_u8), jnp.asarray(st.quals_arr),
                jnp.asarray(st.lens), jnp.asarray(job.cons_u8),
                jnp.int32(job.cons_len))
        if donate and _sweep_backend() == "conv":
            q, o = _sweep_conv_donating()(*args)
        else:
            q, o = _sweep(*args)
        return q[None], o[None]
    G = 1 << (len(pairs) - 1).bit_length()
    reads_b = np.zeros((G, R, L), np.uint8)
    quals_b = np.zeros((G, R, L), np.int32)
    lens_b = np.zeros((G, R), np.int32)
    cons_b = np.zeros((G, CL), np.uint8)
    clen_b = np.zeros(G, np.int32)
    for g, (st, job) in enumerate(pairs):
        reads_b[g] = st.reads_u8
        quals_b[g] = st.quals_arr
        lens_b[g] = st.lens
        cons_b[g] = job.cons_u8
        clen_b[g] = job.cons_len
    for arr in (reads_b, quals_b, lens_b, cons_b, clen_b):
        arr[len(pairs):] = arr[0]
    return _sweep_many(reads_b, quals_b, lens_b, cons_b, clen_b,
                       donate=donate)


def _sweep_groups(states: List[_GroupState],
                  donate: bool = False) -> List[Dict[int, _Read]]:
    """Sweep every (group, consensus) job, bucketed by padded shape so one
    vmapped dispatch covers many targets (VERDICT r1 #7: the per-target
    Python loop + per-consensus dispatch never scaled past fixture groups).
    """
    buckets: Dict[Tuple[int, int, int], List[Tuple[int, int]]] = {}
    for si, st in enumerate(states):
        for ji, job in enumerate(st.jobs):
            buckets.setdefault(job.shape, []).append((si, ji))

    results: Dict[Tuple[int, int], Tuple[np.ndarray, np.ndarray]] = {}
    for (R, L, CL), members in buckets.items():
        # chunk so the workspace stays under budget; G pads to a power of
        # two to bound the number of distinct compilations per (R, L, CL)
        g_max = _sweep_g_max(R, L, CL)
        for lo in range(0, len(members), g_max):
            chunk = members[lo:lo + g_max]
            q, o = sweep_dispatch(
                [(states[si], states[si].jobs[ji]) for si, ji in chunk],
                donate=donate)
            qs, os_ = np.asarray(q), np.asarray(o)
            for g, (si, ji) in enumerate(chunk):
                results[(si, ji)] = (qs[g], os_[g])

    out: List[Dict[int, _Read]] = []
    for si, st in enumerate(states):
        out.append(_finish_group(
            st, [results[(si, ji)] for ji in range(len(st.jobs))]))
    return out


@dataclass
class _PrepContext:
    """Host-side realignment context for one table: the target mapping
    plus the packed columns group construction reads from."""
    table: pa.Table
    batch: ReadBatch
    start: np.ndarray       # int64 [n] per-row alignment start
    in_target: np.ndarray   # global row indices inside any target
    sub_tgt: np.ndarray     # target id per in_target row

    def groups(self):
        """Yield per-target ``_Read`` lists, built columnar.

        The per-read Python of the old path ([ord(c) - 33 ...] over every
        qual string, a regex parse per cigar) is gone: quals slice out of
        the packed ``ReadBatch.quals`` plane, cigars come from the packed
        ``cigar_ops``/``cigar_lens`` columns, and mapq/start are the
        batch's int columns — prep cost scales with the columns, not
        reads x Python.  MD tags still parse per read (a genuine FSM),
        but one vectorized regex pass gates whole groups first: a group
        with no mismatching read can never produce ``reads_to_clean``
        (consensuses only come from mismatching reads), so skipping it
        before any ``MdTag.parse`` is output-identical.
        """
        import pyarrow.compute as pc

        rows = self.in_target
        sub = self.table.select(
            ["sequence", "cigar", "mismatchingPositions", "qual"]
        ).take(pa.array(rows))
        seqs = sub.column("sequence").to_pylist()
        mds = sub.column("mismatchingPositions").to_pylist()
        cig_null = pc.is_null(sub.column("cigar")).combine_chunks() \
            .to_numpy(zero_copy_only=False)
        qlens = pc.fill_null(pc.binary_length(sub.column("qual")), 0) \
            .combine_chunks().to_numpy(zero_copy_only=False) \
            .astype(np.int64)
        # a mismatch is a letter directly after a digit run (deleted
        # bases follow '^'), so one regex pass marks mismatching reads
        has_mm = pc.fill_null(pc.match_substring_regex(
            sub.column("mismatchingPositions"), "[0-9][A-Za-z]"), False) \
            .combine_chunks().to_numpy(zero_copy_only=False)
        quals8 = self.batch.quals
        ops8 = self.batch.cigar_ops
        lens32 = self.batch.cigar_lens
        nops = self.batch.n_cigar
        mapq = np.maximum(np.asarray(self.batch.mapq), 0)
        start = self.start

        # group rows by target via one stable argsort + slice bounds — a
        # per-target masked scan would be O(targets x reads) at genome
        # scale
        order = np.argsort(self.sub_tgt, kind="stable")
        sorted_t = self.sub_tgt[order]
        bounds = np.flatnonzero(
            np.r_[True, sorted_t[1:] != sorted_t[:-1], True])
        for bi in range(len(bounds) - 1):
            sub_rows = order[bounds[bi]:bounds[bi + 1]]
            if not has_mm[sub_rows].any():
                continue
            group: List[_Read] = []
            for i in sub_rows:
                i = int(i)
                row = int(rows[i])
                seq = seqs[i]
                if seq is None or cig_null[i]:
                    continue
                md_str = mds[i]
                md = MdTag.parse(md_str, int(start[row])) \
                    if md_str is not None else None
                k = int(nops[row])
                cigar = [(int(lens32[row, j]), S.CIGAR_OPS[ops8[row, j]])
                         for j in range(k)]
                group.append(_Read(
                    row, seq, quals8[row, :qlens[i]].astype(np.int32),
                    int(start[row]), int(mapq[row]), cigar, md, md_str))
            if group:
                yield group


def _prep_context(table: pa.Table,
                  batch: Optional[ReadBatch]) -> Optional[_PrepContext]:
    """Targets + read→target mapping; ``None`` when nothing can realign
    (realign_indels then returns the table unchanged)."""
    from ..ops.pileup import reads_to_pileups
    n = table.num_rows
    if batch is None or batch.quals is None or batch.cigar_ops is None:
        # group prep reads the packed qual/cigar planes — re-pack when the
        # caller's batch was projected without them
        batch = pack_reads(table)

    pileups = reads_to_pileups(table, batch)
    targets = find_targets(pileups)
    if len(targets) == 0:
        return None

    from ..ops import cigar as C
    flags = np.asarray(batch.flags[:n], np.int64)
    refid = np.asarray(batch.refid[:n], np.int64)
    start = np.asarray(batch.start[:n], np.int64)
    end = np.asarray(C.read_end(jnp.asarray(batch.start),
                                jnp.asarray(batch.cigar_ops),
                                jnp.asarray(batch.cigar_lens)))[:n]
    mapped = (flags & S.FLAG_UNMAPPED) == 0
    tgt = map_reads_to_targets(refid, start, end.astype(np.int64), mapped,
                               targets)
    # only rows inside targets are touched — gather just those
    in_target = np.flatnonzero(tgt >= 0)
    if len(in_target) == 0:
        return None
    return _PrepContext(table, batch, start, in_target, tgt[in_target])


@dataclass
class RealignWork:
    """One table's host-prepared realignment: everything up to — but not
    including — the device sweeps.  ``parallel/realign_exec.py`` schedules
    the sweep jobs of many in-flight bins together through this seam;
    :func:`realign_indels` drives the same states serially."""
    table: pa.Table
    states: List[_GroupState]

    @property
    def n_jobs(self) -> int:
        return sum(len(st.jobs) for st in self.states)


def plan_realign(table: pa.Table, batch: Optional[ReadBatch] = None
                 ) -> Optional[RealignWork]:
    """Host-side phases of :func:`realign_indels` (pileups, targets,
    columnar group prep, packed states); ``None`` when the table has
    nothing to realign."""
    ctx = _prep_context(table, batch)
    if ctx is None:
        return None
    states = []
    for group in ctx.groups():
        st = _prepare_group(group)
        if st is not None:
            states.append(st)
    return RealignWork(table, states) if states else None


def finish_realign(work: RealignWork,
                   results: List[List[Tuple[np.ndarray, np.ndarray]]]
                   ) -> pa.Table:
    """Apply sweep results (one ``[(q, o)]`` list per state, job order)
    to the planned table: LOD gate, rewrites, vectorized write-back."""
    updates: Dict[int, _Read] = {}
    for st, res in zip(work.states, results):
        updates.update(_finish_group(st, res))
    return apply_updates(work.table, updates)


def apply_updates(table: pa.Table, updates: Dict[int, _Read]) -> pa.Table:
    """Scatter accepted rewrites into the table.

    O(changed) host work plus one Arrow ``take`` per column — replacing
    the old four ``.tolist()`` + whole-table Python loops, which scaled
    O(total rows) even when a handful of reads moved.
    """
    if not updates:
        return table
    rows = np.sort(np.fromiter(updates, np.int64, len(updates)))
    reads = [updates[int(r)] for r in rows]
    n = table.num_rows

    def set_int(t, name, vals, typ):
        col = column_int64(t, name)          # nulls -> the old -1 sentinel
        col[rows] = vals
        arr = pa.array(col, typ, mask=(col == -1))
        return t.set_column(t.column_names.index(name), name, arr)

    def set_str(t, name, new_vals):
        ca = t.column(name).combine_chunks()
        chunks = ca.chunks if isinstance(ca, pa.ChunkedArray) else [ca]
        merged = pa.chunked_array(
            [*chunks, pa.array(new_vals, type=ca.type)], type=ca.type)
        idx = np.arange(n, dtype=np.int64)
        idx[rows] = n + np.arange(len(rows), dtype=np.int64)
        return t.set_column(t.column_names.index(name), name,
                            merged.take(pa.array(idx)))

    table = set_int(table, "start",
                    np.fromiter((r.start for r in reads), np.int64,
                                len(reads)), pa.int64())
    table = set_int(table, "mapq",
                    np.fromiter((r.mapq for r in reads), np.int64,
                                len(reads)), pa.int32())
    table = set_str(table, "cigar",
                    [cigar_to_string(r.cigar) for r in reads])
    table = set_str(table, "mismatchingPositions",
                    [r.md_str for r in reads])
    return table


def realign_indels(table: pa.Table, batch: Optional[ReadBatch] = None
                   ) -> pa.Table:
    """adamRealignIndels (AdamRDDFunctions.scala:109-112)."""
    ctx = _prep_context(table, batch)
    if ctx is None:
        return table

    # prepare -> sweep -> finish in slabs of groups, so host memory stays
    # O(slab) — a whole-genome run has ~1M targets and holding every
    # padded _GroupState at once would cost tens of GB
    updates: Dict[int, _Read] = {}
    states: List[_GroupState] = []

    def flush():
        for upd in _sweep_groups(states):
            updates.update(upd)
        states.clear()

    for group in ctx.groups():
        state = _prepare_group(group)
        if state is not None:
            states.append(state)
        if len(states) >= _GROUP_SLAB:
            flush()
    flush()

    if not updates:
        return table
    return apply_updates(table, updates)
