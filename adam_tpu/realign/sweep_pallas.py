"""Pallas TPU kernel for the consensus sweep.

The realignment hot loop (sweepReadOverReferenceForQuality,
RealignIndels.scala:376-394) scores every read at every admissible offset of
a candidate consensus.  The jnp formulation in realigner.py materializes the
[R, CL, L] mismatch tensor in HBM — fine for test-sized targets, ruinous for
a 3 kb target (maxIndelSize) with hundreds of reads.  This kernel keeps the
[R, L] read block and the consensus resident in VMEM and streams offsets
with a fori_loop, carrying only the running (best score, best offset) pair:
HBM traffic drops from O(R*CL*L) to O(R*L + CL), and each offset step is one
wide VPU compare+FMA over the read block.

Shapes are padded to TPU tile boundaries (R to 8 sublanes, L to 128 lanes,
int32 operands).  Tie-breaking matches the jnp path: strict improvement
keeps the lowest admissible offset.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..packing import _round_up

BIG = 1 << 30


def _sweep_body(reads_ref, w_ref, lens_ref, cons_ref, conslen_ref,
                bestq_ref, besto_ref, *, n_offsets: int):
    reads = reads_ref[:].astype(jnp.int32)          # [R, L]
    w = w_ref[:]                                    # [R, L] int32, pre-masked
    lens = lens_ref[:]                              # [R, 1]
    cons = cons_ref[:]                              # [1, CLpad]
    cons_len = conslen_ref[0]
    R, L = reads.shape

    CLp = cons.shape[1]

    def body(o, carry):
        # Mosaic cannot dynamic_slice along lanes, so the consensus is
        # carried and rotated left one lane per offset: its first L lanes
        # are always the window starting at o (CLp >= CL + L keeps the
        # wraparound junk out of reach).
        bq, bo, cons_c = carry
        win = cons_c[:, :L]                                      # [1, L]
        mm = (reads != win).astype(jnp.int32)
        s = jnp.sum(mm * w, axis=1, keepdims=True)               # [R, 1]
        # admissible: 0 <= o < cons_len - read_len  (RealignIndels.scala:381)
        valid = o < (cons_len - lens)
        s = jnp.where(valid, s, BIG)
        better = s < bq
        return (jnp.where(better, s, bq), jnp.where(better, o, bo),
                pltpu.roll(cons_c, shift=CLp - 1, axis=1))

    init = (jnp.full((R, 1), BIG, jnp.int32), jnp.zeros((R, 1), jnp.int32),
            cons)
    bq, bo, _ = jax.lax.fori_loop(0, n_offsets, body, init)
    bestq_ref[:] = bq
    besto_ref[:] = bo


@functools.partial(jax.jit, static_argnames=("interpret",))
def _sweep_padded(reads_u8, w, read_lens, cons_u8, cons_len, interpret=False):
    R, L = reads_u8.shape
    CL = cons_u8.shape[1]
    kernel = functools.partial(_sweep_body, n_offsets=CL - L)
    bq, bo = pl.pallas_call(
        kernel,
        out_shape=(jax.ShapeDtypeStruct((R, 1), jnp.int32),
                   jax.ShapeDtypeStruct((R, 1), jnp.int32)),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM),
                  pl.BlockSpec(memory_space=pltpu.VMEM),
                  pl.BlockSpec(memory_space=pltpu.VMEM),
                  pl.BlockSpec(memory_space=pltpu.VMEM),
                  pl.BlockSpec(memory_space=pltpu.SMEM)],
        out_specs=(pl.BlockSpec(memory_space=pltpu.VMEM),
                   pl.BlockSpec(memory_space=pltpu.VMEM)),
        interpret=interpret,
    )(reads_u8.astype(jnp.int32), w, read_lens, cons_u8.astype(jnp.int32),
      cons_len)
    return bq[:, 0], bo[:, 0]


def sweep_pallas(reads_u8, quals, read_lens, cons_u8, cons_len, *,
                 interpret: bool = False):
    """Drop-in equivalent of realigner._sweep_kernel, Pallas-backed.

    reads_u8 [R, L], quals [R, L], read_lens [R], cons_u8 [CL], cons_len
    scalar.  Returns (best_quality [R], best_offset [R]).  ``interpret=True``
    runs the kernel in the Pallas interpreter (any backend) — the CI path on
    the CPU mesh.
    """
    R, L = reads_u8.shape
    CL = int(cons_u8.shape[0])
    Rp, Lp = _round_up(max(R, 8), 8), _round_up(max(L, 128), 128)
    # consensus pad: room for the last dynamic_slice window to stay in-bounds
    CLp = _round_up(max(CL, Lp) + Lp, 128)

    reads_p = jnp.zeros((Rp, Lp), jnp.int32).at[:R, :L].set(
        reads_u8.astype(jnp.int32))
    # weights: quality inside the read, 0 in padding (padding never scores)
    w = jnp.zeros((Rp, Lp), jnp.int32).at[:R, :L].set(quals.astype(jnp.int32))
    mask = (jnp.arange(Lp)[None, :] <
            jnp.zeros((Rp,), jnp.int32).at[:R].set(read_lens)[:, None])
    w = jnp.where(mask, w, 0)
    # padded rows: read_len = CL so no offset is admissible -> stay at BIG
    lens_p = jnp.full((Rp, 1), CL, jnp.int32).at[:R, 0].set(read_lens)
    cons_p = jnp.zeros((1, CLp), jnp.int32).at[0, :CL].set(
        cons_u8.astype(jnp.int32))

    bq, bo = _sweep_padded(reads_p, w, lens_p, cons_p,
                           jnp.asarray([cons_len], jnp.int32),
                           interpret=interpret)
    return bq[:R], bo[:R]


# ---------------------------------------------------------------------------
# ragged sweep: rows from MANY jobs in one block, per-row consensus
# ---------------------------------------------------------------------------

def _sweep_body_ragged(reads_ref, w_ref, lens_ref, cons_ref, conslen_ref,
                       bestq_ref, besto_ref, *, n_offsets: int):
    """The roll-sweep of :func:`_sweep_body` with a PER-ROW consensus:
    rows belonging to different (group, consensus) jobs share one block
    (concatenated along R at true counts — no per-job R rung), each row
    scoring against its own job's consensus lane.  L pads once to the
    dispatch-wide lane rung instead of per-job, so the batcher buckets
    only on the (CL, G) rungs (docs/ARCHITECTURE.md §6g)."""
    reads = reads_ref[:].astype(jnp.int32)          # [R, L]
    w = w_ref[:]                                    # [R, L], pre-masked
    lens = lens_ref[:]                              # [R, 1]
    conslen = conslen_ref[:]                        # [R, 1]
    cons = cons_ref[:].astype(jnp.int32)            # [R, CLp]
    R, L = reads.shape
    CLp = cons.shape[1]

    def body(o, carry):
        bq, bo, cons_c = carry
        win = cons_c[:, :L]
        mm = (reads != win).astype(jnp.int32)
        s = jnp.sum(mm * w, axis=1, keepdims=True)
        valid = o < (conslen - lens)
        s = jnp.where(valid, s, BIG)
        better = s < bq
        return (jnp.where(better, s, bq), jnp.where(better, o, bo),
                pltpu.roll(cons_c, shift=CLp - 1, axis=1))

    init = (jnp.full((R, 1), BIG, jnp.int32), jnp.zeros((R, 1), jnp.int32),
            cons)
    bq, bo, _ = jax.lax.fori_loop(0, n_offsets, body, init)
    bestq_ref[:] = bq
    besto_ref[:] = bo


@functools.partial(jax.jit, static_argnames=("interpret",))
def _sweep_ragged_call(reads, w, lens, cons_rows, conslen, interpret=False):
    R, L = reads.shape
    CLp = cons_rows.shape[1]
    kernel = functools.partial(_sweep_body_ragged, n_offsets=CLp - L)
    bq, bo = pl.pallas_call(
        kernel,
        out_shape=(jax.ShapeDtypeStruct((R, 1), jnp.int32),
                   jax.ShapeDtypeStruct((R, 1), jnp.int32)),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)] * 5,
        out_specs=(pl.BlockSpec(memory_space=pltpu.VMEM),
                   pl.BlockSpec(memory_space=pltpu.VMEM)),
        interpret=interpret,
    )(reads, w, lens, cons_rows, conslen)
    return bq[:, 0], bo[:, 0]


def sweep_pallas_ragged(reads_rows, w_rows, lens_rows, cons_rows,
                        conslen_rows, *, interpret: bool = False):
    """Ragged consensus sweep, Pallas-backed: ``reads_rows``/``w_rows``
    [R, L] concatenate every job's TRUE rows (weights pre-masked past
    each read's length), ``cons_rows`` [R, CLp] carries each row's own
    consensus, ``lens_rows``/``conslen_rows`` [R] the true lengths.
    Returns (best_quality [R], best_offset [R]) — bit-identical to the
    XLA segment-sum form (realigner._sweep_ragged_xla)."""
    R, L = reads_rows.shape
    CLin = int(cons_rows.shape[1])
    Rp = _round_up(max(R, 8), 8)
    Lp = _round_up(max(L, 128), 128)
    CLp = _round_up(max(CLin, Lp) + Lp, 128)
    reads_p = jnp.zeros((Rp, Lp), jnp.int32).at[:R, :L].set(
        jnp.asarray(reads_rows, jnp.int32))
    w_p = jnp.zeros((Rp, Lp), jnp.int32).at[:R, :L].set(
        jnp.asarray(w_rows, jnp.int32))
    cons_p = jnp.zeros((Rp, CLp), jnp.int32).at[:R, :CLin].set(
        jnp.asarray(cons_rows, jnp.int32))
    # pad rows: no admissible offset (cons_len 0, read_len CLp)
    lens_p = jnp.full((Rp, 1), CLp, jnp.int32).at[:R, 0].set(
        jnp.asarray(lens_rows, jnp.int32))
    conslen_p = jnp.zeros((Rp, 1), jnp.int32).at[:R, 0].set(
        jnp.asarray(conslen_rows, jnp.int32))
    bq, bo = _sweep_ragged_call(reads_p, w_p, lens_p, cons_p, conslen_p,
                                interpret=interpret)
    return bq[:R], bo[:R]


@functools.partial(jax.jit, static_argnames=("interpret",))
def _sweep_padded_batch(reads, w, lens, cons, cons_len, interpret=False):
    return jax.vmap(
        lambda r, wq, ln, c, cl: _sweep_padded(r, wq, ln, c, cl,
                                               interpret=interpret)
    )(reads, w, lens, cons, cons_len)


def sweep_pallas_batch(reads_u8, quals, read_lens, cons_u8, cons_len, *,
                       interpret: bool = False):
    """Batched form of :func:`sweep_pallas` over a leading G axis — the
    pallas counterpart of realigner._sweep_conv_many (one vmapped dispatch
    per padded-shape bucket).  reads_u8 [G, R, L], quals [G, R, L],
    read_lens [G, R], cons_u8 [G, CL], cons_len [G]."""
    G, R, L = reads_u8.shape
    CL = int(cons_u8.shape[1])
    Rp, Lp = _round_up(max(R, 8), 8), _round_up(max(L, 128), 128)
    CLp = _round_up(max(CL, Lp) + Lp, 128)

    reads_p = jnp.zeros((G, Rp, Lp), jnp.int32).at[:, :R, :L].set(
        reads_u8.astype(jnp.int32))
    w = jnp.zeros((G, Rp, Lp), jnp.int32).at[:, :R, :L].set(
        quals.astype(jnp.int32))
    lens_full = jnp.zeros((G, Rp), jnp.int32).at[:, :R].set(read_lens)
    mask = jnp.arange(Lp)[None, None, :] < lens_full[:, :, None]
    w = jnp.where(mask, w, 0)
    lens_p = jnp.full((G, Rp, 1), CL, jnp.int32).at[:, :R, 0].set(read_lens)
    cons_p = jnp.zeros((G, 1, CLp), jnp.int32).at[:, 0, :CL].set(
        cons_u8.astype(jnp.int32))
    bq, bo = _sweep_padded_batch(
        reads_p, w, lens_p, cons_p,
        jnp.asarray(cons_len, jnp.int32).reshape(G, 1),
        interpret=interpret)
    return bq[:, :R], bo[:, :R]
