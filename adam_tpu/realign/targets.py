"""Indel realignment target discovery.

Re-designs ``algorithms/realignmenttarget/`` (RealignmentTargetFinder:27-101,
IndelRealignmentTarget:251-437): the reference converts reads to pileups,
groups by position into rods, builds per-position targets, sorts, collects to
the driver and tail-recursively merges overlapping targets.  Here the whole
thing is vectorized over the pileup table: per-position evidence sums via
sorted segment reductions, then a linear interval merge.

Evidence rules (IndelRealignmentTarget.apply :262-333):
  * indel evidence = any pileup with rangeOffset set (insertions, deletions
    and — faithfully to the reference — soft clips);
  * SNP evidence = aligned mismatch pileups whose summed quality is >= 0.15
    of the summed match quality (mismatchThreshold :254), or any mismatch
    when there are no matches;
  * a position's target spans [min readStart, max readEnd) of the
    contributing reads; overlapping targets merge.

The per-target indel/SNP sets only ever feed the merged read range, so the
final representation is just an [T, 2] interval array — which is also what
the read->target assignment (binary search) wants.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np
import pyarrow as pa

from ..packing import ReadBatch, column_int64

MISMATCH_THRESHOLD = 0.15  # IndelRealignmentTarget.scala:254
MAX_TARGET_SPREAD = 3000   # empty-target skew spread (RealignIndels.scala:77)


def find_targets(pileups: pa.Table) -> np.ndarray:
    """[T, 3] (referenceId, start, end) inclusive read-range intervals,
    sorted by (refid, start) and merged per contig."""
    n = pileups.num_rows
    if n == 0:
        return np.zeros((0, 3), np.int64)
    pos = column_int64(pileups, "position")
    refid = column_int64(pileups, "referenceId", 0)
    range_off = column_int64(pileups, "rangeOffset", -1)
    softclip = column_int64(pileups, "numSoftClipped", 0)
    qual = column_int64(pileups, "sangerQuality", 0)
    rstart = column_int64(pileups, "readStart", 0)
    rend = column_int64(pileups, "readEnd", 0)
    import pyarrow.compute as pc
    rb_col = pileups.column("readBase")
    read_base = pc.is_valid(rb_col).to_numpy(zero_copy_only=False)
    ref_base_eq = pc.fill_null(
        pc.equal(rb_col, pileups.column("referenceBase")),
        False).to_numpy(zero_copy_only=False)

    is_indel = range_off >= 0
    aligned = ~is_indel & (softclip == 0)
    is_match = aligned & ref_base_eq
    is_mismatch = aligned & read_base & ~ref_base_eq

    # per-(refid, position) evidence sums
    key = (refid << 34) | pos
    uniq, inv = np.unique(key, return_inverse=True)
    m = len(uniq)
    match_q = np.bincount(inv, weights=qual * is_match, minlength=m)
    mismatch_q = np.bincount(inv, weights=qual * is_mismatch, minlength=m)
    snp_ev = (mismatch_q > 0) & ((match_q == 0) |
                                 (mismatch_q / np.maximum(match_q, 1e-9) >=
                                  MISMATCH_THRESHOLD))

    # contributing pileups: indels always; mismatches when SNP evidence holds
    contrib = is_indel | (is_mismatch & snp_ev[inv])
    if not contrib.any():
        return np.zeros((0, 3), np.int64)
    c_inv = inv[contrib]
    big = np.int64(1) << 60
    t_start = np.full(m, big, np.int64)
    np.minimum.at(t_start, c_inv, rstart[contrib])
    t_end = np.full(m, -big, np.int64)
    np.maximum.at(t_end, c_inv, rend[contrib] - 1)
    t_ref = uniq >> 34  # recover refid from the position key
    keep = t_start < big
    t_ref, t_start, t_end = t_ref[keep], t_start[keep], t_end[keep]

    # sort by (refid, start) + merge per-contig overlapping inclusive
    # intervals (joinTargets :54-71; targets never span contigs)
    order = np.lexsort((t_start, t_ref))
    t_ref, t_start, t_end = t_ref[order], t_start[order], t_end[order]
    merged = []
    cr, cs, ce = int(t_ref[0]), int(t_start[0]), int(t_end[0])
    for r, s, e in zip(t_ref[1:], t_start[1:], t_end[1:]):
        if r == cr and s <= ce:  # same contig, inclusive ranges overlap
            ce = max(ce, int(e))
        else:
            merged.append((cr, cs, ce))
            cr, cs, ce = int(r), int(s), int(e)
    merged.append((cr, cs, ce))
    return np.array(merged, np.int64).reshape(-1, 3)


def map_reads_to_targets(refid: np.ndarray, start: np.ndarray,
                         end: np.ndarray, mapped: np.ndarray,
                         targets: np.ndarray) -> np.ndarray:
    """[N] target index per read, -1-ish for "no target".

    A read maps to the first target on its contig whose inclusive read range
    overlaps [start, end-1] (TargetOrdering.contains :79-88).  Unassigned
    reads get the reference's skew-spread empty key -1 - start/3000
    (RealignIndels.mapToTarget :77-80) so downstream grouping stays balanced.
    """
    out = -1 - (np.maximum(start, 0) // MAX_TARGET_SPREAD)
    if len(targets) == 0:
        return out.astype(np.int64)
    tr, ts, te = targets[:, 0], targets[:, 1], targets[:, 2]
    # encode (refid, pos) into one sortable key; targets are lexsorted so the
    # composite keys are sorted too
    shift = np.int64(1) << 34
    read_start_key = refid * shift + start
    read_end_key = refid * shift + (end - 1)
    t_start_key = tr * shift + ts
    t_end_key = tr * shift + te
    # first target with end key >= read start key; overlap iff also starts
    # before the read's end key (same-contig by key construction)
    idx = np.searchsorted(t_end_key, read_start_key)
    idx_c = np.minimum(idx, len(ts) - 1)
    overlaps = mapped & (idx < len(ts)) & \
        (t_start_key[idx_c] <= read_end_key) & \
        (t_end_key[idx_c] >= read_start_key) & (tr[idx_c] == refid)
    return np.where(overlaps, idx_c, out).astype(np.int64)
