"""Pallas TPU kernel for BQSR pass-1 counting.

Re-designs the hot loop of ``rdd/RecalibrateBaseQualities.scala:52-64`` /
``RecalTable.scala:23-215`` (per-base covariate -> count-table increment)
as a VMEM-resident one-hot-matmul sweep.

Why another backend (joining scatter / matmul / chain / host in
``recalibrate._count_impl``): on TPU, scatter-adds serialize on duplicate
indices and the XLA matmul formulation must materialize its one-hot
operands in HBM — ~4 KB of traffic per base (``[X, Q]`` + ``[X, C]`` bf16
round trips) against ~8 B of actual information.  This kernel:

  * packs the four covariate indices of a base into ONE int32 word in an
    XLA prologue (k:10 | cycle:10 | context:5 | qual:7 bits — ranges are
    asserted by :func:`fits`; quals arrive as int8 so 7 bits are exact),
    plus a 3-bit int8 weight byte: 5 B/base of HBM traffic total;
  * unpacks in VMEM, builds the one-hot indicator tiles in vector
    registers, and contracts them on the MXU with NT-form ``dot_general``
    (contraction over the lane axis — the attention-QK^T shape);
  * accumulates the [Q, cyc_bins + 128] obs/mm tables and the 256-bin
    qual histogram in revisited int32 output blocks across a sequential
    grid (cyc_bins = n_cycle lane-padded, e.g. 256 for 100 bp reads,
    384 for 128 bp).

Exactness: one-hot products are 0/1 bf16, each f32 block dot sums at most
``BLOCK_ELEMS`` ones (< 2^24), and blocks accumulate in int32 — so the
tables are bit-identical to the scatter oracle (differential-tested).

Column layout of the fused category axis: columns [0, cyc_bins) are the
cycle bins, [cyc_bins, cyc_bins+N_CONTEXT) the context bins.  qual_obs/qual_mm are NOT
separate outputs: every counted base lands in exactly one (clipped) cycle
bin, so the wrapper derives them as row sums of the cycle table.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ..packing import _round_up
from ..platform import pallas_tpu_compiler_params, shard_map
from .covariates import (MAX_REASONABLE_QSCORE, N_CONTEXT,
                         covariate_tensors)
from .recalibrate import STATE_MASKED, STATE_MISMATCH

#: elements (bases) swept per grid step; lane-aligned
BLOCK_ELEMS = 2048
#: context bins occupy one lane-tile after the cycle bins
CTX_COLS = 128

_K_BITS, _CYC_BITS, _CTX_BITS, _Q_BITS = 10, 10, 5, 7


def fits(n_qual_rg: int, n_cycle: int) -> bool:
    """Do the covariate ranges fit the packed-word bit budget?  (True for
    every real configuration: k < 1024 covers 15 read groups; cycle <
    1024 covers the 511-bp length bucket, i.e. every short-read input;
    context < 32 always; quals are int8 so 7 bits are exact.)"""
    return (n_qual_rg <= 1 << _K_BITS and n_cycle <= 1 << _CYC_BITS
            and N_CONTEXT <= 1 << _CTX_BITS)


@functools.partial(jax.jit, static_argnames=("n_qual_rg", "n_cycle"))
def _pack_words(bases, quals, read_len, flags, read_group, state, usable,
                n_qual_rg: int, n_cycle: int):
    """XLA prologue: covariates -> [n_blocks, 1, BLOCK_ELEMS] packed index
    and weight words (zero-weight padding past the real bases)."""
    cov = covariate_tensors(bases, quals, read_len, flags, read_group)
    counted = cov["in_window"] & usable[:, None] & (state != STATE_MASKED)
    mm = (state == STATE_MISMATCH) & counted
    windowed = cov["in_window"] & usable[:, None]
    k = jnp.clip(cov["qual_rg"], 0, n_qual_rg - 1)
    cyc = jnp.clip(cov["cycle_idx"], 0, n_cycle - 1)
    # int8 quals are <= 127, so the 7-bit field loses nothing (negative
    # pad values clip to 0, matching the scatter oracle's qhist clip)
    q = jnp.clip(quals.astype(jnp.int32), 0, (1 << _Q_BITS) - 1)

    word = (k | (cyc << _K_BITS) | (cov["context"] << (_K_BITS + _CYC_BITS))
            | (q << (_K_BITS + _CYC_BITS + _CTX_BITS)))
    wbits = (counted.astype(jnp.int8) | (mm.astype(jnp.int8) << 1)
             | (windowed.astype(jnp.int8) << 2))

    n_elems = word.size
    n_blocks = max(-(-n_elems // BLOCK_ELEMS), 1)
    pad = n_blocks * BLOCK_ELEMS - n_elems

    def blocked(a):
        return jnp.pad(a.reshape(-1), (0, pad)).reshape(
            n_blocks, 1, BLOCK_ELEMS)

    return blocked(word), blocked(wbits)


def _kernel(word_ref, wbits_ref, obs_ref, mm_ref, qh_ref, *,
            q_rows: int, cyc_bins: int, int8_mxu: bool = False):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        obs_ref[...] = jnp.zeros_like(obs_ref)
        mm_ref[...] = jnp.zeros_like(mm_ref)
        qh_ref[...] = jnp.zeros_like(qh_ref)

    word = word_ref[...]                    # [1, X] int32 rows
    wbits = wbits_ref[...].astype(jnp.int32)     # int8 on the wire
    k = word & ((1 << _K_BITS) - 1)
    cyc = (word >> _K_BITS) & ((1 << _CYC_BITS) - 1)
    ctx = (word >> (_K_BITS + _CYC_BITS)) & ((1 << _CTX_BITS) - 1)
    q = (word >> (_K_BITS + _CYC_BITS + _CTX_BITS)) & ((1 << _Q_BITS) - 1)
    # int8 one-hots double MXU throughput on v5e (394 int8 TOPS vs 197
    # bf16 TFLOPs) and products are exact integers either way; the race
    # decides whether Mosaic's int8 matmul path actually wins
    oh_t = jnp.int8 if int8_mxu else jnp.bfloat16
    acc_t = jnp.int32 if int8_mxu else jnp.float32
    w = (wbits & 1).astype(oh_t)
    wm = ((wbits >> 1) & 1).astype(oh_t)
    ww = ((wbits >> 2) & 1).astype(oh_t)

    X = word.shape[-1]
    # qual-rg one-hot: [q_rows, X], element lanes contract in the NT dots
    eq = (jax.lax.broadcasted_iota(jnp.int32, (q_rows, X), 0)
          == k).astype(oh_t)
    # fused cycle+context category one-hot: [cyc_bins + CTX_COLS, X]
    cat = jax.lax.broadcasted_iota(jnp.int32,
                                   (cyc_bins + CTX_COLS, X), 0)
    ohc = (((cat < cyc_bins) & (cat == cyc))
           | ((cat >= cyc_bins) & (cat - cyc_bins == ctx))
           ).astype(oh_t)
    nt = (((1,), (1,)), ((), ()))           # contract both lane axes
    obs_ref[...] += jax.lax.dot_general(
        eq * w, ohc, nt, preferred_element_type=acc_t
    ).astype(jnp.int32)
    mm_ref[...] += jax.lax.dot_general(
        eq * wm, ohc, nt, preferred_element_type=acc_t
    ).astype(jnp.int32)
    # 256-bin qual histogram of windowed bases: one [8, X] @ [256, X]^T dot
    ohq = (jax.lax.broadcasted_iota(jnp.int32, (256, X), 0)
           == q).astype(oh_t)
    ww8 = jnp.broadcast_to(ww, (8, X)) * \
        (jax.lax.broadcasted_iota(jnp.int32, (8, X), 0) == 0)
    qh_ref[...] += jax.lax.dot_general(
        ww8, ohq, nt, preferred_element_type=acc_t
    ).astype(jnp.int32)


@functools.partial(jax.jit,
                   static_argnames=("q_rows", "cyc_bins", "interpret",
                                    "int8_mxu"))
def _count_call(word3, wbits3, q_rows: int, cyc_bins: int,
                interpret: bool, int8_mxu: bool = False):
    from jax.experimental.pallas import tpu as pltpu

    n_blocks = word3.shape[0]
    cat_cols = cyc_bins + CTX_COLS
    spec = pl.BlockSpec((None, 1, BLOCK_ELEMS), lambda i: (i, 0, 0))
    acc = pl.BlockSpec((q_rows, cat_cols), lambda i: (0, 0))
    qh = pl.BlockSpec((8, 256), lambda i: (0, 0))
    return pl.pallas_call(
        functools.partial(_kernel, q_rows=q_rows, cyc_bins=cyc_bins,
                          int8_mxu=int8_mxu),
        grid=(n_blocks,),
        in_specs=[spec, spec],
        out_specs=(acc, acc, qh),
        out_shape=(jax.ShapeDtypeStruct((q_rows, cat_cols), jnp.int32),
                   jax.ShapeDtypeStruct((q_rows, cat_cols), jnp.int32),
                   jax.ShapeDtypeStruct((8, 256), jnp.int32)),
        compiler_params=pallas_tpu_compiler_params(
            dimension_semantics=("arbitrary",)),
        interpret=interpret,
    )(word3, wbits3)


def count_kernel_pallas(bases, quals, read_len, flags, read_group, state,
                        usable, n_qual_rg: int, n_cycle: int,
                        interpret: bool = False, int8_mxu: bool = False):
    """Drop-in for ``recalibrate._count_kernel`` (same 7-tensor contract):
    (qual_obs, qual_mm, cycle_obs, cycle_mm, ctx_obs, ctx_mm, qhist)."""
    assert fits(n_qual_rg, n_cycle), (n_qual_rg, n_cycle)
    word3, wbits3 = _pack_words(bases, quals, read_len, flags, read_group,
                                state, usable, n_qual_rg=n_qual_rg,
                                n_cycle=n_cycle)
    q_rows = _round_up(n_qual_rg, 8)
    cyc_bins = _round_up(n_cycle, 128)
    obs, mm, qh = _count_call(word3, wbits3, q_rows=q_rows,
                              cyc_bins=cyc_bins, interpret=interpret,
                              int8_mxu=int8_mxu)
    return _unpack_tables(obs, mm, qh, n_qual_rg=n_qual_rg,
                          n_cycle=n_cycle, cyc_bins=cyc_bins)


@functools.partial(jax.jit,
                   static_argnames=("n_qual_rg", "n_cycle", "cyc_bins"))
def _unpack_tables(obs, mm, qh, n_qual_rg: int, n_cycle: int,
                   cyc_bins: int):
    cycle_obs = obs[:n_qual_rg, :n_cycle]
    cycle_mm = mm[:n_qual_rg, :n_cycle]
    ctx_obs = obs[:n_qual_rg, cyc_bins:cyc_bins + N_CONTEXT]
    ctx_mm = mm[:n_qual_rg, cyc_bins:cyc_bins + N_CONTEXT]
    # every counted base lands in exactly one clipped cycle bin, so the
    # qual marginals are the cycle-table row sums
    return (jnp.sum(cycle_obs, axis=1), jnp.sum(cycle_mm, axis=1),
            cycle_obs.reshape(-1), cycle_mm.reshape(-1),
            ctx_obs.reshape(-1), ctx_mm.reshape(-1), qh[0])


# ---------------------------------------------------------------------------
# v3: per-read-row kernel, covariates computed IN KERNEL (~2 B/base wire)
# ---------------------------------------------------------------------------

#: reads per grid step for the rows kernel; each read occupies
#: ``lane_tiles`` 128-lane slices (bucket_len is always a multiple of 128)
ROWS_BLOCK = 32

_SW_RG_BITS, _SW_LEN_BITS = 8, 9


@functools.partial(jax.jit, static_argnames=())
def _pack_rows_jit(bases, quals, read_len, flags, read_group, state,
                   usable):
    """Covariates (context needs the real bases) -> (cb [N, L] int8,
    sw [N, 1] int32), padded rows handled by the caller."""
    cov = covariate_tensors(bases, quals, read_len, flags, read_group)
    counted = cov["in_window"] & usable[:, None] & (state != STATE_MASKED)
    mm = (state == STATE_MISMATCH) & counted
    windowed = cov["in_window"] & usable[:, None]
    cb = (cov["context"].astype(jnp.int32)
          | (counted.astype(jnp.int32) << 5)
          | (mm.astype(jnp.int32) << 6)
          | (windowed.astype(jnp.int32) << 7)).astype(jnp.int8)
    from .. import schema as S  # noqa: local import avoids module cycle
    rev = ((flags & S.FLAG_REVERSE) != 0).astype(jnp.int32)
    sec = (((flags & S.FLAG_PAIRED) != 0) &
           ((flags & S.FLAG_SECOND_OF_PAIR) != 0)).astype(jnp.int32)
    rg = jnp.clip(jnp.maximum(read_group, 0), 0,
                  (1 << _SW_RG_BITS) - 1)
    ln = jnp.clip(read_len, 0, (1 << _SW_LEN_BITS) - 1)
    sw = (rg | (rev << _SW_RG_BITS) | (sec << (_SW_RG_BITS + 1))
          | (ln << (_SW_RG_BITS + 2)))[:, None]
    return cb, sw


def _rows_kernel(q_ref, cb_ref, sw_ref, obs_ref, mm_ref, qh_ref, *,
                 q_rows: int, cyc_bins: int, n_qual_rg: int,
                 n_cycle: int, max_read_len: int, lane_tiles: int,
                 int8_mxu: bool):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        obs_ref[...] = jnp.zeros_like(obs_ref)
        mm_ref[...] = jnp.zeros_like(mm_ref)
        qh_ref[...] = jnp.zeros_like(qh_ref)

    oh_t = jnp.int8 if int8_mxu else jnp.bfloat16
    acc_t = jnp.int32 if int8_mxu else jnp.float32
    nt = (((1,), (1,)), ((), ()))
    iota_q = jax.lax.broadcasted_iota(jnp.int32, (q_rows, 128), 0)
    cat = jax.lax.broadcasted_iota(jnp.int32,
                                   (cyc_bins + CTX_COLS, 128), 0)
    iota_256 = jax.lax.broadcasted_iota(jnp.int32, (256, 128), 0)
    lane = jax.lax.broadcasted_iota(jnp.int32, (1, 128), 1)

    obs_acc = jnp.zeros((q_rows, cyc_bins + CTX_COLS), acc_t)
    mm_acc = jnp.zeros((q_rows, cyc_bins + CTX_COLS), acc_t)
    qh_acc = jnp.zeros((1, 256), acc_t)
    for r in range(q_ref.shape[0]):
        s = sw_ref[r, 0]
        rg = s & ((1 << _SW_RG_BITS) - 1)
        rev = (s >> _SW_RG_BITS) & 1
        sec = (s >> (_SW_RG_BITS + 1)) & 1
        rlen = (s >> (_SW_RG_BITS + 2)) & ((1 << _SW_LEN_BITS) - 1)
        for t in range(lane_tiles):
            sl = slice(t * 128, (t + 1) * 128)
            q = jnp.maximum(q_ref[r:r + 1, sl].astype(jnp.int32), 0)
            cbv = cb_ref[r:r + 1, sl].astype(jnp.int32)
            ctx = cbv & 31
            w = ((cbv >> 5) & 1).astype(oh_t)
            wm = ((cbv >> 6) & 1).astype(oh_t)
            ww = ((cbv >> 7) & 1).astype(oh_t)
            pos = lane + t * 128
            # DiscreteCycle (StandardCovariate.scala:39-48) + L offset,
            # exactly covariate_tensors' formula
            cyc = jnp.where(rev == 1, rlen - pos, pos + 1)
            cyc = jnp.where(sec == 1, -cyc, cyc) + max_read_len
            cyc = jnp.clip(cyc, 0, n_cycle - 1)
            k = jnp.clip(q + MAX_REASONABLE_QSCORE * rg, 0,
                         n_qual_rg - 1)
            eq = (iota_q == k).astype(oh_t)
            ohc = (((cat < cyc_bins) & (cat == cyc))
                   | ((cat >= cyc_bins) & (cat - cyc_bins == ctx))
                   ).astype(oh_t)
            obs_acc += jax.lax.dot_general(
                eq * w, ohc, nt, preferred_element_type=acc_t)
            mm_acc += jax.lax.dot_general(
                eq * wm, ohc, nt, preferred_element_type=acc_t)
            ohq = (iota_256 == jnp.minimum(q, 255)).astype(oh_t)
            qh_acc += jax.lax.dot_general(
                ww.astype(oh_t), ohq, nt,
                preferred_element_type=acc_t)
    obs_ref[...] += obs_acc.astype(jnp.int32)
    mm_ref[...] += mm_acc.astype(jnp.int32)
    qh_ref[0:1, :] += qh_acc.astype(jnp.int32)


@functools.partial(jax.jit,
                   static_argnames=("q_rows", "cyc_bins", "n_qual_rg",
                                    "n_cycle", "max_read_len",
                                    "interpret", "int8_mxu"))
def _rows_call(quals2, cb2, sw2, q_rows: int, cyc_bins: int,
               n_qual_rg: int, n_cycle: int, max_read_len: int,
               interpret: bool, int8_mxu: bool):
    from jax.experimental.pallas import tpu as pltpu

    n_rows, L = quals2.shape
    n_blocks = n_rows // ROWS_BLOCK
    cat_cols = cyc_bins + CTX_COLS
    row_spec = pl.BlockSpec((ROWS_BLOCK, L), lambda i: (i, 0))
    sw_spec = pl.BlockSpec((ROWS_BLOCK, 1), lambda i: (i, 0))
    acc = pl.BlockSpec((q_rows, cat_cols), lambda i: (0, 0))
    qh = pl.BlockSpec((8, 256), lambda i: (0, 0))
    kern = functools.partial(
        _rows_kernel, q_rows=q_rows, cyc_bins=cyc_bins,
        n_qual_rg=n_qual_rg, n_cycle=n_cycle, max_read_len=max_read_len,
        lane_tiles=L // 128, int8_mxu=int8_mxu)
    return pl.pallas_call(
        kern, grid=(n_blocks,),
        in_specs=[row_spec, row_spec, sw_spec],
        out_specs=(acc, acc, qh),
        out_shape=(jax.ShapeDtypeStruct((q_rows, cat_cols), jnp.int32),
                   jax.ShapeDtypeStruct((q_rows, cat_cols), jnp.int32),
                   jax.ShapeDtypeStruct((8, 256), jnp.int32)),
        compiler_params=pallas_tpu_compiler_params(
            dimension_semantics=("arbitrary",)),
        interpret=interpret,
    )(quals2, cb2, sw2)


def count_kernel_pallas_rows(bases, quals, read_len, flags, read_group,
                             state, usable, n_qual_rg: int, n_cycle: int,
                             interpret: bool = False,
                             int8_mxu: bool = False):
    """v3 of the Pallas count backend — same 7-tensor contract, ~2 B/base
    of wire.  Reads lay out as rows ([reads, bucket_len], bucket_len a
    multiple of 128 like the product packer emits); the kernel computes
    the qual-rg and cycle covariates from the quals byte + a 4 B/read
    scalar word, so only the context/weights byte rides per base."""
    assert fits(n_qual_rg, n_cycle), (n_qual_rg, n_cycle)
    N, L = quals.shape
    max_read_len = (n_cycle - 1) // 2        # table geometry: 2L+1
    # the oracle's cycle offset is the ARRAY width; this kernel derives
    # it from the table geometry — they must be the same number or the
    # cycle bins silently shift (the product packer guarantees it:
    # bucket_len == RecalTable.max_read_len)
    assert L == max_read_len, (L, max_read_len)
    if N == 0:
        z = jnp.zeros
        return (z((n_qual_rg,), jnp.int32), z((n_qual_rg,), jnp.int32),
                z((n_qual_rg * n_cycle,), jnp.int32),
                z((n_qual_rg * n_cycle,), jnp.int32),
                z((n_qual_rg * N_CONTEXT,), jnp.int32),
                z((n_qual_rg * N_CONTEXT,), jnp.int32),
                z((256,), jnp.int32))
    cb, sw = _pack_rows_jit(bases, quals, read_len, flags, read_group,
                            state, usable)
    L_pad = _round_up(L, 128)
    N_pad = _round_up(N, ROWS_BLOCK)
    q2 = jnp.pad(jnp.asarray(quals), ((0, N_pad - N), (0, L_pad - L)))
    cb2 = jnp.pad(cb, ((0, N_pad - N), (0, L_pad - L)))
    sw2 = jnp.pad(sw, ((0, N_pad - N), (0, 0)))
    q_rows = _round_up(n_qual_rg, 8)
    cyc_bins = _round_up(n_cycle, 128)
    obs, mm, qh = _rows_call(q2, cb2, sw2, q_rows=q_rows,
                             cyc_bins=cyc_bins, n_qual_rg=n_qual_rg,
                             n_cycle=n_cycle, max_read_len=max_read_len,
                             interpret=interpret, int8_mxu=int8_mxu)
    return _unpack_tables(obs, mm, qh, n_qual_rg=n_qual_rg,
                          n_cycle=n_cycle, cyc_bins=cyc_bins)


# ---------------------------------------------------------------------------
# ragged count: flat covariate walk, no padded-lane masking
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnames=("n_rows", "n_qual_rg",
                                             "n_cycle", "max_read_len"))
def _pack_words_flat(bases_flat, quals_flat, row_of, pos_of, row_starts,
                     read_len, flags, read_group, state_flat, usable,
                     n_bases, n_rows: int, n_qual_rg: int, n_cycle: int,
                     max_read_len: int):
    """Ragged prologue: flat covariates -> the same packed index/weight
    words as :func:`_pack_words`, but over ``T`` real bases instead of
    ``N x L`` padded lanes — the per-read cycle walk is driven by true
    lengths through the prefix-sum row index, so no padded element is
    ever packed (slack past ``n_bases`` gets zero weights)."""
    from .covariates import covariate_flat

    cov = covariate_flat(bases_flat, quals_flat, row_of, pos_of,
                         row_starts, read_len, flags, read_group,
                         n_bases, n_rows=n_rows,
                         max_read_len=max_read_len)
    usable_b = usable[row_of]
    counted = cov["in_window"] & usable_b & (state_flat != STATE_MASKED)
    mm = (state_flat == STATE_MISMATCH) & counted
    windowed = cov["in_window"] & usable_b
    k = jnp.clip(cov["qual_rg"], 0, n_qual_rg - 1)
    cyc = jnp.clip(cov["cycle_idx"], 0, n_cycle - 1)
    q = jnp.clip(quals_flat.astype(jnp.int32), 0, (1 << _Q_BITS) - 1)

    word = (k | (cyc << _K_BITS) | (cov["context"] << (_K_BITS + _CYC_BITS))
            | (q << (_K_BITS + _CYC_BITS + _CTX_BITS)))
    wbits = (counted.astype(jnp.int8) | (mm.astype(jnp.int8) << 1)
             | (windowed.astype(jnp.int8) << 2))

    n_elems = word.shape[0]
    n_blocks = max(-(-n_elems // BLOCK_ELEMS), 1)
    pad = n_blocks * BLOCK_ELEMS - n_elems

    def blocked(a):
        return jnp.pad(a, (0, pad)).reshape(n_blocks, 1, BLOCK_ELEMS)

    return blocked(word), blocked(wbits)


@functools.partial(jax.jit, static_argnames=("n_qual_rg", "n_cycle"))
def _count_flat_xla(word3, wbits3, n_qual_rg: int, n_cycle: int):
    """The ragged kernel's off-TPU form: unpack the packed words and
    segment-sum the weights into the dense tables (``.at[].add`` — XLA's
    segment_sum — over the fused covariate index).  Zero-weight slack
    words contribute nothing, so the tables equal the scatter oracle's
    exactly (integer adds, order-free)."""
    from .covariates import N_CONTEXT

    word = word3.reshape(-1)
    wbits = wbits3.reshape(-1).astype(jnp.int32)
    k = word & ((1 << _K_BITS) - 1)
    cyc = (word >> _K_BITS) & ((1 << _CYC_BITS) - 1)
    ctx = (word >> (_K_BITS + _CYC_BITS)) & ((1 << _CTX_BITS) - 1)
    q = (word >> (_K_BITS + _CYC_BITS + _CTX_BITS)) & ((1 << _Q_BITS) - 1)
    w = wbits & 1
    wm = (wbits >> 1) & 1
    ww = (wbits >> 2) & 1
    qual_obs = jnp.zeros((n_qual_rg,), jnp.int32).at[k].add(w)
    qual_mm = jnp.zeros((n_qual_rg,), jnp.int32).at[k].add(wm)
    cyc_flat = k * n_cycle + cyc
    cycle_obs = jnp.zeros((n_qual_rg * n_cycle,), jnp.int32
                          ).at[cyc_flat].add(w)
    cycle_mm = jnp.zeros((n_qual_rg * n_cycle,), jnp.int32
                         ).at[cyc_flat].add(wm)
    ctx_flat = k * N_CONTEXT + ctx
    ctx_obs = jnp.zeros((n_qual_rg * N_CONTEXT,), jnp.int32
                        ).at[ctx_flat].add(w)
    ctx_mm = jnp.zeros((n_qual_rg * N_CONTEXT,), jnp.int32
                       ).at[ctx_flat].add(wm)
    qhist = jnp.zeros((256,), jnp.int32).at[q].add(ww)
    return (qual_obs, qual_mm, cycle_obs, cycle_mm, ctx_obs, ctx_mm,
            qhist)


def count_kernel_ragged(rb, state_flat, usable, n_qual_rg: int,
                        n_cycle: int, max_read_len: int,
                        interpret: bool = False, int8_mxu: bool = False,
                        impl: str = "auto"):
    """Ragged twin of :func:`count_kernel_pallas` — same 7-tensor
    contract, fed by a :class:`packing.RaggedBatch` (``rb``) plus the
    flat mismatch-state plane.

    Device work scales with the TRUE base count ``T``: the prologue
    packs one word per real base (per-read cycle walk via the
    prefix-sum row index — no padded-lane masking anywhere), and the
    word sweep runs ``T / BLOCK_ELEMS`` grid steps instead of
    ``N x L / BLOCK_ELEMS``.  On TPU the words feed the SAME Mosaic
    one-hot-matmul kernel as the padded path (``impl="pallas"``);
    off-TPU they fall back to the XLA segment-sum formulation
    (``impl="xla"``).  Bit-identical to the padded scatter oracle either
    way — integer monoid over the same (covariate, weight) multiset —
    pinned by tests/test_ragged.py.
    """
    assert fits(n_qual_rg, n_cycle), (n_qual_rg, n_cycle)
    word3, wbits3 = _pack_words_flat(
        jnp.asarray(rb.bases_flat), jnp.asarray(rb.quals_flat),
        jnp.asarray(rb.row_of), jnp.asarray(rb.pos_of),
        jnp.asarray(rb.row_offsets[:-1]), jnp.asarray(rb.read_len),
        jnp.asarray(rb.flags), jnp.asarray(rb.read_group),
        jnp.asarray(state_flat), jnp.asarray(usable),
        jnp.int32(rb.n_bases), n_rows=rb.n_reads,
        n_qual_rg=n_qual_rg, n_cycle=n_cycle,
        max_read_len=max_read_len)
    if impl == "auto":
        from ..platform import is_tpu_backend
        impl = "pallas" if is_tpu_backend() else "xla"
    if impl == "xla":
        return _count_flat_xla(word3, wbits3, n_qual_rg=n_qual_rg,
                               n_cycle=n_cycle)
    q_rows = _round_up(n_qual_rg, 8)
    cyc_bins = _round_up(n_cycle, 128)
    obs, mm, qh = _count_call(word3, wbits3, q_rows=q_rows,
                              cyc_bins=cyc_bins, interpret=interpret,
                              int8_mxu=int8_mxu)
    return _unpack_tables(obs, mm, qh, n_qual_rg=n_qual_rg,
                          n_cycle=n_cycle, cyc_bins=cyc_bins)


#: the five flat planes the paged count pool pages (name, dtype) — the
#: ragged layout's [T]-sized shipping cost, now delta-only resident
PAGED_COUNT_PLANES = (("bases", "int8"), ("quals", "int8"),
                      ("state", "int8"), ("row_of", "int32"),
                      ("pos_of", "int32"))


def count_kernel_paged(pools: dict, page_table, *, row_starts, read_len,
                       flags, read_group, usable, n_bases: int,
                       n_rows: int, n_qual_rg: int, n_cycle: int,
                       max_read_len: int, interpret: bool = False,
                       int8_mxu: bool = False, impl: str = "auto"):
    """Paged twin of :func:`count_kernel_ragged` — same 7-tensor
    contract, fed by the RESIDENT page pools instead of freshly shipped
    flat planes (docs/ARCHITECTURE.md §6l).

    ``pools`` maps each :data:`PAGED_COUNT_PLANES` name to its
    ``[pool_pages, page_rows]`` device array; ``page_table`` lists the
    physical pages of this chunk's flat planes in logical order.  One
    gather per plane reconstructs exactly the arrays the ragged kernel
    would receive — the page-table walk IS the prefix-sum row walk,
    relocated into residency — then the identical prologue + sweep
    runs, so the tables are bit-identical to :func:`count_kernel_ragged`
    (and through it to the padded scatter oracle) by construction,
    pinned by tests/test_paged.py.  Scalar per-read columns ([N]-sized,
    a rounding error next to the [T] planes) still ship per chunk.
    """
    from types import SimpleNamespace

    from ..parallel.pagedbuf import gather_pages

    pt = jnp.asarray(page_table, jnp.int32)
    # the gathered view IS the RaggedBatch the ragged kernel consumes
    # (count_kernel_ragged reads row_offsets[:-1] — the row starts),
    # so the identity is literal delegation, never a copied epilogue
    starts = jnp.asarray(row_starts, jnp.int32)
    view = SimpleNamespace(
        bases_flat=gather_pages(pools["bases"], pt),
        quals_flat=gather_pages(pools["quals"], pt),
        row_of=gather_pages(pools["row_of"], pt),
        pos_of=gather_pages(pools["pos_of"], pt),
        row_offsets=jnp.concatenate([starts, jnp.zeros(1, jnp.int32)]),
        read_len=read_len, flags=flags, read_group=read_group,
        n_bases=int(n_bases), n_reads=int(n_rows))
    return count_kernel_ragged(view, gather_pages(pools["state"], pt),
                               usable, n_qual_rg=n_qual_rg,
                               n_cycle=n_cycle,
                               max_read_len=max_read_len,
                               interpret=interpret, int8_mxu=int8_mxu,
                               impl=impl)


def flatten_state(state, read_len, t_pad: int):
    """[N, L] mismatch-state plane -> flat [t_pad] by true lengths
    (row-major — concatenation order), STATE_MASKED in the slack."""
    import numpy as np

    state = np.asarray(state)
    L = state.shape[1]
    rl = np.minimum(np.asarray(read_len, np.int64), L)
    mask = np.arange(L, dtype=np.int64)[None, :] < rl[:, None]
    out = np.full(t_pad, STATE_MASKED, np.int8)
    flat = state[mask]
    out[:len(flat)] = flat
    return out


@functools.lru_cache(maxsize=16)
def sharded_count_pallas(mesh, n_qual_rg: int, n_cycle: int,
                         variant: str = "flat", interpret: bool = False,
                         int8_mxu: bool = False):
    """Mesh-sharded count: each shard runs the Pallas kernel on its local
    rows, the 7 count tensors psum over ICI — the same shape as
    ``flagstat_wire32_sharded_pallas`` and the distributed form the
    reference reaches with its driver aggregate
    (RecalibrateBaseQualities.scala:52-64).  Unlike the chain impl (a
    host loop that cannot enter shard_map), the pallas_call is traceable,
    so the sharded product path gets the fast kernel instead of the
    scan-form matmul and its remote-AOT unroll hazard.

    ``check_vma=False`` for the same reason as the flagstat kernel: the
    pallas_call out_shape carries no varying-mesh-axes annotation.
    """
    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import READS_AXIS

    kern = count_kernel_pallas if variant == "flat" \
        else count_kernel_pallas_rows

    def fn(bases, quals, read_len, flags, read_group, state, usable):
        out = kern(bases, quals, read_len, flags, read_group, state,
                   usable, n_qual_rg=n_qual_rg, n_cycle=n_cycle,
                   interpret=interpret, int8_mxu=int8_mxu)
        return tuple(jax.lax.psum(o, READS_AXIS) for o in out)

    spec = P(READS_AXIS)
    return jax.jit(shard_map(
        fn, mesh=mesh, in_specs=(spec,) * 7, out_specs=(P(),) * 7,
        check_vma=False))
