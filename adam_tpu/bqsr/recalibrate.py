"""BQSR driver: two passes over the reads, both device-resident.

Re-designs ``rdd/RecalibrateBaseQualities.scala``:

  pass 1 (computeTable :52-64): per-base covariates + mismatch/mask state ->
    scatter-add into the dense count tensors; across shards the tables merge
    with psum (the reference tree-reduces JVM hash maps to the driver);
  pass 2 (applyTable :66-76): per-base gathers from the finalized delta
    tables rewrite the quality scores.

Usable-read filter (:29-32): mapped, primary, not duplicate, has MD.
Recalibrated reads (:69-74): mapped, primary, not duplicate (MD not
required at apply time — unknown bases are masked, not skipped).

One deliberate divergence: RecalUtil.recalibrate (:31-42) rebuilds the qual
string from only the clip-window bases, silently *truncating* the quals of
reads with low-quality ends; we keep the original qual for bases outside the
window (what GATK does).
"""

from __future__ import annotations

import os
from functools import lru_cache, partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import pyarrow as pa

from .. import schema as S
from ..models.snptable import SnpTable
from ..platform import shard_map
from ..ops import cigar as C
from ..packing import ReadBatch, pack_reads
from ..util.mdtag import MdTag
from ..util.phred import PHRED_TO_ERROR
from .covariates import MAX_REASONABLE_QSCORE, covariate_tensors
from .table import FinalizedTable, RecalTable

# mismatch state codes (host -> device)
STATE_MATCH = 0
STATE_MISMATCH = 1
STATE_MASKED = 2


def usable_read_mask(flags: np.ndarray, has_md: np.ndarray) -> np.ndarray:
    """RecalibrateBaseQualities.usableRead (:29-32)."""
    return ((flags & S.FLAG_UNMAPPED) == 0) & \
        ((flags & S.FLAG_SECONDARY) == 0) & \
        ((flags & S.FLAG_DUPLICATE) == 0) & has_md


@partial(jax.jit, static_argnames=("max_len",))
def _state_base_kernel(start, cigar_ops, cigar_lens, has_md,
                       max_len: int):
    """Base state computed ON DEVICE: MATCH where the reference position
    is defined (aligned, within [start, end)) and the read has an MD
    tag, else MASKED.  Returns (state int8, end, pos) with pos left on
    device — the host copies 1 byte/base instead of the 4-byte position
    matrix (which only complex-cigar event rows ever need)."""
    pos = C.reference_positions(start, cigar_ops, cigar_lens, max_len)
    end = C.read_end(start, cigar_ops, cigar_lens)
    in_align = (pos >= 0) & (pos >= start[:, None]) & \
        (pos < end[:, None]) & has_md[:, None]
    state = jnp.where(in_align, STATE_MATCH, STATE_MASKED).astype(jnp.int8)
    return state, end, pos


# per-event gather budget for _apply_events' complex-cigar path: bounds
# the [E_chunk, L] row gathers so event scatters never materialize more
# than ~32 MB at once
_EVENT_CHUNK_BYTES = 32 << 20


def _apply_events(state: np.ndarray, start: np.ndarray,
                  simple: np.ndarray, pos_dev,
                  ev_row: np.ndarray, ev_pos: np.ndarray,
                  value: int) -> None:
    """Set ``state[r, j] = value`` at the base of read ``r`` aligned to
    reference position ``p``, gated on that base being unmasked (the
    defined/in-alignment gate: MASKED marks undefined positions, and
    events never target them).

    Single-M-cigar rows (the overwhelming majority) resolve the offset
    arithmetically (``j = p - start``) with NO position matrix at all;
    complex-cigar rows gather their device-resident position rows in
    bounded chunks and use argmax-first-hit, which is exact because
    aligned positions within a read are strictly increasing and
    clip-extrapolated positions fall outside [start, end).  Work and
    memory are O(E) + O(E_complex x L) over the (rare) events instead of
    O(N x L) over every base.
    """
    if len(ev_row) == 0:
        return
    L = state.shape[1]
    is_simple = simple[ev_row]
    r = ev_row[is_simple]
    off = ev_pos[is_simple] - start[r]
    ok = (off >= 0) & (off < L)
    r, off = r[ok], off[ok].astype(np.intp)
    sel = state[r, off] != STATE_MASKED
    state[r[sel], off[sel]] = value

    r2 = ev_row[~is_simple]
    p2 = ev_pos[~is_simple]
    if len(r2) == 0:
        return
    chunk = max(1, _EVENT_CHUNK_BYTES // max(L * 4, 1))
    for s in range(0, len(r2), chunk):
        rr = r2[s:s + chunk]
        pp = p2[s:s + chunk]
        uniq, inv = np.unique(rr, return_inverse=True)
        posu = np.asarray(pos_dev[jnp.asarray(uniq)])    # [u, L]
        hit = posu[inv] == pp[:, None]                   # [e, L]
        j = np.argmax(hit, axis=1)
        found = hit[np.arange(len(rr)), j]
        rs, js = rr[found], j[found]
        sel = state[rs, js] != STATE_MASKED
        state[rs[sel], js[sel]] = value


def md_events_for(table: pa.Table, starts: np.ndarray
                  ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Parse a chunk's MD tags ONCE into the compact event form the
    fused transform hoists into stream 1: ``(has_md, ev_rows, ev_pos)``
    — per-read MD presence plus the ~1-per-read mismatch events
    (chunk-local row, absolute reference position).  Feeding this back
    through ``count_tables_device(md_info=...)`` skips the MD re-parse
    (and lets the count walk's spill projection drop the
    ``mismatchingPositions`` column entirely — it is the largest column
    of the raw spill on typical inputs)."""
    from ..ops.pileup import _col_valid, _md_lookup_arrays

    md_col = table.column("mismatchingPositions")
    has_md = _col_valid(md_col)
    mm_keys, _, _, _ = _md_lookup_arrays(md_col, starts,
                                         np.flatnonzero(has_md))
    return (has_md, (mm_keys >> 34).astype(np.int64),
            mm_keys & ((np.int64(1) << 34) - 1))


def slice_md_info(md_info, s: int, e: int):
    """Row-slice an ``(has_md, ev_rows, ev_pos)`` triple to [s, e) with
    rows re-based to the slice (the slab walk's counterpart of
    ``ReadBatch.row_slice``)."""
    has_md, ev_rows, ev_pos = md_info
    sel = (ev_rows >= s) & (ev_rows < e)
    return has_md[s:e], ev_rows[sel] - s, ev_pos[sel]


def mismatch_state(table: pa.Table, batch: ReadBatch,
                   snp_table: Optional[SnpTable] = None,
                   device_batch: Optional[ReadBatch] = None,
                   md_info=None) -> np.ndarray:
    """[N, L] int8 per-base state for pass 1.

    Mirrors ReadCovariates.next (:49-60): a base is MASKED when its reference
    position is undefined (insertion/soft-clip/outside the alignment), the
    read has no MD tag, or dbSNP masks the position; else MATCH/MISMATCH by
    the MD tag (RichADAMRecord.isMismatchAtReadOffset :138-154).

    Event-side formulation: every aligned base of an MD-bearing read defaults
    to MATCH, then the MD mismatch events (~1 per read) and the dbSNP sites
    overlapping each alignment span are scattered in as MISMATCH/MASKED.
    Peak memory is O(N x L) int8/bool plus an O(events x L) chunked gather —
    the round-2 version materialized an [N, L] int64 key matrix (~1 GB per
    1M-read x 128 bp chunk) and looped Python over every dbSNP accession.
    """
    n = table.num_rows
    L = batch.max_len
    if md_info is None:
        from ..ops.pileup import _col_valid
        has_md = _col_valid(table.column("mismatchingPositions"))
    else:
        has_md = md_info[0][:n]     # may carry the padded tail
    has_md_pad = np.zeros(batch.n_reads, bool)
    has_md_pad[:n] = has_md

    # one fused jit for geometry AND the base state: eager per-op
    # dispatch of the reference-position walk measured 6.3 s per
    # 500k-read chunk on CPU, and copying the int32 position matrix to
    # host another ~2.5 s/M — so the state is built on device (1 B/base
    # crosses) and positions stay device-resident for the few
    # complex-cigar event rows that need them.  ``device_batch`` (the
    # executor's prefetched feed) supplies already-transferred columns so
    # the geometry inputs don't cross the link twice.
    db = device_batch if device_batch is not None else batch
    state_d, end_d, pos_d = _state_base_kernel(
        jnp.asarray(db.start), jnp.asarray(db.cigar_ops),
        jnp.asarray(db.cigar_lens), jnp.asarray(has_md_pad), max_len=L)
    # .copy(): the CPU backend zero-copies device buffers read-only, and
    # the event scatters below write in place
    state = np.asarray(state_d)[:n].copy()
    end = np.asarray(end_d)[:n]
    start = np.asarray(batch.start[:n], np.int64)
    ops = np.asarray(batch.cigar_ops)[:n]
    simple = ops[:, 0] == S.CIGAR_M
    if ops.shape[1] > 1:          # single-op batches have no slot 1
        simple &= ops[:, 1] < 0

    # MD mismatch events (shared key encoding with the pileup engine:
    # row << 34 | ref_pos); ``md_info`` supplies them pre-parsed (the
    # fused transform parses MD once in stream 1 — events are
    # same-valued scatters, so supply order cannot change the state)
    if md_info is None:
        from ..ops.pileup import _md_lookup_arrays
        mm_keys, _, _, _ = _md_lookup_arrays(
            table.column("mismatchingPositions"), start,
            np.flatnonzero(has_md))
        ev_rows = mm_keys >> 34
        ev_pos = mm_keys & ((np.int64(1) << 34) - 1)
    else:
        _, ev_rows, ev_pos = md_info
    _apply_events(state, start, simple, pos_d, ev_rows, ev_pos,
                  STATE_MISMATCH)

    if snp_table is not None and len(snp_table):
        # dictionary-encode the contig column once, then iterate only the
        # contigs PRESENT IN THIS BATCH (<= #chromosomes) — dbSNP itself
        # carries thousands of accessions.  Per contig, each read's site
        # hits are the sorted-site range [start, end): two searchsorteds
        # and a flat range-expand, no per-base keys.
        enc = table.column("referenceName").combine_chunks() \
            .dictionary_encode()
        codes = enc.indices.to_numpy(zero_copy_only=False)
        for ci, contig in enumerate(enc.dictionary.to_pylist()):
            sites = snp_table.sites(contig)
            if sites is None or len(sites) == 0:
                continue
            crows = np.flatnonzero(codes == ci)
            if len(crows) == 0:
                continue
            lo = np.searchsorted(sites, start[crows])
            hi = np.searchsorted(sites, end[crows])
            cnt = hi - lo
            tot = int(cnt.sum())
            if tot == 0:
                continue
            ev_row = np.repeat(crows, cnt)
            first = np.cumsum(cnt) - cnt
            idx = np.repeat(lo - first, cnt) + np.arange(tot)
            _apply_events(state, start, simple, pos_d, ev_row,
                          sites[idx], STATE_MASKED)
    return state


@partial(jax.jit, static_argnames=("n_qual_rg", "n_cycle", "axis_name"))
def _count_kernel(bases, quals, read_len, flags, read_group, state, usable,
                  n_qual_rg: int, n_cycle: int, axis_name=None):
    """Pass-1 scatter-add into the dense count tensors."""
    cov = covariate_tensors(bases, quals, read_len, flags, read_group)
    counted = cov["in_window"] & usable[:, None] & (state != STATE_MASKED)
    mm = (state == STATE_MISMATCH) & counted
    k = jnp.clip(cov["qual_rg"], 0, n_qual_rg - 1)
    cyc = jnp.clip(cov["cycle_idx"], 0, n_cycle - 1)
    ctx = cov["context"]

    w = counted.astype(jnp.int32)
    wm = mm.astype(jnp.int32)
    qual_obs = jnp.zeros((n_qual_rg,), jnp.int32).at[k].add(w)
    qual_mm = jnp.zeros((n_qual_rg,), jnp.int32).at[k].add(wm)
    cyc_flat = k * n_cycle + cyc
    cycle_obs = jnp.zeros((n_qual_rg * n_cycle,), jnp.int32).at[cyc_flat].add(w)
    cycle_mm = jnp.zeros((n_qual_rg * n_cycle,), jnp.int32).at[cyc_flat].add(wm)
    from .covariates import N_CONTEXT
    ctx_flat = k * N_CONTEXT + ctx
    ctx_obs = jnp.zeros((n_qual_rg * N_CONTEXT,), jnp.int32).at[ctx_flat].add(w)
    ctx_mm = jnp.zeros((n_qual_rg * N_CONTEXT,), jnp.int32).at[ctx_flat].add(wm)

    # expectedMismatch sums reported error over every window base of a usable
    # read, masked or not (RecalTable.+= :62).  The kernel returns the exact
    # 256-bin qual histogram instead of a float sum: int32 counts psum
    # exactly, so every backend/sharding produces the bit-identical f64
    # expectation on host (a f32 device sum flipped trunc() at phred
    # boundaries between sharded and unsharded runs).
    windowed = cov["in_window"] & usable[:, None]
    qidx = jnp.clip(quals.astype(jnp.int32), 0, 255)
    qhist = jnp.zeros((256,), jnp.int32).at[qidx].add(
        windowed.astype(jnp.int32))

    out = (qual_obs, qual_mm, cycle_obs, cycle_mm, ctx_obs, ctx_mm, qhist)
    if axis_name is not None:
        out = tuple(jax.lax.psum(o, axis_name) for o in out)
    return out


def _count_block_prep(bases, quals, read_len, flags, read_group, state,
                      usable, n_qual_rg: int, n_cycle: int,
                      block_rows: int):
    """Covariates + masks flattened into per-block arrays — the shared
    prologue of the matmul-scan and dispatch-chain count kernels."""
    cov = covariate_tensors(bases, quals, read_len, flags, read_group)
    counted = cov["in_window"] & usable[:, None] & (state != STATE_MASKED)
    mm = (state == STATE_MISMATCH) & counted
    k = jnp.clip(cov["qual_rg"], 0, n_qual_rg - 1)
    cyc = jnp.clip(cov["cycle_idx"], 0, n_cycle - 1)
    ctx = cov["context"]

    N, L = bases.shape
    n_blocks = -(-N // block_rows)
    pad = n_blocks * block_rows - N

    def padded(a, fill=0):
        return jnp.pad(a, ((0, pad), (0, 0)), constant_values=fill)

    windowed = cov["in_window"] & usable[:, None]
    qidx = jnp.clip(quals.astype(jnp.int32), 0, 255)
    return (padded(k).reshape(n_blocks, block_rows * L),
            padded(cyc).reshape(n_blocks, block_rows * L),
            padded(ctx).reshape(n_blocks, block_rows * L),
            padded(qidx).reshape(n_blocks, block_rows * L),
            padded(counted.astype(jnp.bfloat16)).reshape(n_blocks, -1),
            padded(mm.astype(jnp.bfloat16)).reshape(n_blocks, -1),
            padded(windowed.astype(jnp.bfloat16)).reshape(n_blocks, -1))


def _count_init(n_qual_rg: int, n_cycle: int):
    from .covariates import N_CONTEXT
    return (jnp.zeros((n_qual_rg,), jnp.int32),
            jnp.zeros((n_qual_rg,), jnp.int32),
            jnp.zeros((2 * n_qual_rg, n_cycle), jnp.int32),
            jnp.zeros((2 * n_qual_rg, N_CONTEXT), jnp.int32),
            jnp.zeros((256,), jnp.int32))


def _count_block_body(carry, blk, n_qual_rg: int, n_cycle: int):
    """One block's one-hot matmuls accumulated into the carry tables
    (shared by the lax.scan and dispatch-chain drivers)."""
    from .covariates import N_CONTEXT
    q_ids = jnp.arange(n_qual_rg, dtype=jnp.int32)
    cyc_ids = jnp.arange(n_cycle, dtype=jnp.int32)
    ctx_ids = jnp.arange(N_CONTEXT, dtype=jnp.int32)
    q256_ids = jnp.arange(256, dtype=jnp.int32)
    qual_o, qual_m, cyc_t, ctx_t, qh_t = carry
    kb, cycb, ctxb, qb, wb, wmb, wwb = blk
    ohk = (kb[:, None] == q_ids[None, :]).astype(jnp.bfloat16)
    wk = jnp.concatenate([ohk * wb[:, None], ohk * wmb[:, None]],
                         axis=1)                       # [X, 2Q]
    qual_sums = jnp.sum(wk, axis=0,
                        dtype=jnp.float32).astype(jnp.int32)  # [2Q]
    ohcyc = (cycb[:, None] == cyc_ids[None, :]).astype(jnp.bfloat16)
    ohctx = (ctxb[:, None] == ctx_ids[None, :]).astype(jnp.bfloat16)
    cyc_pair = jax.lax.dot(wk.T, ohcyc,
                           preferred_element_type=jnp.float32)
    ctx_pair = jax.lax.dot(wk.T, ohctx,
                           preferred_element_type=jnp.float32)
    ohq = (qb[:, None] == q256_ids[None, :]).astype(jnp.bfloat16)
    qh = jax.lax.dot(wwb.reshape(1, -1), ohq,
                     preferred_element_type=jnp.float32)[0]
    return (qual_o + qual_sums[:n_qual_rg],
            qual_m + qual_sums[n_qual_rg:],
            cyc_t + cyc_pair.astype(jnp.int32),
            ctx_t + ctx_pair.astype(jnp.int32),
            qh_t + qh.astype(jnp.int32))


def _pack_count_out(carry, n_qual_rg: int, axis_name=None):
    qual_obs, qual_mm, cyc_t, ctx_t, qhist = carry
    out = (qual_obs, qual_mm,
           cyc_t[:n_qual_rg].reshape(-1), cyc_t[n_qual_rg:].reshape(-1),
           ctx_t[:n_qual_rg].reshape(-1), ctx_t[n_qual_rg:].reshape(-1),
           qhist)
    if axis_name is not None:
        out = tuple(jax.lax.psum(o, axis_name) for o in out)
    return out


@partial(jax.jit, static_argnames=("n_qual_rg", "n_cycle", "block_rows",
                                   "axis_name"))
def _count_kernel_matmul(bases, quals, read_len, flags, read_group, state,
                         usable, n_qual_rg: int, n_cycle: int,
                         block_rows: int = 512, axis_name=None):
    """Pass-1 counting as blocked one-hot matmuls — the MXU formulation.

    Scatter-adds serialize on duplicate indices (ruinous on TPU); here each
    table is ``(one_hot(k) * w).T @ one_hot(attr)`` over row blocks:
    table[q, c] = sum_x [k_x = q] * w_x * [attr_x = c].  The observed and
    mismatch tables stack along the Q axis so one [2Q, X] @ [X, C] matmul
    per block produces both.  f32 block products are exact (block sums
    < 2^24) and accumulate into int32 carries.
    """
    blocks = _count_block_prep(bases, quals, read_len, flags, read_group,
                               state, usable, n_qual_rg, n_cycle,
                               block_rows)

    def body(carry, blk):
        return _count_block_body(carry, blk, n_qual_rg, n_cycle), None

    carry, _ = jax.lax.scan(body, _count_init(n_qual_rg, n_cycle), blocks)
    return _pack_count_out(carry, n_qual_rg, axis_name)


@partial(jax.jit, static_argnames=("n_qual_rg", "n_cycle", "block_rows"))
def _count_chain_prep_jit(bases, quals, read_len, flags, read_group, state,
                          usable, n_qual_rg, n_cycle, block_rows):
    return _count_block_prep(bases, quals, read_len, flags, read_group,
                             state, usable, n_qual_rg, n_cycle, block_rows)


@partial(jax.jit, static_argnames=("n_qual_rg", "n_cycle"),
         donate_argnums=(0,))
def _count_chain_step_jit(carry, kb, cycb, ctxb, qb, wb, wmb, wwb,
                          n_qual_rg, n_cycle):
    return _count_block_body(carry, (kb, cycb, ctxb, qb, wb, wmb, wwb),
                             n_qual_rg, n_cycle)


def _count_kernel_chain(bases, quals, read_len, flags, read_group, state,
                        usable, n_qual_rg: int, n_cycle: int,
                        block_rows: int = 512, axis_name=None):
    """The matmul formulation driven by a HOST dispatch chain instead of a
    lax.scan: one compiled block step re-dispatched per block with a
    donated device-resident carry.  Compile time is one block regardless
    of chunk size — the escape hatch for toolchains whose loop compiler
    unrolls (the remote TPU AOT compiler took ~2 s/iteration on an
    equivalent scan body; at product chunk sizes that is hours).
    ``ADAM_TPU_BQSR_COUNT=chain`` selects it.
    """
    assert axis_name is None, "chain impl runs outside shard_map"
    blocks = _count_chain_prep_jit(bases, quals, read_len, flags,
                                   read_group, state, usable,
                                   n_qual_rg=n_qual_rg, n_cycle=n_cycle,
                                   block_rows=block_rows)
    carry = _count_init(n_qual_rg, n_cycle)
    n_blocks = blocks[0].shape[0]
    for i in range(n_blocks):
        carry = _count_chain_step_jit(
            carry, *(b[i] for b in blocks),
            n_qual_rg=n_qual_rg, n_cycle=n_cycle)
    return _pack_count_out(carry, n_qual_rg)


def _count_tables_host(batch: ReadBatch, state, usable, n_qual_rg: int,
                       n_cycle: int):
    """Pass-1 counting with host bincounts over the counted subset.

    On the CPU backend XLA's scatter-add was the single hottest stage of
    the end-to-end transform (70 s / 2M reads); gathering the counted
    elements (~the window) and np.bincount-ing them runs at C-loop speed.
    """
    from .covariates import N_CONTEXT
    cov = covariate_tensors(
        jnp.asarray(batch.bases), jnp.asarray(batch.quals),
        jnp.asarray(batch.read_len), jnp.asarray(batch.flags),
        jnp.asarray(batch.read_group))
    in_window = np.asarray(cov["in_window"])
    k = np.clip(np.asarray(cov["qual_rg"]), 0, n_qual_rg - 1)
    cyc = np.clip(np.asarray(cov["cycle_idx"]), 0, n_cycle - 1)
    ctx = np.asarray(cov["context"])

    counted = in_window & usable[:, None] & (state != STATE_MASKED)
    sel = counted.ravel()
    ks = k.ravel()[sel]
    flat_cyc = ks * n_cycle + cyc.ravel()[sel]
    flat_ctx = ks * N_CONTEXT + ctx.ravel()[sel]
    mm_sel = ((state == STATE_MISMATCH) & counted).ravel()
    km = k.ravel()[mm_sel]

    def bc(vals, n):
        return np.bincount(vals, minlength=n).astype(np.int32)

    qual_obs = bc(ks, n_qual_rg)
    qual_mm = bc(km, n_qual_rg)
    cycle_obs = bc(flat_cyc, n_qual_rg * n_cycle)
    cycle_mm = bc(km * n_cycle + cyc.ravel()[mm_sel], n_qual_rg * n_cycle)
    ctx_obs = bc(flat_ctx, n_qual_rg * N_CONTEXT)
    ctx_mm = bc(km * N_CONTEXT + ctx.ravel()[mm_sel],
                n_qual_rg * N_CONTEXT)

    windowed = in_window & usable[:, None]
    quals_np = np.asarray(batch.quals)
    qidx = np.clip(quals_np.astype(np.int64), 0, 255)
    qhist = np.bincount(qidx.ravel()[windowed.ravel()],
                        minlength=256).astype(np.int32)
    return (qual_obs, qual_mm, cycle_obs, cycle_mm, ctx_obs, ctx_mm,
            qhist)


#: count implementation override: "scatter" | "matmul" | "host" | "auto".
#: auto = scatter on the CPU backend (measured fastest there: 4.4 s per
#: 500k-read chunk vs ~5.2 s for host bincounts — the covariate pulls eat
#: the bincount savings), matmul on accelerators (TPU scatter-adds
#: serialize on duplicate indices; the blocked one-hot matmul stays on the
#: MXU).  "host" is kept selectable as the third differential oracle.
_COUNT_IMPL_ENV = "ADAM_TPU_BQSR_COUNT"


def _count_impl(sharded: bool = False) -> str:
    choice = os.environ.get(_COUNT_IMPL_ENV, "auto")
    if sharded and choice == "chain":
        # chain is a host loop that cannot enter shard_map; honoring it
        # under a mesh would silently drop the sharding — coerce to the
        # scan form (same matmul math).  The pallas impls ARE traceable
        # and run sharded (count_pallas.sharded_count_pallas).
        return "matmul"
    if choice in ("scatter", "matmul", "host", "chain", "pallas",
                  "pallas_rows"):
        return choice
    if jax.default_backend() == "cpu":
        return "scatter"
    # TPU auto: the chain form (host-dispatched matmul blocks) compiles in
    # one block regardless of chunk size — the remote AOT compiler showed
    # ~2 s/iteration compile on an equivalent scan body, which at product
    # chunk sizes (thousands of blocks) is effectively a hang.  The scan
    # form stays the pick under shard_map, which a host loop cannot enter.
    # Both answers may be upgraded to the Pallas rows kernel by the
    # per-geometry self-check (_tpu_auto_upgrade) at the call site.
    return "matmul" if sharded else "chain"


#: (n_qual_rg, n_cycle, sharded, mesh) -> bool: did the Pallas rows
#: kernel prove itself exact in the SAME configuration production uses?
_AUTO_UPGRADE_CACHE: dict = {}


def _tpu_auto_upgrade(fallback: str, n_qual_rg: int, n_cycle: int,
                      n_read_groups: int, mesh=None) -> str:
    """On TPU backends, upgrade the auto count impl to the Pallas rows
    kernel after a one-time exactness check against the scatter oracle
    at this table geometry — run through the SAME callable production
    will use (sharded wrapper + interpret flag included).  The check
    batch is adversarial: invalid/pad bases, pad and boundary quals,
    null read groups, zero-length and unusable reads.  Any failure —
    Mosaic rejection, value divergence — caches False and the caller's
    own fallback is returned, so a failed check on the sharded path can
    never leak a host-loop impl to it (or vice versa)."""
    sharded = mesh is not None
    key = (n_qual_rg, n_cycle, mesh)
    ok = _AUTO_UPGRADE_CACHE.get(key)
    if ok is None:
        ok = False
        try:
            from .count_pallas import ROWS_BLOCK, fits
            from ..platform import is_tpu_backend
            L = (n_cycle - 1) // 2
            # TPU only: on any other accelerator the probe would pass in
            # interpret mode and then run the Mosaic INTERPRETER on real
            # chunks (platform.is_tpu_backend's documented hazard)
            if is_tpu_backend() and fits(n_qual_rg, n_cycle) and L >= 1:
                rng = np.random.RandomState(0)
                n = ROWS_BLOCK * 2 * (mesh.size if sharded else 1)
                quals = rng.randint(-1, 94, (n, L)).astype(np.int8)
                quals[0] = 0
                quals[1] = 93
                read_len = rng.randint(0, L + 1, n).astype(np.int32)
                usable = rng.rand(n) < 0.8
                usable[2] = False
                args = (
                    # -1 pad and 4 (N) both out of the valid 0-3 range
                    jnp.asarray(rng.randint(-1, 5, (n, L))
                                .astype(np.int8)),
                    jnp.asarray(quals),
                    jnp.asarray(read_len),
                    jnp.asarray(rng.choice([0, 16, 83, 163, 512 | 1], n)
                                .astype(np.int32)),
                    jnp.asarray(rng.randint(-1, n_read_groups, n)
                                .astype(np.int32)),
                    jnp.asarray(rng.randint(0, 3, (n, L))
                                .astype(np.int8)),
                    jnp.asarray(usable))
                ref = _count_kernel(*args, n_qual_rg=n_qual_rg,
                                    n_cycle=n_cycle)
                if sharded:
                    cand = _sharded_pallas_fn(
                        mesh, n_qual_rg, n_cycle, "rows",
                        not is_tpu_backend())(*args)
                else:
                    from .count_pallas import count_kernel_pallas_rows
                    cand = count_kernel_pallas_rows(
                        *args, n_qual_rg=n_qual_rg, n_cycle=n_cycle,
                        interpret=not is_tpu_backend())
                ok = all(np.array_equal(np.asarray(a), np.asarray(b))
                         for a, b in zip(cand, ref))
        except Exception:  # noqa: BLE001 — fallback is the answer
            ok = False
        _AUTO_UPGRADE_CACHE[key] = ok
    return "pallas_rows" if ok else fallback


#: row-slab bound for the pass-1 chunk walk.  The count kernels materialize
#: several [rows, L] int32 covariate tensors; at the streaming pipeline's
#: 1M-row chunks that working set (~2.4 GB) falls out of cache and the
#: measured cost turns superlinear: 1M rows took 38 s where 5x the 200k-row
#: time predicts 8 s (CPU backend, this box).  Walking the chunk in
#: 256k-row slabs and summing the (tiny) count tensors restores the linear
#: rate — count tensors are exact integer monoids, so the slab sum is
#: bit-identical to the monolithic call for every impl.
_COUNT_SLAB_ENV = "ADAM_TPU_COUNT_SLAB"


def _count_slab_rows() -> int:
    return int(os.environ.get(_COUNT_SLAB_ENV, str(256 * 1024)))


@lru_cache(maxsize=16)
def _sharded_count_fn(kernel, mesh, n_qual_rg: int, n_cycle: int,
                      donate: bool = False):
    """Build (and cache — a fresh shard_map+jit per chunk would retrace
    every call, like distributed.py's _build_resharder) the count kernel
    under shard_map over the read axis, tables psum-merged across the
    mesh — the distributed form the reference reaches with its
    driver-side aggregate (RecalibrateBaseQualities:52-64 tree-reduce).

    ``donate=True`` (the streaming executor's per-chunk path) donates
    all 7 per-chunk inputs: each chunk's tensors are consumed exactly
    once, so the device reuses their HBM for the next chunk's arrivals
    instead of re-allocating.  Callers that re-dispatch the same buffers
    (the bench race chains) must keep the default."""
    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import READS_AXIS

    spec = P(READS_AXIS)
    fn = shard_map(
        partial(kernel, n_qual_rg=n_qual_rg, n_cycle=n_cycle,
                axis_name=READS_AXIS),
        mesh=mesh, in_specs=(spec,) * 7, out_specs=(P(),) * 7)
    return jax.jit(fn, donate_argnums=tuple(range(7)) if donate else ())


@lru_cache(maxsize=8)
def _donating_count_fn(kernel):
    """The unsharded count kernel re-jitted with its 7 per-chunk array
    args donated (same trace — ``__wrapped__`` is the undecorated body;
    the jit cache keys the two variants separately)."""
    statics = ("n_qual_rg", "n_cycle", "block_rows", "axis_name") \
        if kernel is _count_kernel_matmul \
        else ("n_qual_rg", "n_cycle", "axis_name")
    return jax.jit(getattr(kernel, "__wrapped__", kernel),
                   static_argnames=statics,
                   donate_argnums=tuple(range(7)))


def _sharded_pallas_fn(mesh, n_qual_rg: int, n_cycle: int, variant: str,
                       interpret: bool):
    # deferred-import shim only: sharded_count_pallas memoizes itself,
    # and a second LRU here would pin entries the outer one evicted
    from .count_pallas import sharded_count_pallas
    return sharded_count_pallas(mesh, n_qual_rg, n_cycle, variant=variant,
                                interpret=interpret)


def _paged_count(box: dict, rb, state_flat, usable, rt, max_read_len,
                 fused: bool = False):
    """One chunk's count through the RESIDENT plane pool
    (parallel/pagedbuf; docs/ARCHITECTURE.md §6l).

    ``box`` is the pass-scoped pool holder ``_count_stream`` threads
    through every chunk ({"pass": name, "put": pex.dispatch_put});
    the pool is created lazily, sized to twice the first chunk's page
    need, and persists across chunks — each chunk ships only its live
    pages (the [T]-sized planes; the rung slack past the last page
    never crosses the link) and the kernel walks the page table.
    Returns None when the pool would thrash (a later chunk outgrowing
    it): the caller's ragged concat path is the fallback, identical
    bytes by the count monoid."""
    import numpy as np

    from ..parallel.pagedbuf import PagePool
    from ..platform import is_tpu_backend
    from .count_pallas import (BLOCK_ELEMS, PAGED_COUNT_PLANES,
                               count_kernel_paged)

    t_pad = len(rb.bases_flat)
    page_rows = BLOCK_ELEMS         # every t-rung is a BLOCK_ELEMS
    #                                 multiple (shape_rung over it)
    table_len = max(t_pad // page_rows, 1)
    # ship only the LIVE pages (true base count, rounded up to a whole
    # page) — the rung slack past them never crosses the link; the page
    # table pads to the rung with the last live page, whose stale
    # content is weight-gated off by the kernel's ``live`` bound
    need = min(max(-(-int(rb.n_bases) // page_rows), 1), table_len)
    pool = box.get("pool")
    if pool is None:
        pool = box["pool"] = PagePool(
            box.get("pass", "p2"), table_len * 2, page_rows,
            planes=PAGED_COUNT_PLANES, put=box.get("put"))
    ids = pool.alloc(need)
    if ids is None:
        return None
    live = need * page_rows
    pool.write(ids, bases=rb.bases_flat[:live],
               quals=rb.quals_flat[:live],
               state=np.asarray(state_flat)[:live],
               row_of=rb.row_of[:live], pos_of=rb.pos_of[:live])
    try:
        if fused:
            # fused_device plan route: the mega-pass bqsr leg over the
            # same resident pools (ops/megapass — one compiled program;
            # the pack + fold jits inline under it unchanged)
            from ..ops.megapass import megapass_bqsr_paged
            return megapass_bqsr_paged(
                {n: pool.device(n) for n, _ in PAGED_COUNT_PLANES},
                pool.table(ids, table_len),
                row_starts=rb.row_offsets[:-1], read_len=rb.read_len,
                flags=rb.flags, read_group=rb.read_group,
                usable=usable, n_bases=rb.n_bases, n_rows=rb.n_reads,
                n_qual_rg=rt.n_qual_rg, n_cycle=rt.n_cycle,
                max_read_len=max_read_len,
                impl="pallas" if is_tpu_backend() else "xla",
                interpret=not is_tpu_backend())
        return count_kernel_paged(
            {n: pool.device(n) for n, _ in PAGED_COUNT_PLANES},
            pool.table(ids, table_len),
            row_starts=rb.row_offsets[:-1], read_len=rb.read_len,
            flags=rb.flags, read_group=rb.read_group, usable=usable,
            n_bases=rb.n_bases, n_rows=rb.n_reads,
            n_qual_rg=rt.n_qual_rg, n_cycle=rt.n_cycle,
            max_read_len=max_read_len,
            interpret=not is_tpu_backend())
    finally:
        # the dispatch is enqueued on the device stream before any
        # later scatter can recycle these pages (FIFO ordering)
        pool.free(ids)


def count_tables_device(table: pa.Table,
                        batch: Optional[ReadBatch] = None,
                        snp_table: Optional[SnpTable] = None,
                        n_read_groups: Optional[int] = None,
                        mesh=None,
                        device_batch: Optional[ReadBatch] = None,
                        donate: bool = False,
                        md_info=None,
                        layout: str = "padded",
                        paged_box: Optional[dict] = None,
                        fused: bool = False):
    """Pass-1 counting for one chunk, WITHOUT the host sync: returns the 7
    count tensors (qual_obs, qual_mm, cycle_obs, cycle_mm, ctx_obs,
    ctx_mm, qhist) still on device (numpy under the "host" impl — both add
    elementwise), so a streaming caller can accumulate chunk tables
    device-side and let host pack/mismatch-state of chunk i+1 overlap the
    device count of chunk i.  ``tables_to_recal`` folds the accumulated
    tensors into a RecalTable at pass end.

    Large chunks walk in `_count_slab_rows()` row slabs (see note at
    ``_COUNT_SLAB_ENV``); the sharded mesh path stays monolithic — its rows
    already split across devices under shard_map.

    ``device_batch`` (the executor's prefetched feed) carries the same
    batch already transferred — consumed by the monolithic paths
    (sharded, or unsharded within one slab), where the kernel takes
    whole columns; the slab walk slices rows, and slicing device arrays
    would dispatch a compiled slice per offset (fresh shapes, the exact
    churn the executor exists to kill), so it keeps the host batch.
    ``donate=True`` donates the kernel's per-chunk inputs (streaming
    path only; see `_sharded_count_fn`).

    ``fused=True`` (the plan's ``fused_device`` dimension) routes the
    unsharded count through the mega-pass bqsr leg (ops/megapass): the
    SAME pack + fold jits composed under one program, so one device
    dispatch replaces the pack/count pair — bit-identical by
    construction.  Sharded meshes and the degraded "host" impl pin stay
    on the unfused kernels.
    """
    n = table.num_rows
    if batch is None:
        batch = pack_reads(table)
    if n_read_groups is None:
        n_read_groups = int(np.asarray(batch.read_group).max(initial=0)) + 1
    sharded = mesh is not None and mesh.size > 1 and \
        batch.n_reads % mesh.size == 0
    # the ragged/paged layouts are unsharded dispatches (the plan
    # demotes them on multi-shard meshes — decide_plan's capable gates)
    lay = layout if layout in ("ragged", "paged") and not sharded \
        else "padded"
    slab = _count_slab_rows()
    if not sharded and batch.n_reads > slab:
        acc = None
        for s in range(0, batch.n_reads, slab):
            e = min(s + slab, batch.n_reads)
            out = _count_tables_one(table.slice(s, max(min(e, n) - s, 0)),
                                    batch.row_slice(s, e),
                                    snp_table, n_read_groups, None,
                                    donate=donate,
                                    md_info=None if md_info is None
                                    else slice_md_info(md_info, s, e),
                                    layout=lay, paged_box=paged_box,
                                    fused=fused)
            acc = out if acc is None else tuple(
                a + b for a, b in zip(acc, out))
        return acc
    return _count_tables_one(table, batch, snp_table, n_read_groups,
                             mesh if sharded else None,
                             device_batch=device_batch, donate=donate,
                             md_info=md_info, layout=lay,
                             paged_box=paged_box, fused=fused)


def _count_tables_one(table: pa.Table, batch: ReadBatch,
                      snp_table: Optional[SnpTable],
                      n_read_groups: int, mesh,
                      device_batch: Optional[ReadBatch] = None,
                      donate: bool = False,
                      md_info=None, layout: str = "padded",
                      paged_box: Optional[dict] = None,
                      fused: bool = False):
    """One slab's pass-1 count (the pre-slab body of
    :func:`count_tables_device`)."""
    n = table.num_rows
    has_md = np.zeros(batch.n_reads, bool)
    if md_info is None:
        from ..ops.pileup import _col_valid
        has_md[:n] = _col_valid(table.column("mismatchingPositions"))
    else:
        has_md[:n] = md_info[0][:n]
    flags_np = np.asarray(batch.flags)
    usable = usable_read_mask(flags_np, has_md) & np.asarray(batch.valid)

    state = np.full((batch.n_reads, batch.max_len), STATE_MASKED, np.int8)
    state[:n] = mismatch_state(table, batch, snp_table,
                               device_batch=device_batch,
                               md_info=md_info)
    dev = device_batch if device_batch is not None else batch

    rt = RecalTable(n_read_groups=max(n_read_groups, 1),
                    max_read_len=batch.max_len)
    sharded = mesh is not None
    if layout in ("ragged", "paged") and not sharded:
        # the ragged layout (docs/ARCHITECTURE.md §6g): flatten the
        # padded planes by true lengths and count over T real bases —
        # the per-read cycle walk rides the prefix-sum row index, so no
        # padded lane (row slack OR past-length lane) reaches the kernel
        from ..packing import ragged_from_batch, shape_rung
        from ..platform import is_tpu_backend
        from .count_pallas import (BLOCK_ELEMS, count_kernel_ragged, fits,
                                   flatten_state)
        if fits(rt.n_qual_rg, rt.n_cycle):
            # pad the flat planes to a canonical geometric rung (the
            # row-ladder recurrence over BLOCK_ELEMS multiples) — exact
            # per-chunk T would mint a fresh compiled shape per chunk,
            # the recompile tax the rung machinery exists to kill
            rl = np.minimum(np.asarray(batch.read_len, np.int64),
                            batch.max_len)
            t_rung = shape_rung(max(int(rl.sum()), 1), BLOCK_ELEMS)
            rb = ragged_from_batch(batch, pad_bases_to=t_rung)
            state_flat = flatten_state(state, rb.read_len,
                                       len(rb.bases_flat))
            if layout == "paged" and paged_box is not None:
                # resident paged planes (docs/ARCHITECTURE.md §6l):
                # ship only this chunk's live pages; a thrashing pool
                # answers None and the ragged concat runs instead
                out = _paged_count(paged_box, rb, state_flat, usable,
                                   rt, batch.max_len, fused=fused)
                if out is not None:
                    return out
            if fused:
                # fused_device plan route (ops/megapass): the ragged
                # mega-pass with only the bqsr leg selected — the same
                # flat pack + fold under one compiled program
                from ..ops.megapass import megapass_from_ragged
                return megapass_from_ragged(
                    rb, want=("bqsr",), state_flat=state_flat,
                    usable=usable, n_qual_rg=rt.n_qual_rg,
                    n_cycle=rt.n_cycle, max_read_len=batch.max_len,
                    impl="pallas" if is_tpu_backend() else "xla",
                    interpret=not is_tpu_backend())["bqsr"]
            return count_kernel_ragged(
                rb, state_flat, usable, n_qual_rg=rt.n_qual_rg,
                n_cycle=rt.n_cycle, max_read_len=batch.max_len,
                interpret=not is_tpu_backend())
        # covariate ranges past the packed-word budget: padded fallback.
        # The ragged feed projects bases/quals OFF the device batch
        # (pipeline._P2_DEV_COLS_RAGGED) — the padded kernels below
        # need them, so fall back to the host batch's columns
        dev = batch
    impl = _count_impl(sharded=sharded)
    if impl in ("chain", "matmul") and \
            os.environ.get(_COUNT_IMPL_ENV, "auto") == "auto":
        # auto on a TPU backend: prefer the Pallas rows kernel once it
        # proves itself exact at this geometry IN this configuration
        # (the sharded check runs the shard_map wrapper itself)
        impl = _tpu_auto_upgrade(impl, rt.n_qual_rg, rt.n_cycle,
                                 rt.n_read_groups,
                                 mesh if sharded else None)
    if fused and not sharded and impl != "host":
        # fused_device plan route, padded layout: the mega-pass bqsr
        # leg (ops/megapass) — respects the degraded "host" env pin and
        # the multi-shard demotion above
        from ..ops.megapass import megapass_bqsr
        from ..platform import is_tpu_backend
        from .count_pallas import fits
        if fits(rt.n_qual_rg, rt.n_cycle):
            return megapass_bqsr(
                jnp.asarray(dev.bases), jnp.asarray(dev.quals),
                jnp.asarray(dev.read_len), jnp.asarray(dev.flags),
                jnp.asarray(dev.read_group), jnp.asarray(state),
                jnp.asarray(usable), n_qual_rg=rt.n_qual_rg,
                n_cycle=rt.n_cycle,
                impl="pallas" if is_tpu_backend() else "xla",
                interpret=not is_tpu_backend())
    if impl == "host":
        out = _count_tables_host(batch, state, usable,
                                 n_qual_rg=rt.n_qual_rg,
                                 n_cycle=rt.n_cycle)
    elif impl in ("pallas", "pallas_rows"):
        from .count_pallas import (count_kernel_pallas,
                                   count_kernel_pallas_rows, fits)
        from ..platform import is_tpu_backend
        assert fits(rt.n_qual_rg, rt.n_cycle), \
            "covariate ranges exceed the packed-word budget"
        variant = "flat" if impl == "pallas" else "rows"
        # pallas_call manages its own VMEM streaming; input donation is
        # not threaded through the Mosaic wrappers
        args = (jnp.asarray(dev.bases), jnp.asarray(dev.quals),
                jnp.asarray(dev.read_len), jnp.asarray(dev.flags),
                jnp.asarray(dev.read_group), jnp.asarray(state),
                jnp.asarray(usable))
        if sharded:
            out = _sharded_pallas_fn(mesh, rt.n_qual_rg, rt.n_cycle,
                                     variant,
                                     not is_tpu_backend())(*args)
        else:
            kern = count_kernel_pallas if impl == "pallas" \
                else count_kernel_pallas_rows
            out = kern(*args, n_qual_rg=rt.n_qual_rg,
                       n_cycle=rt.n_cycle,
                       interpret=not is_tpu_backend())
    else:
        kernel = {"matmul": _count_kernel_matmul,
                  "chain": _count_kernel_chain}.get(impl, _count_kernel)
        args = (jnp.asarray(dev.bases), jnp.asarray(dev.quals),
                jnp.asarray(dev.read_len), jnp.asarray(dev.flags),
                jnp.asarray(dev.read_group), jnp.asarray(state),
                jnp.asarray(usable))
        if impl == "chain":
            # host-driven dispatch loop; runs outside shard_map by design
            # (and keeps its own donated carry — see the step jit)
            out = kernel(*args, n_qual_rg=rt.n_qual_rg,
                         n_cycle=rt.n_cycle)
        elif sharded:
            out = _sharded_count_fn(kernel, mesh, rt.n_qual_rg,
                                    rt.n_cycle, donate)(*args)
        else:
            fn = _donating_count_fn(kernel) if donate else kernel
            out = fn(*args, n_qual_rg=rt.n_qual_rg,
                     n_cycle=rt.n_cycle)
    return out


def tables_to_recal(out, n_read_groups: int, max_read_len: int
                    ) -> RecalTable:
    """Fold (possibly chunk-accumulated) count tensors into a RecalTable."""
    rt = RecalTable(n_read_groups=max(n_read_groups, 1),
                    max_read_len=max_read_len)
    (qual_obs, qual_mm, cycle_obs, cycle_mm, ctx_obs, ctx_mm, qhist) = \
        [np.asarray(o) for o in out]
    rt.qual_obs += qual_obs.astype(np.int64)
    rt.qual_mm += qual_mm.astype(np.int64)
    rt.cycle_obs += cycle_obs.reshape(rt.n_qual_rg, rt.n_cycle).astype(np.int64)
    rt.cycle_mm += cycle_mm.reshape(rt.n_qual_rg, rt.n_cycle).astype(np.int64)
    rt.ctx_obs += ctx_obs.reshape(rt.n_qual_rg, -1).astype(np.int64)
    rt.ctx_mm += ctx_mm.reshape(rt.n_qual_rg, -1).astype(np.int64)
    # exact f64 expectation from the integer qual histogram — identical for
    # every backend and sharding (order-independent integer psum)
    rt.expected_mismatch += float(
        qhist.astype(np.float64) @ np.asarray(PHRED_TO_ERROR))
    return rt


def compute_table(table: pa.Table, batch: Optional[ReadBatch] = None,
                  snp_table: Optional[SnpTable] = None,
                  n_read_groups: Optional[int] = None,
                  mesh=None) -> RecalTable:
    """Pass 1: build the RecalTable from usable reads (one-chunk form).

    With ``mesh``, the counting kernel runs under shard_map across the
    devices (rows must divide the mesh; streaming_transform's bucketed
    pads guarantee it) and the count tensors psum over ICI.
    """
    if batch is None:
        batch = pack_reads(table)
    if n_read_groups is None:
        n_read_groups = int(np.asarray(batch.read_group).max(initial=0)) + 1
    out = count_tables_device(table, batch, snp_table,
                              n_read_groups=n_read_groups, mesh=mesh)
    return tables_to_recal(out, n_read_groups, batch.max_len)


def _recalibrated_qual(reported, k, cyc, ctx, rg_delta, qual_delta,
                       cycle_delta, ctx_delta, rg_of_qualrg):
    """RecalUtil.recalibrate (:31-42): reported error + the delta chain
    -> truncated new phred.  THE one copy of the formula — both the
    per-base kernel and the LUT grid builder evaluate it, which is what
    makes their bit-identity structural rather than hand-synchronized.
    Flat gathers keep the lookup O(elements), never [.., NC]."""
    n_cycle = cycle_delta.shape[1]
    n_ctx = ctx_delta.shape[1]
    p = reported + rg_delta[rg_of_qualrg[k]] + qual_delta[k] + \
        cycle_delta.reshape(-1)[k * n_cycle + cyc] + \
        ctx_delta.reshape(-1)[k * n_ctx + ctx]
    from .covariates import MIN_REASONABLE_ERROR
    p = jnp.clip(p, MIN_REASONABLE_ERROR, 1.0)
    return jnp.trunc(-10.0 * jnp.log10(p)).astype(jnp.int8)


#: the LUT's raw-qual axis is sized from the SAME table the per-base
#: kernel gathers ``reported`` from, so the two paths share one qual
#: domain by construction (a 128-entry axis silently clipped quals the
#: kernel path would have looked up past 127 — round-5 advisor)
_LUT_QUALS = int(PHRED_TO_ERROR.shape[0])


def _require_int8_quals(quals) -> None:
    """Both apply entry points take int8 quals (the packer's dtype).

    int8 tops out at 127, which is what makes the LUT's raw-qual clip
    and ``_apply_kernel``'s 0..255 reported-error clip agree on every
    reachable value — enforce it at trace time so the bit-identity is a
    checked contract, not an accident of current callers."""
    if quals.dtype != jnp.int8:
        raise TypeError(
            f"BQSR apply kernels take int8 quals, got {quals.dtype}: "
            "wider quals would index past the LUT's qual axis and break "
            "LUT/per-base bit-identity")


@partial(jax.jit, static_argnames=())
def _apply_kernel(bases, quals, read_len, flags, read_group, recal_mask,
                  rg_delta, qual_delta, cycle_delta, ctx_delta, rg_of_qualrg):
    """Pass-2: per-base gathers from the delta tables -> new quals."""
    _require_int8_quals(quals)
    cov = covariate_tensors(bases, quals, read_len, flags, read_group)
    Q = qual_delta.shape[0]
    k = jnp.clip(cov["qual_rg"], 0, Q - 1)
    cyc = jnp.clip(cov["cycle_idx"], 0, cycle_delta.shape[1] - 1)
    err_lut = jnp.asarray(PHRED_TO_ERROR)
    reported = err_lut[jnp.clip(quals.astype(jnp.int32), 0, 255)]
    new_q = _recalibrated_qual(reported, k, cyc, cov["context"], rg_delta,
                               qual_delta, cycle_delta, ctx_delta,
                               rg_of_qualrg)
    recal = cov["in_window"] & recal_mask[:, None]
    return jnp.where(recal, new_q, quals)


@partial(jax.jit, static_argnames=("n_rg",))
def _build_apply_lut(n_rg: int, rg_delta, qual_delta, cycle_delta,
                     ctx_delta, rg_of_qualrg):
    """[_LUT_QUALS*n_rg*n_cycle*17] int8 new-qual table: the recalibrated
    qual is a pure function of (raw qual, read group, cycle bin,
    context), so evaluate ``_apply_kernel``'s EXACT expression once over
    the enumerated grid — same jnp ops, same backend, same precision —
    and pass 2 becomes one int8 gather per base.  Bit-identity with the
    per-base kernel is by construction (and differential-pinned).

    Grid axes carry raw qual and read group separately (not the fused
    qual_rg index): ``reported`` reads the RAW qual while the delta
    lookups read the clipped fused index, so a k-only table would alias
    quals >= MAX_REASONABLE_QSCORE across neighboring read groups.  The
    qual axis spans the whole PHRED_TO_ERROR domain (``_LUT_QUALS``), the
    same table the per-base kernel gathers from.
    """
    Q = qual_delta.shape[0]
    n_cycle = cycle_delta.shape[1]
    n_ctx = ctx_delta.shape[1]
    q = jnp.arange(_LUT_QUALS, dtype=jnp.int32)[:, None, None, None]
    rg = jnp.arange(n_rg, dtype=jnp.int32)[None, :, None, None]
    cyc = jnp.arange(n_cycle, dtype=jnp.int32)[None, None, :, None]
    ctx = jnp.arange(n_ctx, dtype=jnp.int32)[None, None, None, :]
    k = jnp.clip(q + MAX_REASONABLE_QSCORE * rg, 0, Q - 1)
    err_lut = jnp.asarray(PHRED_TO_ERROR)
    reported = err_lut[q]
    return _recalibrated_qual(reported, k, cyc, ctx, rg_delta, qual_delta,
                              cycle_delta, ctx_delta,
                              rg_of_qualrg).reshape(-1)


@partial(jax.jit, static_argnames=("n_rg",))
def _apply_kernel_lut(bases, quals, read_len, flags, read_group,
                      recal_mask, lut, n_rg: int):
    """Pass-2 via the precomputed new-qual LUT: covariates + ONE gather
    (vs three flat delta gathers + log10 per base in ``_apply_kernel``)."""
    from .covariates import N_CONTEXT
    _require_int8_quals(quals)
    cov = covariate_tensors(bases, quals, read_len, flags, read_group)
    n_ctx = N_CONTEXT
    n_cycle = lut.shape[0] // (_LUT_QUALS * n_rg * n_ctx)
    iq = jnp.clip(quals.astype(jnp.int32), 0, _LUT_QUALS - 1)
    irg = jnp.clip(jnp.maximum(read_group, 0), 0, n_rg - 1)[:, None]
    cyc = jnp.clip(cov["cycle_idx"], 0, n_cycle - 1)
    idx = ((iq * n_rg + irg) * n_cycle + cyc) * n_ctx + cov["context"]
    new_q = lut[idx]
    recal = cov["in_window"] & recal_mask[:, None]
    return jnp.where(recal, new_q, quals)


@lru_cache(maxsize=8)
def _sharded_apply_fn(mesh, n_rg: int, donate: bool = False):
    """Cached shard_map+jit of the LUT apply kernel: reads shard over
    the mesh, the LUT replicates (the reference's broadcast variable).

    ``donate=True`` donates the 6 per-chunk read columns — the quals
    input has the output's exact shape and dtype, so the rewritten quals
    alias the arriving buffer instead of allocating a second [N, L] per
    chunk.  The replicated LUT (arg 6) is reused across chunks and never
    donated."""
    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import READS_AXIS
    spec = P(READS_AXIS)
    return jax.jit(shard_map(
        partial(_apply_kernel_lut, n_rg=n_rg), mesh=mesh,
        in_specs=(spec,) * 6 + (P(),), out_specs=spec),
        donate_argnums=tuple(range(6)) if donate else ())


@lru_cache(maxsize=4)
def _donating_apply_lut():
    """Unsharded LUT apply with the 6 per-chunk args donated (the LUT
    stays undonated — it is reused across slabs and chunks)."""
    return jax.jit(getattr(_apply_kernel_lut, "__wrapped__",
                           _apply_kernel_lut),
                   static_argnames=("n_rg",),
                   donate_argnums=tuple(range(6)))


def apply_table(rt: RecalTable, table: pa.Table,
                batch: Optional[ReadBatch] = None, mesh=None,
                device_batch: Optional[ReadBatch] = None,
                donate: bool = False) -> pa.Table:
    """Pass 2: rewrite the qual strings of recalibratable reads.

    With ``mesh``, the gather kernel shard_maps over the read axis (the
    delta tables replicate — the reference's broadcast variable).
    ``device_batch``/``donate`` are the streaming executor's prefetched
    feed and HBM-reuse knobs (see count_tables_device — device_batch is
    consumed by the monolithic sharded path only)."""
    n = table.num_rows
    if batch is None:
        batch = pack_reads(table)
    fin = rt.finalize()
    flags_np = np.asarray(batch.flags)
    recal_mask = ((flags_np & S.FLAG_UNMAPPED) == 0) & \
        ((flags_np & S.FLAG_SECONDARY) == 0) & \
        ((flags_np & S.FLAG_DUPLICATE) == 0) & np.asarray(batch.valid)

    # one small grid eval per chunk turns pass 2 into covariates + a
    # single int8 gather (the delta math and log10 happen 128*n_rg*NC*17
    # times instead of once per base); bit-identical to _apply_kernel by
    # construction — the grid runs the same expression on the same
    # backend (differential-pinned in tests/test_bqsr_apply_lut.py)
    n_rg = max(rt.n_read_groups, 1)
    lut = _build_apply_lut(
        n_rg, jnp.asarray(fin.rg_delta), jnp.asarray(fin.qual_delta),
        jnp.asarray(fin.cycle_delta), jnp.asarray(fin.ctx_delta),
        jnp.asarray(fin.rg_of_qualrg))

    def slab_args(b, mask):
        return (jnp.asarray(b.bases), jnp.asarray(b.quals),
                jnp.asarray(b.read_len), jnp.asarray(b.flags),
                jnp.asarray(b.read_group), jnp.asarray(mask), lut)

    sharded = mesh is not None and mesh.size > 1 and \
        batch.n_reads % mesh.size == 0
    slab = _count_slab_rows()
    if sharded:
        dev = device_batch if device_batch is not None else batch
        new_quals = np.asarray(_sharded_apply_fn(mesh, n_rg, donate)(
            *slab_args(dev, recal_mask)))[:n]
    elif batch.n_reads > slab:
        # same bounded-working-set walk as pass 1 (the apply gathers
        # materialize the identical [rows, L] covariate tensors); per-row
        # output, so slab concatenation is trivially the monolithic result
        fn = _donating_apply_lut() if donate else _apply_kernel_lut
        parts = [np.asarray(fn(
            *slab_args(batch.row_slice(s, min(s + slab, batch.n_reads)),
                       recal_mask[s:s + slab]), n_rg=n_rg))
            for s in range(0, batch.n_reads, slab)]
        new_quals = np.concatenate(parts, axis=0)[:n]
    else:
        dev = device_batch if device_batch is not None else batch
        fn = _donating_apply_lut() if donate else _apply_kernel_lut
        new_quals = np.asarray(fn(
            *slab_args(dev, recal_mask), n_rg=n_rg))[:n]

    read_len = np.asarray(batch.read_len[:n], np.int64)
    old_col = table.column("qual").combine_chunks()
    nulls = np.asarray(old_col.is_null()) if old_col.null_count \
        else np.zeros(n, bool)
    # vectorized string rebuild: the apply kernel already returns the
    # original qual for non-recalibrated bases/rows, so every non-null row's
    # new string is just its (new_quals + 33) prefix — build the Arrow
    # column straight from an offsets+data buffer pair, no per-read loop
    lens = np.where(nulls, 0, read_len)
    offsets = np.zeros(n + 1, np.int32)
    np.cumsum(lens, out=offsets[1:])
    mat = (new_quals.astype(np.int16) + 33).astype(np.uint8)
    L = mat.shape[1] if mat.ndim == 2 else 0
    keep = (np.arange(L)[None, :] < lens[:, None])
    data = mat[keep].tobytes()
    buffers = [None, pa.py_buffer(offsets), pa.py_buffer(data)]
    null_count = int(nulls.sum())
    if null_count:
        buffers[0] = pa.py_buffer(
            np.packbits(~nulls, bitorder="little").tobytes())
    new_col = pa.Array.from_buffers(pa.string(), n, buffers,
                                    null_count=null_count)
    idx = table.column_names.index("qual")
    return table.set_column(idx, "qual", new_col)


def recalibrate_base_qualities(table: pa.Table,
                               snp_table: Optional[SnpTable] = None
                               ) -> pa.Table:
    """adamBQSR (AdamRDDFunctions.scala:104-107): compute + apply."""
    batch = pack_reads(table)
    rt = compute_table(table, batch, snp_table)
    return apply_table(rt, table, batch)
