"""BQSR covariates as batched device tensors.

Re-designs ``rdd/recalibration/StandardCovariate.scala`` +
``ReadCovariates.scala``: instead of per-read iterators allocating Int arrays,
every covariate is an [N, L] tensor computed in one jitted kernel.

Covariates (all exactly as the reference computes them):
  * qualByRG (StandardCovariate.scala:25-32): qual + 60 * recordGroupId;
  * DiscreteCycle (:39-48): forward 1..len, reverse len..1, negated for
    second-of-pair;
  * BaseContext size 2 (:50-104): code 0 for the first in-window base or any
    window containing a non-ACGT base, else 1 + 4*prev + cur.  For reverse
    strand reads the reference takes a slice of the reverse-complemented
    sequence whose element order is *mirrored* relative to the per-base
    iteration (:75-79 with ReadCovariates.scala:50-60) — we reproduce that
    pairing bit-for-bit, since apply-time lookups use the same pairing.

The low-quality end clip (ReadCovariates.scala:37-39: leading/trailing run of
quals <= 2 excluded) becomes the ``in_window`` mask.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from .. import schema as S

MAX_REASONABLE_QSCORE = 60     # RecalUtil.Constants (RecalUtil.scala:26)
MIN_REASONABLE_ERROR = 10.0 ** (-MAX_REASONABLE_QSCORE / 10.0)
MIN_QUALITY = 2                # ReadCovariates.scala:31
CONTEXT_SIZE = 2
N_CONTEXT = 4 ** CONTEXT_SIZE + 1   # 0 reserved for "no context"


def clip_window(quals, read_len):
    """(start, end) [N] of the window after trimming leading/trailing runs of
    quals <= MIN_QUALITY (ReadCovariates.scala:37-39)."""
    L = quals.shape[1]
    offs = jnp.arange(L)
    in_read = offs[None, :] < read_len[:, None]
    lowq = (quals <= MIN_QUALITY) & in_read
    # leading run: count while cumprod of lowq stays 1
    start = jnp.sum(jnp.cumprod(lowq.astype(jnp.int32), axis=1), axis=1)
    # trailing run within the read: reverse scan over in-read positions
    lowq_or_pad = lowq | ~in_read
    trail = jnp.cumprod(jnp.flip(lowq_or_pad.astype(jnp.int32), 1), axis=1)
    trailing = jnp.sum(trail, axis=1) - (L - read_len)
    end = read_len - trailing
    return start, jnp.maximum(end, start)


@partial(jax.jit, static_argnames=())
def covariate_tensors(bases, quals, read_len, flags, read_group):
    """All per-base covariate tensors.

    Returns dict of [N, L] tensors: in_window (bool), qual_rg, cycle_idx
    (cycle + L, so always >= 0), context (0..16).
    """
    N, L = bases.shape
    offs = jnp.arange(L)
    start, end = clip_window(quals, read_len)
    in_window = (offs[None, :] >= start[:, None]) & \
        (offs[None, :] < end[:, None])

    qual_rg = quals.astype(jnp.int32) + \
        MAX_REASONABLE_QSCORE * jnp.maximum(read_group, 0)[:, None]

    reverse = (flags & S.FLAG_REVERSE) != 0
    second = ((flags & S.FLAG_PAIRED) != 0) & \
        ((flags & S.FLAG_SECOND_OF_PAIR) != 0)
    cycle = jnp.where(reverse[:, None], read_len[:, None] - offs[None, :],
                      offs[None, :] + 1)
    cycle = jnp.where(second[:, None], -cycle, cycle)
    cycle_idx = cycle + L

    b = bases.astype(jnp.int32)
    valid = (b >= 0) & (b < 4)

    # forward: context of base i = enc(b[i-1], b[i]) when both valid
    prev_idx = jnp.maximum(offs - 1, 0)
    fwd_ok = valid[:, prev_idx] & valid & (offs > 0)[None, :]
    fwd = jnp.where(fwd_ok, 1 + 4 * b[:, prev_idx] + b, 0)
    # reverse (mirrored pairing, see module docstring): element i pairs
    # with p = end-1-(i-start); context = enc(compl(b[p+1]), compl(b[p])).
    # That value is a pure complement-swap of the FORWARD context at
    # p+1 — enc(y, x) -> enc(3-x, 3-y) is the 17-entry involution below —
    # so one gather of fwd replaces four take_along_axis gathers (the
    # dominant cost of this kernel at [N, L] scale).  fwd[p+1] is
    # nonzero exactly when valid[p] & valid[p+1] & (p+1 > 0); the p >= 0
    # boundary is subsumed (p = -1 means p+1 = 0, where fwd is 0), and
    # p+1 < end is the one condition applied on top.
    g = jnp.arange(N_CONTEXT)
    y, x = (g - 1) // 4, (g - 1) % 4
    compl_swap = jnp.where(g == 0, 0, 1 + 4 * (3 - x) + (3 - y))
    p = end[:, None] - 1 - (offs[None, :] - start[:, None])
    p1_safe = jnp.clip(p + 1, 0, L - 1)
    fwd_at_p1 = jnp.take_along_axis(fwd, p1_safe, 1)
    rev = jnp.where(p + 1 < end[:, None], compl_swap[fwd_at_p1], 0)
    context = jnp.where(reverse[:, None], rev, fwd)
    # the first in-window base never has a context
    context = jnp.where(offs[None, :] == start[:, None], 0, context)
    return dict(in_window=in_window, qual_rg=qual_rg, cycle_idx=cycle_idx,
                context=context, window_start=start, window_end=end)
