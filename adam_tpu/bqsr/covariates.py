"""BQSR covariates as batched device tensors.

Re-designs ``rdd/recalibration/StandardCovariate.scala`` +
``ReadCovariates.scala``: instead of per-read iterators allocating Int arrays,
every covariate is an [N, L] tensor computed in one jitted kernel.

Covariates (all exactly as the reference computes them):
  * qualByRG (StandardCovariate.scala:25-32): qual + 60 * recordGroupId;
  * DiscreteCycle (:39-48): forward 1..len, reverse len..1, negated for
    second-of-pair;
  * BaseContext size 2 (:50-104): code 0 for the first in-window base or any
    window containing a non-ACGT base, else 1 + 4*prev + cur.  For reverse
    strand reads the reference takes a slice of the reverse-complemented
    sequence whose element order is *mirrored* relative to the per-base
    iteration (:75-79 with ReadCovariates.scala:50-60) — we reproduce that
    pairing bit-for-bit, since apply-time lookups use the same pairing.

The low-quality end clip (ReadCovariates.scala:37-39: leading/trailing run of
quals <= 2 excluded) becomes the ``in_window`` mask.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from .. import schema as S

MAX_REASONABLE_QSCORE = 60     # RecalUtil.Constants (RecalUtil.scala:26)
MIN_REASONABLE_ERROR = 10.0 ** (-MAX_REASONABLE_QSCORE / 10.0)
MIN_QUALITY = 2                # ReadCovariates.scala:31
CONTEXT_SIZE = 2
N_CONTEXT = 4 ** CONTEXT_SIZE + 1   # 0 reserved for "no context"


def clip_window(quals, read_len):
    """(start, end) [N] of the window after trimming leading/trailing runs of
    quals <= MIN_QUALITY (ReadCovariates.scala:37-39)."""
    L = quals.shape[1]
    offs = jnp.arange(L)
    in_read = offs[None, :] < read_len[:, None]
    lowq = (quals <= MIN_QUALITY) & in_read
    # leading run: count while cumprod of lowq stays 1
    start = jnp.sum(jnp.cumprod(lowq.astype(jnp.int32), axis=1), axis=1)
    # trailing run within the read: reverse scan over in-read positions
    lowq_or_pad = lowq | ~in_read
    trail = jnp.cumprod(jnp.flip(lowq_or_pad.astype(jnp.int32), 1), axis=1)
    trailing = jnp.sum(trail, axis=1) - (L - read_len)
    end = read_len - trailing
    return start, jnp.maximum(end, start)


@partial(jax.jit, static_argnames=())
def covariate_tensors(bases, quals, read_len, flags, read_group):
    """All per-base covariate tensors.

    Returns dict of [N, L] tensors: in_window (bool), qual_rg, cycle_idx
    (cycle + L, so always >= 0), context (0..16).
    """
    N, L = bases.shape
    offs = jnp.arange(L)
    start, end = clip_window(quals, read_len)
    in_window = (offs[None, :] >= start[:, None]) & \
        (offs[None, :] < end[:, None])

    qual_rg = quals.astype(jnp.int32) + \
        MAX_REASONABLE_QSCORE * jnp.maximum(read_group, 0)[:, None]

    reverse = (flags & S.FLAG_REVERSE) != 0
    second = ((flags & S.FLAG_PAIRED) != 0) & \
        ((flags & S.FLAG_SECOND_OF_PAIR) != 0)
    cycle = jnp.where(reverse[:, None], read_len[:, None] - offs[None, :],
                      offs[None, :] + 1)
    cycle = jnp.where(second[:, None], -cycle, cycle)
    cycle_idx = cycle + L

    b = bases.astype(jnp.int32)
    valid = (b >= 0) & (b < 4)

    # forward: context of base i = enc(b[i-1], b[i]) when both valid
    prev_idx = jnp.maximum(offs - 1, 0)
    fwd_ok = valid[:, prev_idx] & valid & (offs > 0)[None, :]
    fwd = jnp.where(fwd_ok, 1 + 4 * b[:, prev_idx] + b, 0)
    # reverse (mirrored pairing, see module docstring): element i pairs
    # with p = end-1-(i-start); context = enc(compl(b[p+1]), compl(b[p])).
    # That value is a pure complement-swap of the FORWARD context at
    # p+1 — enc(y, x) -> enc(3-x, 3-y) is the 17-entry involution below —
    # so one gather of fwd replaces four take_along_axis gathers (the
    # dominant cost of this kernel at [N, L] scale).  fwd[p+1] is
    # nonzero exactly when valid[p] & valid[p+1] & (p+1 > 0); the p >= 0
    # boundary is subsumed (p = -1 means p+1 = 0, where fwd is 0), and
    # p+1 < end is the one condition applied on top.
    g = jnp.arange(N_CONTEXT)
    y, x = (g - 1) // 4, (g - 1) % 4
    compl_swap = jnp.where(g == 0, 0, 1 + 4 * (3 - x) + (3 - y))
    p = end[:, None] - 1 - (offs[None, :] - start[:, None])
    p1_safe = jnp.clip(p + 1, 0, L - 1)
    fwd_at_p1 = jnp.take_along_axis(fwd, p1_safe, 1)
    rev = jnp.where(p + 1 < end[:, None], compl_swap[fwd_at_p1], 0)
    context = jnp.where(reverse[:, None], rev, fwd)
    # the first in-window base never has a context
    context = jnp.where(offs[None, :] == start[:, None], 0, context)
    return dict(in_window=in_window, qual_rg=qual_rg, cycle_idx=cycle_idx,
                context=context, window_start=start, window_end=end)


@partial(jax.jit, static_argnames=("n_rows", "max_read_len"))
def covariate_flat(bases_flat, quals_flat, row_of, pos_of, row_starts,
                   read_len, flags, read_group, n_bases, *,
                   n_rows: int, max_read_len: int):
    """:func:`covariate_tensors` over the RAGGED layout: concatenated
    ``[T]`` planes + the prefix-sum row index (packing.RaggedBatch).

    Same covariate definitions BIT FOR BIT — the per-read cycle walk is
    driven by true lengths via ``row_of``/``pos_of``, so no padded-lane
    element is ever computed or masked.  The window clip becomes two
    segment reductions (first/last non-low-qual position per read); the
    reverse-strand context gathers through ``row_starts`` instead of
    ``take_along_axis``.  Slack elements past ``n_bases`` (their
    ``row_of`` is 0) contribute reduction-neutral values and return
    ``in_window=False``.

    ``max_read_len`` is the cycle-axis offset — the padded form uses its
    plane width ``L``, which the product packer pins to the RecalTable's
    ``max_read_len``; here the table geometry is passed explicitly.
    Returns flat [T] tensors: ``in_window``, ``qual_rg``, ``cycle_idx``,
    ``context``, plus per-read ``window_start``/``window_end``.
    """
    T = bases_flat.shape[0]
    live = jnp.arange(T) < n_bases
    rlen = read_len[row_of]
    quals = quals_flat.astype(jnp.int32)

    # clip window (ReadCovariates.scala:37-39) as segment reductions:
    # ws = first position with qual > MIN_QUALITY (read_len when none),
    # we = last such position + 1 — identical to the padded cumprod form
    lowq = quals <= MIN_QUALITY
    big = jnp.int32(1 << 30)
    ws = jnp.minimum(jax.ops.segment_min(
        jnp.where(live & ~lowq, pos_of, big), row_of,
        num_segments=n_rows), read_len)
    last = jax.ops.segment_max(
        jnp.where(live & ~lowq, pos_of, -1), row_of,
        num_segments=n_rows)
    we = jnp.maximum(last + 1, ws)
    in_window = (pos_of >= ws[row_of]) & (pos_of < we[row_of]) & live

    qual_rg = quals + MAX_REASONABLE_QSCORE * \
        jnp.maximum(read_group, 0)[row_of]

    reverse = (flags & S.FLAG_REVERSE) != 0
    second = ((flags & S.FLAG_PAIRED) != 0) & \
        ((flags & S.FLAG_SECOND_OF_PAIR) != 0)
    rev_b = reverse[row_of]
    cycle = jnp.where(rev_b, rlen - pos_of, pos_of + 1)
    cycle = jnp.where(second[row_of], -cycle, cycle)
    cycle_idx = cycle + max_read_len

    b = bases_flat.astype(jnp.int32)
    valid = (b >= 0) & (b < 4)
    # forward context: the previous flat element IS the previous base of
    # the same read whenever pos > 0 (reads concatenate contiguously)
    prev = jnp.maximum(jnp.arange(T) - 1, 0)
    fwd_ok = valid[prev] & valid & (pos_of > 0)
    fwd = jnp.where(fwd_ok, 1 + 4 * b[prev] + b, 0)
    # reverse (mirrored pairing — covariate_tensors' complement-swap of
    # the forward context at p+1, gathered within the read's own span)
    g = jnp.arange(N_CONTEXT)
    y, x = (g - 1) // 4, (g - 1) % 4
    compl_swap = jnp.where(g == 0, 0, 1 + 4 * (3 - x) + (3 - y))
    ws_b, we_b = ws[row_of], we[row_of]
    p = we_b - 1 - (pos_of - ws_b)
    p1_in_row = jnp.clip(p + 1, 0, jnp.maximum(rlen - 1, 0))
    fwd_at_p1 = fwd[jnp.clip(row_starts[row_of] + p1_in_row, 0, T - 1)]
    rev = jnp.where(p + 1 < we_b, compl_swap[fwd_at_p1], 0)
    context = jnp.where(rev_b, rev, fwd)
    context = jnp.where(pos_of == ws_b, 0, context)
    return dict(in_window=in_window, qual_rg=qual_rg, cycle_idx=cycle_idx,
                context=context, window_start=ws, window_end=we)
