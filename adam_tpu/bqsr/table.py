"""The recalibration count table as dense tensors.

Re-designs ``rdd/recalibration/RecalTable.scala`` (nested mutable hash maps of
ErrorCount, merged pairwise on the driver :23-215) as dense int64 count
tensors indexed by the qualByRG stratification and covariate values:

    qual_obs/qual_mm   [Q]            Q = MAX_REASONABLE_QSCORE * nRG + 94
    cycle_obs/cycle_mm [Q, 2L+1]      cycle c -> index c + L
    ctx_obs/ctx_mm     [Q, 17]

Counts accumulate on device via scatter-add and merge across shards with a
single ``psum`` — the reference's ``aggregate(RecalTable)(+, ++)`` tree
reduce to the driver (RecalibrateBaseQualities.scala:52-64) becomes one
collective over ICI.

Finalization and the delta hierarchy (readgroup -> qual -> covariates) follow
RecalTable.finalizeTable/getErrorRateShifts (:118-152) exactly, including the
``(qualByRG - 1) / MAX_REASONABLE_QSCORE`` truncating-division read-group
regrouping (:121,129 — a quirk for qual-0 bases of non-zero read groups that
we reproduce for parity).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from ..util.phred import PHRED_TO_ERROR
from .covariates import (MAX_REASONABLE_QSCORE, MIN_REASONABLE_ERROR,
                         N_CONTEXT)


def _error_prob(mm: np.ndarray, obs: np.ndarray, fallback: np.ndarray):
    """ErrorCount.getErrorProb (RecalTable.scala:199-203): max(1e-6, mm/obs)
    when observed, else the caller's fallback."""
    safe = np.maximum(obs, 1)
    p = np.maximum(MIN_REASONABLE_ERROR, mm / safe)
    return np.where(obs > 0, p, fallback)


def _rg_of_qualrg(k: np.ndarray) -> np.ndarray:
    """(k - 1) / 60 with Scala's truncate-toward-zero division."""
    return np.where(k >= 1, (k - 1) // MAX_REASONABLE_QSCORE, 0)


@dataclass
class RecalTable:
    """Dense recalibration counts + finalized delta tables."""
    n_read_groups: int
    max_read_len: int
    qual_obs: np.ndarray = field(default=None)
    qual_mm: np.ndarray = field(default=None)
    cycle_obs: np.ndarray = field(default=None)
    cycle_mm: np.ndarray = field(default=None)
    ctx_obs: np.ndarray = field(default=None)
    ctx_mm: np.ndarray = field(default=None)
    expected_mismatch: float = 0.0

    def __post_init__(self):
        Q = self.n_qual_rg
        NC = self.n_cycle
        for name, shape in (("qual_obs", (Q,)), ("qual_mm", (Q,)),
                            ("cycle_obs", (Q, NC)), ("cycle_mm", (Q, NC)),
                            ("ctx_obs", (Q, N_CONTEXT)),
                            ("ctx_mm", (Q, N_CONTEXT))):
            if getattr(self, name) is None:
                setattr(self, name, np.zeros(shape, np.int64))

    @property
    def n_qual_rg(self) -> int:
        # + 94 headroom for quals beyond MAX_REASONABLE_QSCORE
        return MAX_REASONABLE_QSCORE * max(self.n_read_groups, 1) + 94

    @property
    def n_cycle(self) -> int:
        return 2 * self.max_read_len + 1

    # -- merge (RecalTable.++ :96-113) -----------------------------------
    def __add__(self, other: "RecalTable") -> "RecalTable":
        assert self.n_qual_rg == other.n_qual_rg and \
            self.n_cycle == other.n_cycle
        return RecalTable(
            self.n_read_groups, self.max_read_len,
            self.qual_obs + other.qual_obs, self.qual_mm + other.qual_mm,
            self.cycle_obs + other.cycle_obs, self.cycle_mm + other.cycle_mm,
            self.ctx_obs + other.ctx_obs, self.ctx_mm + other.ctx_mm,
            self.expected_mismatch + other.expected_mismatch)

    # -- finalize (RecalTable.finalizeTable :118-126) --------------------
    def finalize(self) -> "FinalizedTable":
        Q = self.n_qual_rg
        ks = np.arange(Q)
        rg_of_k = _rg_of_qualrg(ks)
        n_rg_groups = int(rg_of_k.max()) + 1
        rg_obs = np.bincount(rg_of_k, weights=self.qual_obs,
                             minlength=n_rg_groups)
        rg_mm = np.bincount(rg_of_k, weights=self.qual_mm,
                            minlength=n_rg_groups)
        total_obs = max(float(self.qual_obs.sum()), 1.0)
        avg_reported = self.expected_mismatch / total_obs

        # readgroup deltas (:128-131)
        rg_err = _error_prob(rg_mm, rg_obs, np.full(n_rg_groups, avg_reported))
        rg_delta = rg_err - avg_reported

        # qual deltas (:133-139): fallback/baseline = reported + rgDelta
        reported = PHRED_TO_ERROR[np.minimum(ks % MAX_REASONABLE_QSCORE, 255)]
        adj1 = reported + rg_delta[rg_of_k]
        qual_err = _error_prob(self.qual_mm, self.qual_obs, adj1)
        qual_delta = qual_err - adj1

        # covariate deltas (:141-146): baseline = reported + rgD + qualD
        adj2 = (reported + rg_delta[rg_of_k] + qual_delta)[:, None]
        cyc_err = _error_prob(self.cycle_mm, self.cycle_obs,
                              np.broadcast_to(adj2, self.cycle_obs.shape))
        ctx_err = _error_prob(self.ctx_mm, self.ctx_obs,
                              np.broadcast_to(adj2, self.ctx_obs.shape))
        return FinalizedTable(
            rg_delta=rg_delta.astype(np.float64),
            qual_delta=qual_delta.astype(np.float64),
            cycle_delta=(cyc_err - adj2).astype(np.float64),
            ctx_delta=(ctx_err - adj2).astype(np.float64),
            rg_of_qualrg=rg_of_k, avg_reported_error=avg_reported)


@dataclass
class FinalizedTable:
    rg_delta: np.ndarray        # [nRGgroups]
    qual_delta: np.ndarray      # [Q]
    cycle_delta: np.ndarray     # [Q, 2L+1]
    ctx_delta: np.ndarray       # [Q, 17]
    rg_of_qualrg: np.ndarray    # [Q]
    avg_reported_error: float
