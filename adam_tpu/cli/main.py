"""``adam-tpu`` command-line interface.

Re-designs the reference CLI framework (cli/AdamMain.scala:23-64,
AdamCommand.scala:22-50): a registry of subcommands, each a small class with
an argparse parser and a ``run``.  Commands are registered lazily so ``--help``
stays fast and optional deps stay optional.
"""

from __future__ import annotations

import argparse
import sys
from typing import Callable, Dict

_COMMANDS: Dict[str, Callable[[], "Command"]] = {}


class Command:
    name: str = ""
    help: str = ""

    def add_args(self, p: argparse.ArgumentParser) -> None:  # pragma: no cover
        pass

    def run(self, args: argparse.Namespace) -> int:
        raise NotImplementedError


def register(factory: Callable[[], Command]) -> Callable[[], Command]:
    cmd = factory()
    _COMMANDS[cmd.name] = lambda c=cmd: c
    return factory


def _load_commands() -> None:
    # import for side effect of @register
    from . import commands  # noqa: F401


def _honor_platform_env() -> None:
    from adam_tpu.platform import honor_platform_env

    honor_platform_env()


def main(argv=None) -> int:
    # anchor the cold-start clock before anything can touch jax — the
    # startup_seconds breakdown in the metrics sidecar measures from
    # here (obs imports no jax; the lazy command imports keep this cheap)
    from ..obs import startup as _startup

    _startup.begin()
    _load_commands()
    parser = argparse.ArgumentParser(
        prog="adam-tpu",
        description="TPU-native genomics read processing "
                    "(capabilities of the ADAM genomic data system)")
    sub = parser.add_subparsers(dest="command", metavar="COMMAND")
    for name in sorted(_COMMANDS):
        cmd = _COMMANDS[name]()
        p = sub.add_parser(name, help=cmd.help)
        cmd.add_args(p)
        # every command gets the telemetry flag (one place, not N):
        # a run manifest + per-stage/per-chunk events + final metrics
        # snapshot, as schema-versioned JSONL (docs/OBSERVABILITY.md)
        p.add_argument("-metrics", default=None, metavar="PATH",
                       help="write run telemetry (JSONL manifest/events/"
                            "metrics snapshot) to PATH")
        # ... the run timeline (docs/OBSERVABILITY.md): thread-aware
        # spans exported as Chrome-trace/Perfetto JSON — main thread,
        # feeder threads, prep pools each get their own lane.  Zero
        # overhead unless the flag (or ADAM_TPU_TRACE, how workers
        # inherit it) names a path.
        p.add_argument("-trace", default=None, metavar="PATH",
                       help="write a Chrome-trace/Perfetto timeline of "
                            "this run's spans (thread lanes) to PATH "
                            "(ADAM_TPU_TRACE is the env fallback)")
        # ... and the fault-injection plane (docs/RESILIENCE.md): a
        # seeded, replayable plan of which site fires on which
        # occurrence with which fault.  Unset (the normal case) the
        # plane is zero-overhead.
        p.add_argument("-fault_plan", default=None, metavar="PATH",
                       help="install a deterministic fault-injection "
                            "plan (JSON; ADAM_TPU_FAULT_PLAN is the "
                            "env fallback)")
        p.set_defaults(_cmd=cmd)
    args = parser.parse_args(argv)
    if not getattr(args, "_cmd", None):
        parser.print_help()
        return 1
    # after parsing (so --help stays jax-import-free), before any command
    # can initialize a backend
    _honor_platform_env()
    # every command compiles the same kernels; persist them across runs
    # (after the platform forcing, so the cache's platform gate sees the
    # forced config — and never before: the gate must not init a backend)
    from ..platform import enable_compilation_cache
    enable_compilation_cache()
    from ..errors import FormatError, malformed_summary, reset_malformed
    from ..instrument import log_invocation, say
    from ..obs import (metrics_path_from, metrics_run, trace_path_from,
                       trace_run)
    from ..resilience import InjectedFault, faults
    full_argv = ["adam-tpu"] + list(argv if argv is not None
                                    else sys.argv[1:])
    log_invocation(full_argv)
    # fault plane: flag wins, ADAM_TPU_FAULT_PLAN is the env fallback
    # (how elastic workers and bench subprocesses inherit the plan);
    # then the worker_proc site fires — a 'kill' rule takes this process
    # down exactly like a preempted worker, before any pipeline state
    try:
        faults.install_from_env(getattr(args, "fault_plan", None))
    except (OSError, ValueError) as e:
        # a missing/malformed plan file is bad input, not a crash —
        # same one-line clean exit every other bad input gets
        print(f"adam-tpu {args.command}: bad fault plan: {e}",
              file=sys.stderr)
        return 2
    reset_malformed()
    # the config fingerprint covers every parsed flag, so two runs with
    # the same manifest fingerprint really ran the same configuration
    # (sidecar paths excluded: where telemetry goes is not what ran)
    config = {k: v for k, v in vars(args).items()
              if not k.startswith("_") and k not in ("metrics", "trace")}
    try:
        with metrics_run(metrics_path_from(args.metrics), argv=full_argv,
                         config=config, command=args.command):
            # trace nests INSIDE metrics so the trace_written receipt
            # lands in the metrics sidecar before its summary closes
            with trace_run(trace_path_from(getattr(args, "trace", None))):
                faults.fire("worker_proc")
                rc = args._cmd.run(args) or 0
    except (FileNotFoundError, IsADirectoryError, FormatError) as e:
        print(f"adam-tpu {args.command}: {e}", file=sys.stderr)
        return 2
    except InjectedFault as e:
        # injected faults that exhaust every recovery path exit cleanly
        # and typed — the chaos matrix's 'fails cleanly' arm
        print(f"adam-tpu {args.command}: {e}", file=sys.stderr)
        return 3
    summary = malformed_summary()
    if summary:
        say(summary)
    return rc


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
