"""CLI subcommands (reference registry: cli/AdamMain.scala:23-37).

Implemented so far:
  * ``flagstat``  — cli/FlagStat.scala:38-109
  * ``bam2adam``  — cli/Bam2Adam.scala:41-126 (SAM/BAM -> Parquet dataset)
  * ``print``     — cli/PrintAdam.scala:35-50
  * ``listdict``  — cli/ListDict.scala:36-53
"""

from __future__ import annotations

import argparse
import os
import sys

from .main import Command, register


def add_parquet_args(p: argparse.ArgumentParser) -> None:
    """The reference's shared ParquetArgs (ParquetArgs.scala:22-31), same
    flag names: block size (bytes -> row-group rotation), page size,
    codec, dictionary encoding.  Composed into every command that writes
    Parquet, like the args4j trait mix-in."""
    p.add_argument("-parquet_block_size", type=int, default=None,
                   metavar="BYTES",
                   help="approximate row-group size in bytes")
    p.add_argument("-parquet_page_size", type=int, default=None,
                   metavar="BYTES", help="Parquet data page size")
    p.add_argument("-parquet_compression_codec", default=None,
                   choices=["gzip", "snappy", "zstd", "uncompressed"],
                   help="overrides -compression when given")
    p.add_argument("-parquet_disable_dictionary", action="store_true",
                   help="turn off dictionary encoding")


def parquet_writer_kwargs(args, fallback_compression: str = "zstd"):
    """argparse namespace -> save_table/DatasetWriter keyword arguments."""
    codec = getattr(args, "parquet_compression_codec", None)
    if codec is None:
        codec = getattr(args, "compression", None) or fallback_compression
    return dict(
        compression=None if codec in ("none", "uncompressed") else codec,
        page_size=getattr(args, "parquet_page_size", None),
        use_dictionary=not getattr(args, "parquet_disable_dictionary",
                                   False),
    )


def add_executor_args(p: argparse.ArgumentParser) -> None:
    """Knobs for the streaming executor (parallel/executor.py) — shared
    by every command on the shape-bucketed chunk hot path.  Flags mirror
    the ADAM_TPU_EXECUTOR_* env overrides (docs/EXECUTOR.md)."""
    p.add_argument("-prefetch_depth", type=int, default=None,
                   metavar="N",
                   help="device-feed look-ahead: chunk i+1's device_put "
                        "overlaps chunk i's compute, at most N chunks "
                        "in flight (default: 2 on accelerators, 0 on "
                        "CPU)")
    p.add_argument("-ladder_base", type=float, default=None,
                   metavar="BASE",
                   help="geometric ratio of the canonical row-bucket "
                        "ladder (default 2.0, floor 1.1; the autotuner "
                        "densifies to sqrt(2) when pad waste exceeds "
                        "35%%)")
    p.add_argument("-no_autotune", action="store_true",
                   help="freeze the executor plan at its defaults (no "
                        "pad-waste/link-rate re-decisions at pass "
                        "boundaries)")
    p.add_argument("-retry_budget", type=int, default=None, metavar="N",
                   help="attempts per chunk dispatch before degrading "
                        "(transient device errors retry with backoff; "
                        "RESOURCE_EXHAUSTED splits along the ladder; "
                        "a persistent failure falls back to the CPU "
                        "backend — default 3, ADAM_TPU_RETRY_* envs "
                        "tune the rest; docs/RESILIENCE.md)")
    g = p.add_mutually_exclusive_group()
    g.add_argument("-ragged", action="store_true",
                   help="force the RAGGED kernel layout on every "
                        "ragged-capable pass (concatenated planes + "
                        "prefix-sum row index, no per-chunk pad tax; "
                        "docs/EXECUTOR.md) — default: let raced bench "
                        "evidence decide, padded without evidence")
    g.add_argument("-no_ragged", action="store_true",
                   help="force the padded layout (the escape hatch; "
                        "ADAM_TPU_RAGGED=0 is the env equivalent)")
    gp = p.add_mutually_exclusive_group()
    gp.add_argument("-paged", action="store_true",
                    help="route every paged-capable pass through the "
                         "RESIDENT page pool (ragged addressing + "
                         "page-granular residency: only delta pages "
                         "cross the host→device link; "
                         "docs/EXECUTOR.md §6, ADAM_TPU_PAGED=1)")
    gp.add_argument("-no_paged", action="store_true",
                    help="force the page pool off even when "
                         "ADAM_TPU_PAGED is set in the environment")
    gm = p.add_mutually_exclusive_group()
    gm.add_argument("-mega", action="store_true",
                    help="route every mega-capable pass through the "
                         "FUSED multi-output device kernel (one "
                         "dispatch per chunk computes flagstat + "
                         "markdup keys + BQSR covariates off one "
                         "plane load; bit-identical by construction; "
                         "docs/ARCHITECTURE.md §6p, ADAM_TPU_MEGA=1) "
                         "— default: let raced mega_race ledger "
                         "evidence decide, unfused without evidence")
    gm.add_argument("-no_mega", action="store_true",
                    help="force the unfused kernels even when "
                         "ADAM_TPU_MEGA or ledger evidence would arm "
                         "the fused route")
    p.add_argument("-page_rows", type=int, default=None, metavar="N",
                   help="flat elements per page (default 32768 for the "
                        "wire plane; ADAM_TPU_PAGE_ROWS)")
    p.add_argument("-pool_pages", type=int, default=None, metavar="N",
                   help="pages in the resident pool (default sized to "
                        "the prefetch depth + one dispatch; "
                        "ADAM_TPU_POOL_PAGES)")


def add_fleet_args(p: argparse.ArgumentParser) -> None:
    """The shard-fleet knobs (parallel/shardstream.py): ``-hosts N``
    turns the command into a supervisor that spawns N worker processes,
    each streaming its contiguous unit range through the product
    executor; results merge through the exact monoid, so fleet output
    is byte-identical to the single-host run."""
    p.add_argument("-hosts", type=int, default=1,
                   help="shard the stream across N worker processes "
                        "(supervisor-spawned elastic fleet; 1 = "
                        "single-host, the default)")
    p.add_argument("-unit_rows", type=int, default=None,
                   help="rows per fleet work unit (the commit/recovery "
                        "granularity; default ~8 units per host)")
    p.add_argument("-lease_ttl", type=float, default=None,
                   help="seconds a worker's heartbeat lease may go "
                        "stale before the supervisor declares it lost "
                        "(ADAM_TPU_FLEET_LEASE_TTL_S)")
    p.add_argument("-max_restarts", type=int, default=None,
                   help="respawned incarnations per shard before its "
                        "range redistributes across survivors "
                        "(ADAM_TPU_FLEET_MAX_RESTARTS)")
    p.add_argument("-no_shrink", action="store_true",
                   help="disable shrink-to-fit redistribution after "
                        "the restart budget (the fleet then fails "
                        "cleanly typed instead)")
    p.add_argument("-speculate", action="store_true",
                   help="deadline-based speculative re-execution of "
                        "the slowest shard's tail range on an idle "
                        "survivor (off by default; the per-unit merge "
                        "dedups, so results never double-count)")
    p.add_argument("-commit_every", type=int, default=1,
                   help="work units per durable commit (each commit "
                        "costs ~3 fsyncs; batch on slow filesystems — "
                        "a coarser cadence only widens what a lost "
                        "worker recomputes, never the result)")
    p.add_argument("-fleet_dir", default=None,
                   help="fleet control directory (plan/leases/commits; "
                        "kept for audit when given, temp otherwise)")
    p.add_argument("-fleet_timeout", type=float, default=900.0,
                   help="seconds before the supervisor declares the "
                        "whole fleet stuck (workers that heartbeat and "
                        "commit are healthy — size this to the run)")


def fleet_policy_from(args):
    from ..resilience.retry import resolve_fleet_policy
    return resolve_fleet_policy(
        max_restarts=args.max_restarts,
        lease_ttl_s=args.lease_ttl,
        redistribute=False if args.no_shrink else None,
        speculate=True if args.speculate else None)


def fleet_worker_env(args) -> dict:
    """Environment for fleet workers carrying the CLI's explicitly set
    executor knobs — workers build their own StreamExecutor and resolve
    these from the env (the executor's flag/env convention), so a flag
    that tunes the single-host path must not silently drop the moment
    ``-hosts`` is added."""
    from ..parallel.executor import (AUTOTUNE_ENV, LADDER_BASE_ENV,
                                     MEGA_ENV, PAGE_ROWS_ENV, PAGED_ENV,
                                     POOL_PAGES_ENV, PREFETCH_ENV,
                                     RAGGED_ENV)
    from ..resilience.retry import RETRY_BUDGET_ENV

    env = dict(os.environ)
    if getattr(args, "prefetch_depth", None) is not None:
        env[PREFETCH_ENV] = str(args.prefetch_depth)
    if getattr(args, "ladder_base", None) is not None:
        env[LADDER_BASE_ENV] = str(args.ladder_base)
    if getattr(args, "no_autotune", False):
        env[AUTOTUNE_ENV] = "0"
    if getattr(args, "retry_budget", None) is not None:
        env[RETRY_BUDGET_ENV] = str(args.retry_budget)
    if getattr(args, "ragged", False):
        env[RAGGED_ENV] = "1"
    elif getattr(args, "no_ragged", False):
        env[RAGGED_ENV] = "0"
    if getattr(args, "paged", False):
        env[PAGED_ENV] = "1"
    elif getattr(args, "no_paged", False):
        env[PAGED_ENV] = "0"
    if getattr(args, "mega", False):
        env[MEGA_ENV] = "1"
    elif getattr(args, "no_mega", False):
        env[MEGA_ENV] = "0"
    if getattr(args, "page_rows", None) is not None:
        env[PAGE_ROWS_ENV] = str(args.page_rows)
    if getattr(args, "pool_pages", None) is not None:
        env[POOL_PAGES_ENV] = str(args.pool_pages)
    return env


def executor_opts_from(args) -> dict:
    """argparse namespace -> StreamExecutor keyword overrides (only the
    explicitly set ones, so env vars and autotuning fill the rest)."""
    opts: dict = {}
    if getattr(args, "prefetch_depth", None) is not None:
        opts["prefetch_depth"] = args.prefetch_depth
    if getattr(args, "ladder_base", None) is not None:
        opts["ladder_base"] = args.ladder_base
    if getattr(args, "no_autotune", False):
        opts["autotune"] = False
    if getattr(args, "retry_budget", None) is not None:
        opts["retry_budget"] = args.retry_budget
    if getattr(args, "ragged", False):
        opts["ragged"] = True
    elif getattr(args, "no_ragged", False):
        opts["ragged"] = False
    if getattr(args, "paged", False):
        opts["paged"] = True
    elif getattr(args, "no_paged", False):
        opts["paged"] = False
    if getattr(args, "mega", False):
        opts["mega"] = True
    elif getattr(args, "no_mega", False):
        opts["mega"] = False
    if getattr(args, "page_rows", None) is not None:
        opts["page_rows"] = args.page_rows
    if getattr(args, "pool_pages", None) is not None:
        opts["pool_pages"] = args.pool_pages
    return opts


def input_size_bytes(path: str) -> int:
    """Size of a file input or a Parquet dataset directory (sum of its
    part files)."""
    if os.path.isdir(path):
        return sum(os.path.getsize(os.path.join(path, f))
                   for f in os.listdir(path) if f.endswith(".parquet"))
    return os.path.getsize(path) if os.path.exists(path) else 0


def should_stream(args, *paths) -> bool:
    """One auto-stream gate for every streaming-capable command: explicit
    -stream wins, -no_stream vetoes, otherwise inputs (files or dataset
    directories) totaling over 1 GB stream."""
    if getattr(args, "no_stream", False):
        return False
    if getattr(args, "stream", False):
        return True
    return sum(input_size_bytes(p) for p in paths) > (1 << 30)


def save_with_args(table, path, args, **kw) -> None:
    """save_table with the shared ParquetArgs applied (incl. the bytes ->
    row-group-rows conversion for -parquet_block_size)."""
    from ..io.parquet import rows_for_block_size, save_table

    kwargs = parquet_writer_kwargs(args)
    bs = getattr(args, "parquet_block_size", None)
    if bs:
        kwargs["row_group_size"] = rows_for_block_size(table, bs)
    save_table(table, path, **kwargs, **kw)


@register
class FlagStatCommand(Command):
    name = "flagstat"
    help = "Print statistics on reads (identical counters to samtools flagstat)"

    def add_args(self, p: argparse.ArgumentParser) -> None:
        p.add_argument("input", help="SAM/BAM file or ADAM Parquet dataset")
        p.add_argument("-chunk_rows", type=int, default=1 << 22,
                       help="reads per streamed chunk (bounds host memory)")
        p.add_argument("-io_threads", type=int, default=1,
                       help="overlap host decode with device dispatch "
                            "(reader thread + pack pool; >1 enables)")
        p.add_argument("-io_procs", type=int, default=1,
                       help="BGZF inflate worker processes (>1 enables; "
                            "byte-identical stream)")
        p.add_argument("-shard_id", type=int, default=None,
                       help="run as ONE fleet worker against an "
                            "existing -fleet_dir (normally the "
                            "supervisor spawns these; exposed for "
                            "manual relaunch/debug)")
        add_fleet_args(p)
        add_executor_args(p)

    def run(self, args) -> int:
        from ..ops.flagstat import format_report

        if args.shard_id is not None:
            if not args.fleet_dir:
                print("flagstat: -shard_id needs -fleet_dir",
                      file=sys.stderr)
                return 2
            from ..parallel.shardstream import run_shard_worker
            return run_shard_worker(args.fleet_dir, args.shard_id)
        if args.hosts > 1:
            from ..parallel.shardstream import fleet_flagstat
            if args.chunk_rows != 1 << 22:
                # don't silently drop an explicitly tuned flag: the
                # fleet's granularity knob is -unit_rows
                print("flagstat -hosts: -chunk_rows does not apply to "
                      "the fleet path (use -unit_rows for the "
                      "commit/recovery granularity)", file=sys.stderr)
            failed, passed = fleet_flagstat(
                args.input, hosts=args.hosts, unit_rows=args.unit_rows,
                fleet_dir=args.fleet_dir,
                commit_every=args.commit_every,
                io_procs=args.io_procs,
                env=fleet_worker_env(args),
                timeout_s=args.fleet_timeout,
                policy=fleet_policy_from(args))
            print(format_report(failed, passed))
            return 0
        from ..parallel.pipeline import streaming_flagstat

        # streams bounded chunks of the 4-column projection (the reference's
        # 13-field projection, cli/FlagStat.scala:50-57) through the mesh
        failed, passed = streaming_flagstat(
            args.input, chunk_rows=args.chunk_rows,
            io_threads=args.io_threads, io_procs=args.io_procs,
            executor_opts=executor_opts_from(args))
        print(format_report(failed, passed))
        return 0


@register
class CallCommand(Command):
    name = "call"
    help = ("Call biallelic SNPs: streamed pileup counts, the integer "
            "device genotyper, VCF out (adam-tpu's fourth workload)")

    def add_args(self, p: argparse.ArgumentParser) -> None:
        p.add_argument("input", help="SAM/BAM file or ADAM Parquet dataset")
        p.add_argument("output",
                       help="output VCF (.vcf text, .vcf.gz/.bgz BGZF, "
                            ".bcf binary)")
        p.add_argument("-chunk_rows", type=int, default=1 << 18,
                       help="reads per streamed chunk (bounds host "
                            "memory)")
        p.add_argument("-io_procs", type=int, default=1,
                       help="BGZF inflate worker processes (>1 enables; "
                            "byte-identical stream)")
        p.add_argument("-stripe_span", type=int, default=None,
                       help="genome-stripe width in bp (flag > "
                            "ADAM_TPU_CALL_SPAN > 32768; "
                            "decide_call_plan records the choice)")
        p.add_argument("-min_depth", type=int, default=None,
                       help="min total coverage to emit a call (flag > "
                            "ADAM_TPU_CALL_MIN_DEPTH > 2)")
        p.add_argument("-min_alt", type=int, default=None,
                       help="min alt-supporting bases to emit a call "
                            "(flag > ADAM_TPU_CALL_MIN_ALT > 2)")
        p.add_argument("-sample", default=None,
                       help="sample name for reads without "
                            "recordGroupSample metadata")
        p.add_argument("-validate", action="store_true",
                       help="re-derive every call through the scalar "
                            "oracle (call/oracle.py) and fail on any "
                            "byte difference; also reports the rods-"
                            "plane coverage summary")
        add_executor_args(p)

    def run(self, args) -> int:
        from ..call.pipeline import streaming_call

        kw = {}
        if args.sample:
            kw["default_sample"] = args.sample
        res = streaming_call(
            args.input, args.output, chunk_rows=args.chunk_rows,
            io_procs=args.io_procs, stripe_span=args.stripe_span,
            min_depth=args.min_depth, min_alt=args.min_alt,
            executor_opts=executor_opts_from(args),
            validate=args.validate, **kw)
        print(f"{res['reads']} reads ({res['admitted']} admitted) -> "
              f"{res['calls']} calls over {res['stripes']} stripes, "
              f"{res['samples']} sample(s) -> {args.output}")
        if res["rod_coverage"] is not None:
            print(f"rod coverage {res['rod_coverage']:.4f}")
        if args.validate:
            if not res["identical"]:
                print("call: device VCF differs from the scalar oracle",
                      file=sys.stderr)
                return 1
            print("oracle: byte-identical")
        return 0


@register
class Bam2AdamCommand(Command):
    name = "bam2adam"
    help = "Convert a SAM/BAM file to an ADAM Parquet dataset"

    def add_args(self, p: argparse.ArgumentParser) -> None:
        p.add_argument("input", help="SAM/BAM file")
        p.add_argument("output", help="output Parquet dataset directory")
        p.add_argument("-parts", type=int, default=1,
                       help="number of part files to write (in-memory "
                            "path; the streamed path rotates one part "
                            "per chunk, like the reference's "
                            "one-part-per-writer-thread)")
        p.add_argument("-compression", default="zstd",
                       choices=["zstd", "snappy", "gzip", "none"])
        p.add_argument("-samtools_validation", default="lenient",
                       choices=["strict", "lenient", "silent"],
                       help="malformed-record handling (same default as "
                            "the reference, Bam2Adam.scala:46-47)")
        p.add_argument("-stream", action="store_true",
                       help="force the chunked bounded-memory path "
                            "(auto for inputs over 1 GB)")
        p.add_argument("-no_stream", action="store_true")
        p.add_argument("-stream_chunk_rows", type=int, default=1 << 20,
                       help="reads per streamed chunk")
        p.add_argument("-io_threads", type=int, default=1,
                       help=">1 moves decode to a read-ahead thread so "
                            "it overlaps the Parquet write on the "
                            "streamed path (bit-identical; bam2adam has "
                            "no pack stage, so this is an on/off "
                            "overlap, not a pool size)")
        p.add_argument("-io_procs", type=int, default=1,
                       help="BGZF inflate worker processes on the "
                            "streamed path (bit-identical)")
        add_parquet_args(p)

    def run(self, args) -> int:
        if should_stream(args, args.input):
            # the reference's Bam2Adam IS a streaming converter (reader
            # thread + N writers over a bounded queue, one part file per
            # writer); this is that shape with bounded chunks
            from .. import schema as S
            from ..io.parquet import DatasetWriter
            from ..io.stream import open_read_stream

            if args.parts != 1:
                print("bam2adam: streaming path rotates one part per "
                      f"chunk; -parts {args.parts} does not apply "
                      "(use -stream_chunk_rows to size parts)")
            stream = open_read_stream(
                args.input, chunk_rows=args.stream_chunk_rows,
                io_procs=args.io_procs,
                stringency=args.samtools_validation)
            chunks = stream
            if args.io_threads > 1:
                from ..parallel.ingest import pipelined
                chunks = pipelined(chunks, workers=args.io_threads)
            n = 0
            import time as _time

            from .. import obs

            t0 = _time.perf_counter()
            with DatasetWriter(args.output,
                               part_rows=args.stream_chunk_rows,
                               row_group_bytes=args.parquet_block_size,
                               **parquet_writer_kwargs(args)) as out:
                for t in chunks:
                    out.write(t)
                    n += t.num_rows
                    obs.chunk_processed("bam2adam", t.num_rows,
                                        bytes_in=t.nbytes)
                if n == 0:
                    # a header-only (or all-dropped) input must still
                    # yield a schema-bearing dataset, like the
                    # in-memory path's one empty part
                    out.write(S.READ_SCHEMA.empty_table())
            obs.run_totals("bam2adam", n, _time.perf_counter() - t0,
                           input_path=args.input, output_path=args.output)
            print(f"wrote {n} reads to {args.output}")
            return 0
        from ..io.dispatch import load_reads

        table, _, _ = load_reads(args.input,
                                 stringency=args.samtools_validation)
        save_with_args(table, args.output, args, n_parts=args.parts)
        print(f"wrote {table.num_rows} reads to {args.output}")
        return 0


@register
class TransformCommand(Command):
    name = "transform"
    help = "Read pre-processing pipeline (markdup/BQSR/realign/sort)"

    def add_args(self, p: argparse.ArgumentParser) -> None:
        # flag names mirror cli/Transform.scala:40-60
        p.add_argument("input", help="SAM/BAM file or ADAM Parquet dataset")
        p.add_argument("output", help="output Parquet dataset directory "
                                      "(or .sam path)")
        p.add_argument("-mark_duplicate_reads", action="store_true")
        p.add_argument("-recalibrate_base_qualities", action="store_true")
        p.add_argument("-dbsnp_sites", default=None,
                       help="sites-only VCF masking known SNPs during BQSR")
        p.add_argument("-realignIndels", action="store_true")
        p.add_argument("-sort_reads", action="store_true")
        p.add_argument("-parts", type=int, default=1)
        p.add_argument("-coalesce", type=int, default=None,
                       help="cap the number of output part files "
                            "(Transform.scala:51-70's repartition knob)")
        p.add_argument("-timing", action="store_true",
                       help="print a per-stage wall-clock report")
        p.add_argument("-trace_dir", default=None,
                       help="write a JAX device profiler trace here")
        p.add_argument("-checkpoint_dir", default=None,
                       help="materialize each stage here and resume a "
                            "previously interrupted run")
        p.add_argument("-stream", action="store_true",
                       help="force the chunked mesh-sharded pipeline "
                            "(bounded host memory; auto-enabled for inputs "
                            "over 1 GB unless the output is .sam)")
        p.add_argument("-stream_chunk_rows", type=int, default=1 << 20,
                       help="reads per streamed chunk")
        p.add_argument("-io_threads", type=int, default=1,
                       help="overlap host decode+pack with device "
                            "dispatch in every streaming pass (reader "
                            "thread + pack pool; output is bit-identical)")
        p.add_argument("-io_procs", type=int, default=1,
                       help="BGZF inflate worker processes for the "
                            "ingest pass (>1 enables; bit-identical "
                            "output — the byte stream is unchanged)")
        p.add_argument("-workdir", default=None,
                       help="scratch directory for streamed spills "
                            "(default: a temp dir)")
        p.add_argument("-realign_pipeline_depth", type=int, default=None,
                       metavar="N",
                       help="pass-4 realign pipeline look-ahead: bin "
                            "i+1's Parquet load + host group prep "
                            "overlaps bin i's sweeps and bin i-1's emit, "
                            "with at most N bins in flight (default 2; "
                            "1 = serial walk through the same engine; "
                            "0 = pipeline off entirely; mirrors "
                            "ADAM_TPU_REALIGN_PIPELINE_DEPTH). "
                            "Output is byte-identical at any depth")
        p.add_argument("-no_realign_pipeline", action="store_true",
                       help="run pass-4 realignment strictly serially "
                            "(the pre-pipeline path; mirrors "
                            "ADAM_TPU_REALIGN_PIPELINE=0). Scheduling "
                            "only — output bytes never change")
        p.add_argument("-no_fuse", action="store_true",
                       help="run the legacy 4-pass streaming transform "
                            "instead of the fused single-decode streams "
                            "(mirrors ADAM_TPU_FUSE=0). Dataflow only — "
                            "output is byte-identical either way")
        add_fleet_args(p)
        add_executor_args(p)
        add_parquet_args(p)

    def run(self, args) -> int:
        sam_out = args.output.endswith(".sam")
        # -checkpoint_dir alone keeps the in-memory staged path (stage
        # tables in Parquet); with -stream it selects the streaming
        # pass-level resume (workdir = checkpoint dir)
        auto_stream = (not sam_out and not args.checkpoint_dir and
                       should_stream(args, args.input))
        if args.hosts > 1:
            from ..parallel.pipeline import resolve_fuse_opt
            is_parquet = not args.input.endswith((".sam", ".bam"))
            # resolve the fusion choice the way the pipeline will
            # (flag wins, ADAM_TPU_FUSE fills) — an env-forced legacy
            # run must get this same typed refusal, not a traceback
            fused = resolve_fuse_opt(False if args.no_fuse else None) \
                is not False
            if (not args.recalibrate_base_qualities or args.sort_reads
                    or args.realignIndels or not fused
                    or not is_parquet or sam_out):
                print("transform: -hosts shards the fused stream-2 "
                      "BQSR count — it needs "
                      "-recalibrate_base_qualities, a Parquet input/"
                      "output, no -sort_reads/-realignIndels, and the "
                      "fused dataflow (no -no_fuse)", file=sys.stderr)
                return 2
        if args.stream or auto_stream or args.hosts > 1:
            if sam_out:
                raise SystemExit(
                    "transform -stream writes Parquet datasets; "
                    "convert with adam-tpu transform OUT.sam afterwards")
            if args.checkpoint_dir and args.workdir and \
                    args.checkpoint_dir != args.workdir:
                raise SystemExit(
                    "-checkpoint_dir IS the streaming workdir; drop "
                    "-workdir or make them equal")
            from ..models.snptable import SnpTable
            from ..parallel.pipeline import streaming_transform
            if args.timing:
                from ..instrument import set_sync_timing
                set_sync_timing(True)
            snp = SnpTable.from_vcf(args.dbsnp_sites) \
                if args.dbsnp_sites else None
            pw = parquet_writer_kwargs(args)
            realign_opts: dict = {}
            if args.realign_pipeline_depth is not None:
                realign_opts["depth"] = args.realign_pipeline_depth
            if args.no_realign_pipeline:
                realign_opts["pipeline"] = False
            if getattr(args, "ragged", False):
                realign_opts["layout"] = "ragged"
            elif getattr(args, "no_ragged", False):
                realign_opts["layout"] = "padded"
            fleet = None
            if args.hosts > 1:
                pol = fleet_policy_from(args)
                fleet = dict(hosts=args.hosts,
                             unit_rows=args.unit_rows,
                             fleet_dir=args.fleet_dir,
                             snp_path=args.dbsnp_sites,
                             commit_every=args.commit_every,
                             env=fleet_worker_env(args),
                             timeout_s=args.fleet_timeout,
                             max_restarts=pol.max_restarts,
                             lease_ttl_s=pol.lease_ttl_s,
                             redistribute=pol.redistribute,
                             speculate=pol.speculate)
            n = streaming_transform(
                args.input, args.output,
                markdup=args.mark_duplicate_reads,
                bqsr=args.recalibrate_base_qualities, snp_table=snp,
                realign=args.realignIndels, sort=args.sort_reads,
                workdir=args.checkpoint_dir or args.workdir,
                chunk_rows=args.stream_chunk_rows,
                coalesce=args.coalesce,
                compression=pw["compression"] or "none",
                page_size=pw["page_size"],
                use_dictionary=pw["use_dictionary"],
                row_group_bytes=args.parquet_block_size,
                resume=bool(args.checkpoint_dir),
                io_threads=args.io_threads,
                io_procs=args.io_procs,
                executor_opts=executor_opts_from(args),
                realign_opts=realign_opts,
                fuse=False if args.no_fuse else None,
                fleet=fleet)
            if args.timing:
                from ..instrument import print_report
                print_report()   # one quiet gate for ALL instrument output
            print(f"wrote {n} reads to {args.output}")
            return 0
        return self._run_inmemory(args)

    def _run_inmemory(self, args) -> int:
        from ..checkpoint import CheckpointDir, run_stages
        from ..instrument import (device_trace, print_report,
                                  set_sync_timing, stage)
        if args.timing:
            set_sync_timing(True)
        from ..io.dispatch import load_reads, sequence_dictionary_from_reads
        from ..io.parquet import save_table

        def timed(name, fn):
            def wrapped(table):
                with stage(name, sync=True):
                    return fn(table)
            return name, wrapped

        stages = []
        if args.mark_duplicate_reads:
            from ..ops.markdup import mark_duplicates
            stages.append(timed("markdup", mark_duplicates))
        if args.recalibrate_base_qualities:
            from ..bqsr.recalibrate import recalibrate_base_qualities
            from ..models.snptable import SnpTable
            snp = SnpTable.from_vcf(args.dbsnp_sites) \
                if args.dbsnp_sites else None
            stages.append(timed(
                "bqsr", lambda t: recalibrate_base_qualities(t, snp)))
        if args.realignIndels:
            from ..realign.realigner import realign_indels
            stages.append(timed("realign", realign_indels))
        if args.sort_reads:
            from ..ops.sort import sort_reads
            stages.append(timed("sort", sort_reads))

        ckpt = None
        if args.checkpoint_dir:
            # every stage-affecting input belongs in the fingerprint —
            # resuming a BQSR checkpoint built from different known-sites
            # would silently use the wrong mask.  Path + size + mtime, so an
            # edited file under the same name invalidates the checkpoint.
            def _stamp(path):
                if not path:
                    return f"{path}"
                try:
                    st = os.stat(path)
                except OSError:
                    return f"{path}:missing"
                if not os.path.isdir(path):
                    return f"{path}:{st.st_size}:{st.st_mtime_ns}"
                # a Parquet dataset directory: a rewritten part file keeps
                # the dir's own size/mtime, so stamp the entries themselves
                parts = []
                for root, _, names in sorted(os.walk(path)):
                    for name in sorted(names):
                        full = os.path.join(root, name)
                        rel = os.path.relpath(full, path)
                        try:
                            fst = os.stat(full)
                        except OSError:
                            parts.append(f"{rel}:missing")
                            continue
                        parts.append(
                            f"{rel}:{fst.st_size}:{fst.st_mtime_ns}")
                return f"{path}:" + ",".join(parts)
            config = [_stamp(args.input), f"dbsnp={_stamp(args.dbsnp_sites)}"] \
                + [name for name, _ in stages]
            ckpt = CheckpointDir(args.checkpoint_dir, config)

        with device_trace(args.trace_dir):
            with stage("load"):
                table, seq_dict, rg_dict = load_reads(args.input)
            table = run_stages(
                ckpt, table, stages,
                on_skip=lambda done: print(
                    f"resuming after checkpointed stages: {', '.join(done)}"))
            with stage("save"):
                if args.output.endswith(".sam"):
                    from ..io.dispatch import \
                        record_group_dictionary_from_reads
                    from ..io.sam import write_sam
                    if seq_dict is None:
                        seq_dict = sequence_dictionary_from_reads(table)
                    if rg_dict is None:
                        rg_dict = record_group_dictionary_from_reads(table)
                    write_sam(table, seq_dict, args.output, rg_dict)
                else:
                    save_with_args(table, args.output, args,
                                   n_parts=args.coalesce or args.parts)
        if args.timing:
            print_report()       # quiet-gated, like every instrument print
        print(f"wrote {table.num_rows} reads to {args.output}")
        return 0


@register
class ServeCommand(Command):
    name = "serve"
    help = ("Long-lived multi-tenant front-end: warm the device once, "
            "serve many jobs from a spool directory")

    def add_args(self, p: argparse.ArgumentParser) -> None:
        p.add_argument("spool",
                       help="spool directory (queue/running/done/failed "
                            "job-spec exchange; clients use "
                            "'adam-tpu submit')")
        p.add_argument("-chunk_rows", type=int, default=1 << 22,
                       help="reads per streamed chunk — the SERVER owns "
                            "this so every tenant's jobs land on one "
                            "canonical shape ladder (structural "
                            "cross-job compile-cache hits)")
        p.add_argument("-max_concurrent", type=int, default=4,
                       help="jobs admitted per round (FIFO; "
                            "docs/ARCHITECTURE.md §6i)")
        p.add_argument("-no_pack", action="store_true",
                       help="disable cross-tenant shared dispatches "
                            "(each admitted flagstat job then streams "
                            "solo)")
        p.add_argument("-pack_segments", type=int, default=8,
                       help="tenants per shared dispatch buffer (the "
                            "segmented kernel's compiled width)")
        p.add_argument("-max_jobs", type=int, default=None,
                       help="exit after serving N jobs (default: serve "
                            "until SPOOL/stop appears)")
        p.add_argument("-idle_timeout", type=float, default=None,
                       help="exit after this many seconds with an "
                            "empty queue (default: wait forever)")
        p.add_argument("-poll_s", type=float, default=0.05,
                       help="queue poll interval when idle")
        p.add_argument("-io_procs", type=int, default=1,
                       help="default BGZF inflate worker processes per "
                            "job (a job spec's args.io_procs overrides)")
        p.add_argument("-hosts", type=int, default=1,
                       help="fleet-serve worker processes (>1: a "
                            "cluster scheduler places queued jobs onto "
                            "N always-warm workers, each a full serve "
                            "loop; docs/FLEET_SERVE.md)")
        p.add_argument("-worker_depth", type=int, default=4,
                       help="fleet mode: max jobs in flight per worker "
                            "before placement holds them in the front "
                            "queue (where stealing can still rebalance)")
        p.add_argument("-max_job_kills", type=int, default=2,
                       help="fleet mode: worker deaths one job may "
                            "cause before it is quarantined with a "
                            "typed failure (the poison-job ladder)")
        p.add_argument("-shard_rows", type=int, default=0,
                       help="fleet mode: flagstat inputs at or above "
                            "this many rows split into per-range "
                            "sub-jobs across the fleet (0: never shard)")
        p.add_argument("-no_steal", action="store_true",
                       help="fleet mode: disable work stealing for "
                            "idle workers")
        p.add_argument("-no_fair", action="store_true",
                       help="disable deficit-round-robin tenant "
                            "fairness (admission/placement fall back "
                            "to pure FIFO — a burst tenant can starve "
                            "the queue)")
        p.add_argument("-backlog_cap", type=int, default=None,
                       help="reject queued jobs past this total "
                            "backlog with a typed rejected/ doc + "
                            "retry_after_s (0/default: unbounded)")
        p.add_argument("-tenant_quota", type=int, default=None,
                       help="max queued jobs one tenant may hold; the "
                            "excess is rejected typed (0/default: "
                            "unlimited)")
        p.add_argument("-tenant_slots", type=int, default=None,
                       help="max admissions one tenant may take per "
                            "round (the in-flight quota; over-slots "
                            "jobs wait, they are not shed)")
        p.add_argument("-backlog_hi", type=int, default=None,
                       help="brownout ladder backlog high watermark "
                            "(default: 8x max_concurrent; 0 disables "
                            "the ladder — docs/ARCHITECTURE.md §6m)")
        p.add_argument("-queue_p99_hi", type=float, default=None,
                       help="brownout ladder queue-wait p99 high "
                            "watermark in seconds (0/default: signal "
                            "disabled)")
        p.add_argument("-rss_budget_mb", type=float, default=None,
                       help="brownout ladder RSS budget in MB "
                            "(0/default: signal disabled)")
        p.add_argument("-no_series", action="store_true",
                       help="disable the always-on time-series sampler "
                            "(SPOOL/series.jsonl; 'adam-tpu status' "
                            "renders its tail — docs/OBSERVABILITY.md)")
        add_executor_args(p)

    def run(self, args) -> int:
        from .. import obs
        from ..instrument import say
        from ..serve.overload import (resolve_admission_limits,
                                      resolve_overload_policy)

        if args.hosts < 1:
            print(f"serve: -hosts must be >= 1 (got {args.hosts})",
                  file=sys.stderr)
            return 2
        limits = resolve_admission_limits(
            fair=False if args.no_fair else None,
            backlog_cap=args.backlog_cap,
            tenant_quota=args.tenant_quota,
            tenant_slots=args.tenant_slots)
        if args.hosts > 1:
            from ..serve.scheduler import FleetServeScheduler

            sched = FleetServeScheduler(
                args.spool, hosts=args.hosts,
                chunk_rows=args.chunk_rows,
                max_concurrent=args.max_concurrent,
                pack=not args.no_pack,
                pack_segments=args.pack_segments,
                poll_s=args.poll_s, io_procs=args.io_procs,
                worker_depth=args.worker_depth,
                max_job_kills=args.max_job_kills,
                shard_rows=args.shard_rows, steal=not args.no_steal,
                series=not args.no_series,
                executor_opts=executor_opts_from(args),
                limits=limits,
                overload=resolve_overload_policy(
                    backlog_hi=args.backlog_hi,
                    queue_p99_hi_s=args.queue_p99_hi,
                    rss_budget_mb=args.rss_budget_mb,
                    max_concurrent=args.worker_depth * args.hosts))
            info = sched.boot()
            say(f"serve: fleet of {info.get('hosts')} always-warm "
                f"worker(s); spool {args.spool}")
            try:
                n = sched.run(max_jobs=args.max_jobs,
                              idle_timeout_s=args.idle_timeout)
            finally:
                # final sample + series_written receipt while the
                # metrics sink is still open (the worker entry's
                # discipline)
                obs.series.stop_series()
            print(f"served {n} job(s) from {args.spool}")
            return 0
        from ..serve.server import ServeServer

        server = ServeServer(
            args.spool, chunk_rows=args.chunk_rows,
            max_concurrent=args.max_concurrent,
            pack=not args.no_pack, pack_segments=args.pack_segments,
            poll_s=args.poll_s, io_procs=args.io_procs,
            series=not args.no_series,
            executor_opts=executor_opts_from(args),
            limits=limits,
            overload=resolve_overload_policy(
                backlog_hi=args.backlog_hi,
                queue_p99_hi_s=args.queue_p99_hi,
                rss_budget_mb=args.rss_budget_mb,
                max_concurrent=args.max_concurrent))
        info = server.boot()
        say(f"serve: warm on {info.get('backend')} "
            f"({info.get('n_devices')} device(s)); "
            f"spool {args.spool}")
        try:
            n = server.run(max_jobs=args.max_jobs,
                           idle_timeout_s=args.idle_timeout)
        finally:
            obs.series.stop_series()
        print(f"served {n} job(s) from {args.spool}")
        return 0


@register
class SubmitCommand(Command):
    name = "submit"
    help = "Submit a job to a running 'adam-tpu serve' spool"

    def add_args(self, p: argparse.ArgumentParser) -> None:
        p.add_argument("spool", help="the server's spool directory")
        p.add_argument("job_command", choices=["flagstat", "transform"],
                       metavar="COMMAND",
                       help="flagstat or transform")
        p.add_argument("input", help="SAM/BAM file or Parquet dataset")
        p.add_argument("output", nargs="?", default=None,
                       help="output dataset (transform only)")
        p.add_argument("-tenant", default="default",
                       help="tenant id — scopes obs labels, trace "
                            "lanes, and fault-plan rules to this job's "
                            "owner")
        p.add_argument("-job_id", default=None,
                       help="explicit job id (default: assigned)")
        p.add_argument("-args", dest="job_args", default=None,
                       metavar="JSON",
                       help="extra command args as a JSON object (e.g. "
                            '\'{"markdup": true}\' for transform)')
        p.add_argument("-wait", action="store_true",
                       help="poll for the result and print it (flagstat "
                            "output is byte-identical to the solo CLI)")
        p.add_argument("-timeout", type=float, default=120.0,
                       help="-wait timeout in seconds")
        p.add_argument("-priority", default="normal",
                       choices=["low", "normal", "high"],
                       help="admission priority — the brownout "
                            "ladder's reject_low rung sheds 'low' "
                            "first (docs/ARCHITECTURE.md §6m)")
        p.add_argument("-deadline", type=float, default=None,
                       metavar="S",
                       help="cancel the job (typed DeadlineExceeded) "
                            "if it is still QUEUED after this many "
                            "seconds — a result nobody waits for must "
                            "not occupy a warm worker")
        p.add_argument("-no_retry", action="store_true",
                       help="with -wait: surface a typed admission "
                            "rejection immediately instead of honoring "
                            "its retry_after_s with one transparent "
                            "resubmit")

    def run(self, args) -> int:
        import json as _json
        import time as _time

        from ..serve import jobspec

        try:
            job_args = _json.loads(args.job_args) if args.job_args \
                else {}
        except ValueError as e:
            print(f"submit: bad -args JSON: {e}", file=sys.stderr)
            return 2
        spec = {"job_id": args.job_id, "tenant": args.tenant,
                "command": args.job_command, "input": args.input,
                "output": args.output, "args": job_args,
                "priority": args.priority,
                "deadline_s": args.deadline}
        try:
            job_id = jobspec.submit_job(args.spool, spec)
        except ValueError as e:
            print(f"submit: {e}", file=sys.stderr)
            return 2
        if not args.wait:
            print(f"queued {job_id}")
            return 0
        resubmitted = False
        deadline = _time.monotonic() + args.timeout
        while True:
            try:
                doc = jobspec.wait_result(
                    args.spool, job_id,
                    timeout_s=max(deadline - _time.monotonic(), 0.01))
            except TimeoutError as e:
                print(f"submit: {e}", file=sys.stderr)
                return 4
            if doc.get("rejected") and not args.no_retry \
                    and not resubmitted:
                # honor the server's typed back-off hint ONCE: wait
                # retry_after_s, resubmit transparently (fresh id — a
                # rejected id keeps its doc), then poll the new job; a
                # second rejection surfaces typed below
                after = float(doc.get("retry_after_s") or 1.0)
                after = min(after, max(deadline - _time.monotonic(),
                                       0.0))
                print(f"submit: job {job_id} rejected "
                      f"[{doc.get('code')}] — resubmitting once after "
                      f"{after:.1f}s", file=sys.stderr)
                _time.sleep(after)
                retry_spec = dict(spec)
                retry_spec["job_id"] = f"{args.job_id}.r1" \
                    if args.job_id else None
                try:
                    job_id = jobspec.submit_job(args.spool, retry_spec)
                except ValueError:
                    # the derived id can itself be unsubmittable (an
                    # id near the 80-char bound, or a stale .r1 doc
                    # from an earlier run) — degrade to an auto id
                    # rather than turning a retryable rejection into
                    # a hard failure
                    retry_spec["job_id"] = None
                    try:
                        job_id = jobspec.submit_job(args.spool,
                                                    retry_spec)
                    except ValueError as e:
                        print(f"submit: {e}", file=sys.stderr)
                        return 2
                resubmitted = True
                continue
            break
        if not doc.get("ok"):
            print(f"submit: job {job_id} failed "
                  f"[{doc.get('error_type')}]: {doc.get('error')}",
                  file=sys.stderr)
            return 3
        result = doc.get("result") or {}
        if args.job_command == "flagstat":
            # the exact line the solo CLI prints (byte-identity is the
            # serve contract, not a best effort)
            print(result.get("report", ""))
        else:
            print(f"wrote {result.get('rows')} reads to {args.output}")
        return 0


@register
class Reads2RefCommand(Command):
    name = "reads2ref"
    help = "Convert reads to pileups (cli/Reads2Ref.scala:39-75)"

    def add_args(self, p: argparse.ArgumentParser) -> None:
        p.add_argument("input", help="SAM/BAM file or ADAM Parquet dataset")
        p.add_argument("output", help="output pileup Parquet dataset")
        p.add_argument("-aggregate", action="store_true")
        p.add_argument("-allow_non_primary", action="store_true",
                       help="skip the locus predicate filter")
        p.add_argument("-parts", type=int, default=1)
        p.add_argument("-stream", action="store_true",
                       help="chunked bounded-memory pipeline (auto-enabled "
                            "for inputs over 1 GB)")
        p.add_argument("-no_stream", action="store_true",
                       help="force the in-memory path even for large "
                            "inputs")
        p.add_argument("-stream_chunk_rows", type=int, default=1 << 20)
        p.add_argument("-window_bp", type=int, default=1 << 20,
                       help="aggregation window width in bp (streaming; "
                            "memory ~ window x coverage)")
        p.add_argument("-workdir", default=None)
        add_parquet_args(p)

    def run(self, args) -> int:
        if should_stream(args, args.input):
            if args.parts != 1:
                import sys
                print("warning: -parts is ignored by the streaming path "
                      "(part size follows -stream_chunk_rows); use "
                      "-no_stream for the in-memory writer",
                      file=sys.stderr)
            from ..parallel.pipeline import streaming_reads2ref
            pw = parquet_writer_kwargs(args)
            n_reads, n_pileups = streaming_reads2ref(
                args.input, args.output, aggregate=args.aggregate,
                allow_non_primary=args.allow_non_primary,
                chunk_rows=args.stream_chunk_rows,
                window_bp=args.window_bp, workdir=args.workdir,
                compression=pw["compression"] or "none",
                page_size=pw["page_size"],
                use_dictionary=pw["use_dictionary"],
                row_group_bytes=args.parquet_block_size)
            n = max(n_reads, 1)
            print(f"wrote {n_pileups} pileups from {n_reads} reads "
                  f"(coverage ~{n_pileups / n:.1f}x read length)")
            return 0
        from ..io.dispatch import load_reads
        from ..io.parquet import locus_predicate
        from ..ops.pileup import aggregate_pileups, reads_to_pileups

        filters = None if args.allow_non_primary else locus_predicate()
        table, _, _ = load_reads(args.input, filters=filters)
        pileups = reads_to_pileups(table)
        if args.aggregate:
            pileups = aggregate_pileups(pileups)
        save_with_args(pileups, args.output, args, n_parts=args.parts)
        n_reads = max(table.num_rows, 1)
        print(f"wrote {pileups.num_rows} pileups from {table.num_rows} reads "
              f"(coverage ~{pileups.num_rows / n_reads:.1f}x read length)")
        return 0


@register
class AggregatePileupsCommand(Command):
    name = "aggregate_pileups"
    help = "Aggregate a pileup dataset by position/base/sample"

    def add_args(self, p: argparse.ArgumentParser) -> None:
        p.add_argument("input", help="pileup Parquet dataset")
        p.add_argument("output", help="output pileup Parquet dataset")
        p.add_argument("-parts", type=int, default=1)
        p.add_argument("-stream", action="store_true",
                       help="windowed bounded-memory aggregation "
                            "(auto-enabled for inputs over 1 GB)")
        p.add_argument("-no_stream", action="store_true")
        p.add_argument("-window_bp", type=int, default=1 << 20)
        p.add_argument("-stream_chunk_rows", type=int, default=1 << 20)
        add_parquet_args(p)

    def run(self, args) -> int:
        from ..io.parquet import load_table
        from ..ops.pileup import aggregate_pileups

        if should_stream(args, args.input):
            if args.parts != 1:
                import sys
                print("warning: -parts is ignored by the streaming path "
                      "(part size follows -stream_chunk_rows); use "
                      "-no_stream for the in-memory writer",
                      file=sys.stderr)
            from ..parallel.pipeline import streaming_aggregate_pileups
            pw = parquet_writer_kwargs(args)
            n_in, n_out = streaming_aggregate_pileups(
                args.input, args.output, window_bp=args.window_bp,
                chunk_rows=args.stream_chunk_rows,
                compression=pw["compression"] or "none",
                page_size=pw["page_size"],
                use_dictionary=pw["use_dictionary"],
                row_group_bytes=args.parquet_block_size)
            print(f"aggregated {n_in} -> {n_out} pileups")
            return 0
        pileups = load_table(args.input)
        # external data: fail loudly on null required fields (the reference
        # NPEs in combineEvidence; we raise up front)
        agg = aggregate_pileups(pileups, validate=True)
        save_with_args(agg, args.output, args, n_parts=args.parts)
        print(f"aggregated {pileups.num_rows} -> {agg.num_rows} pileups")
        return 0


@register
class Vcf2AdamCommand(Command):
    name = "vcf2adam"
    help = "Convert a VCF file to ADAM variant-context Parquet datasets"

    def add_args(self, p: argparse.ArgumentParser) -> None:
        p.add_argument("input", help="VCF file")
        p.add_argument("output", help="output basename (.v/.g/.vd datasets)")
        p.add_argument("-stream", action="store_true",
                       help="chunked bounded-memory parse, text or BCF "
                            "(auto-enabled for inputs over 1 GB)")
        p.add_argument("-no_stream", action="store_true")
        p.add_argument("-stream_chunk_rows", type=int, default=1 << 18)
        add_parquet_args(p)

    def run(self, args) -> int:
        from ..io.vcf import read_vcf

        if should_stream(args, args.input):
            from .. import schema as S
            from ..io.parquet import DatasetWriter
            from ..io.vcf import VcfStream
            pw = parquet_writer_kwargs(args)
            source = args.input
            if str(args.input).endswith(".bcf"):
                # binary records stream as decoded VCF lines
                from ..io.bcf import iter_bcf_vcf_lines
                source = iter_bcf_vcf_lines(args.input)
            writers = {ext: DatasetWriter(args.output + ext, **pw)
                       for ext in (".v", ".g", ".vd")}
            schemas = {".v": S.VARIANT_SCHEMA, ".g": S.GENOTYPE_SCHEMA,
                       ".vd": S.VARIANT_DOMAIN_SCHEMA}
            n = {".v": 0, ".g": 0, ".vd": 0}
            for v, g, d in VcfStream(source,
                                     chunk_rows=args.stream_chunk_rows):
                for ext, tbl in ((".v", v), (".g", g), (".vd", d)):
                    n[ext] += tbl.num_rows
                    writers[ext].write(tbl)
            import pyarrow.parquet as pq
            for ext, w in writers.items():
                w.close()
                if w.rows_written == 0:
                    # a sites-only VCF has no genotype rows; the dataset
                    # must still carry its schema (the in-memory path
                    # writes a schema-bearing empty part, and
                    # DatasetWriter never emits a part for zero rows)
                    pq.write_table(
                        schemas[ext].empty_table(),
                        os.path.join(w.path, "part-r-00000.parquet"))
            print(f"wrote {n['.v']} variants, {n['.g']} genotypes, "
                  f"{n['.vd']} domains to {args.output}.{{v,g,vd}}")
            return 0
        variants, genotypes, domains, _ = read_vcf(args.input)
        # three datasets, the reference's .v/.g/.vd convention
        # (AdamRDDFunctions.scala:330-363)
        save_with_args(variants, args.output + ".v", args)
        save_with_args(genotypes, args.output + ".g", args)
        save_with_args(domains, args.output + ".vd", args)
        print(f"wrote {variants.num_rows} variants, {genotypes.num_rows} "
              f"genotypes, {domains.num_rows} domains to {args.output}.{{v,g,vd}}")
        return 0


@register
class Adam2VcfCommand(Command):
    name = "adam2vcf"
    help = "Convert ADAM variant-context Parquet datasets to VCF"

    def add_args(self, p: argparse.ArgumentParser) -> None:
        p.add_argument("input", help="basename of .v/.g datasets")
        p.add_argument("output", help="output VCF file")
        p.add_argument("-stream", action="store_true",
                       help="windowed bounded-memory VCF text (plain .vcf "
                            "only; auto-enabled over 1 GB)")
        p.add_argument("-no_stream", action="store_true")

    def run(self, args) -> int:
        import os
        import pyarrow as pa
        from .. import schema as S
        from ..io.parquet import load_table
        from ..io.vcf import write_vcf

        wants_stream = should_stream(args, args.input + ".v",
                                     args.input + ".g")
        compressed_out = str(args.output).endswith((".gz", ".bgz", ".bcf"))
        if wants_stream and compressed_out:
            import sys
            print("warning: streaming adam2vcf writes plain .vcf only; "
                  "buffering the whole dataset for compressed/BCF output "
                  "(-no_stream silences this)", file=sys.stderr)
        if wants_stream and not compressed_out:
            from ..parallel.pipeline import streaming_adam2vcf
            n_v, n_g = streaming_adam2vcf(args.input, args.output)
            print(f"wrote {n_v} variants / {n_g} genotypes to "
                  f"{args.output}")
            return 0
        variants = load_table(args.input + ".v")
        if os.path.exists(args.input + ".g"):
            genotypes = load_table(args.input + ".g")
        else:
            genotypes = pa.Table.from_pydict(
                {n: [] for n in S.GENOTYPE_SCHEMA.names},
                schema=S.GENOTYPE_SCHEMA)
        write_vcf(variants, genotypes, args.output)
        print(f"wrote {variants.num_rows} variants to {args.output}")
        return 0


@register
class ComputeVariantsCommand(Command):
    name = "compute_variants"
    help = "Compute variant data from genotypes (cli/ComputeVariants.scala)"

    def add_args(self, p: argparse.ArgumentParser) -> None:
        p.add_argument("input", help="genotype Parquet dataset (.g)")
        p.add_argument("output", help="output basename (.v/.g datasets)")
        p.add_argument("-runValidation", action="store_true")
        p.add_argument("-runStrictValidation", action="store_true")
        p.add_argument("-stream", action="store_true",
                       help="windowed bounded-memory conversion "
                            "(auto-enabled for inputs over 1 GB)")
        p.add_argument("-no_stream", action="store_true",
                       help="force the in-memory path for large inputs")

    def run(self, args) -> int:
        from ..converters.genotypes_to_variants import convert_genotypes
        from ..io.parquet import load_table, save_table

        if should_stream(args, args.input):
            from ..parallel.pipeline import streaming_compute_variants
            n_geno, n_var = streaming_compute_variants(
                args.input, args.output,
                validate=args.runValidation or args.runStrictValidation,
                strict=args.runStrictValidation)
            print(f"computed {n_var} variants from {n_geno} genotypes")
            return 0
        genotypes = load_table(args.input)
        variants = convert_genotypes(
            genotypes, validate=args.runValidation or args.runStrictValidation,
            strict=args.runStrictValidation)
        save_table(variants, args.output + ".v")
        save_table(genotypes, args.output + ".g")
        print(f"computed {variants.num_rows} variants from "
              f"{genotypes.num_rows} genotypes")
        return 0


@register
class CompareCommand(Command):
    name = "compare"
    help = "Compare two read datasets pipeline-concordance style"

    def add_args(self, p: argparse.ArgumentParser) -> None:
        p.add_argument("input1", nargs="?")
        p.add_argument("input2", nargs="?")
        p.add_argument("-comparisons", default=None,
                       help="comma-separated comparison names (default: all)")
        p.add_argument("-list_comparisons", action="store_true")
        p.add_argument("-directory", default=None,
                       help="directory to write per-metric histogram files")
        p.add_argument("-stream", action="store_true",
                       help="name-hash bucketed bounded-memory compare "
                            "(auto-enabled when the inputs total over "
                            "1 GB)")
        p.add_argument("-no_stream", action="store_true",
                       help="force the in-memory engine for large inputs")
        p.add_argument("-buckets", type=int, default=32,
                       help="streaming: number of name-hash buckets "
                            "(memory ~ input / buckets)")

    def run(self, args) -> int:
        from ..compare.engine import (ComparisonTraversalEngine,
                                      DEFAULT_COMPARISONS, find_comparison)
        if args.list_comparisons:
            print("\nAvailable comparisons:")
            for c in DEFAULT_COMPARISONS.values():
                print(f"\t{c.name:>10} : {c.description}")
            return 0
        if not args.input1 or not args.input2:
            print("compare: INPUT1 and INPUT2 required", file=__import__("sys").stderr)
            return 2
        names = (args.comparisons.split(",") if args.comparisons
                 else list(DEFAULT_COMPARISONS))
        comps = [find_comparison(n) for n in names]
        p1, p2 = args.input1.split(","), args.input2.split(",")

        def print_summary(n1, u1, n2, u2, hists):
            # format mirrors cli/CompareAdam.scala:148-174; one printer
            # for both engines so the outputs cannot drift
            print(f"{'INPUT1':>15}: {args.input1}")
            print(f"\t{'total-reads':>15}: {n1}")
            print(f"\t{'unique-reads':>15}: {u1}")
            print(f"{'INPUT2':>15}: {args.input2}")
            print(f"\t{'total-reads':>15}: {n2}")
            print(f"\t{'unique-reads':>15}: {u2}")
            for comp in comps:
                hist = hists[comp.name]
                count = hist.count()
                ident = hist.count_identical()
                diff_frac = (count - ident) / count if count else 0.0
                print()
                print(comp.name)
                print(f"\t{'count':>15}: {count}")
                print(f"\t{'identity':>15}: {ident}")
                print(f"\t{'diff%':>15}: {100.0 * diff_frac:.5f}")
                if args.directory:
                    os.makedirs(args.directory, exist_ok=True)
                    with open(os.path.join(args.directory,
                                           comp.name + ".txt"), "w") as f:
                        hist.write(f)

        if should_stream(args, *(p1 + p2)):
            from ..compare.engine import streaming_compare
            r = streaming_compare(p1, p2, comps, n_buckets=args.buckets)
            t = r["totals"]
            print_summary(t["n_names_1"], t["unique_to_1"],
                          t["n_names_2"], t["unique_to_2"],
                          r["histograms"])
            return 0
        from ..io.dispatch import load_reads_union
        # comma-separated paths per input union with id reconciliation
        # (the reference's -recurse multi-file load, CompareAdam.scala:70-86)
        t1, sd1, _ = load_reads_union(p1)
        t2, sd2, _ = load_reads_union(p2)
        engine = ComparisonTraversalEngine(t1, t2, sd1, sd2)
        # one combined traversal for every requested metric
        # (CombinedComparisons, Comparisons.scala:112-152)
        print_summary(engine.n_names_1, engine.unique_to_1(),
                      engine.n_names_2, engine.unique_to_2(),
                      engine.aggregate_all(comps))
        return 0


@register
class FindReadsCommand(Command):
    name = "findreads"
    help = "Find reads that match comparative criteria (e.g. positions!=0)"

    def add_args(self, p: argparse.ArgumentParser) -> None:
        p.add_argument("input1")
        p.add_argument("input2")
        p.add_argument("filter",
                       help='e.g. "positions!=0" or "dupemismatch=(1,0)"; '
                            "semicolon-separated filters AND together")
        p.add_argument("-file", default=None,
                       help="write matching read names to this file")
        p.add_argument("-stream", action="store_true",
                       help="name-hash bucketed bounded-memory traversal "
                            "(auto-enabled over 1 GB)")
        p.add_argument("-no_stream", action="store_true")

    def run(self, args) -> int:
        from ..compare.engine import ComparisonTraversalEngine, parse_filters
        from ..io.dispatch import load_reads_union
        p1, p2 = args.input1.split(","), args.input2.split(",")
        filters = parse_filters(args.filter)
        if should_stream(args, *(p1 + p2)):
            from ..compare.engine import streaming_compare
            # comparisons=[]: the filters drive the traversal; histogram
            # aggregation would be pure waste here
            r = streaming_compare(p1, p2, [], find_filters=filters)
            names = sorted(r["matching_names"])
        else:
            t1, sd1, _ = load_reads_union(p1)
            t2, sd2, _ = load_reads_union(p2)
            engine = ComparisonTraversalEngine(t1, t2, sd1, sd2)
            names = engine.find(filters)
        if args.file:
            with open(args.file, "w") as f:
                f.write("\n".join(names) + ("\n" if names else ""))
        else:
            for n in names:
                print(n)
        return 0


@register
class Fasta2AdamCommand(Command):
    name = "fasta2adam"
    help = "Convert a FASTA reference to an ADAM contig Parquet dataset"

    def add_args(self, p: argparse.ArgumentParser) -> None:
        p.add_argument("input", help="FASTA file")
        p.add_argument("output", help="output Parquet dataset")
        p.add_argument("-reads", default=None,
                       help="reads file whose dictionary supplies contig ids "
                            "(cli/Fasta2Adam.scala:57-82)")
        p.add_argument("-stream", action="store_true",
                       help="bounded-memory per-contig conversion "
                            "(auto-enabled for inputs over 1 GB)")
        p.add_argument("-no_stream", action="store_true")
        add_parquet_args(p)

    def _remap_ids(self, contigs, sd):
        import pyarrow as pa
        names = contigs.column("contigName").to_pylist()
        new_ids = [sd[n].id if n in sd else None for n in names]
        return contigs.set_column(
            contigs.column_names.index("contigId"), "contigId",
            pa.array(new_ids, pa.int32()))

    def run(self, args) -> int:
        from ..io.fasta import contig_batches, read_fasta

        sd = None
        if args.reads:
            from ..io.dispatch import (load_reads,
                                       sequence_dictionary_from_reads)
            rtable, sd, _ = load_reads(args.reads)
            if sd is None:
                sd = sequence_dictionary_from_reads(rtable)
        if should_stream(args, args.input):
            # bounded-memory path (FastaConverter.scala:27-166 converts
            # distributed; here contigs flush to parts as they complete)
            from ..io.parquet import DatasetWriter
            kw = parquet_writer_kwargs(args)
            if kw.get("compression") is None:       # "uncompressed"
                kw["compression"] = "none"
            kw["row_group_bytes"] = getattr(args, "parquet_block_size",
                                            None)
            n = 0
            with DatasetWriter(args.output, **kw) as w:
                for contigs in contig_batches(args.input, url=args.input):
                    if sd is not None:
                        contigs = self._remap_ids(contigs, sd)
                    w.write(contigs)
                    n += contigs.num_rows
            print(f"wrote {n} contigs to {args.output}")
            return 0
        contigs = read_fasta(args.input)
        if sd is not None:
            contigs = self._remap_ids(contigs, sd)
        save_with_args(contigs, args.output, args)
        print(f"wrote {contigs.num_rows} contigs to {args.output}")
        return 0


@register
class MpileupCommand(Command):
    name = "mpileup"
    help = "Output samtools mpileup-style text (cli/MpileupCommand.scala)"

    def add_args(self, p: argparse.ArgumentParser) -> None:
        p.add_argument("input", help="SAM/BAM file or ADAM Parquet dataset")
        p.add_argument("-stream", action="store_true",
                       help="windowed bounded-memory pileup text "
                            "(auto-enabled for inputs over 1 GB)")
        p.add_argument("-no_stream", action="store_true")

    def run(self, args) -> int:
        from ..io.dispatch import load_reads
        from ..ops.pileup import reads_to_pileups

        if should_stream(args, args.input):
            from ..parallel.pipeline import windowed_pileups
            # windows partition positions exactly and emit in genome
            # order, so per-window text == the globally sorted traversal
            with windowed_pileups(args.input,
                                  allow_non_primary=True) as (_n, wins):
                for wtbl in wins:
                    self._emit(wtbl)
            return 0
        table, _, _ = load_reads(args.input)
        self._emit(reads_to_pileups(table))
        return 0

    def _emit(self, pileups) -> None:
        rows = pileups.sort_by([("referenceId", "ascending"),
                                ("position", "ascending")]).to_pylist()
        # group by position; event layout mirrors MpileupCommand.scala:47-78
        from itertools import groupby
        for (name, pos), group in groupby(
                rows, key=lambda r: (r["referenceName"], r["position"])):
            group = list(group)
            aligned = [r for r in group if r["rangeOffset"] is None]
            inserts = [r for r in group if r["rangeOffset"] is not None and
                       r["readBase"] is not None and not r["numSoftClipped"]]
            deletes = [r for r in group if r["readBase"] is None]
            ref_base = next((r["referenceBase"] for r in aligned + deletes
                             if r["referenceBase"]), "?")
            # numReads = aligned events + whole insertions + deletions —
            # soft clips excluded, insertions counted once
            # (PileupTraversable event model)
            n_ins = len({r["readName"] for r in inserts})
            depth = len(aligned) + n_ins + len(deletes)
            out = [f"{name} {pos} {ref_base} {depth} "]
            for r in aligned:
                if r["readBase"] == r["referenceBase"]:
                    out.append("," if r["numReverseStrand"] else ".")
                else:
                    b = r["readBase"] or "?"
                    out.append(b.lower() if r["numReverseStrand"] else b)
            for r in deletes:
                out.append(f"-1{ref_base}")
            for r in inserts:
                if r["rangeOffset"] == 0:
                    # whole insertion reported once, at its first base
                    ins = [x for x in inserts
                           if x["readName"] == r["readName"]]
                    seq = "".join(x["readBase"] for x in sorted(
                        ins, key=lambda x: x["rangeOffset"]))
                    out.append(f"+{len(seq)}{seq}")
            print("".join(out))


@register
class PrintTagsCommand(Command):
    name = "print_tags"
    help = "Print the distinct attribute tags and their counts"

    def add_args(self, p: argparse.ArgumentParser) -> None:
        p.add_argument("input")
        p.add_argument("-list", dest="list_n", type=int, default=None,
                       help="also list the first N attribute fields")
        p.add_argument("-count", default=None,
                       help="comma-separated tags: print value census")

    def run(self, args) -> int:
        from collections import Counter
        from .. import schema as S
        from ..io.stream import open_read_stream
        from ..packing import column_int64

        # counters accumulate per streamed chunk — the census is a
        # monoid, so the whole-file table never materializes
        to_count = set(args.count.split(",")) if args.count else set()
        tag_counts: Counter = Counter()
        value_counts: dict = {t: Counter() for t in to_count}
        n_usable = 0
        listed = args.list_n
        stream = open_read_stream(args.input,
                                  columns=("attributes", "flags"))
        for table in stream:
            flags = column_int64(table, "flags", 0)
            attrs = table.column("attributes").to_pylist()
            # the reference filters failed-vendor-quality reads
            # (PrintTags.scala:70)
            usable = [(a or "") for a, f in zip(attrs, flags)
                      if not (f & S.FLAG_QC_FAIL)]
            n_usable += len(usable)
            if listed:
                for a in usable[:listed]:
                    print(a)
                listed -= min(len(usable), listed)
            for a in usable:
                for field in a.split("\t") if a else []:
                    # tag census stays a cheap split (the CLI hot loop);
                    # util.attributes provides the typed view when values
                    # matter
                    tag = field.split(":", 1)[0]
                    tag_counts[tag] += 1
                    if tag in to_count:
                        # census keys keep the on-disk SAM encoding (the
                        # typed value's repr splits '3' vs '3.0' buckets)
                        value_counts[tag][field.split(":", 2)[-1]] += 1
        for tag, count in tag_counts.most_common():
            print(f"{tag:>3}\t{count}")
            for value, vc in value_counts.get(tag, {}).items():
                print(f"\t{vc:>10}\t{value}")
        print(f"Total: {n_usable}")
        return 0


@register
class PrintCommand(Command):
    name = "print"
    help = "Print an ADAM Parquet dataset (or SAM) as records"

    def add_args(self, p: argparse.ArgumentParser) -> None:
        p.add_argument("input")
        p.add_argument("-limit", type=int, default=25)

    def run(self, args) -> int:
        from ..io.stream import open_read_stream

        # stream and stop: printing 25 rows of a 100 GB dataset must not
        # load the dataset (the reference's driver-side
        # ParquetFileTraversable iterates the same way)
        remaining = args.limit
        stream = open_read_stream(
            args.input, chunk_rows=max(min(remaining, 1 << 16), 1))
        for table in stream:
            for row in table.slice(0, remaining).to_pylist():
                print({k: v for k, v in row.items() if v is not None})
            remaining -= min(table.num_rows, remaining)
            if remaining <= 0:
                break
        return 0


@register
class ListDictCommand(Command):
    name = "listdict"
    help = "Print the sequence dictionary of a reads file"

    def add_args(self, p: argparse.ArgumentParser) -> None:
        p.add_argument("input")

    def run(self, args) -> int:
        from ..io.stream import open_read_stream
        from ..models.dictionary import SequenceDictionary
        from ..parallel.pipeline import _accumulate_seq_records

        # SAM/BAM answer from the header without reading the body; Parquet
        # accumulates the denormalized columns (primary AND mate) chunk by
        # chunk (the reference's scan+dedup, AdamContext.scala:175-236) —
        # either way the file never materializes whole.  The projection
        # intersects with the dataset schema so a column-subset dataset
        # cannot fail the select (sequence/qual bytes are the bulk of a
        # reads file; reading them to list contigs would be absurd).
        wanted = ("referenceId", "referenceName", "referenceLength",
                  "referenceUrl", "mateReferenceId", "mateReference",
                  "mateReferenceLength", "mateReferenceUrl")
        columns = None
        if os.path.isdir(args.input) or args.input.endswith(".parquet"):
            import pyarrow.dataset as ds
            avail = set(ds.dataset(args.input, format="parquet").schema.names)
            columns = [c for c in wanted if c in avail] or None
        stream = open_read_stream(args.input, columns=columns)
        seq_dict = stream.seq_dict
        if seq_dict is None:
            seen: dict = {}
            for table in stream:
                _accumulate_seq_records(table, seen)
            seq_dict = SequenceDictionary(seen.values())
        for rec in seq_dict:
            print(f"{rec.id}\t{rec.name}\t{rec.length}\t{rec.url or ''}")
        return 0


@register
class StatusCommand(Command):
    name = "status"
    help = ("Render a serve spool's durable status docs: liveness, "
            "backlog, rung, tenants, workers (works live or crashed)")

    def add_args(self, p: argparse.ArgumentParser) -> None:
        p.add_argument("spool", help="the server's spool directory")
        p.add_argument("-json", dest="as_json", action="store_true",
                       help="print the joined view as JSON instead of "
                            "the human rendering")
        p.add_argument("-follow", action="store_true",
                       help="re-render every -interval seconds until "
                            "interrupted")
        p.add_argument("-interval", type=float, default=2.0,
                       help="-follow refresh cadence in seconds")
        p.add_argument("-count", type=int, default=None, metavar="N",
                       help="-follow: stop after N renders (default: "
                            "until interrupted)")

    def run(self, args) -> int:
        import json as _json
        import time as _time

        from ..serve import status as status_mod

        if not os.path.isdir(args.spool):
            print(f"status: no such spool: {args.spool}",
                  file=sys.stderr)
            return 2
        n = 0
        while True:
            view = status_mod.collect_status(args.spool)
            if args.as_json:
                print(_json.dumps(view, sort_keys=True, default=str))
            else:
                print(status_mod.render_status(view))
            n += 1
            if not args.follow or (args.count is not None
                                   and n >= args.count):
                return 0
            try:
                _time.sleep(max(args.interval, 0.05))
            except KeyboardInterrupt:
                return 0


@register
class TopCommand(Command):
    name = "top"
    help = ("Live-updating serve status (the -follow view with screen "
            "refresh; rendered purely from durable docs)")

    def add_args(self, p: argparse.ArgumentParser) -> None:
        p.add_argument("spool", help="the server's spool directory")
        p.add_argument("-interval", type=float, default=1.0,
                       help="refresh cadence in seconds")
        p.add_argument("-count", type=int, default=None, metavar="N",
                       help="stop after N renders (default: until "
                            "interrupted)")

    def run(self, args) -> int:
        import time as _time

        from ..serve import status as status_mod

        if not os.path.isdir(args.spool):
            print(f"top: no such spool: {args.spool}", file=sys.stderr)
            return 2
        clear = sys.stdout.isatty()
        n = 0
        while True:
            view = status_mod.collect_status(args.spool)
            body = status_mod.render_status(view)
            if clear:
                # home + clear-below, not full clear: no flicker
                sys.stdout.write("\x1b[H\x1b[J")
            print(body)
            sys.stdout.flush()
            n += 1
            if args.count is not None and n >= args.count:
                return 0
            try:
                _time.sleep(max(args.interval, 0.05))
            except KeyboardInterrupt:
                return 0


@register
class GcCommand(Command):
    name = "gc"
    help = ("Collect retired spool artifacts (result docs, claim "
            "tables, ring files, rotated series) under the retention "
            "floors; serve loops also sweep periodically")

    def add_args(self, p: argparse.ArgumentParser) -> None:
        from ..serve import retention

        p.add_argument("spool", help="the spool (or fleet) directory")
        p.add_argument("-min_age_s", type=float,
                       default=retention.DEFAULT_MIN_AGE_S,
                       help="age floor: never collect anything "
                            "younger than this many seconds")
        p.add_argument("-keep", type=int, metavar="N",
                       default=retention.DEFAULT_KEEP_PER_KIND,
                       help="count floor: the N newest of each "
                            "artifact kind always survive")
        p.add_argument("-dry_run", action="store_true",
                       help="decide + print, delete nothing")

    def run(self, args) -> int:
        from ..serve import retention

        if not os.path.isdir(args.spool):
            print(f"gc: no such spool: {args.spool}", file=sys.stderr)
            return 2
        d = retention.sweep(args.spool, min_age_s=args.min_age_s,
                            keep_per_kind=args.keep,
                            dry_run=args.dry_run)
        verb = "would collect" if args.dry_run else "removed"
        print(f"gc: {verb} {len(d['collect'])} of "
              f"{len(d['inputs']['candidates'])} candidate(s) "
              f"({d['reason']})")
        for rel in d["collect"]:
            print(f"  - {rel}")
        return 0


@register
class ExplainCommand(Command):
    name = "explain"
    help = ("Reconstruct one served job's causal timeline (queue "
            "position, admission/placement inputs, retries, requeues, "
            "rung/breaker context) from durable artifacts alone")

    def add_args(self, p: argparse.ArgumentParser) -> None:
        p.add_argument("spool", help="the server's spool directory")
        p.add_argument("job", help="job id (the result doc's stem, "
                                   "e.g. 00000003-tenantA)")
        # NOT -trace / -metrics: main() owns those for THIS process's
        # own telemetry; these name artifacts a PAST run left behind
        p.add_argument("-events", action="append", default=[],
                       metavar="PATH",
                       help="extra event sidecar(s) beyond spool "
                            "auto-discovery (repeatable)")
        p.add_argument("-series", action="append", default=[],
                       metavar="PATH",
                       help="extra series.jsonl file(s) (repeatable)")
        p.add_argument("-timeline", action="append", default=[],
                       metavar="PATH",
                       help="extra .trace.json file(s) (repeatable)")
        p.add_argument("-json", dest="as_json", action="store_true",
                       help="print the full timeline doc as JSON")

    def run(self, args) -> int:
        import json as _json

        from ..serve.explain import explain_job, render_timeline

        if not os.path.isdir(args.spool):
            print(f"explain: no such spool: {args.spool}",
                  file=sys.stderr)
            return 2
        doc = explain_job(args.spool, args.job, events=args.events,
                          series=args.series, timelines=args.timeline)
        if args.as_json:
            print(_json.dumps(doc, sort_keys=True, default=str))
        else:
            print(render_timeline(doc))
        return 0 if doc["found"] else 3
