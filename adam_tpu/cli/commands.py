"""CLI subcommands (reference registry: cli/AdamMain.scala:23-37).

Implemented so far:
  * ``flagstat``  — cli/FlagStat.scala:38-109
  * ``bam2adam``  — cli/Bam2Adam.scala:41-126 (SAM/BAM -> Parquet dataset)
  * ``print``     — cli/PrintAdam.scala:35-50
  * ``listdict``  — cli/ListDict.scala:36-53
"""

from __future__ import annotations

import argparse

from .main import Command, register


@register
class FlagStatCommand(Command):
    name = "flagstat"
    help = "Print statistics on reads (identical counters to samtools flagstat)"

    def add_args(self, p: argparse.ArgumentParser) -> None:
        p.add_argument("input", help="SAM/BAM file or ADAM Parquet dataset")

    def run(self, args) -> int:
        from ..io.dispatch import FLAGSTAT_COLUMNS, load_reads
        from ..ops.flagstat import flagstat, format_report
        from ..packing import pack_reads

        # project just the 4 flagstat columns
        # (the reference's 13-field projection, cli/FlagStat.scala:50-57)
        table, _, _ = load_reads(args.input, columns=FLAGSTAT_COLUMNS)
        batch = pack_reads(table, with_bases=False, with_cigar=False)
        failed, passed = flagstat(batch)
        print(format_report(failed, passed))
        return 0


@register
class Bam2AdamCommand(Command):
    name = "bam2adam"
    help = "Convert a SAM/BAM file to an ADAM Parquet dataset"

    def add_args(self, p: argparse.ArgumentParser) -> None:
        p.add_argument("input", help="SAM/BAM file")
        p.add_argument("output", help="output Parquet dataset directory")
        p.add_argument("-parts", type=int, default=1,
                       help="number of part files to write")
        p.add_argument("-compression", default="zstd",
                       choices=["zstd", "snappy", "gzip", "none"])

    def run(self, args) -> int:
        from ..io.dispatch import load_reads
        from ..io.parquet import save_table

        table, _, _ = load_reads(args.input)
        save_table(table, args.output,
                   compression=None if args.compression == "none" else args.compression,
                   n_parts=args.parts)
        print(f"wrote {table.num_rows} reads to {args.output}")
        return 0


@register
class TransformCommand(Command):
    name = "transform"
    help = "Read pre-processing pipeline (markdup/BQSR/realign/sort)"

    def add_args(self, p: argparse.ArgumentParser) -> None:
        # flag names mirror cli/Transform.scala:40-60
        p.add_argument("input", help="SAM/BAM file or ADAM Parquet dataset")
        p.add_argument("output", help="output Parquet dataset directory "
                                      "(or .sam path)")
        p.add_argument("-mark_duplicate_reads", action="store_true")
        p.add_argument("-recalibrate_base_qualities", action="store_true")
        p.add_argument("-dbsnp_sites", default=None,
                       help="sites-only VCF masking known SNPs during BQSR")
        p.add_argument("-realignIndels", action="store_true")
        p.add_argument("-sort_reads", action="store_true")
        p.add_argument("-parts", type=int, default=1)

    def run(self, args) -> int:
        from ..io.dispatch import load_reads, sequence_dictionary_from_reads
        from ..io.parquet import save_table

        table, seq_dict, rg_dict = load_reads(args.input)
        if args.mark_duplicate_reads:
            from ..ops.markdup import mark_duplicates
            table = mark_duplicates(table)
        if args.recalibrate_base_qualities:
            from ..bqsr.recalibrate import recalibrate_base_qualities
            from ..models.snptable import SnpTable
            snp = SnpTable.from_vcf(args.dbsnp_sites) if args.dbsnp_sites \
                else None
            table = recalibrate_base_qualities(table, snp)
        if args.realignIndels:
            from ..realign.realigner import realign_indels
            table = realign_indels(table)
        if args.sort_reads:
            from ..ops.sort import sort_reads
            table = sort_reads(table)
        if args.output.endswith(".sam"):
            from ..io.dispatch import record_group_dictionary_from_reads
            from ..io.sam import write_sam
            if seq_dict is None:
                seq_dict = sequence_dictionary_from_reads(table)
            if rg_dict is None:
                rg_dict = record_group_dictionary_from_reads(table)
            write_sam(table, seq_dict, args.output, rg_dict)
        else:
            save_table(table, args.output, n_parts=args.parts)
        print(f"wrote {table.num_rows} reads to {args.output}")
        return 0


@register
class Reads2RefCommand(Command):
    name = "reads2ref"
    help = "Convert reads to pileups (cli/Reads2Ref.scala:39-75)"

    def add_args(self, p: argparse.ArgumentParser) -> None:
        p.add_argument("input", help="SAM/BAM file or ADAM Parquet dataset")
        p.add_argument("output", help="output pileup Parquet dataset")
        p.add_argument("-aggregate", action="store_true")
        p.add_argument("-allow_non_primary", action="store_true",
                       help="skip the locus predicate filter")
        p.add_argument("-parts", type=int, default=1)

    def run(self, args) -> int:
        from ..io.dispatch import load_reads
        from ..io.parquet import locus_predicate, save_table
        from ..ops.pileup import aggregate_pileups, reads_to_pileups

        filters = None if args.allow_non_primary else locus_predicate()
        table, _, _ = load_reads(args.input, filters=filters)
        pileups = reads_to_pileups(table)
        if args.aggregate:
            pileups = aggregate_pileups(pileups)
        save_table(pileups, args.output, n_parts=args.parts)
        n_reads = max(table.num_rows, 1)
        print(f"wrote {pileups.num_rows} pileups from {table.num_rows} reads "
              f"(coverage ~{pileups.num_rows / n_reads:.1f}x read length)")
        return 0


@register
class AggregatePileupsCommand(Command):
    name = "aggregate_pileups"
    help = "Aggregate a pileup dataset by position/base/sample"

    def add_args(self, p: argparse.ArgumentParser) -> None:
        p.add_argument("input", help="pileup Parquet dataset")
        p.add_argument("output", help="output pileup Parquet dataset")
        p.add_argument("-parts", type=int, default=1)

    def run(self, args) -> int:
        from ..io.parquet import load_table, save_table
        from ..ops.pileup import aggregate_pileups

        pileups = load_table(args.input)
        # external data: fail loudly on null required fields (the reference
        # NPEs in combineEvidence; we raise up front)
        agg = aggregate_pileups(pileups, validate=True)
        save_table(agg, args.output, n_parts=args.parts)
        print(f"aggregated {pileups.num_rows} -> {agg.num_rows} pileups")
        return 0


@register
class PrintCommand(Command):
    name = "print"
    help = "Print an ADAM Parquet dataset (or SAM) as records"

    def add_args(self, p: argparse.ArgumentParser) -> None:
        p.add_argument("input")
        p.add_argument("-limit", type=int, default=25)

    def run(self, args) -> int:
        from ..io.dispatch import load_reads
        table, _, _ = load_reads(args.input)
        for row in table.slice(0, args.limit).to_pylist():
            print({k: v for k, v in row.items() if v is not None})
        return 0


@register
class ListDictCommand(Command):
    name = "listdict"
    help = "Print the sequence dictionary of a reads file"

    def add_args(self, p: argparse.ArgumentParser) -> None:
        p.add_argument("input")

    def run(self, args) -> int:
        from ..io.dispatch import load_reads, sequence_dictionary_from_reads
        table, seq_dict, _ = load_reads(args.input)
        if seq_dict is None:
            seq_dict = sequence_dictionary_from_reads(table)
        for rec in seq_dict:
            print(f"{rec.id}\t{rec.name}\t{rec.length}\t{rec.url or ''}")
        return 0
