"""Biallelic SNP genotyping over pileup count tensors — integer-exact.

The genotype-likelihood kernel is a pure function of the per-position
count tensor (parallel/pileup.py channels), written ENTIRELY in int32
arithmetic so the batched device kernel and the scalar Python oracle
produce the same integers by construction — bit-identical VCF output is
an arithmetic identity, not a tolerance (docs/CALL.md §oracle contract).

Model (per position, per sample):

* reference allele = plurality base among A/C/G/T counts (first max on
  ties — ``argmax`` and ``list.index(max(...))`` agree on tie order);
  the count tensor carries no reference sequence, so the plurality base
  IS the site's reference hypothesis (mpileup's consensus fallback);
* alt allele = plurality of the remaining three bases;
* with ``r`` ref-supporting and ``a`` alt-supporting bases and
  ``qavg = QUAL_SUM // COVERAGE`` the phred likelihoods are
  ``PL(0/0) = a*qavg`` (every alt base a miscall),
  ``PL(1/1) = r*qavg``, and
  ``PL(0/1) = (30103*(r+a) + 5000) // 10000`` — the integer phred of
  0.5^(r+a) (10*log10(2) = 3.0103, scaled to avoid floats);
* genotype = first argmin of the PL triple, GQ = min(second-best PL
  minus best PL, 99), reported PLs normalize to min 0 (VCF convention).

Coverage per position must stay under ~71k (30103*(r+a) in int32) and
channel sums under 2^31; both hold by orders of magnitude for any input
the streamed pass admits.
"""

from __future__ import annotations

import io as _io
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import pyarrow as pa

from .. import schema as S
from ..converters.genotypes_to_variants import convert_genotypes
from ..io.vcf import _rows_to_table, write_vcf
from ..models.dictionary import SequenceDictionary, SequenceRecord
from ..parallel.pileup import (CH_COVERAGE, CH_MAPQ, CH_QUAL, CH_REVERSE)

#: genotype-field columns of the kernel output, in order
GT_FIELDS = ("ref_code", "alt_code", "alt_count", "gt", "gq",
             "pl_ref", "pl_het", "pl_alt", "depth", "qual_avg",
             "mapq_avg", "fwd")
(GF_REF, GF_ALT, GF_ALT_COUNT, GF_GT, GF_GQ, GF_PL0, GF_PL1, GF_PL2,
 GF_DEPTH, GF_QAVG, GF_MAPQ, GF_FWD) = range(len(GT_FIELDS))

#: 10000 * 10*log10(2) — the het PL slope, integer-scaled
_PHRED_HALF_NUM = 30103
_PHRED_SCALE = 10000
_MAX_GQ = 99


@jax.jit
def genotype_fields_kernel(counts) -> jnp.ndarray:
    """[span, N_CHANNELS] int32 counts -> [span, len(GT_FIELDS)] int32.

    One compiled shape per stripe span; the fold over shards/tenants
    happened BEFORE this kernel (counts are an exact monoid), so running
    it once on the merged tensor is what makes solo/fleet/packed output
    identical by construction.
    """
    c = counts.astype(jnp.int32)
    bc = c[:, :4]                                   # A/C/G/T counts
    cov = c[:, CH_COVERAGE]
    covn = jnp.maximum(cov, 1)
    qavg = c[:, CH_QUAL] // covn
    mapq_avg = c[:, CH_MAPQ] // covn
    fwd = cov - c[:, CH_REVERSE]
    ref = jnp.argmax(bc, axis=1).astype(jnp.int32)
    masked = jnp.where(jnp.arange(4)[None, :] == ref[:, None], -1, bc)
    alt = jnp.argmax(masked, axis=1).astype(jnp.int32)
    r = jnp.take_along_axis(bc, ref[:, None], axis=1)[:, 0]
    a = jnp.take_along_axis(bc, alt[:, None], axis=1)[:, 0]
    pl0 = a * qavg
    pl2 = r * qavg
    pl1 = (_PHRED_HALF_NUM * (r + a) + _PHRED_SCALE // 2) // _PHRED_SCALE
    pls = jnp.stack([pl0, pl1, pl2], axis=1)
    gt = jnp.argmin(pls, axis=1).astype(jnp.int32)
    mn = jnp.min(pls, axis=1)
    mx = jnp.max(pls, axis=1)
    second = pl0 + pl1 + pl2 - mn - mx
    gq = jnp.minimum(second - mn, _MAX_GQ)
    return jnp.stack([ref, alt, a, gt, gq, pl0 - mn, pl1 - mn, pl2 - mn,
                      cov, qavg, mapq_avg, fwd], axis=1)


def genotype_site(c) -> dict:
    """The kernel's scalar twin: one position's counts (12 ints) -> the
    same GT_FIELDS integers in plain Python (the oracle's genotyper)."""
    cov = int(c[CH_COVERAGE])
    covn = max(cov, 1)
    qavg = int(c[CH_QUAL]) // covn
    mapq_avg = int(c[CH_MAPQ]) // covn
    fwd = cov - int(c[CH_REVERSE])
    bc = [int(c[0]), int(c[1]), int(c[2]), int(c[3])]
    ref = bc.index(max(bc))
    masked = list(bc)
    masked[ref] = -1
    alt = masked.index(max(masked))
    r, a = bc[ref], bc[alt]
    pl0, pl2 = a * qavg, r * qavg
    pl1 = (_PHRED_HALF_NUM * (r + a) + _PHRED_SCALE // 2) // _PHRED_SCALE
    pls = [pl0, pl1, pl2]
    mn, mx = min(pls), max(pls)
    gt = pls.index(mn)
    gq = min(pl0 + pl1 + pl2 - mn - mx - mn, _MAX_GQ)
    return dict(ref_code=ref, alt_code=alt, alt_count=a, gt=gt, gq=gq,
                pl_ref=pl0 - mn, pl_het=pl1 - mn, pl_alt=pl2 - mn,
                depth=cov, qual_avg=qavg, mapq_avg=mapq_avg, fwd=fwd)


def should_emit(fields: dict, min_depth: int, min_alt: int) -> bool:
    """The shared emission floor: a non-ref call with enough total and
    alt-supporting evidence."""
    return (fields["gt"] > 0 and fields["depth"] >= min_depth
            and fields["alt_count"] >= min_alt)


def calls_from_fields(out_np: np.ndarray, *, refid: int, refname: str,
                      stripe_start: int, sample: str,
                      min_depth: int, min_alt: int) -> List[dict]:
    """Kernel output [span, GT_FIELDS] -> emitted call dicts (host side
    of the device path; the oracle builds the same dicts from
    :func:`genotype_site`)."""
    emit = np.flatnonzero(
        (out_np[:, GF_GT] > 0) & (out_np[:, GF_DEPTH] >= min_depth)
        & (out_np[:, GF_ALT_COUNT] >= min_alt))
    calls = []
    for i in emit:
        row = out_np[i]
        calls.append(dict(
            refid=int(refid), refname=refname,
            pos=int(stripe_start + i), sample=sample,
            fields={k: int(row[j]) for j, k in enumerate(GT_FIELDS)}))
    return calls


def build_call_tables(calls: List[dict],
                      contigs: Dict[int, Tuple[str, Optional[int]]]
                      ) -> Tuple[pa.Table, pa.Table, SequenceDictionary]:
    """Emitted calls -> (variants, genotypes, seq_dict), shared by the
    device and oracle paths: identical call sets in produce identical
    tables (and so identical VCF bytes) out.

    Diploid biallelic rows: GT=0/1 emits a ref and an alt haplotype row,
    GT=1/1 two alt rows — the row shape io/vcf.py's reader produces, so
    ``write_vcf`` round-trips the calls.

    Site-reference consensus: each sample's count tensor infers its own
    reference hypothesis (plurality base), so two samples overlapping
    one site can disagree on REF — which a VCF line cannot represent
    (one REF per site, and convert_genotypes rejects inconsistent
    ``referenceAllele`` groups).  The site's reference is settled by
    the heaviest total claimed depth per candidate (ties to the lower
    base code) and calls contradicting it are dropped — a pure function
    of the call set, so the device pass and the scalar oracle stay
    byte-identical by construction (docs/CALL.md §limitations)."""
    calls = sorted(calls, key=lambda cl: (cl["refname"], cl["pos"],
                                          cl["sample"]))
    by_site: Dict[Tuple[str, int], List[dict]] = {}
    for cl in calls:
        by_site.setdefault((cl["refname"], cl["pos"]), []).append(cl)
    kept = []
    for site in sorted(by_site):
        cls = by_site[site]
        weight: Dict[int, int] = {}
        for cl in cls:
            rc = cl["fields"]["ref_code"]
            weight[rc] = weight.get(rc, 0) + cl["fields"]["depth"]
        site_ref = min(weight, key=lambda rc: (-weight[rc], rc))
        kept += [cl for cl in cls
                 if cl["fields"]["ref_code"] == site_ref]
    calls = kept
    g_rows = []
    for cl in calls:
        f = cl["fields"]
        ref_base = S.BASES[f["ref_code"]]
        alt_base = S.BASES[f["alt_code"]]
        pair = (ref_base, alt_base) if f["gt"] == 1 else \
            (alt_base, alt_base)
        pl_str = f"{f['pl_ref']},{f['pl_het']},{f['pl_alt']}"
        for hap, allele in enumerate(pair):
            g_rows.append({
                "referenceId": cl["refid"],
                "referenceName": cl["refname"],
                "position": cl["pos"], "sampleId": cl["sample"],
                "ploidy": 2, "haplotypeNumber": hap,
                "allele": allele, "isReference": allele == ref_base,
                "referenceAllele": ref_base,
                "alleleVariantType": "SNP",
                "genotypeQuality": f["gq"], "depth": f["depth"],
                "phredLikelihoods": pl_str,
                "rmsBaseQuality": f["qual_avg"],
                "rmsMapQuality": f["mapq_avg"],
                "readsMappedForwardStrand": f["fwd"],
                "isPhased": False,
            })
    genotypes = _rows_to_table(g_rows, S.GENOTYPE_SCHEMA)
    variants = convert_genotypes(genotypes)
    seq_dict = SequenceDictionary(
        SequenceRecord(rid, name, length or 0)
        for rid, (name, length) in sorted(contigs.items()))
    return variants, genotypes, seq_dict


def vcf_text(variants: pa.Table, genotypes: pa.Table,
             seq_dict: SequenceDictionary) -> str:
    """The VCF byte stream as a string — what the identity comparison
    (and the .vcf.gz/.bcf encoders) consume."""
    buf = _io.StringIO()
    write_vcf(variants, genotypes, buf, seq_dict)
    return buf.getvalue()
