"""The scalar calling oracle: one read at a time, in plain Python.

Independent re-derivation of the batched path's integers — a per-read
CIGAR walk mirroring ``ops.pileup.pileup_walk``'s emission semantics and
``parallel.pileup.pileup_count_kernel``'s channel arithmetic, followed
by the SAME scalar genotyper (:func:`..call.genotyper.genotype_site`)
and the SAME table builder.  The device pass must reproduce this
byte-for-byte (tests/test_call.py); where the kernel has a sharp edge
the oracle mirrors the edge rather than idealizing it:

* base codes >= 4 (N and the IUPAC ambiguity codes) count ``N_OTHER``;
  a byte outside the alphabet packs to -1 and the device scatter wraps
  a -1 channel index to the LAST channel (MAPQ_SUM) — mirrored here;
* qual bytes decode as int8(byte - 33), clamped at 0 (pad/underflow);
* CIGAR ops past the packer's ``MAX_CIGAR_OPS`` budget raise in packing,
  so the oracle never sees them; a read whose CIGAR consumes more read
  bases than its sequence holds is rejected by both paths (the shared
  :func:`admit_read` rule), which keeps identity invariant to the
  executor's chunking and length buckets.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import pyarrow as pa

from .. import schema as S
from ..parallel.pileup import (CH_CLIP, CH_COVERAGE, CH_DEL, CH_INS,
                               CH_MAPQ, CH_OTHER, CH_QUAL, CH_REVERSE,
                               N_CHANNELS)
from ..packing import MAX_CIGAR_OPS
from .genotyper import (build_call_tables, genotype_site, should_emit,
                        vcf_text)

DEFAULT_SAMPLE = "sample"

_READ_CONSUMING = {S.CIGAR_M, S.CIGAR_I, S.CIGAR_S, S.CIGAR_EQ,
                   S.CIGAR_X}
_MATCHISH = {S.CIGAR_M, S.CIGAR_EQ, S.CIGAR_X}


def parse_cigar(cigar: Optional[str]) -> List[Tuple[int, int]]:
    """CIGAR text -> [(op_code, length)]; None/'*' -> [] (contributes
    nothing, the no-cigar rule)."""
    if not cigar or cigar == "*":
        return []
    out, num = [], 0
    for ch in cigar:
        if ch.isdigit():
            num = num * 10 + ord(ch) - 48
        else:
            out.append((S.CIGAR_CODE[ch], num))
            num = 0
    return out


def admit_read(flags: Optional[int], refid: Optional[int],
               start: Optional[int], ops: List[Tuple[int, int]],
               seq_len: int) -> bool:
    """The shared admission rule: mapped, placed on a real contig, and
    the CIGAR's read-base consumption fits the sequence (otherwise the
    kernel's length-bucket cap would make output depend on chunking)."""
    if flags is None:
        flags = 0
    if (int(flags) & S.FLAG_UNMAPPED) or refid is None or refid < 0 \
            or start is None or start < 0:
        return False
    if len(ops) > MAX_CIGAR_OPS:
        return False
    consumed = sum(ln for op, ln in ops if op in _READ_CONSUMING)
    return consumed <= seq_len


def _qual_at(qual: str, i: int) -> int:
    """int8(byte-33) clamped at 0 — the packed decode, scalar."""
    if i >= len(qual):
        return 0
    v = ord(qual[i]) - 33
    v = ((v + 128) % 256) - 128          # int8 wrap, as the decode LUT
    return max(v, 0)


def _base_channel(ch: str) -> int:
    code = S.BASE_CODE.get(ch, S.BASE_PAD)
    if 0 <= code < 4:
        return code
    if code >= 4:
        return CH_OTHER
    # out-of-alphabet byte: the device scatter wraps channel -1 to the
    # last channel — mirror the wrap, don't idealize it
    return N_CHANNELS - 1


def count_read(counts: Dict[int, List[int]], *, start: int, seq: str,
               qual: str, mapq: Optional[int], flags: int,
               ops: List[Tuple[int, int]]) -> None:
    """Walk one admitted read into a position->channel-counts dict."""
    mq = max(mapq if mapq is not None else -1, 0)
    rev = (flags & S.FLAG_REVERSE) != 0
    ref_pos, off = start, 0

    def at(pos: int) -> List[int]:
        row = counts.get(pos)
        if row is None:
            row = counts[pos] = [0] * N_CHANNELS
        return row

    for op, ln in ops:
        if op in _MATCHISH:
            for k in range(ln):
                row = at(ref_pos + k)
                row[_base_channel(seq[off])] += 1
                row[CH_COVERAGE] += 1
                row[CH_QUAL] += _qual_at(qual, off)
                row[CH_MAPQ] += mq
                if rev:
                    row[CH_REVERSE] += 1
                off += 1
            ref_pos += ln
        elif op == S.CIGAR_I:
            at(ref_pos)[CH_INS] += ln
            off += ln
        elif op == S.CIGAR_S:
            at(ref_pos)[CH_CLIP] += ln
            off += ln
        elif op == S.CIGAR_D:
            for k in range(ln):
                at(ref_pos + k)[CH_DEL] += 1
            ref_pos += ln
        elif op == S.CIGAR_N:
            ref_pos += ln
        # H / P: consume nothing, emit nothing


def oracle_counts(table: pa.Table, *, default_sample: str = DEFAULT_SAMPLE
                  ) -> Tuple[Dict[Tuple[str, int], Dict[int, List[int]]],
                             Dict[int, Tuple[str, Optional[int]]]]:
    """Reads table -> ({(sample, refid): {pos: [12 channel counts]}},
    {refid: (name, length)})."""
    counts: Dict[Tuple[str, int], Dict[int, List[int]]] = {}
    contigs: Dict[int, Tuple[str, Optional[int]]] = {}
    names = set(table.column_names)

    def col(name):
        if name in names:
            return table.column(name).to_pylist()
        return [None] * table.num_rows

    flags_c, refid_c, start_c = col("flags"), col("referenceId"), \
        col("start")
    seq_c, qual_c, cigar_c = col("sequence"), col("qual"), col("cigar")
    mapq_c, sample_c = col("mapq"), col("recordGroupSample")
    refname_c, reflen_c = col("referenceName"), col("referenceLength")

    for i in range(table.num_rows):
        seq = seq_c[i] or ""
        ops = parse_cigar(cigar_c[i])
        if not admit_read(flags_c[i], refid_c[i], start_c[i], ops,
                          len(seq)):
            continue
        refid = int(refid_c[i])
        if refid not in contigs:
            contigs[refid] = (refname_c[i] or str(refid),
                              reflen_c[i])
        sample = sample_c[i] or default_sample
        key = (sample, refid)
        count_read(counts.setdefault(key, {}), start=int(start_c[i]),
                   seq=seq, qual=qual_c[i] or "", mapq=mapq_c[i],
                   flags=int(flags_c[i] or 0), ops=ops)
    return counts, contigs


def oracle_call(table: pa.Table, *, min_depth: int, min_alt: int,
                default_sample: str = DEFAULT_SAMPLE):
    """The full scalar path: counts -> genotypes -> tables.

    Returns (variants, genotypes, seq_dict, calls); ``vcf_text`` of the
    tables is the byte stream the device pass must reproduce."""
    counts, contigs = oracle_counts(table, default_sample=default_sample)
    calls = []
    for (sample, refid), by_pos in counts.items():
        refname = contigs[refid][0]
        for pos, row in by_pos.items():
            fields = genotype_site(row)
            if should_emit(fields, min_depth, min_alt):
                calls.append(dict(refid=refid, refname=refname,
                                  pos=pos, sample=sample,
                                  fields=fields))
    variants, genotypes, seq_dict = build_call_tables(calls, contigs)
    return variants, genotypes, seq_dict, calls


def oracle_vcf_text(table: pa.Table, *, min_depth: int, min_alt: int,
                    default_sample: str = DEFAULT_SAMPLE) -> str:
    variants, genotypes, seq_dict, _ = oracle_call(
        table, min_depth=min_depth, min_alt=min_alt,
        default_sample=default_sample)
    return vcf_text(variants, genotypes, seq_dict)
