"""The streamed variant-calling pass: reads -> stripes -> counts -> VCF.

Dataflow (docs/CALL.md):

1. reads stream in bounded chunks (io/stream.py) under the
   shape-bucketed executor (``begin_pass("call")`` — ladder rungs,
   prefetchable feed, retry/degrade ladder on every dispatch);
2. each chunk packs once (``pack_reads``), its planes ship to the
   device once, and ``route_reads_to_stripes`` assigns reads
   (boundary-duplicated) to genome stripes; one
   ``pileup_count_kernel`` dispatch per (stripe, sample) counts the
   chunk's evidence into a [span, 12] int32 tensor — only the cheap
   validity mask differs between dispatches, so the compiled shape set
   is the chunk ladder x the length buckets;
3. count tensors accumulate on host in int64 — an exact monoid, so
   chunk order, chunking, sharding and co-tenant packing cannot change
   the totals;
4. after the stream drains, the merged tensor of every (sample, refid,
   stripe) genotypes in one ``genotype_fields_kernel`` dispatch
   (integer math, docs/CALL.md §oracle contract) and emitted calls
   serialize through ``io.vcf.write_vcf``.

The ``ragged`` layout reuses the padded kernel over one fixed-capacity
buffer (rows live below the prefix bound, ``note_ragged`` accounting)
instead of per-chunk ladder rungs — same counts, fewer compiled row
shapes.  ``paged`` is not applicable: the page pool is the u32
wire-plane's residency scheme and the call pass ships multi-plane
batches.
"""

from __future__ import annotations

import hashlib
import math
from typing import Dict, List, Optional, Tuple

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

from .. import obs
from .. import schema as S
from ..io.stream import open_read_stream
from ..io.vcf import write_vcf
from ..packing import MAX_CIGAR_OPS, len_bucket, pack_reads
from ..parallel.mesh import make_mesh
from ..parallel.pileup import (CH_COVERAGE, N_CHANNELS,
                               pileup_count_kernel,
                               route_reads_to_stripes)
from .genotyper import (build_call_tables, calls_from_fields,
                        genotype_fields_kernel, vcf_text)
from .oracle import DEFAULT_SAMPLE, oracle_vcf_text
from .plan import resolve_call_knobs

#: columns the pass streams — the packing planes plus contig identity
CALL_COLUMNS = ("referenceName", "referenceId", "start", "mapq",
                "sequence", "qual", "cigar", "flags",
                "recordGroupSample", "referenceLength")

_CONSUMES_READ = np.array(S.CIGAR_CONSUMES_READ, np.int64)
_CONSUMES_REF = np.array(S.CIGAR_CONSUMES_REF, np.int64)

#: est. host bytes per read row shipped per chunk (bases+quals at ~150bp
#: plus the scalar planes) — the executor's prefetch-depth sizing hint
_BYTES_PER_ROW = 384.0


def _drop_overbudget_cigars(tbl: pa.Table) -> pa.Table:
    """Drop reads whose CIGAR has more ops than the packer's slot budget
    (pack_cigars raises past MAX_CIGAR_OPS); the oracle's admit_read
    rejects the same rows, so both paths see the same read set."""
    cig = pc.fill_null(tbl.column("cigar"), "")
    # op count == non-digit char count (CIGAR text is digit runs, each
    # closed by one op letter)
    n_ops = pc.subtract(
        pc.binary_length(cig),
        pc.binary_length(pc.replace_substring_regex(
            cig, r"[^0-9]", "")))
    keep = pc.less_equal(n_ops, MAX_CIGAR_OPS)
    if pc.all(keep).as_py() is not False:
        return tbl
    return tbl.filter(keep)


class _ChunkCounter:
    """Per-run state of the counting stage: host int64 accumulators per
    (sample, refid, stripe), contig identities, interned sample names."""

    def __init__(self, pex, span: int,
                 default_sample: str = DEFAULT_SAMPLE):
        self.pex = pex
        self.span = int(span)
        self.default_sample = default_sample
        self.accum: Dict[Tuple[str, int, int], np.ndarray] = {}
        self.contigs: Dict[int, Tuple[str, Optional[int]]] = {}
        self.reads = 0
        self.admitted = 0
        self.chunks = 0

    def count_chunk(self, tbl: pa.Table) -> None:
        import jax

        self.reads += tbl.num_rows
        self.chunks += 1
        tbl = _drop_overbudget_cigars(tbl)
        n = tbl.num_rows
        if n == 0:
            return
        lens = pc.fill_null(pc.binary_length(tbl.column("sequence")), 0)
        max_len = max(int(pc.max(lens).as_py() or 0), 1)
        len_b = len_bucket(max_len)
        pex = self.pex
        if pex.layout == "ragged":
            # fixed-capacity buffer: ONE compiled row count for the
            # whole run, rows live below the prefix bound
            n_pad = max(pex.chunk_rows, n)
            pex.note_ragged(n, n_pad)
        else:
            n_pad = pex.pad_rows(n, len_b, max_len=max_len)
        batch = pack_reads(tbl, bucket_len=len_b, pad_rows_to=n_pad)

        flags = batch.flags.astype(np.int64)
        consumed_read = (_CONSUMES_READ[batch.cigar_ops]
                         * batch.cigar_lens).sum(axis=1)
        ok = (batch.valid
              & ((flags & S.FLAG_UNMAPPED) == 0)
              & (batch.refid >= 0) & (batch.start >= 0)
              & (consumed_read <= batch.read_len))
        self.admitted += int(ok.sum())
        if not ok.any():
            return
        ref_span = (_CONSUMES_REF[batch.cigar_ops]
                    * batch.cigar_lens).sum(axis=1)
        # +1: trailing soft-clip/insert events pin AT start+ref_span, so
        # the routed span must include that position's stripe
        ref_end = batch.start.astype(np.int64) + ref_span + 1

        sample_col = tbl.column("recordGroupSample").to_pylist()
        sample_of_row = np.full(n_pad, "", dtype=object)
        sample_of_row[:n] = [sm or self.default_sample
                             for sm in sample_col]
        name_col = ref_len_col = None

        planes_np = (batch.bases, batch.quals, batch.start, batch.flags,
                     batch.mapq, batch.cigar_ops, batch.cigar_lens)
        nbytes = sum(int(p.nbytes) for p in planes_np)
        dev = pex.dispatch_put(
            "planes", lambda attempt: jax.device_put(planes_np),
            nbytes=nbytes)
        (d_bases, d_quals, d_start, d_flags, d_mapq, d_ops,
         d_lens) = dev

        span = self.span
        for rid in np.unique(batch.refid[ok]):
            rid = int(rid)
            rows_r = ok & (batch.refid == rid)
            if rid not in self.contigs:
                if name_col is None:
                    name_col = tbl.column("referenceName").to_pylist()
                    ref_len_col = tbl.column(
                        "referenceLength").to_pylist()
                first = int(np.flatnonzero(rows_r)[0])
                self.contigs[rid] = (name_col[first] or str(rid),
                                     ref_len_col[first])
            k_lo = int(batch.start[rows_r].min()) // span
            k_hi = int(ref_end[rows_r].max() - 1) // span
            stripe_starts = (np.arange(k_lo, k_hi + 1)
                             * span).astype(np.int64)
            gather, stripe_of = route_reads_to_stripes(
                batch.refid, batch.start, ref_end, rows_r, rows_r,
                stripe_starts, span)
            for j in np.unique(stripe_of):
                rows_j = gather[stripe_of == j]
                samp_j = sample_of_row[rows_j]
                for sample in np.unique(samp_j):
                    sel = rows_j[samp_j == sample]
                    vmask = np.zeros(n_pad, bool)
                    vmask[sel] = True
                    bin_start = np.int32(stripe_starts[j])

                    def run(attempt, vm=vmask, bs=bin_start):
                        return np.asarray(pileup_count_kernel(
                            d_bases, d_quals, d_start, d_flags, d_mapq,
                            vm, d_ops, d_lens, bs,
                            bin_span=span, max_len=len_b))

                    def cpu(exc, vm=vmask, bs=bin_start):
                        with jax.default_device(jax.devices("cpu")[0]):
                            return np.asarray(pileup_count_kernel(
                                batch.bases, batch.quals, batch.start,
                                batch.flags, batch.mapq, vm,
                                batch.cigar_ops, batch.cigar_lens, bs,
                                bin_span=span, max_len=len_b))

                    counts = pex.dispatch("pileup", run, fallback=cpu)
                    key = (str(sample), rid, k_lo + int(j))
                    acc = self.accum.get(key)
                    if acc is None:
                        self.accum[key] = counts.astype(np.int64)
                    else:
                        acc += counts


def streaming_call(path: str, out_path: Optional[str] = None, *,
                   chunk_rows: int = 1 << 18, io_procs: int = 1,
                   stripe_span: Optional[int] = None,
                   min_depth: Optional[int] = None,
                   min_alt: Optional[int] = None,
                   executor_opts: Optional[dict] = None,
                   validate: bool = False,
                   default_sample: str = DEFAULT_SAMPLE) -> dict:
    """Chunked, executor-driven variant calling over any reads input.

    Returns a result doc with the call counts, the VCF's sha256 (the
    serve identity handle), and — under ``validate`` — the scalar-oracle
    verdict plus the rods-plane coverage summary.  ``out_path`` (when
    given) receives the VCF via the durable tmp+rename writer.
    """
    import jax  # noqa: F401  (device runtime; imported before dispatches)

    from ..parallel.executor import StreamExecutor
    from ..platform import is_tpu_backend

    plan = resolve_call_knobs(stripe_span, min_depth, min_alt)
    span, mdep, malt = (plan["stripe_span"], plan["min_depth"],
                        plan["min_alt"])

    mesh = make_mesh()
    on_tpu = is_tpu_backend()
    ex = StreamExecutor(mesh, chunk_rows, on_tpu=on_tpu,
                        **(executor_opts or {}))
    pex = ex.begin_pass("call", bytes_per_row=_BYTES_PER_ROW,
                        ragged_capable=True, paged_capable=False,
                        sync_every=1)
    counter = _ChunkCounter(pex, span, default_sample)
    with obs.ioledger.pass_scope("call"):
        stream = open_read_stream(path, columns=list(CALL_COLUMNS),
                                  chunk_rows=pex.chunk_rows,
                                  io_procs=io_procs)
        for tbl in stream:
            counter.count_chunk(tbl)

    # genotype stage: one dispatch per merged (sample, refid, stripe)
    # tensor — post-monoid, so solo/fleet/packed runs genotype the same
    # integers
    calls: List[dict] = []
    samples = set()
    for key in sorted(counter.accum):
        sample, rid, k = key
        samples.add(sample)
        counts32 = counter.accum[key].astype(np.int32)
        out = pex.dispatch(
            "genotype",
            lambda attempt, c=counts32: np.asarray(
                genotype_fields_kernel(c)))
        stripe_calls = calls_from_fields(
            out, refid=rid, refname=counter.contigs[rid][0],
            stripe_start=k * span, sample=sample,
            min_depth=mdep, min_alt=malt)
        calls += stripe_calls
        obs.emit("call_stripe", refid=int(rid),
                 stripe_start=int(k * span), span=int(span),
                 sample=str(sample),
                 covered=int((counts32[:, CH_COVERAGE] > 0).sum()),
                 called=len(stripe_calls))
    ex.finish()

    variants, genotypes, seq_dict = build_call_tables(
        calls, counter.contigs)
    text = vcf_text(variants, genotypes, seq_dict)
    sha = hashlib.sha256(text.encode()).hexdigest()

    identical = None
    rod_cov = None
    if validate:
        # the validation leg: re-derive everything read-by-read in
        # Python (call/oracle.py) and summarize depth through the rods
        # plane (ops/rods.py) — RodView aggregation's production caller
        from ..ops.rods import aggregate_rods, reads_to_rods, \
            rod_coverage
        # full column set: the rods plane reads the MD tag and sample
        # metadata beyond the pass's streaming projection
        full = pa.concat_tables(list(open_read_stream(
            path, chunk_rows=chunk_rows, io_procs=io_procs)))
        identical = text == oracle_vcf_text(
            full, min_depth=mdep, min_alt=malt,
            default_sample=default_sample)
        # the rods plane packs CIGARs too — drop the over-budget rows
        # it cannot represent, as the counting path did
        rods = aggregate_rods(reads_to_rods(
            _drop_overbudget_cigars(full)))
        cov = rod_coverage(rods)
        rod_cov = None if math.isnan(cov) else round(float(cov), 6)

    if out_path:
        write_vcf(variants, genotypes, out_path, seq_dict)
    obs.emit("call_emit", path=out_path, reads=counter.reads,
             admitted=counter.admitted, stripes=len(counter.accum),
             calls=len(calls), variants=variants.num_rows,
             genotypes=genotypes.num_rows, samples=len(samples),
             vcf_sha256=sha, identical=identical, rod_coverage=rod_cov)
    return dict(reads=counter.reads, admitted=counter.admitted,
                stripes=len(counter.accum), calls=len(calls),
                variants=variants.num_rows,
                genotypes=genotypes.num_rows, samples=len(samples),
                vcf=out_path, vcf_sha256=sha, identical=identical,
                rod_coverage=rod_cov)
