"""The call plan: pure, replayable knob resolution (decide_plan convention).

``decide_call_plan`` is the one place the calling pass's genome/genotype
knobs are decided: stripe span (the genome-bin width each device counts),
and the emission thresholds (min depth, min alt evidence).  PURE — the
returned plan is a deterministic function of the keyword inputs, which
the ``call_plan_selected`` event records in full (``inputs`` +
``input_digest``), so a recorded sidecar can be replayed offline and the
decision re-derived bit-for-bit (tools/check_executor.py).  Precedence
is the executor's: explicit flags > environment > defaults.
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Optional

#: default genome-stripe width: one [span, 12] int32 count tensor is
#: ~1.5 MiB at 2^15 — small enough to keep many stripes resident, large
#: enough that boundary-read duplication stays <1% at read length ~150
DEFAULT_STRIPE_SPAN = 1 << 15
#: emission floors: a biallelic SNP call needs this much total coverage
#: and this many alt-supporting bases (mpileup-style evidence floor)
DEFAULT_MIN_DEPTH = 2
DEFAULT_MIN_ALT = 2
#: stripes narrower than this make the boundary-duplication tax dominate
MIN_STRIPE_SPAN = 1 << 10

ENV_SPAN = "ADAM_TPU_CALL_SPAN"
ENV_MIN_DEPTH = "ADAM_TPU_CALL_MIN_DEPTH"
ENV_MIN_ALT = "ADAM_TPU_CALL_MIN_ALT"


def _env_int(name: str) -> Optional[int]:
    v = os.environ.get(name)
    if v is None or v == "":
        return None
    try:
        return int(v)
    except ValueError:
        raise ValueError(f"{name} must be an integer, got {v!r}")


def resolve_call_knobs(stripe_span: Optional[int] = None,
                       min_depth: Optional[int] = None,
                       min_alt: Optional[int] = None) -> dict:
    """Read the env half of the precedence ladder, hand decide_call_plan
    its full keyword set (the only impure step, kept outside the decider
    so the decision itself replays offline) and emit the decision's
    ``call_plan_selected`` record — inputs + digest ride the event so
    tools/check_executor.py can re-derive the plan bit-for-bit."""
    from .. import obs

    plan = decide_call_plan(
        stripe_span=stripe_span, min_depth=min_depth, min_alt=min_alt,
        env_stripe_span=_env_int(ENV_SPAN),
        env_min_depth=_env_int(ENV_MIN_DEPTH),
        env_min_alt=_env_int(ENV_MIN_ALT))
    obs.emit("call_plan_selected", stripe_span=plan["stripe_span"],
             min_depth=plan["min_depth"], min_alt=plan["min_alt"],
             reason=plan["reason"], inputs=plan["inputs"],
             input_digest=plan["input_digest"])
    return plan


def decide_call_plan(*, stripe_span: Optional[int] = None,
                     min_depth: Optional[int] = None,
                     min_alt: Optional[int] = None,
                     env_stripe_span: Optional[int] = None,
                     env_min_depth: Optional[int] = None,
                     env_min_alt: Optional[int] = None) -> dict:
    """The calling pass's frozen knob plan.

    PURE — explicit flags outrank the (pre-read) environment values,
    which outrank the defaults; out-of-range spans clamp with a recorded
    reason rather than erroring, so a serve job with a bad span knob
    degrades instead of failing admission-validated work.
    """
    inputs = dict(
        stripe_span=None if stripe_span is None else int(stripe_span),
        min_depth=None if min_depth is None else int(min_depth),
        min_alt=None if min_alt is None else int(min_alt),
        env_stripe_span=None if env_stripe_span is None
        else int(env_stripe_span),
        env_min_depth=None if env_min_depth is None else int(env_min_depth),
        env_min_alt=None if env_min_alt is None else int(env_min_alt))
    reasons = []

    def pick(flag, env, default, label):
        if flag is not None:
            reasons.append(f"{label}-flag")
            return flag
        if env is not None:
            reasons.append(f"{label}-env")
            return env
        return default

    span = pick(inputs["stripe_span"], inputs["env_stripe_span"],
                DEFAULT_STRIPE_SPAN, "span")
    if span < MIN_STRIPE_SPAN:
        reasons.append(f"span-clamped:{MIN_STRIPE_SPAN}")
        span = MIN_STRIPE_SPAN
    depth = max(pick(inputs["min_depth"], inputs["env_min_depth"],
                     DEFAULT_MIN_DEPTH, "depth"), 1)
    alt = max(pick(inputs["min_alt"], inputs["env_min_alt"],
                   DEFAULT_MIN_ALT, "alt"), 1)
    digest = hashlib.sha256(
        json.dumps(inputs, sort_keys=True).encode()).hexdigest()[:16]
    return dict(stripe_span=int(span), min_depth=int(depth),
                min_alt=int(alt),
                reason=";".join(reasons) or "default",
                inputs=inputs, input_digest=digest)
