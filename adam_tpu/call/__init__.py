"""Variant calling plane: batched pileup -> genotype -> VCF (ISSUE 17).

The reference's back half — pileup aggregation
(PileupAggregator.scala) and genotype/variant computation
(GenotypesToVariantsConverter.scala) — as a fourth served workload.
The streamed pass (``streaming_call``) drives position-binned pileup
counting through the shape-bucketed executor, genotypes the merged
count tensors with an integer device kernel, and emits VCF through
``io.vcf.write_vcf``; a pure scalar oracle (``oracle_call``) replays
the same integers read-by-read in Python and the two VCF byte streams
must be identical (tests/test_call.py, docs/CALL.md).
"""

from .plan import decide_call_plan, resolve_call_knobs  # noqa: F401
from .pipeline import streaming_call  # noqa: F401
from .oracle import oracle_call, oracle_counts  # noqa: F401
from .genotyper import (genotype_fields_kernel, genotype_site,  # noqa: F401
                        build_call_tables, vcf_text)
