"""Ragged read records -> fixed-shape device tensors.

This is the TPU substrate replacing the reference's per-record JVM objects:
instead of an ``RDD[ADAMRecord]`` we carry a structure-of-arrays
:class:`ReadBatch` — padded int8/int32 tensors in HBM — and every kernel
(flagstat, markdup scoring, BQSR, pileup, realignment sweep) is a batched
tensor op over it.  Columnar projection (the reference's Parquet trick,
cli/FlagStat.scala:50-57) becomes "only pack the columns you need".

Packing policy (SURVEY.md §7 hard part (a)): bases/quals pad to a length
bucket (reads are ~100-150 bp; the bucket is rounded up to a multiple of 128
so rows map cleanly onto TPU lanes), batch row-count pads to a multiple of
``pad_rows_to`` so the batch splits evenly across a device mesh.  Padded rows
have ``valid == False`` and are ignored by every kernel.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, fields as dc_fields
from typing import Optional

import numpy as np
import pyarrow as pa

try:  # keep importable without jax for host-only tooling
    import jax
    _HAVE_JAX = True
except Exception:  # pragma: no cover
    _HAVE_JAX = False

from . import schema as S

_BASE_LUT = np.full(256, S.BASE_PAD, np.int8)
for _ch, _code in S.BASE_CODE.items():
    _BASE_LUT[ord(_ch)] = _code

#: byte-value minus offset as one int8 gather (qual decode); bytes under
#: the offset only occur in masked-out padding and may wrap freely
_OFFSET_LUTS = {33: (np.arange(256, dtype=np.int16) - 33).astype(np.int8)}

_CIGAR_LUT = np.full(256, -1, np.int8)
for _ch, _code in S.CIGAR_CODE.items():
    _CIGAR_LUT[ord(_ch)] = _code

QUAL_PAD = -1
MAX_CIGAR_OPS = 16  # default op-slot budget per read


@dataclass
class ReadBatch:
    """Fixed-shape columnar batch of reads (device pytree).

    Scalar-per-read columns are always present; base-level and cigar-level
    columns are optional (None when not packed).  ``row_index`` maps each row
    back to its source row in the originating Arrow table so host-side string
    fields (readName, cigar/MD rewrites) can be joined back after device
    compute.
    """
    flags: np.ndarray          # int32 [N] SAM flag word
    refid: np.ndarray          # int32 [N], -1 = null/unmapped
    start: np.ndarray          # int32 [N], -1 = null (0-based)
    mapq: np.ndarray           # int32 [N], -1 = null
    mate_refid: np.ndarray     # int32 [N], -1 = null
    mate_start: np.ndarray     # int32 [N], -1 = null
    read_group: np.ndarray     # int32 [N], -1 = null (dense record-group index)
    valid: np.ndarray          # bool  [N]
    row_index: np.ndarray      # int32 [N], -1 for padding rows
    read_len: Optional[np.ndarray] = None    # int32 [N]
    bases: Optional[np.ndarray] = None       # int8 [N, L] codes, -1 pad
    quals: Optional[np.ndarray] = None       # int8 [N, L] phred, -1 pad
    cigar_ops: Optional[np.ndarray] = None   # int8 [N, C], -1 pad
    cigar_lens: Optional[np.ndarray] = None  # int32 [N, C], 0 pad
    n_cigar: Optional[np.ndarray] = None     # int32 [N]

    @property
    def n_reads(self) -> int:
        return int(self.flags.shape[0])

    @property
    def max_len(self) -> int:
        return 0 if self.bases is None else int(self.bases.shape[1])

    def device_put(self, sharding=None) -> "ReadBatch":
        kw = {}
        for f in dc_fields(self):
            v = getattr(self, f.name)
            kw[f.name] = None if v is None else jax.device_put(v, sharding)
        return ReadBatch(**kw)

    def row_slice(self, s: int, e: int) -> "ReadBatch":
        """Row-slice every populated column (zero-copy views)."""
        kw = {}
        for f in dc_fields(self):
            v = getattr(self, f.name)
            kw[f.name] = None if v is None else v[s:e]
        return ReadBatch(**kw)


if _HAVE_JAX:
    jax.tree_util.register_pytree_node(
        ReadBatch,
        lambda rb: (tuple(getattr(rb, f.name) for f in dc_fields(rb)), None),
        lambda _, children: ReadBatch(*children),
    )


def _round_up(x: int, mult: int) -> int:
    return ((x + mult - 1) // mult) * mult if mult > 1 else x


# ---------------------------------------------------------------------------
# canonical shape buckets (the executor's ladder — parallel/executor.py)
# ---------------------------------------------------------------------------

#: default geometric ratio between consecutive row-bucket rungs; the
#: executor's autotuner densifies to sqrt(2) when observed pad waste
#: exceeds its target (docs/EXECUTOR.md)
LADDER_BASE_DEFAULT = 2.0


@functools.lru_cache(maxsize=512)
def row_bucket_ladder(cap_rows: int, mult: int = 1,
                      base: float = LADDER_BASE_DEFAULT) -> tuple:
    """Geometric ladder of canonical row buckets: ``mult``-multiples from
    ``mult`` up to the ``mult``-rounded ``cap_rows`` (always the top rung).

    Every streamed chunk pads its row count to a rung, so a whole pass —
    and, because the ladder is shared, a whole multi-pass run — compiles
    each kernel against at most ``len(ladder)`` row shapes.  Previously
    each pass re-derived power-of-two buckets independently and a skewed
    tail chunk could mint a fresh shape (= a fresh XLA compile) mid-run.

    Memoized per (cap, mult, base): the fused transform's per-chunk plan
    consumers and the realign batcher re-derive ladders in hot loops, and
    a dense (sqrt-2) ladder over a multi-million-row cap is hundreds of
    Python loop iterations per call.  The ladder is a pure function of
    its arguments and the returned tuple is immutable, so caching cannot
    change a single rung — pinned by tests/test_ragged.py alongside the
    zero-recompile rerun property.
    """
    if base <= 1.0:
        raise ValueError(f"ladder base must exceed 1.0, got {base}")
    mult = max(int(mult), 1)
    cap = max(_round_up(int(cap_rows), mult), mult)
    rungs = []
    r = mult
    while r < cap:
        rungs.append(r)
        r = _round_up(max(int(r * base + 0.5), r + 1), mult)
    rungs.append(cap)
    return tuple(rungs)


def pad_rows_for(rows: int, ladder) -> int:
    """Smallest ladder rung holding ``rows`` (top rung for anything
    larger — streams bound chunk rows by the cap the ladder was built
    for, so overflow indicates a caller bug and the top rung keeps the
    shape canonical rather than minting a new one)."""
    for r in ladder:
        if rows <= r:
            return r
    return ladder[-1]


@functools.lru_cache(maxsize=8192)
def shape_rung(n: int, mult: int, base: float = LADDER_BASE_DEFAULT) -> int:
    """Smallest canonical rung (the :func:`row_bucket_ladder` recurrence
    from ``mult``) that holds ``n`` — the unbounded form of
    :func:`pad_rows_for` for group-shaped work whose cap is data-dependent.

    The realignment sweep pads its (R, L, CL) job geometry with this
    (realign/realigner.py, scheduled by parallel/realign_exec.py): the
    rungs follow ``row_bucket_ladder``'s growth recurrence exactly (a
    ladder's non-top rungs are this sequence; its TOP rung is the
    mult-rounded cap, which only coincides when the cap sits on the
    sequence), so sweep shapes are canonical across bins and runs —
    independent of any per-run cap — and each kernel compiles a bounded
    shape set.
    """
    if base <= 1.0:
        raise ValueError(f"ladder base must exceed 1.0, got {base}")
    mult = max(int(mult), 1)
    r = mult
    n = int(n)
    while r < n:
        r = _round_up(max(int(r * base + 0.5), r + 1), mult)
    return r


@functools.lru_cache(maxsize=1024)
def len_bucket(max_len: int, base: float = LADDER_BASE_DEFAULT) -> int:
    """Canonical length bucket: the next 128-multiple (TPU lane width),
    rounded up its own geometric ladder (128, 256, 512, ... for the
    default base) so a late chunk carrying a slightly longer read reuses
    an already-compiled [N, L] shape instead of forcing a recompile of
    every base-level kernel."""
    units = max(-(-int(max_len) // 128), 1)
    r = 1
    while r < units:
        r = max(int(r * base + 0.5), r + 1)
    return 128 * r


def _string_column_to_padded(col: pa.ChunkedArray, n_rows: int, pad_to: int,
                             lut: np.ndarray, pad_value: int,
                             offset: int = 0) -> tuple[np.ndarray, np.ndarray]:
    """Vectorized: Arrow string column -> (padded int8 [N,L], lengths int32 [N])."""
    arr = col.combine_chunks()
    if isinstance(arr, pa.ChunkedArray):  # zero-chunk edge case
        arr = pa.concat_arrays(arr.chunks) if arr.num_chunks else pa.array([], pa.string())
    # offsets/data straight from the Arrow buffers — no per-row Python
    bufs = arr.buffers()
    offsets = np.frombuffer(bufs[1], np.int32, count=len(arr) + 1, offset=arr.offset * 4)
    data = np.frombuffer(bufs[2], np.uint8) if bufs[2] is not None else np.zeros(0, np.uint8)
    lens = (offsets[1:] - offsets[:-1]).astype(np.int32)
    if arr.null_count:
        nulls = np.asarray(arr.is_null())
        lens = np.where(nulls, 0, lens)
    L = max(int(lens.max(initial=0)), 1)
    L = _round_up(L, 128) if pad_to == 0 else pad_to
    if lens.max(initial=0) > L:
        raise ValueError(f"read length {lens.max()} exceeds bucket {L}")
    out = np.full((n_rows, L), pad_value, np.int8)
    lens_full = np.zeros(n_rows, np.int32)
    lens_full[:len(arr)] = lens
    if data.size == 0:
        return out, lens_full
    lut8 = lut if offset == 0 else _OFFSET_LUTS[offset]
    # dense fast path: every row the same length Lc with contiguous
    # offsets (the fixed-read-length norm for sequencer output) — the
    # Arrow data buffer IS the [n, Lc] byte matrix, so one reshape + LUT
    # replaces the (row, pos) gather and its [n, L] index intermediate
    n_arr = len(arr)
    Lc = int(lens[0]) if n_arr else 0
    if (Lc > 0 and not arr.null_count and data.size == n_arr * Lc and
            int(offsets[0]) == 0 and int(offsets[-1]) == data.size and
            bool((lens == Lc).all())):
        out[:n_arr, :Lc] = lut8[data.reshape(n_arr, Lc)]
        return out, lens_full
    pos = np.arange(L, dtype=np.int32)[None, :]
    mask = pos < lens[:n_arr, None]
    # gather source byte for every (row, pos); one int8 LUT pass decodes
    # AND offsets, and the padded region overwrites via a single where.
    # int32 indices suffice (Arrow string offsets are int32) and halve the
    # index-matrix traffic — but the position must clamp to the row's own
    # last byte BEFORE the add: offset + raw pos could pass 2^31 on a
    # near-2GB chunk and wrap negative.
    pos_in_row = np.minimum(pos, np.maximum(lens[:n_arr, None] - 1, 0))
    src = np.minimum(offsets[:-1, None] + pos_in_row,
                     np.int32(max(data.size - 1, 0)))
    vals = data[src]
    out[:n_arr] = np.where(mask, lut8[vals], pad_value)
    return out, lens_full


def _nan_to_null(np_col: np.ndarray, null_value: int) -> np.ndarray:
    """Arrow's to_numpy renders nulls as NaN (float); coerce to a sentinel."""
    if np_col.dtype.kind == "f":
        np_col = np.where(np.isnan(np_col), null_value, np_col)
    return np_col.astype(np.int64)


def column_int64(table: pa.Table, name: str, null_value: int = -1) -> np.ndarray:
    """Integer column -> int64 numpy with nulls as ``null_value``."""
    return _nan_to_null(
        table.column(name).to_numpy(zero_copy_only=False), null_value)


def hash_strings_128(col: pa.ChunkedArray) -> tuple[np.ndarray, np.ndarray]:
    """Vectorized 128-bit hash of a string column -> (lo, hi) uint64 [N].

    The streaming pipelines bucket reads by (recordGroup, readName) across
    chunks without holding every name in memory — a 128-bit multiplicative
    hash stands in for the name (collision odds ~2^-77 at 51 M reads, far
    below sequencer error rates).  Vectorization: pad names into a byte
    matrix, view 8 bytes per lane as u64 words, Horner-reduce over the ~8
    word columns with two independent odd multipliers, then fold in the
    length (so "ab" and "ab\\0" differ).  Null names hash to a fixed
    sentinel, preserving the reference's null-name grouping.
    """
    arr = col.combine_chunks()
    if isinstance(arr, pa.ChunkedArray):  # zero-chunk edge case
        arr = pa.concat_arrays(arr.chunks) if arr.num_chunks \
            else pa.array([], pa.string())
    n = len(arr)
    if n == 0:
        return np.zeros(0, np.uint64), np.zeros(0, np.uint64)
    bufs = arr.buffers()
    offsets = np.frombuffer(bufs[1], np.int32, count=n + 1,
                            offset=arr.offset * 4)
    data = np.frombuffer(bufs[2], np.uint8) if bufs[2] is not None \
        else np.zeros(0, np.uint8)
    lens = (offsets[1:] - offsets[:-1]).astype(np.int64)
    nulls = np.asarray(arr.is_null()) if arr.null_count else None
    if nulls is not None:
        lens = np.where(nulls, 0, lens)
    W = max((int(lens.max(initial=0)) + 7) // 8, 1)
    mat = np.zeros((n, W * 8), np.uint8)
    if data.size:
        pos = np.arange(W * 8)[None, :]
        mask = pos < lens[:, None]
        src = offsets[:-1, None].astype(np.int64) + pos
        mat[mask] = data[np.where(mask, src, 0)][mask]
    words = mat.view(np.uint64).reshape(n, W)
    M1 = np.uint64(0x9E3779B97F4A7C15)   # two independent odd multipliers
    M2 = np.uint64(0xC2B2AE3D27D4EB4F)
    h1 = np.full(n, 0x8445D61A4E774912, np.uint64)
    h2 = np.full(n, 0x61C8864680B583EB, np.uint64)
    with np.errstate(over="ignore"):
        for j in range(W):
            # a round only mixes rows whose name actually reaches word j:
            # W is the CHUNK's max width, and an unconditional transform
            # would make the hash depend on the longest name sharing the
            # chunk — the same read name must hash identically in every
            # chunk layout (streaming markdup pairs mates across chunks
            # by this hash; the compare engine buckets by it per side)
            live = (np.int64(j) * 8) < lens
            w = words[:, j]
            n1 = (h1 + w) * M1
            n1 ^= n1 >> np.uint64(29)
            h1 = np.where(live, n1, h1)
            n2 = (h2 ^ w) * M2
            n2 ^= n2 >> np.uint64(31)
            h2 = np.where(live, n2, h2)
        h1 = (h1 + lens.astype(np.uint64)) * M1
        h2 = (h2 ^ lens.astype(np.uint64)) * M2
    if nulls is not None:
        h1 = np.where(nulls, np.uint64(0), h1)
        h2 = np.where(nulls, np.uint64(0), h2)
    return h1, h2


def dictionary_codes(col: pa.ChunkedArray) -> np.ndarray:
    """Dictionary-encode a string column -> dense int64 codes, null -> -1."""
    import pyarrow.compute as pc
    codes = pc.dictionary_encode(col.combine_chunks())
    return _nan_to_null(codes.indices.to_numpy(zero_copy_only=False), -1)


def _int_column(table: pa.Table, name: str, n_rows: int, null_value=-1) -> np.ndarray:
    if name not in table.column_names:  # projected-out column
        return np.full(n_rows, null_value, np.int32)
    vals = column_int64(table, name, null_value)
    if vals.size and (vals.max(initial=0) > np.iinfo(np.int32).max or
                      vals.min(initial=0) < np.iinfo(np.int32).min):
        # device columns are int32; contigs longer than 2^31 bp would need a
        # (refid, offset) split which no current genome requires
        raise OverflowError(f"column {name!r} exceeds int32 range")
    out = np.full(n_rows, null_value, np.int32)
    out[:len(vals)] = vals.astype(np.int32)
    return out


def pack_cigars(cigars, n_rows: int, max_ops: int = MAX_CIGAR_OPS):
    """CIGAR strings -> (ops int8 [N,C], lens int32 [N,C], n_ops int32 [N]).

    Replaces the samtools TextCigarCodec the reference leans on
    (rich/RichADAMRecord.scala:58-60).  An Arrow string column takes the
    flat-byte vectorized path (one pass over the offsets+data buffers, no
    per-row Python — the text codec was one of the three packing hot spots
    in the first end-to-end profile); lists fall back to the char loop.
    """
    if isinstance(cigars, (pa.ChunkedArray, pa.Array)):
        return _pack_cigars_arrow(cigars, n_rows, max_ops)
    ops = np.full((n_rows, max_ops), -1, np.int8)
    lens = np.zeros((n_rows, max_ops), np.int32)
    n_ops = np.zeros(n_rows, np.int32)
    for i, c in enumerate(cigars):
        if c is None or c == "*":
            continue
        j = 0
        num = 0
        for ch in c:
            if ch.isdigit():
                num = num * 10 + ord(ch) - 48
            else:
                if j >= max_ops:
                    raise ValueError(f"cigar {c!r} exceeds {max_ops} ops")
                ops[i, j] = S.CIGAR_CODE[ch]
                lens[i, j] = num
                num = 0
                j += 1
        n_ops[i] = j
    return ops, lens, n_ops


_POW10 = 10 ** np.arange(10, dtype=np.int64)


def _pack_cigars_arrow(col, n_rows: int, max_ops: int = MAX_CIGAR_OPS):
    """Vectorized CIGAR text parse over the Arrow buffers.

    Each op character closes a digit run: the run's value is the sum of
    digit * 10^(digits-remaining-after-it-in-run), computed with one
    cumulative-count pass — no per-row loop.
    """
    arr = col.combine_chunks() if isinstance(col, pa.ChunkedArray) else col
    n = len(arr)
    ops = np.full((n_rows, max_ops), -1, np.int8)
    lens = np.zeros((n_rows, max_ops), np.int32)
    n_ops = np.zeros(n_rows, np.int32)
    if n == 0:
        return ops, lens, n_ops
    bufs = arr.buffers()
    offsets = np.frombuffer(bufs[1], np.int32, count=n + 1,
                            offset=arr.offset * 4).astype(np.int64)
    data = np.frombuffer(bufs[2], np.uint8) if bufs[2] is not None \
        else np.zeros(0, np.uint8)
    # normalize away slicing: views outside [offsets[0], offsets[-1]) belong
    # to rows not in this array and must not be scanned
    data = data[offsets[0]:offsets[-1]]
    offsets = offsets - offsets[0]
    if data.size == 0:
        return ops, lens, n_ops
    codes = _CIGAR_LUT[data]                       # -1 for digits/junk
    is_digit = (data >= 48) & (data <= 57)
    junk = ~is_digit & (codes < 0)
    if junk.any():
        # '*' rows (no cigar) are the one legal non-token; anything else is
        # corrupt input and must fail LOUDLY like the loop path's KeyError —
        # folding a stray byte into a digit run would silently corrupt the
        # geometry feeding realignment/BQSR
        jrows = np.searchsorted(offsets[1:], np.flatnonzero(junk),
                                side="right")
        row_len = offsets[jrows + 1] - offsets[jrows]
        star = (row_len == 1) & (data[offsets[jrows]] == ord("*"))
        if not star.all():
            bad = int(jrows[~star][0])
            raise ValueError(f"unparseable cigar {arr[bad].as_py()!r}")
    op_idx = np.flatnonzero(~is_digit & (codes >= 0))
    if len(op_idx) == 0:
        return ops, lens, n_ops
    # row of each op char, and its slot within the row
    row = np.searchsorted(offsets[1:], op_idx, side="right")
    first_op_of_row = np.searchsorted(row, np.arange(n))
    slot = np.arange(len(op_idx)) - first_op_of_row[row]
    if slot.max(initial=0) >= max_ops:
        bad = row[slot >= max_ops][0]
        raise ValueError(
            f"cigar {arr[int(bad)].as_py()!r} exceeds {max_ops} ops")
    # digit-run value per op: digits between the previous op (or row
    # start) and this op.  weight = 10^(run_end - i - 1) for digit at i.
    run_start = np.maximum(
        np.concatenate([[np.int64(-1)], op_idx[:-1]]) + 1,
        offsets[row])
    run_len = op_idx - run_start
    digit_rows = np.repeat(np.arange(len(op_idx)), run_len)
    flat = np.repeat(run_start, run_len) + _ranges_within(run_len)
    weights = _POW10[np.repeat(op_idx, run_len) - flat - 1]
    values = np.zeros(len(op_idx), np.int64)
    np.add.at(values, digit_rows,
              (data[flat].astype(np.int64) - 48) * weights)
    ops[row, slot] = codes[op_idx]
    lens[row, slot] = values.astype(np.int32)
    np.maximum.at(n_ops, row, (slot + 1).astype(np.int32))
    return ops, lens, n_ops


def _ranges_within(counts: np.ndarray) -> np.ndarray:
    """[sum(counts)] 0..count_i-1 for each i, concatenated."""
    total = int(counts.sum())
    if total == 0:
        return np.zeros(0, np.int64)
    first = np.cumsum(counts) - counts
    return np.arange(total, dtype=np.int64) - np.repeat(first, counts)


def pack_reads(table: pa.Table, *, with_bases: bool = True,
               with_cigar: bool = True, bucket_len: int = 0,
               pad_rows_to: int = 1, max_cigar_ops: int = MAX_CIGAR_OPS) -> ReadBatch:
    """Pack an Arrow reads table (READ_SCHEMA) into a :class:`ReadBatch`."""
    n = table.num_rows
    n_pad = _round_up(max(n, 1), pad_rows_to)

    flags = _int_column(table, "flags", n_pad, null_value=0)
    batch = dict(
        flags=flags,
        refid=_int_column(table, "referenceId", n_pad),
        start=_int_column(table, "start", n_pad),
        mapq=_int_column(table, "mapq", n_pad),
        mate_refid=_int_column(table, "mateReferenceId", n_pad),
        mate_start=_int_column(table, "mateAlignmentStart", n_pad),
        read_group=_int_column(table, "recordGroupId", n_pad),
        valid=np.arange(n_pad) < n,
        row_index=np.where(np.arange(n_pad) < n,
                           np.arange(n_pad), -1).astype(np.int32),
    )
    if with_bases:
        bases, read_len = _string_column_to_padded(
            table.column("sequence"), n_pad, bucket_len, _BASE_LUT, S.BASE_PAD)
        quals, _ = _string_column_to_padded(
            table.column("qual"), n_pad, bases.shape[1], _BASE_LUT, QUAL_PAD,
            offset=33)
        batch.update(bases=bases, quals=quals, read_len=read_len)
    if with_cigar:
        ops, lens, n_ops = pack_cigars(
            table.column("cigar"), n_pad, max_cigar_ops)
        batch.update(cigar_ops=ops, cigar_lens=lens, n_cigar=n_ops)
    return ReadBatch(**batch)


# ---------------------------------------------------------------------------
# ragged layout: concatenated planes + row-offset prefix sums
# ---------------------------------------------------------------------------

@dataclass
class RaggedBatch:
    """Variable-length reads as CONCATENATED planes (no per-read padding).

    The padded :class:`ReadBatch` pays a "pad tax" on two axes: rows pad
    to a ladder rung and every base-level plane pads to the 128-multiple
    length bucket, so a skewed input can spend a third of device cycles
    on ``valid=False`` rows and past-length lanes.  This layout is the
    ragged-paged-attention answer (docs/ARCHITECTURE.md §6g): base/qual
    bytes of all reads concatenate into flat ``[T]`` planes and an int32
    ``row_offsets`` prefix sum (the :mod:`..io.wirespill` length-sidecar
    format, cumulated) says where each read starts — kernels walk a
    prefix-sum row index instead of masking padded lanes.

    ``row_of``/``pos_of`` materialize the prefix-sum walk (source row and
    position-in-read of every flat element) so jitted kernels need no
    host searchsorted.  The flat planes MAY carry slack past
    ``n_bases == row_offsets[-1]`` (padding ``T`` to a canonical rung
    keeps compiled shapes bounded); slack elements carry pad sentinels
    and ``row_of == 0`` and every kernel excludes them by flat index,
    never by a valid bit.

    Scalar per-read columns keep :class:`ReadBatch` semantics (rows pad
    to ``pad_rows_to`` with ``valid == False``); cigars stay the packed
    fixed-op columns — op counts are tiny and bounded, so raggedness
    buys nothing there.
    """
    flags: np.ndarray          # int32 [N]
    refid: np.ndarray          # int32 [N]
    start: np.ndarray          # int32 [N]
    mapq: np.ndarray           # int32 [N]
    mate_refid: np.ndarray     # int32 [N]
    mate_start: np.ndarray     # int32 [N]
    read_group: np.ndarray     # int32 [N]
    valid: np.ndarray          # bool  [N]
    row_index: np.ndarray      # int32 [N]
    read_len: np.ndarray       # int32 [N] true lengths (0 for pad/null)
    row_offsets: np.ndarray    # int32 [N+1] prefix sums into the planes
    bases_flat: Optional[np.ndarray] = None  # int8 [Tpad], BASE_PAD slack
    quals_flat: Optional[np.ndarray] = None  # int8 [Tpad], QUAL_PAD slack
    row_of: Optional[np.ndarray] = None      # int32 [Tpad], 0 in slack
    pos_of: Optional[np.ndarray] = None      # int32 [Tpad], 0 in slack
    cigar_ops: Optional[np.ndarray] = None   # int8 [N, C]
    cigar_lens: Optional[np.ndarray] = None  # int32 [N, C]
    n_cigar: Optional[np.ndarray] = None     # int32 [N]

    @property
    def n_reads(self) -> int:
        return int(self.flags.shape[0])

    @property
    def n_bases(self) -> int:
        """True flat-plane length (elements past it are slack)."""
        return int(self.row_offsets[-1])

    def device_put(self, sharding=None) -> "RaggedBatch":
        kw = {}
        for f in dc_fields(self):
            v = getattr(self, f.name)
            kw[f.name] = None if v is None else jax.device_put(v, sharding)
        return RaggedBatch(**kw)


if _HAVE_JAX:
    jax.tree_util.register_pytree_node(
        RaggedBatch,
        lambda rb: (tuple(getattr(rb, f.name) for f in dc_fields(rb)), None),
        lambda _, children: RaggedBatch(*children),
    )


def _flat_string_column(col, n_rows: int, lut: np.ndarray,
                        clip_lens: Optional[np.ndarray] = None
                        ) -> tuple[np.ndarray, np.ndarray]:
    """Arrow string/binary column -> (decoded flat int8 [T], lens int32
    [n_rows], source-gather tuple) with no inter-read padding.  The Arrow
    var-length layout already IS concatenated-bytes + prefix-sum offsets,
    so the dense (null-free, unsliced) case decodes with ONE LUT pass
    over the data buffer — no per-row work at all.

    ``clip_lens`` caps each row's decoded length (the qual plane clips to
    the sequence length: flat planes share the sequence's offsets, and a
    kernel never reads past ``read_len`` anyway — exactly the bytes the
    padded packer exposes)."""
    arr = col.combine_chunks() if isinstance(col, (pa.ChunkedArray,)) \
        else col
    if isinstance(arr, pa.ChunkedArray):  # zero-chunk edge case
        arr = pa.concat_arrays(arr.chunks) if arr.num_chunks \
            else pa.array([], pa.string())
    n = len(arr)
    bufs = arr.buffers()
    offsets = np.frombuffer(bufs[1], np.int32, count=n + 1,
                            offset=arr.offset * 4) if n else \
        np.zeros(1, np.int32)
    data = np.frombuffer(bufs[2], np.uint8) if len(bufs) > 2 and \
        bufs[2] is not None else np.zeros(0, np.uint8)
    lens = (offsets[1:] - offsets[:-1]).astype(np.int32)
    if n and arr.null_count:
        lens = np.where(np.asarray(arr.is_null()), 0, lens)
    lens_full = np.zeros(n_rows, np.int32)
    lens_full[:n] = lens
    if clip_lens is not None:
        lens_full = np.minimum(lens_full, clip_lens)
        lens = lens_full[:n]
    T = int(lens.sum())
    if T == 0:
        return np.zeros(0, np.int8), lens_full
    contiguous = (not (n and arr.null_count) and clip_lens is None and
                  data.size == int(offsets[-1]) - int(offsets[0]) and
                  bool((offsets[1:] >= offsets[:-1]).all()))
    if contiguous:
        flat = lut[data[int(offsets[0]):int(offsets[0]) + T]].astype(
            np.int8, copy=False)
        return flat, lens_full
    src = np.repeat(offsets[:-1].astype(np.int64), lens) + \
        _ranges_within(lens)
    return lut[data[src]].astype(np.int8, copy=False), lens_full


def _ragged_walk(lens: np.ndarray, t_pad: int
                 ) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """(row_offsets [N+1], row_of [t_pad], pos_of [t_pad]) for per-read
    lengths — the materialized prefix-sum row index; slack walks row 0 at
    position 0 (excluded by flat index, never consumed)."""
    n = len(lens)
    row_offsets = np.zeros(n + 1, np.int32)
    np.cumsum(lens, out=row_offsets[1:])
    T = int(row_offsets[-1])
    row_of = np.zeros(t_pad, np.int32)
    pos_of = np.zeros(t_pad, np.int32)
    row_of[:T] = np.repeat(np.arange(n, dtype=np.int32), lens)
    pos_of[:T] = _ranges_within(lens).astype(np.int32)
    return row_offsets, row_of, pos_of


def pack_reads_ragged(table: pa.Table, *, with_bases: bool = True,
                      with_cigar: bool = True, pad_rows_to: int = 1,
                      pad_bases_to: int = 1,
                      max_cigar_ops: int = MAX_CIGAR_OPS) -> RaggedBatch:
    """:func:`pack_reads`' ragged twin: same scalar columns, flat planes.

    Lossless against the padded packers by construction — the flat
    planes hold exactly the per-read prefixes :func:`pack_reads` (and
    :func:`..io.wirespill.pack_reads_wire`) expose below ``read_len``,
    in the same row order; tests/test_ragged.py pins the differential
    over adversarial alphabets, nulls, empty reads and one-read chunks.
    Wire-format chunks (``io.wirespill.to_wire`` spills) route through
    :func:`..io.wirespill.pack_reads_ragged_wire`, which rebuilds the
    same planes off the wire matrices.
    """
    from .io.wirespill import is_wire_table, pack_reads_ragged_wire

    if with_bases and is_wire_table(table):
        return pack_reads_ragged_wire(
            table, pad_rows_to=pad_rows_to, pad_bases_to=pad_bases_to,
            with_cigar=with_cigar, max_cigar_ops=max_cigar_ops)
    n = table.num_rows
    n_pad = _round_up(max(n, 1), pad_rows_to)
    batch = dict(
        flags=_int_column(table, "flags", n_pad, null_value=0),
        refid=_int_column(table, "referenceId", n_pad),
        start=_int_column(table, "start", n_pad),
        mapq=_int_column(table, "mapq", n_pad),
        mate_refid=_int_column(table, "mateReferenceId", n_pad),
        mate_start=_int_column(table, "mateAlignmentStart", n_pad),
        read_group=_int_column(table, "recordGroupId", n_pad),
        valid=np.arange(n_pad) < n,
        row_index=np.where(np.arange(n_pad) < n,
                           np.arange(n_pad), -1).astype(np.int32),
    )
    if with_bases:
        bases, read_len = _flat_string_column(
            table.column("sequence"), n_pad, _BASE_LUT)
        quals, qual_eff = _flat_string_column(
            table.column("qual"), n_pad, _OFFSET_LUTS[33],
            clip_lens=read_len)
        t_pad = _round_up(max(len(bases), 1), max(int(pad_bases_to), 1))
        bases_p = np.full(t_pad, S.BASE_PAD, np.int8)
        bases_p[:len(bases)] = bases
        row_offsets, row_of, pos_of = _ragged_walk(read_len, t_pad)
        # the qual plane shares the SEQUENCE offsets: a shorter qual
        # string fills its prefix and leaves QUAL_PAD up to read_len,
        # exactly the padded packer's tail — so scatter, don't concat
        quals_p = np.full(t_pad, QUAL_PAD, np.int8)
        if len(quals):
            dst = np.repeat(row_offsets[:-1].astype(np.int64),
                            qual_eff) + _ranges_within(qual_eff)
            quals_p[dst] = quals
        batch.update(read_len=read_len, row_offsets=row_offsets,
                     bases_flat=bases_p, quals_flat=quals_p,
                     row_of=row_of, pos_of=pos_of)
    else:
        batch.update(read_len=np.zeros(n_pad, np.int32),
                     row_offsets=np.zeros(n_pad + 1, np.int32))
    if with_cigar:
        ops, lens, n_ops = pack_cigars(
            table.column("cigar"), n_pad, max_cigar_ops)
        batch.update(cigar_ops=ops, cigar_lens=lens, n_cigar=n_ops)
    return RaggedBatch(**batch)


def ragged_from_batch(batch: ReadBatch, pad_bases_to: int = 1
                      ) -> RaggedBatch:
    """Flatten an already-packed padded :class:`ReadBatch` into the
    ragged layout (one boolean take per plane — row-major order is
    concatenation order).  The bridge the streaming passes use to feed
    ragged kernels without re-decoding, and the differential oracle for
    :func:`pack_reads_ragged`."""
    if batch.bases is None or batch.read_len is None:
        raise ValueError("ragged_from_batch needs packed base planes")
    n, L = batch.bases.shape
    read_len = np.minimum(np.asarray(batch.read_len, np.int32), L)
    mask = np.arange(L, dtype=np.int32)[None, :] < read_len[:, None]
    T = int(read_len.sum())
    t_pad = _round_up(max(T, 1), max(int(pad_bases_to), 1))
    bases_p = np.full(t_pad, S.BASE_PAD, np.int8)
    bases_p[:T] = np.asarray(batch.bases)[mask]
    quals_p = np.full(t_pad, QUAL_PAD, np.int8)
    if batch.quals is not None:
        quals_p[:T] = np.asarray(batch.quals)[mask]
    row_offsets, row_of, pos_of = _ragged_walk(read_len, t_pad)
    return RaggedBatch(
        flags=batch.flags, refid=batch.refid, start=batch.start,
        mapq=batch.mapq, mate_refid=batch.mate_refid,
        mate_start=batch.mate_start, read_group=batch.read_group,
        valid=batch.valid, row_index=batch.row_index,
        read_len=read_len, row_offsets=row_offsets,
        bases_flat=bases_p, quals_flat=quals_p,
        row_of=row_of, pos_of=pos_of,
        cigar_ops=batch.cigar_ops, cigar_lens=batch.cigar_lens,
        n_cigar=batch.n_cigar)
