"""Tracing and stage timing.

The reference has no profiling subsystem — observability is log4j messages
plus stage-progress printlns (RealignIndels.scala:442-450,
RecalibrateBaseQualities.scala:37-44) and whatever the Spark web UI shows;
AdamMain logs its argv for reproduction (AdamMain.scala:55,66-71).  This
module is the TPU framework's own: nested wall-clock stage timers that
accumulate into a report, and an opt-in bridge to the JAX device profiler
(jax.profiler) for XLA-level traces viewable in Perfetto/TensorBoard.

Usage::

    with stage("markdup"):
        table = mark_duplicates(table)
    print(report().format())

Timers are process-global (one pipeline per process, matching the CLI) and
cheap enough to leave on; the JAX profiler is only started when a trace
directory is given (it interacts with compilation caching).
"""

from __future__ import annotations

import contextlib
import os
import sys
import time
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional

from .obs import stage_finished as _obs_stage_finished


@dataclass
class StageStats:
    name: str
    calls: int = 0
    seconds: float = 0.0
    children: "Dict[str, StageStats]" = field(default_factory=dict)


@dataclass
class PipelineReport:
    root: StageStats = field(default_factory=lambda: StageStats("pipeline"))
    _stack: List[StageStats] = field(default_factory=list)

    def format(self) -> str:
        lines = ["stage timing:"]
        total = sum(c.seconds for c in self.root.children.values())

        def walk(node: StageStats, depth: int) -> None:
            pct = 100.0 * node.seconds / total if total else 0.0
            lines.append(f"  {'  ' * depth}{node.name:<24s}"
                         f"{node.seconds:9.3f} s  x{node.calls:<4d}{pct:5.1f}%")
            for c in node.children.values():
                walk(c, depth + 1)

        for c in self.root.children.values():
            walk(c, 0)
        return "\n".join(lines)

    def reset(self) -> None:
        self.root = StageStats("pipeline")
        self._stack = []


_REPORT = PipelineReport()


def quiet() -> bool:
    """THE stderr gate: every instrument print routes through here, so
    ``ADAM_TPU_QUIET`` silences all of it — log_invocation honored it
    while device_trace and the CLI's report print did not (one env var,
    three behaviors was a bug)."""
    return bool(os.environ.get("ADAM_TPU_QUIET"))


def say(msg: str) -> None:
    """Quiet-gated stderr print; the single exit for instrument chatter."""
    if not quiet():
        print(msg, file=sys.stderr)


def print_report() -> None:
    """The CLI's ``-timing`` output, through the same quiet gate."""
    if not quiet():
        print(_REPORT.format())

#: whether ``stage(sync=True)`` actually drains device queues.  Accurate
#: per-stage attribution costs a host/device barrier per stage entry+exit,
#: which forfeits async-dispatch overlap in the production hot loops — so
#: the barrier only runs when a timing consumer opted in (-timing,
#: bench_e2e); otherwise sync stages degrade to plain wall-clock timers.
_SYNC_TIMING = False


def set_sync_timing(enabled: bool) -> None:
    global _SYNC_TIMING
    _SYNC_TIMING = enabled


def report() -> PipelineReport:
    return _REPORT


@contextlib.contextmanager
def stage(name: str, *, sync: bool = False) -> Iterator[None]:
    """Time a pipeline stage; nests.  ``sync=True`` drains pending device
    work first so the stage is charged its own device time, not its
    predecessor's (async dispatch otherwise misattributes) — gated on
    :func:`set_sync_timing` so untimed runs keep full pipelining."""
    parent = _REPORT._stack[-1] if _REPORT._stack else _REPORT.root
    node = parent.children.setdefault(name, StageStats(name))
    sync = sync and _SYNC_TIMING
    if sync:
        _block_on_device()
    t0 = time.perf_counter()
    _REPORT._stack.append(node)
    try:
        yield
    finally:
        if sync:
            _block_on_device()
        _REPORT._stack.pop()
        node.calls += 1
        dt = time.perf_counter() - t0
        node.seconds += dt
        # the metrics plane sees every stage too: counters/histograms in
        # the process registry (merge-able across workers) plus a JSONL
        # event when a -metrics log is open (a few dict ops; the report
        # tree stays the -timing formatter's source)
        _obs_stage_finished(name, dt)


def _block_on_device() -> None:
    """Drain every local device's queue, not just the default one — a
    shard_map stage leaves work in flight on all mesh devices, and TPU
    queues complete in order, so one trailing op per device is a barrier."""
    try:
        import jax
        jax.block_until_ready([jax.device_put(0, device=d) + 0
                               for d in jax.local_devices()])
    except Exception:  # pragma: no cover - no backend
        pass


@contextlib.contextmanager
def device_trace(trace_dir: Optional[str]) -> Iterator[None]:
    """XLA-level profiler trace (Perfetto/TensorBoard) when a dir is given."""
    if not trace_dir:
        yield
        return
    import jax
    jax.profiler.start_trace(trace_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
        say(f"device trace written to {trace_dir}")


def log_invocation(argv: Optional[List[str]] = None) -> None:
    """AdamMain parity: record the exact argv for reproduction
    (AdamMain.scala:55,66-71)."""
    argv = sys.argv if argv is None else argv
    say(f"adam-tpu invocation: {' '.join(argv)}")
