"""Tracing and stage timing.

The reference has no profiling subsystem — observability is log4j messages
plus stage-progress printlns (RealignIndels.scala:442-450,
RecalibrateBaseQualities.scala:37-44) and whatever the Spark web UI shows;
AdamMain logs its argv for reproduction (AdamMain.scala:55,66-71).  This
module is the TPU framework's own: nested wall-clock stage timers that
accumulate into a report, and an opt-in bridge to the JAX device profiler
(jax.profiler) for XLA-level traces viewable in Perfetto/TensorBoard.

Usage::

    with stage("markdup"):
        table = mark_duplicates(table)
    print(report().format())

Timers are process-global (one pipeline per process, matching the CLI) and
cheap enough to leave on; the stage STACK is per-thread (contextvar), so
feeder threads and prep pools time their own stages without corrupting
the main thread's nesting.  Every stage exit also lands on the opt-in
run timeline (``obs.trace`` — the CLI's ``-trace`` flag) as a span on
the calling thread's lane.  The JAX profiler is only started when a
trace directory is given (it interacts with compilation caching).
"""

from __future__ import annotations

import contextlib
import contextvars
import os
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional

from .obs import stage_finished as _obs_stage_finished
from .obs import ioledger as _ioledger
from .obs import trace as _trace


@dataclass
class StageStats:
    name: str
    calls: int = 0
    seconds: float = 0.0
    children: "Dict[str, StageStats]" = field(default_factory=dict)


#: the stage stack is PER-THREAD (contextvar: each thread — and each
#: asyncio task — sees its own), replacing the process-shared list that
#: forced PR 3 to run feed producers unstaged: interleaved stages from a
#: feeder thread and the consumer would pop each other's frames and
#: mis-nest the whole timing tree.  Each thread's stages root at the
#: report root, so feeder/prep-pool work shows up as its own top-level
#: lane instead of corrupting the main thread's nesting.
_STACKS: "contextvars.ContextVar[Optional[List[StageStats]]]" = \
    contextvars.ContextVar("adam_tpu_stage_stack", default=None)

#: tree mutations (setdefault + the exit accounting) are cross-thread
#: now; one cheap lock keeps calls/seconds exact
_TREE_LOCK = threading.Lock()


def _stage_stack() -> List[StageStats]:
    s = _STACKS.get()
    if s is None:
        s = []
        _STACKS.set(s)
    return s


@dataclass
class PipelineReport:
    root: StageStats = field(default_factory=lambda: StageStats("pipeline"))

    def format(self) -> str:
        lines = ["stage timing:"]
        total = sum(c.seconds for c in self.root.children.values())

        def walk(node: StageStats, depth: int) -> None:
            pct = 100.0 * node.seconds / total if total else 0.0
            lines.append(f"  {'  ' * depth}{node.name:<24s}"
                         f"{node.seconds:9.3f} s  x{node.calls:<4d}{pct:5.1f}%")
            for c in node.children.values():
                walk(c, depth + 1)

        for c in self.root.children.values():
            walk(c, 0)
        return "\n".join(lines)

    def reset(self) -> None:
        self.root = StageStats("pipeline")
        # clear the CALLING thread's stack: stages opened after a reset
        # must not nest under a node of the discarded tree (other
        # threads' stacks drain naturally as their open stages exit)
        _STACKS.set([])


_REPORT = PipelineReport()


def quiet() -> bool:
    """THE stderr gate: every instrument print routes through here, so
    ``ADAM_TPU_QUIET`` silences all of it — log_invocation honored it
    while device_trace and the CLI's report print did not (one env var,
    three behaviors was a bug)."""
    return bool(os.environ.get("ADAM_TPU_QUIET"))


def say(msg: str) -> None:
    """Quiet-gated stderr print; the single exit for instrument chatter."""
    if not quiet():
        print(msg, file=sys.stderr)


def print_report() -> None:
    """The CLI's ``-timing`` output, through the same quiet gate.  The
    per-pass I/O ledger rides along when a run recorded any — the
    decoded/spilled/re-read breakdown belongs in the same end-of-run
    report as the stage walls it explains."""
    if not quiet():
        print(_REPORT.format())
        io_lines = _ioledger.format_report()
        if io_lines:
            print(io_lines)

#: whether ``stage(sync=True)`` actually drains device queues.  Accurate
#: per-stage attribution costs a host/device barrier per stage entry+exit,
#: which forfeits async-dispatch overlap in the production hot loops — so
#: the barrier only runs when a timing consumer opted in (-timing,
#: bench_e2e); otherwise sync stages degrade to plain wall-clock timers.
_SYNC_TIMING = False


def set_sync_timing(enabled: bool) -> None:
    global _SYNC_TIMING
    _SYNC_TIMING = enabled


def report() -> PipelineReport:
    return _REPORT


@contextlib.contextmanager
def stage(name: str, *, sync: bool = False) -> Iterator[None]:
    """Time a pipeline stage; nests.  ``sync=True`` drains pending device
    work first so the stage is charged its own device time, not its
    predecessor's (async dispatch otherwise misattributes) — gated on
    :func:`set_sync_timing` so untimed runs keep full pipelining.

    THREAD-AWARE: the stack is per-thread (contextvar), so feeder
    threads, the realign prep pool, and pipelined ingest workers may all
    run staged concurrently — each thread's stages nest among themselves
    and root at the report root.  When the tracing plane is on
    (``obs.trace``), every stage exit also records a span on this
    thread's timeline lane."""
    stack = _stage_stack()
    with _TREE_LOCK:
        parent = stack[-1] if stack else _REPORT.root
        node = parent.children.setdefault(name, StageStats(name))
    sync = sync and _SYNC_TIMING
    if sync:
        _block_on_device()
    tr = _trace.active()
    ts0 = tr.now_us() if tr is not None else 0.0
    t0 = time.perf_counter()
    stack.append(node)
    try:
        yield
    finally:
        if sync:
            _block_on_device()
        stack.pop()
        dt = time.perf_counter() - t0
        with _TREE_LOCK:
            node.calls += 1
            node.seconds += dt
        if tr is not None:
            # end = the collector's OWN clock at exit (not ts0 + dt):
            # both clocks tick off perf_counter, so exit order implies
            # end order and nested spans can never outlive their parent
            # in the written trace by a scheduling gap between the two
            # entry-time captures
            tr.complete(name, ts0, tr.now_us() - ts0)
        # the metrics plane sees every stage too: counters/histograms in
        # the process registry (merge-able across workers) plus a JSONL
        # event when a -metrics log is open (a few dict ops; the report
        # tree stays the -timing formatter's source)
        _obs_stage_finished(name, dt)


def _block_on_device() -> None:
    """Drain every local device's queue, not just the default one — a
    shard_map stage leaves work in flight on all mesh devices, and TPU
    queues complete in order, so one trailing op per device is a barrier."""
    try:
        import jax
        jax.block_until_ready([jax.device_put(0, device=d) + 0
                               for d in jax.local_devices()])
    except Exception:  # pragma: no cover - no backend
        pass


@contextlib.contextmanager
def device_trace(trace_dir: Optional[str]) -> Iterator[None]:
    """XLA-level profiler trace (Perfetto/TensorBoard) when a dir is given."""
    if not trace_dir:
        yield
        return
    import jax
    jax.profiler.start_trace(trace_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
        say(f"device trace written to {trace_dir}")


def log_invocation(argv: Optional[List[str]] = None) -> None:
    """AdamMain parity: record the exact argv for reproduction
    (AdamMain.scala:55,66-71)."""
    argv = sys.argv if argv is None else argv
    say(f"adam-tpu invocation: {' '.join(argv)}")
