"""Pipeline checkpoint/resume.

The reference has no mid-pipeline checkpointing; its de-facto durability is
"every CLI command materializes a Parquet dataset" while `transform` chains
all stages in memory and restarts from zero on failure (SURVEY §5).  Here
the same Parquet materialization becomes an explicit, resumable mechanism:
each completed stage is written to ``<dir>/<N>-<stage>/`` next to a manifest
recording the stage sequence and a fingerprint of the pipeline
configuration.  On rerun with the same directory, completed stages are
skipped and the pipeline restarts from the latest surviving stage's table.

A stage directory only enters the manifest after its Parquet write has
finished, so a crash mid-write is invisible to resume (the manifest is
rewritten atomically via rename).
"""

from __future__ import annotations

import hashlib
import json
import os
import tempfile
from dataclasses import dataclass, field
from typing import Callable, List, Optional

import pyarrow as pa

from .resilience import faults as _faults

MANIFEST = "checkpoint.json"


def _fingerprint(parts: List[str]) -> str:
    return hashlib.sha256("\x00".join(parts).encode()).hexdigest()[:16]


def fsync_dir(path: str) -> None:
    """fsync a directory so a just-renamed entry survives power loss
    (rename durability needs the PARENT flushed, not just the file).
    Best effort: some filesystems refuse directory fds."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def atomic_write(path: str, payload: str, *,
                 fault_site: Optional[str] = None,
                 fsync: bool = True) -> None:
    """THE durable atomic text write: tmp in the target's directory,
    flush + fsync the content, optional fault-injection hook on the
    in-flight tmp (`checkpoint_write` truncation = a torn power-loss
    write, as the next process observes it), atomic rename, parent-dir
    fsync.  One implementation for every manifest writer (this module,
    the streaming checkpoint in parallel/pipeline.py, the evidence
    ledger) so the durability discipline cannot drift between copies.
    A fired fault leaves the torn tmp behind — that IS the post-crash
    disk state the resume paths must tolerate.  An OSError (a real or
    injected disk-full, ENOSPC) is different: the writer is still
    alive to clean up, so the tmp is removed before re-raising — a
    disk-full run must not leave torn durable artifacts behind.  The
    two contracts coexist because InjectedTornWrite is a
    RuntimeError, never an OSError.

    ``fsync=False`` keeps the tmp+rename atomicity but skips BOTH
    syncs — for writers whose content durability is not load-bearing
    and who batch their own directory fsync per round (the heartbeat
    lease renewal, parallel/shardstream.py)."""
    parent = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(parent, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=parent, suffix=".tmp")
    try:
        with os.fdopen(fd, "w") as f:
            f.write(payload)
            if fsync:
                f.flush()
                os.fsync(f.fileno())
        if fault_site is not None:
            _faults.fire(fault_site, path=tmp)
        os.replace(tmp, path)
    except OSError:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    if fsync:
        fsync_dir(parent)


def atomic_np_write(path: str, writer: Callable, *,
                    fsync: bool = True) -> str:
    """THE durable atomic binary-blob write — ``atomic_write``'s twin
    for np.save/np.savez payloads: tmp in the target dir + flush +
    fsync + rename + parent-dir fsync, ``writer(f)`` doing the save
    onto the open handle (a handle, not a path — np.save appends
    ``.npy`` to bare paths).  One implementation for fleet commit
    files, broadcast seed blobs, and any future binary artifact so the
    discipline cannot drift between copies.  The parent-dir fsync
    matters most where a marker ordering rides on it: the fleet
    recovery contract is commit file FIRST, progress marker second — a
    marker whose dir entry survives a power loss while the commit's
    does not would silently drop the unit from the merge.

    ``fsync=False`` keeps the tmp+rename atomicity but skips both the
    file fsync and the parent-dir fsync — for writers that batch ONE
    directory fsync per commit window themselves (the fleet's batched
    spool, parallel/shardstream.py): under an ordered-journal
    filesystem the renames still become durable in order, so the
    commit-before-marker contract holds with a single sync."""
    parent = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(parent, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=parent, suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            writer(f)
            if fsync:
                f.flush()
                os.fsync(f.fileno())
        os.replace(tmp, path)
        if fsync:
            fsync_dir(parent)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    return path


@dataclass
class CheckpointDir:
    """A resumable run rooted at ``path`` for a given pipeline config.

    ``config`` describes the pipeline (input path + flag spellings); a
    directory created by a different config is rejected rather than
    silently resumed into a different pipeline.
    """
    path: str
    config: List[str]
    completed: List[str] = field(default_factory=list)

    def __post_init__(self):
        os.makedirs(self.path, exist_ok=True)
        mpath = os.path.join(self.path, MANIFEST)
        if os.path.exists(mpath):
            with open(mpath) as f:
                m = json.load(f)
            if m.get("fingerprint") != _fingerprint(self.config):
                # say WHICH kind of mismatch: a changed input file needs a
                # recompute (stale checkpoints), different flags usually
                # means the wrong -checkpoint_dir
                old = m.get("config")
                detail = "pipeline configuration differs"
                if isinstance(old, list) and len(old) == len(self.config):
                    changed = [i for i, (a, b)
                               in enumerate(zip(old, self.config)) if a != b]
                    if changed and all(
                            ":" in self.config[i] for i in changed):
                        detail = ("input file(s) changed since the "
                                  "checkpoint was written — the cached "
                                  "stages are stale")
                    elif changed:
                        detail = ("pipeline stages/flags differ: "
                                  f"{[old[i] for i in changed]} vs "
                                  f"{[self.config[i] for i in changed]}")
                elif isinstance(old, list):
                    detail = "pipeline stage list differs"
                raise ValueError(
                    f"checkpoint dir {self.path}: {detail}; refusing to "
                    f"resume (delete it or use another -checkpoint_dir)")
            self.completed = [s for s in m.get("completed", [])
                              if os.path.isdir(self._stage_dir(s))]

    def _stage_dir(self, name: str) -> str:
        return os.path.join(self.path, name)

    def _write_manifest(self) -> None:
        payload = json.dumps({"fingerprint": _fingerprint(self.config),
                              "config": self.config,
                              "completed": self.completed})
        atomic_write(os.path.join(self.path, MANIFEST), payload,
                     fault_site="checkpoint_write")

    def latest(self) -> Optional[str]:
        return self.completed[-1] if self.completed else None

    def load(self, name: str) -> pa.Table:
        from .io.parquet import load_table
        return load_table(self._stage_dir(name))

    def save(self, name: str, table: pa.Table) -> None:
        from .io.parquet import save_table
        save_table(table, self._stage_dir(name))
        if name not in self.completed:
            self.completed.append(name)
        self._write_manifest()


def run_stages(ckpt: Optional[CheckpointDir], table: pa.Table,
               stages: List[tuple], *, on_skip=None) -> pa.Table:
    """Run ``[(name, fn), ...]`` over ``table``, checkpointing each stage.

    With a checkpoint dir, stages up to the last completed one are skipped
    and the pipeline resumes from its saved table.  Stage names get an
    ordinal prefix so the same op appearing twice checkpoints separately.
    """
    names = [f"{i:02d}-{name}" for i, (name, _) in enumerate(stages)]
    start = 0
    if ckpt is not None and ckpt.latest() is not None:
        latest = ckpt.latest()
        if latest in names:
            start = names.index(latest) + 1
            table = ckpt.load(latest)
            if on_skip:
                on_skip(names[:start])
    for i in range(start, len(stages)):
        _, fn = stages[i]
        table = fn(table)
        if ckpt is not None:
            ckpt.save(names[i], table)
    return table
