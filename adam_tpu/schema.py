"""Columnar record schemas for the TPU-native ADAM rebuild.

The reference defines ten Avro records in
``adam-format/src/main/resources/avro/adam.avdl`` (ADAMRecord :4-68, Base :70-88,
ADAMNucleotideContig :90-97, ADAMPileup :99-128, ADAMVariant :157-217,
ADAMGenotype :219-298, ADAMVariantDomain :310-325, ...).  We mirror those records
as Arrow schemas so Parquet files keep the reference's columnar/projection
discipline, with one TPU-first change: the eleven read-flag booleans of
ADAMRecord (adam.avdl:31-43) are packed into a single uint32 ``flags`` column
using the standard SAM flag bit layout.  On device that single word is what the
kernels consume; the Avro-style boolean views are exposed as helper expressions
(see :data:`FLAG_FIELDS`).

Coordinates are 0-based throughout, like the reference (adam.avdl:16-17).
"""

from __future__ import annotations

import pyarrow as pa

# --------------------------------------------------------------------------
# SAM flag bits (standard layout; replaces adam.avdl:31-43 booleans)
# --------------------------------------------------------------------------

FLAG_PAIRED = 0x1            # readPaired
FLAG_PROPER_PAIR = 0x2       # properPair
FLAG_UNMAPPED = 0x4          # !readMapped
FLAG_MATE_UNMAPPED = 0x8     # !mateMapped
FLAG_REVERSE = 0x10          # readNegativeStrand
FLAG_MATE_REVERSE = 0x20     # mateNegativeStrand
FLAG_FIRST_OF_PAIR = 0x40    # firstOfPair
FLAG_SECOND_OF_PAIR = 0x80   # secondOfPair
FLAG_SECONDARY = 0x100       # !primaryAlignment
FLAG_QC_FAIL = 0x200         # failedVendorQualityChecks
FLAG_DUPLICATE = 0x400       # duplicateRead
FLAG_SUPPLEMENTARY = 0x800   # (not modeled by the reference; kept for SAM parity)

#: Mapping from the reference's ADAMRecord boolean field names (adam.avdl:31-43)
#: to ``(bit, inverted)`` pairs over the packed ``flags`` column.
FLAG_FIELDS = {
    "readPaired": (FLAG_PAIRED, False),
    "properPair": (FLAG_PROPER_PAIR, False),
    "readMapped": (FLAG_UNMAPPED, True),
    "mateMapped": (FLAG_MATE_UNMAPPED, True),
    "readNegativeStrand": (FLAG_REVERSE, False),
    "mateNegativeStrand": (FLAG_MATE_REVERSE, False),
    "firstOfPair": (FLAG_FIRST_OF_PAIR, False),
    "secondOfPair": (FLAG_SECOND_OF_PAIR, False),
    "primaryAlignment": (FLAG_SECONDARY, True),
    "failedVendorQualityChecks": (FLAG_QC_FAIL, False),
    "duplicateRead": (FLAG_DUPLICATE, False),
}

# --------------------------------------------------------------------------
# Base / CIGAR alphabets
# --------------------------------------------------------------------------

#: IUPAC nucleotide alphabet, same 17 symbols as the Base enum (adam.avdl:70-88).
#: The first four codes are the base-4 encoding used by the BQSR context
#: covariate (cf. StandardCovariate.scala:50-104); N is code 4.
BASES = "ACGTNUXKMRYSWBVHD"
BASE_CODE = {b: i for i, b in enumerate(BASES)}
BASE_CODE.update({b.lower(): i for i, b in enumerate(BASES)})
BASE_PAD = -1

#: CIGAR operators in SAM spec order: code = index in "MIDNSHP=X".
CIGAR_OPS = "MIDNSHP=X"
CIGAR_CODE = {op: i for i, op in enumerate(CIGAR_OPS)}
(CIGAR_M, CIGAR_I, CIGAR_D, CIGAR_N, CIGAR_S,
 CIGAR_H, CIGAR_P, CIGAR_EQ, CIGAR_X) = range(9)
#: ops that consume read bases / reference bases (SAM spec)
CIGAR_CONSUMES_READ = (True, True, False, False, True, False, False, True, True)
CIGAR_CONSUMES_REF = (True, False, True, True, False, False, False, True, True)

# --------------------------------------------------------------------------
# Arrow schemas (Parquet on-disk layout)
# --------------------------------------------------------------------------

#: ADAMRecord (adam.avdl:4-68) with the flag booleans packed into ``flags``.
READ_SCHEMA = pa.schema([
    pa.field("referenceName", pa.string()),
    pa.field("referenceId", pa.int32()),
    pa.field("start", pa.int64()),
    pa.field("mapq", pa.int32()),
    pa.field("readName", pa.string()),
    pa.field("sequence", pa.string()),
    pa.field("mateReference", pa.string()),
    pa.field("mateAlignmentStart", pa.int64()),
    pa.field("cigar", pa.string()),
    pa.field("qual", pa.string()),
    pa.field("recordGroupName", pa.string()),
    pa.field("recordGroupId", pa.int32()),
    pa.field("flags", pa.uint32()),
    pa.field("mismatchingPositions", pa.string()),   # the SAM MD tag
    pa.field("attributes", pa.string()),
    # denormalized record-group metadata (adam.avdl:49-59)
    pa.field("recordGroupSequencingCenter", pa.string()),
    pa.field("recordGroupDescription", pa.string()),
    pa.field("recordGroupRunDateEpoch", pa.int64()),
    pa.field("recordGroupFlowOrder", pa.string()),
    pa.field("recordGroupKeySequence", pa.string()),
    pa.field("recordGroupLibrary", pa.string()),
    pa.field("recordGroupPredictedMedianInsertSize", pa.int32()),
    pa.field("recordGroupPlatform", pa.string()),
    pa.field("recordGroupPlatformUnit", pa.string()),
    pa.field("recordGroupSample", pa.string()),
    pa.field("mateReferenceId", pa.int32()),
    # denormalized sequence-dictionary fields (adam.avdl:6-12,62-67)
    pa.field("referenceLength", pa.int64()),
    pa.field("referenceUrl", pa.string()),
    pa.field("mateReferenceLength", pa.int64()),
    pa.field("mateReferenceUrl", pa.string()),
])

#: ADAMNucleotideContig (adam.avdl:90-97); sequence stored as a string, not an
#: enum array — strings are the natural Arrow/Parquet layout.
CONTIG_SCHEMA = pa.schema([
    pa.field("contigName", pa.string()),
    pa.field("contigId", pa.int32()),
    pa.field("description", pa.string()),
    pa.field("sequence", pa.large_string()),
    pa.field("sequenceLength", pa.int64()),
    pa.field("url", pa.string()),
])

#: ADAMPileup (adam.avdl:99-128).
PILEUP_SCHEMA = pa.schema([
    pa.field("referenceName", pa.string()),
    pa.field("referenceId", pa.int32()),
    pa.field("position", pa.int64()),
    pa.field("rangeOffset", pa.int32()),
    pa.field("rangeLength", pa.int32()),
    pa.field("referenceBase", pa.string()),
    pa.field("readBase", pa.string()),
    pa.field("sangerQuality", pa.int32()),
    pa.field("mapQuality", pa.int32()),
    pa.field("numSoftClipped", pa.int32()),
    pa.field("numReverseStrand", pa.int32()),
    pa.field("countAtPosition", pa.int32()),
    pa.field("readName", pa.string()),
    pa.field("readStart", pa.int64()),
    pa.field("readEnd", pa.int64()),
    pa.field("recordGroupSequencingCenter", pa.string()),
    pa.field("recordGroupDescription", pa.string()),
    pa.field("recordGroupRunDateEpoch", pa.int64()),
    pa.field("recordGroupFlowOrder", pa.string()),
    pa.field("recordGroupKeySequence", pa.string()),
    pa.field("recordGroupLibrary", pa.string()),
    pa.field("recordGroupPredictedMedianInsertSize", pa.int32()),
    pa.field("recordGroupPlatform", pa.string()),
    pa.field("recordGroupPlatformUnit", pa.string()),
    pa.field("recordGroupSample", pa.string()),
])

#: ADAMVariant (adam.avdl:157-217).
VARIANT_SCHEMA = pa.schema([
    pa.field("referenceId", pa.int32()),
    pa.field("referenceName", pa.string()),
    pa.field("referenceLength", pa.int64()),
    pa.field("referenceUrl", pa.string()),
    pa.field("position", pa.int64()),
    pa.field("referenceAllele", pa.string()),
    pa.field("isReference", pa.bool_()),
    pa.field("variant", pa.string()),
    pa.field("variantType", pa.string()),
    pa.field("id", pa.string()),
    pa.field("quality", pa.int32()),
    pa.field("filters", pa.string()),
    pa.field("filtersRun", pa.bool_()),
    pa.field("alleleFrequency", pa.float64()),
    pa.field("rmsBaseQuality", pa.int32()),
    pa.field("siteRmsMappingQuality", pa.int32()),
    pa.field("siteMapQZeroCounts", pa.int32()),
    pa.field("totalSiteMapCounts", pa.int32()),
    pa.field("numberOfSamplesWithData", pa.int32()),
    pa.field("totalNumberOfSamplesCount", pa.int32()),
    pa.field("strandBias", pa.float64()),
    pa.field("svType", pa.string()),
    pa.field("svLength", pa.int64()),
    pa.field("svIsPrecise", pa.bool_()),
    pa.field("svEnd", pa.int64()),
    pa.field("svConfidenceIntervalStartLow", pa.int64()),
    pa.field("svConfidenceIntervalStartHigh", pa.int64()),
    pa.field("svConfidenceIntervalEndLow", pa.int64()),
    pa.field("svConfidenceIntervalEndHigh", pa.int64()),
])

#: ADAMGenotype (adam.avdl:219-298).
GENOTYPE_SCHEMA = pa.schema([
    pa.field("referenceId", pa.int32()),
    pa.field("referenceName", pa.string()),
    pa.field("position", pa.int64()),
    pa.field("sampleId", pa.string()),
    pa.field("ploidy", pa.int32()),
    pa.field("haplotypeNumber", pa.int32()),
    pa.field("alleleVariantType", pa.string()),
    pa.field("allele", pa.string()),
    pa.field("isReference", pa.bool_()),
    pa.field("referenceAllele", pa.string()),
    pa.field("expectedAlleleDosage", pa.float64()),
    pa.field("genotypeQuality", pa.int32()),
    pa.field("depth", pa.int32()),
    pa.field("phredLikelihoods", pa.string()),
    pa.field("phredPosteriorLikelihoods", pa.string()),
    pa.field("ploidyStateGenotypeLikelihoods", pa.string()),
    pa.field("haplotypeQuality", pa.int32()),
    pa.field("rmsBaseQuality", pa.int32()),
    pa.field("rmsMapQuality", pa.int32()),
    pa.field("readsMappedForwardStrand", pa.int32()),
    pa.field("readsMappedMapQ0", pa.int32()),
    pa.field("svType", pa.string()),
    pa.field("svLength", pa.int64()),
    pa.field("svIsPrecise", pa.bool_()),
    pa.field("svEnd", pa.int64()),
    pa.field("svConfidenceIntervalStartLow", pa.int64()),
    pa.field("svConfidenceIntervalStartHigh", pa.int64()),
    pa.field("svConfidenceIntervalEndLow", pa.int64()),
    pa.field("svConfidenceIntervalEndHigh", pa.int64()),
    pa.field("isPhased", pa.bool_()),
    pa.field("isPhaseSwitch", pa.bool_()),
    pa.field("phaseSetId", pa.string()),
    pa.field("phaseQuality", pa.int32()),
])

#: ADAMVariantDomain (adam.avdl:310-325).
VARIANT_DOMAIN_SCHEMA = pa.schema([
    pa.field("referenceId", pa.int32()),
    pa.field("position", pa.int64()),
    pa.field("referenceAllele", pa.string()),
    pa.field("variant", pa.string()),
    pa.field("inDbSNP", pa.bool_()),
    pa.field("inHM2", pa.bool_()),
    pa.field("inHM3", pa.bool_()),
    pa.field("in1000G", pa.bool_()),
])

#: ADAMNestedPileup (adam.avdl:130-135): a pileup plus its overlapping read
#: evidence.  Declared but unused by any reference code; carried for schema
#: parity as nested structs (which is why the reference notes it "cannot be
#: used with databases" — same caveat applies to flat-columnar projection).
NESTED_PILEUP_SCHEMA = pa.schema([
    pa.field("pileup", pa.struct(list(PILEUP_SCHEMA))),
    pa.field("readEvidence", pa.list_(pa.struct(list(READ_SCHEMA)))),
])

#: ADAMGenotypeIdentification (adam.avdl:327-345): sample cohort/ethnicity +
#: record-group fields.  Declared but unused by any reference code.
GENOTYPE_IDENTIFICATION_SCHEMA = pa.schema([
    pa.field("sampleEthnicity", pa.string()),
    pa.field("sampleCohort", pa.string()),
    pa.field("recordGroupSequencingCenter", pa.string()),
    pa.field("recordGroupDescription", pa.string()),
    pa.field("recordGroupRunDateEpoch", pa.int64()),
    pa.field("recordGroupFlowOrder", pa.string()),
    pa.field("recordGroupKeySequence", pa.string()),
    pa.field("recordGroupLibrary", pa.string()),
    pa.field("recordGroupPredictedMedianInsertSize", pa.int32()),
    pa.field("recordGroupPlatform", pa.string()),
    pa.field("recordGroupPlatformUnit", pa.string()),
    pa.field("recordGroupSample", pa.string()),
])

SCHEMAS = {
    "read": READ_SCHEMA,
    "contig": CONTIG_SCHEMA,
    "pileup": PILEUP_SCHEMA,
    "nestedpileup": NESTED_PILEUP_SCHEMA,
    "variant": VARIANT_SCHEMA,
    "genotype": GENOTYPE_SCHEMA,
    "variantdomain": VARIANT_DOMAIN_SCHEMA,
    "genotypeidentification": GENOTYPE_IDENTIFICATION_SCHEMA,
}
