"""Device-side shuffle and halo exchange — the XLA-collective backend.

The reference's distribution substrate is the Spark 0.8.1 shuffle (TCP block
transfers keyed by partitioner) plus driver aggregates (SURVEY.md §2.4).
This module provides the TPU-native equivalents as collectives that ride ICI
inside a slice (and DCN between hosts when the mesh spans processes):

* :func:`all_to_all_reshard` — the shuffle itself.  Rows arrive sharded in
  arrival order (file order); each device routes its rows to the device that
  owns their key (e.g. the genome-bin stripe owner from
  ``GenomicRegionPartitioner``) with one fixed-capacity
  ``jax.lax.all_to_all``.  This is the MoE-dispatch formulation of a
  shuffle: dense [n_shards, capacity, ...] send/recv buffers with validity
  masks instead of dynamic blocks, because XLA collectives need static
  shapes.
* :func:`ring_halo_merge` — neighbor exchange via ``ppermute``.  The
  host-side partitioner handles boundary-spanning reads by duplicating them
  into both bins (partitioner.py); when reads are already on-device, the
  cheaper alternative is to let each stripe count a halo of positions past
  its right edge and ``ppermute`` the halo to the right neighbor — a ring
  step, the same communication shape as ring attention's kv rotation.
* :func:`pileup_counts_halo_exchange` — the sequence-parallel pileup built
  from the two: each device counts its stripe + halo, one ppermute merges
  boundaries.  No host round-trip, no read duplication.

Multi-host: :func:`initialize` wraps ``jax.distributed.initialize`` and
:func:`make_host_mesh` builds the 2-D ("host", "chip") mesh whose outer axis
maps onto DCN and inner axis onto ICI — shard the genome axis over "host"
(rare, bulky resharding over DCN) and the read axis over "chip" (frequent
psum/all_to_all over ICI), the layout SURVEY.md §2.4 calls for.
"""

from __future__ import annotations

import os
from functools import lru_cache, partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ..platform import axis_size, shard_map
from .mesh import READS_AXIS

HOST_AXIS = "host"
CHIP_AXIS = "chip"


# --------------------------------------------------------------------------
# multi-host runtime
# --------------------------------------------------------------------------

def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> None:
    """Join the multi-host runtime (no-op for single-process runs).

    Replaces the reference's Akka/Spark control plane (pom.xml:33-35): after
    this, ``jax.devices()`` spans every host and collectives cross DCN.
    The contract is explicit opt-in: the join happens only when arguments
    are passed or a coordinator address is in the environment
    (JAX_COORDINATOR_ADDRESS / COORDINATOR_ADDRESS /
    MEGASCALE_COORDINATOR_ADDRESS — what multi-host launchers export).
    Anything implicit (SLURM job vars, TPU-pod worker metadata) deliberately
    does NOT trigger a join: those markers are present for lone processes
    too — a single process SSH'd onto one worker of a slice, or inside
    `salloc -n 8` — and an inferred barrier would block them forever.
    Multi-host launches must export a coordinator address (or pass
    arguments); whenever a join is attempted, failures RAISE — a swallowed
    failure would mean psums silently reporting per-host partial results.
    """
    if num_processes is not None and num_processes <= 1:
        return
    explicit = (coordinator_address is not None or num_processes is not None
                or process_id is not None)
    coordinator_env = any(os.environ.get(k) for k in (
        "JAX_COORDINATOR_ADDRESS", "COORDINATOR_ADDRESS",
        "MEGASCALE_COORDINATOR_ADDRESS"))
    if not explicit and not coordinator_env:
        return
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)


def make_host_mesh(devices=None) -> Mesh:
    """2-D mesh [hosts, chips-per-host] with axes ("host", "chip").

    Collectives over "chip" stay on ICI; collectives over "host" cross DCN.
    Single-process runs get a 1×n mesh, so code written against the two-axis
    layout runs unchanged on one host.
    """
    if devices is None:
        devices = jax.devices()
    by_proc: dict = {}
    for d in devices:
        by_proc.setdefault(d.process_index, []).append(d)
    counts = {p: len(v) for p, v in by_proc.items()}
    if len(set(counts.values())) != 1:
        raise ValueError(
            f"hosts hold unequal device counts {counts}; a rectangular "
            "(host, chip) mesh needs the same chips per host")
    grid = np.array([by_proc[p] for p in sorted(by_proc)], dtype=object)
    return Mesh(grid, (HOST_AXIS, CHIP_AXIS))


# --------------------------------------------------------------------------
# per-worker metrics gather (coordination-service control plane)
# --------------------------------------------------------------------------

#: monotonic sequence so repeated gathers use fresh KV keys (every process
#: calls in the same program order, so sequence numbers agree)
_METRICS_GATHER_SEQ = [0]


def gather_metrics_snapshots(timeout_ms: int = 60_000) -> list:
    """Every process's obs-registry snapshot, gathered over the
    coordination service's key-value store.

    This is deliberately the CONTROL plane (the same gRPC service
    ``jax.distributed.initialize`` brought up), not a device collective:
    snapshots are small JSON, the gather happens once per run at report
    time, and the KV path works on every backend — including CPU jaxlibs
    whose XLA build has no multiprocess computations.  The reference's
    analog is executors shipping accumulator updates to the driver.
    Single-process runs return ``[own snapshot]`` without any service.
    """
    import json

    from ..obs.registry import registry

    snap = registry().snapshot()
    if jax.process_count() == 1:
        return [snap]
    from jax._src import distributed as _dist

    client = _dist.global_state.client
    if client is None:
        raise RuntimeError(
            "metrics gather needs the coordination service; call "
            "initialize() (or pass a coordinator address) first")
    seq = _METRICS_GATHER_SEQ[0]
    _METRICS_GATHER_SEQ[0] += 1
    prefix = f"adam_tpu/obs/{seq}"
    client.key_value_set(f"{prefix}/{jax.process_index()}",
                         json.dumps(snap))
    snaps = []
    for pid in range(jax.process_count()):
        if pid == jax.process_index():
            snaps.append(snap)
        else:
            snaps.append(json.loads(client.blocking_key_value_get(
                f"{prefix}/{pid}", timeout_ms)))
    return snaps


#: monotonic sequence for trace gathers (separate namespace from the
#: metrics gather so the two cannot race each other's keys)
_TRACE_GATHER_SEQ = [0]


def gather_trace_events(timeout_ms: int = 60_000) -> list:
    """Every process's trace-event buffer, gathered over the same
    coordination-service KV store as the metrics snapshots.

    SYMMETRIC — every process must call in the same program order (like
    ``gather_metrics_snapshots``); a process with tracing off
    contributes an empty list, so mixed configurations gather without
    deadlock.  Events are small JSON dicts (stage granularity); a run's
    buffer is a few hundred KB at worst, well inside KV payload bounds.
    """
    import json

    from ..obs import trace

    t = trace.active()
    own = t.events() if t is not None else []
    if jax.process_count() == 1:
        return [own]
    from jax._src import distributed as _dist

    client = _dist.global_state.client
    if client is None:
        raise RuntimeError(
            "trace gather needs the coordination service; call "
            "initialize() (or pass a coordinator address) first")
    seq = _TRACE_GATHER_SEQ[0]
    _TRACE_GATHER_SEQ[0] += 1
    prefix = f"adam_tpu/trace/{seq}"
    client.key_value_set(f"{prefix}/{jax.process_index()}",
                         json.dumps(own))
    out = []
    for pid in range(jax.process_count()):
        if pid == jax.process_index():
            out.append(own)
        else:
            out.append(json.loads(client.blocking_key_value_get(
                f"{prefix}/{pid}", timeout_ms)))
    return out


def merge_worker_traces(timeout_ms: int = 60_000) -> int:
    """Fold every peer's trace events into THIS process's collector (the
    coordinator then writes ONE timeline with a lane per process —
    exactly how metrics snapshots merge).  Returns the number of foreign
    events folded; 0 with tracing off locally (the gather still runs, so
    the call stays symmetric across the fleet)."""
    from ..obs import trace

    bufs = gather_trace_events(timeout_ms)
    t = trace.active()
    if t is None:
        return 0
    me = jax.process_index() if jax.process_count() > 1 else 0
    n = 0
    for i, evs in enumerate(bufs):
        if i != me and evs:
            n += t.add_events(evs)
    return n


#: registry generation at the last fold — the once-per-run guard below
_LAST_MERGE_GEN = [None]


def merge_worker_metrics(timeout_ms: int = 60_000) -> dict:
    """Fold every peer worker's registry snapshot into THIS process's
    registry (counters sum, gauges max, histograms bucket-add) and return
    the merged snapshot.

    Symmetric — every process ends up with the fleet view — so the
    coordinator's report (and its ``-metrics`` summary event) carries
    merged per-worker counters, the acceptance shape for distributed
    runs.  The reference got this from Spark's driver-side aggregate
    of executor metrics; here it is one KV gather + three monoid merges.

    At most once per run: after the fold every registry already holds
    fleet totals, so a second gather would sum peers' fleet views and
    double-count.  Guarded — raises unless the registry was reset since
    the previous merge (a new run).
    """
    from ..obs.registry import registry

    gen = registry().generation
    if _LAST_MERGE_GEN[0] == gen:
        raise RuntimeError(
            "merge_worker_metrics already ran for this registry "
            "generation; a second fold would double-count peers "
            "(reset the registry to start a new run)")
    snaps = gather_metrics_snapshots(timeout_ms)
    me = jax.process_index() if jax.process_count() > 1 else 0
    for i, s in enumerate(snaps):
        if i != me:
            registry().merge(s)
    # stamp the fleet-view marker (obs.snapshot_is_fleet_merged): any
    # aggregator folding this process's sidecar with its peers' must
    # merge at most one of them, or every counter counts N times
    registry().gauge("fleet_merged").set(1)
    _LAST_MERGE_GEN[0] = gen
    return registry().snapshot()


# --------------------------------------------------------------------------
# all_to_all reshard: the shuffle
# --------------------------------------------------------------------------

def _dispatch_local(dest, cols, n_shards: int, capacity: int):
    """Pack this device's rows into [n_shards, capacity, ...] send buffers.

    Rows beyond a destination's capacity are dropped (counted in the returned
    overflow); callers size capacity from the partitioner's bin histogram the
    same way the reference sizes reducer counts from coverage
    (PileupAggregator.scala:204-209).
    """
    n = dest.shape[0]
    # stable sort by destination; rank within destination group = position -
    # start of group.  O(n log n), fully vectorized.
    order = jnp.argsort(dest, stable=True)
    sorted_dest = dest[order]
    group_start = jnp.searchsorted(sorted_dest, jnp.arange(n_shards),
                                   side="left")
    rank_sorted = jnp.arange(n) - group_start[sorted_dest]
    rank = jnp.zeros_like(rank_sorted).at[order].set(rank_sorted)

    keep = rank < capacity
    slot = jnp.where(keep, dest * capacity + rank, n_shards * capacity)
    overflow = jnp.sum(~keep)

    def scatter(col):
        buf = jnp.zeros((n_shards * capacity + 1,) + col.shape[1:], col.dtype)
        return buf.at[slot].set(col)[:-1].reshape(
            (n_shards, capacity) + col.shape[1:])

    sent_valid = scatter(keep.astype(jnp.int8)).astype(bool)
    return jax.tree.map(scatter, cols), sent_valid, overflow


def _reshard_step(dest, cols, n_shards: int, capacity: int, axis_name: str):
    send, sent_valid, overflow = _dispatch_local(dest, cols, n_shards,
                                                 capacity)
    a2a = partial(jax.lax.all_to_all, axis_name=axis_name, split_axis=0,
                  concat_axis=0, tiled=True)
    recv = jax.tree.map(a2a, send)
    recv_valid = a2a(sent_valid)
    flat = jax.tree.map(
        lambda x: x.reshape((n_shards * capacity,) + x.shape[2:]), recv)
    total_overflow = jax.lax.psum(overflow, axis_name)
    return flat, recv_valid.reshape(-1), total_overflow


def all_to_all_reshard(mesh: Mesh, dest: jnp.ndarray, cols, capacity: int,
                       axis_name: str = READS_AXIS):
    """Route rows to the shard owning their key — the device-side shuffle.

    Args:
      mesh: 1-D mesh over ``axis_name``.
      dest: [N] int32 global array (sharded on the read axis) of destination
        shard ids in [0, mesh.size).
      cols: pytree of [N, ...] arrays to move with each row.
      capacity: max rows any one source sends to any one destination.  Each
        device receives exactly ``mesh.size * capacity`` slots back.

    Returns (cols_out, valid, overflow): resharded pytree of
    [mesh.size * capacity, ...] per device (global shape
    [mesh.size² * capacity, ...]), a validity mask, and the global count of
    rows dropped to the capacity limit (0 when capacity was sized right).
    """
    _, treedef = jax.tree.flatten(cols)
    fn = _build_resharder(mesh, treedef, capacity, axis_name)
    return fn(dest, cols)


@lru_cache(maxsize=None)
def _build_resharder(mesh: Mesh, treedef, capacity: int, axis_name: str):
    """One shard_map+jit per (mesh, tree shape, capacity) — cached so
    per-batch calls reuse the compiled collective."""
    n_shards = mesh.shape[axis_name]
    step = partial(_reshard_step, n_shards=n_shards, capacity=capacity,
                   axis_name=axis_name)
    spec = P(axis_name)
    spec_tree = jax.tree.unflatten(
        treedef, [spec] * treedef.num_leaves)
    fn = shard_map(
        step, mesh=mesh,
        in_specs=(spec, spec_tree),
        out_specs=(spec_tree, spec, P()))
    return jax.jit(fn)


# --------------------------------------------------------------------------
# ppermute halo exchange
# --------------------------------------------------------------------------

def ring_halo_merge(stripe: jnp.ndarray, halo: jnp.ndarray,
                    axis_name: str = READS_AXIS) -> jnp.ndarray:
    """Merge per-stripe halo counts into the right neighbor's leading rows.

    ``stripe`` is this device's [span, ...] count block; ``halo`` holds counts
    this device accumulated for the first H positions *past* its right edge
    (they belong to the next stripe).  One ``ppermute`` ring step moves every
    halo one device to the right; the halo arriving at stripe 0 wraps from
    the genome's end and is dropped, mirroring the partitioner's refusal to
    spill ranges into the unmapped bin (partitioner.py bins_for_ranges).
    """
    n = axis_size(axis_name)
    incoming = jax.lax.ppermute(halo, axis_name,
                                perm=[(i, (i + 1) % n) for i in range(n)])
    first = jax.lax.axis_index(axis_name) == 0
    incoming = jnp.where(first, jnp.zeros_like(incoming), incoming)
    h = halo.shape[0]
    return stripe.at[:h].add(incoming.astype(stripe.dtype))


def route_by_start(start, mapped, valid, bin_span: int, n_stripes: int):
    """Host-side start-only routing for the halo-exchange pileup: each read
    goes to exactly ONE stripe, the one holding its start position.

    This is the required counterpart of :func:`pileup_counts_halo_exchange` —
    do NOT use ``route_reads_to_stripes`` (parallel/pileup.py) with it: that
    router *duplicates* boundary-spanning reads into both stripes, which the
    halo merge would then count twice.  Returns (rows, stripe) for the
    mapped+valid reads.
    """
    rows = np.flatnonzero(np.asarray(mapped) & np.asarray(valid))
    stripe = np.minimum(np.asarray(start)[rows] // bin_span, n_stripes - 1)
    return rows.astype(np.int64), stripe.astype(np.int32)


@lru_cache(maxsize=None)
def pileup_counts_halo_exchange(mesh: Mesh, bin_span: int, halo: int,
                                max_len: int):
    """Sequence-parallel pileup without boundary-read duplication.
    Memoized per (mesh, bin_span, halo, max_len) like
    ``_build_resharder`` — the validation errors below re-raise on
    every call (lru_cache never caches exceptions).

    Each device counts positions [i*bin_span, i*bin_span + bin_span + halo)
    for its stripe i — its own span plus a halo wide enough for the longest
    read/deletion overhang — then one ring ppermute folds halos into
    neighbors.  Compare ``sharded_pileup_counts`` (parallel/pileup.py), which
    instead expects the host to have duplicated boundary reads.

    Returns a jitted fn(bases, quals, start, flags, mapq, valid, cigar_ops,
    cigar_lens) -> [n_devices * bin_span, N_CHANNELS] with reads sharded on
    the leading axis by the stripe of their START (route with
    :func:`route_by_start`; start-only routing is what makes the halo merge
    count each base exactly once).
    """
    from .pileup import pileup_count_kernel

    if halo > bin_span:
        raise ValueError(
            f"halo {halo} exceeds bin_span {bin_span}: one ring step only "
            "reaches the immediate neighbor, so overhang beyond a full "
            "stripe would be lost — widen the stripes or shrink the halo")
    if halo < max_len - 1:
        # the silent-undercount direction: a read starting on a stripe's
        # last position reaches max_len - 1 positions past the edge; a
        # smaller halo would drop those boundary counts without any error
        # (deletions consume extra reference — callers still owe headroom
        # for them on top of this read-length floor)
        raise ValueError(
            f"halo {halo} below the read-length floor max_len - 1 = "
            f"{max_len - 1}: boundary positions past bin_span + halo would "
            "be silently lost")
    spec = P(READS_AXIS)

    def step(bases, quals, start, flags, mapq, valid, cigar_ops, cigar_lens):
        i = jax.lax.axis_index(READS_AXIS)
        bin_start = (i * bin_span).astype(jnp.int32)
        counts = pileup_count_kernel(bases, quals, start, flags, mapq, valid,
                                     cigar_ops, cigar_lens, bin_start,
                                     bin_span=bin_span + halo,
                                     max_len=max_len)
        return ring_halo_merge(counts[:bin_span], counts[bin_span:],
                               READS_AXIS)

    fn = shard_map(step, mesh=mesh, in_specs=(spec,) * 8, out_specs=spec)
    return jax.jit(fn)
