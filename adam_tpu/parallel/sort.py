"""Distributed device sample-sort — the cluster sort, on the mesh.

Re-designs ``adamSortReadsByReferencePosition``'s range-partition +
``sortByKey`` (rdd/AdamRDDFunctions.scala:63-93) as an on-device sample
sort over XLA collectives:

  1. each shard sorts locally and takes evenly spaced key samples;
  2. one ``all_gather`` pools the samples; the pooled sort's quantiles
     become the n-1 range splitters (the reference's RangePartitioner
     does exactly this with a driver-side sample collect);
  3. rows route to the shard owning their key range with the MoE-style
     fixed-capacity ``all_to_all`` (parallel/distributed.py);
  4. each shard sorts what it received; shard order == key-range order,
     so reading shards in order yields the global sort.

Keys are TWO int32 words — (dense contig rank, biased position) — not one
int64: TPUs have no native int64 (and this runtime keeps x64 off, where
int64 device arrays silently truncate), while ``lax.sort`` with
``num_keys`` gives exact lexicographic multiword ordering for free.  Ties
break by original row index (a third sort word), which makes the whole
sort STABLE — the same guarantee ``ops/sort.sort_order``'s lexsort gives,
so the two agree bit-for-bit and the multi-device path is testable
against the host path.

The reference scatters unmapped reads over 10k synthetic keys to dodge
range-partitioner skew (:66-82); here unmapped rows share one maximal key
and skew is bounded by the capacity factor instead — overflow raises
loudly rather than silently dropping rows.
"""

from __future__ import annotations

from functools import lru_cache, partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from .. import schema as S
from ..platform import shard_map
from .mesh import READS_AXIS, make_mesh

_POS_BIAS = np.int64(1) << 31
_PAD_HI = np.int32(2**31 - 1)   # sorts after every real rank incl. unmapped


def pack_sort_keys(flags: np.ndarray, refid: np.ndarray,
                   start: np.ndarray):
    """(flags, refid, start) -> (hi int32, lo uint32) key words matching
    ``ops/sort``'s (key_ref, key_pos) lexsort order.  Contig ids densify
    to ranks first (ids can be sparse, e.g. crc32-assigned)."""
    flags = np.asarray(flags, np.int64)
    refid = np.asarray(refid, np.int64)
    start = np.asarray(start, np.int64)
    mapped = (flags & S.FLAG_UNMAPPED) == 0
    ids = np.unique(refid)
    rank = np.searchsorted(ids, refid).astype(np.int64)
    n_rank = len(ids) + 1
    hi = np.where(mapped, rank, n_rank).astype(np.int32)
    # unmapped reads keep input order, so their ORDER IS their key: using
    # the row index as the position word spreads what would otherwise be
    # one giant equal-key run across every splitter range — the same skew
    # dodge as the reference's 10k-synthetic-key scatter
    # (AdamRDDFunctions.scala:66-82), but exact instead of approximate
    lo = np.where(mapped, start + _POS_BIAS,
                  np.arange(len(flags))).astype(np.uint32)
    return hi, lo


def _lex_dest(hi, lo, sp_hi, sp_lo):
    """searchsorted(splitters, key, side='right') over two-word keys:
    dest = count of splitters <= key, lexicographically."""
    le = (sp_hi[None, :] < hi[:, None]) | \
        ((sp_hi[None, :] == hi[:, None]) & (sp_lo[None, :] <= lo[:, None]))
    return jnp.sum(le.astype(jnp.int32), axis=1)


def _sort_step(hi, lo, idx, n_shards: int, capacity: int, n_samples: int):
    m = hi.shape[0]
    lh, ll, li = jax.lax.sort((hi, lo, idx), num_keys=3)
    stride = max(m // n_samples, 1)
    sh = jax.lax.all_gather(lh[::stride][:n_samples], READS_AXIS).reshape(-1)
    sl = jax.lax.all_gather(ll[::stride][:n_samples], READS_AXIS).reshape(-1)
    sh, sl = jax.lax.sort((sh, sl), num_keys=2)
    q = sh.shape[0] // n_shards
    sp_hi = sh[q::q][:n_shards - 1]
    sp_lo = sl[q::q][:n_shards - 1]
    dest = _lex_dest(lh, ll, sp_hi, sp_lo)

    from .distributed import _reshard_step
    (rh, rl, ri), recv_valid, overflow = _reshard_step(
        dest, (lh, ll, li), n_shards, capacity, READS_AXIS)
    rh = jnp.where(recv_valid, rh, _PAD_HI)
    ri = jnp.where(recv_valid, ri, jnp.iinfo(jnp.int32).max)
    oh, ol, oi = jax.lax.sort((rh, rl, ri), num_keys=3)
    return oh, oi, jnp.sum(recv_valid.astype(jnp.int32))[None], overflow


@lru_cache(maxsize=None)
def _build_sorter(mesh: Mesh, capacity: int, n_samples: int):
    n_shards = mesh.shape[READS_AXIS]
    spec = P(READS_AXIS)
    fn = shard_map(
        partial(_sort_step, n_shards=n_shards, capacity=capacity,
                n_samples=n_samples),
        mesh=mesh, in_specs=(spec, spec, spec),
        out_specs=(spec, spec, spec, P()))
    return jax.jit(fn)


def sample_sort_permutation(hi: np.ndarray, lo: np.ndarray,
                            mesh: Mesh = None, *,
                            capacity_factor: float = 3.0,
                            n_samples: int = 64) -> np.ndarray:
    """Global stable-sort permutation of two-word keys, computed on the
    mesh.  ``perm`` satisfies: (hi, lo)[perm] is sorted with ties in
    original order — identical to ``np.lexsort((lo, hi))``."""
    if mesh is None:
        mesh = make_mesh()
    n = len(hi)
    if n == 0:
        return np.zeros(0, np.int64)
    if n >= 2**31:
        raise ValueError("row index exceeds int32 (shard the input first)")
    n_shards = mesh.shape[READS_AXIS]
    m = -(-n // n_shards)  # rows per shard
    n_pad = m * n_shards
    hp = np.full(n_pad, _PAD_HI, np.int32)
    lp = np.arange(n_pad, dtype=np.uint32)  # pads spread like unmapped rows
    hp[:n] = hi
    lp[:n] = lo
    idx = np.arange(n_pad, dtype=np.int32)
    capacity = max(int(capacity_factor * m / n_shards) + n_samples, 16)
    fn = _build_sorter(mesh, capacity, n_samples)
    from .mesh import reads_sharding
    sharding = reads_sharding(mesh)
    oh, oi, counts, overflow = fn(jax.device_put(hp, sharding),
                                  jax.device_put(lp, sharding),
                                  jax.device_put(idx, sharding))
    if int(overflow) != 0:
        raise ValueError(
            f"sample sort overflowed capacity {capacity} on "
            f"{int(overflow)} rows — key skew beyond capacity_factor "
            f"{capacity_factor}; raise it (the reference's analog is its "
            "10k-synthetic-key unmapped scatter, AdamRDDFunctions.scala:66)")
    oi = np.asarray(oi).reshape(n_shards, -1).astype(np.int64)
    counts = np.asarray(counts).reshape(n_shards)
    perm = np.concatenate([oi[s, :counts[s]] for s in range(n_shards)])
    return perm[perm < n]  # drop padding rows (maximal keys, sort last)


def sort_reads_distributed(table, mesh: Mesh = None):
    """``adamSortReadsByReferencePosition`` over the mesh: device sample
    sort of the packed keys, then one host gather by the permutation."""
    import pyarrow as pa

    from ..packing import column_int64

    hi, lo = pack_sort_keys(column_int64(table, "flags", 0),
                            column_int64(table, "referenceId"),
                            column_int64(table, "start"))
    perm = sample_sort_permutation(hi, lo, mesh)
    return table.take(pa.array(perm))
