"""Overlapped host ingest: decode/pack workers feeding device dispatch.

Re-designs ``cli/Bam2Adam.scala:56-97`` (one reader thread handing record
batches to N writer threads over a blocking queue) for the streaming
pipeline: one READER thread walks the chunk iterator in order (format
decode happens on it), a thread pool runs the per-chunk host work
(``pack_reads`` — the native packer releases the GIL, packer.c:144), and
the consumer receives results IN INPUT ORDER, so every downstream
decision (markdup keys, spill layout, output rows) is bit-identical to
the sequential walk — chunk-order-independence is a differential test,
not a hope.

Backpressure: at most ``depth`` chunks are in flight (queue slots), so
host RSS stays bounded by depth x chunk size no matter how fast the
reader outruns the device.

``workers <= 1`` degrades to the plain synchronous loop — the default
path stays exactly what rounds 1-3 shipped and measured.
"""

from __future__ import annotations

import queue
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Iterable, Iterator, Optional

from ..resilience import faults as _faults

_DONE = object()


def pipelined(items: Iterable, fn: Optional[Callable] = None,
              workers: int = 1,
              prepare: Optional[Callable] = None,
              depth: Optional[int] = None,
              pool_name: str = "ingest-pool") -> Iterator[Any]:
    """Yield ``fn(item, prepare(item))`` for each item, in input order.

    * ``prepare`` (optional) runs on the READER thread in strict input
      order before submission — the hook for sequential state such as the
      growing length bucket (its return value is passed to ``fn``).
    * ``fn`` runs on pool workers, up to ``workers`` chunks ahead.
    * ``workers <= 1``: fully synchronous, no threads.

    The reader also performs the iterator's own work (format decode), so
    decode itself overlaps the consumer even when ``fn`` is None.
    ``pool_name`` names the worker threads (``<pool_name>_N``) — the
    tracing plane (obs.trace) labels timeline lanes by thread name, so
    the realign prep pool and the ingest pack pool stay tellable apart.
    """
    if fn is None:
        fn = _passthrough
    if prepare is None:
        prepare = _no_prepare
    if workers <= 1:
        for item in items:
            # feeder_load fires on the synchronous path too, so the
            # default (thread-less) configuration exercises the same
            # fault matrix with the same occurrence ordering
            _faults.fire("feeder_load")
            yield fn(item, prepare(item))
        return

    depth = depth or workers + 1
    futs: "queue.Queue" = queue.Queue(maxsize=depth)
    stop = threading.Event()

    def put(x) -> bool:
        # bounded put that notices consumer cancellation (a plain
        # blocking put would decode the whole remaining input just to
        # have the drain discard it)
        while not stop.is_set():
            try:
                futs.put(x, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def reader(pool):
        try:
            for item in items:
                if stop.is_set():
                    return
                # injected reader-side faults surface on the consumer
                # through the same error queue a real decode error uses
                _faults.fire("feeder_load")
                ctx = prepare(item)
                if not put(pool.submit(fn, item, ctx)):
                    return
            put(_DONE)
        except BaseException as e:  # noqa: BLE001 — surface on consumer
            put(e)

    with ThreadPoolExecutor(max_workers=workers,
                            thread_name_prefix=pool_name) as pool:
        t = threading.Thread(target=reader, args=(pool,), daemon=True,
                             name="ingest-reader")
        t.start()
        try:
            while True:
                got = futs.get()
                if got is _DONE:
                    break
                if isinstance(got, BaseException):
                    raise got
                yield got.result()
        finally:
            # consumer bailed early (exception downstream): stop the
            # reader and discard whatever is already queued
            stop.set()
            while t.is_alive():
                try:
                    futs.get_nowait()
                except queue.Empty:
                    pass
                t.join(timeout=0.05)


def _passthrough(item, _ctx):
    return item


def _no_prepare(_item):
    return None


def prefetched(items: Iterable, put: Callable, depth: int = 2,
               on_chunk: Optional[Callable] = None) -> Iterator[Any]:
    """Bounded look-ahead device feed: yield ``put(item)`` in input order
    while a feeder thread runs ``put`` up to ``depth`` items AHEAD of the
    consumer.

    ``put`` is the host→device transfer (``jax.device_put`` of a padded
    wire / packed batch): running it ahead means chunk i+1's transfer
    overlaps chunk i's device compute — the double-buffer the streaming
    executor (parallel/executor.py) feeds the jit'd kernels with.  The
    in-flight queue is structurally bounded at ``depth`` results (plus
    the one the feeder is computing), the same backpressure discipline as
    :func:`pipelined`, so device HBM held by prefetched inputs is capped
    regardless of how far the host outruns the device.

    ``on_chunk(stall_seconds, inflight)`` (optional) is called on the
    CONSUMER thread once per yielded item with the time the consumer
    spent blocked waiting for it and the queue depth observed at that
    moment — the telemetry hook behind ``executor_prefetch_stall_s``.
    Host-side timing only: nothing here takes a device barrier.

    ``depth <= 0`` degrades to the plain synchronous loop (no threads),
    the default off-accelerator path.
    """
    if depth <= 0:
        for item in items:
            _faults.fire("feeder_load")
            t0 = time.perf_counter()
            got = put(item)
            if on_chunk is not None:
                on_chunk(time.perf_counter() - t0, 0)
            yield got
        return

    out: "queue.Queue" = queue.Queue(maxsize=depth)
    stop = threading.Event()

    def send(x) -> bool:
        while not stop.is_set():
            try:
                out.put(x, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def feeder():
        try:
            for item in items:
                if stop.is_set():
                    return
                _faults.fire("feeder_load")
                if not send((None, put(item))):
                    return
            send(_DONE)
        except BaseException as e:  # noqa: BLE001 — surface on consumer
            send((e, None))

    t = threading.Thread(target=feeder, daemon=True, name="device-feed")
    t.start()
    try:
        while True:
            t0 = time.perf_counter()
            got = out.get()
            stall = time.perf_counter() - t0
            if got is _DONE:
                break
            err, value = got
            if err is not None:
                raise err
            if on_chunk is not None:
                # qsize() AFTER the get: results queued ahead of the
                # consumer at pickup — structurally bounded at ``depth``
                # (the queue's maxsize), which is the bound the
                # executor's inflight-peak gauge publishes
                on_chunk(stall, out.qsize())
            yield value
    finally:
        stop.set()
        while t.is_alive():
            try:
                out.get_nowait()
            except queue.Empty:
                pass
            t.join(timeout=0.05)
