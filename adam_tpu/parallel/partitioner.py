"""Genome-coordinate partitioning — the "sequence parallelism" axis.

Re-designs ``rdd/GenomicRegionPartitioner.scala:36-104``: positions map to
equal-width bins over the cumulative genome length, with UNMAPPED reads in one
extra final bin.  The reference uses this as a Spark ``Partitioner`` inside
shuffles; here it is a vectorized numpy/JAX function that assigns every read
of a batch to a genome bin so hosts can reshard by bin (the shuffle
replacement) and kernels can segment-reduce within bins.

Boundary-spanning reads are handled the reference's way (the rod-bucket trick,
AdamRDDFunctions.scala:144-191): a read whose [start, end) crosses a bin edge
is *duplicated* into both bins by :func:`bins_for_ranges`, so per-bin kernels
never need halo exchange.
"""

from __future__ import annotations

from typing import Dict

import numpy as np

from ..models.dictionary import SequenceDictionary


class GenomicRegionPartitioner:
    """Equal-width genome bins (GenomicRegionPartitioner.scala:36-84)."""

    def __init__(self, num_parts: int, seq_lengths: Dict[int, int]):
        self.ids = np.array(sorted(seq_lengths), np.int64)
        lengths = np.array([seq_lengths[i] for i in self.ids], np.int64)
        self.total_length = int(lengths.sum())
        # parts is clamped to the genome length (degenerate tiny genomes)
        self.parts = int(min(num_parts, self.total_length))
        # cumulative length before each contig, addressed via searchsorted
        # (ids can be sparse, e.g. crc32-assigned by SequenceDictionary.map_to)
        self._cumul = np.concatenate([[0], np.cumsum(lengths)[:-1]])

    @classmethod
    def from_dictionary(cls, num_parts: int, seq_dict: SequenceDictionary):
        return cls(num_parts, {r.id: r.length for r in seq_dict})

    @property
    def num_partitions(self) -> int:
        return self.parts + 1  # +1 for the UNMAPPED bin

    def partition(self, refid: np.ndarray, pos: np.ndarray) -> np.ndarray:
        """[N] bin index per position; unmapped (refid < 0) -> last bin.

        Raises on refids not present in the dictionary — silently binning an
        unknown contig would corrupt every downstream per-bin kernel.
        """
        refid = np.asarray(refid, np.int64)
        pos = np.asarray(pos, np.int64)
        slot = np.searchsorted(self.ids, refid)
        mapped = refid >= 0
        known = mapped & (slot < len(self.ids)) & \
            (self.ids[np.minimum(slot, len(self.ids) - 1)] == refid)
        if (mapped & ~known).any():
            bad = refid[mapped & ~known]
            raise ValueError(f"unknown referenceId(s) {np.unique(bad)[:5]} "
                             "not in the sequence dictionary")
        total_offset = self._cumul[np.minimum(slot, len(self.ids) - 1)] + pos
        bins = self.bin_of_flat(total_offset)
        return np.where(mapped, bins, self.parts).astype(np.int32)

    def bin_of_flat(self, flat: np.ndarray) -> np.ndarray:
        """Mapped-bin index of a flat coordinate — exact integer floor
        division, the ONE formula shared by partition(), bin_lower_flat()
        and the halo router (pipeline._route_halo), so boundary rounding
        can never disagree between them."""
        return np.clip(np.asarray(flat, np.int64) * self.parts
                       // self.total_length, 0, self.parts - 1)

    def flat(self, refid: np.ndarray, pos: np.ndarray) -> np.ndarray:
        """[N] cumulative-genome ("flat") coordinate of each position;
        refid < 0 -> 0 (sorts before every contig, like sort_order)."""
        refid = np.asarray(refid, np.int64)
        pos = np.asarray(pos, np.int64)
        slot = np.clip(np.searchsorted(self.ids, refid), 0,
                       len(self.ids) - 1)
        return np.where(refid < 0, 0, self._cumul[slot] + pos)

    def bin_lower_flat(self, b: int) -> int:
        """Smallest flat coordinate belonging to mapped bin ``b``."""
        return (b * self.total_length + self.parts - 1) // self.parts

    def bins_for_ranges(self, refid: np.ndarray, start: np.ndarray,
                        end: np.ndarray):
        """(row_indices, bins): each read assigned to every bin its
        [start, end) range touches — boundary reads are duplicated into both
        neighbors (the reference's 1-or-2-bucket trick,
        AdamRDDFunctions.scala:175-183, generalized)."""
        first = self.partition(refid, start)
        last = self.partition(refid, np.maximum(start, end - 1))
        # a range overhanging the genome end must not spill into the
        # reserved unmapped bin
        last = np.where(first < self.parts,
                        np.minimum(last, self.parts - 1), last)
        n_bins = (last - first + 1).astype(np.int64)
        rows = np.repeat(np.arange(len(refid)), n_bins)
        offsets = np.arange(int(n_bins.sum())) - \
            np.repeat(np.cumsum(n_bins) - n_bins, n_bins)
        bins = first[rows] + offsets
        return rows.astype(np.int32), bins.astype(np.int32)
