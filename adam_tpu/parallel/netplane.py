"""TCP data plane for cross-box fleets (the third transport).

``decide_transport``'s cross-box leg was a fiction until this module:
workers either mmap'd the supervisor's page cache (``ring``) or shared
a filesystem (``fleet_dir``).  The net plane carries everything those
two ship — unit-result segments, broadcast blobs (markdup dup bits +
MD events), heartbeat leases, and the job/result relay (assignments,
redistributed extras, the done signal) — over length-framed,
CRC32-checked messages, so a fleet needs nothing but ``host:port``.

Frame discipline mirrors the ring's (ringplane.py): fixed header
``(magic, header_len, payload_len, crc32)``, JSON header, raw payload.
A frame that fails magic/length/CRC is DETECTED AND NEVER TRUSTED —
the receiver drops the connection (a byte stream cannot resync past
garbage) and the sender reconnects and resends; the supervisor's
first-wins merge dedup by ``(incarnation, shard, seq)`` absorbs the
redelivery, so exactly-once stays structural, not protocol-dependent.

Robustness contract:

* per-connection deadlines (socket timeouts, ``ADAM_TPU_FLEET_NET_TIMEOUT_S``);
* reconnect with exponential backoff and digest-deterministic jitter
  (``resilience.retry.backoff_delay`` — replayable chaos);
* the worker-local npz spool stays authoritative: every segment is
  renamed into the local spool BEFORE it is sent, and the progress
  marker lands only AFTER the supervisor acks, so a kill mid-send
  recomputes (and the dedup absorbs) instead of losing work;
* past the retry budget the worker degrades TYPED: fall back to the
  shared spool (``ADAM_TPU_FLEET_SHARED_DIR``) when one is usable —
  local commits are copied over and the worker re-enters the
  ``fleet_dir`` plane — else it exits with a typed line and the
  supervisor's ``decide_shard_reassignment`` redistributes the shard;
* SIGKILL fencing runs on socket-level lease expiry (the supervisor
  tracks lease *receipt* times), not filesystem mtimes.

Fault sites: ``net_send`` fires MID-FRAME on the worker side (error =
a dropped connection, truncate = half a frame then close, corrupt =
garbage bytes on the wire, latency = a slow peer, kill = SIGKILL
mid-send); ``net_recv`` fires before each server-side frame read;
``net_accept`` fires per accepted connection.
"""

from __future__ import annotations

import json
import os
import shutil
import socket
import struct
import sys
import threading
import time
import zlib
from typing import Dict, List, Optional, Tuple

from .. import obs
from ..resilience import faults
from ..resilience.retry import (DEFAULT_BACKOFF_CAP_S, DEFAULT_BACKOFF_S,
                                RETRY_SEED_ENV, backoff_delay)
from . import ringplane

#: supervisor address handed to net workers (host:port) — its presence
#: in a worker's env IS the transport switch
NET_ENV = "ADAM_TPU_FLEET_NET"
#: a worker's stable host identity; the boot handshake reports it and
#: run_fleet compares supervisor vs worker identity to feed
#: decide_transport a real ``same_box`` signal
HOST_ID_ENV = "ADAM_TPU_FLEET_HOST_ID"
#: the shared spool a net worker may degrade onto when the peer is
#: unreachable past the retry budget; empty/unset = no shared
#: filesystem exists, fail the shard typed instead
SHARED_DIR_ENV = "ADAM_TPU_FLEET_SHARED_DIR"
#: per-connection deadline (connect + each send/recv), seconds
NET_TIMEOUT_ENV = "ADAM_TPU_FLEET_NET_TIMEOUT_S"
#: reconnect budget per request (retries after the first attempt)
NET_RETRIES_ENV = "ADAM_TPU_FLEET_NET_RETRIES"
#: backoff base for reconnects (cap rides the retry default)
NET_BACKOFF_ENV = "ADAM_TPU_FLEET_NET_BACKOFF_S"
#: supervisor bind address (default loopback — the emulated pod)
NET_BIND_ENV = "ADAM_TPU_FLEET_NET_BIND"

DEFAULT_TIMEOUT_S = 10.0
DEFAULT_RETRIES = 4

#: frame header: magic, header_len, payload_len, crc32(header+payload)
_MAGIC = 0x41544E50                     # "ATNP"
_FRAME = struct.Struct("<IIII")
#: bounded lengths: a garbage length field must not allocate the moon
MAX_HEADER_BYTES = 8 << 20
MAX_PAYLOAD_BYTES = 256 << 20


class NetError(RuntimeError):
    """Base of every net-plane failure (typed, like InjectedFault)."""


class NetFrameError(NetError):
    """A frame failed magic/length/CRC validation, or the stream ended
    mid-frame — torn/garbage bytes, never trusted, never parsed."""


class NetUnreachable(NetError):
    """The peer stayed unreachable past the whole retry budget."""


class NetDegraded(Exception):
    """Raised by the worker plane after copying its local spool onto a
    usable shared dir: the caller re-enters the ``fleet_dir`` plane
    rooted there.  NOT a NetError — it is a handled transition, and
    catching NetError must never swallow it."""

    def __init__(self, shared_dir: str, cause: str):
        self.shared_dir = shared_dir
        self.cause = cause
        super().__init__(
            f"net plane degraded to shared spool {shared_dir!r}: {cause}")


def host_identity(env: Optional[dict] = None) -> str:
    """This process's (or a worker env's) stable host identity:
    ``ADAM_TPU_FLEET_HOST_ID`` wins, else the hostname — how two
    emulated 'hosts' on one box get distinct identities in tests and
    how real hosts get real ones."""
    env = os.environ if env is None else env
    return str(env.get(HOST_ID_ENV) or "") or socket.gethostname()


def probe_net() -> bool:
    """Whether a loopback socket can be bound at all — the capability
    input ``decide_transport`` consumes for its net leg (the net twin
    of ringplane.probe_mmap)."""
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        try:
            s.bind(("127.0.0.1", 0))
        finally:
            s.close()
        return True
    except OSError:
        return False


def _env_float(name: str, default: float) -> float:
    try:
        v = os.environ.get(name)
        return float(v) if v else default
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    try:
        v = os.environ.get(name)
        return int(v) if v else default
    except ValueError:
        return default


# ---------------------------------------------------------------------------
# frame codec
# ---------------------------------------------------------------------------

def send_frame(sock: socket.socket, header: dict,
               payload: bytes = b"", *,
               fault_site: Optional[str] = None) -> None:
    """Write one framed message.  With a fault site, the frame goes out
    in two halves with the injection hook between them — an injected
    kill IS a SIGKILL mid-frame, truncate closes the socket after half
    a frame, corrupt puts garbage bytes on the wire; all three leave
    the receiver a torn frame it must detect and drop."""
    hb = json.dumps(header, sort_keys=True).encode()
    if len(hb) > MAX_HEADER_BYTES or len(payload) > MAX_PAYLOAD_BYTES:
        raise NetFrameError("frame exceeds protocol bounds")
    crc = zlib.crc32(hb + payload) & 0xFFFFFFFF
    buf = _FRAME.pack(_MAGIC, len(hb), len(payload), crc) + hb + payload
    if fault_site is None:
        sock.sendall(buf)
    else:
        half = max(len(buf) // 2, 1)
        sock.sendall(buf[:half])
        try:
            faults.fire(fault_site)
        except faults.InjectedTornWrite as e:
            if getattr(e, "fault", "") == "corrupt":
                try:
                    sock.sendall(b"\xff" * 64)
                except OSError:
                    pass
            raise
        sock.sendall(buf[half:])
    obs.registry().counter("net_frames_out").inc()
    obs.registry().counter("net_bytes_out").inc(len(buf))


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    got = 0
    while got < n:
        chunk = sock.recv(min(n - got, 1 << 20))
        if not chunk:
            raise NetFrameError(
                f"stream ended mid-frame ({got}/{n} bytes)")
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)


def recv_frame(sock: socket.socket, *,
               fault_site: Optional[str] = None) -> Tuple[dict, bytes]:
    """Read one framed message, validating magic, bounds, and CRC —
    garbage is detected and raised as :class:`NetFrameError`, never
    parsed.  The caller's only safe recovery is dropping the
    connection: a byte stream cannot resync past a torn frame."""
    if fault_site is not None:
        faults.fire(fault_site)
    hdr = _recv_exact(sock, _FRAME.size)
    magic, hlen, plen, crc = _FRAME.unpack(hdr)
    if magic != _MAGIC:
        raise NetFrameError(f"bad frame magic {magic:#010x}")
    if hlen > MAX_HEADER_BYTES or plen > MAX_PAYLOAD_BYTES:
        raise NetFrameError(
            f"frame lengths out of bounds ({hlen}/{plen})")
    body = _recv_exact(sock, hlen + plen)
    if zlib.crc32(body) & 0xFFFFFFFF != crc:
        raise NetFrameError("frame CRC mismatch")
    try:
        header = json.loads(body[:hlen])
    except ValueError as e:
        raise NetFrameError(f"frame header is not JSON: {e}") from e
    if not isinstance(header, dict) or "t" not in header:
        raise NetFrameError("frame header missing message type")
    obs.registry().counter("net_frames_in").inc()
    obs.registry().counter("net_bytes_in").inc(
        _FRAME.size + hlen + plen)
    return header, body[hlen:]


# ---------------------------------------------------------------------------
# client (worker side)
# ---------------------------------------------------------------------------

class NetClient:
    """One worker's connection to the supervisor: synchronous framed
    request/response with per-connection deadlines and deterministic
    reconnect backoff.  Thread-safe (the lease thread and the worker
    main loop share it); a request that fails mid-flight closes the
    socket and RESENDS on a fresh connection — the server side dedups
    by ``(incarnation, shard, seq)``, so resend-on-doubt is always the
    right move."""

    def __init__(self, address: str, shard: int):
        host, _, port = address.rpartition(":")
        self.host, self.port = host or "127.0.0.1", int(port)
        self.shard = int(shard)
        self.timeout_s = _env_float(NET_TIMEOUT_ENV, DEFAULT_TIMEOUT_S)
        self.retries = _env_int(NET_RETRIES_ENV, DEFAULT_RETRIES)
        self.backoff_s = _env_float(NET_BACKOFF_ENV, DEFAULT_BACKOFF_S)
        self.seed = _env_int(RETRY_SEED_ENV, 0)
        self._sock: Optional[socket.socket] = None
        self._lock = threading.Lock()

    def _connect(self) -> None:
        s = socket.create_connection((self.host, self.port),
                                     timeout=self.timeout_s)
        s.settimeout(self.timeout_s)
        try:
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass
        self._sock = s
        obs.registry().counter("net_connects").inc()
        obs.emit("net_connect", shard=self.shard, host=self.host,
                 port=self.port)

    def _drop(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def request(self, header: dict, payload: bytes = b"", *,
                retries: Optional[int] = None) -> Tuple[dict, bytes]:
        """Send one message and return the peer's reply, reconnecting
        and resending on any failure until the retry budget runs out
        (then :class:`NetUnreachable`).  Injected net faults — drops,
        torn frames, latency — ride the same recovery as real ones."""
        budget = self.retries if retries is None else int(retries)
        kind = str(header.get("t", "?"))
        last: Optional[BaseException] = None
        with self._lock:
            for attempt in range(1, budget + 2):
                try:
                    if self._sock is None:
                        self._connect()
                    send_frame(self._sock, header, payload,
                               fault_site="net_send")
                    reply, rp = recv_frame(self._sock)
                    if reply.get("t") == "err":
                        raise NetError(
                            f"peer rejected {kind!r}: "
                            f"{reply.get('msg')}")
                    return reply, rp
                except (OSError, NetFrameError,
                        faults.InjectedDeviceError,
                        faults.InjectedTornWrite) as e:
                    # one recovery for real and injected failures:
                    # drop the connection, back off, reconnect, resend
                    self._drop()
                    last = e
                    if attempt > budget:
                        break
                    delay = backoff_delay(
                        f"net:{self.shard}:{kind}", attempt,
                        self.backoff_s, DEFAULT_BACKOFF_CAP_S,
                        seed=self.seed)
                    obs.registry().counter("net_retries").inc()
                    obs.emit("net_retry", shard=self.shard, kind=kind,
                             attempt=attempt, delay_s=delay,
                             error=type(e).__name__)
                    time.sleep(delay)
        raise NetUnreachable(
            f"peer {self.host}:{self.port} unreachable for {kind!r} "
            f"after {budget} retries: {type(last).__name__}: {last}"
        ) from last

    def close(self) -> None:
        with self._lock:
            self._drop()


class NetHeartbeat:
    """The worker lease renewal loop over TCP — the net twin of
    shardstream.Heartbeat, same fault site, same typed-death contract.
    A renewal the supervisor never receives needs no local action:
    socket-level lease expiry fences us from the supervisor side, the
    safe direction (so net failures here are swallowed, not fatal)."""

    def __init__(self, client: NetClient, shard: int, incarnation: int,
                 heartbeat_s: float):
        self.client = client
        self.shard = shard
        self.incarnation = incarnation
        self.heartbeat_s = heartbeat_s
        self._stop = threading.Event()
        self._seq = 0
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="net-lease")

    def start(self) -> "NetHeartbeat":
        try:
            self._beat()                # lease exists before any work
        except NetError:
            pass
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()

    def _beat(self) -> None:
        faults.fire("shard_lease")
        self._seq += 1
        self.client.request(
            dict(t="lease", shard=self.shard,
                 incarnation=self.incarnation, seq=self._seq),
            retries=1)

    def _run(self) -> None:
        while not self._stop.wait(self.heartbeat_s):
            try:
                self._beat()
            except faults.InjectedFault as e:
                sys.stderr.write(
                    f"shard-worker: lease renewal failed (typed): "
                    f"{type(e).__name__}: {e}\n")
                sys.stderr.flush()
                os._exit(13)
            except NetError:
                continue            # expiry fences us; keep trying
            except OSError:
                continue


class NetWorkerPlane:
    """The worker side of the net transport, presenting the same plane
    surface shardstream's ``_FileWorkerPlane`` does: load (the boot
    handshake + broadcast blob fetch), heartbeat, publish, poll.  The
    local dir is this worker's authoritative spool — commits and the
    progress marker live there; nothing is ever read from or written
    to a shared filesystem unless the plane degrades."""

    supports_steal = False

    def __init__(self, address: str, local_dir: str, shard: int):
        self.dir = local_dir
        self.shard = int(shard)
        self.client = NetClient(address, shard)
        self.incarnation = 0

    # -- handshake ---------------------------------------------------------

    def load(self) -> Optional[dict]:
        h, _ = self._rpc(dict(t="hello", shard=self.shard,
                              pid=os.getpid(),
                              host=host_identity()))
        spec = h.get("spec")
        if not isinstance(spec, dict):
            return None
        self.incarnation = int(h.get("incarnation", 0))
        os.makedirs(self.dir, exist_ok=True)
        for name in h.get("blobs", []):
            self._fetch_blob(str(name))
        # the worker's view of the fleet dir IS its local spool: the
        # task runtimes load broadcast blobs from spec["fleet_dir"],
        # which now points at the just-fetched local copies
        return dict(spec=dict(spec, fleet_dir=self.dir),
                    incarnation=self.incarnation,
                    runs=list(h.get("runs", [])))

    def _fetch_blob(self, name: str) -> None:
        base = os.path.basename(name)
        dst = os.path.join(self.dir, base)
        if os.path.exists(dst):
            return                  # a respawn re-uses its local copy
        h, payload = self._rpc(dict(t="blob", shard=self.shard,
                                    name=base))
        # frame CRC already vouched for the bytes; tmp+rename so a
        # kill mid-write leaves no torn blob for the next incarnation
        tmp = dst + ".tmp"
        with open(tmp, "wb") as f:
            f.write(payload)
        os.replace(tmp, dst)
        obs.registry().counter("broadcast_blob_bytes_net").inc(
            len(payload))

    # -- plane surface -----------------------------------------------------

    def prepare(self, spec: dict, incarnation: int) -> None:
        self.incarnation = int(incarnation)

    def heartbeat(self, heartbeat_s: float,
                  incarnation: int) -> NetHeartbeat:
        return NetHeartbeat(self.client, self.shard, incarnation,
                            heartbeat_s).start()

    def publish(self, seq: int,
                results: List[Tuple[int, dict]]) -> None:
        payload = ringplane.encode_unit_results(results)
        self._rpc(dict(t="result", shard=self.shard,
                       incarnation=self.incarnation, seq=int(seq),
                       n=len(results)), payload)

    def poll(self, incarnation: int, seen_version: int,
             ticks: int) -> dict:
        h, _ = self._rpc(dict(t="status", shard=self.shard,
                              incarnation=int(incarnation),
                              version=int(seen_version)))
        out = dict(stop=bool(h.get("done")) or bool(h.get("fenced")),
                   extra=None)
        if int(h.get("version", 0)) > seen_version:
            out["extra"] = (int(h["version"]), list(h.get("runs", [])))
        return out

    def close(self) -> None:
        self.client.close()

    # -- degradation -------------------------------------------------------

    def _rpc(self, header: dict,
             payload: bytes = b"") -> Tuple[dict, bytes]:
        try:
            return self.client.request(header, payload)
        except NetUnreachable as e:
            self._degrade_or_raise(e)
            raise               # pragma: no cover — above always raises

    def _degrade_or_raise(self, err: NetUnreachable) -> None:
        """Peer gone past the retry budget: typed degradation.  A
        usable shared spool (its plan file parses) absorbs this
        worker's local commits and progress — duplicates are absorbed
        by the supervisor's first-wins merge — and the caller re-enters
        the fleet_dir plane there; no shared spool means the shard
        fails cleanly typed and the supervisor redistributes it."""
        shared = os.environ.get(SHARED_DIR_ENV) or ""
        plan = None
        if shared:
            try:
                with open(os.path.join(shared, "plan.json")) as f:
                    plan = json.load(f)
            except (OSError, ValueError):
                plan = None
        if not isinstance(plan, dict):
            raise err
        for sub in ("commits", "progress"):
            src = os.path.join(self.dir, sub)
            if not os.path.isdir(src):
                continue
            dstdir = os.path.join(shared, sub)
            os.makedirs(dstdir, exist_ok=True)
            for name in sorted(os.listdir(src)):
                dst = os.path.join(dstdir, name)
                if sub == "commits" and os.path.exists(dst):
                    continue    # immutable once renamed; keep theirs
                tmp = os.path.join(dstdir, f".{name}.net.tmp")
                shutil.copyfile(os.path.join(src, name), tmp)
                os.replace(tmp, dst)
        obs.registry().counter("net_degradations").inc()
        obs.emit("net_degraded", shard=self.shard,
                 shared_dir=shared, error=str(err))
        raise NetDegraded(shared, str(err))


# ---------------------------------------------------------------------------
# server (supervisor side)
# ---------------------------------------------------------------------------

class NetServer:
    """The supervisor's end of the net plane: an accept loop plus one
    handler thread per connection, serving the boot handshake
    (spec + assignment + blob names), broadcast blob bytes, lease
    receipt, the status relay (done/fenced/extra), and unit-result
    ingestion.  Results are stashed raw under ``(incarnation, shard,
    seq)`` and ACKED ONLY AFTER the stash — the client treats anything
    unacked as unsent, and the supervisor's merge dedup makes the
    resulting at-least-once delivery exactly-once.

    All mutable state is instance-held behind one lock; the supervisor
    main loop pushes assignment snapshots in (``update_state``) and
    drains results out (``drain_results``), so handler threads never
    touch supervisor internals."""

    def __init__(self, plan_doc: dict, blobs: Dict[str, str],
                 bind: Optional[str] = None):
        self._plan_doc = plan_doc
        self._blobs = dict(blobs)
        host = bind or os.environ.get(NET_BIND_ENV) or "127.0.0.1"
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, 0))
        self._sock.listen(64)
        self._sock.settimeout(0.2)
        self.host, self.port = self._sock.getsockname()[:2]
        self.timeout_s = _env_float(NET_TIMEOUT_ENV, DEFAULT_TIMEOUT_S)
        self._lock = threading.Lock()
        self._results: Dict[Tuple[int, int, int], bytes] = {}
        self._leases: Dict[int, Tuple[float, int]] = {}
        self._hosts: Dict[int, str] = {}
        self._state: Dict[int, dict] = {}
        self._done = False
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []

    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def start(self) -> "NetServer":
        t = threading.Thread(target=self._accept_loop, daemon=True,
                             name="netplane-accept")
        t.start()
        self._threads.append(t)
        return self

    def close(self) -> None:
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass
        for t in self._threads:
            t.join(timeout=2.0)

    # -- supervisor-facing state -------------------------------------------

    def update_state(self, shard: int, *, incarnation: int,
                     runs: List[List[int]], extra_version: int,
                     extra_runs: List[List[int]]) -> None:
        with self._lock:
            self._state[int(shard)] = dict(
                incarnation=int(incarnation), runs=list(runs),
                extra_version=int(extra_version),
                extra_runs=list(extra_runs))

    def set_done(self) -> None:
        with self._lock:
            self._done = True

    def drain_results(self) -> List[Tuple[Tuple[int, int, int], bytes]]:
        with self._lock:
            out = sorted(self._results.items())
            self._results.clear()
        return out

    def lease_age(self, shard: int,
                  incarnation: int) -> Optional[float]:
        """Seconds since the last lease RECEIVED from this shard's
        current incarnation; None when none arrived yet (the boot
        grace applies).  Receipt time is supervisor-local monotonic —
        no clocks are compared across hosts."""
        with self._lock:
            ent = self._leases.get(int(shard))
        if ent is None or ent[1] != int(incarnation):
            return None
        return time.monotonic() - ent[0]

    def clear_lease(self, shard: int) -> None:
        with self._lock:
            self._leases.pop(int(shard), None)

    def host_of(self, shard: int) -> Optional[str]:
        with self._lock:
            return self._hosts.get(int(shard))

    # -- accept / handle ---------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _addr = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return              # socket closed: shutting down
            try:
                faults.fire("net_accept")
            except faults.InjectedFault:
                obs.registry().counter("net_accept_rejects").inc()
                try:
                    conn.close()
                except OSError:
                    pass
                continue
            conn.settimeout(self.timeout_s)
            t = threading.Thread(target=self._handle, args=(conn,),
                                 daemon=True, name="netplane-conn")
            t.start()
            self._threads.append(t)

    def _handle(self, conn: socket.socket) -> None:
        try:
            while not self._stop.is_set():
                try:
                    header, payload = recv_frame(
                        conn, fault_site="net_recv")
                except (NetFrameError, faults.InjectedFault) as e:
                    # torn/garbage frame (or an injected recv fault):
                    # detected, counted, connection dropped — the
                    # sender reconnects and resends, dedup absorbs
                    if isinstance(e, NetFrameError) and \
                            "stream ended" not in str(e):
                        obs.registry().counter(
                            "net_garbage_frames").inc()
                    return
                except (socket.timeout, OSError):
                    return          # idle past deadline or peer reset
                try:
                    reply, rp = self._dispatch(header, payload)
                except faults.InjectedFault:
                    return
                try:
                    send_frame(conn, reply, rp)
                except (OSError, NetError):
                    return
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _dispatch(self, header: dict,
                  payload: bytes) -> Tuple[dict, bytes]:
        kind = header.get("t")
        shard = int(header.get("shard", -1))
        if kind == "hello":
            with self._lock:
                st = dict(self._state.get(shard) or {})
                self._hosts[shard] = str(header.get("host", ""))
            return (dict(t="ok", spec=self._plan_doc,
                         incarnation=int(st.get("incarnation", 0)),
                         runs=list(st.get("runs", [])),
                         blobs=sorted(self._blobs)), b"")
        if kind == "blob":
            name = os.path.basename(str(header.get("name", "")))
            path = self._blobs.get(name)
            if path is None:
                return dict(t="err", msg=f"unknown blob {name!r}"), b""
            try:
                with open(path, "rb") as f:
                    blob = f.read()
            except OSError as e:
                return dict(t="err", msg=f"blob read failed: {e}"), b""
            return dict(t="ok", name=name), blob
        if kind == "lease":
            with self._lock:
                self._leases[shard] = (
                    time.monotonic(), int(header.get("incarnation", 0)))
            return dict(t="ok"), b""
        if kind == "result":
            key = (int(header.get("incarnation", 0)), shard,
                   int(header.get("seq", 0)))
            with self._lock:
                self._results.setdefault(key, payload)
            obs.registry().counter("net_segments").inc()
            # the ack leaves only AFTER the stash: an acked segment can
            # never be lost to a supervisor-side race
            return dict(t="ok"), b""
        if kind == "status":
            with self._lock:
                st = dict(self._state.get(shard) or {})
                done = self._done
            fenced = int(header.get("incarnation", -1)) != \
                int(st.get("incarnation", 0))
            return (dict(t="ok", done=done, fenced=fenced,
                         version=int(st.get("extra_version", 0)),
                         runs=list(st.get("extra_runs", []))), b"")
        return dict(t="err", msg=f"unknown message type {kind!r}"), b""
