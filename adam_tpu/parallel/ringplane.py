"""Zero-copy fleet data plane: the shared-memory ring transport, the
unit claim table, and the broadcast-blob cache.

ROADMAP item 3: ``BENCH_SHARD.json`` measured the fleet-dir transport
(npz unit results at several fsyncs per commit) as real overhead, not
neighbor noise.  This module is the same-box fast path the shard fleet
(parallel/shardstream.py) rides when the pure, replayable
:func:`decide_transport` selects it:

* **ring transport** — each worker appends its unit results to a
  fixed-capacity mmap'd ring file (``ring/shard{S}-inc{I}.ring``) as
  Arrow-IPC-framed segments.  The file header carries a seqlock-guarded
  commit cursor: the writer lays the whole segment down PAST the cursor,
  then publishes it with an odd/even seqlock dance, so a reader never
  observes a half-written segment as committed.  Every segment frame
  records its payload length and CRC32 — a SIGKILL mid-write leaves a
  *torn* segment beyond the cursor that the supervisor detects (length
  or checksum mismatch) and ignores.  Readers and the writer share the
  page cache (``MAP_SHARED`` on one file, one box), so publishing is a
  memory write, not an fsync.

  The ring is an ACCELERATOR, never the spine: the worker renames its
  durable npz commit *before* publishing the same results to the ring,
  so ring contents are always a subset of the filesystem spool and the
  crash-recovery contract (commit file first, progress marker second)
  is untouched.  The supervisor merges ring-delivered segments by the
  same ``(incarnation, shard, seq)`` first-wins key as file commits —
  a segment and its npz twin are ONE commit, not a duplicate.

* **claim table** — ``claims/unit{U}.json`` created with ``O_EXCL``:
  the structural exactly-once primitive behind unit-granular work
  stealing.  Two idle workers racing for the same pending unit cannot
  both win the create; the loser moves on.  Claims are advisory for
  WORK (the merge's dedup remains the correctness backstop) and the
  supervisor releases a dead claimant's claims so its victim recomputes.

* **broadcast cache** — the per-task broadcast blobs (markdup dup bits,
  hoisted MD events) are mapped read-only ONCE per worker process and
  memoized by (path, mtime, size); N shard incarnations in one process
  open the blob once (``broadcast_blob_opens`` counts real opens).

Both deciders here are PURE and recorded in full (``inputs`` +
``input_digest``) by their events (``transport_selected``,
``shard_entry_selected``); tools/check_executor.py replays them offline
exactly like ``decide_shard_plan``.
"""

from __future__ import annotations

import hashlib
import json
import mmap
import os
import struct
import zlib
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .. import obs
from ..resilience import faults

#: fleet-dir subdirectories owned by this plane
RING_DIR = "ring"
CLAIM_DIR = "claims"

#: knobs (the resolve-from-env convention of ADAM_TPU_FLEET_*)
TRANSPORT_ENV = "ADAM_TPU_FLEET_TRANSPORT"     # auto | ring | fleet_dir | net
SPOOL_SYNC_ENV = "ADAM_TPU_FLEET_SPOOL_SYNC"   # auto | batched | every
ENTRY_ENV = "ADAM_TPU_FLEET_ENTRY"             # auto | index | forward
RING_BYTES_ENV = "ADAM_TPU_RING_BYTES"

DEFAULT_RING_BYTES = 8 << 20

#: ring file header: magic, capacity, shard, incarnation live at fixed
#: offsets; the committed cursor (u64 @24) and seqlock counter (u32 @32)
#: are written independently by the publish dance
_MAGIC = b"ATRING01"
_HDR_CAP_OFF = 8
_HDR_SHARD_OFF = 12
_HDR_INC_OFF = 16
_HDR_COMMIT_OFF = 24
_HDR_SEQLOCK_OFF = 32
HEADER_BYTES = 64

#: segment frame: seg magic, commit seq, n_units, payload_len, crc32
_SEG_MAGIC = 0x41544E52
_SEG = struct.Struct("<IIIII")


def _pad8(n: int) -> int:
    return (n + 7) & ~7


def _digest(inputs: dict) -> str:
    return hashlib.sha256(
        json.dumps(inputs, sort_keys=True).encode()).hexdigest()[:16]


# ---------------------------------------------------------------------------
# the pure decisions
# ---------------------------------------------------------------------------

def decide_transport(*, requested: str, same_box: bool,
                     mmap_capable: bool, spool_requested: str,
                     net_available=None) -> dict:
    """Which data plane a fleet run uses — PURE.

    ``transport`` ∈ ``ring`` (mmap ring segments + spool as durable
    spine) / ``fleet_dir`` (spool only, the PR 9 plane) / ``net``
    (length-framed TCP segments, parallel/netplane.py — the cross-box
    plane that needs no shared filesystem).  The ring engages only
    when workers share the supervisor's box (page-cache coherence is
    the whole mechanism) and the fleet dir's filesystem takes an mmap;
    cross-box workers get the net plane when a socket can be bound
    (``net_available``, netplane.probe_net), else the shared-spool
    fallback.  ``net_available`` joins the recorded inputs ONLY when
    the caller supplies it (cross-box or explicit request), so
    pre-net sidecars replay digest-identical.  ``spool_sync`` ∈
    ``batched`` (one directory fsync per commit window) / ``every``
    (the conservative per-file discipline); ``auto`` resolves to
    batched.  Recorded in full by ``transport_selected``;
    tools/check_executor.py replays it.
    """
    inputs = dict(requested=str(requested), same_box=bool(same_box),
                  mmap_capable=bool(mmap_capable),
                  spool_requested=str(spool_requested))
    if net_available is not None:
        inputs["net_available"] = bool(net_available)
    net_cap = bool(inputs.get("net_available", False))
    reasons = []
    if inputs["requested"] == "fleet_dir":
        transport, why = "fleet_dir", "forced"
    elif inputs["requested"] == "net":
        transport, why = "net", "forced"
    elif not inputs["mmap_capable"]:
        if not inputs["same_box"] and net_cap:
            # no mmap AND no page-cache coherence: TCP beats a shared
            # spool that cannot even take the ring
            transport, why = "net", "no-mmap-cross-box"
        else:
            transport, why = "fleet_dir", "no-mmap"
    elif inputs["requested"] == "ring":
        transport, why = "ring", "forced"
    elif not inputs["same_box"]:
        # cross-box workers share no page cache: the net plane if a
        # socket binds, else the spool (a shared filesystem) is the
        # only coherent medium
        if net_cap:
            transport, why = "net", "cross-box-net"
        else:
            transport, why = "fleet_dir", "cross-box"
    else:
        transport, why = "ring", "same-box"
    reasons.append(why)
    spool_sync = inputs["spool_requested"]
    if spool_sync not in ("batched", "every"):
        spool_sync = "batched"
        reasons.append("spool-auto-batched")
    return dict(transport=transport, spool_sync=spool_sync,
                reason="+".join(reasons), inputs=inputs,
                input_digest=_digest(inputs))


def decide_shard_entry(*, kind: str, requested: str,
                       index_available: bool) -> dict:
    """How a shard's range reader enters the input — PURE.

    ``entry`` ∈ ``rowgroup`` (Parquet native range skip) / ``index``
    (SAM byte offsets / BAM BGZF virtual offsets: seek to the unit
    range) / ``forward`` (decode from row 0 — the honest re-decode
    fallback when no index exists or the caller forces it).  Recorded
    in full by ``shard_entry_selected``; tools/check_executor.py
    replays it.
    """
    inputs = dict(kind=str(kind), requested=str(requested),
                  index_available=bool(index_available))
    if inputs["kind"] not in ("sam", "bam"):
        entry, reason = "rowgroup", "parquet-native-range"
    elif inputs["requested"] == "forward":
        entry, reason = "forward", "forced"
    elif not inputs["index_available"]:
        entry, reason = "forward", "no-index"
    else:
        entry, reason = "index", ("forced" if inputs["requested"]
                                  == "index" else "index-available")
    return dict(entry=entry, reason=reason, inputs=inputs,
                input_digest=_digest(inputs))


def probe_mmap(directory: str) -> bool:
    """Whether ``directory``'s filesystem takes a shared writable mmap
    (some network filesystems refuse) — the capability input
    ``decide_transport`` consumes."""
    path = os.path.join(directory, ".ring_probe")
    try:
        with open(path, "wb") as f:
            f.truncate(mmap.PAGESIZE)
        with open(path, "r+b") as f:
            m = mmap.mmap(f.fileno(), mmap.PAGESIZE)
            m[0:1] = b"\x01"
            m.close()
        return True
    except (OSError, ValueError):
        return False
    finally:
        try:
            os.unlink(path)
        except OSError:
            pass


# ---------------------------------------------------------------------------
# Arrow-IPC segment payloads
# ---------------------------------------------------------------------------

def encode_unit_results(results: Sequence[Tuple[int, Dict[str, "np.ndarray"]]]
                        ) -> bytes:
    """Unit results -> one Arrow IPC stream: a ``units`` int64 column
    plus one binary column per result key (raw array bytes; dtype and
    shape ride the field metadata).  Keys sort so the frame layout is
    deterministic for a given result set."""
    import pyarrow as pa

    fields = [pa.field("units", pa.int64())]
    cols = [pa.array([int(u) for u, _ in results], pa.int64())]
    for key in sorted(results[0][1]):
        arrs = [np.ascontiguousarray(r[key]) for _, r in results]
        meta = {b"dtype": str(arrs[0].dtype).encode(),
                b"shape": json.dumps(list(arrs[0].shape)).encode()}
        fields.append(pa.field(key, pa.binary(), metadata=meta))
        cols.append(pa.array([a.tobytes() for a in arrs], pa.binary()))
    batch = pa.record_batch(cols, schema=pa.schema(fields))
    sink = pa.BufferOutputStream()
    with pa.ipc.new_stream(sink, batch.schema) as w:
        w.write_batch(batch)
    return sink.getvalue().to_pybytes()


def decode_unit_results(payload: bytes
                        ) -> List[Tuple[int, Dict[str, "np.ndarray"]]]:
    """Inverse of :func:`encode_unit_results`."""
    import pyarrow as pa

    with pa.ipc.open_stream(pa.py_buffer(payload)) as r:
        table = r.read_all()
    units = [int(u) for u in table.column("units").to_pylist()]
    out: List[Tuple[int, Dict[str, np.ndarray]]] = \
        [(u, {}) for u in units]
    for field in table.schema:
        if field.name == "units":
            continue
        dtype = np.dtype(field.metadata[b"dtype"].decode())
        shape = tuple(json.loads(field.metadata[b"shape"].decode()))
        for row, raw in enumerate(table.column(field.name).to_pylist()):
            out[row][1][field.name] = np.frombuffer(
                raw, dtype=dtype).reshape(shape)
    return out


# ---------------------------------------------------------------------------
# the ring itself
# ---------------------------------------------------------------------------

class RingWriter:
    """Single-producer ring: the worker appends framed segments and
    publishes them through the seqlock'd commit cursor.  A full ring
    stops publishing (``full``; the ``ring_full`` counter records it) —
    the durable spool carries everything regardless, so capacity is a
    perf cliff, never a correctness one."""

    def __init__(self, path: str, capacity: int, shard: int,
                 incarnation: int):
        self.path = path
        self.capacity = max(int(capacity), HEADER_BYTES + _SEG.size)
        self.full = False
        self.bytes_written = 0
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "wb") as f:
            f.truncate(self.capacity)
        self._f = open(path, "r+b")
        self._m = mmap.mmap(self._f.fileno(), self.capacity)
        self._m[0:8] = _MAGIC
        struct.pack_into("<I", self._m, _HDR_CAP_OFF, self.capacity)
        struct.pack_into("<I", self._m, _HDR_SHARD_OFF, int(shard))
        struct.pack_into("<I", self._m, _HDR_INC_OFF, int(incarnation))
        struct.pack_into("<Q", self._m, _HDR_COMMIT_OFF, HEADER_BYTES)
        struct.pack_into("<I", self._m, _HDR_SEQLOCK_OFF, 0)
        self._end = HEADER_BYTES

    def publish(self, seq: int, results) -> bool:
        """Append one segment; True when it landed in the ring."""
        if self.full:
            return False
        payload = encode_unit_results(results)
        need = _SEG.size + _pad8(len(payload))
        if self._end + need > self.capacity:
            self.full = True
            obs.registry().counter("ring_full").inc()
            return False
        off = self._end
        _SEG.pack_into(self._m, off, _SEG_MAGIC, int(seq),
                       len(results), len(payload),
                       zlib.crc32(payload) & 0xFFFFFFFF)
        body = off + _SEG.size
        half = len(payload) // 2
        self._m[body:body + half] = payload[:half]
        # the torn-segment chaos cell: a 'kill' fault here leaves the
        # frame header claiming a length+crc the half-written payload
        # cannot satisfy — exactly the torn state readers must detect
        faults.fire("ring_write", path=self.path)
        self._m[body + half:body + len(payload)] = payload[half:]
        new_end = off + need
        lock, = struct.unpack_from("<I", self._m, _HDR_SEQLOCK_OFF)
        struct.pack_into("<I", self._m, _HDR_SEQLOCK_OFF, lock + 1)
        struct.pack_into("<Q", self._m, _HDR_COMMIT_OFF, new_end)
        struct.pack_into("<I", self._m, _HDR_SEQLOCK_OFF, lock + 2)
        self._end = new_end
        self.bytes_written += need
        obs.registry().counter("ring_bytes").inc(need)
        obs.registry().counter("ring_segments").inc()
        return True

    def close(self) -> None:
        try:
            self._m.close()
            self._f.close()
        except OSError:
            pass


class RingReader:
    """The supervisor's side: poll for newly committed segments, and
    probe past the cursor for the torn tail a killed writer leaves."""

    def __init__(self, path: str):
        self.path = path
        self._f = open(path, "rb")
        size = os.fstat(self._f.fileno()).st_size
        self._m = mmap.mmap(self._f.fileno(), size,
                            access=mmap.ACCESS_READ)
        if self._m[0:8] != _MAGIC:
            self.close()
            raise ValueError(f"{path}: not a ring file")
        self.capacity, = struct.unpack_from("<I", self._m, _HDR_CAP_OFF)
        self.shard, = struct.unpack_from("<I", self._m, _HDR_SHARD_OFF)
        self.incarnation, = struct.unpack_from("<I", self._m,
                                               _HDR_INC_OFF)
        self._pos = HEADER_BYTES
        self.torn = 0

    def _committed(self) -> int:
        """Seqlock read: retry while the writer is mid-publish."""
        for _ in range(64):
            s1, = struct.unpack_from("<I", self._m, _HDR_SEQLOCK_OFF)
            if s1 & 1:
                continue
            committed, = struct.unpack_from("<Q", self._m,
                                            _HDR_COMMIT_OFF)
            s2, = struct.unpack_from("<I", self._m, _HDR_SEQLOCK_OFF)
            if s1 == s2:
                return committed
        return self._pos                    # writer died mid-publish

    def _frame_at(self, off: int, limit: int):
        """(seq, n_units, payload, end) for a VALID frame at ``off``,
        else None (torn / not a frame)."""
        if off + _SEG.size > limit:
            return None
        magic, seq, n_units, plen, crc = _SEG.unpack_from(self._m, off)
        end = off + _SEG.size + _pad8(plen)
        if magic != _SEG_MAGIC or end > limit:
            return None
        payload = self._m[off + _SEG.size:off + _SEG.size + plen]
        if (zlib.crc32(payload) & 0xFFFFFFFF) != crc:
            return None
        return seq, n_units, payload, end

    def poll(self) -> List[Tuple[int, int, bytes]]:
        """New ``(seq, n_units, payload)`` entries committed since the
        last poll.  A corrupt frame inside the committed region (never
        produced by a correct writer) poisons the rest of this ring:
        counted in ``torn`` and never re-read."""
        out: List[Tuple[int, int, bytes]] = []
        committed = min(self._committed(), self.capacity)
        while self._pos < committed:
            frame = self._frame_at(self._pos, committed)
            if frame is None:
                self.torn += 1
                self._pos = committed
                break
            seq, n_units, payload, end = frame
            out.append((seq, n_units, payload))
            self._pos = end
        return out

    def scan_tail(self) -> int:
        """1 when an unpublished/torn segment sits past the commit
        cursor (the SIGKILL-mid-write residue), else 0.  Call after the
        writer is known dead — a live writer's in-flight segment looks
        identical, by design."""
        committed = min(self._committed(), self.capacity)
        if committed + _SEG.size > self.capacity:
            return 0
        magic, _, _, plen, _ = _SEG.unpack_from(self._m, committed)
        return 1 if magic == _SEG_MAGIC else 0

    def close(self) -> None:
        try:
            self._m.close()
            self._f.close()
        except OSError:
            pass


# ---------------------------------------------------------------------------
# unit claim table (exactly-once stealing)
# ---------------------------------------------------------------------------

def claim_unit(fleet_dir: str, unit: int, shard: int,
               incarnation: int) -> bool:
    """Claim ``unit`` for ``shard`` — atomic via ``O_EXCL`` create, the
    same one-winner primitive as the commit-file discipline.  False
    when another worker already holds the claim.  The EXISTENCE of the
    claim file is the decision; the owner doc inside is published by a
    tmp+replace second step, so a crash between the two leaves an
    empty claim that reads as unclaimed (``claim_owner`` -> None) —
    the victim then recomputes the unit, which risks only duplicate
    WORK; the merge's first-wins dedup keeps the count exact."""
    path = os.path.join(fleet_dir, CLAIM_DIR, f"unit{unit}.json")
    try:
        fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY, 0o644)
    except FileExistsError:
        return False
    except OSError:
        return False
    os.close(fd)
    tmp = f"{path}.tmp{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(dict(shard=int(shard),
                       incarnation=int(incarnation)), f)
    os.replace(tmp, path)
    return True


def claim_owner(fleet_dir: str, unit: int) -> Optional[dict]:
    """The claim doc for ``unit`` (None = unclaimed or unreadable —
    an in-flight create reads as unclaimed, which only risks duplicate
    WORK, never a duplicate count)."""
    path = os.path.join(fleet_dir, CLAIM_DIR, f"unit{unit}.json")
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    return doc if isinstance(doc, dict) else None


def release_shard_claims(fleet_dir: str, shard: int,
                         keep_units) -> int:
    """Drop every claim owned by ``shard`` whose unit is NOT in
    ``keep_units`` (the committed set) — called by the supervisor when
    a claimant dies, so its victims recompute the released units on
    their next drain pass.  Returns claims released."""
    import glob as _glob
    n = 0
    for path in _glob.glob(os.path.join(fleet_dir, CLAIM_DIR,
                                        "unit*.json")):
        try:
            with open(path) as f:
                doc = json.load(f)
            unit = int(os.path.basename(path)[4:-5])
        except (OSError, ValueError):
            continue
        if isinstance(doc, dict) and int(doc.get("shard", -1)) == \
                int(shard) and unit not in keep_units:
            try:
                os.unlink(path)
                n += 1
            except OSError:
                pass
    return n


# ---------------------------------------------------------------------------
# broadcast-blob cache (map once per worker process)
# ---------------------------------------------------------------------------

_BLOB_CACHE: Dict[Tuple[str, int, int], object] = {}


def _blob_key(path: str) -> Tuple[str, int, int]:
    st = os.stat(path)
    return (os.path.abspath(path), st.st_mtime_ns, st.st_size)


def load_broadcast_array(path: str) -> "np.ndarray":
    """A broadcast ``.npy`` blob mapped read-only, memoized per process
    by (path, mtime, size): N shard loads in one worker open (and map)
    the file once.  ``broadcast_blob_opens`` counts REAL opens — the
    open-once pin tests/test_shardstream.py holds."""
    key = _blob_key(path)
    got = _BLOB_CACHE.get(key)
    if got is None:
        obs.registry().counter("broadcast_blob_opens").inc()
        got = np.load(path, mmap_mode="r")
        _BLOB_CACHE[key] = got
    return got


def load_broadcast_npz(path: str) -> Dict[str, "np.ndarray"]:
    """A broadcast ``.npz`` blob's arrays, memoized like
    :func:`load_broadcast_array` (materialized once so the zip handle
    closes; the arrays themselves are shared thereafter)."""
    key = _blob_key(path)
    got = _BLOB_CACHE.get(key)
    if got is None:
        obs.registry().counter("broadcast_blob_opens").inc()
        with np.load(path) as z:
            got = {k: z[k] for k in z.files}
        _BLOB_CACHE[key] = got
    return got
