"""Adaptive shape-bucketed chunk-stream executor — the streaming hot path.

The per-chunk cycle (decode → pack → pad → dispatch) is the binding cost
of every streaming command (BENCH_r05: the fused kernels finish far
ahead of the feed).  This module is the one owner of that cycle's three
silent killers, replacing the ad-hoc dispatch loops in
parallel/pipeline.py:

1. **Canonical shape buckets.**  Row counts pad to one geometric ladder
   (packing.row_bucket_ladder) shared across every pass of a run, and
   read lengths to the 128-multiple ladder (packing.len_bucket), so each
   kernel compiles against at most ``len(ladder)`` shapes — a skewed
   tail chunk can no longer mint a fresh shape (= a fresh XLA compile,
   20-40 s through the tunnel's remote AOT compiler) mid-run.
2. **Prefetching device feed** (ingest.prefetched): chunk i+1's
   ``device_put`` runs on a feeder thread while chunk i's kernels
   execute — double-buffered, in-flight bounded at ``prefetch_depth``
   results, the same backpressure discipline as the pipelined ingest
   pool and the drain-every-``sync_every`` device accumulators.
3. **Pad-waste/recompile autotuner** (:func:`decide_plan`): at pass
   boundaries — never mid-pass — the next pass's plan (chunk rows,
   ladder density) is re-decided from the pad waste observed so far and
   the evidence ledger's measured link rate (adam_tpu/evidence).  The
   decision is a PURE function of its recorded inputs, so
   tools/check_executor.py can replay a run's sidecar and assert the
   decisions were deterministic.

Donated input buffers ride along: on TPU backends the executor asks the
jit'd kernels (ops/flagstat, bqsr/recalibrate) to donate their per-chunk
inputs, so the device reuses the arriving chunk's HBM for outputs and
scratch instead of re-allocating every chunk.  Donation stays off on the
CPU backend, where it buys nothing and XLA warns per call.

Every decision emits through :mod:`adam_tpu.obs`:

* ``executor_bucket_selected`` event + ``executor_passes`` counter — one
  per pass boundary, carrying the plan AND its inputs (replayable);
* ``executor_recompile`` event + ``executor_shapes{pass=}`` counter —
  first sighting of a (rows, len) shape in a pass (each sighting
  predicts one XLA compile per kernel the pass runs);
* ``executor_prefetch_stall_s`` event + histogram and the
  ``executor_prefetch_inflight_peak{pass=}`` gauge — where the feed
  waited on the host, and proof the in-flight bound held.

No code path here takes a device barrier; with no ``-metrics`` sink the
event half stays dead weight (the obs no-op contract).
"""

from __future__ import annotations

import hashlib
import json
import os
import time
from typing import Callable, Iterable, Iterator, Optional

from .. import obs
from ..packing import (LADDER_BASE_DEFAULT, len_bucket,  # noqa: F401
                       pad_rows_for, row_bucket_ladder)
from ..resilience.retry import dispatch_with_retry, resolve_retry_policy

#: env overrides (flags on the CLI commands mirror these)
LADDER_BASE_ENV = "ADAM_TPU_EXECUTOR_LADDER_BASE"
PREFETCH_ENV = "ADAM_TPU_EXECUTOR_PREFETCH"
AUTOTUNE_ENV = "ADAM_TPU_EXECUTOR_AUTOTUNE"
DONATE_ENV = "ADAM_TPU_EXECUTOR_DONATE"
#: layout escape hatch shared by every ragged-capable pass (flagstat,
#: BQSR count, realign sweep): 1/ragged forces the ragged layout,
#: 0/off/padded forces padded; unset lets raced bench evidence decide
RAGGED_ENV = "ADAM_TPU_RAGGED"
#: paged-layout pin + page geometry (parallel/pagedbuf.py,
#: docs/EXECUTOR.md §6): ADAM_TPU_PAGED=1 routes every paged-capable
#: pass through the resident page pool, 0 forces it off; unset leaves
#: the plan default (off — paging is an explicit opt-in)
PAGED_ENV = "ADAM_TPU_PAGED"
PAGE_ROWS_ENV = "ADAM_TPU_PAGE_ROWS"
POOL_PAGES_ENV = "ADAM_TPU_POOL_PAGES"
#: fused mega-pass pin (ops/megapass.py, docs/ARCHITECTURE.md §6p):
#: ADAM_TPU_MEGA=1 routes every mega-capable pass through the fused
#: multi-output kernel, 0 forces the unfused dispatches; unset leaves
#: the decision to raced ``mega_race`` ledger evidence (off without it)
MEGA_ENV = "ADAM_TPU_MEGA"

#: the autotuner densifies the ladder once observed mean pad waste
#: crosses this fraction (sqrt(2) rungs halve the worst-case waste of
#: the default power-of-two ladder)
PAD_WASTE_TARGET = 0.35
DENSE_LADDER_BASE = 2.0 ** 0.5

#: floor for a caller/env-supplied ladder base: a base barely above 1.0
#: (a plausible flag typo like 1.001) would build a ladder with millions
#: of rungs and serialize it into every executor_bucket_selected event
MIN_LADDER_BASE = 1.1

#: a re-streamed pass's chunk transfer should fit this many seconds of
#: the measured link (the evidence scheduler's transfer-budget
#: discipline, applied to the product path)
TRANSFER_BUDGET_S = 45.0
MIN_CHUNK_ROWS = 1 << 14

#: default look-ahead of the device feed (double-buffered)
DEFAULT_PREFETCH_DEPTH = 2

#: evidence-armed paging (ROADMAP item-2 headroom): with no explicit
#: layout pin, a paged-capable pass arms the resident pool only when
#: the ledger's platform-matched ``paged_race`` record shows the
#: steady-state h2d-byte reduction at or past this factor (the gate-7
#: acceptance floor) AND the paged serve wall within this slack of the
#: unpaged wall — a transfer win that costs wall is not a win here
PAGED_EVIDENCE_MIN_REDUCTION = 2.0
PAGED_EVIDENCE_WALL_SLACK = 1.05

#: evidence-armed mega-pass (ROADMAP item-6): with no explicit pin, a
#: mega-capable pass arms the fused kernel only when the ledger's
#: platform-matched ``mega_race`` record shows the per-chunk dispatch
#: count reduced at or past this factor (the gate-10 acceptance floor)
#: on the combined leg, with identity clean and no wall regression past
#: the slack — the paged-evidence discipline applied to dispatch count
MEGA_EVIDENCE_MIN_REDUCTION = 2.0
MEGA_EVIDENCE_WALL_SLACK = 1.05


def decide_plan(*, pass_name: str, chunk_rows: int, mesh_size: int,
                on_tpu: bool, waste_mean: Optional[float] = None,
                link_bytes_per_sec: Optional[float] = None,
                bytes_per_row: Optional[float] = None,
                ladder_base: Optional[float] = None,
                prefetch_depth: Optional[int] = None,
                donate: Optional[bool] = None,
                layout: Optional[str] = None,
                ragged_capable: bool = False,
                ragged_rates: Optional[dict] = None,
                paged_capable: bool = False,
                paged_rates: Optional[dict] = None,
                page_rows: Optional[int] = None,
                pool_pages: Optional[int] = None,
                mega: Optional[bool] = None,
                mega_capable: bool = False,
                mega_rates: Optional[dict] = None,
                autotune: bool = True) -> dict:
    """The autotuner: one pass's frozen execution plan.

    PURE — the returned plan is a deterministic function of the keyword
    inputs, which the ``executor_bucket_selected`` event records in full
    (``inputs`` + ``input_digest``), so a recorded sidecar can be
    replayed offline and the decision re-derived bit-for-bit
    (tools/check_executor.py).  Explicit ``ladder_base`` /
    ``prefetch_depth`` / ``donate`` / ``layout`` pin those knobs;
    ``autotune=False`` freezes everything at the defaults.

    ``layout`` is the ragged-vs-padded dimension (docs/EXECUTOR.md):
    ``ragged_capable`` says whether THIS pass has a ragged twin in this
    run configuration (single-shard mesh, a kernel with a ragged form);
    ``ragged_rates`` is the raced bench evidence — the PR 2 ledger's
    ``ragged_race`` record for this pass's kernel, ``{"padded": r/s,
    "ragged": r/s}`` measured on the CURRENT platform — and the plan
    picks ragged only when an explicit pin or measured evidence backs
    it.  Padded is the no-evidence default: the ragged layout is a
    measured optimization, never a guess.

    ``layout="paged"`` (the ``-paged``/``ADAM_TPU_PAGED`` pin) routes a
    ``paged_capable`` pass through the resident page pool
    (parallel/pagedbuf.py, docs/ARCHITECTURE.md §6l): chunk capacity
    rounds up to a whole number of ``page_rows``-element pages and the
    plan carries the page geometry (``page_rows``/``pool_pages``, the
    pool sized for the prefetch depth plus one dispatch in flight).
    ``paged_rates`` is the raced bench evidence for the PAGED twin —
    the ledger's ``paged_race`` record for the CURRENT platform
    (:func:`ledger_paged_rates`): with no explicit pin, a
    ``paged_capable`` pass arms the resident pool when the measured
    steady-state h2d reduction clears
    :data:`PAGED_EVIDENCE_MIN_REDUCTION` and the paged serve wall did
    not regress past :data:`PAGED_EVIDENCE_WALL_SLACK` — paging stops
    being explicit-opt-in-only, but stays a measured optimization,
    never a guess (the ragged-evidence discipline).  The paged keys
    join the recorded inputs ONLY when the dimension is engaged, so
    pre-paged sidecars replay digest-identical (the tenant/shard
    scoping precedent in resilience.faults).

    ``fused_device`` is the mega-pass dimension (ops/megapass.py,
    docs/ARCHITECTURE.md §6p): ``mega_capable`` says this pass has a
    fused multi-output route wired in; ``mega`` is the explicit
    ``-mega``/``ADAM_TPU_MEGA`` pin (True/False; None leaves the
    decision to evidence); ``mega_rates`` is the ledger's
    platform-matched ``mega_race`` record
    (:func:`ledger_mega_rates`) — the fused route arms when the
    measured per-chunk dispatch reduction clears
    :data:`MEGA_EVIDENCE_MIN_REDUCTION` with identity clean and the
    fused wall within :data:`MEGA_EVIDENCE_WALL_SLACK` of the unfused
    wall.  Off is the no-evidence default, and the mega keys join the
    recorded inputs ONLY when the dimension is engaged, so pre-mega
    sidecars replay digest-identical.
    """
    inputs = dict(pass_name=pass_name, chunk_rows=int(chunk_rows),
                  mesh_size=int(mesh_size), on_tpu=bool(on_tpu),
                  waste_mean=None if waste_mean is None
                  else round(float(waste_mean), 6),
                  link_bytes_per_sec=None if not link_bytes_per_sec
                  else round(float(link_bytes_per_sec), 1),
                  bytes_per_row=None if bytes_per_row is None
                  else float(bytes_per_row),
                  ladder_base=ladder_base, prefetch_depth=prefetch_depth,
                  donate=donate, layout=layout,
                  ragged_capable=bool(ragged_capable),
                  ragged_rates=None if not ragged_rates else {
                      k: round(float(v), 1)
                      for k, v in sorted(ragged_rates.items())},
                  autotune=bool(autotune))
    paged_engaged = bool(paged_capable) or layout == "paged" or \
        page_rows is not None or pool_pages is not None
    if paged_engaged:
        # only-when-engaged: pre-paged sidecars must digest identically
        inputs["paged_capable"] = bool(paged_capable)
        inputs["page_rows"] = None if page_rows is None \
            else int(page_rows)
        inputs["pool_pages"] = None if pool_pages is None \
            else int(pool_pages)
        if paged_rates:
            # only-when-present: pre-evidence sidecars keep digesting
            inputs["paged_rates"] = {
                k: round(float(v), 4)
                for k, v in sorted(paged_rates.items())}
    mega_engaged = bool(mega_capable) or mega is not None or \
        bool(mega_rates)
    if mega_engaged:
        # only-when-engaged: pre-mega sidecars must digest identically
        inputs["mega_capable"] = bool(mega_capable)
        inputs["mega"] = None if mega is None else bool(mega)
        if mega_rates:
            inputs["mega_rates"] = {
                k: round(float(v), 4)
                for k, v in sorted(mega_rates.items())}
    # decide from the CANONICALIZED inputs (what the event records) —
    # deciding from the raw floats would let a rounding boundary make
    # the offline replay disagree with the recorded plan
    waste_mean = inputs["waste_mean"]
    link_bytes_per_sec = inputs["link_bytes_per_sec"]
    reasons = []
    lay = "padded"
    if inputs["layout"] == "paged":
        if paged_engaged and inputs["paged_capable"]:
            lay = "paged"
            reasons.append("layout-pinned-paged")
        else:
            reasons.append("paged-pin-unsupported:padded")
    elif inputs["layout"] == "ragged":
        if inputs["ragged_capable"]:
            lay = "ragged"
            reasons.append("layout-pinned-ragged")
        else:
            reasons.append("ragged-pin-unsupported:padded")
    elif inputs["layout"] == "padded":
        reasons.append("layout-pinned-padded")
    elif autotune and paged_engaged and inputs.get("paged_capable") \
            and inputs.get("paged_rates") and \
            inputs["paged_rates"].get("h2d_reduction", 0) >= \
            PAGED_EVIDENCE_MIN_REDUCTION and \
            inputs["paged_rates"].get("paged_wall_s", float("inf")) <= \
            PAGED_EVIDENCE_WALL_SLACK * \
            inputs["paged_rates"].get("unpaged_wall_s", 0):
        # evidence-armed residency: the measured h2d win outranks the
        # ragged-evidence branch below (paging IS the ragged addressing
        # scheme plus residency)
        pr = inputs["paged_rates"]
        lay = "paged"
        reasons.append(
            f"paged-evidence h2d {pr['h2d_reduction']:.1f}x")
    elif autotune and inputs["ragged_capable"] and inputs["ragged_rates"]:
        rr = inputs["ragged_rates"]
        if rr.get("ragged", 0) > rr.get("padded", 0) > 0:
            lay = "ragged"
            reasons.append(
                f"ragged-evidence {rr['ragged']:.0f}>{rr['padded']:.0f}")
    # the fused mega-pass dimension rides orthogonally to layout (every
    # layout has a fused twin): explicit pin > ledger evidence > off
    fused = False
    if mega_engaged:
        if inputs["mega"] is True:
            if inputs["mega_capable"]:
                fused = True
                reasons.append("mega-pinned")
            else:
                reasons.append("mega-pin-unsupported:unfused")
        elif inputs["mega"] is False:
            reasons.append("mega-pinned-off")
        elif autotune and inputs["mega_capable"] and \
                inputs.get("mega_rates") and \
                inputs["mega_rates"].get("dispatch_reduction", 0) >= \
                MEGA_EVIDENCE_MIN_REDUCTION and \
                inputs["mega_rates"].get("fused_wall_s",
                                         float("inf")) <= \
                MEGA_EVIDENCE_WALL_SLACK * \
                inputs["mega_rates"].get("unfused_wall_s", 0):
            mr = inputs["mega_rates"]
            fused = True
            reasons.append(
                f"mega-evidence dispatch {mr['dispatch_reduction']:.1f}x")
    base = max(ladder_base, MIN_LADDER_BASE) if ladder_base \
        else LADDER_BASE_DEFAULT
    if autotune and not ladder_base and waste_mean is not None \
            and waste_mean > PAD_WASTE_TARGET:
        base = DENSE_LADDER_BASE
        reasons.append(f"pad_waste {waste_mean:.2f}>{PAD_WASTE_TARGET}"
                       ":dense-ladder")
    rows = int(chunk_rows)
    if autotune and on_tpu and link_bytes_per_sec and bytes_per_row:
        # cap the re-streamed chunk so its wire fits a bounded slice of
        # the measured link — the round-5 lesson (a 206 MB wire on a
        # ~1 MB/s flap stalls the whole window) applied to the product
        cap = int(link_bytes_per_sec * TRANSFER_BUDGET_S /
                  max(bytes_per_row, 1e-9))
        if cap < rows:
            rows = max(MIN_CHUNK_ROWS, cap)
            reasons.append("link-rate-chunk-cap")
    mult = max(int(mesh_size), 1)
    rows = max(-(-rows // mult) * mult, mult)
    depth = prefetch_depth if prefetch_depth is not None else \
        (DEFAULT_PREFETCH_DEPTH if on_tpu else 0)
    do_donate = bool(on_tpu) if donate is None else bool(donate)
    plan_page_rows = plan_pool_pages = None
    if lay == "paged":
        from .pagedbuf import DEFAULT_PAGE_ROWS
        plan_page_rows = inputs.get("page_rows") or DEFAULT_PAGE_ROWS
        # capacity is a whole number of pages; the pool holds the
        # prefetch look-ahead plus the dispatch in flight
        rows = max(-(-rows // plan_page_rows), 1) * plan_page_rows
        per_dispatch = rows // plan_page_rows
        # steady-state live set under a prefetched feed: depth queued
        # chunks + the consumer's not-yet-freed chunk + the feeder's
        # next alloc — depth + 2 dispatches' worth of pages
        plan_pool_pages = inputs.get("pool_pages") or \
            (int(depth) + 2) * per_dispatch
    ladder = row_bucket_ladder(rows, mult, base)
    digest = hashlib.sha256(
        json.dumps(inputs, sort_keys=True).encode()).hexdigest()[:16]
    plan = dict(pass_name=pass_name, chunk_rows=rows,
                ladder_base=round(float(base), 6), ladder=list(ladder),
                prefetch_depth=int(depth), donate=do_donate,
                layout=lay,
                reason=";".join(reasons) or "default",
                inputs=inputs, input_digest=digest)
    if lay == "paged":
        plan["page_rows"] = int(plan_page_rows)
        plan["pool_pages"] = int(plan_pool_pages)
    if mega_engaged:
        # only-when-engaged, like the paged keys: pre-mega sidecars
        # replay without the field and check_executor compares it only
        # when recorded
        plan["fused_device"] = bool(fused)
    return plan


#: which ragged-race evidence keys back which streaming pass: the bench
#: ``ragged_race`` stage (bench.py) races each kernel's ragged twin
#: against its padded form and the ledger keeps the best record
_RAGGED_KERNEL_OF_PASS = {"flagstat": "flagstat", "p2": "bqsr",
                          "s2": "bqsr"}


def resolve_ragged_env(env_val: Optional[str]) -> Optional[str]:
    """ADAM_TPU_RAGGED / flag string -> explicit layout pin or None."""
    if env_val is None or env_val == "":
        return None
    if env_val in ("0", "off", "padded", "no"):
        return "padded"
    return "ragged"


def resolve_mega_env(env_val: Optional[str]) -> Optional[bool]:
    """ADAM_TPU_MEGA / flag string -> explicit fused pin or None."""
    if env_val is None or env_val == "":
        return None
    return env_val not in ("0", "off", "no")


def ledger_ragged_rates(kernel: str,
                        platform: Optional[str] = None) -> Optional[dict]:
    """The evidence ledger's raced ragged-vs-padded rates for ``kernel``
    (``flagstat`` | ``bqsr`` | ``realign``) — ``{"padded": r/s,
    "ragged": r/s}`` from the bench ``ragged_race`` stage, or None when
    the ledger has no record FOR THE CURRENT PLATFORM (cross-platform
    evidence must never steer a layout: a CPU win says nothing about the
    MXU).  Best-effort, like :func:`_ledger_link_rate`."""
    try:
        import jax

        from ..evidence.ledger import Ledger, default_path
        from ..platform import is_tpu_backend

        # normalize like Ledger.record_stages does: the axon TPU plugin
        # reports backend "axon" but records land as platform "tpu" —
        # a raw default_backend() compare would orphan the evidence on
        # the exact hardware the ragged layout targets
        plat = platform or \
            ("tpu" if is_tpu_backend() else jax.default_backend())
        rec = Ledger(default_path()).record("ragged_race")
        if not rec or rec.get("platform") != plat:
            return None
        payload = rec.get("payload") or rec
        p = payload.get(f"ragged_{kernel}_padded_per_sec")
        r = payload.get(f"ragged_{kernel}_ragged_per_sec")
        if p and r:
            return {"padded": float(p), "ragged": float(r)}
    except Exception:  # noqa: BLE001 — telemetry-grade, never fatal
        pass
    return None


def ledger_paged_rates(platform: Optional[str] = None) -> Optional[dict]:
    """The evidence ledger's raced paged-vs-unpaged record — the bench
    ``paged_race`` stage's steady-state serve-leg numbers
    (``{"h2d_reduction", "unpaged_wall_s", "paged_wall_s"}``), or None
    when the ledger has no record FOR THE CURRENT PLATFORM or the
    record's identity bit is not clean (cross-platform evidence must
    never steer a layout; a twin mismatch disqualifies the whole
    record).  Best-effort, like :func:`ledger_ragged_rates`."""
    try:
        import jax

        from ..evidence.ledger import Ledger, default_path
        from ..platform import is_tpu_backend

        plat = platform or \
            ("tpu" if is_tpu_backend() else jax.default_backend())
        rec = Ledger(default_path()).record("paged_race")
        if not rec or rec.get("platform") != plat:
            return None
        payload = rec.get("payload") or rec
        red = payload.get("paged_h2d_reduction")
        u = payload.get("unpaged_serve_wall_s")
        p = payload.get("paged_serve_wall_s")
        if red and u and p and payload.get("paged_identical") is True:
            return {"h2d_reduction": float(red),
                    "unpaged_wall_s": float(u),
                    "paged_wall_s": float(p)}
    except Exception:  # noqa: BLE001 — telemetry-grade, never fatal
        pass
    return None


def ledger_mega_rates(platform: Optional[str] = None) -> Optional[dict]:
    """The evidence ledger's raced fused-vs-unfused record — the bench
    ``mega_race`` stage's combined-leg numbers
    (``{"dispatch_reduction", "unfused_wall_s", "fused_wall_s"}``), or
    None when the ledger has no record FOR THE CURRENT PLATFORM or the
    record's identity bit is not clean (cross-platform evidence must
    never arm the fused route; a twin mismatch disqualifies the whole
    record).  Best-effort, like :func:`ledger_paged_rates`."""
    try:
        import jax

        from ..evidence.ledger import Ledger, default_path
        from ..platform import is_tpu_backend

        plat = platform or \
            ("tpu" if is_tpu_backend() else jax.default_backend())
        rec = Ledger(default_path()).record("mega_race")
        if not rec or rec.get("platform") != plat:
            return None
        payload = rec.get("payload") or rec
        red = payload.get("mega_dispatch_reduction")
        u = payload.get("mega_unfused_wall_s")
        f = payload.get("mega_fused_wall_s")
        if red and u and f and payload.get("mega_identical") is True:
            return {"dispatch_reduction": float(red),
                    "unfused_wall_s": float(u),
                    "fused_wall_s": float(f)}
    except Exception:  # noqa: BLE001 — telemetry-grade, never fatal
        pass
    return None


def _ledger_link_rate() -> Optional[float]:
    """The evidence ledger's latest measured host→device link rate
    (bytes/s) — the probe writes it once per capture window; the
    autotuner reads it instead of re-measuring on the product path.
    Best-effort: no ledger, no rate."""
    try:
        from ..evidence.ledger import Ledger, default_path

        probe = Ledger(default_path()).last_probe()
        if probe:
            v = probe.get("link_bytes_per_sec")
            return float(v) if v else None
    except Exception:  # noqa: BLE001 — telemetry-grade, never fatal
        pass
    return None


class PassExecutor:
    """One pass's frozen plan plus its shape/waste/stall accounting.

    Handed out by :meth:`StreamExecutor.begin_pass`; the pass uses
    :meth:`pad_rows` for every chunk, :meth:`feed` around its device
    transfers, and the plan's ``donate`` / ``sync_every`` knobs on its
    kernels.  ``finish()`` (or the next ``begin_pass``) emits the pass's
    prefetch-stall rollup.
    """

    def __init__(self, parent: "StreamExecutor", plan: dict,
                 sync_every: int):
        import threading

        self._parent = parent
        self.plan = plan
        self.pass_name = plan["pass_name"]
        self.ladder = tuple(plan["ladder"])
        self.chunk_rows = plan["chunk_rows"]
        self.prefetch_depth = plan["prefetch_depth"]
        self.donate = plan["donate"]
        self.layout = plan.get("layout", "padded")
        self.page_rows = plan.get("page_rows")
        self.pool_pages = plan.get("pool_pages")
        self.fused_device = bool(plan.get("fused_device", False))
        self.sync_every = max(int(sync_every), 1)
        self._shapes: set = set()
        self._lock = threading.Lock()   # pad_rows runs on pipelined
        #                                 ingest pool workers too
        self._stall_s = 0.0
        self._inflight_peak = 0
        self._chunks = 0
        self._h2d_bytes = 0
        self._h2d_puts = 0
        self._dispatches = 0
        self._finished = False

    # -- shape bucketing ---------------------------------------------------

    def pad_rows(self, rows: int, len_b: Optional[int] = None,
                 max_len: Optional[int] = None) -> int:
        """Canonical row bucket for a chunk (ladder rung); records pad
        waste and first-sighting-of-a-shape telemetry.  ``max_len`` (the
        chunk's true longest read) adds the length-axis waste sample
        against the ``len_b`` bucket — the lane half of the pad tax."""
        bucket = pad_rows_for(rows, self.ladder)
        obs.pad_waste(self.pass_name, rows, bucket,
                      max_len=max_len, padded_len=len_b)
        if bucket > 0:
            self._parent._note_waste(self.pass_name,
                                     (bucket - rows) / bucket)
        self.note_shape(bucket, len_b)
        return bucket

    def note_ragged(self, rows: int, capacity: int) -> None:
        """Ragged-layout accounting for one fixed-capacity dispatch:
        ``rows`` live rows below the prefix-sum bound, ``capacity`` the
        buffer's compiled row count.  Waste collapses to the final
        partial buffer instead of every chunk's rung slack — recorded
        through the same ``pad_waste_frac`` series so padded and ragged
        runs compare on one metric."""
        obs.pad_waste(self.pass_name, rows, capacity)
        if capacity > 0:
            self._parent._note_waste(self.pass_name,
                                     (capacity - rows) / capacity)
        self.note_shape(capacity, None)

    def note_shape(self, rows_bucket: int,
                   len_b: Optional[int] = None) -> None:
        """First sighting of a (rows, len) shape in this pass — the
        event each kernel's XLA compile at that shape hangs off."""
        key = (rows_bucket, len_b)
        with self._lock:
            if key in self._shapes:
                return
            self._shapes.add(key)
            n = len(self._shapes)
        obs.registry().counter("executor_shapes",
                               **{"pass": self.pass_name}).inc()
        obs.emit("executor_recompile", **{"pass": self.pass_name},
                 rows=int(rows_bucket),
                 len=None if len_b is None else int(len_b),
                 n_shapes=n)

    @property
    def n_shapes(self) -> int:
        return len(self._shapes)

    # -- resilient dispatch ------------------------------------------------

    def dispatch(self, label: str, fn: Callable, *,
                 split: Optional[Callable] = None,
                 fallback: Optional[Callable] = None):
        """Run one chunk's device dispatch under the scoped retry/
        degradation ladder (resilience.retry): transient device errors
        re-dispatch with backoff, ``RESOURCE_EXHAUSTED`` splits along
        the ladder rungs via ``split``, a persistent failure degrades to
        the caller's per-chunk CPU ``fallback``.  ``fn(attempt)`` — the
        attempt number lets the caller re-transfer from host state and
        confine buffer donation to attempt 1.  The ``device_dispatch``
        fault-injection site fires inside each attempt.

        Every call lands on the ``dispatch_count{pass=}`` counter — the
        per-chunk dispatch accounting the fused mega-pass plan is gated
        on (one ``dispatch_count`` rollup event per pass at finish;
        docs/OBSERVABILITY.md) — so "three dispatches became one" is a
        measured number, not a story."""
        with self._lock:
            self._dispatches += 1
        obs.registry().counter("dispatch_count",
                               **{"pass": self.pass_name}).inc()
        # trace.span is near-free when tracing is off (one global read
        # in __enter__) — and keeps ONE dispatch call site either way
        with obs.trace.span(f"{self.pass_name}:{label}", cat="dispatch"):
            return dispatch_with_retry(
                fn, site="device_dispatch",
                label=f"{self.pass_name}:{label}",
                policy=self._parent.retry_policy, split=split,
                fallback=fallback)

    def dispatch_put(self, label: str, fn: Callable,
                     nbytes: Optional[int] = None):
        """A host→device transfer under the same retry ladder (site
        ``device_put``; no split/fallback — a put either lands or the
        run fails cleanly after the budget).  ``nbytes`` — the host
        bytes this put ships — feeds the ``h2d_bytes{pass=}`` counter,
        so "transfer disappeared under paging" is a gated number
        instead of a trace screenshot (docs/OBSERVABILITY.md); the
        rollup lands as one ``h2d_bytes`` event at pass finish."""
        if nbytes:
            with self._lock:
                self._h2d_bytes += int(nbytes)
                self._h2d_puts += 1
            obs.registry().counter(
                "h2d_bytes", **{"pass": self.pass_name}).inc(int(nbytes))
        return dispatch_with_retry(
            fn, site="device_put", label=f"{self.pass_name}:{label}",
            policy=self._parent.retry_policy)

    # -- device feed -------------------------------------------------------

    def feed(self, items: Iterable, put: Callable) -> Iterator:
        """``put(item)`` (the host→device transfer) for each item in
        input order, prefetched ``prefetch_depth`` ahead (see
        ingest.prefetched); depth 0 — the CPU default — is the plain
        synchronous loop.  Stall/in-flight telemetry lands on this
        executor either way."""
        from .ingest import prefetched

        def on_chunk(stall_s: float, inflight: int) -> None:
            self._stall_s += stall_s
            self._chunks += 1
            self._inflight_peak = max(self._inflight_peak, inflight)
            tr = obs.trace.active()
            if tr is not None:
                # the timeline's proof the feed ran ahead: a counter
                # series of results queued at each consumer pickup
                tr.counter(f"prefetch_inflight:{self.pass_name}",
                           inflight)
            r = obs.registry()
            r.histogram("executor_prefetch_stall_s",
                        **{"pass": self.pass_name}).observe(stall_s)
            if inflight > self._parent._gauged.get(self.pass_name, -1):
                self._parent._gauged[self.pass_name] = inflight
                r.gauge("executor_prefetch_inflight_peak",
                        **{"pass": self.pass_name}).set(inflight)

        return prefetched(items, put, depth=self.prefetch_depth,
                          on_chunk=on_chunk)

    def finish(self) -> None:
        """Emit the pass's prefetch rollup (idempotent; also run by the
        next ``begin_pass`` so pass boundaries stay the one place
        executor events happen)."""
        if self._finished:
            return
        self._finished = True
        if self._chunks:
            obs.emit("executor_prefetch_stall_s",
                     **{"pass": self.pass_name},
                     seconds=round(self._stall_s, 6),
                     chunks=self._chunks,
                     inflight_peak=self._inflight_peak,
                     depth=self.prefetch_depth)
        if self._h2d_puts:
            obs.emit("h2d_bytes", **{"pass": self.pass_name},
                     bytes=int(self._h2d_bytes), puts=self._h2d_puts,
                     layout=self.layout)
        if self._dispatches:
            obs.emit("dispatch_count", **{"pass": self.pass_name},
                     dispatches=int(self._dispatches),
                     chunks=self._chunks, layout=self.layout,
                     fused_device=self.fused_device)


class StreamExecutor:
    """One per streaming run; hands each pass a frozen plan at its
    boundary and carries the cross-pass autotuner state (observed pad
    waste, the ledger link rate, resolved env overrides)."""

    def __init__(self, mesh, chunk_rows: int, *,
                 on_tpu: Optional[bool] = None,
                 autotune: Optional[bool] = None,
                 ladder_base: Optional[float] = None,
                 prefetch_depth: Optional[int] = None,
                 donate: Optional[bool] = None,
                 ragged: Optional[bool] = None,
                 paged: Optional[bool] = None,
                 page_rows: Optional[int] = None,
                 pool_pages: Optional[int] = None,
                 mega: Optional[bool] = None,
                 link_bytes_per_sec: Optional[float] = None,
                 retry_budget: Optional[int] = None):
        self.mesh_size = getattr(mesh, "size", None) or int(mesh or 1)
        self.chunk_rows = int(chunk_rows)
        if on_tpu is None:
            from ..platform import is_tpu_backend
            on_tpu = is_tpu_backend()
        self.on_tpu = bool(on_tpu)
        env = os.environ
        if autotune is None:
            autotune = env.get(AUTOTUNE_ENV, "1") not in ("0", "off")
        self.autotune = bool(autotune)
        if ladder_base is None and env.get(LADDER_BASE_ENV):
            try:
                ladder_base = float(env[LADDER_BASE_ENV])
            except ValueError:
                ladder_base = None
        self.ladder_base = ladder_base
        if prefetch_depth is None and env.get(PREFETCH_ENV):
            try:
                prefetch_depth = int(env[PREFETCH_ENV])
            except ValueError:
                prefetch_depth = None
        self.prefetch_depth = prefetch_depth
        if donate is None and env.get(DONATE_ENV) in ("0", "off"):
            donate = False
        self.donate = donate
        # layout pin: the -ragged/-no_ragged flags win; ADAM_TPU_RAGGED
        # fills an unset flag; None leaves the decision to evidence
        if ragged is None:
            self.layout_pin = resolve_ragged_env(env.get(RAGGED_ENV))
        else:
            self.layout_pin = "ragged" if ragged else "padded"
        # paged pin outranks the ragged pin (paging is the ragged
        # addressing scheme plus residency — an explicit -paged means
        # "use the pool", not "also stay ragged")
        from .pagedbuf import resolve_paged_env
        if paged is None:
            paged = resolve_paged_env(env.get(PAGED_ENV))
        if paged:
            self.layout_pin = "paged"
        if page_rows is None and env.get(PAGE_ROWS_ENV):
            try:
                page_rows = int(env[PAGE_ROWS_ENV])
            except ValueError:
                page_rows = None
        self.page_rows = page_rows
        if pool_pages is None and env.get(POOL_PAGES_ENV):
            try:
                pool_pages = int(env[POOL_PAGES_ENV])
            except ValueError:
                pool_pages = None
        self.pool_pages = pool_pages
        # fused mega-pass pin: the -mega/-no_mega flags win;
        # ADAM_TPU_MEGA fills an unset flag; None leaves the decision
        # to raced mega_race evidence (off without it)
        if mega is None:
            self.mega_pin = resolve_mega_env(env.get(MEGA_ENV))
        else:
            self.mega_pin = bool(mega)
        if link_bytes_per_sec is None and self.autotune and self.on_tpu:
            link_bytes_per_sec = _ledger_link_rate()
        self.link_bytes_per_sec = link_bytes_per_sec
        # one resolved retry/degradation policy per run scope
        # (-retry_budget flag / ADAM_TPU_RETRY_* envs)
        self.retry_policy = resolve_retry_policy(budget=retry_budget)
        import threading

        self._waste: dict = {}      # pass -> [frac_sum, n]
        self._waste_lock = threading.Lock()
        self._gauged: dict = {}     # pass -> last inflight gauge value
        self._current: Optional[PassExecutor] = None

    # -- autotuner state ---------------------------------------------------

    def _note_waste(self, pass_name: str, frac: float) -> None:
        with self._waste_lock:
            s = self._waste.setdefault(pass_name, [0.0, 0])
            s[0] += frac
            s[1] += 1

    def observed_waste_mean(self) -> Optional[float]:
        """Mean pad-waste fraction over every chunk padded so far (all
        completed passes of THIS run) — the autotuner's densify signal."""
        tot = sum(s[0] for s in self._waste.values())
        n = sum(s[1] for s in self._waste.values())
        return (tot / n) if n else None

    # -- pass boundaries ---------------------------------------------------

    def begin_pass(self, pass_name: str, *,
                   bytes_per_row: Optional[float] = None,
                   ragged_capable: bool = False,
                   paged_capable: bool = False,
                   mega_capable: bool = False,
                   sync_every: int = 1) -> PassExecutor:
        """Freeze the plan for one pass (the ONLY place decisions are
        made — never mid-pass) and emit it through obs.

        ``ragged_capable=True`` opens the layout dimension: the pass has
        a ragged kernel twin wired in for this run (the caller also
        requires ``mesh_size == 1`` — ragged dispatches are unsharded,
        so a multi-shard mesh always stays padded).
        ``mega_capable=True`` opens the fused mega-pass dimension the
        same way (the fused entries are unsharded multi-output jits, so
        the same single-shard gate applies)."""
        if self._current is not None:
            self._current.finish()
        capable = bool(ragged_capable) and self.mesh_size == 1
        capable_paged = bool(paged_capable) and self.mesh_size == 1
        capable_mega = bool(mega_capable) and self.mesh_size == 1
        rates = None
        if capable and self.layout_pin is None and self.autotune:
            rates = ledger_ragged_rates(
                _RAGGED_KERNEL_OF_PASS.get(pass_name, pass_name))
        prates = None
        if capable_paged and self.layout_pin is None and self.autotune:
            # raced evidence can arm the resident pool (ROADMAP item-2
            # headroom); explicit pins above always win
            prates = ledger_paged_rates()
        mrates = None
        if capable_mega and self.mega_pin is None and self.autotune:
            # raced evidence can arm the fused route (ROADMAP item-6);
            # the explicit -mega/ADAM_TPU_MEGA pin always wins
            mrates = ledger_mega_rates()
        plan = decide_plan(
            pass_name=pass_name, chunk_rows=self.chunk_rows,
            mesh_size=self.mesh_size, on_tpu=self.on_tpu,
            waste_mean=self.observed_waste_mean(),
            link_bytes_per_sec=self.link_bytes_per_sec,
            bytes_per_row=bytes_per_row, ladder_base=self.ladder_base,
            prefetch_depth=self.prefetch_depth, donate=self.donate,
            layout=self.layout_pin, ragged_capable=capable,
            ragged_rates=rates, paged_capable=capable_paged,
            paged_rates=prates,
            page_rows=self.page_rows if capable_paged else None,
            pool_pages=self.pool_pages if capable_paged else None,
            mega=self.mega_pin, mega_capable=capable_mega,
            mega_rates=mrates,
            autotune=self.autotune)
        obs.registry().counter("executor_passes",
                               **{"pass": pass_name}).inc()
        obs.trace.instant(f"pass:{pass_name}",
                          chunk_rows=plan["chunk_rows"],
                          prefetch_depth=plan["prefetch_depth"])
        extra = {}
        if "page_rows" in plan:
            extra = dict(page_rows=plan["page_rows"],
                         pool_pages=plan["pool_pages"])
        if "fused_device" in plan:
            extra["fused_device"] = plan["fused_device"]
            # lightweight companion event for dashboards/check_metrics:
            # which passes armed the fused route and why (replayability
            # lives in executor_bucket_selected's recorded inputs)
            obs.emit("mega_plan_selected", **{"pass": pass_name},
                     fused_device=plan["fused_device"],
                     reason=plan["reason"])
        obs.emit("executor_bucket_selected", **{"pass": pass_name},
                 chunk_rows=plan["chunk_rows"],
                 ladder=plan["ladder"], ladder_base=plan["ladder_base"],
                 prefetch_depth=plan["prefetch_depth"],
                 donate=plan["donate"], layout=plan["layout"],
                 reason=plan["reason"],
                 inputs=plan["inputs"],
                 input_digest=plan["input_digest"], **extra)
        pex = PassExecutor(self, plan, sync_every)
        self._current = pex
        return pex

    def finish(self) -> None:
        if self._current is not None:
            self._current.finish()
            self._current = None
