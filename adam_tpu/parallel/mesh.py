"""Device mesh + batch sharding utilities.

The reference's parallelism substrate is the Spark RDD: partitions over
executors, shuffles between them (SURVEY.md §2.4).  Ours is a
``jax.sharding.Mesh``: a batch of packed reads is sharded along its leading
(read) axis across devices, kernels run under ``shard_map``, and the
reference's driver-side aggregates become ``psum`` over ICI.

One mesh axis ("shard") suffices for the read-processing pipelines — they are
data-parallel with all-reduce aggregation; the genome-coordinate axis is
handled by the partitioner (parallel/partitioner.py), which assigns genome
bins to shards host-side, replacing Spark's shuffle.
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

READS_AXIS = "shard"


def make_mesh(n_devices: Optional[int] = None, devices=None) -> Mesh:
    """1-D mesh over (up to) all local devices."""
    if devices is None:
        devices = jax.devices()
        if n_devices is not None:
            devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (READS_AXIS,))


def reads_sharding(mesh: Mesh) -> NamedSharding:
    """Shard the leading (read) axis; replicate everything else."""
    return NamedSharding(mesh, P(READS_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_batch(batch, mesh: Mesh):
    """Place a ReadBatch on the mesh, read axis sharded.

    The batch row count must divide evenly by mesh size — pack with
    ``pad_rows_to=mesh.size`` (padding rows are valid=False).
    """
    n = batch.n_reads
    if n % mesh.size != 0:
        raise ValueError(
            f"batch rows {n} not divisible by mesh size {mesh.size}; "
            f"pack with pad_rows_to={mesh.size}")
    return batch.device_put(reads_sharding(mesh))
