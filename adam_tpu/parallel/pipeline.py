"""Streaming, mesh-sharded pipeline execution — the product path.

The reference's pipelines are distributed by construction: ``Transform.run``
chains RDD stages over partitioned data and every command streams through
executors (Transform.scala:62-97, AdamContext.scala:122-161).  This module is
that property for the TPU substrate: inputs stream in bounded chunks
(io/stream.py), each chunk pads to the mesh and runs the shard_map kernels
with psum/collective aggregation, and cross-chunk state stays compact
(counter blocks, recalibration tables, per-read key columns) — host RSS is
bounded by the chunk size, never the dataset.

Round 1 shipped these kernels but no command used the mesh; this module is
what the CLI now calls.
"""

from __future__ import annotations

import os
import shutil
import tempfile
from typing import Optional, Tuple

import numpy as np
import pyarrow as pa

from .. import obs
from ..packing import column_int64
from .mesh import make_mesh, reads_sharding


def _wire32_from_table(table: pa.Table) -> np.ndarray:
    """Chunk table -> the 4-byte flagstat projection word."""
    from ..ops.flagstat import pack_flagstat_wire32

    n = table.num_rows
    flags = column_int64(table, "flags", 0)
    mapq = np.maximum(column_int64(table, "mapq", -1), 0)  # null -> 0,
    # matching the unpacked kernel's mapq=-1 (both fail the >=5 test)
    refid = column_int64(table, "referenceId", -1)
    mate_refid = column_int64(table, "mateReferenceId", -1)
    # the wire consumes only the COMPARISON of the refids, so compute the
    # cross bit at full width and feed the packer a 0/1 surrogate pair —
    # a >32k-contig BAM (beyond int16) flagstats identically to the
    # native fast path instead of tripping the packer's narrowing guard
    cross = (refid != mate_refid).astype(np.int16)
    return pack_flagstat_wire32(
        flags.astype(np.uint16), mapq.astype(np.uint8),
        cross, np.zeros(n, np.int16),
        np.ones(n, np.uint8))


def flagstat_wire_chunks(path: str, *, chunk_rows: int,
                         io_procs: int = 1, wire_cache=None):
    """Wire-word chunks for any reads input — the streaming flagstat
    front half, shared with the serve front-end's cross-tenant packer
    (adam_tpu/serve/packed.py).  BAM inputs take the native wire walk
    (no string decode; ``ADAM_TPU_FLAGSTAT_DECODE=arrow`` opts out),
    everything else packs the 4-column Arrow projection per chunk.  The
    I/O-ledger scope attributes the input's on-disk bytes to pass
    ``flagstat`` at open, exactly like the solo path.

    ``wire_cache`` (a :class:`..serve.wirecache.WireChunkCache`) makes
    the pack once-per-input within its holder's lifetime: a second
    consumer of the same (identity, chunk_rows) input in the same serve
    round replays the packed host chunks — no file open, no decode (and
    so no re-attributed ledger bytes)."""
    if wire_cache is not None:
        return wire_cache.chunks(
            path, chunk_rows,
            lambda: _flagstat_wire_chunks_raw(path, chunk_rows,
                                              io_procs))
    return _flagstat_wire_chunks_raw(path, chunk_rows, io_procs)


def _flagstat_wire_chunks_raw(path: str, chunk_rows: int, io_procs: int):
    from ..io.dispatch import FLAGSTAT_COLUMNS
    from ..io.stream import open_read_stream

    with obs.ioledger.pass_scope("flagstat"):
        if path.endswith(".bam") and \
                os.environ.get("ADAM_TPU_FLAGSTAT_DECODE",
                               "auto") != "arrow":
            from ..io.fastbam import open_bam_wire32_stream
            wire_chunks = open_bam_wire32_stream(path,
                                                 chunk_rows=chunk_rows,
                                                 io_procs=io_procs)
            if wire_chunks is not None:     # None: no native module —
                return wire_chunks          # fall back to the Arrow path
        stream = open_read_stream(path, columns=FLAGSTAT_COLUMNS,
                                  chunk_rows=chunk_rows,
                                  io_procs=io_procs)
        return (_wire32_from_table(t) for t in stream)


def streaming_flagstat(path: str, *, mesh=None, chunk_rows: int = 1 << 22,
                       io_threads: int = 1, io_procs: int = 1,
                       executor_opts: Optional[dict] = None,
                       wire_cache=None
                       ) -> Tuple["FlagStatMetrics", "FlagStatMetrics"]:
    """Chunked, mesh-sharded flagstat over any reads input.

    Each chunk ships as one contiguous u32 buffer (the 26-bit projection),
    shards over the mesh, and the 18x2 counter block psums over ICI; blocks
    accumulate across chunks on host (the counters form a monoid, like the
    reference's FlagStatMetrics aggregate).

    The chunk cycle runs under the shape-bucketed executor
    (parallel/executor.py): wires pad to the canonical row ladder (one
    compiled shape set for the whole run), the device feed prefetches
    chunk i+1's ``device_put`` behind chunk i's count on accelerators,
    and the kernel donates each chunk's wire buffer there.
    ``executor_opts`` forwards StreamExecutor knobs (prefetch_depth,
    ladder_base, autotune, donate).
    """
    import jax

    from ..ops.flagstat import (FlagStatMetrics, flagstat_wire32_sharded)
    from .executor import StreamExecutor

    if mesh is None:
        mesh = make_mesh()
    # kernel selection: the Pallas wire sweep is ~4.5x the XLA einsum on
    # TPU; ADAM_TPU_FLAGSTAT_IMPL=pallas forces it (interpret mode off-TPU
    # so the virtual-CPU test mesh runs the identical path), =xla opts out
    from ..platform import is_tpu_backend
    impl = os.environ.get("ADAM_TPU_FLAGSTAT_IMPL", "auto")
    on_tpu = is_tpu_backend()
    ex = StreamExecutor(mesh, chunk_rows, on_tpu=on_tpu,
                        **(executor_opts or {}))
    # sync_every: counters accumulate ON DEVICE between drains — a
    # per-chunk np.asarray would serialize host decode/pack against
    # device compute (and pay a full link round trip per chunk); the
    # periodic int64 fold both bounds the in-flight queue and keeps the
    # int32 accumulation window small regardless of file size.
    pex = ex.begin_pass("flagstat", bytes_per_row=4.0,
                        ragged_capable=True, paged_capable=True,
                        mega_capable=True,
                        sync_every=8 if on_tpu else 1)
    use_pallas = impl == "pallas" or (impl == "auto" and on_tpu)
    paged_mode = pex.layout == "paged"
    ragged_mode = pex.layout == "ragged"
    # the fused mega-pass route (ops/megapass.py, plan dimension
    # fused_device): the flagstat leg of the one-dispatch-per-chunk
    # program — same 26-bit unpack + indicator einsum, housed in the
    # mega jit so the dispatch_count accounting covers this pass too.
    # The plan only arms it on a single-shard mesh (begin_pass's
    # capable gate), so the unsharded jit IS the whole dispatch.
    fused_mode = pex.fused_device
    if ragged_mode or paged_mode:
        kernel = None           # ragged/paged dispatches are unsharded
    elif fused_mode:
        from ..ops.megapass import megapass_wire32
        kernel = megapass_wire32
    elif use_pallas:
        from ..ops.flagstat_pallas import flagstat_wire32_sharded_pallas
        kernel = flagstat_wire32_sharded_pallas(mesh,
                                                interpret=not on_tpu,
                                                donate=pex.donate)
    else:
        kernel = flagstat_wire32_sharded(mesh, donate=pex.donate)
    sharding = reads_sharding(mesh)

    totals = np.zeros((18, 2), np.int64)
    totals_dev = None
    n_chunks = 0
    # BAM fast path: the native walk emits the wire word straight from the
    # record bytes — no string decode at all (ADAM_TPU_FLAGSTAT_DECODE=
    # arrow opts back into the Arrow path, e.g. for differential checks).
    # The I/O-ledger scope attributes the input's on-disk bytes (counted
    # by the stream openers) to this pass as decoded input.
    wire_chunks = flagstat_wire_chunks(path, chunk_rows=pex.chunk_rows,
                                       io_procs=io_procs,
                                       wire_cache=wire_cache)
    if io_threads > 1:
        # decode (native wire walk / Arrow projection) moves to a reader
        # thread so it overlaps device dispatch; counter accumulation is
        # an exact integer monoid, so the result cannot depend on timing
        from .ingest import pipelined
        wire_chunks = pipelined(wire_chunks, workers=io_threads)
    import time as _time
    t_start = _time.perf_counter()
    n_reads = 0

    def _pad_wire(wire_u):
        n_pad = pex.pad_rows(len(wire_u))
        if n_pad != len(wire_u):
            return np.concatenate(
                [wire_u, np.zeros(n_pad - len(wire_u), np.uint32)])
        return wire_u

    def _pad_put(wire):
        # pad to the canonical rung (padding words carry valid=0), then
        # start the host→device transfer — under the prefetching feed
        # this runs up to prefetch_depth chunks ahead of the dispatch.
        # The padded host wire rides along as the retry/split/fallback
        # source (a failed donated dispatch needs a fresh transfer).
        rows = len(wire)
        wire = _pad_wire(wire)
        dev = pex.dispatch_put(
            "wire", lambda attempt: jax.device_put(wire, sharding),
            nbytes=wire.nbytes)
        return rows, wire, dev

    mesh_mult = max(getattr(mesh, "size", 1) or 1, 1)

    def _host_cpu_counts(wire_padded):
        # degraded per-chunk CPU fallback: the same integer count kernel
        # on the CPU backend — counters are exact sums over valid words,
        # so the degraded chunk is byte-identical by construction
        import jax.numpy as jnp
        from ..ops.flagstat import flagstat_kernel_wire32
        with jax.default_device(jax.devices("cpu")[0]):
            return np.asarray(
                flagstat_kernel_wire32(jnp.asarray(wire_padded))
            ).astype(np.int64)

    def _split_halves(wire_valid, err):
        # RESOURCE_EXHAUSTED: halve along the ladder rungs and
        # re-dispatch each half under its own policy ladder — the
        # counter monoid makes half-sums equal the whole
        rows = len(wire_valid)
        mid = max((rows // 2) // mesh_mult, 1) * mesh_mult
        if rows <= mesh_mult or mid >= rows:
            raise err
        return (_dispatch_sub(wire_valid[:mid]) +
                _dispatch_sub(wire_valid[mid:]))

    def _dispatch_sub(wire_valid):
        padded = _pad_wire(wire_valid)
        counts = pex.dispatch(
            "count-split",
            lambda attempt: kernel(jax.device_put(padded, sharding)),
            split=lambda e: _split_halves(wire_valid, e),
            fallback=lambda e: _host_cpu_counts(padded))
        return np.asarray(counts).astype(np.int64)

    # -- ragged layout: fixed-capacity concat buffers, prefix-sum bound --
    # Chunks concatenate into ONE compiled buffer shape (the plan's top
    # rung); validity is positional (docs/ARCHITECTURE.md §6g), so the
    # slack past each buffer's total is garbage the kernel never reads
    # and the per-chunk rung padding — the pad tax — is gone.  Counters
    # are an exact integer monoid over reads, so any re-chunking of the
    # stream is byte-identical to the padded walk.
    def _rag_host_counts(buf, total):
        from ..ops.flagstat_pallas import flagstat_wire32_ragged_xla
        with jax.default_device(jax.devices("cpu")[0]):
            return np.asarray(flagstat_wire32_ragged_xla(
                buf, np.array([0, total], np.int32))).astype(np.int64)

    def _rag_dispatch(dev_or_host, total, attempt):
        from ..ops.flagstat_pallas import flagstat_ragged_dispatch
        arr = dev_or_host if attempt == 1 else \
            jax.device_put(dev_or_host, sharding)
        if fused_mode:
            # fused route: the mega program's positional-bound twin —
            # identical indicator monoid, one compiled dispatch
            from ..ops.megapass import megapass_wire32_bounded
            return megapass_wire32_bounded(arr, int(total))
        return flagstat_ragged_dispatch(
            arr, total, interpret=use_pallas and not on_tpu,
            use_pallas=use_pallas)

    def _rag_sub(vw):
        # pad the half up to a ladder rung (zero slack sits past the
        # positional bound anyway) — exact-length sub-buffers would
        # mint a fresh compiled shape per split, compounding the OOM
        # the split is recovering from
        padded = _pad_wire(vw)
        counts = pex.dispatch(
            "count-split",
            lambda attempt: _rag_dispatch(
                jax.device_put(padded, sharding), len(vw), 1),
            split=lambda e: _rag_split(vw, e),
            fallback=lambda e: _rag_host_counts(padded, len(vw)))
        return np.asarray(counts).astype(np.int64)

    def _rag_split(vw, err):
        if len(vw) <= 1:
            raise err
        mid = len(vw) // 2
        return _rag_sub(vw[:mid]) + _rag_sub(vw[mid:])

    def _rag_buffers(chunks):
        cap = pex.chunk_rows
        parts: list = []
        have = 0
        for w in chunks:
            w = np.asarray(w, np.uint32)
            while w.size:
                take = min(cap - have, int(w.size))
                parts.append(w[:take])
                have += take
                w = w[take:]
                if have == cap:
                    yield parts, have
                    parts, have = [], 0
        if have:
            yield parts, have

    def _rag_put(item):
        parts, total = item
        cap = pex.chunk_rows
        # slack past ``total`` stays unwritten: the kernels' positional
        # bound (the row-offset prefix sum) is what excludes it
        buf = np.empty(cap, np.uint32)
        off = 0
        for p in parts:
            buf[off:off + len(p)] = p
            off += len(p)
        dev = pex.dispatch_put(
            "wire", lambda attempt: jax.device_put(buf, sharding),
            nbytes=buf.nbytes)
        return total, buf, dev

    # -- paged layout: the resident page pool (docs/ARCHITECTURE §6l) --
    # The ragged concat still re-ships the WHOLE fixed-capacity buffer
    # per dispatch, slack included; here the buffer lives resident as
    # pages (parallel/pagedbuf) and only the live pages of each round
    # cross the link — the kernel walks (page_table, total) instead of
    # a fresh concat.  Counters stay the same exact monoid, so paged
    # runs are byte-identical to padded/ragged walks.
    pool = None
    if paged_mode:
        from ..ops.flagstat_pallas import flagstat_paged_dispatch
        from .pagedbuf import PagePool
        pool = PagePool("flagstat", pex.pool_pages, pex.page_rows,
                        planes=(("wire", np.uint32),),
                        put=pex.dispatch_put)
        table_len = pex.chunk_rows // pex.page_rows

    def _paged_put(item):
        parts, total = item
        need = max(-(-total // pex.page_rows), 1)
        ids = pool.alloc(need)
        if ids is None:
            # pool thrash (decide_pages' fallback answer): this round
            # rides the concat path — identical bytes, full transfer
            return _rag_put(item)
        buf = np.empty(need * pex.page_rows, np.uint32)
        off = 0
        for p in parts:
            buf[off:off + len(p)] = p
            off += len(p)
        # slack past ``total`` in the last page is garbage the
        # positional bound never reads; resident pages never re-ship
        pool.write(ids, wire=buf)
        return total, buf, ("paged", pool.table(ids, table_len), ids)

    if paged_mode:
        fed = pex.feed(_rag_buffers(wire_chunks), _paged_put)
    elif ragged_mode:
        fed = pex.feed(_rag_buffers(wire_chunks), _rag_put)
    else:
        fed = pex.feed(wire_chunks, _pad_put)
    for rows, wire_host, wire_dev in fed:
        t_chunk = _time.perf_counter()
        if paged_mode and isinstance(wire_dev, tuple) and \
                wire_dev[0] == "paged":
            _, ptable, ids = wire_dev
            pex.note_ragged(rows, pex.chunk_rows)

            def _paged_first(tab, t):
                if fused_mode:
                    from ..ops.megapass import megapass_wire32_paged
                    return megapass_wire32_paged(pool.device("wire"),
                                                 tab, t)
                return flagstat_paged_dispatch(
                    pool.device("wire"), tab, t,
                    interpret=use_pallas and not on_tpu,
                    use_pallas=use_pallas)

            counts = pex.dispatch(
                "count",
                lambda attempt, tab=ptable, host=wire_host, t=rows:
                    _paged_first(tab, t)
                    if attempt == 1 else _rag_dispatch(host, t, 2),
                split=lambda e, host=wire_host, t=rows:
                    _rag_split(host[:t], e),
                fallback=lambda e, host=wire_host, t=rows:
                    _rag_host_counts(host, t))
            # the dispatch is enqueued (single device stream = FIFO),
            # so recycling the pages for the NEXT round's scatter is
            # ordered after this count reads them
            pool.free(ids)
        elif paged_mode or ragged_mode:
            pex.note_ragged(rows, pex.chunk_rows)
            counts = pex.dispatch(
                "count",
                lambda attempt, dev=wire_dev, host=wire_host, t=rows:
                    _rag_dispatch(dev if attempt == 1 else host, t,
                                  attempt),
                split=lambda e, host=wire_host, t=rows:
                    _rag_split(host[:t], e),
                fallback=lambda e, host=wire_host, t=rows:
                    _rag_host_counts(host, t))
        else:
            counts = pex.dispatch(
                "count",
                lambda attempt, dev=wire_dev, host=wire_host:
                    kernel(dev) if attempt == 1
                    else kernel(jax.device_put(host, sharding)),
                split=lambda e, host=wire_host, r=rows:
                    _split_halves(host[:r], e),
                fallback=lambda e, host=wire_host: _host_cpu_counts(host))
        del wire_dev            # donated on TPU: consumed by the kernel
        if isinstance(counts, np.ndarray):
            # a split/degraded chunk returns host counters — fold them
            # straight into the host totals, never back onto a device
            # that just failed
            totals += counts.astype(np.int64)
        else:
            totals_dev = counts if totals_dev is None \
                else totals_dev + counts
        n_chunks += 1
        n_reads += rows
        if n_chunks % pex.sync_every == 0 and totals_dev is not None:
            totals += np.asarray(totals_dev).astype(np.int64)
            totals_dev = None
        obs.chunk_processed("flagstat", rows, bytes_in=4 * rows,
                            seconds=_time.perf_counter() - t_chunk)
    if totals_dev is not None:
        totals += np.asarray(totals_dev).astype(np.int64)
    ex.finish()
    # same end-of-run rollup as transform (rows_total / reads_per_sec /
    # bytes_in + the run_totals event), so -metrics consumers see one
    # schema across commands; the io_ledger events ride the same exit
    obs.run_totals("flagstat", n_reads, _time.perf_counter() - t_start,
                   input_path=path)
    obs.ioledger.emit_events()
    passed = FlagStatMetrics.from_counters(totals[:, 0])
    failed = FlagStatMetrics.from_counters(totals[:, 1])
    return failed, passed


# ---------------------------------------------------------------------------
# streaming transform
# ---------------------------------------------------------------------------

def _global_codes(col: pa.ChunkedArray, mapping: dict) -> np.ndarray:
    """Chunk-local dictionary codes remapped through a cross-chunk dict.

    ``mapping`` (str -> dense code) persists across chunks, so equal strings
    in different chunks get equal codes without holding every value — only
    the distinct ones (libraries: a handful).
    """
    import pyarrow.compute as pc
    from ..packing import _nan_to_null

    enc = pc.dictionary_encode(col.combine_chunks())
    vals = enc.dictionary.to_pylist()
    remap = np.array(
        [-1 if v is None else mapping.setdefault(v, len(mapping))
         for v in vals] or [0], np.int64)
    idx = _nan_to_null(enc.indices.to_numpy(zero_copy_only=False), -1)
    return np.where(idx >= 0, remap[np.maximum(idx, 0)], -1)


def _accumulate_seq_records(table: pa.Table, seen: dict) -> None:
    """Fold a chunk's denormalized dictionary fields into ``seen``
    ((id, name) -> SequenceRecord) — the reference's scan+dedup
    (AdamContext.scala:175-236), incrementally."""
    from ..models.dictionary import SequenceRecord

    for cset in (("referenceId", "referenceName", "referenceLength",
                  "referenceUrl"),
                 ("mateReferenceId", "mateReference", "mateReferenceLength",
                  "mateReferenceUrl")):
        if not all(c in table.column_names for c in cset):
            continue
        ids = column_int64(table, cset[0])
        uniq, first = np.unique(ids, return_index=True)
        rows = first[uniq >= 0]
        if not len(rows):
            continue
        sub = table.select(list(cset)).take(pa.array(rows)).to_pylist()
        for r in sub:
            i, nm = r[cset[0]], r[cset[1]]
            if i is not None and nm is not None and (i, nm) not in seen:
                seen[(i, nm)] = SequenceRecord(i, nm, r[cset[2]] or 0,
                                               r[cset[3]])


def _apply_dup_bits(table: pa.Table, dup: np.ndarray) -> pa.Table:
    from .. import schema as S

    flags = column_int64(table, "flags", 0)
    new = np.where(dup, flags | S.FLAG_DUPLICATE,
                   flags & ~np.int64(S.FLAG_DUPLICATE))
    idx = table.column_names.index("flags")
    return table.set_column(idx, "flags",
                            pa.array(new.astype(np.uint32), pa.uint32()))


class _BinStub:
    """Stand-in for a closed DatasetWriter when pass 4 resumes from a
    checkpoint: _emit_bins/_bin_unit_descs only consume ``path`` and
    ``rows_written``."""

    def __init__(self, path: str, rows_written: int):
        self.path = path
        self.rows_written = rows_written


def _snp_digest(snp_table) -> str:
    """Content digest of the BQSR known-sites mask for the resume
    fingerprint: a checkpointed RecalTable counted against a different
    dbSNP mask must not be reused (the mask changes which bases count)."""
    if snp_table is None:
        return "none"
    import hashlib

    h = hashlib.sha256()
    for contig in sorted(snp_table._by_contig):
        h.update(contig.encode())
        h.update(snp_table._by_contig[contig].tobytes())
    return h.hexdigest()[:16]


class _StreamCheckpoint:
    """Pass-level resume manifest for :func:`streaming_transform`.

    The in-memory pipeline checkpoints whole stage TABLES
    (checkpoint.CheckpointDir); the streaming pipeline's state between
    passes is already durable Parquet in the workdir (raw spill, genome
    bins, halos) plus three compact artifacts — the markdup dup bits, the
    RecalTable, and the run metadata.  So resume here is a manifest that
    records which passes completed for WHICH (input, config) fingerprint,
    the compact artifacts beside it, and pre-pass cleanup of any
    half-written artifacts from a crashed attempt.  Markers write via
    tmp+rename, so a crash mid-mark is invisible (same discipline as
    checkpoint.py).
    """

    MANIFEST = "stream_checkpoint.json"

    def __init__(self, workdir: str, fingerprint: str):
        import json

        self.dir = workdir
        self.path = os.path.join(workdir, self.MANIFEST)
        self.state = {"fingerprint": fingerprint, "passes": {}}
        if os.path.exists(self.path):
            try:
                with open(self.path) as f:
                    prev = json.load(f)
            except ValueError:
                prev = None
            if prev and prev.get("fingerprint") == fingerprint:
                self.state = prev
            else:
                # a different input/config owns these artifacts: refusing
                # beats silently destroying another run's (possibly
                # multi-hour) resume state — same contract as the
                # in-memory CheckpointDir (checkpoint.py:51-77)
                raise ValueError(
                    f"checkpoint dir {workdir!r} belongs to a different "
                    "transform (input/flags changed or manifest corrupt); "
                    "delete it or use another -checkpoint_dir")

    @staticmethod
    def fingerprint(input_path: str, output_path: str, config: dict) -> str:
        import hashlib
        import json

        parts = [os.path.abspath(input_path), os.path.abspath(output_path),
                 json.dumps(config, sort_keys=True)]
        try:
            st = os.stat(input_path)
            parts.append(f"{st.st_size}:{st.st_mtime_ns}")
        except OSError:
            pass
        return hashlib.sha256("\x00".join(parts).encode()).hexdigest()[:16]

    def has(self, name: str) -> bool:
        return name in self.state["passes"]

    def meta(self, name: str) -> dict:
        return self.state["passes"][name]

    def mark(self, name: str, **meta) -> None:
        import json

        from ..checkpoint import atomic_write

        self.state["passes"][name] = meta
        atomic_write(self.path, json.dumps(self.state),
                     fault_site="checkpoint_write")

    def save_array(self, name: str, arr) -> None:
        np.save(os.path.join(self.dir, name + ".npy"), arr)

    def load_array(self, name: str):
        return np.load(os.path.join(self.dir, name + ".npy"))

    def save_arrays(self, name: str, **arrays) -> None:
        np.savez(os.path.join(self.dir, name + ".npz"), **arrays)

    def load_arrays(self, name: str):
        return np.load(os.path.join(self.dir, name + ".npz"))

    def clean_unless(self, marker: str, *glob_patterns: str) -> None:
        """Remove artifacts of an uncompleted pass (crashed half-writes)."""
        import glob as _glob

        if self.has(marker):
            return
        for pat in glob_patterns:
            for full in _glob.glob(os.path.join(self.dir, pat)):
                shutil.rmtree(full, ignore_errors=True) \
                    if os.path.isdir(full) else os.unlink(full)


class _MarkdupKeys:
    """Per-chunk compact markdup key accumulator (~42 bytes/read).

    The streaming replacement for the reference's two name/position shuffles
    (MarkDuplicates.scala:59-109): each chunk contributes device-computed 5'
    positions and phred>=15 scores plus host-hashed name keys; the global
    decision then runs once over the concatenated columns, never holding the
    records themselves.
    """

    def __init__(self, mesh):
        self.mesh = mesh
        self.flags, self.refid, self.rgid = [], [], []
        self.fp, self.score, self.h1, self.h2, self.lib = [], [], [], [], []
        self.lib_map: dict = {}

    def add_chunk(self, table: pa.Table, batch, pex=None,
                  repack=None) -> None:
        import jax
        import jax.numpy as jnp
        from ..ops.markdup import _device_fiveprime_and_score
        from ..packing import hash_strings_128

        n = table.num_rows
        is_host = isinstance(batch.flags, np.ndarray)

        # the fused mega-pass route (plan dimension fused_device, only
        # armed on a single-shard mesh): the markdup leg of the
        # multi-output program — the SAME jitted key kernel inlined
        # under the mega jit, so keys are bit-identical by construction
        fused = pex is not None and getattr(pex, "fused_device", False)

        def compute(b):
            # the executor's device feed may hand the batch in already
            # sharded (its transfer then overlapped the previous
            # chunk's key kernel); host batches take the put here
            sharded = b if not isinstance(b.flags, np.ndarray) \
                else b.device_put(reads_sharding(self.mesh))
            if fused:
                from ..ops.megapass import megapass_markdup
                fp, score = megapass_markdup(
                    sharded.flags, sharded.start, sharded.cigar_ops,
                    sharded.cigar_lens, sharded.n_cigar, sharded.quals)
            else:
                fp, score = _device_fiveprime_and_score(
                    sharded.flags, sharded.start, sharded.cigar_ops,
                    sharded.cigar_lens, sharded.n_cigar, sharded.quals)
            # materialize BEFORE any accumulator mutates: a device
            # error must surface here, inside the retry ladder — never
            # between appends (a partial append would corrupt the keys)
            return (np.asarray(fp)[:n].astype(np.int64),
                    np.asarray(score)[:n])

        def run(attempt):
            if attempt == 1 or is_host:
                return compute(batch)
            # a failed attempt may have consumed the prefetched device
            # batch — rebuild the chunk's host batch and re-transfer
            return compute(repack() if repack is not None else batch)

        def fallback(e):
            # degraded per-chunk CPU fallback: the same integer key
            # kernel (5' positions + phred>=15 sums) pinned to the CPU
            # backend — byte-identical by construction
            b = batch if is_host else \
                (repack() if repack is not None else None)
            if b is None or not isinstance(b.flags, np.ndarray):
                raise e
            with jax.default_device(jax.devices("cpu")[0]):
                fp, score = _device_fiveprime_and_score(
                    jnp.asarray(b.flags), jnp.asarray(b.start),
                    jnp.asarray(b.cigar_ops),
                    jnp.asarray(b.cigar_lens),
                    jnp.asarray(b.n_cigar), jnp.asarray(b.quals))
                return (np.asarray(fp)[:n].astype(np.int64),
                        np.asarray(score)[:n])

        if pex is not None:
            fp_np, score_np = pex.dispatch("markdup-keys", run,
                                           fallback=fallback)
        else:
            fp_np, score_np = run(1)
        self.fp.append(fp_np)
        self.score.append(score_np)
        self.flags.append(column_int64(table, "flags", 0))
        self.refid.append(column_int64(table, "referenceId"))
        self.rgid.append(column_int64(table, "recordGroupId"))
        h1, h2 = hash_strings_128(table.column("readName"))
        self.h1.append(h1)
        self.h2.append(h2)
        self.lib.append(_global_codes(table.column("recordGroupLibrary"),
                                      self.lib_map))

    def decide(self) -> np.ndarray:
        from ..ops.markdup import bucket_ids_from_keys, decide_duplicates

        cat = {k: np.concatenate(getattr(self, k)) for k in
               ("flags", "refid", "rgid", "fp", "score", "h1", "h2", "lib")}
        bucket_id = bucket_ids_from_keys(cat["rgid"], cat["h1"], cat["h2"])
        return decide_duplicates(cat["flags"], cat["refid"], cat["fp"],
                                 cat["score"], bucket_id, cat["lib"])


#: realignment halo width: maxIndelSize == max target span
#: (RealignIndels.scala:176-182) plus an allowance for read length, so any
#: read that can share a merged target group with a neighbor bin's read is
#: duplicated into that bin's halo
_REALIGN_HALO = 3000 + 1024


# ---------------------------------------------------------------------------
# fused single-stream transform: decode once, collapse the re-streams
# ---------------------------------------------------------------------------

#: escape hatch: ADAM_TPU_FUSE=0/off forces the legacy 4-pass transform,
#: =1 forces fusion (the -no_fuse transform flag mirrors the former)
FUSE_ENV = "ADAM_TPU_FUSE"

#: global-row join column the fused binned streams carry through the bin
#: spill (dup bits + MD events re-join by it in s2/p4); stripped before
#: any row reaches realign/sort/output
RIDX_COL = "__ridx"


def resolve_fuse_opt(fuse=None):
    """Caller's explicit choice wins; ``ADAM_TPU_FUSE`` fills None (the
    executor's flag/env convention)."""
    if fuse is None and os.environ.get(FUSE_ENV):
        fuse = os.environ[FUSE_ENV] not in ("0", "off")
    return fuse


def decide_fusion_plan(*, markdup: bool, bqsr: bool, realign: bool,
                       sort: bool, is_parquet: bool,
                       coalesced: bool = False,
                       fuse: Optional[bool] = None) -> dict:
    """The transform's frozen dataflow plan: fused streams vs the legacy
    4-pass chain, per flag combination.

    PURE — a deterministic function of the keyword inputs, recorded in
    full (``inputs`` + ``input_digest``) by the ``fusion_plan_selected``
    event so tools/check_executor.py can replay the decision offline
    (the ``decide_plan`` convention).  The stream structure it encodes:

    * binned (sort/realign on): stream 1 decodes the input ONCE and
      routes rows straight to the genome bins (+realign halos) — no raw
      spill at all; with BQSR, stream 2 walks the own-bins with a
      column projection to accumulate the RecalTable; pass 4 applies
      dup bits + the deferred LUT qual rewrite at bin load, then
      realigns/sorts/emits.  Only the two genuine barriers (markdup
      decision, RecalTable finalize) materialize state.
    * unbinned: stream 1 spills in the ReadBatch wire format
      (io/wirespill — base/qual planes, not raw rows), stream 2 (BQSR
      only) re-reads a projected plane subset for the count, and the
      emit stream applies dup bits + the LUT at output emit.  With no
      stage enabled at all, stream 1 writes the output directly (zero
      spill).
    """
    inputs = dict(markdup=bool(markdup), bqsr=bool(bqsr),
                  realign=bool(realign), sort=bool(sort),
                  is_parquet=bool(is_parquet), coalesced=bool(coalesced),
                  fuse=None if fuse is None else bool(fuse))
    import hashlib
    import json

    reasons = []
    fused = True if inputs["fuse"] is None else inputs["fuse"]
    if not fused:
        reasons.append("fuse-off")
    binned = bool(sort or realign)
    # direct emit needs total_rows to be un-needed up front: an explicit
    # -coalesce sizes output parts from the total, so it keeps the
    # spill + emit-stream shape even with no stages enabled
    direct_emit = fused and not binned and not markdup and not bqsr \
        and not coalesced
    # the wire spill only exists when a later stream re-reads it; a
    # Parquet input needs no spill (streams re-read the input itself)
    wire_spill = fused and not binned and not is_parquet and \
        not direct_emit
    if direct_emit:
        reasons.append("passthrough")
    if fused:
        streams = ["s1"] + (["s2"] if bqsr else []) + \
            (["p4"] if binned else ([] if direct_emit else ["s3"]))
    else:
        streams = ["p1"] + (["p2"] if bqsr else []) + ["p3"] + \
            (["p4"] if binned else [])
    plan = dict(
        mode="fused" if fused else "legacy",
        binned=binned,
        route_in_s1=fused and binned,
        # __ridx joins dup bits (markdup) and the hoisted MD events
        # (bqsr) back to bin rows after the s1 scatter
        carry_ridx=fused and binned and (markdup or bqsr),
        count_pass=("s2" if fused else "p2") if bqsr else None,
        apply_at=(("p4" if binned else "s3") if fused else "p3")
        if bqsr else None,
        wire_spill=wire_spill,
        direct_emit=direct_emit,
        streams=streams,
        reason=";".join(reasons) or "default",
        inputs=inputs)
    plan["input_digest"] = hashlib.sha256(
        json.dumps(inputs, sort_keys=True).encode()).hexdigest()[:16]
    return plan


def emit_fusion_plan(plan: dict) -> None:
    """One ``fusion_plan_selected`` event + counter per transform run —
    the pass-boundary discipline of ``StreamExecutor.begin_pass``."""
    obs.registry().counter("fusion_plans").inc()
    obs.emit("fusion_plan_selected", mode=plan["mode"],
             streams=list(plan["streams"]),
             route_in_s1=plan["route_in_s1"],
             carry_ridx=plan["carry_ridx"],
             count_pass=plan["count_pass"], apply_at=plan["apply_at"],
             wire_spill=plan["wire_spill"],
             direct_emit=plan["direct_emit"], reason=plan["reason"],
             inputs=plan["inputs"], input_digest=plan["input_digest"])


class _MdEventStore:
    """Stream-1 accumulator for the BQSR mismatch evidence: per-read MD
    presence plus the ~1-per-read MD mismatch events, keyed by GLOBAL
    row index.

    The legacy count pass re-reads and re-parses every read's
    ``mismatchingPositions`` string (the largest column of the raw
    spill on typical inputs); the fused transform parses it exactly
    once while the bytes are already decoded in stream 1, holds the
    compact event form (~a few bytes/read — the markdup-keys RSS
    envelope), and stream 2's projection drops the MD column from its
    re-read entirely.  ``md_info_for`` re-joins the events to any row
    subset (a bin chunk's ``__ridx`` gather, or a sequential re-stream's
    offset range) in the exact shape ``count_tables_device(md_info=)``
    consumes.
    """

    def __init__(self):
        self._has, self._rows, self._pos = [], [], []
        self._base = 0
        self.has_md = None
        self.ev_rows = None
        self.ev_pos = None

    def add_chunk(self, table: pa.Table) -> None:
        """Strict chunk order (stream 1's reader), so local rows offset
        by the running base are globally sorted."""
        from ..bqsr.recalibrate import md_events_for

        starts = column_int64(table, "start", -1)
        has_md, rows, pos = md_events_for(table, starts)
        self._has.append(has_md)
        self._rows.append(rows + self._base)
        self._pos.append(pos)
        self._base += table.num_rows

    def freeze(self) -> None:
        self.has_md = np.concatenate(self._has) if self._has \
            else np.zeros(0, bool)
        self.ev_rows = np.concatenate(self._rows) if self._rows \
            else np.zeros(0, np.int64)
        self.ev_pos = np.concatenate(self._pos) if self._pos \
            else np.zeros(0, np.int64)
        self._has = self._rows = self._pos = None

    def save(self, ck: "_StreamCheckpoint") -> None:
        ck.save_arrays("mdinfo", has_md=self.has_md,
                       ev_rows=self.ev_rows, ev_pos=self.ev_pos)

    @classmethod
    def load(cls, ck: "_StreamCheckpoint") -> "_MdEventStore":
        z = ck.load_arrays("mdinfo")
        st = cls()
        st.has_md = z["has_md"]
        st.ev_rows = z["ev_rows"]
        st.ev_pos = z["ev_pos"]
        return st

    def md_info_for(self, ridx: np.ndarray):
        """(has_md, local_rows, positions) for the chunk whose rows map
        to global rows ``ridx`` — a two-searchsorted range expand, no
        per-row Python."""
        has = self.has_md[ridx] if len(self.has_md) else \
            np.zeros(len(ridx), bool)
        lo = np.searchsorted(self.ev_rows, ridx, side="left")
        hi = np.searchsorted(self.ev_rows, ridx, side="right")
        cnt = hi - lo
        tot = int(cnt.sum())
        first = np.cumsum(cnt) - cnt
        idx = np.repeat(lo - first, cnt) + np.arange(tot)
        local = np.repeat(np.arange(len(ridx), dtype=np.int64), cnt)
        return has, local, self.ev_pos[idx]


def _estimate_input_rows(path: str, chunk_rows: int) -> int:
    """Row-count estimate for the fused default bin count: exact from
    Parquet footers, else input bytes over a nominal compressed
    bytes/read.  Output VALUES are bin-count-invariant (the halo makes
    realignment edge-independent, pinned by TestBinEdgeAndSkew), so an
    estimate only shifts scheduling granularity."""
    try:
        if not (path.endswith(".sam") or path.endswith(".bam")):
            import pyarrow.parquet as pq
            if os.path.isdir(path):
                return sum(
                    pq.ParquetFile(os.path.join(path, f)).metadata.num_rows
                    for f in os.listdir(path) if f.endswith(".parquet"))
            return pq.ParquetFile(path).metadata.num_rows
        return max(os.stat(path).st_size // 256, 1)
    except (OSError, ValueError):
        return max(int(chunk_rows), 1)


def _packed_chunks(chunk_iter, pex, io_threads: int,
                   pack_reads, bucket_len: int, timed_chunks,
                   want_pack: bool = True):
    """(table, batch) pairs for passes with a FIXED length bucket —
    sequential (decode/pack stages timed apart) or overlapped via
    parallel.ingest.pipelined (stall time lands in ``<pass>-ingest-wait``).
    Row padding comes from the pass executor's canonical ladder
    (``pex.pad_rows``), which also owns the pad-waste/recompile
    telemetry.

    ALWAYS staged: the stage stack is per-thread now (instrument), so
    when the executor's device feed drives this generator from its
    feeder thread, the decode/pack stages land correctly nested on that
    thread's own report lane (and its timeline lane under ``-trace``) —
    the PR 3 unstaged-producer workaround is gone."""
    from ..instrument import stage

    pass_name = pex.pass_name

    def work(table, _ctx):
        if not want_pack:
            return table, None
        padded = pex.pad_rows(table.num_rows, bucket_len,
                              max_len=_chunk_max_len(table)
                              if bucket_len else None)
        return table, pack_reads(
            table, pad_rows_to=padded, bucket_len=bucket_len)

    if io_threads > 1:
        from .ingest import pipelined
        piped = pipelined(chunk_iter, work, io_threads)
        yield from timed_chunks(piped, f"{pass_name}-ingest-wait")
        return
    for table in timed_chunks(chunk_iter, f"{pass_name}-decode"):
        if not want_pack:
            yield table, None
            continue
        with stage(f"{pass_name}-pack"):
            out = work(table, None)
        # yield OUTSIDE the stage context: a yield inside would leave the
        # pack timer running across the consumer's whole chunk body and
        # nest its stages under pack (observed in the first e2e rerun)
        yield out


def _chunk_max_len(table: pa.Table):
    """The chunk's true longest read (for the length-axis pad-waste
    sample against the bucket) — one vectorized Arrow pass; None when
    the projection carries no base-level column.  Best-effort telemetry,
    never fatal."""
    try:
        import pyarrow.compute as pc

        from ..io.wirespill import WIRE_SEQ_LEN, is_wire_table
        if is_wire_table(table):
            v = pc.max(table.column(WIRE_SEQ_LEN)).as_py()
        elif "sequence" in table.column_names:
            v = pc.max(pc.binary_length(table.column("sequence"))).as_py()
        else:
            return None
        return int(v) if v is not None else None
    except Exception:  # noqa: BLE001 — telemetry-grade
        return None


def _project_batch(batch, keep: tuple):
    """None out columns a pass's kernels never touch before the device
    feed ships the batch — the projection-to-the-bit discipline applied
    to the prefetch wire (p1's markdup keys never read bases; shipping
    them would double the transfer)."""
    from dataclasses import fields as _dc_fields, replace as _dc_replace

    drop = {f.name: None for f in _dc_fields(batch)
            if f.name not in keep and getattr(batch, f.name) is not None}
    return _dc_replace(batch, **drop) if drop else batch


#: device-feed projections: the columns each pass's device kernels read
_P1_DEV_COLS = ("flags", "start", "cigar_ops", "cigar_lens", "n_cigar",
                "quals")
_P2_DEV_COLS = ("flags", "start", "read_group", "read_len", "bases",
                "quals", "cigar_ops", "cigar_lens")
#: the ragged count rebuilds FLAT planes from the host batch
#: (recalibrate._count_tables_one), so pre-shipping the padded [N, L]
#: base/qual planes would transfer exactly the pad-tax bytes the layout
#: removes; mismatch_state's geometry columns still ride the feed
_P2_DEV_COLS_RAGGED = ("flags", "start", "read_group", "read_len",
                       "cigar_ops", "cigar_lens")
_P3_DEV_COLS = ("flags", "read_group", "read_len", "bases", "quals")


def _p2_dev_cols(pex) -> tuple:
    return _P2_DEV_COLS if pex.layout == "padded" else _P2_DEV_COLS_RAGGED


def _feed_packed(chunk_iter, pex, io_threads: int, pack_reads,
                 bucket_len: int, timed_chunks, mesh, dev_cols: tuple,
                 want_pack: bool = True, feed_wait=None):
    """``_packed_chunks`` composed with the executor's device feed:
    yields (table, host_batch, device_batch_or_None) triples.

    The feed pre-transfers the batch (projected to ``dev_cols``) only
    when the downstream kernel can consume whole columns: the sharded
    mesh path, or an unsharded chunk small enough for the monolithic
    (non-slab) walk — the slab walk slices rows, and slicing device
    arrays would dispatch a compiled slice per offset (fresh shapes, the
    churn the executor exists to kill).

    When the feed is active (prefetch_depth > 0) the producer runs
    STAGED on the feeder thread (the stage stack is per-thread now —
    decode/pack walls land on the feeder's own lane), and the consumer's
    stall is still attributed as ``<pass>-feed-wait`` via ``feed_wait``
    — a stage-only wrapper (no chunk accounting: the producer already
    counted each chunk once)."""
    from ..bqsr.recalibrate import _count_slab_rows

    active = pex.prefetch_depth > 0
    base = _packed_chunks(chunk_iter, pex, io_threads, pack_reads,
                          bucket_len, timed_chunks,
                          want_pack=want_pack)
    sharding = reads_sharding(mesh)
    slab = _count_slab_rows()

    def put(item):
        table, batch = item
        dev = None
        if batch is not None and batch.n_reads % mesh.size == 0 and \
                (mesh.size > 1 or batch.n_reads <= slab):
            proj = _project_batch(batch, dev_cols)
            dev = pex.dispatch_put(
                "batch", lambda attempt: proj.device_put(sharding))
        return table, batch, dev

    fed = pex.feed(base, put)
    if active and feed_wait is not None:
        fed = feed_wait(fed, f"{pex.pass_name}-feed-wait")
    return fed


def streaming_transform(input_path: str, output_path: str, *,
                        markdup: bool = False, bqsr: bool = False,
                        snp_table=None, realign: bool = False,
                        sort: bool = False, workdir: Optional[str] = None,
                        mesh=None, chunk_rows: int = 1 << 20,
                        n_bins: Optional[int] = None,
                        coalesce: Optional[int] = None,
                        max_bin_rows: Optional[int] = None,
                        compression: str = "zstd",
                        page_size: Optional[int] = None,
                        use_dictionary: bool = True,
                        row_group_bytes: Optional[int] = None,
                        resume: bool = False,
                        io_threads: int = 1,
                        io_procs: int = 1,
                        executor_opts: Optional[dict] = None,
                        realign_opts: Optional[dict] = None,
                        fuse: Optional[bool] = None,
                        fleet: Optional[dict] = None) -> int:
    """The ``transform`` pipeline over a chunked stream and a device mesh.

    Multi-pass, like the reference's shuffle stages (Transform.scala:62-97):

      pass 1  ingest: stream the input once, spill raw chunks to a Parquet
              workdir (skipped when the input already is Parquet), compute
              markdup key columns on device per chunk;
      -       global markdup decision over the compact keys (the two
              shuffles of MarkDuplicates.scala collapse into host sorts);
      pass 2  BQSR table pass: re-stream, apply dup bits, accumulate the
              dense RecalTable (devices psum within a chunk, chunks merge
              with RecalTable.__add__, the reference's driver aggregate);
      pass 3  emit: re-stream, apply dup bits + recalibrated quals, route
              rows to genome bins (GenomicRegionPartitioner) when
              sort/realign is on, else write output parts directly;
      pass 4  per-bin: realign + in-bin sort; bins emit through a sorted
              merge window, so the output is globally position-sorted
              (AdamRDDFunctions.scala:63-93's range partition + sort).
              With realignment on, bins run through the pipelined realign
              engine (parallel/realign_exec.py): load+prep of the next
              bin overlaps the current bin's device sweeps and the
              previous bin's emit, and sweep jobs from all in-flight bins
              batch by padded shape.  ``realign_opts`` forwards its knobs
              ({pipeline: bool, depth: int, donate: bool} — the
              -realign_pipeline_depth / -no_realign_pipeline flags and
              ADAM_TPU_REALIGN_* envs); output is byte-identical at any
              depth, pipeline on or off.

    Host RSS is bounded by chunk size + ~42 bytes/read of markdup keys —
    never the dataset.  Two skew/edge mechanisms:

      * realign halo: reads within ``_REALIGN_HALO`` of a bin edge are
        duplicated into the neighbor bin's halo set (the rod-bucket trick,
        AdamRDDFunctions.scala:175-183); each bin realigns own+halo reads so
        a target group straddling the edge sees the SAME evidence from both
        sides, and emits only its own rows — matching the reference's
        global target collect (RealignmentTargetFinder.scala:54-71, which
        has no edges) without holding the genome in memory;
      * hot-bin split: a bin whose row count exceeds ``max_bin_rows``
        (default 4x chunk_rows) splits into position sub-ranges at row
        quantiles before processing (the reference scales reducer counts by
        coverage the same way, PileupAggregator.scala:204-209), so one
        high-coverage contig (chrM, rDNA) cannot blow host RSS.

    ``coalesce`` caps the number of output part files (Transform.scala's
    -coalesce repartition, :51-70).

    ``io_threads > 1`` overlaps host ingest with device dispatch in every
    pass (one reader thread decoding in order + a pool packing chunks,
    results consumed in input order — parallel.ingest.pipelined; the
    reference's Bam2Adam.scala:56-97 reader/writer pool).  Output is
    bit-identical to the sequential walk (differential-tested); only the
    stage report changes shape (decode+pack collapse into
    ``pN-ingest-wait``, the consumer's stall time).

    Chunk shapes, device transfers, and buffer donation are owned by the
    shape-bucketed executor (parallel/executor.py): row counts pad to
    one canonical ladder across all passes (each kernel compiles at most
    ``len(ladder)`` shapes for the run), the device feed prefetches the
    next chunk's transfer behind the current chunk's kernels on
    accelerators, and the autotuner re-decides the chunk size / ladder
    density at pass boundaries from observed pad waste and the evidence
    ledger's link rate.  Padding rows carry ``valid=False`` and every
    kernel ignores them, so bucket geometry never changes results.
    ``executor_opts`` forwards StreamExecutor knobs (prefetch_depth,
    ladder_base, autotune, donate).

    ``fleet`` (``{"hosts": N, ...}`` — the transform CLI's ``-hosts``)
    distributes the fused stream-2 RecalTable count across N worker
    processes via parallel/shardstream.py: supported for the fused,
    unbinned, Parquet-input dataflow (the count is an exact integer
    monoid, so the sharded table — and therefore the output — is
    byte-identical to the single-host run; markdup dup bits and the
    hoisted MD events ship to the fleet and re-join by global row).
    """
    from ..bqsr.recalibrate import apply_table
    from ..instrument import stage
    from ..io.parquet import DatasetWriter, iter_tables
    from ..io.stream import open_read_stream
    from ..models.dictionary import SequenceDictionary
    from ..packing import pack_reads
    from .partitioner import GenomicRegionPartitioner
    from .. import schema as S

    # one bundle for every DatasetWriter this run constructs (spills, bins,
    # halos, subs, output) — the next knob gets added HERE, not at eight
    # call sites; row_group_bytes applies to the output writer alone
    wopts = dict(compression=compression, page_size=page_size,
                 use_dictionary=use_dictionary)

    timed_chunks = _timed_chunks
    waited = _feed_wait

    import time as _time
    t_start = _time.perf_counter()
    if mesh is None:
        mesh = make_mesh()
    is_parquet = not (input_path.endswith(".sam") or
                      input_path.endswith(".bam"))
    # one frozen dataflow decision per run (pure + replayable +
    # event-recorded, the executor convention): fused streams decode the
    # bytes once and collapse the p2/p3 re-streams; the -no_fuse flag /
    # ADAM_TPU_FUSE env pins the legacy 4-pass chain
    fplan = decide_fusion_plan(markdup=markdup, bqsr=bqsr,
                               realign=realign, sort=sort,
                               is_parquet=is_parquet,
                               coalesced=coalesce is not None,
                               fuse=resolve_fuse_opt(fuse))
    emit_fusion_plan(fplan)
    # workdir + pass-level checkpoint: built ONCE for both dataflows —
    # the fingerprint carries the fusion mode, so a fused workdir
    # refuses a legacy resume (and vice versa: the two layouts spill
    # different artifacts under the same paths)
    own_workdir = workdir is None
    if own_workdir:
        workdir = tempfile.mkdtemp(prefix="adam_tpu_transform_")
    os.makedirs(workdir, exist_ok=True)
    ck = None
    if resume:
        if own_workdir:
            raise ValueError(
                "streaming resume needs a persistent workdir "
                "(pass workdir=/checkpoint dir)")
        fp = _StreamCheckpoint.fingerprint(input_path, output_path, dict(
            markdup=markdup, bqsr=bqsr, realign=realign, sort=sort,
            chunk_rows=chunk_rows, n_bins=n_bins, coalesce=coalesce,
            max_bin_rows=max_bin_rows, snp=_snp_digest(snp_table),
            fuse=fplan["mode"]))
        ck = _StreamCheckpoint(workdir, fp)
        if ck.has("done") and os.path.isdir(output_path) and any(
                f.endswith(".parquet") for f in os.listdir(output_path)):
            return ck.meta("done")["total_rows"]
    if fleet and int(fleet.get("hosts", 1)) > 1 and (
            fplan["mode"] != "fused" or fplan["binned"] or
            not is_parquet or not bqsr):
        # refuse rather than silently run single-host: a dropped hosts
        # request is the kind of quiet degradation the fleet layer
        # exists to make impossible
        raise ValueError(
            "transform -hosts shards the fused stream-2 count: it "
            "needs -recalibrate_base_qualities, the fused dataflow "
            "(no -no_fuse), a Parquet input, and no "
            "-sort_reads/-realignIndels")
    if fplan["mode"] == "fused":
        return _fused_transform(
            input_path, output_path, plan=fplan, markdup=markdup,
            bqsr=bqsr, snp_table=snp_table, realign=realign, sort=sort,
            workdir=workdir, own_workdir=own_workdir, ck=ck, mesh=mesh,
            chunk_rows=chunk_rows,
            n_bins=n_bins, coalesce=coalesce, max_bin_rows=max_bin_rows,
            wopts=wopts, row_group_bytes=row_group_bytes,
            io_threads=io_threads, io_procs=io_procs,
            executor_opts=executor_opts, realign_opts=realign_opts,
            t_start=t_start, fleet=fleet)
    # shape buckets / device feed / autotuner for every pass's chunk
    # cycle — replaces the per-pass pad_bucket closures (whose power-of-
    # two buckets each pass re-derived independently)
    from .executor import StreamExecutor
    ex = StreamExecutor(mesh, chunk_rows, **(executor_opts or {}))

    raw_path = input_path if is_parquet else os.path.join(workdir, "raw")

    try:
        # ---- pass 1: ingest ------------------------------------------------
        from ..models.dictionary import SequenceRecord
        if ck is not None and ck.has("p1"):
            m1 = ck.meta("p1")
            total_rows = m1["total_rows"]
            max_rgid = m1["max_rgid"]
            bucket_len = m1["bucket_len"]
            seq_dict = SequenceDictionary(
                SequenceRecord(i, nm, ln or 0, u)
                for i, nm, ln, u in m1["seq_records"])
            dup = ck.load_array("dup") if m1["has_dup"] else None
            p1_skipped = True
        else:
            p1_skipped = False
        if ck is not None and not p1_skipped:
            ck.clean_unless("p1", "raw", "dup.npy")
        pex1 = ex.begin_pass("p1")
        if p1_skipped:
            stream = []
        else:
            # the I/O ledger counts the input's on-disk bytes (recorded
            # by the stream opener) as pass 1's decoded input
            with obs.ioledger.pass_scope("p1"):
                stream = open_read_stream(input_path,
                                          chunk_rows=pex1.chunk_rows,
                                          io_procs=io_procs)
        keys = _MarkdupKeys(mesh) if (markdup and not p1_skipped) else None
        seq_seen: dict = {}
        raw_writer = None if (is_parquet or p1_skipped) else DatasetWriter(
            raw_path, part_rows=chunk_rows, io_pass="p1", **wopts)
        if not p1_skipped:
            total_rows = 0
            max_rgid = -1
            bucket_len = 0
        import pyarrow.compute as pc

        from ..packing import len_bucket

        def grow_bucket(table):
            # grow the length bucket BEFORE packing — a later chunk may
            # hold a longer read than anything seen so far.  Runs in
            # strict chunk order (main thread, or the pipelined reader's
            # prepare hook), so chunk i's pack sees max(len) over <= i
            # exactly like the sequential walk.  Buckets come from the
            # canonical 128-multiple ladder (packing.len_bucket), so a
            # marginally longer late read reuses a compiled [N, L] shape.
            nonlocal bucket_len
            chunk_max = pc.max(pc.binary_length(
                table.column("sequence"))).as_py() or 1
            bucket_len = max(bucket_len, len_bucket(chunk_max))
            return bucket_len

        def p1_pack(table, blen):
            if keys is None:
                return table, None
            padded = pex1.pad_rows(table.num_rows, blen)
            return table, pack_reads(
                table, pad_rows_to=padded, bucket_len=blen)

        track_len = keys is not None or bqsr
        use_p1_feed = keys is not None and pex1.prefetch_depth > 0
        if io_threads > 1 and not p1_skipped:
            # no pack / no length tracking still overlaps: the reader
            # thread performs the format decode (fn degrades to pack-less
            # passthrough, prepare to a no-op)
            from .ingest import pipelined
            p1_base = pipelined(stream, p1_pack, io_threads,
                                prepare=grow_bucket if track_len else None)
            p1_iter = timed_chunks(p1_base, "p1-ingest-wait")
        else:
            # staged even when the device feed's feeder thread drives
            # this generator: the stage stack is per-thread, so
            # p1-decode/p1-pack land on the feeder's own lane (the PR 3
            # unstaged workaround is gone)
            def p1_sync():
                for table in timed_chunks(stream, "p1-decode"):
                    batch = None
                    if track_len:
                        grow_bucket(table)
                    if keys is not None:
                        with stage("p1-pack"):
                            _, batch = p1_pack(table, bucket_len)
                    yield table, batch
            p1_iter = p1_sync()
        if use_p1_feed:
            # device feed: the markdup-key batch ships (projected to the
            # columns the key kernel reads) up to prefetch_depth chunks
            # ahead of the kernel dispatch; add_chunk detects the
            # pre-sharded batch and skips its own put.  The consumer's
            # stall is timed as p1-feed-wait (stage only — the staged
            # producer already counted every chunk once)
            p1_sharding = reads_sharding(mesh)

            def _p1_put(item):
                table, batch = item
                if batch is not None and \
                        batch.n_reads % mesh.size == 0:
                    proj = _project_batch(batch, _P1_DEV_COLS)
                    batch = pex1.dispatch_put(
                        "batch",
                        lambda attempt: proj.device_put(p1_sharding))
                return table, batch
            p1_iter = waited(pex1.feed(p1_iter, _p1_put), "p1-feed-wait")
        for table, batch in p1_iter:
            total_rows += table.num_rows
            max_rgid = max(max_rgid,
                           int(column_int64(table, "recordGroupId")
                               .max(initial=-1)))
            _accumulate_seq_records(table, seq_seen)
            if raw_writer is not None:
                with stage("p1-spill"):
                    raw_writer.write(table)
            if keys is not None:
                with stage("p1-markdup-keys", sync=True):
                    keys.add_chunk(
                        table, batch, pex=pex1,
                        # retry/fallback source when the fed device
                        # batch was consumed by a failed attempt
                        repack=lambda t=table: pack_reads(
                            t, pad_rows_to=pex1.pad_rows(
                                t.num_rows, bucket_len),
                            bucket_len=bucket_len))
        if raw_writer is not None:
            raw_writer.close()
        if not p1_skipped:
            seq_dict = stream.seq_dict or \
                SequenceDictionary(seq_seen.values())
            with stage("markdup-decide"):
                dup = keys.decide() if keys is not None else None
            if ck is not None:
                if dup is not None:
                    ck.save_array("dup", dup)
                ck.mark("p1", total_rows=total_rows, max_rgid=max_rgid,
                        bucket_len=bucket_len, has_dup=dup is not None,
                        seq_records=[[r.id, r.name, r.length, r.url]
                                     for r in seq_dict])

        def reread(rows=chunk_rows, io_pass=None, columns=None):
            # a re-streamed pass may use its own (autotuned) chunk size:
            # dup-bit offsets track rows, and every per-chunk consumer is
            # an exact monoid or per-row map, so re-chunking never
            # changes results (differential-pinned).  Each re-stream
            # counts the spill's on-disk bytes as the pass's re-read I/O
            # (the ledger's "decode the bytes once" denominator): one
            # record per invocation, from the Parquet footers — never
            # from the data.  A projected re-read charges only the
            # projected columns' compressed bytes (the honest-accounting
            # currency of the fusion gauge; ioledger.dataset_bytes).
            if io_pass is not None:
                obs.ioledger.record(
                    "reread",
                    obs.ioledger.dataset_bytes(raw_path, columns),
                    io_pass)
            offset = 0
            for table in iter_tables(raw_path, chunk_rows=rows,
                                     columns=columns):
                if dup is not None:
                    table = _apply_dup_bits(
                        table, dup[offset:offset + table.num_rows])
                offset += table.num_rows
                yield table

        # ---- pass 2: BQSR table -------------------------------------------
        # count tensors accumulate on device (async dispatch): the host's
        # decode/pack/mismatch-state of chunk i+1 overlaps the device count
        # of chunk i; one bounded sync every few chunks caps the in-flight
        # queue.  The RecalTable materializes once at pass end.
        rt = None
        if bqsr and ck is not None and ck.has("p2"):
            rt = _recal_from_ck(ck)
        elif bqsr:
            from ..platform import is_tpu_backend
            # Bounded async on accelerators: the host's decode/pack/
            # mismatch-state of chunk i+1 overlaps the device count of
            # chunk i.  The drain folds the int32 device tables into host
            # int64 via np.asarray — a REAL round trip (the tunnel
            # backend's block_until_ready is a no-op), which both caps the
            # in-flight queue and keeps the int32 accumulation window to a
            # few chunks (a whole-pass int32 sum would wrap on WGS-scale
            # inputs).  On the CPU backend overlap buys nothing — sync
            # every chunk keeps the stage report attribution exact.
            pex2 = ex.begin_pass(
                "p2", bytes_per_row=2.0 * max(bucket_len, 1) + 64.0,
                ragged_capable=True, paged_capable=True,
                mega_capable=True,
                sync_every=4 if is_tpu_backend() else 1)
            rt = _count_stream(
                pex2,
                _feed_packed(reread(pex2.chunk_rows, io_pass="p2"),
                             pex2, io_threads, pack_reads, bucket_len,
                             timed_chunks, mesh, _p2_dev_cols(pex2),
                             feed_wait=waited),
                snp_table=snp_table, n_rg_run=max(max_rgid + 1, 1),
                bucket_len=bucket_len, mesh=mesh)
            if ck is not None:
                _save_recal(ck, rt, "p2")

        # ---- pass 3: emit / route to bins ---------------------------------
        binned = sort or realign
        p3_skipped = binned and ck is not None and ck.has("p3")
        if p3_skipped:
            # the resolved bin count depends on mesh.size when defaulted;
            # a resume on different hardware must honor the count the
            # checkpointed bins were actually routed with
            n_bins = ck.meta("p3")["n_bins"]
        if binned:
            if n_bins is None:
                n_bins = max(int(np.ceil(total_rows / max(chunk_rows, 1))),
                             mesh.size)
            part = GenomicRegionPartitioner.from_dictionary(n_bins, seq_dict)
            bin_part_rows = max(chunk_rows // n_bins, 1 << 14)
            if p3_skipped:
                m3 = ck.meta("p3")
                bin_writers = [
                    _BinStub(os.path.join(workdir, f"bin-{b:05d}"), r)
                    for b, r in enumerate(m3["bin_rows"])]
                halo_writers = {
                    int(b): _BinStub(
                        os.path.join(workdir, f"halo-{int(b):05d}"), r)
                    for b, r in m3["halo_rows"].items()}
            else:
                if ck is not None:
                    ck.clean_unless("p3", "bin-*", "halo-*")
                bin_writers = [
                    DatasetWriter(os.path.join(workdir, f"bin-{b:05d}"),
                                  part_rows=bin_part_rows, io_pass="p3",
                                  **wopts)
                    for b in range(part.num_partitions)]
                halo_writers: dict = {}
        out_part_rows = chunk_rows if coalesce is None else \
            max(1, -(-total_rows // max(coalesce, 1)))
        if ck is not None and os.path.isdir(output_path):
            # idempotent rerun: stale parts from an interrupted emit would
            # otherwise survive next to the fresh ones
            for f in os.listdir(output_path):
                if f.endswith(".parquet"):
                    os.unlink(os.path.join(output_path, f))
        out = DatasetWriter(output_path, part_rows=out_part_rows,
                            row_group_bytes=row_group_bytes, **wopts)
        pex3 = ex.begin_pass(
            "p3", bytes_per_row=2.0 * max(bucket_len, 1) + 64.0)
        p3_iter = _feed_packed([] if p3_skipped else
                               reread(pex3.chunk_rows, io_pass="p3"),
                               pex3, io_threads, pack_reads, bucket_len,
                               timed_chunks, mesh, _P3_DEV_COLS,
                               want_pack=bqsr, feed_wait=waited)
        def _p3_cpu_fallback(table, batch):
            # degraded per-chunk CPU fallback: the unsharded LUT apply
            # pinned to the CPU backend (a per-row integer map — the
            # slab/sharded forms are bit-identical by construction)
            import jax
            with jax.default_device(jax.devices("cpu")[0]):
                return apply_table(rt, table, batch, mesh=None)

        for table, batch, dev_batch in p3_iter:
            if bqsr:
                with stage("p3-bqsr-apply", sync=True):
                    table = pex3.dispatch(
                        "apply",
                        lambda attempt, t=table, b=batch, d=dev_batch:
                            apply_table(
                                rt, t, b, mesh=mesh,
                                device_batch=d if attempt == 1 else None,
                                donate=pex3.donate and attempt == 1),
                        fallback=lambda e, t=table, b=batch:
                            _p3_cpu_fallback(t, b))
            if not binned:
                with stage("p3-write"):
                    out.write(table)
                continue
            with stage("p3-route"):
                _route_chunk(table, part, bin_writers, halo_writers,
                             realign, workdir, bin_part_rows, wopts)

        # ---- pass 4: per-bin realign/sort through the merge window --------
        if binned:
            if not p3_skipped:
                for w in bin_writers:
                    w.close()
                for w in halo_writers.values() if realign else ():
                    w.close()
                if ck is not None:
                    ck.mark("p3", n_bins=n_bins,
                            bin_rows=[w.rows_written for w in bin_writers],
                            halo_rows={str(b): w.rows_written
                                       for b, w in halo_writers.items()})
            budget = max_bin_rows if max_bin_rows is not None \
                else 4 * chunk_rows
            with stage("p4-bins", sync=True):
                _emit_bins(out, bin_writers,
                           halo_writers if realign else {}, part,
                           chunk_rows, budget, realign, sort, wopts,
                           realign_opts=realign_opts,
                           retry_policy=ex.retry_policy)
        out.close()
        if ck is not None:
            ck.mark("done", total_rows=total_rows)
        ex.finish()
        obs.run_totals("transform", total_rows,
                       _time.perf_counter() - t_start,
                       input_path=input_path, output_path=output_path)
        # per-pass io_ledger events + the spill-amplification gauge —
        # the number ROADMAP item 1's fusion refactor exists to move
        obs.ioledger.emit_events()
        return total_rows
    finally:
        if own_workdir:
            shutil.rmtree(workdir, ignore_errors=True)
        elif raw_path != input_path and ck is None:
            # checkpointed runs keep the spill: it IS the resume state
            shutil.rmtree(raw_path, ignore_errors=True)


def _timed_chunks(it, name, count=True):
    """Attribute an iterator's own work (format decode / parquet scan)
    to a named stage, chunk by chunk; each chunk also lands in the
    metrics plane (chunk_rows/bytes_in + a JSONL chunk event) unless
    ``count=False``.  The pipelined paths yield (table, ...) tuples,
    the sync paths bare tables — account the table either way.  ONE
    implementation serves the legacy and fused transforms, so a chunk-
    accounting fix can never diverge between them."""
    from ..instrument import stage

    it = iter(it)
    while True:
        with stage(name):
            try:
                item = next(it)
            except StopIteration:
                return
        if count:
            table = item[0] if isinstance(item, tuple) else item
            obs.chunk_processed(name, table.num_rows,
                                bytes_in=table.nbytes)
        yield item


def _feed_wait(it, name):
    """Stage-only stall attribution for the consumer side of the device
    feed (``<pass>-feed-wait``): times the wait, records NO chunk event
    — the staged producer already counted each chunk once on its own
    thread."""
    return _timed_chunks(it, name, count=False)


def _count_stream(pex, fed_iter, *, snp_table, n_rg_run, bucket_len,
                  mesh, md_info_fn=None):
    """The RecalTable count loop shared by legacy pass 2 and fused
    stream 2 (ONE implementation, like ``_timed_chunks``): bounded-async
    device accumulation — ``sync_every`` folds the int32 device tables
    into host int64 (exact integer monoid, so the fold cadence and the
    chunk source can differ without changing a bit) — with the per-chunk
    retry ladder and a host-bincount CPU fallback, materializing the
    RecalTable once at pass end.  ``md_info_fn(table)`` supplies the
    fused layout's hoisted MD events; None means parse MD from the
    table (the legacy path)."""
    import jax

    from ..bqsr.recalibrate import (_COUNT_IMPL_ENV, count_tables_device,
                                    tables_to_recal)
    from ..bqsr.table import RecalTable
    from ..instrument import stage

    count_stage = f"{pex.pass_name}-bqsr-count"

    def cpu_fallback(table, batch, md_info):
        # degraded per-chunk CPU fallback: the host bincount oracle
        # (bqsr.recalibrate's "host" impl — exact integer counts, kept
        # selectable as a differential oracle) with every jax op pinned
        # to the CPU backend
        old = os.environ.get(_COUNT_IMPL_ENV)
        os.environ[_COUNT_IMPL_ENV] = "host"
        try:
            with jax.default_device(jax.devices("cpu")[0]):
                out = count_tables_device(
                    table, batch, snp_table, n_read_groups=n_rg_run,
                    mesh=None, md_info=md_info)
        finally:
            if old is None:
                os.environ.pop(_COUNT_IMPL_ENV, None)
            else:
                os.environ[_COUNT_IMPL_ENV] = old
        return tuple(np.asarray(a) for a in out)

    def fold(into, out):
        folded = tuple(np.asarray(a).astype(np.int64) for a in out)
        return folded if into is None else tuple(
            h + f for h, f in zip(into, folded))

    host_acc = None
    acc = None
    n_counted = 0
    # paged layout: one resident plane pool shared by every chunk of
    # this pass (parallel/pagedbuf; sized lazily by the first chunk's
    # rung) — count_tables_device routes the flat planes through it and
    # falls back to the ragged concat when the pool would thrash
    paged_box = None
    if pex.layout == "paged":
        paged_box = {"pass": pex.pass_name, "put": pex.dispatch_put}
    # fused_device plan dimension: route the count through the mega-pass
    # bqsr leg (ops/megapass — the SAME pack + fold jits, composed under
    # one program).  Retries and the CPU fallback stay unfused: a chunk
    # that failed under the fused program re-runs the plain kernels.
    fused = pex.fused_device
    for table, batch, dev_batch in fed_iter:
        md_info = None if md_info_fn is None else md_info_fn(table)
        will_sync = (n_counted + 1) % pex.sync_every == 0
        with stage(count_stage, sync=will_sync):
            out = pex.dispatch(
                "count",
                lambda attempt, t=table, b=batch, d=dev_batch,
                mi=md_info:
                    count_tables_device(
                        t, b, snp_table, n_read_groups=n_rg_run,
                        mesh=mesh,
                        device_batch=d if attempt == 1 else None,
                        donate=pex.donate and attempt == 1,
                        md_info=mi, layout=pex.layout,
                        paged_box=paged_box if attempt == 1 else None,
                        fused=fused and attempt == 1),
                fallback=lambda e, t=table, b=batch, mi=md_info:
                    cpu_fallback(t, b, mi))
            if isinstance(out[0], np.ndarray):
                # a degraded chunk's host counts fold straight into the
                # host accumulator — never back onto a device that just
                # failed
                host_acc = fold(host_acc, out)
            else:
                acc = out if acc is None else tuple(
                    a + b for a, b in zip(acc, out))
            n_counted += 1
            if will_sync and acc is not None:
                host_acc = fold(host_acc, acc)
                acc = None
    if acc is not None:
        host_acc = fold(host_acc, acc)
    if host_acc is None:
        return RecalTable(n_read_groups=1, max_read_len=bucket_len or 1)
    with stage(count_stage, sync=True):
        return tables_to_recal(host_acc, n_rg_run, bucket_len or 1)


def _recal_from_ck(ck) -> "RecalTable":
    """Restore a checkpointed RecalTable (the p2/s2 marker's arrays)."""
    from ..bqsr.table import RecalTable

    z = ck.load_arrays("recal")
    return RecalTable(
        n_read_groups=int(z["n_read_groups"]),
        max_read_len=int(z["max_read_len"]),
        qual_obs=z["qual_obs"], qual_mm=z["qual_mm"],
        cycle_obs=z["cycle_obs"], cycle_mm=z["cycle_mm"],
        ctx_obs=z["ctx_obs"], ctx_mm=z["ctx_mm"],
        expected_mismatch=float(z["expected_mismatch"]))


def _save_recal(ck, rt, marker: str) -> None:
    ck.save_arrays(
        "recal", n_read_groups=rt.n_read_groups,
        max_read_len=rt.max_read_len, qual_obs=rt.qual_obs,
        qual_mm=rt.qual_mm, cycle_obs=rt.cycle_obs,
        cycle_mm=rt.cycle_mm, ctx_obs=rt.ctx_obs, ctx_mm=rt.ctx_mm,
        expected_mismatch=rt.expected_mismatch)
    ck.mark(marker)


def _prescan_seq_dict(input_path: str, chunk_rows: int):
    """Parquet inputs carry no header: recover the sequence dictionary
    from a PROJECTED pre-scan of the denormalized dictionary columns
    (first-appearance order, exactly `_accumulate_seq_records` over the
    stream — so the fused router's bins equal the legacy pass-3 bins).
    Counted as decoded input at its projected size."""
    from ..io.parquet import iter_tables
    from ..models.dictionary import SequenceDictionary

    cols = ["referenceId", "referenceName", "referenceLength",
            "referenceUrl", "mateReferenceId", "mateReference",
            "mateReferenceLength", "mateReferenceUrl"]
    obs.ioledger.record("decoded",
                        obs.ioledger.dataset_bytes(input_path, cols), "s1")
    seen: dict = {}
    for t in iter_tables(input_path, chunk_rows=chunk_rows, columns=cols):
        _accumulate_seq_records(t, seen)
    return SequenceDictionary(seen.values())


def _fused_transform(input_path: str, output_path: str, *, plan: dict,
                     markdup: bool, bqsr: bool, snp_table, realign: bool,
                     sort: bool, workdir: str, own_workdir: bool, ck,
                     mesh, chunk_rows: int, n_bins: Optional[int],
                     coalesce: Optional[int], max_bin_rows: Optional[int],
                     wopts: dict, row_group_bytes: Optional[int],
                     io_threads: int, io_procs: int,
                     executor_opts: Optional[dict],
                     realign_opts: Optional[dict], t_start: float,
                     fleet: Optional[dict] = None) -> int:
    """The fused dataflow of :func:`streaming_transform` (plan mode
    ``fused``): one decode of the input drives ALL chunk-local work, and
    only the two genuine barriers — the markdup decision and the
    RecalTable finalize — materialize state.

      stream 1  decode each chunk ONCE: markdup key columns on device,
                MD mismatch events parsed into the compact host store,
                rows routed straight to genome bins (+halos, +__ridx)
                when binned — no raw spill at all — or spilled in the
                ReadBatch wire format (io/wirespill) when a later
                stream must re-read them;
      barrier   markdup decision over the compact keys;
      stream 2  (BQSR only) accumulate the RecalTable over a PROJECTED
                re-read — the own-bins walk (binned; readName/MD/mate
                columns never leave disk) or the wire-plane subset of
                the spill — joining dup bits and MD events back by
                ``__ridx``;
      barrier   RecalTable finalize;
      pass 4 /  bins: dup bits + the DEFERRED LUT qual apply happen at
      stream 3  bin load (on the realign engine's prep pool, overlapped
                with sweeps), then realign/sort/emit exactly as legacy;
                unbinned: one emit walk rebuilds rows from the wire
                planes, applies dup bits + LUT, and writes the output.

    Byte-identical to the legacy 4-pass chain across the whole flag
    matrix (tests/test_fusion.py): routing reads only flags/refid/start
    (untouched by either barrier), the count is an exact integer monoid
    (bin order == chunk order under addition), and the LUT apply is a
    pure per-row map (applying it per-bin instead of per-chunk cannot
    change a byte).
    """
    import time as _time

    from ..instrument import stage
    from ..io.parquet import DatasetWriter
    from ..io.stream import open_read_stream
    from ..models.dictionary import SequenceDictionary, SequenceRecord
    from ..packing import len_bucket, pack_reads
    from .executor import StreamExecutor
    from .partitioner import GenomicRegionPartitioner

    import pyarrow.compute as pc

    binned = plan["binned"]
    carry_ridx = plan["carry_ridx"]
    wire_spill = plan["wire_spill"]
    direct_emit = plan["direct_emit"]
    is_parquet = plan["inputs"]["is_parquet"]

    ex = StreamExecutor(mesh, chunk_rows, **(executor_opts or {}))
    raw_path = input_path if is_parquet else os.path.join(workdir, "raw")

    try:
        # ---- stream 1: decode once -----------------------------------
        s1_skipped = ck is not None and ck.has("s1")
        if s1_skipped:
            m1 = ck.meta("s1")
            total_rows = m1["total_rows"]
            max_rgid = m1["max_rgid"]
            bucket_len = m1["bucket_len"]
            seq_dict = SequenceDictionary(
                SequenceRecord(i, nm, ln or 0, u)
                for i, nm, ln, u in m1["seq_records"])
            dup = ck.load_array("dup") if m1["has_dup"] else None
            mdstore = _MdEventStore.load(ck) if m1.get("has_md") else None
            if binned:
                n_bins = m1["n_bins"]
                part = GenomicRegionPartitioner.from_dictionary(
                    n_bins, seq_dict)
                bin_part_rows = max(chunk_rows // n_bins, 1 << 14)
                bin_writers = [
                    _BinStub(os.path.join(workdir, f"bin-{b:05d}"), r)
                    for b, r in enumerate(m1["bin_rows"])]
                halo_writers = {
                    int(b): _BinStub(
                        os.path.join(workdir, f"halo-{int(b):05d}"), r)
                    for b, r in m1["halo_rows"].items()}
        else:
            if ck is not None:
                ck.clean_unless("s1", "bin-*", "halo-*", "raw",
                                "dup.npy", "mdinfo.npz")
            pex1 = ex.begin_pass("s1", mega_capable=markdup)
            with obs.ioledger.pass_scope("s1"):
                stream = open_read_stream(input_path,
                                          chunk_rows=pex1.chunk_rows,
                                          io_procs=io_procs)
            keys = _MarkdupKeys(mesh) if markdup else None
            mdstore = _MdEventStore() if bqsr else None
            seq_seen: dict = {}
            total_rows = 0
            max_rgid = -1
            bucket_len = 0
            track_len = keys is not None or bqsr or wire_spill

            from ..io.wirespill import to_wire

            def grow_bucket(table):
                nonlocal bucket_len
                chunk_max = pc.max(pc.binary_length(
                    table.column("sequence"))).as_py() or 1
                bucket_len = max(bucket_len, len_bucket(chunk_max))
                return bucket_len

            def s1_work(table, blen):
                batch = None
                if keys is not None:
                    padded = pex1.pad_rows(table.num_rows, blen)
                    batch = pack_reads(table, pad_rows_to=padded,
                                       bucket_len=blen)
                wire = to_wire(table, blen) if wire_spill else None
                return table, batch, wire

            if binned:
                if n_bins is None:
                    est = _estimate_input_rows(input_path, chunk_rows)
                    n_bins = max(int(np.ceil(est / max(chunk_rows, 1))),
                                 mesh.size)
                # the router needs the dictionary BEFORE the scan: the
                # SAM/BAM header carries it; Parquet inputs pre-scan
                # their (tiny) projected dictionary columns
                seq_route = stream.seq_dict or (
                    _prescan_seq_dict(input_path, chunk_rows)
                    if is_parquet else SequenceDictionary(()))
                part = GenomicRegionPartitioner.from_dictionary(
                    n_bins, seq_route)
                bin_part_rows = max(chunk_rows // n_bins, 1 << 14)
                bin_writers = [
                    DatasetWriter(os.path.join(workdir, f"bin-{b:05d}"),
                                  part_rows=bin_part_rows, io_pass="s1",
                                  **wopts)
                    for b in range(part.num_partitions)]
                halo_writers: dict = {}
            raw_writer = None
            direct_out = None
            if wire_spill:
                raw_writer = DatasetWriter(raw_path, part_rows=chunk_rows,
                                           io_pass="s1", **wopts)
            elif direct_emit and not binned:
                if ck is not None:
                    _purge_stale_parts(output_path)
                direct_out = DatasetWriter(
                    output_path, part_rows=chunk_rows,
                    row_group_bytes=row_group_bytes, **wopts)

            if io_threads > 1:
                from .ingest import pipelined
                s1_base = pipelined(stream, s1_work, io_threads,
                                    prepare=grow_bucket if track_len
                                    else None)
                s1_iter = _timed_chunks(s1_base, "s1-ingest-wait")
            else:
                def s1_sync():
                    for table in _timed_chunks(stream, "s1-decode"):
                        if track_len:
                            grow_bucket(table)
                        if keys is not None or wire_spill:
                            with stage("s1-pack"):
                                item = s1_work(table, bucket_len)
                        else:
                            item = (table, None, None)
                        yield item
                s1_iter = s1_sync()
            if keys is not None and pex1.prefetch_depth > 0:
                s1_sharding = reads_sharding(mesh)

                def _s1_put(item):
                    table, batch, wire = item
                    if batch is not None and \
                            batch.n_reads % mesh.size == 0:
                        proj = _project_batch(batch, _P1_DEV_COLS)
                        batch = pex1.dispatch_put(
                            "batch",
                            lambda attempt: proj.device_put(s1_sharding))
                    return table, batch, wire
                s1_iter = _feed_wait(pex1.feed(s1_iter, _s1_put),
                                     "s1-feed-wait")

            ridx_base = 0
            for table, batch, wire in s1_iter:
                n = table.num_rows
                max_rgid = max(max_rgid,
                               int(column_int64(table, "recordGroupId")
                                   .max(initial=-1)))
                _accumulate_seq_records(table, seq_seen)
                if mdstore is not None:
                    # the one MD parse of the run (stream 2 joins the
                    # events back by global row; its projection drops
                    # the MD column from the re-read entirely)
                    with stage("s1-md-events"):
                        mdstore.add_chunk(table)
                if keys is not None:
                    with stage("s1-markdup-keys", sync=True):
                        keys.add_chunk(
                            table, batch, pex=pex1,
                            repack=lambda t=table: pack_reads(
                                t, pad_rows_to=pex1.pad_rows(
                                    t.num_rows, bucket_len),
                                bucket_len=bucket_len))
                if binned:
                    routed = table
                    if carry_ridx:
                        routed = table.append_column(
                            RIDX_COL, pa.array(np.arange(
                                ridx_base, ridx_base + n), pa.int64()))
                    with stage("s1-route"):
                        _route_chunk(routed, part, bin_writers,
                                     halo_writers, realign, workdir,
                                     bin_part_rows, wopts, io_pass="s1")
                elif raw_writer is not None:
                    with stage("s1-spill"):
                        raw_writer.write(wire)
                elif direct_out is not None:
                    with stage("s1-write"):
                        direct_out.write(table)
                total_rows += n
                ridx_base += n
            if raw_writer is not None:
                raw_writer.close()
            if direct_out is not None:
                direct_out.close()
            if binned:
                for w in bin_writers:
                    w.close()
                for w in halo_writers.values():
                    w.close()
            seq_dict = stream.seq_dict or \
                SequenceDictionary(seq_seen.values())
            with stage("markdup-decide"):
                dup = keys.decide() if keys is not None else None
            if mdstore is not None:
                mdstore.freeze()
            # direct-emit runs never mark s1: their output IS the final
            # output, so the only honest resume points are "nothing"
            # (re-run the idempotent passthrough) and "done" — an s1
            # marker would let a crash between mark and done resume
            # into an emit-less run
            if ck is not None and not direct_emit:
                if dup is not None:
                    ck.save_array("dup", dup)
                if mdstore is not None:
                    mdstore.save(ck)
                meta = dict(total_rows=total_rows, max_rgid=max_rgid,
                            bucket_len=bucket_len,
                            has_dup=dup is not None,
                            has_md=mdstore is not None,
                            seq_records=[[r.id, r.name, r.length, r.url]
                                         for r in seq_dict])
                if binned:
                    meta.update(
                        n_bins=n_bins,
                        bin_rows=[w.rows_written for w in bin_writers],
                        halo_rows={str(b): w.rows_written
                                   for b, w in halo_writers.items()})
                ck.mark("s1", **meta)

        # ---- stream 2: RecalTable over a projected re-read -----------
        rt = None
        if bqsr and ck is not None and ck.has("s2"):
            rt = _recal_from_ck(ck)
        elif bqsr and fleet and int(fleet.get("hosts", 1)) > 1:
            # fleet count: stream 2 is the transform's one exact-monoid
            # re-stream, so it shards across worker processes and the
            # merged RecalTable — and therefore the output — is
            # byte-identical to the single-host count (shardstream's
            # per-unit commit/merge contract)
            rt = _fleet_count_pass(
                input_path, fleet=fleet, snp_table=snp_table, dup=dup,
                mdstore=mdstore, max_rgid=max_rgid,
                bucket_len=bucket_len)
            if ck is not None:
                _save_recal(ck, rt, "s2")
        elif bqsr:
            rt = _fused_count_pass(
                ex=ex, workdir=workdir, raw_path=raw_path, plan=plan,
                mesh=mesh, snp_table=snp_table, dup=dup, mdstore=mdstore,
                bin_writers=bin_writers if binned else None,
                max_rgid=max_rgid, bucket_len=bucket_len,
                io_threads=io_threads)
            if ck is not None:
                _save_recal(ck, rt, "s2")

        # ---- emit: pass 4 (binned) / stream 3 (unbinned) -------------
        out_part_rows = chunk_rows if coalesce is None else \
            max(1, -(-total_rows // max(coalesce, 1)))
        if direct_emit and not binned:
            pass                      # stream 1 already wrote the output
        elif binned:
            if ck is not None and os.path.isdir(output_path):
                _purge_stale_parts(output_path)
            out = DatasetWriter(output_path, part_rows=out_part_rows,
                                row_group_bytes=row_group_bytes, **wopts)
            budget = max_bin_rows if max_bin_rows is not None \
                else 4 * chunk_rows
            prepare = _fused_bin_prepare(
                dup, rt, mesh, bucket_len, ex.retry_policy) \
                if (carry_ridx or rt is not None) else None
            with stage("p4-bins", sync=True):
                _emit_bins(out, bin_writers,
                           halo_writers if realign else {}, part,
                           chunk_rows, budget, realign, sort, wopts,
                           realign_opts=realign_opts,
                           retry_policy=ex.retry_policy,
                           prepare=prepare)
            out.close()
        else:
            if ck is not None and os.path.isdir(output_path):
                _purge_stale_parts(output_path)
            _fused_emit_stream(
                ex=ex, raw_path=raw_path, output_path=output_path,
                plan=plan, mesh=mesh, dup=dup, rt=rt,
                bucket_len=bucket_len, out_part_rows=out_part_rows,
                row_group_bytes=row_group_bytes, wopts=wopts,
                io_threads=io_threads)
        if ck is not None:
            ck.mark("done", total_rows=total_rows)
        ex.finish()
        obs.run_totals("transform", total_rows,
                       _time.perf_counter() - t_start,
                       input_path=input_path, output_path=output_path)
        obs.ioledger.emit_events()
        return total_rows
    finally:
        if own_workdir:
            shutil.rmtree(workdir, ignore_errors=True)
        elif plan["wire_spill"] and ck is None:
            shutil.rmtree(raw_path, ignore_errors=True)


def _fused_count_pass(*, ex, workdir, raw_path, plan, mesh, snp_table,
                      dup, mdstore, bin_writers, max_rgid, bucket_len,
                      io_threads):
    """Stream 2: the BQSR RecalTable over the fused layout's ONE
    projected re-read — own-bins in genome order (binned; the count is
    an exact integer monoid, so bin order equals chunk order) or the
    wire spill / Parquet input (unbinned).  Dup bits and the stream-1
    MD events re-join by global row index; the projection never reads
    readName / MD / mate columns off disk.  The count loop itself is
    ``_count_stream`` — the same machinery legacy pass 2 runs."""
    from ..io.parquet import iter_tables
    from ..io.wirespill import WIRE_COLUMNS, pack_reads_wire
    from ..packing import pack_reads
    from ..platform import is_tpu_backend

    binned = plan["binned"]
    wire = plan["wire_spill"]
    pex2 = ex.begin_pass(
        "s2", bytes_per_row=2.0 * max(bucket_len, 1) + 64.0,
        ragged_capable=True, paged_capable=True, mega_capable=True,
        sync_every=4 if is_tpu_backend() else 1)
    scalar_cols = ["flags", "start", "recordGroupId", "cigar"]
    if snp_table is not None:
        scalar_cols.append("referenceName")
    if wire:
        s2_cols = scalar_cols + list(WIRE_COLUMNS)
    else:
        s2_cols = scalar_cols + ["sequence", "qual"]
    if binned:
        s2_cols = s2_cols + [RIDX_COL]

    def s2_chunks():
        if binned:
            for b, w in enumerate(bin_writers):
                if w.rows_written == 0:
                    continue
                obs.ioledger.record(
                    "reread",
                    obs.ioledger.dataset_bytes(w.path, s2_cols), "s2")
                for tbl in iter_tables(w.path, columns=s2_cols,
                                       chunk_rows=pex2.chunk_rows):
                    if dup is not None:
                        tbl = _apply_dup_bits(
                            tbl, dup[column_int64(tbl, RIDX_COL)])
                    yield tbl
            return
        obs.ioledger.record(
            "reread", obs.ioledger.dataset_bytes(raw_path, s2_cols),
            "s2")
        offset = 0
        for tbl in iter_tables(raw_path, columns=s2_cols,
                               chunk_rows=pex2.chunk_rows):
            n = tbl.num_rows
            tbl = tbl.append_column(
                RIDX_COL, pa.array(np.arange(offset, offset + n),
                                   pa.int64()))
            if dup is not None:
                tbl = _apply_dup_bits(tbl, dup[offset:offset + n])
            offset += n
            yield tbl

    if wire:
        def pack_fn(table, *, pad_rows_to=1, bucket_len=0):
            return pack_reads_wire(table, bucket_len=bucket_len,
                                   pad_rows_to=pad_rows_to)
    else:
        pack_fn = pack_reads
    return _count_stream(
        pex2,
        _feed_packed(s2_chunks(), pex2, io_threads, pack_fn, bucket_len,
                     _timed_chunks, mesh, _p2_dev_cols(pex2),
                     feed_wait=_feed_wait),
        snp_table=snp_table, n_rg_run=max(max_rgid + 1, 1),
        bucket_len=bucket_len, mesh=mesh,
        md_info_fn=None if mdstore is None else
        (lambda table: mdstore.md_info_for(
            column_int64(table, RIDX_COL))))


def _fleet_count_pass(input_path, *, fleet, snp_table, dup, mdstore,
                      max_rgid, bucket_len):
    """Stream 2, fleet-sharded (parallel/shardstream.py): the same
    projected Parquet re-read the single-host unbinned count walks,
    split into contiguous unit ranges across worker processes; per-unit
    count tensors merge through the RecalTable monoid.  Dup bits and
    the stream-1 MD event store ship once via the fleet dir and re-join
    per shard by global row index — exactly the ``__ridx`` joins of the
    single-host walk, keyed by unit offset instead of a carried column.
    """
    from ..resilience.retry import resolve_fleet_policy
    from .shardstream import fleet_bqsr_count

    snp_path = fleet.get("snp_path")
    if snp_table is not None and not snp_path:
        raise ValueError(
            "fleet transform needs the dbsnp PATH (workers rebuild the "
            "mask themselves); pass fleet={'snp_path': ...}")
    cols = ["flags", "start", "recordGroupId", "cigar"]
    if snp_table is not None:
        cols.append("referenceName")
    cols += ["sequence", "qual"]
    policy = resolve_fleet_policy(
        max_restarts=fleet.get("max_restarts"),
        lease_ttl_s=fleet.get("lease_ttl_s"),
        redistribute=fleet.get("redistribute"),
        speculate=fleet.get("speculate"))
    return fleet_bqsr_count(
        input_path, hosts=int(fleet["hosts"]),
        n_rg_run=max(max_rgid + 1, 1), bucket_len=bucket_len,
        columns=cols, dup=dup, mdstore=mdstore, snp_path=snp_path,
        unit_rows=fleet.get("unit_rows"),
        fleet_dir=fleet.get("fleet_dir"), policy=policy,
        env=fleet.get("env"),
        commit_every=int(fleet.get("commit_every", 1)),
        timeout_s=float(fleet.get("timeout_s", 900.0)))


def _fused_emit_stream(*, ex, raw_path, output_path, plan, mesh, dup, rt,
                       bucket_len, out_part_rows, row_group_bytes, wopts,
                       io_threads):
    """Stream 3 (fused, unbinned): rebuild rows from the wire spill (or
    re-read the Parquet input), apply dup bits + the deferred LUT qual
    rewrite, and write the output — the ONE full re-read of the fused
    unbinned layout.  The chunk cycle runs through ``_feed_packed``
    exactly like legacy pass 3 (pipelined ingest, prefetching device
    feed, ladder padding), so the executor pins — feed-wait
    attribution, inflight bound, shape ladder — hold unchanged under
    the new pass name."""
    import jax

    from ..bqsr.recalibrate import apply_table
    from ..instrument import stage
    from ..io.parquet import DatasetWriter, iter_tables
    from ..io.wirespill import from_wire
    from ..packing import pack_reads

    wire = plan["wire_spill"]
    pex3 = ex.begin_pass(
        "s3", bytes_per_row=2.0 * max(bucket_len, 1) + 64.0)
    out = DatasetWriter(output_path, part_rows=out_part_rows,
                        row_group_bytes=row_group_bytes, **wopts)

    def s3_chunks():
        # one full re-read: rows rebuild exactly from the wire planes
        # (prefix bytes verbatim), dup bits join by stream offset
        obs.ioledger.record(
            "reread", obs.ioledger.dataset_bytes(raw_path), "s3")
        offset = 0
        for spill_tbl in iter_tables(raw_path,
                                     chunk_rows=pex3.chunk_rows):
            n = spill_tbl.num_rows
            if dup is not None:
                spill_tbl = _apply_dup_bits(spill_tbl,
                                            dup[offset:offset + n])
            offset += n
            yield from_wire(spill_tbl) if wire else spill_tbl

    s3_iter = _feed_packed(s3_chunks(), pex3, io_threads, pack_reads,
                           bucket_len, _timed_chunks, mesh, _P3_DEV_COLS,
                           want_pack=rt is not None,
                           feed_wait=_feed_wait)

    def _cpu_apply(table, batch):
        with jax.default_device(jax.devices("cpu")[0]):
            return apply_table(rt, table, batch, mesh=None)

    for table, batch, dev_batch in s3_iter:
        if rt is not None:
            with stage("s3-bqsr-apply", sync=True):
                table = pex3.dispatch(
                    "apply",
                    lambda attempt, t=table, b=batch, d=dev_batch:
                        apply_table(
                            rt, t, b, mesh=mesh,
                            device_batch=d if attempt == 1 else None,
                            donate=pex3.donate and attempt == 1),
                    fallback=lambda e, t=table, b=batch:
                        _cpu_apply(t, b))
        with stage("s3-write"):
            out.write(table)
    out.close()


def _fused_bin_prepare(dup, rt, mesh, bucket_len, retry_policy):
    """The fused pass-4 load hook: join dup bits back by ``__ridx``,
    strip the join column, and run the deferred BQSR LUT apply — a pure
    per-row map, so applying it per-bin (here) instead of per-chunk
    (legacy pass 3) is byte-identical.  Runs wherever the bin load runs
    (the realign engine's prep pool when pass 4 is pipelined), under
    the same retry/degrade ladder as every other device dispatch."""
    from ..packing import pack_reads, shape_rung
    from ..resilience.retry import dispatch_with_retry

    mult = max(getattr(mesh, "size", 1) or 1, 1)

    def prepare(tbl):
        if tbl is None:
            return None
        if RIDX_COL in tbl.column_names:
            if dup is not None and tbl.num_rows:
                tbl = _apply_dup_bits(tbl,
                                      dup[column_int64(tbl, RIDX_COL)])
            tbl = tbl.drop_columns([RIDX_COL])
        if rt is None or tbl.num_rows == 0:
            return tbl
        import jax

        from ..bqsr.recalibrate import apply_table

        # canonical rung padding (the realign sweep's shape discipline):
        # arbitrary bin sizes must not mint a fresh apply shape each
        batch = pack_reads(tbl,
                           pad_rows_to=shape_rung(max(tbl.num_rows, 1),
                                                  mult),
                           bucket_len=bucket_len)

        def run(attempt):
            return apply_table(rt, tbl, batch,
                               mesh=mesh if attempt == 1 else None)

        def fallback(err):
            with jax.default_device(jax.devices("cpu")[0]):
                return apply_table(rt, tbl, batch, mesh=None)

        with obs.trace.span("p4:apply", cat="dispatch"):
            return dispatch_with_retry(run, site="device_dispatch",
                                       label="p4:apply",
                                       policy=retry_policy,
                                       fallback=fallback)
    return prepare


def _route_chunk(table, part, bin_writers, halo_writers, realign, workdir,
                 bin_part_rows, wopts, io_pass="p3"):
    """Route one chunk's rows to their genome bins (+realign halos): the
    GenomicRegionPartitioner scatter shared by legacy pass 3 and the
    fused stream 1 (which routes at decode time, before dup bits — bin
    assignment reads only flags/refid/start, none of which any earlier
    barrier rewrites)."""
    from .. import schema as S

    flags = column_int64(table, "flags", 0)
    refid = column_int64(table, "referenceId")
    start = column_int64(table, "start")
    f_mapped = (flags & S.FLAG_UNMAPPED) == 0
    bins = part.partition(np.where(f_mapped, refid, -1),
                          np.maximum(start, 0))
    # flag-mapped reads with a null refid sort before every contig
    # (sort_order keys by flags, not refid) -> front bin
    bins = np.where(f_mapped & (refid < 0), 0, bins)
    for b in np.unique(bins):
        rows = np.flatnonzero(bins == b)
        bin_writers[int(b)].write(table.take(pa.array(rows)))
    if realign:
        _route_halo(table, bins, part, f_mapped & (refid >= 0),
                    refid, start, halo_writers, workdir,
                    bin_part_rows, wopts, io_pass=io_pass)


def _route_halo(table, bins, part, mapped_ok, refid, start, halo_writers,
                workdir, part_rows, wopts, io_pass="p3"):
    """Duplicate reads near a bin edge into the neighbor bins' halo sets
    (the rod-bucket trick, AdamRDDFunctions.scala:175-183): any bin whose
    range a read's ±halo window touches gets a copy, so edge-straddling
    realignment targets see full evidence on both sides."""
    import pyarrow.compute as pc

    from ..io.parquet import DatasetWriter

    if part.parts <= 1:
        return
    W = _REALIGN_HALO
    rows_m = np.flatnonzero(mapped_ok)
    if len(rows_m) == 0:
        return
    flat = part.flat(refid[rows_m], np.maximum(start[rows_m], 0))
    slen = pc.binary_length(table.column("sequence")).combine_chunks() \
        .fill_null(0).to_numpy(zero_copy_only=False)[rows_m]
    fend = flat + np.maximum(slen.astype(np.int64), 1)
    bfirst = part.bin_of_flat(np.maximum(flat - W, 0))
    blast = part.bin_of_flat(fend + W)
    own = bins[rows_m].astype(np.int64)
    cnt = blast - bfirst + 1
    rr = np.repeat(np.arange(len(rows_m)), cnt)
    offs = np.arange(int(cnt.sum())) - np.repeat(np.cumsum(cnt) - cnt, cnt)
    tgt = bfirst[rr] + offs
    keep = tgt != own[rr]
    rr, tgt = rr[keep], tgt[keep]
    for b2 in np.unique(tgt):
        sel = rows_m[rr[tgt == b2]]
        w = halo_writers.get(int(b2))
        if w is None:
            w = halo_writers[int(b2)] = DatasetWriter(
                os.path.join(workdir, f"halo-{int(b2):05d}"),
                part_rows=part_rows, io_pass=io_pass, **wopts)
        w.write(table.take(pa.array(sel)))


def _realign_with_halo(own: pa.Table, halo: Optional[pa.Table],
                       realign_indels) -> pa.Table:
    """Realign own+halo evidence together, emit only the own rows (realign
    preserves row order/count, so the own rows are the leading slice)."""
    if halo is None or halo.num_rows == 0:
        return realign_indels(own)
    u = pa.concat_tables([own, halo])
    return realign_indels(u).slice(0, own.num_rows)


def _flat_of_table(table: pa.Table, part) -> np.ndarray:
    refid = column_int64(table, "referenceId")
    start = column_int64(table, "start")
    return part.flat(refid, np.maximum(start, 0))


def _bin_unit_descs(path, halo_path, part, rows, chunk_rows, budget,
                    realign, next_lo, wopts):
    """Describe one mapped bin's schedulable pass-4 units lazily: one
    ``(load, next_lower_flat)`` pair for an in-budget bin, or one per
    position sub-range after the hot-bin quantile split.

    The split I/O runs during ITERATION (on the realign pipeline's reader
    thread when pass 4 is pipelined — overlapped with downstream sweeps
    and emits; see parallel/realign_exec.py), and each ``load()`` reads
    its unit's tables once and removes its sub-range spill, so in-flight
    host rows stay bounded at ~(pipeline depth + 2) x budget (depth + 1
    queued prepared units, one under prep, one being finished).
    """
    import glob as _glob
    import shutil as _shutil
    import tempfile as _tempfile
    import threading as _threading

    from ..io.parquet import DatasetWriter, iter_tables, load_table

    if rows <= budget:
        def load_small():
            # pass 4 re-reads the whole bin (+halo) spill — count its
            # on-disk bytes BEFORE the load (the engine may delete the
            # spill after materializing); runs on the realign pipeline's
            # reader thread, so attribution is explicit, not scoped
            obs.ioledger.record(
                "reread", obs.ioledger.path_bytes(path) +
                obs.ioledger.path_bytes(halo_path), "p4")
            halo = load_table(halo_path) if halo_path else None
            return load_table(path), halo
        yield load_small, next_lo
        return

    # hot bin: pick cut positions at row quantiles of the flat coordinate
    # (projection-only scan), then stream rows into sub-range writers with
    # their own ±halo duplication.  Ties collapse — a single position's
    # pileup can exceed the budget but a position cannot be split.
    for stale in _glob.glob(os.path.join(path, "hotbin_*")):
        _shutil.rmtree(stale, ignore_errors=True)   # a crashed prior split
    key_tbl = load_table(path, columns=["referenceId", "start"])
    flat_sorted = np.sort(_flat_of_table(key_tbl, part))
    del key_tbl
    k = int(np.ceil(rows / budget))
    cuts = np.unique(flat_sorted[np.minimum(
        np.arange(1, k) * budget, rows - 1)])
    lows = np.concatenate([[0], cuts])              # sub-range lower edges
    highs = np.concatenate([cuts, [np.iinfo(np.int64).max]])
    W = _REALIGN_HALO
    workdir_b = _tempfile.mkdtemp(prefix="hotbin_", dir=path)
    sub_own = [DatasetWriter(os.path.join(workdir_b, f"sub-{i:03d}"),
                             part_rows=budget, io_pass="p4", **wopts)
               for i in range(len(lows))]
    sub_halo = [DatasetWriter(os.path.join(workdir_b, f"subhalo-{i:03d}"),
                              part_rows=budget, io_pass="p4", **wopts)
                for i in range(len(lows))] if realign else []

    def route(tbl, is_halo_source):
        flat = _flat_of_table(tbl, part)
        if realign:         # fend only feeds the halo windows
            import pyarrow.compute as pc
            slen = pc.binary_length(tbl.column("sequence")) \
                .combine_chunks().fill_null(0) \
                .to_numpy(zero_copy_only=False).astype(np.int64)
            fend = flat + np.maximum(slen, 1)
        for i, (lo, hi) in enumerate(zip(lows, highs)):
            if not is_halo_source:
                sel = np.flatnonzero((flat >= lo) & (flat < hi))
                if len(sel):
                    sub_own[i].write(tbl.take(pa.array(sel)))
            if realign:
                osel = np.flatnonzero(
                    (fend + W > lo) & (flat - W < hi) &
                    (is_halo_source | (flat < lo) | (flat >= hi)))
                if len(osel):
                    sub_halo[i].write(tbl.take(pa.array(osel)))

    # the split streams the whole over-budget bin (+halo) once to route
    # it into sub-ranges: p4 re-read I/O (the quantile key pre-scan above
    # is a 2-column projection — a few % of the bin — and is not counted)
    obs.ioledger.record("reread", obs.ioledger.path_bytes(path) +
                        obs.ioledger.path_bytes(halo_path), "p4")
    for tbl in iter_tables(path, chunk_rows=chunk_rows):
        route(tbl, is_halo_source=False)
    if halo_path:
        for tbl in iter_tables(halo_path, chunk_rows=chunk_rows):
            route(tbl, is_halo_source=True)
    for i in range(len(lows)):
        sub_own[i].close()
        if realign:
            sub_halo[i].close()

    live = [i for i in range(len(lows)) if sub_own[i].rows_written]
    if not live:
        _shutil.rmtree(workdir_b, ignore_errors=True)
        return
    # loaders may execute concurrently (and complete out of order) on the
    # realign pipeline's prep pool — the split spill goes away when the
    # LAST of them has loaded, not when the last is issued
    remaining = [len(live)]
    rlock = _threading.Lock()
    for i in live:
        nxt = int(highs[i]) if i + 1 < len(lows) else next_lo

        def load_sub(i=i):
            obs.ioledger.record(
                "reread", obs.ioledger.path_bytes(sub_own[i].path) +
                (obs.ioledger.path_bytes(sub_halo[i].path)
                 if realign and sub_halo[i].rows_written else 0), "p4")
            own = load_table(sub_own[i].path)
            halo = load_table(sub_halo[i].path) \
                if realign and sub_halo[i].rows_written else None
            _shutil.rmtree(sub_own[i].path, ignore_errors=True)
            if realign:
                _shutil.rmtree(sub_halo[i].path, ignore_errors=True)
            with rlock:
                remaining[0] -= 1
                done = remaining[0] == 0
            if done:
                _shutil.rmtree(workdir_b, ignore_errors=True)
            return own, halo
        yield load_sub, nxt


def _wrap_load(load, prepare):
    """Compose a unit's lazy loader with the fused prepare hook (dup
    bits via ``__ridx`` + the deferred BQSR LUT apply): it runs where
    the load runs — the realign engine's prep pool when pass 4 is
    pipelined — so the rewrite overlaps sweeps exactly like the load
    itself."""
    if prepare is None:
        return load

    def wrapped():
        own, halo = load()
        return prepare(own), (None if halo is None else prepare(halo))
    return wrapped


def _emit_bins(out, bin_writers, halo_writers, part, chunk_rows, budget,
               realign, sort, wopts, realign_opts=None,
               retry_policy=None, prepare=None):
    """Pass 4 driver: process mapped bins in genome order, emitting sorted
    output through a merge window — realignment can move a read up to the
    halo width across a bin edge, so rows only emit once no later bin can
    produce a smaller sort key.

    ``prepare`` (fused transform): a per-table rewrite applied to every
    loaded bin/halo table (and the unmapped tail) BEFORE realign/sort —
    the deferred dup-bit + LUT qual apply, joined by the ``__ridx``
    column the fused stream 1 routed into the bins (stripped here, so
    downstream stages see the exact legacy schema).

    With realignment on, the bins run through the pipelined engine
    (parallel/realign_exec.py): bin i+1's load+prep overlaps bin i's
    sweeps and bin i-1's finish/emit, with sweep jobs from every in-flight
    bin batched by padded shape.  The engine changes scheduling only —
    emit order and bytes are identical to the serial walk (and
    ``-no_realign_pipeline`` / ``ADAM_TPU_REALIGN_PIPELINE=0`` forces the
    serial walk outright).
    """
    from .. import schema as S
    from ..instrument import stage
    from ..io.parquet import iter_tables
    from ..ops.sort import sort_reads

    pending: Optional[pa.Table] = None

    def emit_sorted(tbl, next_lower_flat):
        nonlocal pending
        with stage("merge-sort"):
            pending = tbl if pending is None else \
                sort_reads(pa.concat_tables([pending, tbl]))
        cutoff = next_lower_flat - _REALIGN_HALO
        flags = column_int64(pending, "flags", 0)
        flat = _flat_of_table(pending, part)
        safe = ((flags & S.FLAG_UNMAPPED) == 0) & (flat < cutoff)
        k = int(safe.sum())  # sorted => safe rows are a prefix
        if k:
            with stage("write"):
                out.write(pending.slice(0, k))
        pending = pending.slice(k) if k < pending.num_rows else None

    emit = emit_sorted if sort else (lambda tbl, nxt: out.write(tbl))

    # mapped bins in genome order; the last partition is the unmapped tail
    mapped = []
    for b, w in enumerate(bin_writers):
        if b == part.num_partitions - 1 or w.rows_written == 0:
            continue
        halo_w = halo_writers.get(b)
        halo_path = halo_w.path if halo_w is not None and \
            halo_w.rows_written else None
        next_lo = part.bin_lower_flat(b + 1) if b + 1 < part.parts \
            else part.total_length + _REALIGN_HALO
        mapped.append((b, w, halo_path, next_lo))

    plan = None
    if realign:
        from ..platform import is_tpu_backend
        from .realign_exec import (decide_realign_plan, emit_realign_plan,
                                   resolve_realign_opts)
        plan = decide_realign_plan(
            n_bins=part.num_partitions, on_tpu=is_tpu_backend(),
            **resolve_realign_opts(realign_opts))
        emit_realign_plan(plan)

    try:
        if plan is not None and plan["pipeline_depth"] > 0:
            from .realign_exec import BinUnitDesc, RealignEngine

            def units():
                for seq, (b, w, halo_path, next_lo) in enumerate(mapped):
                    for k, (load, nxt) in enumerate(_bin_unit_descs(
                            w.path, halo_path, part, w.rows_written,
                            chunk_rows, budget, True, next_lo, wopts)):
                        yield BinUnitDesc(b, (seq, k),
                                          _wrap_load(load, prepare), nxt)

            RealignEngine(plan, retry_policy=retry_policy).run(
                units(), emit, sort)
        else:
            from ..realign.realigner import realign_indels
            for b, w, halo_path, next_lo in mapped:
                for load, nxt in _bin_unit_descs(
                        w.path, halo_path, part, w.rows_written,
                        chunk_rows, budget, realign, next_lo, wopts):
                    own, halo = _wrap_load(load, prepare)()
                    tbl = _realign_with_halo(own, halo, realign_indels) \
                        if realign else own
                    if sort:
                        tbl = sort_reads(tbl)
                    emit(tbl, nxt)
    finally:
        # sub-range loaders normally consume and remove their own spill;
        # an abort between the hot-bin split and the last load must not
        # leak up to a bin budget of duplicated rows into the workdir
        # (the pre-pipeline code's per-bin try/finally, hoisted here)
        import glob as _glob
        import shutil as _shutil
        for _b, w, _h, _n in mapped:
            for stale in _glob.glob(os.path.join(w.path, "hotbin_*")):
                _shutil.rmtree(stale, ignore_errors=True)

    # unmapped tail: flush the merge window, then the stable unmapped rows
    if pending is not None:
        out.write(pending)
        pending = None
    uw = bin_writers[part.num_partitions - 1]
    if uw.rows_written:
        obs.ioledger.record("reread", obs.ioledger.path_bytes(uw.path),
                            "p4")
        for t in iter_tables(uw.path, chunk_rows=chunk_rows):
            # the fused prepare applies here too: unmapped rows need
            # their dup bits cleared/set and the (identity) LUT column
            # rebuild exactly like the legacy pass-3 chunk walk did
            out.write(t if prepare is None else prepare(t))


# ---------------------------------------------------------------------------
# streaming reads2ref
# ---------------------------------------------------------------------------


def _purge_stale_parts(output_path: str) -> None:
    """Remove pre-existing part files so a rerun that writes fewer parts
    does not leave the old run's tail mixed into the dataset."""
    if os.path.isdir(output_path):
        for f in os.listdir(output_path):
            if f.endswith(".parquet"):
                os.unlink(os.path.join(output_path, f))


def route_slices_to_dirs(table: pa.Table, key: np.ndarray, workdir: str,
                         chunk_i: int, dirs: dict, wopts: dict,
                         name_of) -> None:
    """Route a chunk's rows into per-key Parquet dirs: one argsort +
    boundary split (a per-unique-key scan is quadratic when a chunk
    touches thousands of keys), one immediately-closed file per
    (chunk, key) slice — no persistent writer handles or pending buffers
    (thousands of keys would exhaust fds and grow host RSS).  Shared by
    the streaming reads2ref window router and the streaming compare
    name-hash bucketer."""
    import pyarrow.parquet as _pq

    if len(key) == 0:
        return
    order = np.argsort(key, kind="stable")
    sk = key[order]
    bounds = np.flatnonzero(np.r_[True, sk[1:] != sk[:-1]])
    for bi, lo in enumerate(bounds):
        hi = bounds[bi + 1] if bi + 1 < len(bounds) else len(sk)
        k = int(sk[lo])
        d = dirs.get(k)
        if d is None:
            d = dirs[k] = os.path.join(workdir, name_of(k))
            os.makedirs(d, exist_ok=True)
        _pq.write_table(table.take(pa.array(order[lo:hi])),
                        os.path.join(d, f"chunk-{chunk_i:06d}.parquet"),
                        compression=wopts.get("compression", "zstd"),
                        data_page_size=wopts.get("page_size"),
                        use_dictionary=wopts.get("use_dictionary", True))



from contextlib import contextmanager


@contextmanager
def windowed_tables(tables_iter, *, window_bp: int = 1 << 20,
                    workdir: Optional[str] = None, wopts: dict = None,
                    prefix: str = "win", with_keys: bool = False):
    """Route (referenceId, position)-keyed tables into power-of-two genome
    windows on disk, then yield an iterator of per-window tables in genome
    order.  The single windowing engine behind streaming reads2ref
    -aggregate, mpileup, aggregate_pileups, and compute_variants —
    exact-position partitioning makes window-wise group-bys equal the
    global ones."""
    from ..io.parquet import load_table

    wopts = wopts or {}
    window_bits = max((window_bp - 1).bit_length(), 1)
    own = workdir is None
    if own:
        workdir = tempfile.mkdtemp(prefix="adam_tpu_window_")
    os.makedirs(workdir, exist_ok=True)
    import glob as _glob
    for stale in _glob.glob(os.path.join(workdir, prefix + "-*")):
        shutil.rmtree(stale, ignore_errors=True)   # a previous run's rows
    #                                                must not aggregate in
    win_dirs: dict = {}
    try:
        for chunk_i, table in enumerate(tables_iter):
            if not table.num_rows:
                continue
            refid = column_int64(table, "referenceId", -1)
            posi = column_int64(table, "position", -1)
            win = np.maximum(posi, 0) >> window_bits
            key = np.where(refid >= 0, refid * (1 << 40) + win, -1)
            route_slices_to_dirs(
                table, key, workdir, chunk_i, win_dirs, wopts,
                lambda k: f"{prefix}-{k & ((1 << 64) - 1):016x}")

        def windows():
            for k in sorted(win_dirs):
                t = load_table(win_dirs[k])
                yield (k, t) if with_keys else t

        yield windows()
    finally:
        if own:
            shutil.rmtree(workdir, ignore_errors=True)
        else:
            for d in win_dirs.values():
                shutil.rmtree(d, ignore_errors=True)


@contextmanager
def windowed_pileups(input_path: str, *, allow_non_primary: bool = False,
                     chunk_rows: int = 1 << 20, window_bp: int = 1 << 20,
                     workdir: Optional[str] = None, wopts: dict = None):
    """Spill a read stream's pileups into genome windows, then yield
    ``(n_reads, windows)`` where ``windows`` iterates per-window pileup
    tables in genome order.  Positions never cross a window, so per-window
    processing (aggregation, mpileup text) equals the global
    position-grouped traversal."""
    from ..io.parquet import locus_predicate
    from ..io.stream import open_read_stream
    from ..ops.pileup import reads_to_pileups

    filters = None if allow_non_primary else locus_predicate()
    # open the stream BEFORE creating a temp workdir: a bad path must not
    # leak a temp dir per failed invocation
    stream = open_read_stream(input_path, filters=filters,
                              chunk_rows=chunk_rows)
    counted = {"n": 0}

    def pileup_chunks():
        for table in stream:
            counted["n"] += table.num_rows
            yield reads_to_pileups(table)

    with windowed_tables(pileup_chunks(), window_bp=window_bp,
                         workdir=workdir, wopts=wopts) as wins:
        # the spill ran eagerly inside windowed_tables, so the count is
        # final by the time it yields
        yield counted["n"], wins


def streaming_reads2ref(input_path: str, output_path: str, *,
                        aggregate: bool = False,
                        allow_non_primary: bool = False,
                        chunk_rows: int = 1 << 20,
                        window_bp: int = 1 << 20,
                        workdir: Optional[str] = None,
                        compression: str = "zstd",
                        page_size: Optional[int] = None,
                        use_dictionary: bool = True,
                        row_group_bytes: Optional[int] = None
                        ) -> Tuple[int, int]:
    """``reads2ref`` over a bounded-memory chunk stream.

    The reference streams this through Spark executors by construction
    (Reads2Ref.scala:56-74: flatMap to pileups, optional groupBy-position
    aggregate); the in-memory path here loads the whole reads table.  This
    is the streaming form:

      * non-aggregated: pure map — each chunk's pileups append to the
        output dataset (the ~readLen× data amplification never lives in
        memory at once);
      * aggregated: pileup rows route to fixed-width genome windows
        (``window_bp`` positions each) in a Parquet workdir, then each
        window aggregates independently — grouping keys include the exact
        position, so window-partitioning by position is exact (no halo
        needed, unlike realignment's target groups), and window-wise
        aggregation equals the global groupBy restricted to that window.
        Host memory is bounded by window span x coverage, the same
        coverage-scaled budget the reference sizes reducers with
        (PileupAggregator.scala:204-209).

    Returns (n_reads, n_output_pileups).
    """
    from ..io.parquet import (DatasetWriter, load_table, locus_predicate)
    from ..io.stream import open_read_stream
    from ..ops.pileup import aggregate_pileups, reads_to_pileups

    wopts = dict(compression=compression, page_size=page_size,
                 use_dictionary=use_dictionary)
    _purge_stale_parts(output_path)
    out = DatasetWriter(output_path, part_rows=chunk_rows,
                        row_group_bytes=row_group_bytes, **wopts)
    n_out = 0

    if not aggregate:
        filters = None if allow_non_primary else locus_predicate()
        stream = open_read_stream(input_path, filters=filters,
                                  chunk_rows=chunk_rows)
        n_reads = 0
        for table in stream:
            n_reads += table.num_rows
            p = reads_to_pileups(table)
            n_out += p.num_rows
            out.write(p)
        out.close()
        return n_reads, n_out

    # windows emit in genome order ((refid, window) == sorted key) so the
    # output dataset reads back position-grouped
    with windowed_pileups(input_path, allow_non_primary=allow_non_primary,
                          chunk_rows=chunk_rows, window_bp=window_bp,
                          workdir=workdir, wopts=wopts) as (n_reads, wins):
        for wtbl in wins:
            agg = aggregate_pileups(wtbl)
            n_out += agg.num_rows
            out.write(agg)
    out.close()
    return n_reads, n_out


# ---------------------------------------------------------------------------
# streaming compute_variants
# ---------------------------------------------------------------------------

def streaming_compute_variants(input_path: str, output_base: str, *,
                               validate: bool = False, strict: bool = False,
                               chunk_rows: int = 1 << 20,
                               window_bp: int = 1 << 20,
                               workdir: Optional[str] = None,
                               compression: str = "zstd") -> Tuple[int, int]:
    """``compute_variants`` over a bounded-memory genotype stream.

    The reference's groupBy-position shuffle (AdamRDDFunctions.scala:
    422-434) becomes the shared windowed routing: variant synthesis is
    per (site, allele), and windows partition sites exactly, so
    window-wise conversion equals the global groupBy.  The genotypes copy
    through to ``<base>.g`` as they stream (the reference writes both
    datasets, ComputeVariants.scala:55-72).

    Returns (n_genotypes, n_variants).
    """
    from ..converters.genotypes_to_variants import convert_genotypes
    from ..io.parquet import DatasetWriter, iter_tables

    wopts = dict(compression=compression)
    _purge_stale_parts(output_base + ".v")
    _purge_stale_parts(output_base + ".g")
    v_out = DatasetWriter(output_base + ".v", part_rows=chunk_rows, **wopts)
    g_out = DatasetWriter(output_base + ".g", part_rows=chunk_rows, **wopts)
    counted = {"n": 0}

    def chunks():
        for table in iter_tables(input_path, chunk_rows=chunk_rows):
            counted["n"] += table.num_rows
            g_out.write(table)
            yield table

    n_var = 0
    with windowed_tables(chunks(), window_bp=window_bp, workdir=workdir,
                         wopts=wopts, prefix="gwin") as wins:
        g_out.close()
        for wtbl in wins:
            variants = convert_genotypes(wtbl, validate=validate,
                                         strict=strict)
            n_var += variants.num_rows
            v_out.write(variants)
    v_out.close()
    return counted["n"], n_var


def streaming_aggregate_pileups(input_path: str, output_path: str, *,
                                chunk_rows: int = 1 << 20,
                                window_bp: int = 1 << 20,
                                workdir: Optional[str] = None,
                                compression: str = "zstd",
                                page_size: Optional[int] = None,
                                use_dictionary: bool = True,
                                row_group_bytes: Optional[int] = None
                                ) -> Tuple[int, int]:
    """``aggregate_pileups`` over a bounded-memory pileup stream: the same
    exact-position window routing as streaming reads2ref -aggregate, fed
    by an existing pileup dataset instead of a read stream
    (PileupAggregator.scala:200-218's coverage-scaled groupBy)."""
    from ..io.parquet import DatasetWriter, iter_tables
    from ..ops.pileup import aggregate_pileups

    wopts = dict(compression=compression, page_size=page_size,
                 use_dictionary=use_dictionary)
    _purge_stale_parts(output_path)
    out = DatasetWriter(output_path, part_rows=chunk_rows,
                        row_group_bytes=row_group_bytes, **wopts)
    counted = {"n": 0}

    def chunks():
        for table in iter_tables(input_path, chunk_rows=chunk_rows):
            counted["n"] += table.num_rows
            yield table

    n_out = 0
    with windowed_tables(chunks(), window_bp=window_bp, workdir=workdir,
                         wopts=wopts) as wins:
        for wtbl in wins:
            agg = aggregate_pileups(wtbl, validate=True)
            n_out += agg.num_rows
            out.write(agg)
    out.close()
    return counted["n"], n_out


def streaming_adam2vcf(input_base: str, output_path: str, *,
                       chunk_rows: int = 1 << 20,
                       window_bp: int = 1 << 20,
                       workdir: Optional[str] = None) -> Tuple[int, int]:
    """``adam2vcf`` over bounded-memory variant/genotype streams.

    Header facts that must be global — the sample column order and the
    contig lines — come from cheap single-column pre-scans; the data
    lines then emit window by window through the shared position router
    (both datasets route with the SAME keys, merged so reference-only
    sites that exist in one table still emit).  Output order follows the
    sequence-dictionary ids, the VCF convention (the in-memory writer
    orders by contig name).  Plain ``.vcf`` text only — the bgzf/bcf
    forms buffer whole files and stay on the in-memory path.

    Returns (n_variants, n_genotypes).
    """
    from contextlib import ExitStack

    from .. import schema as S
    from ..io.parquet import iter_tables
    from ..io.vcf import _write_vcf_header, _write_vcf_records
    from ..models.dictionary import SequenceDictionary, SequenceRecord

    if str(output_path).endswith((".gz", ".bgz", ".bcf")):
        raise ValueError("streaming adam2vcf writes plain .vcf text; "
                         "use -no_stream for compressed/BCF output")

    # pre-scan 1: global sample order (first appearance, like the
    # in-memory writer); pre-scan 2: contig lines.  Both stay columnar —
    # per-chunk pyarrow unique, then dedupe the small unique lists (a
    # per-row Python loop over the >1 GB inputs this path exists for
    # would be quadratic in the unique count).  A variants-only dataset
    # (no .g — the in-memory path supports it) streams too.
    import pyarrow.compute as pc
    g_path = input_base + ".g"
    # a .g dataset may be a part-file directory OR one plain parquet file
    # (both load_table-readable; the in-memory path supports both)
    has_g = (os.path.isdir(g_path) and any(
        f.endswith(".parquet") for f in os.listdir(g_path))) or \
        os.path.isfile(g_path)
    sample_order: list = []
    seen_samples: set = set()
    if has_g:
        for t in iter_tables(input_base + ".g", columns=["sampleId"],
                             chunk_rows=chunk_rows):
            for sid in pc.unique(t.column("sampleId")).to_pylist():
                if sid not in seen_samples:
                    seen_samples.add(sid)
                    sample_order.append(sid)
    contigs: dict = {}
    for t in iter_tables(input_base + ".v",
                         columns=["referenceName", "referenceLength"],
                         chunk_rows=chunk_rows):
        grouped = t.group_by("referenceName").aggregate(
            [("referenceLength", "max")])
        for v in grouped.to_pylist():
            if v["referenceName"] is not None and \
                    v["referenceName"] not in contigs:
                contigs[v["referenceName"]] = \
                    v["referenceLength_max"] or 0
    seq_dict = SequenceDictionary(
        SequenceRecord(i, n, ln) for i, (n, ln) in
        enumerate(contigs.items()))

    counted = {"v": 0, "g": 0}

    def chunks(path, key):
        for t in iter_tables(path, chunk_rows=chunk_rows):
            counted[key] += t.num_rows
            yield t

    with open(output_path, "wt") as out, ExitStack() as stack:
        _write_vcf_header(out, S.VARIANT_SCHEMA.empty_table(),
                          sample_order, seq_dict)

        vw = stack.enter_context(windowed_tables(
            chunks(input_base + ".v", "v"), window_bp=window_bp,
            workdir=workdir, prefix="vwin", with_keys=True))
        gw = stack.enter_context(windowed_tables(
            chunks(input_base + ".g", "g") if has_g else iter(()),
            window_bp=window_bp, workdir=workdir, prefix="gwin",
            with_keys=True))
        # two-pointer merge over the sorted window keys: a site may exist
        # in either table alone (reference-only sites live in .g)
        vi = iter(vw)
        gi = iter(gw)
        v_item = next(vi, None)
        g_item = next(gi, None)
        while v_item is not None or g_item is not None:
            vk = v_item[0] if v_item is not None else None
            gk = g_item[0] if g_item is not None else None
            if gk is None or (vk is not None and vk < gk):
                _write_vcf_records(out, v_item[1],
                                   S.GENOTYPE_SCHEMA.empty_table(),
                                   sample_order)
                v_item = next(vi, None)
            elif vk is None or gk < vk:
                _write_vcf_records(out, S.VARIANT_SCHEMA.empty_table(),
                                   g_item[1], sample_order)
                g_item = next(gi, None)
            else:
                _write_vcf_records(out, v_item[1], g_item[1],
                                   sample_order)
                v_item = next(vi, None)
                g_item = next(gi, None)
    return counted["v"], counted["g"]
