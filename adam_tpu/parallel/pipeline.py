"""Streaming, mesh-sharded pipeline execution — the product path.

The reference's pipelines are distributed by construction: ``Transform.run``
chains RDD stages over partitioned data and every command streams through
executors (Transform.scala:62-97, AdamContext.scala:122-161).  This module is
that property for the TPU substrate: inputs stream in bounded chunks
(io/stream.py), each chunk pads to the mesh and runs the shard_map kernels
with psum/collective aggregation, and cross-chunk state stays compact
(counter blocks, recalibration tables, per-read key columns) — host RSS is
bounded by the chunk size, never the dataset.

Round 1 shipped these kernels but no command used the mesh; this module is
what the CLI now calls.
"""

from __future__ import annotations

import os
import shutil
import tempfile
from typing import Optional, Tuple

import numpy as np
import pyarrow as pa

from ..packing import column_int64
from .mesh import make_mesh, reads_sharding


def _pad_to(n: int, mult: int) -> int:
    return ((n + mult - 1) // mult) * mult if mult > 1 else n


def _wire32_from_table(table: pa.Table) -> np.ndarray:
    """Chunk table -> the 4-byte flagstat projection word."""
    from ..ops.flagstat import pack_flagstat_wire32

    n = table.num_rows
    flags = column_int64(table, "flags", 0)
    mapq = np.maximum(column_int64(table, "mapq", -1), 0)  # null -> 0,
    # matching the unpacked kernel's mapq=-1 (both fail the >=5 test)
    refid = column_int64(table, "referenceId", -1)
    mate_refid = column_int64(table, "mateReferenceId", -1)
    # range-check BEFORE narrowing: a wrapped int16 would pass the packer's
    # own guard and silently corrupt the cross-chromosome counters
    from ..ops.flagstat import _check_refid_range
    _check_refid_range(refid, mate_refid)
    return pack_flagstat_wire32(
        flags.astype(np.uint16), mapq.astype(np.uint8),
        refid.astype(np.int16), mate_refid.astype(np.int16),
        np.ones(n, np.uint8))


def streaming_flagstat(path: str, *, mesh=None, chunk_rows: int = 1 << 22
                       ) -> Tuple["FlagStatMetrics", "FlagStatMetrics"]:
    """Chunked, mesh-sharded flagstat over any reads input.

    Each chunk ships as one contiguous u32 buffer (the 26-bit projection),
    shards over the mesh, and the 18x2 counter block psums over ICI; blocks
    accumulate across chunks on host (the counters form a monoid, like the
    reference's FlagStatMetrics aggregate).
    """
    import jax

    from ..io.dispatch import FLAGSTAT_COLUMNS
    from ..io.stream import open_read_stream
    from ..ops.flagstat import (FlagStatMetrics, flagstat_wire32_sharded)

    if mesh is None:
        mesh = make_mesh()
    kernel = flagstat_wire32_sharded(mesh)
    sharding = reads_sharding(mesh)

    totals: Optional[np.ndarray] = None
    stream = open_read_stream(path, columns=FLAGSTAT_COLUMNS,
                              chunk_rows=chunk_rows)
    for table in stream:
        wire = _wire32_from_table(table)
        n_pad = _pad_to(len(wire), mesh.size)
        if n_pad != len(wire):  # padding words carry valid=0
            wire = np.concatenate(
                [wire, np.zeros(n_pad - len(wire), np.uint32)])
        counts = np.asarray(kernel(jax.device_put(wire, sharding)))
        totals = counts if totals is None else totals + counts
    if totals is None:
        totals = np.zeros((18, 2), np.int64)
    passed = FlagStatMetrics.from_counters(totals[:, 0])
    failed = FlagStatMetrics.from_counters(totals[:, 1])
    return failed, passed


# ---------------------------------------------------------------------------
# streaming transform
# ---------------------------------------------------------------------------

def _global_codes(col: pa.ChunkedArray, mapping: dict) -> np.ndarray:
    """Chunk-local dictionary codes remapped through a cross-chunk dict.

    ``mapping`` (str -> dense code) persists across chunks, so equal strings
    in different chunks get equal codes without holding every value — only
    the distinct ones (libraries: a handful).
    """
    import pyarrow.compute as pc
    from ..packing import _nan_to_null

    enc = pc.dictionary_encode(col.combine_chunks())
    vals = enc.dictionary.to_pylist()
    remap = np.array(
        [-1 if v is None else mapping.setdefault(v, len(mapping))
         for v in vals] or [0], np.int64)
    idx = _nan_to_null(enc.indices.to_numpy(zero_copy_only=False), -1)
    return np.where(idx >= 0, remap[np.maximum(idx, 0)], -1)


def _accumulate_seq_records(table: pa.Table, seen: dict) -> None:
    """Fold a chunk's denormalized dictionary fields into ``seen``
    ((id, name) -> SequenceRecord) — the reference's scan+dedup
    (AdamContext.scala:175-236), incrementally."""
    from ..models.dictionary import SequenceRecord

    for cset in (("referenceId", "referenceName", "referenceLength",
                  "referenceUrl"),
                 ("mateReferenceId", "mateReference", "mateReferenceLength",
                  "mateReferenceUrl")):
        if not all(c in table.column_names for c in cset):
            continue
        ids = column_int64(table, cset[0])
        uniq, first = np.unique(ids, return_index=True)
        rows = first[uniq >= 0]
        if not len(rows):
            continue
        sub = table.select(list(cset)).take(pa.array(rows)).to_pylist()
        for r in sub:
            i, nm = r[cset[0]], r[cset[1]]
            if i is not None and nm is not None and (i, nm) not in seen:
                seen[(i, nm)] = SequenceRecord(i, nm, r[cset[2]] or 0,
                                               r[cset[3]])


def _apply_dup_bits(table: pa.Table, dup: np.ndarray) -> pa.Table:
    from .. import schema as S

    flags = column_int64(table, "flags", 0)
    new = np.where(dup, flags | S.FLAG_DUPLICATE,
                   flags & ~np.int64(S.FLAG_DUPLICATE))
    idx = table.column_names.index("flags")
    return table.set_column(idx, "flags",
                            pa.array(new.astype(np.uint32), pa.uint32()))


class _MarkdupKeys:
    """Per-chunk compact markdup key accumulator (~42 bytes/read).

    The streaming replacement for the reference's two name/position shuffles
    (MarkDuplicates.scala:59-109): each chunk contributes device-computed 5'
    positions and phred>=15 scores plus host-hashed name keys; the global
    decision then runs once over the concatenated columns, never holding the
    records themselves.
    """

    def __init__(self, mesh):
        self.mesh = mesh
        self.flags, self.refid, self.rgid = [], [], []
        self.fp, self.score, self.h1, self.h2, self.lib = [], [], [], [], []
        self.lib_map: dict = {}

    def add_chunk(self, table: pa.Table, batch) -> None:
        import jax
        import jax.numpy as jnp
        from ..ops.markdup import _device_fiveprime_and_score
        from ..packing import hash_strings_128

        n = table.num_rows
        sharded = batch.device_put(reads_sharding(self.mesh))
        fp, score = _device_fiveprime_and_score(
            sharded.flags, sharded.start, sharded.cigar_ops,
            sharded.cigar_lens, sharded.n_cigar, sharded.quals)
        self.fp.append(np.asarray(fp)[:n].astype(np.int64))
        self.score.append(np.asarray(score)[:n])
        self.flags.append(column_int64(table, "flags", 0))
        self.refid.append(column_int64(table, "referenceId"))
        self.rgid.append(column_int64(table, "recordGroupId"))
        h1, h2 = hash_strings_128(table.column("readName"))
        self.h1.append(h1)
        self.h2.append(h2)
        self.lib.append(_global_codes(table.column("recordGroupLibrary"),
                                      self.lib_map))

    def decide(self) -> np.ndarray:
        from ..ops.markdup import bucket_ids_from_keys, decide_duplicates

        cat = {k: np.concatenate(getattr(self, k)) for k in
               ("flags", "refid", "rgid", "fp", "score", "h1", "h2", "lib")}
        bucket_id = bucket_ids_from_keys(cat["rgid"], cat["h1"], cat["h2"])
        return decide_duplicates(cat["flags"], cat["refid"], cat["fp"],
                                 cat["score"], bucket_id, cat["lib"])


def streaming_transform(input_path: str, output_path: str, *,
                        markdup: bool = False, bqsr: bool = False,
                        snp_table=None, realign: bool = False,
                        sort: bool = False, workdir: Optional[str] = None,
                        mesh=None, chunk_rows: int = 1 << 20,
                        n_bins: Optional[int] = None,
                        compression: str = "zstd") -> int:
    """The ``transform`` pipeline over a chunked stream and a device mesh.

    Multi-pass, like the reference's shuffle stages (Transform.scala:62-97):

      pass 1  ingest: stream the input once, spill raw chunks to a Parquet
              workdir (skipped when the input already is Parquet), compute
              markdup key columns on device per chunk;
      -       global markdup decision over the compact keys (the two
              shuffles of MarkDuplicates.scala collapse into host sorts);
      pass 2  BQSR table pass: re-stream, apply dup bits, accumulate the
              dense RecalTable (devices psum within a chunk, chunks merge
              with RecalTable.__add__, the reference's driver aggregate);
      pass 3  emit: re-stream, apply dup bits + recalibrated quals, route
              rows to genome bins (GenomicRegionPartitioner) when
              sort/realign is on, else write output parts directly;
      pass 4  per-bin: realign + in-bin sort; bins concatenate in genome
              order, so the output is globally position-sorted
              (AdamRDDFunctions.scala:63-93's range partition + sort).

    Host RSS is bounded by chunk size + ~42 bytes/read of markdup keys —
    never the dataset.  Realignment note: targets are found per genome bin;
    a target group spanning a bin edge sees only its own bin's reads
    (boundary effect << bin span; the reference's global target collect has
    no such edge, the in-memory path matches it exactly).
    """
    from ..bqsr.recalibrate import apply_table, compute_table
    from ..bqsr.table import RecalTable
    from ..io.parquet import DatasetWriter, iter_tables
    from ..io.stream import open_read_stream
    from ..models.dictionary import SequenceDictionary
    from ..packing import pack_reads
    from .partitioner import GenomicRegionPartitioner
    from .. import schema as S

    if mesh is None:
        mesh = make_mesh()
    own_workdir = workdir is None
    if own_workdir:
        workdir = tempfile.mkdtemp(prefix="adam_tpu_transform_")
    os.makedirs(workdir, exist_ok=True)

    is_parquet = not (input_path.endswith(".sam") or
                      input_path.endswith(".bam"))
    raw_path = input_path if is_parquet else os.path.join(workdir, "raw")

    try:
        # ---- pass 1: ingest ------------------------------------------------
        stream = open_read_stream(input_path, chunk_rows=chunk_rows)
        keys = _MarkdupKeys(mesh) if markdup else None
        seq_seen: dict = {}
        raw_writer = None if is_parquet else DatasetWriter(
            raw_path, part_rows=chunk_rows, compression=compression)
        total_rows = 0
        max_rgid = -1
        bucket_len = 0
        for table in stream:
            total_rows += table.num_rows
            max_rgid = max(max_rgid,
                           int(column_int64(table, "recordGroupId")
                               .max(initial=-1)))
            _accumulate_seq_records(table, seq_seen)
            if raw_writer is not None:
                raw_writer.write(table)
            if keys is not None or bqsr:
                # grow the length bucket BEFORE packing — a later chunk may
                # hold a longer read than anything seen so far
                import pyarrow.compute as pc
                chunk_max = pc.max(pc.binary_length(
                    table.column("sequence"))).as_py() or 1
                bucket_len = max(bucket_len,
                                 ((chunk_max + 127) // 128) * 128)
                batch = pack_reads(table, pad_rows_to=mesh.size,
                                   bucket_len=bucket_len)
                if keys is not None:
                    keys.add_chunk(table, batch)
        if raw_writer is not None:
            raw_writer.close()
        seq_dict = stream.seq_dict or SequenceDictionary(seq_seen.values())

        dup = keys.decide() if keys is not None else None

        def reread():
            offset = 0
            for table in iter_tables(raw_path, chunk_rows=chunk_rows):
                if dup is not None:
                    table = _apply_dup_bits(
                        table, dup[offset:offset + table.num_rows])
                offset += table.num_rows
                yield table

        # ---- pass 2: BQSR table -------------------------------------------
        rt = None
        if bqsr:
            for table in reread():
                batch = pack_reads(table, pad_rows_to=mesh.size,
                                   bucket_len=bucket_len)
                part = compute_table(table, batch, snp_table,
                                     n_read_groups=max(max_rgid + 1, 1))
                rt = part if rt is None else rt + part
            if rt is None:
                rt = RecalTable(n_read_groups=1, max_read_len=bucket_len or 1)

        # ---- pass 3: emit / route to bins ---------------------------------
        binned = sort or realign
        if binned:
            if n_bins is None:
                n_bins = max(int(np.ceil(total_rows / max(chunk_rows, 1))),
                             mesh.size)
            part = GenomicRegionPartitioner.from_dictionary(n_bins, seq_dict)
            bin_writers = [
                DatasetWriter(os.path.join(workdir, f"bin-{b:05d}"),
                              part_rows=max(chunk_rows // n_bins, 1 << 14),
                              compression=compression)
                for b in range(part.num_partitions)]
        out = DatasetWriter(output_path, part_rows=chunk_rows,
                            compression=compression)
        for table in reread():
            if bqsr:
                batch = pack_reads(table, pad_rows_to=mesh.size,
                                   bucket_len=bucket_len)
                table = apply_table(rt, table, batch)
            if not binned:
                out.write(table)
                continue
            flags = column_int64(table, "flags", 0)
            refid = column_int64(table, "referenceId")
            start = column_int64(table, "start")
            f_mapped = (flags & S.FLAG_UNMAPPED) == 0
            bins = part.partition(np.where(f_mapped, refid, -1),
                                  np.maximum(start, 0))
            # flag-mapped reads with a null refid sort before every contig
            # (sort_order keys by flags, not refid) -> front bin
            bins = np.where(f_mapped & (refid < 0), 0, bins)
            for b in np.unique(bins):
                rows = np.flatnonzero(bins == b)
                bin_writers[int(b)].write(table.take(pa.array(rows)))

        # ---- pass 4: per-bin realign/sort, concatenate in genome order ----
        if binned:
            from ..ops.sort import sort_reads
            from ..realign.realigner import realign_indels
            for b, w in enumerate(bin_writers):
                w.close()
                if w.rows_written == 0:
                    continue
                unmapped_bin = (b == part.num_partitions - 1)
                for btab in _bin_tables(w.path, chunk_rows, unmapped_bin,
                                        realign, sort, sort_reads,
                                        realign_indels):
                    out.write(btab)
        out.close()
        return total_rows
    finally:
        if own_workdir:
            shutil.rmtree(workdir, ignore_errors=True)
        elif raw_path != input_path:
            shutil.rmtree(raw_path, ignore_errors=True)


def _bin_tables(path: str, chunk_rows: int, unmapped_bin: bool,
                realign: bool, sort: bool, sort_reads, realign_indels):
    """Load one genome bin and yield its processed table(s).

    Mapped bins hold ~dataset/n_bins reads and process in memory (realign
    needs the whole bin's evidence); the unmapped bin streams through
    untouched in input order, matching the in-memory sort's stable tail.
    """
    from ..io.parquet import iter_tables, load_table

    if unmapped_bin:
        yield from iter_tables(path, chunk_rows=chunk_rows)
        return
    table = load_table(path)
    if realign:
        table = realign_indels(table)
    if sort:
        table = sort_reads(table)
    yield table
