"""Resident paged device buffers: continuous batching for the hot kernels.

Every streaming pass used to pay a full host→device transfer per
dispatch — the padded path re-ships each chunk's rung-padded wire, the
ragged path re-ships the whole fixed-capacity concat buffer (slack
included), and serve mode re-filled the shared wire buffer from scratch
every packing round.  Ragged Paged Attention (PAPERS.md,
arXiv:2604.15464) shows the TPU-native fix: keep ONE persistently
resident device allocation per plane, organized as fixed-size pages
with a page table, and let variable-length work stream *through*
residency — new rows land in free pages (only the DELTA pages ever
cross the link), finished work frees its pages without touching
neighbors, and the kernels walk ``(page_table, row_offsets)`` instead
of consuming a freshly concatenated buffer.

Three pieces (docs/ARCHITECTURE.md §6l):

1. **The pure allocator** — :func:`decide_pages` maps incoming rows
   onto free pages, lowest-id-first (deterministic), and answers
   ``fallback`` when the pool would thrash (fewer free pages than the
   request needs).  It follows the ``decide_plan`` convention exactly:
   a PURE function of keyword inputs, recorded in full (inputs +
   digest) on every ``pages_selected`` event, replayed offline by
   tools/check_executor.py.
2. **The resident pool** — :class:`PagePool` owns one device array per
   plane (``[pool_pages, page_rows]``), a host-side free list, and the
   delta-only write path: a page write is a ``device_put`` of the new
   pages plus a device-local scatter into the resident allocation
   (pow2 batches — bounded compiled shapes, exactly the delta bytes;
   never donating, so no failure or concurrent dispatch ever holds a
   dead handle), so resident pages are never re-shipped over the link.
   Writes route through the
   executor's ``dispatch_put`` when bound (retry ladder + the
   ``h2d_bytes{pass=}`` transfer accounting).
3. **Page-table kernel twins** — the three hot kernels grew paged
   entries (``ops/flagstat_pallas.flagstat_pallas_wire32_paged``,
   ``bqsr/count_pallas.count_kernel_paged``,
   ``realign/realigner.sweep_paged_xla``) that walk the page table via
   scalar prefetch (XLA gather off-TPU), each bit-identical to its
   ragged form: the gathered logical buffer IS the ragged concat, so
   identity is structural, pinned by tests/test_paged.py.

Pages are sized in FLAT elements (``page_rows``), a multiple of the
128-lane tile — the ragged layout already flattened the length axis
into the planes, so row-granular pages over flat planes are the
"rows x length-rung" pages of the paper's layout.

Knobs: ``-paged`` / ``ADAM_TPU_PAGED`` pins the layout (the
``-ragged`` convention), ``ADAM_TPU_PAGE_ROWS`` / ``ADAM_TPU_POOL_PAGES``
override the page geometry (docs/EXECUTOR.md §6).
"""

from __future__ import annotations

import functools
import hashlib
import json
import threading
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

try:  # keep importable without jax for host-only tooling
    import jax
    import jax.numpy as jnp
    _HAVE_JAX = True
except Exception:  # pragma: no cover
    _HAVE_JAX = False

from .. import obs

#: layout pin (the RAGGED_ENV convention): 1 forces the paged layout on
#: every paged-capable pass, 0/off forces it off, unset leaves the
#: decision to the plan default (off — paging is an explicit opt-in
#: until the bench gate arms it per platform)
PAGED_ENV = "ADAM_TPU_PAGED"
#: flat elements per page (every plane); must be a multiple of 1024 u32
#: lanes for the Pallas wire sweep's block geometry
PAGE_ROWS_ENV = "ADAM_TPU_PAGE_ROWS"
#: pages in the resident pool (per plane)
POOL_PAGES_ENV = "ADAM_TPU_POOL_PAGES"

#: default page size for the flagstat wire plane: 32768 u32 words
#: (128 KiB) — a multiple of the Pallas sweep's 8x1024 sublane tile, so
#: a page maps onto whole kernel blocks
DEFAULT_PAGE_ROWS = 1 << 15


def resolve_paged_env(env_val: Optional[str]) -> Optional[bool]:
    """ADAM_TPU_PAGED / flag string -> explicit pin or None (the
    resolve_ragged_env convention: env resolution stays OUT of the pure
    planners)."""
    if env_val is None or env_val == "":
        return None
    if env_val in ("0", "off", "no", "padded"):
        return False
    return True


def decide_pages(*, pass_name: str, need: int, free: Sequence[int],
                 pool_pages: int, page_rows: int,
                 tenant: Optional[str] = None) -> dict:
    """The page allocator: map one request onto free pages, or fall
    back.

    PURE — the returned decision is a deterministic function of the
    keyword inputs, which every ``pages_selected`` event records in
    full (``inputs`` + ``input_digest``), so tools/check_executor.py
    can replay a sidecar offline (the ``decide_plan`` contract).

    Policy: lowest-id-first from the sorted free list — deterministic,
    and it keeps the resident pool dense at the low end so a shrinking
    working set strands no high pages.  ``need > len(free)`` answers
    ``action="fallback"``: the caller routes this dispatch through the
    existing concat path instead of thrashing the pool (evicting pages
    a pending dispatch still reads would corrupt it; re-shipping them
    would be the exact transfer the pool exists to kill).
    """
    inputs = dict(pass_name=pass_name, need=int(need),
                  free=sorted(int(p) for p in free),
                  pool_pages=int(pool_pages), page_rows=int(page_rows),
                  tenant=tenant)
    free_sorted = inputs["free"]
    if inputs["need"] > len(free_sorted):
        pages: List[int] = []
        action = "fallback"
        reason = (f"need {inputs['need']} > free {len(free_sorted)}"
                  ":concat-fallback")
    else:
        pages = free_sorted[:inputs["need"]]
        action = "alloc"
        reason = (f"alloc {len(pages)}/{len(free_sorted)} free"
                  + (f" tenant={tenant}" if tenant else ""))
    digest = hashlib.sha256(
        json.dumps(inputs, sort_keys=True).encode()).hexdigest()[:16]
    return dict(pages=pages, action=action, reason=reason,
                inputs=inputs, input_digest=digest)


# ---------------------------------------------------------------------------
# resident device pool
# ---------------------------------------------------------------------------

if _HAVE_JAX:
    @jax.jit
    def gather_pages(pool, page_table):
        """``[P, page_rows]`` resident pool + ``[k]`` page table ->
        ``[k * page_rows]`` logical flat buffer (the ragged concat, in
        page-table order).  The paged kernels' off-TPU walk: one gather
        replaces the host concatenation AND its host→device transfer."""
        return jnp.take(pool, page_table, axis=0).reshape(-1)

    @jax.jit
    def _scatter_pages(pool, page_ids, pages):
        """Land delta pages in the resident pool.  NON-donating on
        purpose: donation would mark the host handle deleted the moment
        the call dispatches, so a failed scatter (the retry ladder's
        whole domain) or a concurrently-building gather dispatch would
        hold a dead array and lose every resident page.  The scatter is
        a device-local copy — the host→device link still ships only the
        delta pages, which is the win this pool exists for."""
        return pool.at[page_ids].set(pages)
else:  # pragma: no cover - host-only tooling
    gather_pages = None


class PagePool:
    """One resident device allocation per plane + the host-side free
    list, fed by :func:`decide_pages`.

    ``planes``: ``((name, dtype), ...)`` — every plane shares the page
    geometry (``[pool_pages, page_rows]``).  ``put`` (optional, also
    settable via :meth:`bind`) is the executor's ``dispatch_put``
    (``put(label, fn, nbytes)``): page writes then ride the retry
    ladder and the ``h2d_bytes{pass=}`` transfer accounting; unbound
    pools charge the counter directly so the accounting never drops.

    Thread-safe: alloc runs on the prefetch feeder thread while free
    runs on the consumer (the ingest.prefetched split).
    """

    def __init__(self, pass_name: str, pool_pages: int, page_rows: int,
                 planes: Sequence[Tuple[str, object]] = (("wire",
                                                          np.uint32),),
                 put: Optional[Callable] = None):
        self.pass_name = pass_name
        self.pool_pages = int(pool_pages)
        self.page_rows = int(page_rows)
        self.planes = tuple((str(n), np.dtype(d)) for n, d in planes)
        self._put = put
        self._lock = threading.Lock()
        self._free: List[int] = list(range(self.pool_pages))
        self._held: Dict[Optional[str], set] = {}
        self._dev: Dict[str, object] = {}
        self._h2d_bytes = 0
        self._writes = 0

    # -- device residency ---------------------------------------------------

    def bind(self, put: Optional[Callable]) -> "PagePool":
        """(Re)attach the executor put hook — a pool outliving one pass
        (the serve server's cross-round pool) rebinds per pass."""
        self._put = put
        return self

    def device(self, plane: str = "wire"):
        """The resident ``[pool_pages, page_rows]`` device array for
        ``plane`` (allocated zeroed on first touch — ONE allocation for
        the pool's lifetime; every later write is a delta scatter)."""
        with self._lock:
            arr = self._dev.get(plane)
            if arr is None:
                dt = dict(self.planes)[plane]
                arr = jnp.zeros((self.pool_pages, self.page_rows), dt)
                self._dev[plane] = arr
            return arr

    # -- allocator ----------------------------------------------------------

    def alloc(self, need: int,
              tenant: Optional[str] = None) -> Optional[List[int]]:
        """Claim ``need`` free pages (None = fallback: route this
        dispatch through the concat path).  Emits the replayable
        ``pages_selected`` event either way."""
        with self._lock:
            plan = decide_pages(pass_name=self.pass_name, need=need,
                                free=tuple(self._free),
                                pool_pages=self.pool_pages,
                                page_rows=self.page_rows, tenant=tenant)
            if plan["action"] == "alloc":
                taken = set(plan["pages"])
                self._free = [p for p in self._free if p not in taken]
                self._held.setdefault(tenant, set()).update(taken)
        obs.emit("pages_selected", **{"pass": self.pass_name},
                 pages=plan["pages"], action=plan["action"],
                 reason=plan["reason"], inputs=plan["inputs"],
                 input_digest=plan["input_digest"])
        if plan["action"] != "alloc":
            obs.registry().counter("paged_fallbacks",
                                   **{"pass": self.pass_name}).inc()
            return None
        return list(plan["pages"])

    def free(self, page_ids: Sequence[int],
             tenant: Optional[str] = None) -> None:
        """Return pages to the free list — host bookkeeping only: the
        resident data becomes garbage no page table references, so no
        device work (and no transfer) happens on free."""
        ids = set(int(p) for p in page_ids)
        with self._lock:
            for held in self._held.values():
                held -= ids
            self._free.extend(sorted(ids - set(self._free)))
            self._free.sort()

    def free_tenant(self, tenant: Optional[str]) -> int:
        """Free every page a finished tenant holds — neighbors'
        resident pages are untouched (the continuous-batching free
        half).  Returns the number of pages released."""
        with self._lock:
            held = self._held.pop(tenant, set())
            self._free.extend(sorted(held))
            self._free.sort()
            return len(held)

    @property
    def free_pages(self) -> int:
        with self._lock:
            return len(self._free)

    @property
    def h2d_bytes(self) -> int:
        """Bytes this pool actually shipped host→device (delta pages
        only) — the number the bench's transfer-reduction gate reads."""
        return self._h2d_bytes

    # -- delta write --------------------------------------------------------

    def write(self, page_ids: Sequence[int], **plane_rows) -> int:
        """Ship the DELTA pages and scatter them into the resident
        pool.  ``plane_rows[name]`` is the new pages' data, flat
        ``[k * page_rows]`` or ``[k, page_rows]``.  Returns the bytes
        that crossed the link (page data only — resident pages never
        re-ship).

        The k pages decompose into power-of-two batches (largest
        first), so the scatter compiles a bounded shape set per pool
        and EXACTLY k pages cross the link — never a padded duplicate.
        The scatter never donates (see :func:`_scatter_pages`), so a
        retried batch refetches a still-valid resident array and the
        pool survives any failed attempt."""
        ids = [int(p) for p in page_ids]
        if not ids:
            return 0
        k = len(ids)
        nbytes = 0
        for name, dt in self.planes:
            rows = np.asarray(plane_rows[name], dt).reshape(
                k, self.page_rows)
            nbytes += rows.nbytes
            off = 0
            while off < k:
                step = 1 << ((k - off).bit_length() - 1)
                ids_arr = np.asarray(ids[off:off + step], np.int32)
                sub = rows[off:off + step]

                def _ship(attempt, ids_arr=ids_arr, sub=sub, name=name):
                    return _scatter_pages(self.device(name),
                                          jnp.asarray(ids_arr),
                                          jax.device_put(sub))

                if self._put is not None:
                    new = self._put(f"page-{name}", _ship, sub.nbytes)
                else:
                    obs.registry().counter(
                        "h2d_bytes", **{"pass": self.pass_name}
                    ).inc(sub.nbytes)
                    new = _ship(1)
                with self._lock:
                    self._dev[name] = new
                off += step
        self._h2d_bytes += nbytes
        self._writes += 1
        obs.registry().counter("paged_writes",
                               **{"pass": self.pass_name}).inc()
        return nbytes

    def table(self, page_ids: Sequence[int],
              table_len: Optional[int] = None) -> np.ndarray:
        """int32 page table in logical order, padded to ``table_len``
        by repeating the last id (rows past the positional bound are
        dead, so any resident page is a legal pad entry)."""
        ids = [int(p) for p in page_ids] or [0]
        if table_len is not None and len(ids) < table_len:
            ids = ids + [ids[-1]] * (table_len - len(ids))
        return np.asarray(ids, np.int32)
