"""Elastic recovery for the multi-host mesh: job-level restart + resume.

The reference inherits failure recovery from Spark lineage re-execution
(SURVEY §5: "entirely delegated to Spark").  A `jax.distributed` mesh has
no per-task lineage — a lost peer wedges every subsequent collective —
so the TPU-native recovery unit is the JOB: a supervisor (the analog of
the cluster manager restarting a Spark executor's whole stage) detects
any worker death, tears the incarnation down, and relaunches the job on
a RE-FORMED mesh with a fresh coordination service; workers resume from
the durable checkpoint (checkpoint.py pass-level fingerprints), which
makes the re-run land on byte-identical output.  This is exactly how
production TPU pods recover (GKE/Borg job restart + orbax resume) — the
design the scaling-book recipe assumes — rather than in-place peer
rejoin, which XLA's SPMD model cannot express mid-program.

Worker-side: a lost peer usually manifests as a HANG (the collective
waits on DCN), not an error.  ``phase_watchdog`` converts "no progress
past the deadline" into a prompt nonzero exit the supervisor can see.

``tests/test_elastic_recovery.py`` kills one worker of a two-process
mesh mid-run and pins recovery-to-correct-output (VERDICT r4 #8).
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys
import tempfile
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, List, Optional, Sequence


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@dataclass
class Incarnation:
    """One launch of the whole job: N processes on one coordinator.

    Worker output goes to the per-worker files in ``logs`` — NOT pipes:
    an undrained pipe wedges any worker chattier than the OS buffer,
    which would read as a hang, not a failure.  ``metrics`` holds each
    worker's telemetry sidecar path (obs JSONL, written when the worker
    honors ``ADAM_TPU_METRICS``); the supervisor folds the finished
    incarnation's sidecars into its own registry."""
    number: int
    coordinator: str
    procs: List[subprocess.Popen] = field(default_factory=list)
    logs: List[str] = field(default_factory=list)
    metrics: List[str] = field(default_factory=list)
    traces: List[str] = field(default_factory=list)


def supervise(argv_for: Callable[[int, str], Sequence[str]],
              num_processes: int,
              max_restarts: int = 2,
              poll_s: float = 0.25,
              grace_kill_s: float = 5.0,
              env: Optional[dict] = None,
              log_dir: Optional[str] = None,
              on_incarnation: Optional[Callable[[Incarnation], None]] = None,
              restart_backoff_s: float = 0.25,
              restart_backoff_cap_s: float = 30.0,
              ) -> Incarnation:
    """Run the N-process job to success, restarting the WHOLE job on any
    worker death (nonzero exit or signal).

    ``argv_for(process_id, coordinator_address)`` builds each worker's
    command line.  Each incarnation gets a fresh coordinator port — a
    re-formed mesh, not a rejoin: the old coordination service dies with
    the incarnation.  Returns the successful incarnation; raises
    RuntimeError after ``max_restarts`` failed relaunches.  Durable state
    (the checkpoint dir the argv points at) is the workers' own
    responsibility — that is what makes restart = resume.

    Relaunches back off exponentially (``restart_backoff_s`` doubling to
    ``restart_backoff_cap_s``, deterministic jitter): an immediate
    relaunch of a deterministically-crashing job burns every restart in
    seconds, and synchronized supervisor fleets would hammer a shared
    coordinator.  The delay is recorded in each ``incarnation`` event.
    """
    from ..obs import (METRICS_ENV, emit, read_snapshot_file, registry,
                       snapshot_is_fleet_merged, trace)
    from ..resilience.faults import INCARNATION_ENV
    from ..resilience.retry import backoff_delay

    last_fail = "never launched"
    log_dir = log_dir or tempfile.mkdtemp(prefix="elastic_logs_")
    os.makedirs(log_dir, exist_ok=True)
    # timeline sidecars ride the metrics discipline: ONLY when this
    # supervisor is tracing (active collector, or the env the workers
    # will actually inherit asks for a trace) do workers get per-worker
    # ADAM_TPU_TRACE paths — a shared path would be clobbered by N
    # writers, and an untraced run must not grow N timeline files per
    # incarnation.  The gate reads the CALLER's env when one is given:
    # a trace path in `env` alone would otherwise reach every worker
    # verbatim, the exact clobber this stamping exists to prevent.
    worker_base_env = env if env is not None else os.environ
    tracing = trace.active() is not None or \
        bool(worker_base_env.get(trace.TRACE_ENV))
    for number in range(max_restarts + 1):
        delay = 0.0
        if number and restart_backoff_s > 0:
            # key the jitter by THIS supervisor's pid: a fleet of
            # supervisors restarting off one shared-coordinator flap
            # must spread out, not compute one identical "jitter" and
            # re-hammer it in lockstep (the delay each process actually
            # used is recorded in its incarnation event)
            delay = backoff_delay(f"elastic_restart:{os.getpid()}",
                                  number, restart_backoff_s,
                                  restart_backoff_cap_s)
            time.sleep(delay)
        coordinator = f"127.0.0.1:{free_port()}"
        inc = Incarnation(number=number, coordinator=coordinator)
        registry().counter("elastic_incarnations").inc()
        emit("incarnation", number=number, coordinator=coordinator,
             workers=num_processes, restart_delay_s=round(delay, 6))
        for pid in range(num_processes):
            path = os.path.join(log_dir, f"inc{number}-worker{pid}.log")
            inc.logs.append(path)
            # each worker gets its OWN metrics sidecar — always, even
            # when the caller's env carries ADAM_TPU_METRICS (a single
            # shared path would be clobbered by N concurrent writers and
            # then merged N times).  A worker that opts into telemetry
            # via obs.metrics_run_from_env writes here, and the
            # supervisor — the coordinator of this recovery scheme —
            # merges the successful incarnation's sidecars below.
            wenv = dict(env if env is not None else os.environ)
            mpath = os.path.join(
                log_dir, f"inc{number}-worker{pid}.metrics.jsonl")
            wenv[METRICS_ENV] = mpath
            # fault-plan rules can scope to one incarnation (e.g. kill
            # the first launch's workers, let the relaunch live) — the
            # supervisor stamps which launch this worker belongs to
            wenv[INCARNATION_ENV] = str(number)
            inc.metrics.append(mpath)
            if tracing:
                tpath = os.path.join(
                    log_dir, f"inc{number}-worker{pid}.trace.json")
                wenv[trace.TRACE_ENV] = tpath
                inc.traces.append(tpath)
            with open(path, "w") as log:
                inc.procs.append(subprocess.Popen(
                    list(argv_for(pid, coordinator)),
                    stdout=log, stderr=subprocess.STDOUT, env=wenv))
        if on_incarnation:
            on_incarnation(inc)
        failed: Optional[int] = None
        while True:
            codes = [p.poll() for p in inc.procs]
            bad = [i for i, c in enumerate(codes)
                   if c is not None and c != 0]
            if bad:
                failed = bad[0]
                break
            if all(c == 0 for c in codes):
                # gather each worker's registry snapshot into the
                # coordinator's report: counter sum / gauge max /
                # histogram merge (obs.registry.MetricsRegistry.merge).
                # A worker that ran distributed.merge_worker_metrics
                # already holds fleet totals (symmetric merge), so fold
                # at most ONE fleet-view sidecar — summing N fleet
                # views would count every worker N times.
                merged_fleet = False
                for mp in inc.metrics:
                    snap = read_snapshot_file(mp)
                    if snap is None:
                        continue
                    fleet = snapshot_is_fleet_merged(snap)
                    if fleet and merged_fleet:
                        continue
                    registry().merge(snap)
                    merged_fleet = merged_fleet or fleet
                # worker timelines fold into the supervisor's (events
                # carry their own pid lanes and wall-anchored clocks, so
                # one merged file shows every process on one axis)
                for tp in inc.traces:
                    trace.merge_trace_file(tp)
                return inc
            time.sleep(poll_s)
        # one worker died: the mesh is wedged — tear down the whole
        # incarnation (peers are likely hung inside a collective on the
        # dead peer, so escalate kill after a grace period)
        rc = inc.procs[failed].returncode
        registry().counter("elastic_worker_deaths").inc()
        emit("worker_death", incarnation=number, worker=failed, rc=rc)
        for p in inc.procs:
            if p.poll() is None:
                p.terminate()
        deadline = time.monotonic() + grace_kill_s
        for p in inc.procs:
            if p.poll() is None:
                try:
                    p.wait(timeout=max(0.0, deadline - time.monotonic()))
                except subprocess.TimeoutExpired:
                    p.kill()
        for p in inc.procs:
            try:
                p.wait(timeout=grace_kill_s)
            except subprocess.TimeoutExpired:
                pass
        last_fail = (f"incarnation {number}: worker {failed} exited "
                     f"rc={rc}")
    raise RuntimeError(
        f"job failed after {max_restarts + 1} incarnations ({last_fail})")


def phase_watchdog(deadline_s: float, exit_code: int = 17,
                   note: str = "") -> Callable[[], None]:
    """Arm a deadline for the current phase; returns a disarm callable.

    A peer lost mid-collective shows up as an indefinite DCN wait, which
    no in-process exception handler can interrupt — ``os._exit`` from a
    watchdog thread is the reliable conversion of "hung past deadline"
    into a worker death the supervisor acts on.
    """
    disarmed = threading.Event()

    def fire():
        if not disarmed.wait(timeout=deadline_s):
            sys.stderr.write(
                f"phase_watchdog: {note or 'phase'} exceeded "
                f"{deadline_s}s — assuming lost peer, exiting "
                f"{exit_code}\n")
            sys.stderr.flush()
            os._exit(exit_code)

    threading.Thread(target=fire, daemon=True,
                     name="phase-watchdog").start()
    return disarmed.set
