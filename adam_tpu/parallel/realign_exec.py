"""Pipelined per-bin realignment engine — transform pass 4's scheduler.

The reference's indel realignment is its most expensive shuffle stage
(AdamRDDFunctions.scala:109-183), and after PR 3 it was the one streaming
pass still outside the executor discipline: bins ran strictly one at a
time, host prep blocked the device, and small bins dispatched
under-filled sweep batches.  This module is the pass-4 counterpart of
``parallel/executor.py`` — a bounded three-stage software pipeline over
the genome-ordered bin sequence:

  stage A  **load + prep**: a worker pool loads bin i+1's Parquet (own +
           halo) and runs the host group prep (pileups → targets →
           columnar group packing, ``realigner.plan_realign``) while …
  stage B  **sweep**: … bin i's sweep jobs sit in the cross-bin batcher.
           Jobs from every in-flight bin bucket by their padded
           ``(R, L, CL)`` shape on the canonical rung ladder
           (``packing.shape_rung`` — the executor's ``row_bucket_ladder``
           recurrence), so tiny bins no longer dispatch G=1 batches and
           each kernel compiles a bounded shape set per run; dispatch is
           asynchronous, so the device runs ahead while …
  stage C  **finish + emit**: … bin i-1 takes the LOD gate, rewrites,
           vectorized write-back, in-bin sort, and the sorted
           merge-window emit — in strict genome order.

The pipeline changes scheduling, never results: units emit in exactly the
serial order (``ingest.pipelined`` preserves input order), sweep lanes are
vmapped independently, and pad lanes replicate lane 0
(``realigner.sweep_dispatch``), so output is byte-identical to the serial
path at any depth — pinned by tests/test_realign_exec.py.

Every decision and stage emits through :mod:`adam_tpu.obs` (the PR 3
``executor_bucket_selected`` convention):

* ``realign_plan_selected`` — the frozen plan with its canonicalized
  ``inputs`` + ``input_digest`` (:func:`decide_realign_plan` is pure, so
  the decision replays offline);
* ``realign_bin`` — per-unit stage wall times
  (load/prep/sweep/finish/emit), group/job counts;
* ``realign_sweep_dispatch`` — per-dispatch bucket occupancy: padded
  shape, jobs carried, padded lane count G, distinct units on board.

On TPU backends the plan turns on sweep-input donation
(``realigner._sweep_conv_many_donating``), reusing each batch's HBM for
outputs instead of re-allocating per dispatch.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, Iterable, Optional, Tuple

import numpy as np
import pyarrow as pa

from .. import obs
from ..realign import realigner as R
from ..resilience.retry import dispatch_with_retry, resolve_retry_policy

#: env overrides (the transform CLI flags mirror these, docs/REALIGN_EXECUTOR.md)
REALIGN_PIPELINE_ENV = "ADAM_TPU_REALIGN_PIPELINE"          # 0/off disables
REALIGN_DEPTH_ENV = "ADAM_TPU_REALIGN_PIPELINE_DEPTH"
REALIGN_DONATE_ENV = "ADAM_TPU_REALIGN_DONATE"              # 0/off disables

#: default look-ahead: bin i+1 preps while bin i sweeps and bin i-1 emits
DEFAULT_REALIGN_DEPTH = 2
#: host RSS is bounded by depth x bin budget — cap runaway flag values
MAX_REALIGN_DEPTH = 16


def decide_realign_plan(*, n_bins: int, on_tpu: bool,
                        pipeline: Optional[bool] = None,
                        depth: Optional[int] = None,
                        donate: Optional[bool] = None,
                        layout: Optional[str] = None,
                        ragged_rates: Optional[dict] = None,
                        paged_rates: Optional[dict] = None) -> dict:
    """The pass-4 plan: one frozen decision per transform run.

    PURE — the returned plan is a deterministic function of the keyword
    inputs, which the ``realign_plan_selected`` event records in full
    (``inputs`` + ``input_digest``), the same replayable-decision
    contract as ``executor.decide_plan``.  Explicit ``pipeline`` /
    ``depth`` / ``donate`` / ``layout`` pin those knobs.

    ``layout`` picks the sweep dispatch form: ``padded`` buckets jobs on
    all four (R, L, CL, G) axes; ``ragged`` concatenates reads across
    jobs and buckets only on the (CL, G) rungs (docs/ARCHITECTURE.md
    §6g); ``paged`` ships the ragged planes page-granular through a
    resident pool (docs/ARCHITECTURE.md §6l,
    ``realigner.sweep_dispatch_paged``).  Unpinned, the decision follows
    the bench ``paged_race`` / ``ragged_race`` evidence the same way
    ``executor.decide_plan`` does — padded stays the no-evidence
    default.  The paged keys join ``inputs`` only when engaged (a pin
    or evidence present), so pre-paged recorded plans replay
    digest-identical.
    """
    inputs = dict(n_bins=int(n_bins), on_tpu=bool(on_tpu),
                  pipeline=None if pipeline is None else bool(pipeline),
                  depth=None if depth is None else int(depth),
                  donate=None if donate is None else bool(donate),
                  layout=layout,
                  ragged_rates=None if not ragged_rates else {
                      k: round(float(v), 1)
                      for k, v in sorted(ragged_rates.items())})
    paged_engaged = layout == "paged" or bool(paged_rates)
    if paged_engaged:
        inputs["paged_rates"] = None if not paged_rates else {
            k: round(float(v), 4)
            for k, v in sorted(paged_rates.items())}
    reasons = []
    lay = "padded"
    if inputs["layout"] == "paged":
        lay = "paged"
        reasons.append("layout-pinned-paged")
    elif inputs["layout"] == "ragged":
        lay = "ragged"
        reasons.append("layout-pinned-ragged")
    elif inputs["layout"] == "padded":
        reasons.append("layout-pinned-padded")
    elif paged_engaged and inputs.get("paged_rates"):
        # the executor's paged-evidence bar: measured h2d win over the
        # reduction floor, serve wall inside the slack band
        from .executor import (PAGED_EVIDENCE_MIN_REDUCTION,
                               PAGED_EVIDENCE_WALL_SLACK)
        pr = inputs["paged_rates"]
        if pr.get("h2d_reduction", 0) >= PAGED_EVIDENCE_MIN_REDUCTION \
                and pr.get("paged_wall_s", float("inf")) <= \
                PAGED_EVIDENCE_WALL_SLACK * pr.get("unpaged_wall_s", 0):
            lay = "paged"
            reasons.append(
                f"paged-evidence h2d {pr['h2d_reduction']:.1f}x")
    if lay == "padded" and not reasons and inputs["ragged_rates"]:
        rr = inputs["ragged_rates"]
        if rr.get("ragged", 0) > rr.get("padded", 0) > 0:
            lay = "ragged"
            reasons.append(
                f"ragged-evidence {rr['ragged']:.0f}>{rr['padded']:.0f}")
    use = True if inputs["pipeline"] is None else inputs["pipeline"]
    d = DEFAULT_REALIGN_DEPTH if inputs["depth"] is None else inputs["depth"]
    if d > MAX_REALIGN_DEPTH:
        d = MAX_REALIGN_DEPTH
        reasons.append("depth-capped")
    if d <= 0:
        # an explicit depth <= 0 means OFF (the prefetch_depth=0
        # convention), and the recorded reason says so — a silent floor
        # to 1 would be invisible in the replayable plan
        use = False
        reasons.append("depth-off")
    if not use:
        d = 0
        if "depth-off" not in reasons:
            reasons.append("pipeline-off")
    do_donate = bool(on_tpu) if inputs["donate"] is None \
        else inputs["donate"]
    digest = hashlib.sha256(
        json.dumps(inputs, sort_keys=True).encode()).hexdigest()[:16]
    return dict(pipeline_depth=int(d), donate=do_donate, layout=lay,
                reason=";".join(reasons) or "default",
                inputs=inputs, input_digest=digest)


def resolve_realign_opts(opts: Optional[dict] = None) -> dict:
    """CLI flags win; ``ADAM_TPU_REALIGN_*`` (and the shared
    ``ADAM_TPU_RAGGED`` / ``ADAM_TPU_PAGED``) envs fill whatever the
    caller left unset (the executor's flag/env convention).  An
    unpinned layout pulls the raced bench evidence for the realign
    sweep from the PR 2 ledger — the paged record first (residency
    outranks the addressing scheme alone), then the ragged race."""
    from .executor import (PAGED_ENV, RAGGED_ENV, ledger_paged_rates,
                           ledger_ragged_rates, resolve_ragged_env)
    from .pagedbuf import resolve_paged_env

    out = dict(opts or {})
    env = os.environ
    if "pipeline" not in out and env.get(REALIGN_PIPELINE_ENV):
        out["pipeline"] = env[REALIGN_PIPELINE_ENV] not in ("0", "off")
    if "depth" not in out and env.get(REALIGN_DEPTH_ENV):
        try:
            out["depth"] = int(env[REALIGN_DEPTH_ENV])
        except ValueError:
            pass
    if "donate" not in out and env.get(REALIGN_DONATE_ENV) in ("0", "off"):
        out["donate"] = False
    if out.get("layout") is None:
        if resolve_paged_env(env.get(PAGED_ENV)):
            out["layout"] = "paged"
        else:
            out["layout"] = resolve_ragged_env(env.get(RAGGED_ENV))
    if out["layout"] is None:
        out.pop("layout")
        prates = ledger_paged_rates()
        if prates:
            out["paged_rates"] = prates
        rates = ledger_ragged_rates("realign")
        if rates:
            out["ragged_rates"] = rates
    return out


def emit_realign_plan(plan: dict) -> None:
    """One ``realign_plan_selected`` event + counter per pass-4 start —
    the pass-boundary discipline of ``StreamExecutor.begin_pass``."""
    obs.registry().counter("realign_plans").inc()
    obs.emit("realign_plan_selected",
             pipeline_depth=plan["pipeline_depth"], donate=plan["donate"],
             layout=plan.get("layout", "padded"),
             reason=plan["reason"], inputs=plan["inputs"],
             input_digest=plan["input_digest"])


class _ChunkResult:
    """One dispatch's device results, converted to numpy exactly once
    (the np conversion is the device sync point; members from several
    units share it)."""

    __slots__ = ("_dev", "_np")

    def __init__(self, q_dev, o_dev):
        self._dev = (q_dev, o_dev)
        self._np = None

    def arrays(self) -> Tuple[np.ndarray, np.ndarray]:
        if self._np is None:
            q, o = self._dev
            self._np = (np.asarray(q), np.asarray(o))
            self._dev = None          # release device buffers promptly
        return self._np


class CrossBinSweepBatcher:
    """Shape-bucketed sweep-job queue across the pipeline's in-flight bins.

    Jobs register from the prep workers (thread-safe); device dispatch
    happens on the scheduler thread only.  Buckets key on the padded
    ``(R, L, CL)`` job shape — realigner's canonical rungs — so jobs from
    different bins share one vmapped dispatch; dispatch G pads to a power
    of two with pad lanes replicating lane 0, so batch composition can
    change scheduling and telemetry but never a byte of output.
    """

    def __init__(self, donate: bool = False, retry_policy=None,
                 layout: str = "padded"):
        self._donate = donate
        self._layout = layout
        # the caller's resolved policy (the -retry_budget flag plumbed
        # through StreamExecutor) wins; standalone use falls back to env
        self._retry = retry_policy or resolve_retry_policy()
        self._lock = threading.Lock()
        self._buckets: Dict[tuple, list] = {}     # key -> [(uid, si, ji)]
        self._states: Dict[tuple, list] = {}      # uid -> states
        self._results: Dict[tuple, tuple] = {}    # (uid,si,ji) -> (chunk,g)
        self._unit_shapes: Dict[tuple, set] = {}  # uid -> undispatched keys
        self._shapes_seen: set = set()            # (G, R, L, CL) sightings
        self._pool = None                         # paged: resident pool

    def _key(self, job) -> tuple:
        """Bucket key: the full padded (R, L, CL) shape, or — ragged
        and paged — the CL rung alone: concatenated reads make R and L
        per-dispatch totals instead of per-job shape axes, so only the
        consensus rung (and the padded lane count G) remain compiled
        axes."""
        return job.shape if self._layout == "padded" \
            else (job.shape[2],)

    # -- producer side (prep workers) --------------------------------------

    def add_unit(self, uid: tuple, states: list) -> None:
        """Register every (group, consensus) job of a prepared unit.
        Called from the load+prep workers; never dispatches."""
        with self._lock:
            self._states[uid] = states
            shapes = self._unit_shapes.setdefault(uid, set())
            for si, st in enumerate(states):
                for ji, job in enumerate(st.jobs):
                    key = self._key(job)
                    self._buckets.setdefault(key, []).append(
                        (uid, si, ji))
                    shapes.add(key)

    # -- scheduler side (strict unit order) --------------------------------

    def sweep_unit(self, uid: tuple) -> list:
        """Dispatch every bucket still holding one of ``uid``'s jobs —
        the WHOLE bucket, so jobs from bins prepped ahead ride along in
        the same batches (that is the cross-bin amortization) — then
        return ``uid``'s per-state result lists (numpy, job order)."""
        while True:
            with self._lock:
                shape = next((s for s in self._unit_shapes.get(uid, ())
                              if self._buckets.get(s)), None)
                if shape is None:
                    break
                members = self._buckets.pop(shape)
                for u, _, _ in members:
                    self._unit_shapes.get(u, set()).discard(shape)
            self._dispatch(shape, members)
        states = self._states.pop(uid)
        self._unit_shapes.pop(uid, None)
        out = []
        for si, st in enumerate(states):
            out.append([self._take(uid, si, ji)
                        for ji in range(len(st.jobs))])
        return out

    def _dispatch(self, shape: tuple, members: list) -> None:
        if self._layout in ("ragged", "paged"):
            # chunk by cumulative flat bases so the [T, CLp] working set
            # stays under budget (realigner.ragged_chunk_jobs)
            t_of = [int(self._states[u][si].lens.sum())
                    for u, si, _ in members]
            splits = R.ragged_chunk_jobs(t_of, shape[0]) + [len(members)]
            dispatch_one = self._dispatch_chunk_paged \
                if self._layout == "paged" \
                else self._dispatch_chunk_ragged
            lo = 0
            for hi in splits:
                if hi > lo:
                    dispatch_one(shape[0], members[lo:hi])
                lo = hi
            return
        Rr, L, CL = shape
        g_max = R._sweep_g_max(Rr, L, CL)
        for lo in range(0, len(members), g_max):
            self._dispatch_chunk(shape, members[lo:lo + g_max])

    def _dispatch_chunk(self, shape: tuple, chunk: list) -> None:
        """One device sweep batch under the scoped retry ladder:
        transient errors re-dispatch (states are host-resident, so every
        attempt rebuilds its device inputs), ``RESOURCE_EXHAUSTED``
        halves the bucket and re-dispatches the halves — lanes are
        independent vmap programs, so batch composition changes
        scheduling and telemetry, never a byte of output."""
        Rr, L, CL = shape
        pairs = [(self._states[u][si], self._states[u][si].jobs[ji])
                 for u, si, ji in chunk]

        def fn(attempt):
            # donation only on the first attempt: a failed donated
            # dispatch may have consumed its buffers
            return R.sweep_dispatch(pairs,
                                    donate=self._donate and attempt == 1)

        def split(err):
            if len(chunk) <= 1:
                raise err
            mid = (len(chunk) + 1) // 2
            self._dispatch_chunk(shape, chunk[:mid])
            self._dispatch_chunk(shape, chunk[mid:])
            return None

        # one timeline span per device sweep batch (near-free when
        # tracing is off): the cross-bin batches are exactly what the
        # Perfetto view needs to show overlapping the prep pool's lanes
        with obs.trace.span("realign:sweep", cat="dispatch",
                            args={"shape": [Rr, L, CL],
                                  "jobs": len(chunk)}):
            out = dispatch_with_retry(fn, site="device_dispatch",
                                      label="realign:sweep",
                                      policy=self._retry, split=split)
        if out is None:
            return              # split path recorded the halves' results
        q_dev, o_dev = out
        cr = _ChunkResult(q_dev, o_dev)
        for g, key in enumerate(chunk):
            self._results[key] = (cr, g)
        # the ACTUAL padded lane count, read off the dispatched
        # result — not a re-derivation of sweep_dispatch's policy
        G = int(q_dev.shape[0])
        r = obs.registry()
        r.counter("realign_sweep_dispatches").inc()
        r.counter("realign_sweep_jobs").inc(len(chunk))
        if (G, Rr, L, CL) not in self._shapes_seen:
            self._shapes_seen.add((G, Rr, L, CL))
            r.counter("realign_shapes").inc()
        # per-axis pad-waste breakdown: the measured justification for
        # the layout decision (docs/OBSERVABILITY.md) — fraction of each
        # padded axis spent on slack, on THIS dispatch's true geometry
        true_r = [len(self._states[u][si].reads_to_clean)
                  for u, si, _ in chunk]
        true_b = [int(self._states[u][si].lens.sum())
                  for u, si, _ in chunk]
        true_cl = [self._states[u][si].jobs[ji].cons_len
                   for u, si, ji in chunk]
        obs.emit("realign_sweep_dispatch", shape=[Rr, L, CL],
                 jobs=len(chunk), g=G,
                 units=len({u for u, _, _ in chunk}),
                 layout="padded",
                 waste_r=round(1 - sum(true_r) / (len(chunk) * Rr), 4),
                 waste_l=round(1 - sum(true_b) /
                               max(sum(true_r) * L, 1), 4),
                 waste_cl=round(1 - sum(true_cl) /
                                (len(chunk) * CL), 4),
                 waste_g=round(1 - len(chunk) / G, 4))

    def _dispatch_chunk_ragged(self, cl: int, chunk: list) -> None:
        """One RAGGED device sweep batch: jobs share only the CL rung;
        reads concatenate at true (R, L) through the prefix-sum row
        index (realigner.sweep_dispatch_ragged).  Same retry discipline
        as the padded dispatch — lanes/rows are independent, so a
        half-split changes scheduling, never a byte."""
        pairs = [(self._states[u][si], self._states[u][si].jobs[ji])
                 for u, si, ji in chunk]

        def fn(attempt):
            return R.sweep_dispatch_ragged(pairs, donate=self._donate
                                           and attempt == 1)

        def split(err):
            if len(chunk) <= 1:
                raise err
            mid = (len(chunk) + 1) // 2
            self._dispatch_chunk_ragged(cl, chunk[:mid])
            self._dispatch_chunk_ragged(cl, chunk[mid:])
            return None

        with obs.trace.span("realign:sweep", cat="dispatch",
                            args={"shape": [cl], "jobs": len(chunk),
                                  "layout": "ragged"}):
            out = dispatch_with_retry(fn, site="device_dispatch",
                                      label="realign:sweep",
                                      policy=self._retry, split=split)
        if out is None:
            return
        q, o, spans, stats = out
        cr = _ChunkResult(q, o)
        for key, span in zip(chunk, spans):
            self._results[key] = (cr, span)
        r = obs.registry()
        r.counter("realign_sweep_dispatches").inc()
        r.counter("realign_sweep_jobs").inc(len(chunk))
        sig = (stats["g"], stats["rows_pad"], stats["bases_pad"], cl)
        if sig not in self._shapes_seen:
            self._shapes_seen.add(sig)
            r.counter("realign_shapes").inc()
        obs.emit("realign_sweep_dispatch",
                 shape=[stats["rows_pad"], stats["bases_pad"], cl],
                 jobs=len(chunk), g=stats["g"],
                 units=len({u for u, _, _ in chunk}),
                 layout="ragged",
                 waste_r=round(1 - stats["rows"] /
                               max(stats["rows_pad"], 1), 4),
                 waste_l=round(1 - stats["bases"] /
                               max(stats["bases_pad"], 1), 4),
                 waste_cl=round(1 - stats["cons_true"] /
                                max(len(chunk) * cl, 1), 4),
                 waste_g=round(1 - len(chunk) / stats["g"], 4))

    def _dispatch_chunk_paged(self, cl: int, chunk: list) -> None:
        """One PAGED device sweep batch: the ragged dispatch's flat
        planes ship page-granular through a batcher-held resident
        :class:`.pagedbuf.PagePool` reused across every dispatch of the
        run (``realigner.sweep_dispatch_paged`` — only live pages cross
        the link; a thrashing pool falls back to the ragged concat
        inside the dispatch, identical bytes either way).  Same retry /
        half-split discipline as the other layouts."""
        pairs = [(self._states[u][si], self._states[u][si].jobs[ji])
                 for u, si, ji in chunk]
        if self._pool is None:
            from ..realign.realigner import (PAGED_SWEEP_PLANES,
                                             _RAGGED_T_MULT)
            from .pagedbuf import DEFAULT_PAGE_ROWS, PagePool
            page_rows = min(DEFAULT_PAGE_ROWS, _RAGGED_T_MULT)
            t = sum(int(st.lens[:len(st.reads_to_clean)].sum())
                    for st, _ in pairs)
            self._pool = PagePool(
                "p4", max(-(-max(t, 1) // page_rows) * 2, 2),
                page_rows, planes=PAGED_SWEEP_PLANES)

        def fn(attempt):
            return R.sweep_dispatch_paged(pairs, pool=self._pool)

        def split(err):
            if len(chunk) <= 1:
                raise err
            mid = (len(chunk) + 1) // 2
            self._dispatch_chunk_paged(cl, chunk[:mid])
            self._dispatch_chunk_paged(cl, chunk[mid:])
            return None

        with obs.trace.span("realign:sweep", cat="dispatch",
                            args={"shape": [cl], "jobs": len(chunk),
                                  "layout": "paged"}):
            out = dispatch_with_retry(fn, site="device_dispatch",
                                      label="realign:sweep",
                                      policy=self._retry, split=split)
        if out is None:
            return
        q, o, spans, stats = out
        cr = _ChunkResult(q, o)
        for key, span in zip(chunk, spans):
            self._results[key] = (cr, span)
        r = obs.registry()
        r.counter("realign_sweep_dispatches").inc()
        r.counter("realign_sweep_jobs").inc(len(chunk))
        sig = (stats["g"], stats["rows_pad"], stats["bases_pad"], cl)
        if sig not in self._shapes_seen:
            self._shapes_seen.add(sig)
            r.counter("realign_shapes").inc()
        obs.emit("realign_sweep_dispatch",
                 shape=[stats["rows_pad"], stats["bases_pad"], cl],
                 jobs=len(chunk), g=stats["g"],
                 units=len({u for u, _, _ in chunk}),
                 layout="paged",
                 waste_r=round(1 - stats["rows"] /
                               max(stats["rows_pad"], 1), 4),
                 waste_l=round(1 - stats["bases"] /
                               max(stats["bases_pad"], 1), 4),
                 waste_cl=round(1 - stats["cons_true"] /
                                max(len(chunk) * cl, 1), 4),
                 waste_g=round(1 - len(chunk) / stats["g"], 4))

    def _take(self, uid: tuple, si: int, ji: int):
        cr, g = self._results.pop((uid, si, ji))
        qs, os_ = cr.arrays()
        if isinstance(g, tuple):        # ragged: a (lo, hi) row span
            lo, hi = g
            return qs[lo:hi], os_[lo:hi]
        return qs[g], os_[g]

    @property
    def n_shapes(self) -> int:
        return len(self._shapes_seen)


@dataclass
class BinUnitDesc:
    """One schedulable unit of pass 4: a whole mapped bin, or one
    position sub-range of a hot (over-budget) bin."""
    bin_id: int
    uid: tuple                      # (sequence, sub-index): emit order
    load: Callable[[], tuple]       # () -> (own_table, halo_table|None)
    next_lo: int                    # merge-window cutoff of the NEXT unit


class RealignEngine:
    """Drives :class:`BinUnitDesc` units through the 3-stage pipeline.

    ``run`` consumes units in order, with ``plan['pipeline_depth']`` prep
    workers feeding a bounded in-order queue (``ingest.pipelined``), so
    host RSS stays ~(depth + 2) x bin budget: depth + 1 queued prepared
    units, one under prep, one being finished.  Depth 1 degrades to the
    fully synchronous walk — same engine, same bytes.
    """

    def __init__(self, plan: dict, retry_policy=None):
        self.plan = plan
        self.depth = int(plan["pipeline_depth"])
        self.batcher = CrossBinSweepBatcher(
            donate=bool(plan["donate"]), retry_policy=retry_policy,
            layout=plan.get("layout", "padded"))

    def run(self, units: Iterable[BinUnitDesc],
            emit: Callable[[pa.Table, int], None], sort: bool) -> int:
        from ..ops.sort import sort_reads
        from .ingest import pipelined

        from ..instrument import stage

        def prep(u: BinUnitDesc, _ctx):
            # runs on pool workers: the stage stack is per-thread now
            # (the tracing plane), so load/prep are REAL stages on the
            # prep pool's own report/timeline lane; the perf timers stay
            # the realign_bin event's source (stage granularity differs)
            t0 = time.perf_counter()
            with stage("p4-load"):
                own, halo = u.load()
            t1 = time.perf_counter()
            with stage("p4-prep"):
                combined = own if halo is None or halo.num_rows == 0 \
                    else pa.concat_tables([own, halo])
                work = R.plan_realign(combined)
                if work is not None:
                    self.batcher.add_unit(u.uid, work.states)
            t2 = time.perf_counter()
            return (u, own.num_rows, combined, work, t1 - t0, t2 - t1)

        reg = obs.registry()
        n_units = 0
        for u, own_rows, combined, work, load_s, prep_s in pipelined(
                units, prep, workers=self.depth, depth=self.depth + 1,
                pool_name="realign-prep"):
            t2 = time.perf_counter()
            if work is not None:
                results = self.batcher.sweep_unit(u.uid)
                t3 = time.perf_counter()
                tbl = R.finish_realign(work, results)
            else:
                t3 = time.perf_counter()
                tbl = combined
            if tbl.num_rows != own_rows:      # drop the halo copies
                tbl = tbl.slice(0, own_rows)
            if sort:
                tbl = sort_reads(tbl)
            t4 = time.perf_counter()
            emit(tbl, u.next_lo)
            t5 = time.perf_counter()
            n_units += 1
            stage_s = dict(load=load_s, prep=prep_s, sweep=t3 - t2,
                           finish=t4 - t3, emit=t5 - t4)
            for name, s in stage_s.items():
                reg.histogram("realign_stage_seconds",
                              stage=name).observe(s)
            obs.emit("realign_bin", bin=int(u.bin_id), rows=int(own_rows),
                     groups=0 if work is None else len(work.states),
                     jobs=0 if work is None else work.n_jobs,
                     **{f"{k}_s": round(v, 6) for k, v in stage_s.items()})
        return n_units
